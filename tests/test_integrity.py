"""Integrity-watchdog suite: online scrubbing, quarantine containment,
and point-in-time recovery (`raft_tpu.integrity`, the serve-loop
watchdog tick, `jobs.resumable_scrub`, and the `Mutator(retain=)` PITR
snapshots).

Four layers of drills:

- **Digest lifecycle** (fast): build attaches the CRC-32C sidecar,
  every mutation op keeps it incrementally fresh, save/load carries it
  as first-class checkpoint fields, and a legacy checkpoint without one
  gets a sidecar attached on the scrubber's first contact.
- **Detection + containment** (fast): seeded rot (`rot_list` /
  FaultPlan-driven `maybe_rot` at ``integrity.table.rot``) is named by
  the scrubber as the exact (field, list) pair; a quarantined index
  serves BIT-IDENTICALLY to an index that never held the victim rows;
  the serve-loop acceptance drill proves detection → honest degraded
  coverage → verified zero-dip repair, all off the request path. The
  MNMG flavor convicts per-rank shard rot and repairs from the PR-4
  replica mirrors.
- **Point-in-time recovery** (fast): `integrity.restore(root, seq)`
  reconstructs a digest-verified checkpoint BYTE-IDENTICAL to the one
  a crash-free run committed at that seq; retention prunes to the K
  newest snapshots with the payload sweep floor at the oldest retained
  cursor; a rotted base falls back to an older snapshot instead of
  failing the restore.
- **Kill-and-resume** (slow, child processes): a seeded kill_rank
  fault at ``integrity.scrub.crash`` SIGKILLs a real child
  (`tests/_integrity_crash_worker.py`) after a scrub-cursor commit;
  re-running resumes from the cursor — committed slices are never
  re-scanned and the rotted list is still named.

The two ``integrity.*`` fault sites drilled here are pinned against
`core.faults.FAULT_SITES` by the drift test in test_raftlint.py.
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from raft_tpu import integrity, jobs, obs, serve
from raft_tpu.core import faults
from raft_tpu.integrity import digest, scrub, watchdog
from raft_tpu.neighbors import ivf_flat, ivf_pq, ivf_rabitq, mutation
from raft_tpu.obs import report as obs_report
from raft_tpu.random import make_blobs

SEED = int(os.environ.get(faults.ENV_SEED, "1234"))
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_integrity_crash_worker.py")

KINDS = ("ivf_flat", "ivf_pq", "ivf_rabitq")

#: the payload field each kind's rot drills flip
PAYLOAD = {"ivf_flat": "list_data", "ivf_pq": "codes", "ivf_rabitq": "codes"}


@pytest.fixture
def obs_on():
    obs.enable()
    obs.reset()
    yield
    obs.reset()
    obs.disable()


@pytest.fixture(scope="module")
def blobs():
    data, _ = make_blobs(512, 16, n_clusters=6, cluster_std=0.4, seed=13)
    return np.asarray(data)


def _build(kind, data, **over):
    """One tiny deterministic index per family (the test_mutation
    recipe: rabitq skips the raw-row store so in-memory and reloaded
    indexes rank identically)."""
    if kind == "ivf_flat":
        p = ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=3, **over)
        return ivf_flat, ivf_flat.build(p, data)
    if kind == "ivf_pq":
        p = ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=3,
                               kmeans_trainset_fraction=1.0, **over)
        return ivf_pq, ivf_pq.build(p, data)
    p = ivf_rabitq.IndexParams(n_lists=8, kmeans_n_iters=3,
                               store_dataset=False, **over)
    return ivf_rabitq, ivf_rabitq.build(p, np.asarray(data, np.float32))


def _search(mod, index, q, k=10):
    v, i = mod.search(mod.SearchParams(n_probes=4), index, q, k)
    return np.asarray(v), np.asarray(i)


def _queries(dim=16, n=16, seed=7):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, dim)).astype(np.float32)


def _list_member_ids(index, lid):
    """The source ids living in list `lid` (slot_rows -> source_ids)."""
    rows = np.asarray(index.slot_rows)[int(lid)]
    rows = rows[rows >= 0]
    return np.asarray(index.source_ids)[rows]


# -- digest lifecycle ---------------------------------------------------

@pytest.mark.parametrize("kind", KINDS)
def test_build_attaches_fresh_sidecar(blobs, kind):
    mod, idx = _build(kind, blobs)
    assert idx.list_digests is not None and idx.table_digests is not None
    for field, gran in digest.DIGEST_FIELDS[kind].items():
        present = getattr(idx, field, None) is not None
        bucket = idx.list_digests if gran == "list" else idx.table_digests
        # presence invariant: a digest row exists iff the attr does
        assert (field in bucket) == present, field
    assert digest.verify(idx, kind) == []
    digest.check_fresh(idx, kind)


@pytest.mark.parametrize("kind", KINDS)
def test_sidecar_roundtrips_save_load(tmp_path, blobs, kind):
    mod, idx = _build(kind, blobs)
    # mutate first so tombstones (a list-granularity field) round-trips
    idx = mutation.delete(idx, np.asarray(_list_member_ids(idx, 0))[:2])
    path = str(tmp_path / "idx.ckpt")
    mod.save(path, idx)
    back = mod.load(path)
    assert back.list_digests is not None
    assert sorted(back.list_digests) == sorted(idx.list_digests)
    for f in idx.list_digests:
        np.testing.assert_array_equal(back.list_digests[f],
                                      idx.list_digests[f])
    assert back.table_digests == {k: int(v)
                                  for k, v in idx.table_digests.items()}
    # and the reloaded index verifies against its own reloaded tables
    digest.check_fresh(back, kind)


@pytest.mark.parametrize("kind", KINDS)
def test_mutations_keep_digests_fresh(blobs, kind):
    """The incremental-refresh completeness claim: every mutation op
    leaves the sidecar verifying clean — delete (tombstone rows),
    upsert (append slots + geometry growth), rebalance (compaction
    repack) — without ever re-digesting the whole index."""
    mod, idx = _build(kind, blobs)
    rng = np.random.default_rng(SEED)
    idx = mutation.delete(idx, _list_member_ids(idx, 1)[:3])
    digest.check_fresh(idx, kind)
    if kind != "ivf_rabitq":  # rabitq upsert needs the raw-row store
        vecs = rng.standard_normal((4, 16)).astype(np.float32)
        idx = mutation.upsert(idx, vecs, np.arange(900, 904))
        digest.check_fresh(idx, kind)
    idx, _ = mutation.rebalance(idx)
    digest.check_fresh(idx, kind)


def test_legacy_checkpoint_attaches_on_first_contact(tmp_path, blobs):
    """A pre-integrity checkpoint (no sidecar fields) loads with
    list_digests None, and the scrubber's first slice attaches a fresh
    sidecar instead of reporting phantom mismatches."""
    mod, idx = _build("ivf_flat", blobs)
    idx.list_digests = None
    idx.table_digests = None
    path = str(tmp_path / "legacy.ckpt")
    mod.save(path, idx)
    back = mod.load(path)
    assert back.list_digests is None and back.table_digests is None
    sc = scrub.Scrubber("ivf_flat", budget_lists=4)
    assert sc.slice_scan(back) == []       # first contact: attach only
    assert back.list_digests is not None
    assert sc.full_scan(back) == []        # now actually verified


# -- detection ----------------------------------------------------------

@pytest.mark.parametrize("kind", KINDS)
def test_rot_named_as_exact_pair(blobs, kind):
    mod, idx = _build(kind, blobs)
    scrub.rot_list(idx, 5, PAYLOAD[kind], frac=0.25, seed=SEED)
    sc = scrub.Scrubber(kind, budget_lists=3)
    assert sc.full_scan(idx) == [(PAYLOAD[kind], 5)]
    assert sc.mismatches == 1


def test_slot_rot_detected_too(blobs):
    """Structural rot (slot_rows, the occupancy table itself) is the
    nastier case — quarantine cannot trust occupancy — and the sidecar
    digests it at list granularity like any payload."""
    _, idx = _build("ivf_flat", blobs)
    scrub.rot_list(idx, 2, "slot_rows", frac=0.5, seed=SEED)
    bad = scrub.Scrubber("ivf_flat").full_scan(idx)
    assert ("slot_rows", 2) in bad


def test_table_rot_reports_sentinel_list(blobs):
    _, idx = _build("ivf_flat", blobs)
    import jax.numpy as jnp

    centers = np.asarray(idx.centers).copy()
    centers[0, 0] += 0.5
    idx.centers = jnp.asarray(centers)
    bad = scrub.Scrubber("ivf_flat").full_scan(idx)
    assert ("centers", -1) in bad  # -1 = table granularity, no mask unit


def test_maybe_rot_drives_from_fault_plan(blobs):
    """The chaos injector: a `corrupt_shard` fault at the registered
    ``integrity.table.rot`` site rots seeded victims; the scrubber
    names every one. Victim choice keys off the plan seed, so the
    3-seed matrix rots different lists."""
    _, idx = _build("ivf_flat", blobs)
    plan = faults.FaultPlan(
        [faults.Fault(kind="corrupt_shard", site="integrity.table.rot",
                      count=2, fraction=0.3)],
        seed=SEED,
    )
    with plan.install():
        victims = scrub.maybe_rot(idx, "ivf_flat")
    assert len(victims) == 2
    bad = scrub.Scrubber("ivf_flat").full_scan(idx)
    assert set(bad) == set(victims)


# -- quarantine ---------------------------------------------------------

@pytest.mark.parametrize("kind", KINDS)
def test_quarantine_bit_identical_to_never_held(blobs, kind):
    """THE containment claim: after rot + quarantine, every search is
    bit-identical to the same search on a clean twin whose victim-list
    members were deleted — the quarantined list is simply gone, not a
    source of garbage."""
    mod, rotted = _build(kind, blobs)
    _, twin = _build(kind, blobs)  # deterministic build: same content
    lid = int(np.random.default_rng(SEED).integers(8))
    victim_ids = _list_member_ids(rotted, lid)
    scrub.rot_list(rotted, lid, PAYLOAD[kind], frac=1.0, seed=SEED)
    quarantined = watchdog.quarantine(rotted, lid, kind)
    reference = mutation.delete(twin, victim_ids)
    q = _queries()
    qv, qi = _search(mod, quarantined, q)
    rv, ri = _search(mod, reference, q)
    np.testing.assert_array_equal(qi, ri)
    np.testing.assert_array_equal(qv, rv)
    assert not np.isin(qi, victim_ids).any()


def test_quarantine_is_a_clone(blobs):
    _, idx = _build("ivf_flat", blobs)
    out = watchdog.quarantine(idx, 3, "ivf_flat")
    assert out is not idx and idx.tombstones is None  # zero-dip swap
    digest.check_fresh(out, "ivf_flat")  # tombstone rows re-digested


def test_watchdog_quarantines_then_repairs_from_checkpoint(
        tmp_path, blobs, obs_on):
    """Watchdog end-to-end, no server: rot → slice scans detect →
    quarantine (coverage honestly < 1) → checkpoint repair swaps in a
    digest-VERIFIED index and coverage returns to 1.0."""
    mod, idx = _build("ivf_flat", blobs)
    mut = mutation.Mutator(str(tmp_path / "mut"), idx, kind="ivf_flat")
    # a no-op commit writes nothing: one real op gives the repairer a
    # committed checkpoint, and the served index IS that committed state
    mut.delete(np.asarray(_list_member_ids(idx, 0))[:1])
    mut.commit()
    idx = mut.index
    q = _queries()
    pre_v, pre_i = _search(mod, idx, q)
    scrub.rot_list(idx, 4, "list_data", frac=1.0, seed=SEED)

    wd = integrity.IntegrityWatchdog("ivf_flat", budget_lists=3)
    for _ in range(4):  # one full lap of 8 lists in 3-list slices
        idx = wd.step(idx)
    assert wd.quarantined == {4}
    assert 0.0 < wd.coverage() < 1.0
    assert not np.isin(_search(mod, idx, q)[1],
                       _list_member_ids(mut.index, 4)).any()

    wd.repair = integrity.checkpoint_repairer(str(tmp_path / "mut"))
    idx = wd.step(idx)
    assert wd.repairs == 1 and not wd.quarantined
    assert wd.coverage() == 1.0
    post_v, post_i = _search(mod, idx, q)
    np.testing.assert_array_equal(pre_i, post_i)
    np.testing.assert_array_equal(pre_v, post_v)


def test_failed_repair_keeps_quarantine(blobs):
    _, idx = _build("ivf_flat", blobs)
    scrub.rot_list(idx, 1, "list_data", frac=1.0, seed=SEED)
    wd = integrity.IntegrityWatchdog(
        "ivf_flat", budget_lists=8,
        repair=lambda _idx: (_ for _ in ()).throw(RuntimeError("nope")))
    idx = wd.step(idx)
    assert wd.quarantined == {1}  # the quarantine outlived the failure
    assert wd.failed_repairs == 1 and wd.repairs == 0
    assert wd.coverage() < 1.0


# -- the serve-loop acceptance drill ------------------------------------

def test_serve_rot_quarantine_repair_zero_dip(tmp_path, blobs):
    """The acceptance drill: rot strikes a LIVE served index; the
    between-batches watchdog tick detects and quarantines it (replies
    turn degraded-but-honest: coverage < 1.0, results bit-identical to
    an index that never held the list), then a verified checkpoint
    repair swaps in between batches and results return bit-identical to
    pre-rot — the request path never sees a blocking scan."""
    mod, idx = _build("ivf_flat", blobs)
    _, twin = _build("ivf_flat", blobs)
    mut = mutation.Mutator(str(tmp_path / "mut"), idx, kind="ivf_flat")
    seeded = np.asarray(_list_member_ids(idx, 0))[:1]
    mut.delete(seeded)  # a no-op commit writes nothing to restore from
    mut.commit()
    idx = mut.index
    twin = mutation.delete(twin, seeded)
    sp = ivf_flat.SearchParams(n_probes=4, engine="query")
    server = serve.SearchServer(
        idx, serve.ServerConfig(buckets=(16,)), search_params=sp)
    wd = integrity.IntegrityWatchdog("ivf_flat", budget_lists=3)
    server.attach_integrity(wd)
    q = _queries()

    pre = server.search(q, k=10, timeout=5.0)
    assert pre.coverage == 1.0

    lid = 4
    victim_ids = _list_member_ids(idx, lid)
    scrub.rot_list(idx, lid, "list_data", frac=1.0, seed=SEED)
    # each served batch buys one scrub slice; within a lap the watchdog
    # has quarantined the rotted list off the request path
    for _ in range(4):
        if wd.quarantined:
            break
        server.search(q[:1], k=10, timeout=5.0)
    assert wd.quarantined == {lid}

    mid = server.search(q, k=10, timeout=5.0)
    assert mid.coverage == pytest.approx(wd.coverage()) and mid.coverage < 1.0
    ref_v, ref_i = _search(mod, mutation.delete(twin, victim_ids), q)
    np.testing.assert_array_equal(mid.ids, ref_i)
    np.testing.assert_array_equal(mid.values, ref_v)

    wd.repair = integrity.checkpoint_repairer(str(tmp_path / "mut"))
    server.search(q[:1], k=10, timeout=5.0)  # the tick that repairs
    post = server.search(q, k=10, timeout=5.0)
    assert post.coverage == 1.0 and wd.repairs == 1
    np.testing.assert_array_equal(post.ids, pre.ids)
    np.testing.assert_array_equal(post.values, pre.values)


# -- MNMG: per-rank shard digests + mirror repair -----------------------

WORLD = 4


@pytest.fixture(scope="module")
def comms4():
    from raft_tpu.comms import Comms

    return Comms(n_devices=WORLD)


@pytest.fixture()
def dist_flat_r2(comms4, blobs):
    from raft_tpu.comms import mnmg

    return mnmg.ivf_flat_build(
        comms4, ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=3), blobs,
        replication=2)


def _mnmg_ids(index, q, k=10):
    from raft_tpu.comms import mnmg

    v, i = mnmg.ivf_flat_search(index, q, k, n_probes=4, engine="list",
                                query_mode="replicated")
    return np.asarray(v), np.asarray(i)


def test_mnmg_rot_convicted_and_mirror_repaired(dist_flat_r2, obs_on):
    """The MNMG half: seeded shard rot (per-rank, the repair
    granularity the mirrors provide) is convicted by the per-rank
    digests, healed from the PR-4 replica mirrors, and post-heal
    searches are bit-identical to pre-rot."""
    index = dist_flat_r2
    q = _queries()
    baseline = watchdog.mnmg_digests(index)
    pre_v, pre_i = _mnmg_ids(index, q)

    plan = faults.FaultPlan(
        [faults.Fault(kind="corrupt_shard", site="integrity.table.rot",
                      rank=-1, fraction=0.05)],
        seed=SEED,
    )
    with plan.install():
        rotted = watchdog.maybe_rot_mnmg(index)
    assert len(rotted) == 1
    assert watchdog.verify_mnmg(index, baseline) == rotted

    index = watchdog.repair_ranks(index, rotted)
    assert watchdog.verify_mnmg(index, baseline) == []
    post_v, post_i = _mnmg_ids(index, q)
    np.testing.assert_array_equal(pre_i, post_i)
    np.testing.assert_array_equal(pre_v, post_v)


# -- point-in-time recovery ---------------------------------------------

def _churn(mut, dim=16, seed=11, rounds=6):
    """Deterministic mixed churn through a Mutator: upserts over build
    ids + fresh ids, deletes, one rebalance midway."""
    rng = np.random.default_rng(seed)
    for r in range(rounds):
        if r == 3:
            mut.rebalance()
            continue
        if r % 2 == 0:
            mut.upsert(rng.standard_normal((3, dim)).astype(np.float32),
                       np.array([r, r + 20, 700 + r]))
        else:
            mut.delete(np.array([r, r + 8]))


def test_pitr_snapshots_retention_and_sweep(tmp_path, blobs):
    """Retention is keyed off committed cursors: `retain=K` keeps the K
    newest cursor-stamped snapshots, and payload containers survive
    down to the OLDEST retained cursor (every retained base can replay
    forward)."""
    _, idx = _build("ivf_flat", blobs)
    root = str(tmp_path / "mut")
    mut = mutation.Mutator(root, idx, kind="ivf_flat", ckpt_every=2,
                           retain=2, slack=8)
    _churn(mut)
    mut.commit()
    cursors = [c for c, _ in integrity.retained(root)]
    assert len(cursors) == 2 and cursors[-1] == mut.applied
    floor = min(cursors)
    for seq in range(mut.applied):
        payload = os.path.join(root, mut.log.payload_path(seq))
        entry_op = mut.log.entries()[seq]["op"]
        if seq < floor or entry_op == "rebalance":
            assert not os.path.exists(payload), seq
        else:
            assert os.path.exists(payload), seq
    # prune to 1 releases the older cursor
    assert integrity.prune(root, keep=1) == [cursors[-1]]


def test_pitr_restore_byte_identical_to_crash_free(tmp_path, blobs):
    """THE PITR acceptance criterion: restore to an arbitrary committed
    seq — forced to REPLAY from an older base, not copy a snapshot —
    writes a digest-verified checkpoint byte-identical to the one the
    crash-free run committed at that seq."""
    _, idx = _build("ivf_flat", blobs)
    root = str(tmp_path / "mut")
    mut = mutation.Mutator(root, idx, kind="ivf_flat", ckpt_every=2,
                           retain=10, slack=8)
    _churn(mut)
    mut.commit()
    snaps = dict(integrity.retained(root))
    assert len(snaps) >= 3
    target = sorted(snaps)[-2]          # an intermediate committed seq
    base = sorted(snaps)[0]             # force a real replay
    out = str(tmp_path / "restored.ckpt")
    restored, out_path = integrity.restore(root, target, out=out,
                                           base_cursor=base)
    assert out_path == out
    assert int(restored.mut_cursor) == target
    digest.check_fresh(restored, "ivf_flat")
    with open(out, "rb") as fa, open(snaps[target], "rb") as fb:
        assert fa.read() == fb.read(), "restore is not byte-identical"


def test_restore_falls_back_past_rotted_base(tmp_path, blobs, obs_on):
    """A rotted snapshot costs replay time, not the restore: the newest
    base fails its load/digest check and the next older one carries the
    same target seq to the same verified state."""
    _, idx = _build("ivf_flat", blobs)
    root = str(tmp_path / "mut")
    mut = mutation.Mutator(root, idx, kind="ivf_flat", ckpt_every=2,
                           retain=10, slack=8)
    _churn(mut)
    mut.commit()
    snaps = dict(integrity.retained(root))
    target = sorted(snaps)[-2]
    clean, _ = integrity.restore(root, target)
    # rot the newest eligible base ON DISK (mid-file byte flips)
    with open(snaps[target], "r+b") as fh:
        fh.seek(os.path.getsize(snaps[target]) // 2)
        buf = bytearray(fh.read(8))
        fh.seek(-len(buf), os.SEEK_CUR)
        fh.write(bytes(b ^ 0xFF for b in buf))
    restored, _ = integrity.restore(root, target)
    assert int(restored.mut_cursor) == target
    np.testing.assert_array_equal(np.asarray(restored.list_data),
                                  np.asarray(clean.list_data))
    events = [e for e in obs.snapshot()["events"]
              if e.get("kind") == "integrity.restore"]
    assert any(e.get("ok") is False for e in events)  # the fallback beat


def test_restore_rejects_out_of_range_seq(tmp_path, blobs):
    _, idx = _build("ivf_flat", blobs)
    root = str(tmp_path / "mut")
    mut = mutation.Mutator(root, idx, kind="ivf_flat")
    mut.delete(np.array([1]))
    mut.commit()
    with pytest.raises(digest.IntegrityError, match="outside"):
        integrity.restore(root, 99)


# -- the resumable scrub job stage --------------------------------------

def test_resumable_scrub_cursor_resume_no_rescan(tmp_path, blobs):
    """In-process resume: a walk cut at a lap boundary re-enters from
    the committed cursor and scans exactly the remainder — never the
    committed slices again."""
    _, idx = _build("ivf_flat", blobs)
    d = str(tmp_path)
    _, st = jobs.resumable_scrub("ivf_flat", idx, scratch=d,
                                 budget_lists=4, laps=1)
    assert st["laps"] == 1 and st["lists_scanned"] == 8
    bad, st = jobs.resumable_scrub("ivf_flat", idx, scratch=d,
                                   budget_lists=4, laps=3)
    assert st["resumed_at"] == 8          # lap 1 was committed
    assert st["lists_scanned"] == 16      # laps 2..3 only
    assert bad == []


def test_resumable_scrub_transient_fault_reentry(tmp_path, blobs):
    """The site's transient flavor: a flaky fault at the scrub loop top
    raises typed; the (supervised-runner-style) re-entry converges with
    full coverage."""
    _, idx = _build("ivf_flat", blobs)
    d = str(tmp_path)
    plan = faults.FaultPlan(
        [faults.Fault(kind="flaky_bootstrap", site="integrity.scrub.crash",
                      count=1)],
        seed=SEED,
    )
    with plan.install():
        with pytest.raises(faults.FaultInjected):
            jobs.resumable_scrub("ivf_flat", idx, scratch=d, budget_lists=4)
        bad, st = jobs.resumable_scrub("ivf_flat", idx, scratch=d,
                                       budget_lists=4)
    assert st["laps"] == 1 and bad == []


def test_resumable_scrub_stale_cursor_restarts(tmp_path, blobs):
    """The fingerprint gate: a cursor committed against a different
    index state (here: a later committed mut_cursor) cannot carry a
    resume — the walk restarts from zero instead of trusting it."""
    _, idx = _build("ivf_flat", blobs)
    d = str(tmp_path)
    jobs.resumable_scrub("ivf_flat", idx, scratch=d, budget_lists=4, laps=1)
    moved = mutation.delete(idx, _list_member_ids(idx, 0)[:1])
    moved = mutation._clone(moved)
    moved.mut_cursor = 1  # a commit happened since the cursor was cut
    _, st = jobs.resumable_scrub("ivf_flat", moved, scratch=d,
                                 budget_lists=4, laps=1)
    assert st["resumed_at"] == 0 and st["lists_scanned"] == 8


# -- kill-and-resume (child-process SIGKILL drills) ---------------------

def _scrub_kill_fault(count: int) -> faults.Fault:
    """The SIGKILL fault the child worker arms: the count-th visit of
    ``integrity.scrub.crash`` — fired after EVERY scrub-cursor commit —
    kills the process, so sweeping the count lands the kill mid-lap and
    at lap boundaries."""
    return faults.Fault(kind="kill_rank", site="integrity.scrub.crash",
                        count=count)


def _worker(args, workdir):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    return subprocess.run(
        [sys.executable, WORKER, *args, "--workdir", str(workdir)],
        env=env, capture_output=True, text=True, timeout=600)


@pytest.mark.slow
@pytest.mark.parametrize("kind", KINDS)
@pytest.mark.parametrize("kill", [1, 2, 3])
def test_sigkill_mid_scrub_resumes_from_cursor(tmp_path, kind, kill):
    """THE scrub chaos drill: a real child is SIGKILLed on the kill-th
    scrub-cursor commit (kill=2 is exactly a lap boundary), then the
    same walk re-runs. The committed cursor must carry the resume:
    resumed_at lands on the killed run's last commit, only the
    remainder is scanned, and the rotted LAST list — positioned so
    every resume still has it ahead — is named. A separate process is
    the point: SIGKILL leaves no in-process cleanup to cheat with."""
    assert _scrub_kill_fault(kill).site == "integrity.scrub.crash"
    # worker geometry: 8 lists, 4-list slices, 2 laps = 4 cursor commits
    r1 = _worker(["--kind", kind, "--seed", str(SEED),
                  "--kill", str(kill)], tmp_path)
    assert r1.returncode == -signal.SIGKILL, (r1.returncode, r1.stderr[-2000:])
    r2 = _worker(["--kind", kind, "--seed", str(SEED)], tmp_path)
    assert r2.returncode == 0, r2.stderr[-2000:]
    got = json.loads(r2.stdout.strip().splitlines()[-1])
    assert got["resumed_at"] == kill * 4
    assert got["lists_scanned"] == 16 - kill * 4  # no committed re-scan
    assert got["rot"] in got["bad"]
    assert got["laps"] == 2


# -- observability ------------------------------------------------------

def test_obs_report_integrity_section(tmp_path, blobs, obs_on):
    mod, idx = _build("ivf_flat", blobs)
    mut = mutation.Mutator(str(tmp_path / "mut"), idx, kind="ivf_flat")
    mut.delete(np.asarray(_list_member_ids(idx, 0))[:1])
    mut.commit()
    idx = mut.index
    scrub.rot_list(idx, 2, "list_data", frac=1.0, seed=SEED)
    wd = integrity.IntegrityWatchdog(
        "ivf_flat", budget_lists=8,
        repair=integrity.checkpoint_repairer(str(tmp_path / "mut")))
    wd.step(idx)
    integrity.restore(str(tmp_path / "mut"))
    text = obs_report.render(obs.snapshot())
    assert "## Integrity" in text
    assert "mismatches: 1" in text
    assert "quarantines: 1" in text
    assert "repairs: 1" in text
    assert "restores: 2" in text  # one inside the repair, one direct
    # integrity counters live in their own section, not misc Counters
    assert "integrity.scans" not in text
