"""Spectral clustering, single-linkage, LAP, label utils, generators
(mirrors cpp/test/{cluster/linkage.cu,sparse/spectral_matrix.cu,lap/,label/,
random/rmat_*} strategies)."""

import numpy as np
import pytest
from scipy.optimize import linear_sum_assignment
from sklearn.metrics import adjusted_rand_score

from raft_tpu import spectral, solver, label
from raft_tpu.cluster import single_linkage
from raft_tpu.random import make_blobs, rmat
from raft_tpu.sparse import neighbors as sp_neighbors


# -- spectral ----------------------------------------------------------------


def two_moons_graph():
    data, labels = make_blobs(300, 5, n_clusters=2, cluster_std=0.5, seed=17)
    g = sp_neighbors.knn_graph(np.asarray(data), 10)
    return g, np.asarray(labels)


@pytest.mark.slow
def test_spectral_partition():
    g, truth = two_moons_graph()
    labels, vals, emb = spectral.partition(g, 2)
    ari = adjusted_rand_score(truth, np.asarray(labels))
    assert ari > 0.95, ari
    cut, cost = spectral.analyze_partition(g, np.asarray(labels), 2)
    assert cut >= 0


def test_fit_embedding_connected_graph():
    # connected graph (uniform data, generous k): embedding is well-defined
    rng = np.random.default_rng(7)
    x = rng.random((200, 3)).astype(np.float32)
    g = sp_neighbors.knn_graph(x, 12)
    from raft_tpu.sparse.formats import coo_to_csr, csr_to_dense

    csr = coo_to_csr(g)
    emb = np.asarray(spectral.fit_embedding(csr, 2))
    assert emb.shape == (200, 2)
    assert np.isfinite(emb).all()
    # eigenvector residual check against the dense normalized Laplacian
    A = np.asarray(csr_to_dense(csr))
    deg = A.sum(1)
    dinv = 1 / np.sqrt(np.maximum(deg, 1e-12))
    L = np.eye(200) - dinv[:, None] * A * dinv[None, :]
    w = np.linalg.eigvalsh(L)
    v = emb[:, 0]
    resid = np.linalg.norm(L @ v - w[1] * v)
    assert resid < 5e-2, resid


@pytest.mark.slow
def test_modularity_maximization():
    g, truth = two_moons_graph()
    labels, _, _ = spectral.modularity_maximization(g, 2)
    q = spectral.modularity(g, np.asarray(labels))
    assert q > 0.3  # strong community structure found


# -- single-linkage ----------------------------------------------------------


@pytest.mark.parametrize("connectivity", [
    pytest.param("knn", marks=pytest.mark.slow), "pairwise",
])
def test_single_linkage_blobs(connectivity):
    data, truth = make_blobs(400, 8, n_clusters=4, cluster_std=0.3, seed=23)
    out = single_linkage(
        np.asarray(data), n_clusters=4, connectivity=connectivity, n_neighbors=10
    )
    ari = adjusted_rand_score(np.asarray(truth), np.asarray(out.labels))
    assert ari > 0.95, ari
    assert np.asarray(out.children).shape[0] == 399
    # merge distances nondecreasing
    d = np.asarray(out.deltas)
    assert np.all(np.diff(d) >= -1e-5)


def test_single_linkage_matches_scipy():
    from scipy.cluster.hierarchy import linkage, fcluster

    rng = np.random.default_rng(5)
    x = rng.random((60, 4)).astype(np.float32)
    out = single_linkage(x, n_clusters=5, connectivity="pairwise")
    Z = linkage(x, method="single", metric="sqeuclidean")
    want = fcluster(Z, 5, criterion="maxclust")
    ari = adjusted_rand_score(want, np.asarray(out.labels))
    assert ari > 0.99, ari


# -- LAP ---------------------------------------------------------------------


@pytest.mark.parametrize("n", [5, 20, 64])
def test_linear_assignment(n):
    rng = np.random.default_rng(n)
    cost = rng.random((n, n)).astype(np.float32)
    rows, cols = solver.linear_assignment(cost)
    cols = np.asarray(cols)
    assert sorted(cols.tolist()) == list(range(n))  # a permutation
    got = cost[np.arange(n), cols].sum()
    r, c = linear_sum_assignment(cost)
    want = cost[r, c].sum()
    assert got <= want * 1.02 + 1e-4, (got, want)


def test_linear_assignment_maximize():
    rng = np.random.default_rng(1)
    cost = rng.random((10, 10)).astype(np.float32)
    _, cols = solver.linear_assignment(cost, maximize=True)
    got = cost[np.arange(10), np.asarray(cols)].sum()
    r, c = linear_sum_assignment(cost, maximize=True)
    assert got >= cost[r, c].sum() * 0.98


# -- label -------------------------------------------------------------------


def test_make_monotonic():
    labels = np.array([10, 30, 10, 20, 30])
    mono, uniq = label.make_monotonic(labels)
    np.testing.assert_array_equal(np.asarray(mono), [0, 2, 0, 1, 2])
    np.testing.assert_array_equal(np.asarray(uniq), [10, 20, 30])


def test_get_unique_labels():
    np.testing.assert_array_equal(
        np.asarray(label.get_unique_labels(np.array([3, 1, 3, 2]))), [1, 2, 3]
    )


def test_merge_labels():
    # a: {0,1}{2,3}; b: {1,2}{0}{3} -> all connected -> one label
    a = np.array([0, 0, 1, 1])
    b = np.array([1, 0, 0, 2])
    merged = np.asarray(label.merge_labels(a, b))
    assert len(np.unique(merged)) == 1
    # disjoint groupings stay separate
    a2 = np.array([0, 0, 1, 1])
    b2 = np.array([5, 5, 7, 7])
    merged2 = np.asarray(label.merge_labels(a2, b2))
    assert len(np.unique(merged2)) == 2


# -- generators --------------------------------------------------------------


def test_rmat():
    edges = np.asarray(rmat(8, 8, 5000, a=0.7, b=0.1, c=0.1, seed=0))
    assert edges.shape == (5000, 2)
    assert edges.min() >= 0 and edges.max() < 256
    # skew: quadrant a=0.7 concentrates mass at low ids
    assert (edges[:, 0] < 128).mean() > 0.6


def test_rmat_rectangular():
    edges = np.asarray(rmat(6, 9, 2000, seed=1))
    assert edges[:, 0].max() < 64
    assert edges[:, 1].max() < 512
