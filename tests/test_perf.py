"""Perf-watchtower suite (ISSUE 7): the analytic cost model pinned
against XLA's own cost analysis, span cost accounting, MFU attribution
in captures and reports, the cross-rank merge renderer (exact
snapshots), the append-only bench ledger, and the per-rank MNMG capture
hook."""

import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from raft_tpu import obs
from raft_tpu.obs import ledger, perf
from raft_tpu.obs import report as obs_report


@pytest.fixture
def obs_on():
    obs.reset()
    obs.enable()
    yield
    obs.disable()
    obs.reset()


# ---------------------------------------------------------------------------
# cost model: formulas, peaks, MFU
# ---------------------------------------------------------------------------

def test_canon_dtype_and_bytes():
    assert perf.canon_dtype("float32") == "f32"
    assert perf.canon_dtype(np.dtype(np.float32)) == "f32"
    assert perf.canon_dtype("bfloat16") == "bf16"
    assert perf.canon_dtype(jnp.bfloat16) == "bf16"
    assert perf.canon_dtype("uint8") == "int8"
    assert perf.canon_dtype("weird") == "f32"  # conservative default
    assert perf.dtype_bytes("bf16") == 2 and perf.dtype_bytes("int8") == 1


def test_formulas_scale():
    a = perf.pairwise_l2(1000, 100, 64)
    b = perf.pairwise_l2(2000, 100, 64)
    assert 1.9 < b["flops"] / a["flops"] < 2.1  # matmul-dominated
    # list-major streams every list: cost grows with n_lists/n_probes
    qm = perf.ivf_pq_scan(nq=64, n_probes=8, n_lists=64, n_rows=64_000,
                          dim=32, pq_dim=16, k=10)
    lm = perf.ivf_pq_scan(nq=64, n_probes=8, n_lists=64, n_rows=64_000,
                          dim=32, pq_dim=16, k=10, scanned_lists=64)
    assert lm["flops"] > 4 * qm["flops"]
    one = perf.kmeans_step(10_000, 64, 128)
    ten = perf.kmeans_step(10_000, 64, 128, iters=10)
    assert ten["flops"] == 10 * one["flops"]
    # rerank adds exact-distance work on top of the integer scan
    plain = perf.rabitq_scan(nq=64, n_probes=8, n_lists=64, n_rows=64_000,
                             dim=64, k=10)
    rer = perf.rabitq_scan(nq=64, n_probes=8, n_lists=64, n_rows=64_000,
                           dim=64, k=10, rerank_mult=8)
    assert rer["flops"] > plain["flops"]
    # popcount ops are their own rate class since ISSUE 11: the scan is
    # charged as uint32 VPU "int" ops, never against a matmul peak
    assert plain["dtype"] == "int"
    assert plain["flops_by_dtype"]["int"] > 0
    assert plain["flops_by_dtype"]["f32"] > 0  # the coarse stage


def test_cost_registry_per_span_name():
    # every instrumented span resolves a formula; a typo fails loudly
    for name in ("neighbors.brute_force.knn", "neighbors.ivf_flat.search",
                 "neighbors.ivf_pq.search", "neighbors.ivf_rabitq.search",
                 "mnmg.knn", "mnmg.kmeans_fit", "mnmg.ivf_flat_search",
                 "mnmg.ivf_pq_search", "mnmg.ivf_rabitq_search"):
        assert name in perf.SPAN_COST_MODEL
    c = perf.cost_for("neighbors.brute_force.knn", n=100, nq=10, d=8, k=3)
    assert c["flops"] > 0 and c["bytes"] > 0
    with pytest.raises(KeyError):
        perf.cost_for("no.such.span")
    perf.register("custom.span", lambda n: {"flops": n, "bytes": 0,
                                            "dtype": "f32"})
    try:
        assert perf.cost_for("custom.span", n=7)["flops"] == 7
    finally:
        del perf.SPAN_COST_MODEL["custom.span"]


def test_platform_info_cpu_is_nominal():
    info = perf.platform_info()
    assert info["platform"] == "cpu"  # conftest pins the CPU mesh
    assert info["nominal"] is True
    assert info["peak_flops"]["bf16"] > 0


def test_mfu_math():
    info = {"peak_flops": {"f32": 50e9, "bf16": 100e9}, "nominal": True}
    assert perf.mfu({"f32": 5e9}, 1.0, info) == pytest.approx(0.1)
    # mixed dtypes weight each against its own peak
    assert perf.mfu({"f32": 5e9, "bf16": 10e9}, 1.0, info) == pytest.approx(0.2)
    # a dtype the platform has no peak for yields no claim, not 0%
    assert perf.mfu({"int8": 1.0}, 1.0, info) is None
    assert perf.mfu({"f32": 1.0}, 0.0, info) is None
    assert perf.mfu({}, 1.0, info) is None


def test_integer_peak_row_and_popcount_canon():
    """ISSUE 11 satellite: uint32 popcount ops resolve onto their own
    "int" peak row on EVERY platform (v5e architectural estimate, CPU
    nominal placeholder) — before the row existed the bit-plane scan's
    flops fell to the f32 fallback and MFU weighed popcounts against a
    matmul peak; and a platform whose table genuinely misses a dtype
    still yields None, never a fabricated 0%."""
    assert perf.canon_dtype("uint32") == "int"
    assert perf.canon_dtype("int32") == "int"
    assert perf.canon_dtype("int") == "int"
    for name, row in perf.PEAK_TABLE.items():
        assert "int" in row["peak_flops"], name
    assert perf.PEAK_TABLE["cpu"]["nominal"] is True
    # mixed int8+popcount span: each component against ITS peak
    peaks = perf.PEAK_TABLE["tpu-v5e"]["peak_flops"]
    info = {"peak_flops": peaks}
    m = perf.mfu({"int8": peaks["int8"], "int": peaks["int"]}, 2.0, info)
    assert m == pytest.approx(1.0)
    assert perf.mfu({"int": 1.0}, 1.0, {"peak_flops": {}}) is None


def test_rabitq_fused_geometry_cost():
    """The fused bit-plane span charges integer-ops flops with NO
    score-matrix / intersection-tensor bytes — the dtype-correct MFU
    attribution the banked smoke rows must show."""
    kw = dict(nq=64, n_probes=8, n_lists=64, n_rows=64_000, dim=64, k=10)
    xla = perf.rabitq_scan(**kw)
    fused = perf.rabitq_scan(**kw, fused=True)
    assert fused["flops_by_dtype"]["int"] == xla["flops_by_dtype"]["int"]
    assert fused["bytes"] < xla["bytes"]  # the deleted HBM round-trips
    # the int8 fused PQ scan splits coarse-f32 from the int8 MXU matmul
    pq = perf.ivf_pq_scan(nq=64, n_probes=8, n_lists=64, n_rows=64_000,
                          dim=32, pq_dim=16, k=10, dtype="int8",
                          scanned_lists=64, fused=True)
    assert pq["flops_by_dtype"]["int8"] > 0
    assert pq["flops_by_dtype"]["f32"] > 0


def test_collective_wire_bytes():
    assert perf.collective_wire_bytes("allreduce", 1024, 8) == \
        int(1024 * 2 * 7 / 8)
    # allgather's counted payload is the per-rank INPUT shard; a ring
    # allgather forwards w-1 foreign shards through each rank
    assert perf.collective_wire_bytes("allgather", 1024, 8) == 1024 * 7
    assert perf.collective_wire_bytes("allreduce", 1024, 1) == 0
    assert perf.collective_wire_bytes("allreduce", 1024, None) == 0


# ---------------------------------------------------------------------------
# the XLA cross-check (the acceptance pin: analytic == cost_analysis)
# ---------------------------------------------------------------------------

def test_analytic_pairwise_l2_matches_xla():
    """The pairwise-L2 formula must track XLA's own flop count tightly —
    this is the hot path ROADMAP item 1's 10x claim will be judged on."""
    from raft_tpu.distance import pairwise_distance
    from raft_tpu.distance.distance_types import DistanceType

    n, m, d = 512, 1024, 64
    x = jnp.ones((n, d))
    y = jnp.ones((m, d))
    xla = perf.xla_cost_analysis(
        lambda a, b: pairwise_distance(a, b, metric=DistanceType.L2Expanded),
        x, y)
    assert xla is not None and xla["flops"] > 0
    an = perf.pairwise_l2(n, m, d)
    assert 0.85 <= an["flops"] / xla["flops"] <= 1.15
    # bytes: XLA counts every intermediate buffer touch, the model
    # counts unavoidable operand/output traffic — same order, not equal
    assert 0.1 <= an["bytes"] / xla["bytes"] <= 10.0


@pytest.mark.slow  # one small IVF-PQ build (~20 s CPU)
def test_analytic_ivf_pq_scan_matches_xla():
    """The IVF-PQ scan formula must be the right order of magnitude and
    engine-aware: the list-major engine streams every padded list, and
    the model charged with scanned_lists=n_lists lands within 3x of
    XLA's count (a model, not a measurement — but one that can't drift
    silently by 10x)."""
    from raft_tpu.neighbors import ivf_pq

    rng = np.random.default_rng(0)
    data = rng.random((20_000, 32), dtype=np.float32)
    idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=64, kmeans_n_iters=3, pq_dim=16), data)
    q = jnp.asarray(rng.random((128, 32), dtype=np.float32))
    sp = ivf_pq.SearchParams(n_probes=8, score_mode="recon8_list")
    jax.block_until_ready(ivf_pq.search(sp, idx, q, 10))  # warm host caches
    xla = perf.xla_cost_analysis(lambda qq: ivf_pq.search(sp, idx, qq, 10), q)
    assert xla is not None and xla["flops"] > 0
    padded = int(idx.codes.shape[0] * idx.codes.shape[1])
    an = perf.ivf_pq_scan(nq=128, n_probes=8, n_lists=64, n_rows=padded,
                          dim=32, pq_dim=16, k=10, scanned_lists=64)
    assert 1 / 3 <= an["flops"] / xla["flops"] <= 3.0
    assert 1 / 10 <= an["bytes"] / xla["bytes"] <= 10.0


# ---------------------------------------------------------------------------
# span cost accounting
# ---------------------------------------------------------------------------

def test_span_cost_accumulates_into_counters_and_event(obs_on):
    with obs.span("pipeline.scan"):
        obs.span_cost(flops=100, bytes=10, dtype="bf16")
        obs.span_cost(flops=50, bytes=5, dtype="bf16")  # accumulates
    counters = obs.registry().snapshot()["counters"]
    assert counters["perf.pipeline.scan.flops.bf16"] == 150
    assert counters["perf.pipeline.scan.bytes"] == 15
    ev = obs.bus().events(kind="span")[-1]
    assert ev["cost_flops"] == 150 and ev["cost_bytes"] == 15
    assert ev["cost_dtype"] == "bf16"


def test_span_cost_keeps_mixed_dtypes_separate(obs_on):
    """A span charging an int8 scan and then an f32 rerank must keep
    both sums — collapsing to the last dtype would weigh all the flops
    against the wrong peak (int8 peak is 2x bf16 on v5e)."""
    with obs.span("pipeline.mixed"):
        obs.span_cost(flops=100, dtype="int8")
        obs.span_cost(flops=40, dtype="f32")
    counters = obs.registry().snapshot()["counters"]
    assert counters["perf.pipeline.mixed.flops.int8"] == 100
    assert counters["perf.pipeline.mixed.flops.f32"] == 40
    ev = obs.bus().events(kind="span")[-1]
    assert ev["cost_flops"] == 140
    assert ev["cost_flops_by_dtype"] == {"int8": 100, "f32": 40}


def test_span_cost_disabled_and_outside_span():
    obs.disable()
    obs.reset()
    assert obs.span_cost(flops=1, dtype="f32") is None
    obs.enable()
    try:
        assert obs.span_cost(flops=1, dtype="f32") is None  # no open span
        snap = obs.registry().snapshot()
        assert not any(name.startswith("perf.") and val
                       for name, val in snap["counters"].items())
    finally:
        obs.disable()
        obs.reset()


def test_capture_totals_derive_mfu(obs_on):
    with obs.capture_spans() as cap:
        with obs.span("phase.score"):
            obs.span_cost(flops=10_000_000, bytes=1_000, dtype="f32")
        with obs.span("phase.idle"):
            pass
    totals = cap.totals()
    score = totals["phase.score"]
    assert score["flops"] == 10_000_000 and score["bytes"] == 1_000
    assert score["gflops_per_s"] > 0
    assert 0.0 < score["mfu"]
    assert score["mfu_nominal"] is True  # CPU peaks are placeholders
    assert "flops" not in totals["phase.idle"]  # uncharged spans stay lean


def test_instrumented_searches_charge_cost(obs_on, rng):
    from raft_tpu.neighbors import brute_force, ivf_flat

    data = rng.random((600, 16), dtype=np.float32)
    brute_force.knn(data, data[:8], k=3)
    index = ivf_flat.build(ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=2),
                           data)
    ivf_flat.search(ivf_flat.SearchParams(n_probes=4), index, data[:4], 3)
    counters = obs.registry().snapshot()["counters"]
    knn_flops = [v for n, v in counters.items()
                 if n.startswith("perf.neighbors.brute_force.knn.flops.")]
    assert knn_flops and all(v > 0 for v in knn_flops)
    flat_flops = [v for n, v in counters.items()
                  if n.startswith("perf.neighbors.ivf_flat.search.flops.")]
    assert flat_flops and all(v > 0 for v in flat_flops)


def test_run_case_fenced_mfu(obs_on):
    """The bench row's headline MFU divides charged cost by the FENCED
    timed-loop wall — not the span's host dispatch window, which on an
    async backend closes before the device finishes (the per-span rates
    in `phases` carry that caveat; the row-level number must not)."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench"))
    import common as bench_common

    def fn():
        with obs.span("phase.scan"):
            obs.span_cost(flops=50_000_000, dtype="f32")
        return jnp.ones((4,)) * 2

    rec = bench_common.run_case("t", "case", fn, iters=4, warmup=1)
    assert rec["phases"]["phase.scan"]["calls"] == 4
    assert rec["gflops_per_s"] > 0
    assert 0.0 < rec["mfu"] and rec["mfu_nominal"] is True
    # fenced-loop rate can never exceed the per-span dispatch-window rate
    assert rec["gflops_per_s"] <= \
        rec["phases"]["phase.scan"]["gflops_per_s"] * 1.001


def test_collective_hook_counts_wire_bytes(obs_on):
    obs.collective("allreduce", np.zeros((8,), np.float32), axis="data",
                   world=8)
    counters = obs.registry().snapshot()["counters"]
    assert counters["comms.allreduce.bytes"] == 32
    assert counters["comms.allreduce.wire_bytes"] == int(32 * 2 * 7 / 8)
    ev = obs.bus().events(kind="collective")[-1]
    assert ev["wire_bytes"] == counters["comms.allreduce.wire_bytes"]
    assert ev["world"] == 8


# ---------------------------------------------------------------------------
# report: the MFU section and the merge view (exact snapshots)
# ---------------------------------------------------------------------------

_PERF_SNAP = {
    "platform": {"platform": "tpu-v5e", "device_kind": "TPU v5e",
                 "peak_flops": {"bf16": 197e12, "f32": 197e12,
                                "int8": 394e12},
                 "hbm_Bps": 819e9, "nominal": False},
    "metrics": {
        "counters": {
            "perf.neighbors.ivf_pq.search.flops.bf16": 19_700_000_000_000,
            "perf.neighbors.ivf_pq.search.bytes": 40_960_000_000,
            "perf.neighbors.brute_force.knn.flops.f32": 985_000_000_000,
        },
        "gauges": {},
        "histograms": {
            "span.neighbors.ivf_pq.search": {
                "count": 4, "total": 1.0, "min": 0.2, "max": 0.3,
                "mean": 0.25, "last": 0.25},
            "span.neighbors.brute_force.knn": {
                "count": 1, "total": 0.5, "min": 0.5, "max": 0.5,
                "mean": 0.5, "last": 0.5},
        },
    },
    "events": [],
}

_PERF_EXPECTED = """\
# pinned

events: 0  counters: 3  gauges: 0

## Spans (wall-clock attribution)

span                       calls  total      mean       max
-------------------------  -----  ---------  ---------  ---------
neighbors.brute_force.knn  1      500.00 ms  500.00 ms  500.00 ms
neighbors.ivf_pq.search    4      1.000 s    250.00 ms  300.00 ms

## Cost attribution (analytic model over span host-time; MFU vs tpu-v5e peak)

span                       flops       dtype  GFLOP/s   MFU     bytes/s
-------------------------  ----------  -----  --------  ------  ----------
neighbors.brute_force.knn  985 GFLOP   f32    1970      1.00%   -
neighbors.ivf_pq.search    19.7 TFLOP  bf16   1.97e+04  10.00%  38.1 GiB/s
"""


def _lines(text):
    # table cells are right-padded; trailing spaces are presentation,
    # not contract — everything else is pinned byte-exact
    return [l.rstrip() for l in text.splitlines()]


def test_report_perf_section_exact_snapshot():
    """Exact render pin: 19.7 TFLOP of bf16 over 1 s against the 197
    TFLOP/s v5e peak MUST read 10.00% MFU — the arithmetic the roofline
    work (ROADMAP item 1) is judged by."""
    out = obs_report.render(_PERF_SNAP, title="pinned")
    assert _lines(out) == _lines(_PERF_EXPECTED)


def test_report_perf_section_tags_nominal_cpu():
    snap = json.loads(json.dumps(_PERF_SNAP))  # deep copy
    snap["platform"] = {"platform": "cpu", "peak_flops": {"f32": 50e9,
                                                          "bf16": 50e9},
                        "nominal": True}
    out = obs_report.render(snap)
    assert "NOMINAL peaks, not a hardware claim" in out


def _rank_snap(rank, slow):
    return {
        "rank": rank, "world": 2,
        "metrics": {
            "counters": {
                "comms.allreduce.calls": 3 if rank == 0 else 2,
                "comms.allreduce.bytes": 3072 if rank == 0 else 2048,
                "comms.allgather.calls": 1, "comms.allgather.bytes": 512,
            },
            "gauges": {},
            "histograms": {
                "span.mnmg.knn": {"count": 2, "total": slow, "min": 0.1,
                                  "max": slow, "mean": slow / 2,
                                  "last": 0.1},
            },
        },
        "events": [
            {"seq": 5, "t": 0.0, "kind": "fault", "site": "comms.allreduce",
             "action": "drop"},
            {"seq": 9, "t": 0.0, "kind": "health", "rank": 1,
             "healthy": rank != 0},
        ],
    }


_MERGE_EXPECTED = """\
# pinned merge

ranks merged: 2  world: 2

## Per-rank span attribution

span      r0         r1         skew
--------  ---------  ---------  -----
mnmg.knn  900.00 ms  200.00 ms  4.50x

straggler: span 'mnmg.knn' slowest on rank 0 (4.50x the fastest rank)

## Collective skew (per-rank calls / payload bytes)

collective  calls r0/r1  bytes
----------  -----------  ---------------
allgather   1/1          512 B/512 B
allreduce   3/2          3.0 KiB/2.0 KiB

DESYNC: collective 'allreduce' call counts differ across ranks (3/2) \
— a rank is missing collectives (hang risk)

## Merged timeline (fault, health; aligned by per-rank seq; last 60)

r0 #5     fault    action=drop site=comms.allreduce
r1 #5     fault    action=drop site=comms.allreduce
r0 #9     health   healthy=False rank=1
r1 #9     health   healthy=True rank=1
"""


def test_report_merge_exact_snapshot():
    """Exact merge pin: rank ordering comes from the snapshots' rank
    fields (inputs deliberately passed out of order), the straggler line
    names the slow rank with its skew, the call-count mismatch surfaces
    as a DESYNC, and the timeline interleaves by (seq, rank)."""
    out = obs_report.render_merged([_rank_snap(1, 0.2), _rank_snap(0, 0.9)],
                                   title="pinned merge")
    assert _lines(out) == _lines(_MERGE_EXPECTED)


def test_report_cli_merge_and_single(tmp_path, capsys):
    p0 = tmp_path / "r0.json"
    p1 = tmp_path / "r1.json"
    p0.write_text(json.dumps(_rank_snap(0, 0.9)))
    p1.write_text(json.dumps(_rank_snap(1, 0.2)))
    assert obs_report.main([str(p0), str(p1), "--merge"]) == 0
    out = capsys.readouterr().out
    assert "ranks merged: 2" in out and "straggler" in out
    # multiple files without --merge is a usage error
    with pytest.raises(SystemExit):
        obs_report.main([str(p0), str(p1)])
    capsys.readouterr()
    assert obs_report.main([str(p0)]) == 0  # single file still renders
    assert "raft_tpu run report" in capsys.readouterr().out


def test_snapshot_carries_rank_and_platform(obs_on, tmp_path):
    path = tmp_path / "snap.json"
    snap = obs.save_snapshot(str(path), rank=3, world=8, label="drill")
    assert snap["rank"] == 3 and snap["world"] == 8
    assert snap["label"] == "drill"
    assert snap["platform"]["platform"] == "cpu"
    assert json.loads(path.read_text())["rank"] == 3


# ---------------------------------------------------------------------------
# ledger
# ---------------------------------------------------------------------------

def test_ledger_roundtrip_and_torn_line(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    e1 = ledger.make_entry(bench="b", row={"case": "x", "value": 1.0,
                                           "unit": "qps"},
                           platform="cpu", sha="abc1234")
    ledger.append(e1, path=path)
    with open(path, "a") as f:
        f.write('{"sha": "torn')  # SIGKILL mid-append
    e2 = ledger.make_entry(bench="b", row={"case": "x", "value": 2.0,
                                           "unit": "qps"},
                           platform="cpu", sha="def5678", fallback="in_process_cpu")
    ledger.append(e2, path=path)
    rows = ledger.read(path)
    assert [e["sha"] for e in rows] == ["abc1234", "def5678"]
    assert rows[0]["bench"] == "b" and rows[0]["platform"] == "cpu"
    assert rows[0]["row"] == {"case": "x", "value": 1.0, "unit": "qps"}
    assert rows[1]["fallback"] == "in_process_cpu"
    assert "utc" in rows[0]
    assert ledger.read(str(tmp_path / "missing.jsonl")) == []


def test_ledger_env_override_and_git_sha(tmp_path, monkeypatch):
    target = str(tmp_path / "override.jsonl")
    monkeypatch.setenv(ledger.ENV_PATH, target)
    assert ledger.resolve_path("/elsewhere") == target
    monkeypatch.delenv(ledger.ENV_PATH)
    assert ledger.resolve_path(str(tmp_path)) == \
        os.path.join(str(tmp_path), ledger.DEFAULT_NAME)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sha = ledger.git_sha(repo)
    assert sha == "unknown" or all(c in "0123456789abcdef" for c in sha)
    assert ledger.git_sha(str(tmp_path)) == "unknown"  # not a repo


def test_banker_rows_reach_ledger(tmp_path, monkeypatch):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench"))
    import common

    path = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv(ledger.ENV_PATH, path)
    # plain CPU rehearsal: diverted results file, honestly tagged ledger row
    bank = common.Banker(str(tmp_path / "BENCH_x.json"), meta={})
    bank.add({"case": "qps", "value": 123.0, "unit": "qps"}, echo=False)
    # engaged fallback: real file, fallback-tagged ledger row
    fb = common.Banker(str(tmp_path / "BENCH_y.json"),
                       fallback="in_process_cpu")
    fb.add({"case": "qps", "value": 99.0, "unit": "qps"}, echo=False)
    rows = ledger.read(path)
    assert len(rows) == 2
    assert rows[0]["bench"] == "BENCH_x" and rows[0]["platform"] == "cpu"
    assert rows[0]["cpu_rehearsal"] is True and "fallback" not in rows[0]
    assert rows[1]["bench"] == "BENCH_y"
    assert rows[1]["fallback"] == "in_process_cpu"
    assert all("sha" in e for e in rows)


# ---------------------------------------------------------------------------
# serve + MNMG wiring
# ---------------------------------------------------------------------------

def test_serve_latencies_feed_bucketed_histogram(obs_on):
    from raft_tpu.serve.metrics import ServerMetrics

    m = ServerMetrics(latency_window=8)
    m.observe_batch(n_requests=2, valid_rows=2, bucket_rows=4,
                    latencies_s=[0.003, 0.2])
    h = obs.histogram("serve.latency_s")
    assert h.aggregate()["count"] == 2
    buckets = dict(h.bucket_counts())
    assert buckets["0.005"] == 1 and buckets["+Inf"] == 2
    # the exposition surface renders them as real series
    text = obs.render_registry_prometheus()
    assert 'raft_tpu_serve_latency_s_bucket{le="+Inf"} 2' in text


def test_mnmg_driver_saves_rank_snapshot(obs_on, tmp_path, monkeypatch, rng):
    from raft_tpu.comms import mnmg
    from raft_tpu.comms.comms import Comms

    monkeypatch.setenv("RAFT_TPU_OBS_RANK_DIR", str(tmp_path))
    comms = Comms()
    data = rng.random((64, 8), dtype=np.float32)
    mnmg.knn(comms, data, data[:4], k=3)
    path = tmp_path / "obs_rank000.json"
    assert path.exists()
    snap = json.loads(path.read_text())
    assert snap["rank"] == 0 and snap["world"] == 8
    assert snap["label"] == "mnmg.knn"
    # the capture includes the driver's own closed span and its cost
    span_evs = [e for e in snap["events"] if e.get("kind") == "span"
                and e.get("name") == "mnmg.knn"]
    assert span_evs and span_evs[-1]["cost_flops"] > 0
    assert any(n.startswith("perf.mnmg.knn.flops.")
               for n, v in snap["metrics"]["counters"].items() if v)


def test_mnmg_driver_no_snapshot_when_env_unset(obs_on, tmp_path, rng):
    from raft_tpu.comms import mnmg
    from raft_tpu.comms.comms import Comms

    comms = Comms()
    data = rng.random((64, 8), dtype=np.float32)
    mnmg.knn(comms, data, data[:4], k=3)
    assert not list(tmp_path.iterdir())


def test_mnmg_driver_keyword_first_arg_still_works(obs_on, tmp_path,
                                                   monkeypatch, rng):
    """rank_captured must not change the call surface: the session
    passed by KEYWORD still works and still resolves the rank file."""
    from raft_tpu.comms import mnmg
    from raft_tpu.comms.comms import Comms

    monkeypatch.setenv("RAFT_TPU_OBS_RANK_DIR", str(tmp_path))
    data = rng.random((64, 8), dtype=np.float32)
    mnmg.knn(comms=Comms(), dataset=data, queries=data[:4], k=3)
    assert (tmp_path / "obs_rank000.json").exists()
