"""Adaptive probing suite (neighbors/probe_budget, ISSUE 12).

Pins the three contracts the feature rests on:

  1. SATURATION BIT-IDENTITY — `recall_target=1.0` (and any saturated
     budget) is bit-identical to the fixed-`n_probes` reference on all
     three engine families, every sub-engine, single-rank AND MNMG on
     the 8-device mesh.
  2. EARLY-TERMINATION SOUNDNESS (oracle) — with valid bounds and
     saturated budgets, bound-based list skipping NEVER drops a true
     top-k neighbor: IVF-Flat results equal the fixed path exactly.
  3. TRUTHFUL ACCOUNTING — `ivf.scanned_lists` / `ivf.budget_hist`
     record the actual per-batch work, and shrunken budgets shrink it.

Plus unit coverage of the budget math, policy resolution, serialization
of the stored bounds, and the serve-layer plumbing (per-request
recall_target, batch coalescing, probe_key folding, the _scaled_probes
floor rule).
"""

import os
import tempfile

import numpy as np
import jax.numpy as jnp
import pytest

from raft_tpu.core import faults
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.neighbors import ivf_flat, ivf_pq, ivf_rabitq, probe_budget

SEED = int(os.environ.get(faults.ENV_SEED, "1234"))


@pytest.fixture(scope="module")
def clustered():
    """Clustered data: the coarse gap profile has real signal, which is
    the regime adaptive budgets exist for."""
    rng = np.random.default_rng(SEED)
    cent = rng.normal(size=(16, 32)) * 8
    data = (cent[rng.integers(0, 16, 4000)]
            + rng.normal(size=(4000, 32))).astype(np.float32)
    return data


@pytest.fixture(scope="module")
def flat16(clustered):
    return ivf_flat.build(
        ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=6), clustered)


@pytest.fixture(scope="module")
def pq16(clustered):
    return ivf_pq.build(
        ivf_pq.IndexParams(n_lists=16, pq_dim=16, kmeans_n_iters=4),
        clustered)


@pytest.fixture(scope="module")
def rabitq16(clustered):
    return ivf_rabitq.build(
        ivf_rabitq.IndexParams(n_lists=16, kmeans_n_iters=4), clustered)


# -- unit: budget math --------------------------------------------------


def test_assign_budgets_profile_semantics():
    # sorted best-first coarse scores with one sharp gap after 2 lists
    cvals = jnp.asarray([[1.0, 1.1, 9.0, 9.1, 9.2, 9.3]])
    b_tight = int(probe_budget.assign_budgets(cvals, True, 0.2, 1)[0])
    assert b_tight == 2  # the gap cuts the profile
    b_sat = int(probe_budget.assign_budgets(cvals, True, 1.0, 1)[0])
    assert b_sat == 6  # tau >= 1 saturates
    b_floor = int(probe_budget.assign_budgets(cvals, True, 0.0, 3)[0])
    assert b_floor == 3  # clamped to min_probes


def test_assign_budgets_ip_orientation():
    # IP scores descend best-first; same gap semantics, flipped sign
    cvals = jnp.asarray([[9.0, 8.9, 1.0, 0.9]])
    assert int(probe_budget.assign_budgets(cvals, False, 0.2, 1)[0]) == 2


def test_assign_budgets_degenerate_flat_profile():
    # identical coarse scores: zero gaps everywhere -> keep everything
    cvals = jnp.full((3, 5), 2.0)
    b = probe_budget.assign_budgets(cvals, True, 0.5, 1)
    assert (np.asarray(b) == 5).all()


def test_plan_monotone_in_tau(flat16, clustered):
    q = clustered[:64]
    scans = []
    for tau in (0.1, 0.4, 0.8, 1.0):
        _, scanned = probe_budget.probe_plan(
            q, flat16.centers, n_probes=8, min_probes=1, k=10,
            metric=flat16.metric, tau=tau)
        scans.append(int(np.asarray(scanned).sum()))
    assert scans == sorted(scans), scans  # larger tau never scans less
    assert scans[-1] == 64 * 8  # tau=1.0 saturates


def test_early_term_bounds_sound_vs_oracle(flat16, clustered):
    """Every dropped list's true minimum member distance must exceed
    the query's true k-th distance within the kept set — the bound can
    never drop a true top-k neighbor."""
    q = clustered[:32]
    keep, _ = probe_budget.probe_plan(
        q, flat16.centers, n_probes=8, min_probes=1, k=10,
        metric=flat16.metric, tau=1.0,
        radii=flat16.list_radii, sizes=flat16.list_sizes)
    fixed = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=8), flat16, q, 10)
    et = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=8, budget_tau=1.0, early_term=True),
        flat16, q, 10)
    np.testing.assert_array_equal(np.asarray(et[1]), np.asarray(fixed[1]))
    np.testing.assert_array_equal(np.asarray(et[0]), np.asarray(fixed[0]))
    # and the mask really dropped something, so the oracle is not vacuous
    assert int(np.asarray(keep).sum()) < 32 * 8


def test_policy_resolution_tuned_and_default(monkeypatch):
    from raft_tpu.core import tuned

    assert probe_budget.resolve_tau(1.0) == 1.0
    assert probe_budget.resolve_tau(None) == \
        probe_budget.DEFAULT_POLICY["default_tau"]
    # default table: a target inside the table picks its banked tau
    assert probe_budget.resolve_tau(0.9) == 0.45
    # above every banked target: saturate
    assert probe_budget.resolve_tau(0.999) == 1.0
    # a banked per-index calibration wins over the built-in
    monkeypatch.setattr(tuned, "get", lambda key, default=None: {
        "default_tau": 0.5, "targets": [[0.9, 0.11], [0.95, 0.33]],
    } if key == probe_budget.POLICY_KEY else default)
    assert probe_budget.resolve_tau(0.9) == 0.11
    assert probe_budget.resolve_tau(0.93) == 0.33
    # a corrupt tuned value degrades to the built-in, never crashes
    monkeypatch.setattr(tuned, "get", lambda key, default=None: "garbage")
    assert probe_budget.resolve_tau(0.9) == 0.45
    # ... and ONE malformed entry inside an otherwise-valid table is
    # skipped (a sort over raw entries used to crash every request)
    monkeypatch.setattr(tuned, "get", lambda key, default=None: {
        "targets": [["oops", 0.5], [0.95, 0.4]],
    } if key == probe_budget.POLICY_KEY else default)
    assert probe_budget.resolve_tau(0.9) == 0.4


def test_resolve_params_fixed_vs_adaptive():
    p = ivf_flat.SearchParams(n_probes=8)
    assert probe_budget.resolve_params(p, 8) is None
    ap = probe_budget.resolve_params(
        ivf_flat.SearchParams(n_probes=8, recall_target=0.9), 8)
    assert ap is not None and ap.tau < 1.0 and ap.early_term
    # recall_target=1.0 saturates AND disables bounds (bit-identity)
    sat = probe_budget.resolve_params(
        ivf_flat.SearchParams(n_probes=8, recall_target=1.0), 8)
    assert sat.tau == 1.0 and not sat.early_term
    # an explicit budget_tau keeps the caller's early_term choice
    et = probe_budget.resolve_params(
        ivf_flat.SearchParams(n_probes=8, budget_tau=1.0), 8)
    assert et.tau == 1.0 and et.early_term


# -- saturation bit-identity, all engines -------------------------------


@pytest.mark.parametrize("engine", ["query", "list", "pallas"])
def test_flat_saturated_bit_identical(flat16, clustered, engine):
    q = clustered[:48]
    fixed = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=8, engine=engine), flat16, q, 10)
    sat = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=8, engine=engine, recall_target=1.0),
        flat16, q, 10)
    np.testing.assert_array_equal(np.asarray(fixed[0]), np.asarray(sat[0]))
    np.testing.assert_array_equal(np.asarray(fixed[1]), np.asarray(sat[1]))


@pytest.mark.parametrize("cfg", [
    dict(score_mode="lut"),
    dict(score_mode="recon8"),
    dict(score_mode="recon8_list"),
    dict(score_mode="recon8_list", trim_engine="exact"),
    dict(score_mode="recon8_list", trim_engine="fused"),
    dict(score_mode="recon8_list", trim_engine="fused", score_dtype="int8"),
], ids=["lut", "recon8", "list", "list_exact", "fused", "fused_int8"])
def test_pq_saturated_bit_identical(pq16, clustered, cfg):
    q = clustered[:48]
    fixed = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=8, **cfg), pq16, q, 10)
    sat = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=8, recall_target=1.0, **cfg),
        pq16, q, 10)
    np.testing.assert_array_equal(np.asarray(fixed[0]), np.asarray(sat[0]))
    np.testing.assert_array_equal(np.asarray(fixed[1]), np.asarray(sat[1]))


@pytest.mark.parametrize("engine", ["xla", "fused"])
def test_rabitq_saturated_bit_identical(rabitq16, clustered, engine):
    q = clustered[:48]
    fixed = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=8, scan_engine=engine),
        rabitq16, q, 10)
    sat = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=8, scan_engine=engine,
                                recall_target=1.0),
        rabitq16, q, 10)
    np.testing.assert_array_equal(np.asarray(fixed[0]), np.asarray(sat[0]))
    np.testing.assert_array_equal(np.asarray(fixed[1]), np.asarray(sat[1]))


def test_mnmg_saturated_bit_identical_all_kinds(clustered):
    """Distributed saturation bit-identity on the 8-device mesh — the
    replicated coarse geometry makes one plan the every-rank plan, and
    a saturated plan must vanish entirely."""
    from raft_tpu.comms import Comms, mnmg

    comms = Comms()
    q = clustered[:16]
    fidx = mnmg.ivf_flat_build(
        comms, ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4), clustered)
    pidx = mnmg.ivf_pq_build(
        comms, ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=4),
        clustered)
    ridx = mnmg.ivf_rabitq_build(
        comms, ivf_rabitq.IndexParams(n_lists=8, kmeans_n_iters=4),
        clustered)
    cases = [
        lambda **ad: mnmg.ivf_flat_search(fidx, q, 10, n_probes=4,
                                          engine="query", **ad),
        lambda **ad: mnmg.ivf_flat_search(fidx, q, 10, n_probes=4,
                                          engine="list", **ad),
        lambda **ad: mnmg.ivf_pq_search(pidx, q, 10, n_probes=4,
                                        engine="recon8_list", **ad),
        lambda **ad: mnmg.ivf_pq_search(pidx, q, 10, n_probes=4,
                                        engine="lut", **ad),
        lambda **ad: mnmg.ivf_rabitq_search(ridx, q, 10, n_probes=4, **ad),
    ]
    for case in cases:
        fv, fi = case()
        sv, si = case(recall_target=1.0)
        np.testing.assert_array_equal(np.asarray(fv), np.asarray(sv))
        np.testing.assert_array_equal(np.asarray(fi), np.asarray(si))
        # shrunken budgets still return full-shape, valid results
        av, ai = case(budget_tau=0.3)
        assert np.asarray(ai).shape == np.asarray(fi).shape
        assert (np.asarray(ai) >= 0).any()


# -- budgets do real work, recall holds ---------------------------------


@pytest.mark.parametrize("make_search", [
    lambda q, idx, **kw: ivf_flat.search(
        ivf_flat.SearchParams(n_probes=8, **kw), idx, q, 10),
], ids=["flat"])
def test_adaptive_recall_vs_scanned(flat16, clustered, make_search):
    """On clustered data a modest tau reaches the fixed-probe recall
    while scanning well under the worst case — the banked-frontier
    claim, pinned at smoke scale."""
    q = clustered[:128]
    fixed_v, fixed_i = make_search(q, flat16)
    _, scanned = probe_budget.probe_plan(
        q, flat16.centers, n_probes=8, min_probes=1, k=10,
        metric=flat16.metric, tau=0.45,
        radii=flat16.list_radii, sizes=flat16.list_sizes)
    frac = float(np.asarray(scanned).sum()) / (128 * 8)
    av, ai = make_search(q, flat16, budget_tau=0.45, early_term=True)
    recall = np.mean([
        len(set(a.tolist()) & set(b.tolist())) / 10
        for a, b in zip(np.asarray(ai), np.asarray(fixed_i))])
    assert frac <= 0.6, frac
    assert recall >= 0.998, (recall, frac)


def test_scanned_counters_and_hist(flat16, clustered):
    from raft_tpu import obs

    q = clustered[:32]
    obs.enable()
    try:
        base = obs.counter("ivf.scanned_lists").value
        ivf_flat.search(
            ivf_flat.SearchParams(n_probes=8, budget_tau=0.3), flat16, q, 10)
        scanned = obs.counter("ivf.scanned_lists").value - base
        worst = 32 * 8
        assert 32 <= scanned < worst  # real work, less than worst case
        h = obs.histogram("ivf.budget_hist")
        assert h.count >= 32  # one observation per query
    finally:
        obs.disable()


def test_cost_model_charges_actual_scan(flat16, clustered):
    """The cost model's scanned_lists charge follows the budgets: a
    shrunken plan charges fewer flops than the fixed plan."""
    from raft_tpu import obs

    q = clustered[:64]
    obs.enable()
    try:
        with obs.span("fixed_probe_span"):
            obs.span_cost(**obs.perf.cost_for(
                "neighbors.ivf_flat.search", nq=64, n_probes=8, n_lists=16,
                n_rows=4096, dim=32, k=10, scanned_lists=8))
        with obs.span("adaptive_probe_span"):
            obs.span_cost(**obs.perf.cost_for(
                "neighbors.ivf_flat.search", nq=64, n_probes=8, n_lists=16,
                n_rows=4096, dim=32, k=10, scanned_lists=2.5))
        snap = obs.snapshot()["metrics"]["counters"]
        fixed_fl = sum(v for k, v in snap.items()
                       if k.startswith("perf.fixed_probe_span.flops"))
        adapt_fl = sum(v for k, v in snap.items()
                       if k.startswith("perf.adaptive_probe_span.flops"))
        assert adapt_fl < fixed_fl
    finally:
        obs.disable()


# -- bounds storage lifecycle -------------------------------------------


def test_flat_radii_roundtrip_and_extend(clustered, tmp_path):
    idx = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4), clustered[:2000])
    r0 = np.asarray(idx.list_radii)
    ext = ivf_flat.extend(idx, clustered[2000:2500])
    r1 = np.asarray(ext.list_radii)
    assert (r1 >= r0 - 1e-6).all()  # max-fold is monotone
    # radii are genuine bounds over the extended store
    d2 = np.array(jnp.sum(
        (ext.list_data.astype(jnp.float32)
         - ext.centers[:, None, :]) ** 2, axis=2))
    d2[np.asarray(ext.slot_rows) < 0] = 0.0
    np.testing.assert_allclose(np.sqrt(d2.max(axis=1)), r1, rtol=1e-5,
                               atol=1e-5)
    p = str(tmp_path / "idx.bin")
    ivf_flat.save(p, ext)
    loaded = ivf_flat.load(p)
    np.testing.assert_array_equal(np.asarray(loaded.list_radii), r1)


def test_old_checkpoint_without_radii_falls_back(clustered, tmp_path, monkeypatch):
    """A checkpoint written without bounds loads with list_radii=None
    and adaptive searches run budgets-only (never crash)."""
    idx = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4), clustered[:2000])
    idx.list_radii = None  # simulate the old format
    p = str(tmp_path / "old.bin")
    ivf_flat.save(p, idx)
    loaded = ivf_flat.load(p)
    assert loaded.list_radii is None
    q = clustered[:8]
    v, i = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=4, budget_tau=0.5, early_term=True),
        loaded, q, 5)
    assert np.asarray(i).shape == (8, 5)
    # extend on a radii-less index keeps the fallback (no fake bounds)
    ext = ivf_flat.extend(loaded, clustered[2000:2100])
    assert ext.list_radii is None


def test_adaptive_centers_invalidate_bounds(clustered):
    idx = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4,
                             adaptive_centers=True), clustered[:2000])
    # the build's own extend already ran under adaptive_centers
    assert idx.list_radii is None


def test_pq_radii_roundtrip(pq16, tmp_path):
    assert pq16.list_radii is not None
    p = str(tmp_path / "pq.bin")
    ivf_pq.save(p, pq16)
    loaded = ivf_pq.load(p)
    np.testing.assert_array_equal(
        np.asarray(loaded.list_radii), np.asarray(pq16.list_radii))


def test_rabitq_radii_derive_from_aux(rabitq16):
    r = np.asarray(rabitq16.list_radii)
    rn = np.array(rabitq16.aux[..., 0])
    rn[np.asarray(rabitq16.slot_rows) < 0] = 0.0
    np.testing.assert_allclose(r, rn.max(axis=1), rtol=1e-6)


# -- prefilter composes with budgets ------------------------------------


def test_adaptive_composes_with_prefilter(flat16, clustered):
    q = clustered[:16]
    mask = np.zeros(flat16.size, bool)
    mask[::2] = True
    fv, fi = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=8), flat16, q, 10, prefilter=mask)
    av, ai = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=8, recall_target=1.0), flat16, q, 10,
        prefilter=mask)
    np.testing.assert_array_equal(np.asarray(fi), np.asarray(ai))
    ai2 = np.asarray(ivf_flat.search(
        ivf_flat.SearchParams(n_probes=8, budget_tau=0.4), flat16, q, 10,
        prefilter=mask)[1])
    assert ((ai2 % 2 == 0) | (ai2 == -1)).all()  # filter still honored


def test_early_term_disabled_under_prefilter(flat16, clustered):
    """Bounds must NOT engage under a prefilter: list_sizes counts
    filtered members, so a bound's k-covering prefix could be entirely
    filtered out and a list holding the only ELIGIBLE neighbors would
    be skipped. With saturated budgets + early_term + a hostile filter
    the result must equal the fixed reference bit for bit (bounds
    silently fall back to budgets-only)."""
    q = clustered[:16]
    # hostile filter: keep only a thin slice of the index, so most
    # lists' "covering" members are filtered away
    mask = np.zeros(flat16.size, bool)
    mask[: flat16.size // 10] = True
    fv, fi = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=8), flat16, q, 10, prefilter=mask)
    ev, ei = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=8, budget_tau=1.0, early_term=True),
        flat16, q, 10, prefilter=mask)
    np.testing.assert_array_equal(np.asarray(fv), np.asarray(ev))
    np.testing.assert_array_equal(np.asarray(fi), np.asarray(ei))


# -- serve plumbing -----------------------------------------------------


@pytest.mark.parametrize("n_probes,scale,want", [
    (2, 0.25, 1),   # floor(0.5) -> min 1
    (6, 0.25, 1),   # floor(1.5) = 1 (round() used to give 2)
    (8, 0.25, 2),
    (20, 0.5, 10),
    (20, 1.0, 20),
    (1, 0.1, 1),
])
def test_scaled_probes_floor_rule(n_probes, scale, want):
    from raft_tpu.serve.engine import _scaled_probes

    assert _scaled_probes(n_probes, scale) == want


def test_serve_recall_target_end_to_end(flat16, clustered):
    """Per-request recall_target flows submit -> batch -> searcher;
    recall_target=1.0 replies are bit-identical to plain requests, and
    mixed targets never share a batch."""
    from raft_tpu import serve

    q = clustered[:4]
    server = serve.SearchServer(
        flat16, serve.ServerConfig(buckets=(8,)),
        search_params=ivf_flat.SearchParams(n_probes=8, engine="query"))
    plain = server.submit(q, k=5)
    server.step()
    sat = server.submit(q, k=5, recall_target=1.0)
    server.step()
    tight = server.submit(q, k=5, recall_target=0.9)
    server.step()
    pv, sv, tv = plain.result(1), sat.result(1), tight.result(1)
    np.testing.assert_array_equal(pv.values, sv.values)
    np.testing.assert_array_equal(pv.ids, sv.ids)
    assert tv.ids.shape == (4, 5)

    # mixed-target coalescing: same k, different targets -> two batches
    a = server.submit(q, k=5, recall_target=0.9)
    b = server.submit(q, k=5, recall_target=0.95)
    served_first = server.step()
    assert served_first == 1  # only the first target's batch
    server.step()
    assert a.done() and b.done()


def test_serve_probe_key_folds_budget(flat16):
    from raft_tpu import serve

    s = serve.IvfFlatSearcher(
        flat16, ivf_flat.SearchParams(n_probes=8, engine="query"))
    fixed_key = s.probe_key(1.0)
    ad_key = s.probe_key(1.0, recall_target=0.9)
    sat_key = s.probe_key(1.0, recall_target=1.0)
    assert fixed_key != ad_key  # adaptive plan = different program
    assert ad_key != sat_key or ad_key[1] == sat_key[1]
    # overload scale still folds through as the n_probes cap
    assert s.probe_key(0.25)[0] == 2


def test_serve_recall_target_validation(flat16):
    from raft_tpu import serve

    server = serve.SearchServer(
        flat16, serve.ServerConfig(buckets=(8,)),
        search_params=ivf_flat.SearchParams(n_probes=8, engine="query"))
    with pytest.raises(ValueError, match="recall_target"):
        server.submit(np.zeros((1, 32), np.float32), k=3, recall_target=1.5)


# -- chaos: the ivf.probe_budget site -----------------------------------


def test_probe_budget_fault_site_registered():
    assert probe_budget.BUDGET_SITE in faults.known_sites()
