"""Bench orchestration logic (bench.py parent/child protocol).

The headline bench must always emit one JSON record even when the
single-client TPU tunnel wedges or a worker crashes mid-run (observed
failure modes; see bench.py _run_child). These tests drive main()'s attempt
loop hermetically with stubbed children — no device, no subprocesses.
"""

import contextlib
import io
import json

import os
import sys

import numpy as np
import pytest

import bench

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench"))
import common  # noqa: E402
import tpu_profile  # noqa: E402


@pytest.fixture
def quiet(monkeypatch, tmp_path):
    # healthy chip by default: the probe returning True keeps children on
    # the full-hour leash (the wedged branch has its own dedicated test);
    # the relay transport reads healthy too (this test box genuinely runs
    # behind a relay, so the real check must be stubbed both ways)
    monkeypatch.setattr(bench, "_wait_for_backend", lambda *a, **k: True)
    monkeypatch.setattr(bench, "_axon_relay_down", lambda: False)
    monkeypatch.setattr(bench, "_PARTIAL_PATH", str(tmp_path / "partial.jsonl"))
    monkeypatch.setattr(bench, "_LAST_GOOD_PATH", str(tmp_path / "last_good.json"))
    # ledger rows from stubbed test sessions must never land in the
    # repo's real BENCH_LEDGER.jsonl
    monkeypatch.setenv("RAFT_TPU_BENCH_LEDGER", str(tmp_path / "ledger.jsonl"))
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.delenv("RAFT_TPU_BENCH_CHILD", raising=False)


def run_main():
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    return json.loads(buf.getvalue().strip().splitlines()[-1])


def test_first_attempt_wins(quiet, monkeypatch):
    calls = []

    def child(kind, t):
        calls.append(kind)
        return {"metric": "m", "value": 42}, True

    monkeypatch.setattr(bench, "_run_child", child)
    rec = run_main()
    assert rec["value"] == 42
    assert calls == ["ivf"]


def test_transient_failures_retry_then_fall_back(quiet, monkeypatch):
    calls = []

    def child(kind, t):
        calls.append(kind)
        return None, True  # crash/timeout after doing real work

    monkeypatch.setattr(bench, "_run_child", child)
    rec = run_main()
    assert calls == ["ivf", "ivf", "bf"]
    assert rec["metric"] == bench._HEADLINE_METRIC
    assert rec["value"] == 0.0 and "error" in rec


def test_deterministic_failure_skips_identical_retry(quiet, monkeypatch):
    calls = []

    def child(kind, t):
        calls.append(kind)
        if kind == "ivf":
            return {"deterministic_failure": "recall gate"}, True
        return {"metric": "bf_fallback", "value": 1}, True

    monkeypatch.setattr(bench, "_run_child", child)
    rec = run_main()
    assert calls == ["ivf", "bf"], "second identical ivf attempt must be skipped"
    assert rec["metric"] == "bf_fallback"


def test_jax_runtime_errors_are_not_deterministic():
    # jax's runtime errors subclass RuntimeError; the child must not
    # classify them as deterministic (a fresh process CAN recover them)
    import jax

    assert issubclass(jax.errors.JaxRuntimeError, RuntimeError)
    assert not issubclass(jax.errors.JaxRuntimeError, bench.DeterministicBenchFailure)


def test_recall_gate_is_deterministic():
    assert issubclass(bench.DeterministicBenchFailure, RuntimeError)


def test_wedged_chip_shortens_child_timeout(quiet, monkeypatch):
    # when the readiness probe fails, children must not get the full-hour
    # leash (they would block in backend init until it expires)
    monkeypatch.setattr(bench, "_wait_for_backend", lambda *a, **k: False)
    timeouts = []

    def child(kind, t):
        timeouts.append(t)
        return {"metric": "m", "value": 1}, True

    monkeypatch.setattr(bench, "_run_child", child)
    run_main()
    assert timeouts == [600]


def test_healthy_chip_keeps_full_timeout(quiet, monkeypatch):
    timeouts = []

    def child(kind, t):
        timeouts.append(t)
        return {"metric": "m", "value": 1}, True

    monkeypatch.setattr(bench, "_run_child", child)
    run_main()
    assert timeouts == [3600]


def test_dead_relay_minimizes_child_leash(quiet, monkeypatch):
    # transport structurally dead: children exist only to catch a relay
    # restart, so the leash drops to 120 s
    monkeypatch.setattr(bench, "_wait_for_backend", lambda *a, **k: False)
    monkeypatch.setattr(bench, "_axon_relay_down", lambda: True)
    timeouts = []

    def child(kind, t):
        timeouts.append(t)
        return {"metric": "m", "value": 1}, True

    monkeypatch.setattr(bench, "_run_child", child)
    run_main()
    assert timeouts == [120]


def test_hung_child_flips_to_short_leashes(quiet, monkeypatch):
    # a child that times out with NO progress signals a lost backend; when
    # the one allowed reprobe confirms the loss, remaining attempts drop
    # to short leashes instead of burning hours
    probes = []

    def probe(*a, **k):
        probes.append(1)
        return len(probes) == 1  # healthy at start, lost afterwards

    monkeypatch.setattr(bench, "_wait_for_backend", probe)
    timeouts = []

    def child(kind, t):
        timeouts.append(t)
        return None, False

    monkeypatch.setattr(bench, "_run_child", child)
    rec = run_main()
    assert timeouts[0] == 3600 and all(t <= 600 for t in timeouts[1:]), timeouts
    assert rec["value"] == 0.0


def test_partial_results_recovered_after_total_failure(quiet, monkeypatch):
    # a killed child's persisted ladder entries become the final record
    def child(kind, t):
        bench._record_partial(
            {"qps": 5000.0, "recall": 0.97, "mode": "recon8_list",
             "n_probes": 8, "refine": True}
        )
        return None, True

    monkeypatch.setattr(bench, "_run_child", child)
    rec = run_main()
    assert rec["value"] == 5000.0 and rec["partial"] is True
    assert rec["recall_gate"] == bench._RECALL_GATE


def test_partial_recovery_skips_smoke_and_suspect_rows(quiet, monkeypatch):
    # the 2026-08-01 incident: a CPU smoke row and a contention artifact
    # (2.2M "qps") landed in a live chip session's partial file; tagged
    # rows must never be recoverable as that session's best
    def child(kind, t):
        bench._record_partial(
            {"qps": 2207548.0, "recall": 0.996, "mode": "recon8_list",
             "n_probes": 16, "refine": True, "suspect": True})
        bench._record_partial(
            {"qps": 16710.0, "recall": 1.0, "mode": "bf_tiled",
             "n_probes": None, "refine": False, "smoke": True})
        bench._record_partial(
            {"qps": 5000.0, "recall": 0.97, "mode": "recon8_list",
             "n_probes": 8, "refine": True})
        return None, True

    monkeypatch.setattr(bench, "_run_child", child)
    rec = run_main()
    assert rec["value"] == 5000.0 and rec["partial"] is True


def test_keep_partial_preserves_session_rows(quiet, monkeypatch):
    # the queue's end-of-session tuned-keys re-run must not erase the
    # rows the same session banked (a relay death mid-re-run would
    # otherwise leave the round with LESS evidence than before it ran)
    bench._record_partial(
        {"qps": 5000.0, "recall": 0.97, "mode": "recon8_list",
         "n_probes": 8, "refine": True})
    monkeypatch.setenv("RAFT_TPU_BENCH_KEEP_PARTIAL", "1")
    monkeypatch.setattr(bench, "_run_child", lambda k, t: (None, True))
    rec = run_main()
    assert rec["value"] == 5000.0 and rec["partial"] is True
    # without the flag the session reset wipes pre-existing rows
    monkeypatch.delenv("RAFT_TPU_BENCH_KEEP_PARTIAL")
    rec = run_main()
    assert rec["value"] == 0.0


def test_success_banks_last_good_but_failure_never_recycles_it(
        quiet, monkeypatch):
    # success still banks the write-only provenance record, but a later
    # total failure reports 0.0 + error — the 72 h recycling path that
    # produced BENCH_r04/r05 (an old 5315-qps row masquerading as fresh
    # trajectory across dead rounds) is gone
    good = {"metric": bench._HEADLINE_METRIC, "value": 5315.2,
            "unit": "qps", "vs_baseline": 0.532, "recall@10": 0.9965}
    monkeypatch.setattr(bench, "_run_child", lambda k, t: (dict(good), True))
    rec = run_main()
    assert rec["value"] == 5315.2
    lg = json.loads(open(bench._LAST_GOOD_PATH).read())
    assert lg["value"] == 5315.2 and "measured_unix" in lg
    monkeypatch.setattr(bench, "_run_child", lambda k, t: (None, True))
    rec = run_main()
    assert rec["value"] == 0.0 and "error" in rec
    assert "recovered_from" not in rec


def test_headline_sessions_append_to_ledger(quiet, monkeypatch):
    # every session — measured or failed — appends one honest row to the
    # append-only ledger; a 0.0 outage row is trajectory signal too
    from raft_tpu.obs import ledger

    path = os.environ["RAFT_TPU_BENCH_LEDGER"]
    good = {"metric": bench._HEADLINE_METRIC, "value": 4321.0, "unit": "qps"}
    monkeypatch.setattr(bench, "_run_child", lambda k, t: (dict(good), True))
    run_main()
    monkeypatch.setattr(bench, "_run_child", lambda k, t: (None, True))
    run_main()
    entries = ledger.read(path)
    assert [e["row"]["value"] for e in entries] == [4321.0, 0.0]
    assert all(e["bench"] == "bench_headline" and "sha" in e
               for e in entries)


def test_smoke_record_never_banks_last_good(quiet, monkeypatch):
    monkeypatch.setattr(
        bench, "_run_child",
        lambda k, t: ({"metric": bench._HEADLINE_METRIC, "value": 9e9,
                       "unit": "qps", "smoke": True}, True))
    run_main()
    assert not os.path.exists(bench._LAST_GOOD_PATH)


def test_record_partial_tags_smoke_rows(quiet, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_BENCH_SMOKE", "1")
    bench._record_partial({"qps": 1.0, "recall": 1.0, "mode": "bf_tiled"})
    row = json.loads(open(bench._PARTIAL_PATH).read().strip())
    assert row["smoke"] is True


def test_measure_protocol_flags_subfloor_walltime(quiet, monkeypatch):
    # a "measurement" faster than the relay dispatch floor means the
    # backend returned without doing the work: recorded, but suspect
    import jax.numpy as jnp

    monkeypatch.setenv("RAFT_TPU_BENCH_MIN_BATCH_MS", "1e9")
    truth = np.arange(4).reshape(4, 1)
    run = lambda: (jnp.zeros((4, 1)), jnp.asarray(truth))
    rec = bench._measure_protocol(run, 4, 1, truth, "bf_tiled", None,
                                  False, smoke=False)
    assert rec["suspect"] is True and rec["recall"] == 1.0
    row = json.loads(open(bench._PARTIAL_PATH).read().strip())
    assert row["suspect"] is True


def test_measure_protocol_bogus_pipelined_falls_back_to_synced(
        quiet, monkeypatch):
    # a bogus pipelined clock alone must not void the row's valid synced
    # measurement — the synced rate carries the row instead
    import jax.numpy as jnp

    monkeypatch.setattr(bench, "_dual_time",
                        lambda *a, **k: ([190.0, 190.0, 190.0], 0.002))
    truth = np.arange(4).reshape(4, 1)
    run = lambda: (jnp.zeros((4, 1)), jnp.asarray(truth))
    rec = bench._measure_protocol(run, 4, 1, truth, "recon8_list", 8,
                                  True, smoke=False)
    assert "suspect" not in rec and rec["pipelined_suspect"] is True
    assert rec["qps"] == pytest.approx(4 / 0.190, rel=1e-6)


def test_partial_floor_pool_excludes_subgate_bf(quiet, monkeypatch):
    # exact search below the gate means the engine is broken, not that
    # the config needs tuning: crash recovery must agree with the
    # in-process fallback and never report it as the floor headline
    def child(kind, t):
        bench._record_partial(
            {"qps": 17446.0, "recall": 0.90, "mode": "bf_tiled",
             "n_probes": None, "refine": False})
        bench._record_partial(
            {"qps": 6000.0, "recall": 0.85, "mode": "recon8_list",
             "n_probes": 32, "refine": False})
        return None, True

    monkeypatch.setattr(bench, "_run_child", child)
    rec = run_main()
    assert rec["value"] == 6000.0
    assert rec["recall_gate"] == bench._RECALL_FLOOR


def test_race_bf_promotes_and_keeps_ivf_best():
    ivf = {"qps": 5315.0, "recall": 0.9965, "mode": "recon8_list",
           "n_probes": 8, "refine": True}
    bf = {"qps": 17446.0, "recall": 1.0, "mode": "bf_tiled",
          "n_probes": None, "refine": False}
    extra = {}
    assert bench._race_bf(ivf, None, bf, extra) is bf
    assert extra["ivf_pq_best"]["qps"] == 5315.0
    # BF slower: IVF keeps the headline, BF recorded with its mode (the
    # racer may be the lossy bf16 variant — it must not read as exact)
    slow_bf = dict(bf, qps=4000.0, mode="bf_tiled_bf16", recall=0.99)
    extra = {}
    assert bench._race_bf(ivf, None, slow_bf, extra) is ivf
    assert extra["bf_best"] == {"qps": 4000.0, "recall": 0.99,
                                "mode": "bf_tiled_bf16"}
    # BF below the gate never wins
    lossy_bf = dict(bf, recall=0.9)
    assert bench._race_bf(ivf, None, lossy_bf, {}) is ivf


def test_race_bf_keeps_floor_ivf_signal():
    # IVF regressed below the gate but cleared the floor: the BF headline
    # must still carry the IVF number (the regression is the signal)
    floor = {"qps": 6000.0, "recall": 0.85, "mode": "recon8_list",
             "n_probes": 32, "refine": False}
    bf = {"qps": 17446.0, "recall": 1.0, "mode": "bf_tiled",
          "n_probes": None, "refine": False}
    extra = {"ladder_validation": {"overall_true_best": floor}}
    assert bench._race_bf(None, floor, bf, extra) is bf
    assert extra["ivf_pq_best"]["qps"] == 6000.0
    assert extra["ladder_validation"]["overall_true_best"] is bf


def test_grouped_crossover_fit():
    """bench_comms._fit_crossover: ring wins imply c >= ratio, planes
    wins imply c < ratio; inconsistent winners must refuse to fit."""
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench"))
    from bench_comms import _fit_crossover

    row = lambda r, w: {"ratio": r, "winner": w, "margin_ms": 1.0}
    # separable: midpoint of the gap
    c = _fit_crossover([row(0.25, "ring"), row(1.5, "planes")])
    assert 0.25 < c < 1.5
    # swept: bound moves past the raced ratios
    assert _fit_crossover([row(0.25, "ring"), row(1.5, "ring")]) >= 1.5
    assert _fit_crossover([row(0.25, "planes")]) < 0.25
    # inconsistent (planes won BELOW a ring win): no fit
    assert _fit_crossover([row(1.5, "ring"), row(0.25, "planes")]) is None
    assert _fit_crossover([]) is None


def test_profiler_bails_with_partial_results(monkeypatch):
    """A dead relay mid-ladder must persist whatever the profiler already
    measured and exit rc=3 (this session's outage lost a whole ladder to
    a mid-kmeans relay death before this path existed)."""
    monkeypatch.setattr(tpu_profile, "R", {"datagen": 1.23})
    import raft_tpu.core.config as cfg

    monkeypatch.setattr(cfg, "relay_transport_down", lambda: True)
    written = {}
    monkeypatch.setattr(tpu_profile, "_finish", lambda R: written.update(R))
    with pytest.raises(SystemExit) as e:
        tpu_profile._bail_if_transport_dead("kmeans_fit")
    assert e.value.code == 3
    assert written["datagen"] == 1.23
    assert "kmeans_fit" in written["aborted"]


def test_profiler_continues_when_transport_up(monkeypatch):
    import raft_tpu.core.config as cfg

    monkeypatch.setattr(cfg, "relay_transport_down", lambda: False)
    tpu_profile._bail_if_transport_dead("anywhere")  # no raise


@pytest.fixture
def tuned_file(monkeypatch, tmp_path):
    """Point core.tuned at a scratch file; always drop the cache on both
    entry and exit so no tuned state leaks across tests."""
    from raft_tpu.core import tuned

    p = str(tmp_path / "tuned_defaults.json")
    monkeypatch.setattr(tuned, "_PATH", p)
    tuned.reload()
    yield p
    tuned.reload()


def test_tuned_defaults_absent_is_none(tuned_file):
    from raft_tpu.core import tuned

    assert tuned.get("pq_auto_engine") is None
    assert tuned.get("anything", "fallback") == "fallback"


def test_tuned_registry_wellformed():
    """TUNED_KEYS is the machine-readable contract raftlint reads by
    AST: literal entries, known kinds, choice sets where claimed, an
    existing owning bench file where named, and the canonical key
    constants spelled from it."""
    import os
    from raft_tpu.core import tuned

    assert tuned.known_keys() == tuple(sorted(tuned.TUNED_KEYS))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for key, entry in tuned.TUNED_KEYS.items():
        assert entry["kind"] in ("choice", "int", "float", "bool",
                                 "dict", "hints"), key
        if entry["kind"] == "choice":
            assert isinstance(entry["choices"], tuple) and entry["choices"], key
        else:
            assert entry["choices"] is None, key
        if entry["bench"] is not None:
            assert os.path.exists(os.path.join(repo, entry["bench"])), key
    assert tuned.INT8_SCAN_KEY in tuned.TUNED_KEYS
    assert tuned.BITPLANE_SCAN_KEY in tuned.TUNED_KEYS
    assert tuned.POLICY_KEY in tuned.TUNED_KEYS
    # the dispatch modules re-export, never respell (importlib: the
    # matrix package re-exports select_k the FUNCTION, which shadows
    # the module on attribute traversal)
    import importlib

    select_k_mod = importlib.import_module("raft_tpu.matrix.select_k")
    from raft_tpu.neighbors import probe_budget

    assert select_k_mod.INT8_SCAN_KEY is tuned.INT8_SCAN_KEY
    assert select_k_mod.BITPLANE_SCAN_KEY is tuned.BITPLANE_SCAN_KEY
    assert probe_budget.POLICY_KEY is tuned.POLICY_KEY


def test_tuned_hints_helper_null_vs_missing(tuned_file):
    """tuned.hints() is the ONE hints access path: {} on a missing
    file, a missing key, AND a hand-edited null/corrupt value — the
    divergence the old get("hints", {}) / get("hints") or {} pair had."""
    import json
    from raft_tpu.core import tuned

    assert tuned.hints() == {}
    with open(tuned_file, "w") as f:
        json.dump({"hints": None}, f)
    tuned.reload()
    assert tuned.hints() == {}
    with open(tuned_file, "w") as f:
        json.dump({"hints": {"measured_on": "tpu"}}, f)
    tuned.reload()
    assert tuned.hints() == {"measured_on": "tpu"}


def test_apply_hints_skips_unregistered_keys(tuned_file, monkeypatch):
    """The runtime belt matching the lint-time registry check: a
    _TUNABLE entry drifting from TUNED_KEYS must not bank a winner
    where no dispatch path will ever read it."""
    import json
    import sys, os

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench"))
    import apply_profile_hints as aph

    monkeypatch.setitem(aph._TUNABLE, "bogus_hint", ("bogus_key", str))
    aph.apply_hints([
        {"hint": "bogus_hint", "recommend": "x", "detail": "drifted"},
        {"hint": "listmajor_chunk", "recommend": "256", "detail": "ok"},
        # registered choice key, value outside its registered set: the
        # lint rule cannot see computed values, so the belt must
        {"hint": "pq_auto_engine", "recommend": "fused", "detail": "bad"},
    ])
    rec = json.load(open(tuned_file))
    assert "bogus_key" not in rec
    assert "pq_auto_engine" not in rec
    assert rec["listmajor_chunk"] == 256


@pytest.mark.slow
def test_tuned_flat_auto_engine_is_consulted(tuned_file, monkeypatch, rng):
    """engine="auto" must take the measured winner when a tuned file says
    so (a tiny batch would heuristically pick "query")."""
    import json
    from raft_tpu.core import tuned
    from raft_tpu.neighbors import ivf_flat

    data = rng.random((600, 16), dtype=np.float32)
    index = ivf_flat.build(ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=2), data)

    with open(tuned_file, "w") as f:
        json.dump({"flat_auto_engine": "list"}, f)
    tuned.reload()

    hit = []
    orig = ivf_flat._search_impl_listmajor

    def spy(*a, **kw):
        hit.append(1)
        return orig(*a, **kw)

    monkeypatch.setattr(ivf_flat, "_search_impl_listmajor", spy)
    ivf_flat.search(ivf_flat.SearchParams(n_probes=4, engine="auto"), index,
                    data[:2], 3)
    assert hit, "tuned flat_auto_engine=list was not consulted"


def test_apply_hints_writes_tuned_file(tuned_file):
    import json
    import sys, os

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench"))
    import apply_profile_hints as aph
    from raft_tpu.core import tuned

    hints = [
        {"hint": "pq_auto_engine", "recommend": "recon8_list", "detail": "x"},
        {"hint": "trim_engine_default", "recommend": "inspect", "detail": "y"},
    ]
    aph.apply_hints(hints)
    rec = json.load(open(tuned_file))
    assert rec["pq_auto_engine"] == "recon8_list"
    assert "trim_engine_default" in rec["hints"]
    assert tuned.get("pq_auto_engine") == "recon8_list"


@pytest.mark.slow
def test_tuned_counting_promotion_dispatch(tuned_file, monkeypatch, rng):
    """The select_k counting auto-promotion (tuned winner + TPU backend +
    2-D f32 + VMEM fit) routes through the counting engine and stays
    exact. Off-chip the TPU gate is monkeypatched true — the kernel runs
    in interpret mode, so the DECISION logic (previously dead code until
    a chip session wrote the tuned file) is exercised in CI."""
    import importlib
    import json
    from raft_tpu.core import tuned
    from raft_tpu import matrix

    # the package re-exports the FUNCTION under the module's name; the
    # module object itself comes from importlib
    sk_module = importlib.import_module("raft_tpu.matrix.select_k")

    with open(tuned_file, "w") as f:
        json.dump({"select_k_auto_strategy": "counting"}, f)
    tuned.reload()
    import raft_tpu.core.config as cfg

    monkeypatch.setattr(cfg, "is_tpu_backend", lambda: True)

    hit = []
    orig = sk_module._select_k_counting

    def spy(*a, **kw):
        hit.append(1)
        return orig(*a, **kw)

    monkeypatch.setattr(sk_module, "_select_k_counting", spy)
    vals = rng.random((4, 512), dtype=np.float32)
    v, i = matrix.select_k(vals, 5, select_min=True)
    assert hit, "tuned counting promotion was not dispatched"
    want = np.sort(vals, axis=1)[:, :5]
    np.testing.assert_allclose(np.asarray(v), want, rtol=1e-6)

    # ineligible shapes fall back: 3-D batch keeps the default path
    hit.clear()
    v3, _ = matrix.select_k(rng.random((2, 3, 256), dtype=np.float32), 4)
    assert not hit, "counting must not take ndim != 2"


def test_merge_race_fit_rule_matches_r3_surface():
    """fit_rule reproduces the recorded round-3 surface exactly: the
    winner flips with k at fixed nq, which a single-nq threshold cannot
    express; the fitted two-key rule classifies every row."""
    import bench_mnmg_merge as bm

    rows = [
        {"nq": 512, "k": 10, "winner": "replicated",
         "replicated_ms": 8066.66, "sharded_ms": 8784.07},
        {"nq": 2048, "k": 10, "winner": "sharded",
         "replicated_ms": 20716.0, "sharded_ms": 18703.21},
        {"nq": 2048, "k": 100, "winner": "replicated",
         "replicated_ms": 20562.66, "sharded_ms": 28615.05},
    ]
    fit = bm.fit_rule(rows)
    assert fit is not None
    min_nq, per_k, err = fit
    assert err == 0.0
    for r in rows:
        pred = r["nq"] >= min_nq and r["nq"] >= r["k"] * per_k
        assert pred == (r["winner"] == "sharded"), (r, min_nq, per_k)


def test_merge_race_fit_rule_degenerate_surfaces():
    """All-replicated surfaces (and noise-only sharded wins that a
    conservative fit rejects) leave the defaults untouched."""
    import bench_mnmg_merge as bm

    all_repl = [{"nq": n, "k": k, "winner": "replicated",
                 "replicated_ms": 10.0, "sharded_ms": 20.0}
                for n in (512, 4096) for k in (10, 100)]
    assert bm.fit_rule(all_repl) is None


def test_merge_race_fit_rule_weights_by_margin():
    """A tiny noise flip must not outvote a large measured regression:
    the fit sacrifices the 5 ms row, never the 8000 ms row."""
    import bench_mnmg_merge as bm

    rows = [
        # genuine big win for sharded at high volume
        {"nq": 8192, "k": 10, "winner": "sharded",
         "replicated_ms": 9000.0, "sharded_ms": 1000.0},
        # noise-level "sharded win" at a shape the rule must keep
        # replicated because of the k=100 regression below
        {"nq": 2048, "k": 100, "winner": "sharded",
         "replicated_ms": 1000.0, "sharded_ms": 995.0},
        {"nq": 2048, "k": 10, "winner": "replicated",
         "replicated_ms": 1000.0, "sharded_ms": 1005.0},
    ]
    min_nq, per_k, err = bm.fit_rule(rows)
    assert err <= 10.0  # only noise rows misclassified
    # the big-margin row is classified correctly
    assert 8192 >= min_nq and 8192 >= 10 * per_k


def test_merge_race_fit_rule_refuses_unrepresentable_surface():
    """When no (min_nq, per_k) rule can express the winners without
    misclassifying a large share of the measured margin, the fit returns
    None and the production defaults stay untouched."""
    import bench_mnmg_merge as bm

    # sharded wins ONLY at small nq, replicated at large nq — the rule
    # family (sharded iff nq large enough) cannot represent this
    rows = [
        {"nq": 512, "k": 10, "winner": "sharded",
         "replicated_ms": 9000.0, "sharded_ms": 1000.0},
        {"nq": 8192, "k": 10, "winner": "replicated",
         "replicated_ms": 1000.0, "sharded_ms": 9000.0},
    ]
    assert bm.fit_rule(rows) is None


def test_merge_race_apply_preserves_chip_backed_keys(tmp_path, monkeypatch):
    """A CPU-measured fit must not clobber chip-backed tuned keys; a chip
    fit overwrites anything."""
    import json
    import bench_mnmg_merge as bm
    from raft_tpu.core import tuned

    p = str(tmp_path / "tuned_defaults.json")
    monkeypatch.setattr(tuned, "_PATH", p)
    tuned.reload()
    rows = [{"nq": 512, "k": 10, "winner": "replicated",
             "replicated_ms": 10.0, "sharded_ms": 500.0},
            {"nq": 4096, "k": 10, "winner": "sharded",
             "replicated_ms": 500.0, "sharded_ms": 10.0}]
    try:
        # chip-backed keys land first
        bm._apply({"backend": "axon", "world": 8, "rows": rows})
        tuned.reload()
        assert tuned.get("mnmg_query_sharded_min_nq") == 4096
        on = tuned.get("hints")["mnmg_merge_measured_on"]
        assert on.startswith("axon")
        # a later CPU fit (different surface) is refused
        cpu_rows = [{"nq": 128, "k": 10, "winner": "sharded",
                     "replicated_ms": 500.0, "sharded_ms": 10.0}]
        bm._apply({"backend": "cpu", "world": 16, "rows": cpu_rows})
        tuned.reload()
        assert tuned.get("mnmg_query_sharded_min_nq") == 4096
        assert tuned.get("hints")["mnmg_merge_measured_on"].startswith("axon")
        # a chip fit overwrites
        bm._apply({"backend": "axon", "world": 16, "rows": cpu_rows})
        tuned.reload()
        assert tuned.get("mnmg_query_sharded_min_nq") == 128
    finally:
        tuned.reload()


def test_chip_probe_guard_env_and_transport(monkeypatch):
    """chip_probe_would_hang: CPU env short-circuits (rehearsals run with
    the relay dead); otherwise it follows the transport check, and a
    broken check fails open."""
    import raft_tpu.core.config as cfg

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setattr(cfg, "relay_transport_down", lambda: True)
    assert cfg.chip_probe_would_hang() is False
    monkeypatch.delenv("JAX_PLATFORMS")
    assert cfg.chip_probe_would_hang() is True
    monkeypatch.setattr(cfg, "relay_transport_down", lambda: False)
    assert cfg.chip_probe_would_hang() is False

    def boom():
        raise OSError("proc unreadable")

    monkeypatch.setattr(cfg, "relay_transport_down", boom)
    assert cfg.chip_probe_would_hang() is False  # fail-open


@pytest.mark.slow  # spawns real child processes (host suite + fallback)
def test_run_all_continues_survivable_on_dead_relay(monkeypatch, tmp_path):
    """run_all's between-suite gate (ROADMAP 5a): on a dead relay the
    sweep CONTINUES with the survivable drivers (in-process CPU
    fallback, honestly tagged rows to the real files + ledger) and skips
    the rest — it must neither abort nor launch a chip process that can
    only hang."""
    import subprocess, sys, os

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # chip intent
    env["PALLAS_AXON_POOL_IPS"] = "127.0.0.1"  # dead-relay signature
    # test seam: one survivable suite + one chip-only suite, tiny ledger
    env["RAFT_TPU_RUN_ALL_SUITES"] = "bench_distance.py,bench_perf_smoke.py"
    env["RAFT_TPU_BENCH_LEDGER"] = str(tmp_path / "ledger.jsonl")
    env["RAFT_TPU_BENCH_OUT"] = str(tmp_path)
    r = subprocess.run(
        [sys.executable, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "bench", "run_all.py")],
        capture_output=True, text=True, env=env, timeout=300)
    assert "continuing with survivable suites" in r.stderr, r.stderr[-2000:]
    assert "skipping bench_distance.py" in r.stderr, r.stderr[-2000:]
    # the host-side io_loader suite ran unconditionally
    assert "io_loader" in r.stdout, r.stdout[-2000:]
    # relay-skipped suites leave the sweep INCOMPLETE: exit 75 ("re-run
    # to resume"), never 0 — a 0 would let run_onchip_queue.sh's
    # run_job delete the job dir and lose the skipped suites' retry
    assert r.returncode == common.PREEMPT_EXIT, (r.returncode,
                                                 r.stderr[-2000:])
    assert "sweep incomplete" in r.stderr, r.stderr[-2000:]
    # the survivable driver banked honestly-tagged fallback rows
    from raft_tpu.obs import ledger

    entries = ledger.read(str(tmp_path / "ledger.jsonl"))
    assert entries and all(e["platform"] == "cpu" for e in entries)
    assert any(e.get("fallback") == "in_process_cpu" for e in entries)


@pytest.mark.slow  # spawns two real sweep runs (child suite processes)
def test_run_all_resumes_completed_suites_from_job_dir(tmp_path):
    """ISSUE 8: with RAFT_TPU_RUN_ALL_JOB_DIR set, a re-run of the sweep
    SKIPS every suite the previous run committed — the mid-queue
    process-tree-loss scenario the retired run_onchip_queue_resume.sh
    used to hand-patch, now carried by the job runner's manifest."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["RAFT_TPU_RUN_ALL_SUITES"] = "bench_perf_smoke.py"
    env["RAFT_TPU_RUN_ALL_JOB_DIR"] = str(tmp_path / "sweep")
    env["RAFT_TPU_BENCH_LEDGER"] = str(tmp_path / "ledger.jsonl")
    env["RAFT_TPU_BENCH_OUT"] = str(tmp_path)
    cmd = [sys.executable, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "bench", "run_all.py")]
    r1 = subprocess.run(cmd, capture_output=True, text=True, env=env,
                        timeout=600)
    assert r1.returncode == 0, r1.stderr[-2000:]
    assert "== bench_perf_smoke.py" in r1.stderr
    from raft_tpu.obs import ledger

    n_rows = len(ledger.read(str(tmp_path / "ledger.jsonl")))
    assert n_rows > 0
    r2 = subprocess.run(cmd, capture_output=True, text=True, env=env,
                        timeout=600)
    assert r2.returncode == 0, r2.stderr[-2000:]
    # the committed suite never relaunched: no suite banner, no fresh
    # ledger rows — the manifest skip carried it
    assert "== bench_perf_smoke.py" not in r2.stderr
    assert len(ledger.read(str(tmp_path / "ledger.jsonl"))) == n_rows


@pytest.mark.slow  # full headline ladder at smoke geometry (~1-2 min CPU)
def test_headline_bench_smoke_geometry(monkeypatch, tmp_path):
    """RAFT_TPU_BENCH_SMOKE=1 runs _bench_ivf_pq's ENTIRE control flow
    (build, truth, ladder, pipelined+synced timing, tally, tflops probe)
    on CPU at toy geometry — so no chip session ever executes this
    function's logic for the first time. The record must be headline-
    shaped with both throughput fields and a cleared gate."""
    monkeypatch.setenv("RAFT_TPU_BENCH_SMOKE", "1")
    monkeypatch.setattr(bench, "_PARTIAL_PATH", str(tmp_path / "partial.jsonl"))
    rec = bench._bench_ivf_pq()
    assert rec["metric"] == bench._HEADLINE_METRIC
    assert rec["value"] > 0
    assert rec["recall@10"] >= rec["recall_gate"] >= bench._RECALL_FLOOR
    assert "qps_synced" not in rec  # headline record carries cfg fields only
    # partial file banked at least one ladder row with both QPS flavors
    rows = [json.loads(l) for l in open(tmp_path / "partial.jsonl")]
    assert rows and all("qps_synced" in r and "qps" in r for r in rows)


# -- obs phase banking --------------------------------------------------

def test_run_case_banks_span_phases(capsys):
    """With observability on, `run_case` attaches per-phase span totals
    to its JSON record (the BENCH-row attribution contract)."""
    import common as bench_common

    from raft_tpu import obs

    import jax.numpy as jnp

    def fn():
        with obs.span("bench.phase.score"):
            out = jnp.ones((4,)) * 2
        return out

    obs.enable()
    try:
        obs.reset()
        rec = bench_common.run_case("t", "case", fn, iters=3, warmup=1)
        assert rec["phases"]["bench.phase.score"]["calls"] == 3  # timed only
        printed = json.loads(capsys.readouterr().out.strip().split("\n")[-1])
        assert printed["phases"] == rec["phases"]
    finally:
        obs.disable()
        obs.reset()
    rec = bench_common.run_case("t", "case", fn, iters=2, warmup=1)
    assert "phases" not in rec  # disabled: records unchanged


# -- dead-relay in-process fallback (ROADMAP 5a, bench/common.py) -------

import common  # noqa: E402  (bench dir is on sys.path above)


def test_survivable_backend_noop_on_cpu_env():
    # an explicit CPU run is already survivable: nothing engages
    assert common.ensure_survivable_backend(_platforms="cpu") is None


def test_survivable_backend_noop_when_relay_alive():
    assert common.ensure_survivable_backend(_platforms="", _dead=False) is None


def test_survivable_backend_pins_cpu_when_relay_dead():
    import jax

    # a chip-intent env with a structurally dead relay pins CPU
    # in-process instead of hanging (the config is already cpu under
    # conftest, so the update is a no-op re-pin)
    tag = common.ensure_survivable_backend(_platforms="tpu,axon", _dead=True)
    assert tag == "in_process_cpu"
    assert str(jax.config.jax_platforms).startswith("cpu")


def test_banker_fallback_banks_to_real_file(tmp_path):
    """An engaged fallback banks to the REAL results file (no .cpu
    rehearsal suffix), with the rows honestly tagged — a dead relay
    stops recycling stale numbers instead of aborting the bench."""
    real = str(tmp_path / "BENCH_x.json")
    bank = common.Banker(real, meta={"k": 10}, fallback="in_process_cpu")
    assert bank.path == real
    bank.add({"case": "qps", "qps": 123.0})
    rec = json.loads(open(real).read())
    assert rec["fallback"] == "in_process_cpu"
    assert rec["rows"] == [{"case": "qps", "qps": 123.0}]
    # a plain CPU rehearsal (no fallback) still diverts to the .cpu file
    plain = common.Banker(str(tmp_path / "BENCH_y.json"), meta={})
    assert plain.path.endswith(".cpu")
    assert plain.record.get("cpu_rehearsal") is True


def test_banker_resume_adopts_and_supersedes_rows(tmp_path, monkeypatch):
    """ISSUE 8 durable-job resume: a resumed Banker carries the prior
    snapshot's rows forward (skipped stages never re-bank), but a
    stage that RE-RUNS supersedes its adopted row — a mid-stage kill
    after banking must not leave duplicates — and mismatched geometry
    or a fresh run adopts nothing."""
    monkeypatch.setenv("RAFT_TPU_BENCH_LEDGER", str(tmp_path / "l.jsonl"))
    out = str(tmp_path / "BENCH_z.json")
    b1 = common.Banker(out, meta={"n": 100}, fallback="x")
    b1.add({"stage": "make_data", "s": 1.0}, echo=False)
    b1.add({"stage": "extend", "s": 2.0}, echo=False)

    b2 = common.Banker(out, meta={"n": 100}, fallback="x", resume=True)
    assert [r["stage"] for r in b2.record["rows"]] == ["make_data",
                                                       "extend"]
    # the killed-mid-stage re-run: fresh row replaces the adopted one
    b2.add({"stage": "extend", "s": 9.0}, echo=False)
    assert [(r["stage"], r["s"]) for r in b2.record["rows"]] == [
        ("make_data", 1.0), ("extend", 9.0)]
    # a second add of the same stage (legit repeat) appends normally
    b2.add({"stage": "extend", "s": 3.0}, echo=False)
    assert len(b2.record["rows"]) == 3

    # geometry change -> nothing adopted
    b3 = common.Banker(out, meta={"n": 200}, fallback="x", resume=True)
    assert b3.record["rows"] == []
    # no resume flag -> fresh record as before
    b4 = common.Banker(out, meta={"n": 200}, fallback="x")
    assert b4.record["rows"] == []
