"""Chunk-table inversion invariants (raft_tpu.neighbors.probe_invert).

The list-major engines rest on invert_probes' static-shape chunk tables;
these tests pin its invariants directly (the engine-level overlap tests in
test_ivf_pq/test_ivf_flat check end-to-end agreement, but a silent
slot-addressing bug can hide behind top-k ties there). Skewed probe
distributions exercise multi-chunk ("virtual list") splitting.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from raft_tpu.neighbors.probe_invert import (
    chunk_count,
    invert_probes,
    invert_probes_count,
    invert_probes_sort,
)


@pytest.mark.parametrize("impl", [invert_probes_sort, invert_probes_count])
@pytest.mark.parametrize(
    "nq,n_probes,n_lists,chunk,skew",
    [
        (64, 8, 16, 16, False),
        (128, 4, 8, 32, True),   # hot lists get several chunks
        (33, 7, 64, 8, True),    # non-divisible everything
        (16, 3, 4, 64, False),   # chunk larger than any bucket
    ],
)
def test_invert_probes_invariants(nq, n_probes, n_lists, chunk, skew, impl, rng):
    if skew:
        # zipf-ish skew: low-id lists drawn far more often
        raw = rng.zipf(1.5, size=(nq, n_probes)) % n_lists
    else:
        raw = rng.integers(0, n_lists, size=(nq, n_probes))
    probes = jnp.asarray(raw.astype(np.int32))
    t = impl(probes, n_lists, chunk)
    assert t.pair_valid is None  # fixed path: every pair live
    lof, qid_tbl, g0, s0 = (np.asarray(t.lof), np.asarray(t.qid_tbl),
                            np.asarray(t.g0), np.asarray(t.s0))

    ncb = chunk_count(nq, n_probes, n_lists, chunk)
    assert lof.shape == (ncb,)
    assert qid_tbl.shape == (ncb, chunk)
    assert g0.shape == s0.shape == (nq * n_probes,)

    # every original (query, list) pair must be recoverable through its
    # (chunk, slot) address, and the chunk must score that pair's list
    flat = raw.reshape(-1)
    qidx = np.arange(nq * n_probes) // n_probes
    assert np.all((g0 >= 0) & (g0 < ncb))
    assert np.all((s0 >= 0) & (s0 < chunk))
    assert np.array_equal(lof[g0], flat)
    assert np.array_equal(qid_tbl[g0, s0], qidx)

    # no two pairs share a slot
    addr = g0.astype(np.int64) * chunk + s0
    assert len(np.unique(addr)) == len(addr)

    # padding sentinel: every table entry is either a valid query id or nq
    assert qid_tbl.min() >= 0
    assert qid_tbl.max() <= nq
    # valid entries per list match the probe counts
    for l in range(n_lists):
        want = int((flat == l).sum())
        got = int((qid_tbl[lof == l] < nq).sum())
        assert got == want, f"list {l}: {got} != {want}"

@pytest.mark.parametrize(
    "nq,n_probes,n_lists,chunk",
    [
        (64, 8, 16, 16),
        (33, 7, 64, 8),
        (100, 5, 300, 32),  # block smaller than 8192, wide list table
        (16, 1, 4, 64),     # n_probes=1
    ],
)
def test_invert_impls_bit_identical(nq, n_probes, n_lists, chunk, rng):
    """The counting construction must reproduce the sort-based tables
    BIT-IDENTICALLY (stable in-bucket order), so the `invert_impl` tuned
    key can flip between them without any behavioral difference."""
    raw = rng.integers(0, n_lists, size=(nq, n_probes)).astype(np.int32)
    # skew one list hot to force multi-chunk splits
    raw[: nq // 2, 0] = 0
    a = invert_probes_sort(jnp.asarray(raw), n_lists, chunk)
    b = invert_probes_count(jnp.asarray(raw), n_lists, chunk)
    for x, y in zip(tuple(a), tuple(b)):
        if x is None or y is None:
            assert x is None and y is None
            continue
        assert np.array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.parametrize("impl", [invert_probes_sort, invert_probes_count])
def test_invert_probes_masked_pairs(impl, rng):
    """Adaptive probe budgets: masked pairs occupy NO chunk slot (the
    populated-chunk count shrinks), live pairs keep exactly the
    addresses/invariants of the unmasked construction restricted to
    them, and the two constructions stay bit-identical under a mask."""
    nq, n_probes, n_lists, chunk = 48, 8, 16, 16
    raw = rng.integers(0, n_lists, size=(nq, n_probes)).astype(np.int32)
    pv = rng.random((nq, n_probes)) < 0.5
    pv[:, 0] = True  # budget floor: first probe always live
    t = impl(jnp.asarray(raw), n_lists, chunk, jnp.asarray(pv))
    lof, qid_tbl, g0, s0, pvalid = map(np.asarray, t)
    flat = raw.reshape(-1)
    qidx = np.arange(nq * n_probes) // n_probes
    live = pv.reshape(-1)
    assert np.array_equal(pvalid, live)
    # live pairs recoverable through their addresses
    assert np.array_equal(lof[g0[live]], flat[live])
    assert np.array_equal(qid_tbl[g0[live], s0[live]], qidx[live])
    # no two live pairs share a slot; masked pairs are clamped to (0,0)
    addr = g0.astype(np.int64) * chunk + s0
    assert len(np.unique(addr[live])) == live.sum()
    assert np.all(g0[~live] == 0) and np.all(s0[~live] == 0)
    # populated entries == live pair count (masked pairs dropped)
    assert int((qid_tbl < nq).sum()) == int(live.sum())
    # masked construction is bit-identical across impls
    other = (invert_probes_count if impl is invert_probes_sort
             else invert_probes_sort)
    t2 = other(jnp.asarray(raw), n_lists, chunk, jnp.asarray(pv))
    for x, y in zip(tuple(t), tuple(t2)):
        assert np.array_equal(np.asarray(x), np.asarray(y))


def test_invert_dispatch_honors_tuned_key(monkeypatch, rng):
    from raft_tpu.core import tuned

    raw = rng.integers(0, 16, size=(32, 4)).astype(np.int32)
    monkeypatch.setattr(tuned, "get_choice",
                        lambda key, allowed, default: "count"
                        if key == "invert_impl" else default)
    t = invert_probes(jnp.asarray(raw), 16, 8)
    ref = invert_probes_count(jnp.asarray(raw), 16, 8)
    for x, y in zip(tuple(t), tuple(ref)):
        if x is None or y is None:
            assert x is None and y is None
            continue
        assert np.array_equal(np.asarray(x), np.asarray(y))
