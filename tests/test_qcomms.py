"""Quantized-collectives suite (comms/quantized; ROADMAP open item 3,
EQuARX arxiv 2506.17615): codec round-trips and error bounds,
mode="off" bit-identity pins (jaxpr and output bytes), quantized
collective correctness vs the exact path on the 8-device mesh,
candidate-exchange recall parity (incl. replication failover and
degraded health), and wire-byte accounting the >=2x savings claims are
judged against."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from raft_tpu import obs
from raft_tpu.comms import Comms, mnmg, quantized
from raft_tpu.comms.comms import op_t
from raft_tpu.comms.quantized import QuantConfig
from raft_tpu.core import tuned
from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq, ivf_rabitq
from raft_tpu.random import make_blobs

INT8 = QuantConfig(mode="int8", block=32)
BF16 = QuantConfig(mode="bf16")
WORLD = 8


@pytest.fixture(scope="module")
def comms():
    return Comms()


@pytest.fixture(scope="module")
def blobs():
    data, _ = make_blobs(1024, 16, n_clusters=6, cluster_std=0.4, seed=13)
    return np.asarray(data)


def _recall(got_ids, ref_ids) -> float:
    got, ref = np.asarray(got_ids), np.asarray(ref_ids)
    k = ref.shape[1]
    return float(np.mean([len(set(got[i].tolist()) & set(ref[i].tolist())) / k
                          for i in range(ref.shape[0])]))


# -- codec ---------------------------------------------------------------

@pytest.mark.parametrize("block", quantized.BLOCK_CHOICES)
def test_codec_roundtrip_absmax_bound(block):
    """Round-trip error per value stays under scale/2 == absmax/254 (the
    documented worst case), including a ragged tail block."""
    rng = np.random.default_rng(7)
    x = rng.normal(size=(517,)).astype(np.float32) * 3.0
    q, sc = quantized.quantize_blocks(x, block)
    y = np.asarray(quantized.dequantize_blocks(q, sc, x.shape))
    nblk = -(-x.size // block)
    padded = np.zeros(nblk * block, np.float32)
    padded[: x.size] = x
    absmax = np.abs(padded.reshape(nblk, block)).max(axis=1)
    bound = np.repeat(absmax / 254.0, block)[: x.size] + 1e-6
    assert np.all(np.abs(y - x) <= bound), np.max(np.abs(y - x) - bound)
    assert q.dtype == jnp.int8 and sc.shape == (nblk,)


def test_codec_zero_block_and_pad_exact():
    x = np.zeros((40,), np.float32)
    x[:3] = [1.0, -2.0, 0.5]  # block 2 (of 32-blocks) is all zero
    q, sc = quantized.quantize_blocks(x, 32)
    assert float(sc[1]) == 0.0  # all-zero block encodes scale 0
    y = np.asarray(quantized.dequantize_blocks(q, sc, x.shape))
    assert y.shape == x.shape
    np.testing.assert_array_equal(y[3:], 0.0)  # zeros decode exactly


def test_codec_worst_case_error_grows_with_block():
    """One heavy value per 128-stretch: a small block isolates the spike
    from its neighbors' scale, a large block drags every cohabitant's
    resolution down — mean error must grow with block size."""
    rng = np.random.default_rng(11)
    x = rng.normal(size=(1024,)).astype(np.float32) * 0.01
    x[::128] = 100.0
    errs = []
    for block in (16, 128):
        q, sc = quantized.quantize_blocks(x, block)
        y = np.asarray(quantized.dequantize_blocks(q, sc, x.shape))
        errs.append(float(np.mean(np.abs(y - x))))
    assert errs[0] < errs[1], errs


def test_packet_bytes_model():
    # 64 values at block 32 -> 2 blocks: 64 int8 + 2 f32 scales
    assert quantized.packet_bytes(64, 32) == 64 + 8
    # ragged: 65 values -> 3 blocks of payload + 3 scales
    assert quantized.packet_bytes(65, 32) == 96 + 12
    # int8 + sidecar stays well under half of f32 for real blocks
    assert quantized.packet_bytes(4096, 32) * 2 < 4096 * 4


def test_quantconfig_validation_and_hashability():
    with pytest.raises(ValueError, match="unknown quantization mode"):
        QuantConfig(mode="fp4")
    with pytest.raises(ValueError, match="block"):
        QuantConfig(mode="int8", block=0)
    with pytest.raises(ValueError, match="exchange_mult"):
        QuantConfig(mode="int8", exchange_mult=0.5)
    # hashable (slots into wrapper_key cache tuples)
    assert len({INT8, BF16, QuantConfig(mode="int8", block=32)}) == 2


def test_resolve_semantics():
    assert quantized.resolve(None) is None
    assert quantized.resolve(False) is None
    assert quantized.resolve("off") is None
    assert quantized.resolve(QuantConfig(mode="off")) is None
    assert quantized.resolve(INT8) is INT8
    cfg = quantized.resolve("int8")
    assert cfg.mode == "int8" and cfg.block in quantized.BLOCK_CHOICES
    assert quantized.resolve("bf16").mode == "bf16"
    with pytest.raises(ValueError, match="unknown quantization"):
        quantized.resolve("fp8")


def test_resolve_auto_backend_guard(monkeypatch):
    """"auto" honors a tuned winner only when measured on THIS backend
    (the merge_schedule_measured_on rule)."""
    values = {"comms_quant_mode": "int8", "comms_quant_block": 64}
    monkeypatch.setattr(tuned, "get", lambda k, d=None: values.get(k, d))
    # measured elsewhere: auto stays exact
    monkeypatch.setattr(
        tuned, "hints", lambda: {"comms_quant_measured_on": "not-a-backend"})
    assert quantized.resolve("auto") is None
    # measured here: auto flips, tuned block honored
    monkeypatch.setattr(
        tuned, "hints",
        lambda: {"comms_quant_measured_on": jax.default_backend()})
    cfg = quantized.resolve("auto")
    assert cfg == QuantConfig(mode="int8", block=64)


def test_resolve_auto_default_is_exact():
    """Precondition for every "auto" driver pin below: with no banked
    CPU-measured winner, "auto" resolves to the exact path."""
    assert quantized.resolve("auto") is None


# -- mode="off" bit-identity (the jaxpr pin) -----------------------------

def test_off_jaxpr_identical_to_default(comms):
    """quantization=None / "off" must trace to the byte-identical jaxpr
    as the pre-quantization collectives — the dispatch happens in Python
    before tracing, for all four wired ops."""
    ac = comms.comms

    def make(quant_kw):
        def body(x):
            a = ac.allreduce(x, **quant_kw)
            g = ac.allgather(x, **quant_kw)
            b = ac.bcast(x, root=3, **quant_kw)
            s = ac.reducescatter(jnp.tile(x, (WORLD, 1)), op_t.SUM,
                                 **quant_kw)
            return a, g, b, s

        return str(jax.make_jaxpr(
            jax.shard_map(body, mesh=comms.mesh, in_specs=P("data"),
                          out_specs=(P("data"), P("data"), P("data"),
                                     P("data")), check_vma=False)
        )(jnp.ones((WORLD, 32), jnp.float32)))

    base = make({})
    assert make({"quantization": None}) == base
    assert make({"quantization": "off"}) == base


# -- quantized collectives vs exact --------------------------------------

def _run_allreduce(comms, x, op, quantization):
    ac = comms.comms

    def body(xs):
        return ac.allreduce(xs[0], op, quantization=quantization)[None]

    return np.asarray(jax.shard_map(
        body, mesh=comms.mesh, in_specs=P("data"), out_specs=P("data"),
        check_vma=False)(x))


@pytest.mark.parametrize(
    "cfg,op,tol",
    [(INT8, op_t.SUM, 0.05), (INT8, op_t.MIN, 0.05), (BF16, op_t.SUM, 0.02)],
    ids=["int8-sum", "int8-min", "bf16-sum"])
def test_qallreduce_accuracy_and_replication(comms, cfg, op, tol):
    rng = np.random.default_rng(3)
    x = rng.normal(size=(WORLD, 257)).astype(np.float32)
    red = {op_t.SUM: lambda a: a.sum(0), op_t.MIN: lambda a: a.min(0)}[op]
    exact = red(x)
    got = _run_allreduce(comms, x, op, cfg)
    # replicated-identical across ranks (the allreduce contract survives
    # quantization: every rank decodes the same packets)
    for r in range(1, WORLD):
        np.testing.assert_array_equal(got[r], got[0])
    scale = np.max(np.abs(exact)) + 1e-9
    assert np.max(np.abs(got[0] - exact)) / scale <= tol
    # and the wire really was quantized (not silently exact)
    assert np.any(got[0] != exact)


def test_qallreduce_off_bit_identical(comms):
    rng = np.random.default_rng(5)
    x = rng.normal(size=(WORLD, 257)).astype(np.float32)
    np.testing.assert_array_equal(
        _run_allreduce(comms, x, op_t.SUM, None),
        _run_allreduce(comms, x, op_t.SUM, "off"))


def test_qallreduce_int_payload_falls_back_exact(comms):
    x = np.arange(WORLD * 16, dtype=np.int32).reshape(WORLD, 16)
    got = _run_allreduce(comms, x, op_t.SUM, INT8)
    np.testing.assert_array_equal(got[0], x.sum(0))


def test_qallgather_matches_exact_layout(comms):
    rng = np.random.default_rng(9)
    x = rng.normal(size=(WORLD, 65)).astype(np.float32) * 4.0
    ac = comms.comms

    def body(xs):
        return ac.allgather(xs[0], quantization=INT8)[None]

    got = np.asarray(jax.shard_map(
        body, mesh=comms.mesh, in_specs=P("data"), out_specs=P("data"),
        check_vma=False)(x))  # (WORLD ranks, WORLD slots, 65)
    for r in range(1, WORLD):
        np.testing.assert_array_equal(got[r], got[0])
    err = np.abs(got[0] - x)
    bound = np.abs(x).max(axis=1, keepdims=True) / 254.0 + 1e-6
    assert np.all(err <= bound)  # one encode, per-rank-block absmax bound


def test_qreducescatter_matches_exact_chunks(comms):
    rng = np.random.default_rng(13)
    x = rng.normal(size=(WORLD * 12, 7)).astype(np.float32)
    ac = comms.comms

    def body(xs):
        return ac.reducescatter(xs, op_t.SUM, quantization=INT8)

    # replicated input (every rank reduces the same full plane), per-rank
    # output chunks stitch back to the full (rows, 7) reduction
    got = np.asarray(jax.shard_map(
        body, mesh=comms.mesh, in_specs=P(None, None), out_specs=P("data"),
        check_vma=False)(x))
    exact = x * WORLD  # identical contribution from each rank
    scale = np.abs(exact).max() + 1e-9
    assert got.shape == exact.shape
    assert np.max(np.abs(got - exact)) / scale <= 0.05


def test_qreducescatter_divisibility_error(comms):
    ac = comms.comms
    with pytest.raises(ValueError, match="not divisible"):
        jax.shard_map(
            lambda xs: ac.reducescatter(xs, op_t.SUM, quantization=INT8),
            mesh=comms.mesh, in_specs=P(None, None), out_specs=P("data"),
            check_vma=False)(np.ones((WORLD * 3 + 1, 4), np.float32))


def test_qbcast_nonzero_root(comms):
    rng = np.random.default_rng(17)
    x = rng.normal(size=(WORLD, 33)).astype(np.float32) * 2.0
    ac = comms.comms

    def body(xs):
        return ac.bcast(xs[0], root=3, quantization=INT8)[None]

    got = np.asarray(jax.shard_map(
        body, mesh=comms.mesh, in_specs=P("data"), out_specs=P("data"),
        check_vma=False)(x))
    for r in range(1, WORLD):
        np.testing.assert_array_equal(got[r], got[0])
    bound = np.abs(x[3]).max() / 254.0 + 1e-6
    assert np.all(np.abs(got[0] - x[3]) <= bound)


def test_grouped_qallreduce(comms):
    """2x4 comm_split: quantized grouped allreduce sums within each group
    only, own contribution exact."""
    rng = np.random.default_rng(19)
    x = rng.normal(size=(WORLD, 64)).astype(np.float32)
    ac = comms.comms
    colors = [0, 0, 0, 0, 1, 1, 1, 1]

    def body(xs):
        sub = ac.comm_split(colors)
        return sub.allreduce(xs[0], quantization=INT8)[None]

    got = np.asarray(jax.shard_map(
        body, mesh=comms.mesh, in_specs=P("data"), out_specs=P("data"),
        check_vma=False)(x))
    for g, ranks in ((0, range(4)), (1, range(4, 8))):
        exact = x[list(ranks)].sum(0)
        scale = np.abs(exact).max() + 1e-9
        for r in ranks:
            assert np.max(np.abs(got[r] - exact)) / scale <= 0.05, (g, r)


# -- candidate exchange --------------------------------------------------

def _run_exchange(comms, v, ids, k, cfg, select_min=True):
    ac = comms.comms

    def body(vs, is_):
        rv, rid = quantized.exchange_candidates(ac, vs[0], is_[0], k,
                                                select_min, cfg)
        return rv[None], rid[None]

    rv, rid = jax.shard_map(
        body, mesh=comms.mesh, in_specs=(P("data"), P("data")),
        out_specs=(P("data"), P("data")), check_vma=False)(v, ids)
    return np.asarray(rv), np.asarray(rid)


def _exact_merge(v, ids, k, select_min=True):
    # flat rank-major reference merge
    cat_v = np.moveaxis(v, 0, 1).reshape(v.shape[1], -1)
    cat_i = np.moveaxis(ids, 0, 1).reshape(v.shape[1], -1)
    order = np.argsort(cat_v if select_min else -cat_v, axis=1,
                       kind="stable")[:, :k]
    return (np.take_along_axis(cat_v, order, 1),
            np.take_along_axis(cat_i, order, 1))


@pytest.fixture(scope="module")
def exchange_data():
    rng = np.random.default_rng(23)
    nq, kk = 16, 16
    v = np.sort(rng.uniform(0, 100, size=(WORLD, nq, kk)), axis=2)
    v = v.astype(np.float32)
    # globally unique ids: the exact-survivor check below keys on them
    ids = rng.permutation(WORLD * nq * kk).reshape(
        WORLD, nq, kk).astype(np.int32)
    return v, ids


@pytest.mark.parametrize("cfg", [INT8, BF16], ids=["int8", "bf16"])
def test_exchange_recall_and_exact_survivor_scores(comms, exchange_data, cfg):
    v, ids, k = exchange_data[0], exchange_data[1], 10
    rv, rid = _run_exchange(comms, v, ids, k, cfg)
    for r in range(1, WORLD):
        np.testing.assert_array_equal(rv[r], rv[0])
        np.testing.assert_array_equal(rid[r], rid[0])
    ev, eids = _exact_merge(v, ids, k)
    assert _recall(rid[0], eids) >= 1.0 - 1e-3
    # the recall-safe shape: every reported (id, score) pair is the
    # owner's EXACT pair, bit-for-bit — quantization only shortlists
    lut = {int(i): float(s)
           for i, s in zip(ids.reshape(-1), v.reshape(-1))}
    for row_v, row_i in zip(rv[0], rid[0]):
        for s, i in zip(row_v, row_i):
            assert lut[int(i)] == float(s)


def test_exchange_saturated_matches_exact_merge(comms, exchange_data):
    """A shortlist covering every candidate must reproduce the exact
    merge bit-for-bit (quantization can then only reorder the shortlist,
    and the exact re-rank undoes that)."""
    v, ids, k = exchange_data[0], exchange_data[1], 10
    cfg = QuantConfig(mode="int8", block=32, exchange_mult=1000.0)
    rv, rid = _run_exchange(comms, v, ids, k, cfg)
    ev, eids = _exact_merge(v, ids, k)
    np.testing.assert_array_equal(rv[0], ev)
    np.testing.assert_array_equal(rid[0], eids)


# -- driver bit-identity pins and quantized recall -----------------------

def test_kmeans_off_bit_identical_and_quantized_tolerance(comms, blobs):
    base = mnmg.kmeans_fit(comms, blobs, 6, max_iter=5, seed=0)
    off = mnmg.kmeans_fit(comms, blobs, 6, max_iter=5, seed=0,
                          quantization="off")
    np.testing.assert_array_equal(np.asarray(base[0]), np.asarray(off[0]))
    assert base[1] == off[1] and base[2] == off[2]
    # quantized partial-sum transport: centroids track the exact fit
    # (assignment flips compound over Lloyd iterations — the gate is a
    # centroid-scale tolerance, not bit-identity)
    ci, inertia_i, _ = mnmg.kmeans_fit(comms, blobs, 6, max_iter=5, seed=0,
                                       quantization="int8")
    cb, inertia_b, _ = mnmg.kmeans_fit(comms, blobs, 6, max_iter=5, seed=0,
                                       quantization="bf16")
    scale = np.abs(np.asarray(base[0])).max()
    assert np.max(np.abs(np.asarray(ci) - np.asarray(base[0]))) <= 0.25 * scale
    assert np.max(np.abs(np.asarray(cb) - np.asarray(base[0]))) <= 0.1 * scale
    assert inertia_i <= base[1] * 1.1 and inertia_b <= base[1] * 1.05


def test_knn_off_bit_identical_and_quantized_recall(comms, blobs):
    q = blobs[:19]
    bv, bi = mnmg.knn(comms, blobs, q, 10)
    ov, oi = mnmg.knn(comms, blobs, q, 10, quantization="off")
    np.testing.assert_array_equal(np.asarray(bv), np.asarray(ov))
    np.testing.assert_array_equal(np.asarray(bi), np.asarray(oi))
    qv, qi = mnmg.knn(comms, blobs, q, 10, quantization="int8")
    assert _recall(qi, oi) >= 1.0 - 1e-3
    # exact re-rank: returned distances are full-precision
    _, truth = brute_force.knn(blobs, q, 10)
    assert _recall(qi, truth) >= 1.0 - 1e-3


def test_ivf_flat_off_bit_identical_and_quantized_recall(comms, blobs):
    index = mnmg.ivf_flat_build(
        comms, ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4), blobs)
    q = blobs[:19]
    bv, bi = mnmg.ivf_flat_search(index, q, 5, n_probes=8)
    ov, oi = mnmg.ivf_flat_search(index, q, 5, n_probes=8,
                                  quantization="off")
    np.testing.assert_array_equal(np.asarray(bv), np.asarray(ov))
    np.testing.assert_array_equal(np.asarray(bi), np.asarray(oi))
    qv, qi = mnmg.ivf_flat_search(index, q, 5, n_probes=8,
                                  quantization="int8")
    assert _recall(qi, oi) >= 1.0 - 1e-3
    np.testing.assert_allclose(np.asarray(qv), np.asarray(ov), rtol=1e-6)


@pytest.mark.slow
def test_ivf_pq_off_bit_identical_and_quantized_recall(comms, blobs):
    index = mnmg.ivf_pq_build(
        comms, ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=4),
        blobs)
    q = blobs[:19]
    ov, oi = mnmg.ivf_pq_search(index, q, 5, n_probes=8, quantization="off")
    bv, bi = mnmg.ivf_pq_search(index, q, 5, n_probes=8)
    np.testing.assert_array_equal(np.asarray(bv), np.asarray(ov))
    np.testing.assert_array_equal(np.asarray(bi), np.asarray(oi))
    qv, qi = mnmg.ivf_pq_search(index, q, 5, n_probes=8,
                                quantization="int8")
    assert _recall(qi, oi) >= 1.0 - 1e-3


@pytest.mark.slow
def test_ivf_rabitq_off_bit_identical_and_quantized_recall(comms, blobs):
    index = mnmg.ivf_rabitq_build(
        comms, ivf_rabitq.IndexParams(n_lists=8, kmeans_n_iters=4), blobs)
    q = blobs[:19]
    ov, oi = mnmg.ivf_rabitq_search(index, q, 5, n_probes=8,
                                    quantization="off")
    bv, bi = mnmg.ivf_rabitq_search(index, q, 5, n_probes=8)
    np.testing.assert_array_equal(np.asarray(bv), np.asarray(ov))
    np.testing.assert_array_equal(np.asarray(bi), np.asarray(oi))
    qv, qi = mnmg.ivf_rabitq_search(index, q, 5, n_probes=8,
                                    quantization="int8")
    assert _recall(qi, oi) >= 1.0 - 1e-3


# -- replication failover + degraded health under quantization -----------

@pytest.mark.slow
def test_quantized_search_failover_and_degraded(blobs):
    """Kill a rank on a replicated index: the quantized search over the
    failover view stays within 1e-3 recall of the exact-path search over
    the SAME view; on an unreplicated index the degraded (health=) path
    keeps coverage honesty under quantization."""
    from raft_tpu.comms.resilience import RankHealth

    comms4 = Comms(n_devices=4)
    q = blobs[:19]
    rep = mnmg.ivf_flat_build(
        comms4, ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4), blobs,
        replication=2)
    health = RankHealth.all_healthy(4).mark_unhealthy(1)
    off = mnmg.ivf_flat_search(rep, q, 5, n_probes=8, health=health,
                               quantization="off")
    qi8 = mnmg.ivf_flat_search(rep, q, 5, n_probes=8, health=health,
                               quantization="int8")
    assert off.coverage == 1.0 and qi8.coverage == 1.0  # replica absorbed
    assert _recall(qi8.ids, off.ids) >= 1.0 - 1e-3
    np.testing.assert_allclose(np.asarray(qi8.values), np.asarray(off.values),
                               rtol=1e-6)
    # unreplicated: degraded coverage reported identically on both paths
    bare = mnmg.ivf_flat_build(
        comms4, ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4), blobs)
    off_d = mnmg.ivf_flat_search(bare, q, 5, n_probes=8, health=health,
                                 quantization="off")
    qi8_d = mnmg.ivf_flat_search(bare, q, 5, n_probes=8, health=health,
                                 quantization="int8")
    assert off_d.coverage == qi8_d.coverage == 0.75
    assert _recall(qi8_d.ids, off_d.ids) >= 1.0 - 1e-3


def test_quantized_mirror_tables(blobs):
    """replication.mirror_table under quantization: float tables decode
    within the absmax bound, int tables (the failover id contract) pass
    through bit-exact, and the default stays bit-identical."""
    from raft_tpu.comms import replication

    comms4 = Comms(n_devices=4)
    rng = np.random.default_rng(29)
    arr = rng.normal(size=(4, 64)).astype(np.float32)
    exact = np.asarray(replication.mirror_table(comms4, arr, r=2))
    q8 = np.asarray(replication.mirror_table(comms4, arr, r=2,
                                             quantization="int8"))
    assert q8.shape == exact.shape == (4, 1, 64)  # (R, r-1, ...) mirrors
    bound = np.abs(arr).max() / 254.0 + 1e-6
    assert np.max(np.abs(q8 - exact)) <= bound
    assert np.any(q8 != exact)  # the mirror really travelled quantized
    ids = np.arange(4 * 16, dtype=np.int32).reshape(4, 16)
    np.testing.assert_array_equal(
        np.asarray(replication.mirror_table(comms4, ids, r=2,
                                            quantization="int8")),
        np.asarray(replication.mirror_table(comms4, ids, r=2)))


# -- wire accounting -----------------------------------------------------

def _wire_counter(op):
    return obs.registry().counter(f"comms.{op}.wire_bytes").value


def test_wire_bytes_2x_reduction_allreduce_allgather(comms):
    """The savings claim: quantized allreduce/allgather charge the wire
    counters with the ACTUAL int8+sidecar bytes, at least 2x below the
    exact f32 wire model on the same payload."""
    x = np.random.default_rng(31).normal(
        size=(WORLD, 4096)).astype(np.float32)
    ac = comms.comms
    obs.enable()
    try:
        wire = {}
        for name, quant in (("exact", None), ("int8", INT8)):
            obs.reset()
            _run_allreduce(comms, x, op_t.SUM, quant)
            ar = _wire_counter("allreduce")

            def body(xs):
                return ac.allgather(xs[0], quantization=quant)[None]

            obs.reset()
            jax.shard_map(body, mesh=comms.mesh, in_specs=P("data"),
                          out_specs=P("data"), check_vma=False)(x)
            wire[name] = (ar, _wire_counter("allgather"))
        assert wire["exact"][0] >= 2 * wire["int8"][0] > 0, wire
        assert wire["exact"][1] >= 2 * wire["int8"][1] > 0, wire
        # the wire dtype rides the event stream
        dtypes = {e.get("wire_dtype") for e in
                  obs.bus().events("collective")}
        assert "int8" in dtypes
    finally:
        obs.disable()
        obs.reset()


def test_wire_bytes_2x_reduction_exchange(comms, exchange_data):
    """Candidate exchange vs the exact packed-plane merge: quantized
    scores + the narrow exact-resolve psums must halve the wire."""
    from raft_tpu.comms.mnmg_merge import _merge_local_topk_allgather

    v, ids, k = exchange_data[0], exchange_data[1], 10
    ac = comms.comms
    obs.enable()
    try:
        obs.reset()
        jax.shard_map(
            lambda vs, is_: _merge_local_topk_allgather(
                ac, vs[0], is_[0], k, True)[0][None],
            mesh=comms.mesh, in_specs=(P("data"), P("data")),
            out_specs=P("data"), check_vma=False)(v, ids)
        exact_wire = (_wire_counter("allreduce")
                      + _wire_counter("allgather"))
        obs.reset()
        _run_exchange(comms, v, ids, k, INT8)
        quant_wire = (_wire_counter("allreduce")
                      + _wire_counter("allgather"))
        assert exact_wire >= 2 * quant_wire > 0, (exact_wire, quant_wire)
    finally:
        obs.disable()
        obs.reset()
