"""Comms tests over the 8-device virtual CPU mesh (mirrors
raft-dask test_comms.py:45-317 — init, per-collective correctness,
comm_split, send/recv, multicast — with the virtual mesh standing in for
LocalCUDACluster, survey §4)."""

import numpy as np
import pytest

from raft_tpu import Resources
from raft_tpu.comms import Comms, init_comms, local_handle, op_t

import comms_selftests as comms_test  # noqa: E402 — tests/ sibling (relocated from raft_tpu/comms)


@pytest.fixture(scope="module")
def comms():
    return Comms()


def test_init_and_handle_injection():
    res = Resources()
    c = init_comms(res)
    assert res.comms_initialized()
    assert local_handle(res) is c
    assert c.get_size() == 8
    assert c.nccl_initialized
    c.destroy()
    assert not c.nccl_initialized


# the heaviest self-tests (fresh grouped/gatherv shard_map compiles,
# 4-7s each on the 1-core box) run full-tier only; the quick tier keeps
# perform_test_comm_split + comm_split_unequal_groups as grouped smokes
_HEAVY_SELF_TESTS = {
    comms_test.perform_test_comm_split_unequal,
    comms_test.perform_test_comm_split_reducescatter,
    comms_test.perform_test_comms_gatherv,
}


@pytest.mark.parametrize(
    "func",
    [pytest.param(f, marks=pytest.mark.slow)
     if f in _HEAVY_SELF_TESTS else f
     for f in comms_test.ALL_TESTS],
    ids=lambda f: f.__name__,
)
def test_collectives(comms, func):
    assert func(comms), func.__name__


def test_bcast_nonzero_root(comms):
    assert comms_test.perform_test_comms_bcast(comms, root=3)


def test_reduce_nonzero_root(comms):
    assert comms_test.perform_test_comms_reduce(comms, root=5)


def test_comm_split_wrong_length_raises(comms):
    ac = comms.comms
    with pytest.raises(ValueError):
        ac.comm_split([0, 1])


def test_comm_split_unequal_groups(comms):
    """3+5 split: grouped allreduce sums differ per group."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    ac = comms.comms

    def body():
        sub = ac.comm_split([0, 0, 0, 1, 1, 1, 1, 1])
        s = sub.allreduce(jnp.ones((), jnp.float32))
        return (s == sub.get_size())[None]

    ok = jax.shard_map(
        body, mesh=comms.mesh, in_specs=(), out_specs=P("data"), check_vma=False
    )()
    assert bool(np.all(np.asarray(ok)))


def test_allgatherv_shape_guard(comms):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    ac = comms.comms

    def body():
        return ac.allgatherv(jnp.ones((2, 3)), counts=[3] * 8)[0, 0, 0]

    with pytest.raises(ValueError, match="max.counts."):
        jax.shard_map(
            body, mesh=comms.mesh, in_specs=(), out_specs=P(), check_vma=False
        )()


def test_allreduce_prod_large_array(comms):
    """Exercises the O(1)-memory log-space PROD path (size > 4096) with a
    zero and negatives in the data."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    ac = comms.comms
    rng = np.random.default_rng(0)
    x = rng.uniform(0.5, 2.0, size=(8, 5000)).astype(np.float32)
    x[1, 0] = 0.0  # exact-zero result at element 0
    x[2, 1] *= -1.0
    x[5, 1] *= -1.0  # two negatives: positive result at element 1
    x[4, 2] *= -1.0  # one negative: negative result at element 2

    def body(s):
        return ac.allreduce(s[0], op_t.PROD)[None]

    out = jax.shard_map(
        body, mesh=comms.mesh, in_specs=P("data"), out_specs=P("data"),
        check_vma=False,
    )(comms.shard(x))
    out = np.asarray(out)
    want = np.prod(x, axis=0)
    assert out.shape == (8, 5000)
    np.testing.assert_allclose(out[0], want, rtol=2e-4)
    assert out[0, 0] == 0.0
    assert out[0, 1] > 0 and out[0, 2] < 0


def test_allgatherv_counts_length_guard(comms):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    ac = comms.comms

    def body():
        return ac.allgatherv(jnp.ones((8, 2)), counts=[1, 2, 3])[0, 0, 0]

    with pytest.raises(ValueError, match="len.counts."):
        jax.shard_map(
            body, mesh=comms.mesh, in_specs=(), out_specs=P(), check_vma=False
        )()


def test_allreduce_ops(comms):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    ac = comms.comms

    def body(x):
        v = x[0]  # each rank holds one element
        return (
            ac.allreduce(v, op_t.SUM),
            ac.allreduce(v, op_t.MAX),
            ac.allreduce(v, op_t.MIN),
        )

    x = comms.shard(np.arange(1.0, 9.0, dtype=np.float32))
    s, mx, mn = jax.shard_map(
        body, mesh=comms.mesh, in_specs=P("data"), out_specs=(P(), P(), P())
    )(x)
    assert float(s) == 36.0
    assert float(mx) == 8.0
    assert float(mn) == 1.0


# each seed's random colors compile a fresh 8-collective shard_map
# (~13-22s on the 1-core box); the quick tier smokes grouped semantics
# via the comm_split self-tests, the oracle sweep is full-tier
@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_grouped_collectives_vs_oracle(comms, seed):
    """Randomized comm_split sweep: random color partition, random int and
    float payloads; grouped allreduce (all ops), bcast, reduce, and
    reducescatter must match a per-group numpy oracle on every rank."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    rng = np.random.default_rng(seed)
    n = comms.get_size()
    colors = rng.integers(0, rng.integers(2, 5), n).tolist()
    groups = {}
    for r, c in enumerate(colors):
        groups.setdefault(c, []).append(r)
    m = max(len(g) for g in groups.values())
    root = int(rng.integers(0, min(len(g) for g in groups.values())))
    d = 4 * m  # divisible by every chunking the sweep uses
    xf = rng.standard_normal((n, d)).astype(np.float32)
    xi = rng.integers(-5, 6, (n, d)).astype(np.int32)
    ac = comms.comms

    def body(xf, xi):
        sub = ac.comm_split(colors)
        return (
            sub.allreduce(xf[0], op_t.SUM),
            sub.allreduce(xf[0], op_t.MIN),
            sub.allreduce(xi[0], op_t.MAX),
            sub.allreduce(xi[0], op_t.PROD),
            sub.bcast(xf[0], root=root),
            sub.reduce(xf[0], root=root, op=op_t.MAX),
            sub.reducescatter(xf[0], op_t.SUM),
            sub.reducescatter(xf[0], op_t.MIN),
        )

    outs = jax.shard_map(
        body, mesh=comms.mesh, in_specs=(P("data"), P("data")),
        out_specs=(P("data"),) * 8, check_vma=False,
    )(comms.shard(xf), comms.shard(xi))
    # out_specs=P("data") concatenates per-rank vectors; split back per rank
    outs = [np.asarray(o).reshape(n, -1) for o in outs]
    per = d // m
    for g in groups.values():
        for pos, r in enumerate(g):
            np.testing.assert_allclose(outs[0][r], xf[g].sum(0), rtol=1e-5)
            np.testing.assert_array_equal(outs[1][r], xf[g].min(0))
            np.testing.assert_array_equal(outs[2][r], xi[g].max(0))
            np.testing.assert_array_equal(outs[3][r], np.prod(xi[g], 0))
            np.testing.assert_array_equal(outs[4][r], xf[g[root]])
            want_red = xf[g].max(0) if pos == root else np.zeros(d, np.float32)
            np.testing.assert_array_equal(outs[5][r], want_red)
            sl = slice(pos * per, (pos + 1) * per)
            np.testing.assert_allclose(outs[6][r], xf[g].sum(0)[sl], rtol=1e-5)
            np.testing.assert_array_equal(outs[7][r], xf[g].min(0)[sl])


@pytest.mark.parametrize("schedule", ["ring", "planes"])
def test_grouped_allreduce_schedules_agree(comms, schedule, monkeypatch):
    """Both grouped-reduce schedules (intra-group ppermute ring vs masked
    planes psum) must match the per-group numpy oracle — incl. ragged
    groups and a size-1 group, the ring's gating edge cases."""
    import jax
    import jax.numpy as jnp
    from raft_tpu.core import tuned

    monkeypatch.setattr(
        tuned, "get",
        lambda key, default=None:
            schedule if key == "grouped_reduce_schedule" else default,
    )
    from jax.sharding import PartitionSpec as P

    n = comms.get_size()
    colors = [0, 1, 1, 2, 2, 2, 2, 3][:n]  # ragged: sizes 1, 2, 4, 1
    rng = np.random.default_rng(5)
    xf = rng.standard_normal((n, 6)).astype(np.float32)
    ac = comms.comms

    def body(xf):
        sub = ac.comm_split(colors)
        return (sub.allreduce(xf[0], op_t.SUM),
                sub.allreduce(xf[0], op_t.MIN),
                sub.allreduce(xf[0], op_t.MAX),
                sub.bcast(xf[0], root=0),
                sub.reduce(xf[0], root=0, op=op_t.SUM),
                sub.allgather(xf[0], axis=0))
    m = max(len([r for r in range(n) if colors[r] == c0])
            for c0 in set(colors))
    outs = jax.shard_map(
        body, mesh=comms.mesh, in_specs=(P("data"),),
        out_specs=(P("data"),) * 5 + (P("data", None),), check_vma=False,
    )(comms.shard(xf))
    ag = np.asarray(outs[5]).reshape(n, m, -1)
    outs = [np.asarray(o).reshape(n, -1) for o in outs[:5]]
    groups = {}
    for r, c in enumerate(colors):
        groups.setdefault(c, []).append(r)
    for g in groups.values():
        for pos, r in enumerate(g):
            np.testing.assert_allclose(outs[0][r], xf[g].sum(0), rtol=1e-5)
            np.testing.assert_array_equal(outs[1][r], xf[g].min(0))
            np.testing.assert_array_equal(outs[2][r], xf[g].max(0))
            np.testing.assert_array_equal(outs[3][r], xf[g[0]])
            want = xf[g].sum(0) if pos == 0 else np.zeros_like(xf[0])
            np.testing.assert_allclose(outs[4][r], want, rtol=1e-5)
            # group slots in group-local order, zero pad past own size
            want_ag = np.zeros((m, xf.shape[1]), xf.dtype)
            want_ag[: len(g)] = xf[g]
            np.testing.assert_array_equal(ag[r], want_ag)


def test_reducescatter_minmax_matches_oracle(comms):
    """Ungrouped MIN/MAX reducescatter (all_to_all path) vs numpy."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    rng = np.random.default_rng(9)
    n = comms.get_size()
    d = 3 * n
    x = rng.standard_normal((n, d)).astype(np.float32)
    ac = comms.comms

    def body(x):
        return (ac.reducescatter(x[0], op_t.MIN),
                ac.reducescatter(x[0], op_t.MAX))

    mn, mx = jax.shard_map(
        body, mesh=comms.mesh, in_specs=P("data"),
        out_specs=(P("data"),) * 2, check_vma=False,
    )(comms.shard(x))
    per = d // n
    mn = np.asarray(mn).reshape(n, per)
    mx = np.asarray(mx).reshape(n, per)
    for r in range(n):
        sl = slice(r * per, (r + 1) * per)
        np.testing.assert_array_equal(mn[r], x.min(0)[sl])
        np.testing.assert_array_equal(mx[r], x.max(0)[sl])


def test_reducescatter_divisibility_guard(comms):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    ac = comms.comms

    def body():
        return ac.reducescatter(jnp.ones((comms.get_size() + 1,), jnp.float32))

    with pytest.raises(ValueError, match="not divisible"):
        jax.shard_map(body, mesh=comms.mesh, in_specs=(),
                      out_specs=P("data"), check_vma=False)()


def test_host_p2p_stubs_document_rescope():
    """comms_t.isend/irecv/waitall/group_start/group_end (core/comms.hpp:
    154-176, 212-230) are DELIBERATELY absent on TPU — the stubs must say
    so loudly and point at the ppermute mapping, not AttributeError."""
    import pytest

    c = Comms(n_devices=2)
    ac = c.comms
    for name in ("isend", "irecv", "waitall", "group_start", "group_end"):
        with pytest.raises(NotImplementedError, match="TPU analogue"):
            getattr(ac, name)()
