"""Comms tests over the 8-device virtual CPU mesh (mirrors
raft-dask test_comms.py:45-317 — init, per-collective correctness,
comm_split, send/recv, multicast — with the virtual mesh standing in for
LocalCUDACluster, survey §4)."""

import numpy as np
import pytest

from raft_tpu import Resources
from raft_tpu.comms import Comms, init_comms, local_handle, comms_test, op_t


@pytest.fixture(scope="module")
def comms():
    return Comms()


def test_init_and_handle_injection():
    res = Resources()
    c = init_comms(res)
    assert res.comms_initialized()
    assert local_handle(res) is c
    assert c.get_size() == 8
    assert c.nccl_initialized
    c.destroy()
    assert not c.nccl_initialized


@pytest.mark.parametrize("func", comms_test.ALL_TESTS, ids=lambda f: f.__name__)
def test_collectives(comms, func):
    assert func(comms), func.__name__


def test_bcast_nonzero_root(comms):
    assert comms_test.perform_test_comms_bcast(comms, root=3)


def test_reduce_nonzero_root(comms):
    assert comms_test.perform_test_comms_reduce(comms, root=5)


def test_comm_split_unequal_raises(comms):
    ac = comms.comms
    with pytest.raises(ValueError):
        ac.comm_split([0, 0, 0, 1, 1, 1, 1, 1])
    with pytest.raises(ValueError):
        ac.comm_split([0, 1])


def test_allreduce_ops(comms):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    ac = comms.comms

    def body(x):
        v = x[0]  # each rank holds one element
        return (
            ac.allreduce(v, op_t.SUM),
            ac.allreduce(v, op_t.MAX),
            ac.allreduce(v, op_t.MIN),
        )

    x = comms.shard(np.arange(1.0, 9.0, dtype=np.float32))
    s, mx, mn = jax.shard_map(
        body, mesh=comms.mesh, in_specs=P("data"), out_specs=(P(), P(), P())
    )(x)
    assert float(s) == 36.0
    assert float(mx) == 8.0
    assert float(mn) == 1.0
