"""Stats tests vs numpy/sklearn oracles (mirrors cpp/test/stats/*)."""

import numpy as np
import pytest
import sklearn.metrics as skm

from raft_tpu import stats


def test_descriptive(rng):
    x = rng.random((50, 6), dtype=np.float32)
    np.testing.assert_allclose(np.asarray(stats.mean(x)), x.mean(0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(stats.sum_stat(x)), x.sum(0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(stats.stddev(x)), x.std(0, ddof=1), rtol=1e-4)
    m, v = stats.meanvar(x)
    np.testing.assert_allclose(np.asarray(v), x.var(0, ddof=1), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(stats.cov(x)), np.cov(x.T), rtol=1e-3, atol=1e-5)
    mn, mx = stats.minmax(x)
    np.testing.assert_allclose(np.asarray(mn), x.min(0))
    np.testing.assert_allclose(np.asarray(mx), x.max(0))
    centered = np.asarray(stats.mean_center(x))
    np.testing.assert_allclose(centered.mean(0), np.zeros(6), atol=1e-5)
    w = rng.random(50, dtype=np.float32)
    np.testing.assert_allclose(
        np.asarray(stats.weighted_mean(x, w)), (w[:, None] * x).sum(0) / w.sum(), rtol=1e-4
    )


def test_histogram(rng):
    x = rng.random(10000, dtype=np.float32)
    h = np.asarray(stats.histogram(x, 10, 0.0, 1.0))
    want, _ = np.histogram(x, bins=10, range=(0.0, 1.0))
    np.testing.assert_array_equal(h, want)


def test_classification_metrics(rng):
    y = rng.integers(0, 3, 200)
    p = y.copy()
    flip = rng.choice(200, 40, replace=False)
    p[flip] = (p[flip] + 1) % 3
    np.testing.assert_allclose(float(stats.accuracy(p, y)), (p == y).mean(), rtol=1e-6)


def test_r2_and_regression(rng):
    y = rng.random(100, dtype=np.float32)
    yh = y + 0.1 * rng.random(100, dtype=np.float32)
    np.testing.assert_allclose(float(stats.r2_score(y, yh)), skm.r2_score(y, yh), atol=1e-4)
    m = stats.regression_metrics(yh, y)
    np.testing.assert_allclose(
        float(m["mean_abs_error"]), np.abs(yh - y).mean(), rtol=1e-5
    )


def test_clustering_comparison_metrics(rng):
    a = rng.integers(0, 4, 300)
    b = a.copy()
    flip = rng.choice(300, 60, replace=False)
    b[flip] = rng.integers(0, 4, 60)
    np.testing.assert_allclose(
        float(stats.adjusted_rand_index(a, b)), skm.adjusted_rand_score(a, b), atol=1e-4
    )
    np.testing.assert_allclose(
        float(stats.rand_index(a, b)), skm.rand_score(a, b), atol=1e-4
    )
    np.testing.assert_allclose(
        float(stats.mutual_info_score(a, b)), skm.mutual_info_score(a, b), atol=1e-4
    )
    np.testing.assert_allclose(
        float(stats.homogeneity_score(a, b)), skm.homogeneity_score(a, b), atol=1e-4
    )
    np.testing.assert_allclose(
        float(stats.completeness_score(a, b)), skm.completeness_score(a, b), atol=1e-4
    )
    np.testing.assert_allclose(
        float(stats.v_measure(a, b)), skm.v_measure_score(a, b), atol=1e-4
    )


def test_entropy_and_kl(rng):
    l = rng.integers(0, 5, 1000)
    counts = np.bincount(l) / 1000
    want = -(counts * np.log(counts)).sum()
    np.testing.assert_allclose(float(stats.entropy(l)), want, atol=1e-4)
    p = rng.random(10).astype(np.float32)
    p /= p.sum()
    q = rng.random(10).astype(np.float32)
    q /= q.sum()
    np.testing.assert_allclose(
        float(stats.kl_divergence(p, q)), (p * np.log(p / q)).sum(), atol=1e-4
    )


@pytest.mark.slow
def test_silhouette(rng):
    from raft_tpu.random import make_blobs

    x, l = make_blobs(600, 8, n_clusters=3, cluster_std=0.5, seed=4)
    x, l = np.asarray(x), np.asarray(l)
    got = float(stats.silhouette_score(x, l))
    want = skm.silhouette_score(x, l)
    np.testing.assert_allclose(got, want, atol=2e-2)


def test_trustworthiness(rng):
    x = rng.random((120, 10), dtype=np.float32)
    # identity embedding: trustworthiness == 1
    t = float(stats.trustworthiness_score(x, x.copy(), n_neighbors=5))
    assert t > 0.999
    # random embedding: markedly lower
    t2 = float(stats.trustworthiness_score(x, rng.random((120, 2), dtype=np.float32)))
    assert t2 < t


def test_information_criterion():
    ll = -120.0
    aic = float(stats.information_criterion_batched(ll, 5, 100, "AIC"))
    np.testing.assert_allclose(aic, -2 * ll + 10)
    bic = float(stats.information_criterion_batched(ll, 5, 100, "BIC"))
    np.testing.assert_allclose(bic, -2 * ll + 5 * np.log(100), rtol=1e-6)


def test_dispersion(rng):
    c = rng.random((4, 3), dtype=np.float32)
    sizes = np.array([10, 20, 30, 40], np.float32)
    d = float(stats.dispersion(c, sizes))
    g = (sizes[:, None] * c).sum(0) / sizes.sum()
    want = np.sqrt((sizes * ((c - g) ** 2).sum(1)).sum())
    np.testing.assert_allclose(d, want, rtol=1e-5)
