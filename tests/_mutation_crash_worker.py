"""Child worker for the mutation kill-and-resume drills
(tests/test_mutation.py).

Builds a deterministic index, then applies a SCRIPTED mutation sequence
(seed-derived upserts and deletes, one rebalance) through the
crash-atomic `neighbors.mutation.Mutator` — optionally under a seeded
FaultPlan whose kill_rank fault at ``mutation.log.commit`` SIGKILLs
THIS process on the count-th visit. The site is visited after EVERY log
append and after EVERY checkpoint commit, so sweeping the kill count
lands the SIGKILL in both crash windows (log ahead of checkpoint, and
just-committed). The parent re-runs the same command line; the
mutation-log dedupe-by-seq must carry the resume to a byte-identical
final checkpoint and identical search results. A separate process is
the point: SIGKILL leaves no chance for in-process cleanup to cheat.

Not a test module (underscore prefix keeps pytest away).
"""

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _params(kind: str):
    if kind == "ivf_flat":
        from raft_tpu.neighbors import ivf_flat as mod

        return mod, mod.IndexParams(n_lists=4, kmeans_n_iters=2)
    if kind == "ivf_pq":
        from raft_tpu.neighbors import ivf_pq as mod

        return mod, mod.IndexParams(n_lists=4, pq_dim=4, pq_bits=4,
                                    kmeans_n_iters=2,
                                    kmeans_trainset_fraction=1.0)
    if kind == "ivf_rabitq":
        from raft_tpu.neighbors import ivf_rabitq as mod

        return mod, mod.IndexParams(n_lists=4, kmeans_n_iters=2,
                                    store_dataset=False)
    raise SystemExit(f"unknown kind {kind!r}")


def scripted_ops(seed: int, dim: int, n0: int):
    """The deterministic mutation sequence every invocation replays:
    upserts (some overwriting build-time ids, some fresh), deletes
    (including a just-upserted id — mid-delete kills must not resurrect
    it), and one rebalance. Pure function of (seed, dim, n0)."""
    rng = np.random.default_rng(seed)
    ops = []
    ops.append(("upsert", rng.standard_normal((5, dim)).astype(np.float32),
                np.arange(3, 8)))                       # overwrite build ids
    ops.append(("delete", None, np.array([0, 1, 5])))   # 5 = upserted above
    ops.append(("upsert", rng.standard_normal((4, dim)).astype(np.float32),
                np.array([n0 + 50, n0 + 51, 1, 6])))    # resurrect 1, fresh 2
    ops.append(("rebalance",))
    ops.append(("delete", None, np.array([n0 + 50, 2])))
    ops.append(("upsert", rng.standard_normal((3, dim)).astype(np.float32),
                np.array([9, 10, n0 + 60])))
    return ops


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--kind", default="ivf_flat")
    ap.add_argument("--kill", type=int, default=0,
                    help="SIGKILL on the kill-th mutation.log.commit visit")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--rows", type=int, default=64)
    ap.add_argument("--dim", type=int, default=8)
    ap.add_argument("--ckpt-every", type=int, default=2)
    args = ap.parse_args()

    import contextlib

    from raft_tpu.core import faults
    from raft_tpu.neighbors import mutation

    cm = contextlib.nullcontext()
    if args.kill > 0:
        cm = faults.FaultPlan(
            [faults.Fault(kind="kill_rank", site="mutation.log.commit",
                          count=args.kill)],
            seed=args.seed,
        ).install()

    mod, params = _params(args.kind)
    rng = np.random.default_rng(args.seed)
    data = rng.standard_normal((args.rows, args.dim)).astype(np.float32)
    # deterministic cold-start seed: every invocation builds the same
    # index, so only the committed mutation state distinguishes a resume
    index = mod.build(params, data)

    with cm:
        mut = mutation.Mutator(os.path.join(args.workdir, "mut"), index,
                               kind=args.kind, ckpt_every=args.ckpt_every,
                               slack=8)
        for op in scripted_ops(args.seed, args.dim, args.rows):
            if op[0] == "upsert":
                mut.upsert(op[1], op[2])
            elif op[0] == "delete":
                mut.delete(op[2])
            else:
                mut.rebalance()
        mut.commit()

    # final artifact: the committed checkpoint is the ground truth; also
    # bank the search results the parent compares across runs
    q = rng.standard_normal((8, args.dim)).astype(np.float32)
    vals, ids = mod.search(mod.SearchParams(n_probes=4), mut.index, q, 10)
    print(json.dumps({
        "applied": int(mut.applied),
        "live_rows": int(mutation.live_rows(mut.index)),
        "ids": np.asarray(ids).tolist(),
        "vals": [[float(v) for v in row] for row in np.asarray(vals)],
    }), flush=True)


if __name__ == "__main__":
    main()
