"""Comms self-tests (device-side collective correctness checks).

Lives in tests/ (not inside the library tree) so tier-1 collection and
raftlint layer-purity need no special case for test code under
raft_tpu/; `__graft_entry__.py` imports it as `tests.comms_selftests`
and tests/test_comms.py parametrizes over `ALL_TESTS`.

Reference parity: `raft::comms::test_collective_*` (comms/comms_test.hpp:1-171,
detail/test.hpp) exposed to Python via raft-dask's comms_utils.pyx:78-171
(`perform_test_comms_allreduce` etc.) and exercised in test_comms.py:45-317.
Each returns True iff the collective produced the mathematically expected
value on every rank.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.comms import Comms, op_t


def _all_ranks_ok(comms: Comms, per_rank_fn) -> bool:
    """Run per_rank_fn(ax_comms) -> bool scalar per rank; AND-reduce."""
    ac = comms.comms

    def fn():
        ok = per_rank_fn(ac)
        return ac.allreduce(jnp.asarray(ok).astype(jnp.float32), op_t.SUM)

    n = comms.get_size()
    out = jax.shard_map(
        fn, mesh=comms.mesh, in_specs=(), out_specs=P(), check_vma=False
    )()
    return bool(np.asarray(out) == n)


def perform_test_comms_allreduce(comms: Comms) -> bool:
    def body(ac):
        v = jnp.ones((), jnp.float32)
        return ac.allreduce(v) == ac.get_size()

    return _all_ranks_ok(comms, body)


def perform_test_comms_bcast(comms: Comms, root: int = 0) -> bool:
    def body(ac):
        rank = ac.get_rank()
        v = jnp.where(rank == root, 42.0, 0.0)
        return ac.bcast(v, root=root) == 42.0

    return _all_ranks_ok(comms, body)


def perform_test_comms_reduce(comms: Comms, root: int = 0) -> bool:
    def body(ac):
        r = ac.reduce(jnp.ones((), jnp.float32), root=root)
        rank = ac.get_rank()
        expected = jnp.where(rank == root, float(comms.get_size()), 0.0)
        return r == expected

    return _all_ranks_ok(comms, body)


def perform_test_comms_allgather(comms: Comms) -> bool:
    def body(ac):
        rank = ac.get_rank()
        v = rank.astype(jnp.float32)[None]
        g = ac.allgather(v)  # (n, 1)
        want = jnp.arange(ac.get_size(), dtype=jnp.float32)[:, None]
        return jnp.all(g == want)

    return _all_ranks_ok(comms, body)


def perform_test_comms_gather(comms: Comms, root: int = 0) -> bool:
    def body(ac):
        rank = ac.get_rank()
        g = ac.gather(rank.astype(jnp.float32)[None], root=root)
        want = jnp.arange(ac.get_size(), dtype=jnp.float32)[:, None]
        ok_root = jnp.all(g == want)
        return jnp.where(rank == root, ok_root, True)

    return _all_ranks_ok(comms, body)


def perform_test_comms_allreduce_prod(comms: Comms) -> bool:
    def body(ac):
        rank = ac.get_rank()
        # alternate 2 and 0.5 so the product stays bounded at any comm size
        v = jnp.where(rank % 2 == 0, 2.0, 0.5)
        n = ac.get_size()
        want = 2.0 ** (n - 2 * (n // 2))  # 1 for even n, 2 for odd
        return jnp.abs(ac.allreduce(v, op_t.PROD) - want) < 1e-5

    return _all_ranks_ok(comms, body)


def perform_test_comms_allgatherv(comms: Comms) -> bool:
    """Each rank contributes rank+1 valid rows out of a padded n-row buffer."""
    n = comms.get_size()
    counts = [r + 1 for r in range(n)]

    def body(ac):
        rank = ac.get_rank()
        rows = jnp.arange(n, dtype=jnp.float32)[:, None]
        x = jnp.where(rows < (rank + 1), rank + 1.0, 99.0)  # junk in the tail
        g = ac.allgatherv(x, counts)  # (n, n, 1)
        rr = jnp.arange(n, dtype=jnp.float32)[:, None, None]
        ii = jnp.arange(n, dtype=jnp.float32)[None, :, None]
        want = jnp.where(ii < (rr + 1), rr + 1.0, 0.0)
        return jnp.all(g == want)

    return _all_ranks_ok(comms, body)


def perform_test_comms_gatherv(comms: Comms, root: int = 0) -> bool:
    n = comms.get_size()
    counts = [r + 1 for r in range(n)]

    def body(ac):
        rank = ac.get_rank()
        rows = jnp.arange(n, dtype=jnp.float32)[:, None]
        x = jnp.where(rows < (rank + 1), rank + 1.0, 99.0)
        g = ac.gatherv(x, counts, root=root)
        rr = jnp.arange(n, dtype=jnp.float32)[:, None, None]
        ii = jnp.arange(n, dtype=jnp.float32)[None, :, None]
        want = jnp.where(ii < (rr + 1), rr + 1.0, 0.0)
        ok_root = jnp.all(g == want)
        return jnp.where(rank == root, ok_root, jnp.all(g == 0.0))

    return _all_ranks_ok(comms, body)


def perform_test_comms_reducescatter(comms: Comms) -> bool:
    def body(ac):
        n = ac.get_size()
        v = jnp.ones((n,), jnp.float32)
        r = ac.reducescatter(v)  # each rank gets its slice summed: n
        return jnp.all(r == n)

    return _all_ranks_ok(comms, body)


def perform_test_comms_reducescatter_ops(comms: Comms) -> bool:
    """MIN/MAX/PROD reducescatter (core/comms.hpp:192 takes any op_t)."""
    def body(ac):
        n = ac.get_size()
        rank = ac.get_rank().astype(jnp.float32)
        # chunk j of rank r's contribution: r + j (distinct per rank+chunk)
        v = rank + jnp.arange(n, dtype=jnp.float32)
        me = rank  # my chunk index == my rank
        ok_min = jnp.all(ac.reducescatter(v, op_t.MIN) == me)          # r=0
        ok_max = jnp.all(ac.reducescatter(v, op_t.MAX) == me + n - 1)  # r=n-1
        w = jnp.where(rank % 2 == 0, 2.0, 0.5)
        want = 2.0 ** (n - 2 * (n // 2))
        pr = ac.reducescatter(jnp.broadcast_to(w, (n,)), op_t.PROD)
        ok_prod = jnp.all(jnp.abs(pr - want) < 1e-5)
        return ok_min & ok_max & ok_prod

    return _all_ranks_ok(comms, body)


def perform_test_comm_split_reducescatter(comms: Comms) -> bool:
    """Grouped reducescatter, equal and unequal partitions (pad semantics:
    group-local rank p gets chunk p of its group's reduction)."""
    n = comms.get_size()
    if n < 4 or n % 2:
        return True

    def body(ac):
        rank = ac.get_rank().astype(jnp.float32)
        ok = jnp.asarray(True)
        # equal split: evens vs odds, SUM over n//2 members
        sub = ac.comm_split([r % 2 for r in range(n)])
        half = n // 2
        v = jnp.ones((half,), jnp.float32)
        ok &= jnp.all(sub.reducescatter(v, op_t.SUM) == half)
        # unequal split: rank 0 alone vs the rest; m = n-1 chunks
        sub2 = ac.comm_split([0] + [1] * (n - 1))
        m = n - 1
        v2 = jnp.broadcast_to(rank, (m,))
        mn = sub2.reducescatter(v2, op_t.MIN)  # group0: 0; group1: min=1
        want = jnp.where(rank == 0, 0.0, 1.0)
        ok &= jnp.all(mn == want)
        return ok

    return _all_ranks_ok(comms, body)


def perform_test_comms_send_recv(comms: Comms) -> bool:
    """Ring send/recv (test_comms.py send_recv analogue)."""
    def body(ac):
        rank = ac.get_rank()
        got = ac.shift(rank.astype(jnp.float32), offset=1)
        n = ac.get_size()
        want = (rank.astype(jnp.float32) - 1) % n
        return got == want

    return _all_ranks_ok(comms, body)


def perform_test_comms_device_multicast_sendrecv(comms: Comms) -> bool:
    n = comms.get_size()
    dests = [[(i + 1) % n, (i + 2) % n] for i in range(n)]

    def body(ac):
        rank = ac.get_rank().astype(jnp.float32)
        got = ac.device_multicast_sendrecv(rank, dests)
        want = ((rank - 1) % n) + ((rank - 2) % n)
        return got == want

    return _all_ranks_ok(comms, body)


def perform_test_comm_split(comms: Comms) -> bool:
    """comm_split into even/odd ranks (test_comms.py comm_split test)."""
    n = comms.get_size()
    if n % 2:
        return True
    colors = [r % 2 for r in range(n)]

    def body(ac):
        sub = ac.comm_split(colors)
        v = jnp.ones((), jnp.float32)
        ok_sum = sub.allreduce(v) == sub.get_size()
        # ring shift stays within the sub-comm: even ranks ring among
        # evens, odds among odds → every rank receives (rank - 2) mod n
        rank = ac.get_rank().astype(jnp.float32)  # global rank (parity split)
        got = sub.shift(rank, offset=1)
        want = (rank - 2) % n
        return ok_sum & (got == want)

    return _all_ranks_ok(comms, body)


def perform_test_comm_split_unequal(comms: Comms) -> bool:
    """Arbitrary color partition (std_comms.hpp:62-218 allows any colors):
    rank 0 alone in one sub-comm, the rest in another."""
    n = comms.get_size()
    if n < 3:
        return True
    colors = [0] + [1] * (n - 1)

    def body(ac):
        sub = ac.comm_split(colors)
        v = jnp.ones((), jnp.float32)
        ok_sum = sub.allreduce(v) == sub.get_size()
        # bcast from group-local root: group 0 roots at global rank 0,
        # group 1 at global rank 1 (its local rank 0)
        rank = ac.get_rank()
        payload = jnp.where(rank < 2, rank + 7.0, 0.0)
        got = sub.bcast(payload, root=0)
        want = jnp.where(rank == 0, 7.0, 8.0)
        # grouped allgather pads slots up to the largest group with zeros
        g = sub.allgather(jnp.ones((1,), jnp.float32))
        valid = jnp.arange(n - 1)[:, None] < sub.get_size()
        ok_gather = jnp.all(jnp.where(valid, g == 1.0, g == 0.0))
        return ok_sum & (got == want) & ok_gather

    return _all_ranks_ok(comms, body)


def perform_test_comms_barrier(comms: Comms) -> bool:
    def body(ac):
        return ac.barrier() == ac.get_size()

    return _all_ranks_ok(comms, body)


ALL_TESTS = [
    perform_test_comms_allreduce,
    perform_test_comms_allreduce_prod,
    perform_test_comms_bcast,
    perform_test_comms_reduce,
    perform_test_comms_allgather,
    perform_test_comms_allgatherv,
    perform_test_comms_gather,
    perform_test_comms_gatherv,
    perform_test_comms_reducescatter,
    perform_test_comms_reducescatter_ops,
    perform_test_comm_split_reducescatter,
    perform_test_comms_send_recv,
    perform_test_comms_device_multicast_sendrecv,
    perform_test_comm_split,
    perform_test_comm_split_unequal,
    perform_test_comms_barrier,
]
