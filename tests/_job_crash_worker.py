"""Child worker for the kill-and-resume drills (tests/test_jobs.py).

Runs one resumable streaming operation — a streaming index build
(`jobs.resumable_extend_from_file`) or chunked dataset synthesis
(`jobs.resumable_write_npy`) — optionally under a seeded FaultPlan whose
kill_rank fault at ``job.stage.crash`` SIGKILLs THIS process on the
count-th batch-boundary checkpoint (`faults.crash_point`). The parent
re-runs the same command line; the scratch-dir cursor + checkpoint must
carry the resume to a bit-identical result. A separate process is the
point: SIGKILL leaves no chance for in-process cleanup to cheat.

Not a test module (underscore prefix keeps pytest away).
"""

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _params(kind: str):
    if kind == "ivf_flat":
        from raft_tpu.neighbors import ivf_flat as mod

        return mod, mod.IndexParams(n_lists=4, kmeans_n_iters=2,
                                    add_data_on_build=False)
    if kind == "ivf_pq":
        from raft_tpu.neighbors import ivf_pq as mod

        return mod, mod.IndexParams(n_lists=4, pq_dim=4, pq_bits=4,
                                    kmeans_n_iters=2,
                                    kmeans_trainset_fraction=1.0,
                                    add_data_on_build=False)
    if kind == "ivf_rabitq":
        from raft_tpu.neighbors import ivf_rabitq as mod

        return mod, mod.IndexParams(n_lists=4, kmeans_n_iters=2,
                                    add_data_on_build=False,
                                    store_dataset=False)
    raise SystemExit(f"unknown kind {kind!r}")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("mode", choices=("stream", "datagen"))
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--data")
    ap.add_argument("--kind", default="ivf_flat")
    ap.add_argument("--kill", type=int, default=0,
                    help="SIGKILL on the kill-th checkpoint commit")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--rows", type=int, default=50)
    ap.add_argument("--dim", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--batch", type=int, default=16)
    args = ap.parse_args()

    import contextlib

    from raft_tpu import jobs
    from raft_tpu.core import faults

    scratch = os.path.join(args.workdir, "scratch")
    os.makedirs(scratch, exist_ok=True)
    cm = contextlib.nullcontext()
    if args.kill > 0:
        cm = faults.FaultPlan(
            [faults.Fault(kind="kill_rank", site="job.stage.crash",
                          count=args.kill)],
            seed=args.seed,
        ).install()

    if args.mode == "stream":
        mod, params = _params(args.kind)
        data = np.load(args.data)
        # deterministic cold-start seed: every invocation trains the
        # same index, so only the checkpoint distinguishes a resume
        index = mod.build(params, data[: max(8, len(data) // 2)])
        with cm:
            index, stats = jobs.resumable_extend_from_file(
                args.kind, index, args.data, args.batch,
                scratch=scratch, checkpoint_every=1)
        mod.save(os.path.join(args.workdir, "out.ckpt"), index)
        print(json.dumps({"stats": stats}), flush=True)
        return

    # datagen: chunked .npy synthesis behind the progress marker
    def make_chunk(lo: int, hi: int) -> np.ndarray:
        rng = np.random.default_rng((args.seed, lo))  # per-chunk seeding
        return rng.random((hi - lo, args.dim), dtype=np.float32)

    with cm:
        stats = jobs.resumable_write_npy(
            os.path.join(args.workdir, "data.npy"), args.rows, args.dim,
            args.chunk, make_chunk, scratch=scratch)
    print(json.dumps({"stats": stats}), flush=True)


if __name__ == "__main__":
    main()
