"""Worker process for the multi-process distributed tests (the raft-dask
LocalCUDACluster analogue, test_comms.py:45): N controller processes x 4
virtual CPU devices each form one global mesh; collectives cross the
process boundary over the distributed runtime.

Run: python tests/_mp_worker.py <process_id> <num_processes> <port>
Prints one PASS line per check; exits non-zero on any failure.
"""

import os
import sys

PID = int(sys.argv[1])
NPROC = int(sys.argv[2])
PORT = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

from raft_tpu.comms import Comms, bootstrap_multihost
from raft_tpu.comms.comms import op_t

import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def check(name, ok):
    if not ok:
        print(f"FAIL {name}", flush=True)
        sys.exit(1)
    print(f"PASS {name}", flush=True)


def main():
    first = bootstrap_multihost(f"127.0.0.1:{PORT}", num_processes=NPROC, process_id=PID)
    check("bootstrap", first and jax.process_count() == NPROC)
    n_dev = len(jax.devices())
    check("global_devices", n_dev == 4 * NPROC and len(jax.local_devices()) == 4)

    mesh = Mesh(np.array(jax.devices()), ("data",))
    comms = Comms(mesh=mesh)
    check("spans_processes", comms.spans_processes())
    R = comms.get_size()

    # shard_from_local: each process contributes its own rows
    local = np.full((8, 3), PID, np.float32)
    g = comms.shard_from_local(local)
    check("shard_from_local_shape", g.shape == (8 * NPROC, 3))

    # collectives across the process boundary. Fetching a process-spanning
    # array needs the multihost gather (device_get only sees local shards).
    from jax.experimental import multihost_utils

    def fetch(a):
        return np.asarray(multihost_utils.process_allgather(a, tiled=True))

    def allreduce_fn(c, xs):
        return c.allreduce(xs, op=op_t.SUM)

    out = comms.run(allreduce_fn, g)
    # rank r's shard is constant PID-of-r; elementwise SUM over the 8 ranks
    # (4 per process) = 4 * (0 + 1) everywhere
    ranks_per_proc = R // NPROC
    want = ranks_per_proc * sum(range(NPROC))
    check("allreduce_sum", np.allclose(fetch(out), want))

    def allgather_fn(c, xs):
        return c.allgather(xs)

    # P() out_specs: the gathered result is identical on every rank
    ag = comms.run(allgather_fn, g, out_specs=P())
    got_ag = fetch(ag)
    check(
        "allgather_content",
        got_ag.size == 8 * NPROC * 3
        and np.allclose(np.sort(got_ag.ravel()), np.sort(fetch(g).ravel())),
    )

    def shift_fn(c, xs):
        return c.shift(xs, 1)

    pp = comms.run(shift_fn, g)
    got_pp, got_g = fetch(pp), fetch(g)
    check(
        "ppermute_shift",
        got_pp.shape == got_g.shape and not np.array_equal(got_pp, got_g),
    )

    # replicate: same value on every controller; the local shard of a
    # replicated array is the full value
    rep = comms.replicate(np.arange(6, dtype=np.float32))
    local_rep = np.asarray(rep.addressable_shards[0].data)
    check("replicate", np.allclose(local_rep, np.arange(6)))

    # a real pipeline: distributed row-block top-k merge (the knn merge
    # topology) — local scores per rank, allgather + exact final merge
    def topk_merge(c, scores):
        from raft_tpu.comms.mnmg import _merge_local_topk
        import jax.numpy as jnp

        v = jnp.sort(scores, axis=-1)[:, :4]
        i = jnp.argsort(scores, axis=-1)[:, :4].astype(jnp.int32)
        mv, mi = _merge_local_topk(c, v, i, 4, True)
        return mv

    rng = np.random.default_rng(7)
    local_scores = rng.random((ranks_per_proc * 4, 32), dtype=np.float32)
    gs = comms.shard_from_local(local_scores, axis=0)
    try:
        mv = comms.run(topk_merge, gs, out_specs=P("data"))
        check("topk_merge", fetch(mv).ndim >= 2)
    except Exception as e:  # surface which pipeline broke, keep rc non-zero
        print(f"FAIL topk_merge: {type(e).__name__}: {e}", flush=True)
        sys.exit(1)

    # distributed k-means from per-process partitions: the full dataset is
    # generated identically on both controllers; each contributes its half
    from raft_tpu.comms import mnmg
    from raft_tpu.cluster import kmeans as local_kmeans

    rngk = np.random.default_rng(5)
    cents = rngk.uniform(-4, 4, (4, 8)).astype(np.float32)
    full = (
        cents[rngk.integers(0, 4, 128)]
        + 0.3 * rngk.standard_normal((128, 8)).astype(np.float32)
    )
    per_proc = 128 // NPROC
    local_part = full[PID * per_proc : (PID + 1) * per_proc]
    centers, inertia, _ = mnmg.kmeans_fit_local(
        comms, local_part, 4, max_iter=25, seed=0, n_init=3
    )
    labels = mnmg.kmeans_predict_local(comms, local_part, centers)
    check("kmeans_local_shapes", labels.shape == (per_proc,) and np.asarray(
        centers.addressable_shards[0].data).shape == (4, 8))
    _, inertia_single, _ = local_kmeans.fit(full, n_clusters=4, seed=0)
    check(
        f"kmeans_local_quality (mp={inertia:.3f} single={float(inertia_single):.3f})",
        inertia <= float(inertia_single) * 1.3 + 1e-6,
    )
    # labels must be consistent with the returned centers
    host_centers = np.asarray(centers.addressable_shards[0].data)
    want_labels = np.argmin(
        ((local_part[:, None, :] - host_centers[None]) ** 2).sum(-1), axis=1
    )
    check("kmeans_local_labels", np.array_equal(np.asarray(labels), want_labels))

    # distributed IVF-Flat build from per-process partitions, searched
    # across the process boundary; recall vs a locally-computed oracle
    from raft_tpu.neighbors import ivf_flat, brute_force

    nrows = 4096
    fdata = (
        cents[rngk.integers(0, 4, nrows)][:, :8].repeat(2, axis=1)
        + 0.3 * rngk.standard_normal((nrows, 16)).astype(np.float32)
    ).astype(np.float32)
    per_proc_f = nrows // NPROC
    flocal = fdata[PID * per_proc_f : (PID + 1) * per_proc_f]
    params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=8)
    di = mnmg.ivf_flat_build_local(comms, params, flocal)
    dv, dids = mnmg.ivf_flat_search(di, fdata[:64], 10, n_probes=8)
    # slot gids ARE caller row ids (process-order concatenation of the
    # partitions == fdata's row order here) — directly comparable
    got_ids = np.asarray(dids.addressable_shards[0].data)
    _, truth_f = brute_force.knn(fdata, fdata[:64], 10, metric="sqeuclidean")
    tf = np.asarray(truth_f)
    rec_f = np.mean(
        [len(set(got_ids[i]) & set(tf[i])) / 10 for i in range(64)]
    )
    check(f"ivf_flat_build_local_recall ({rec_f:.3f})", rec_f > 0.85)
    # extend must reject mirror-less multi-controller indexes clearly
    try:
        mnmg.ivf_flat_extend(di, fdata[:8])
        check("ivf_flat_local_extend_guard", False)
    except ValueError:
        check("ivf_flat_local_extend_guard", True)

    # collective extend_local: each controller appends 32 of its own rows
    # (uneven: proc 1 appends 16); new ids continue the global id space
    extra = (cents[rngk.integers(0, 4, 48)][:, :8].repeat(2, axis=1)
             + 0.3 * rngk.standard_normal((48, 16))).astype(np.float32)
    my_extra = extra[:32] if PID == 0 else extra[32:]
    di2 = mnmg.ivf_flat_extend_local(di, my_extra)
    check("ivf_flat_extend_local_n", di2.n == nrows + 48)
    _, xi = mnmg.ivf_flat_search(di2, extra[:8], 1, n_probes=16)
    got_x = np.asarray(xi.addressable_shards[0].data).ravel()
    # each appended row is its own nearest neighbor at full probing
    check("ivf_flat_extend_local_ids",
          np.array_equal(got_x, np.arange(nrows, nrows + 8)))

    # file-backed collective ingestion with UNEVEN files: proc 0 streams
    # 30 rows (2 batches @ 16), proc 1 only 10 (1 batch) — the batch-
    # count consensus keeps proc 1 participating with an empty tail call
    from raft_tpu import io as rt_io
    import tempfile

    more = (cents[rngk.integers(0, 4, 40)][:, :8].repeat(2, axis=1)
            + 0.3 * rngk.standard_normal((40, 16))).astype(np.float32)
    my_more = more[:30] if PID == 0 else more[30:]
    fpath = os.path.join(tempfile.gettempdir(), f"_mp_stream_{PID}.npy")
    np.save(fpath, my_more)
    di3 = rt_io.extend_from_file_local(
        mnmg.ivf_flat_extend_local, di2, fpath, batch_rows=16)
    check("extend_from_file_local_n", di3.n == nrows + 48 + 40)
    os.unlink(fpath)

    # distributed exact kNN from per-process partitions: ids are caller
    # row ids, so they compare directly against the local oracle
    kd, kids = mnmg.knn_local(comms, flocal, fdata[:32], 5)
    got_k = np.asarray(kids.addressable_shards[0].data)
    _, tk = brute_force.knn(fdata, fdata[:32], 5, metric="sqeuclidean")
    tk = np.asarray(tk)
    rec_k = np.mean([len(set(got_k[i]) & set(tk[i])) / 5 for i in range(32)])
    check(f"knn_local_exact ({rec_k:.3f})", rec_k == 1.0)

    # distributed IVF-PQ build from per-process partitions
    from raft_tpu.neighbors import ivf_pq

    pparams = ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=6)
    dpq = mnmg.ivf_pq_build_local(comms, pparams, flocal)
    _, pids = mnmg.ivf_pq_search(dpq, fdata[:64], 10, n_probes=8)
    got_p = np.asarray(pids.addressable_shards[0].data)
    rec_p = np.mean([len(set(got_p[i]) & set(tf[i])) / 10 for i in range(64)])
    check(f"ivf_pq_build_local_recall ({rec_p:.3f})", rec_p > 0.5)
    # the high-recall pipeline: per-rank exact refine of each rank's own
    # candidates, merged — every controller passes its partition
    _, rids = mnmg.ivf_pq_search(
        dpq, fdata[:64], 10, n_probes=8, refine_dataset=flocal
    )
    got_r = np.asarray(rids.addressable_shards[0].data)
    rec_r = np.mean([len(set(got_r[i]) & set(tf[i])) / 10 for i in range(64)])
    check(f"ivf_pq_local_refined_recall ({rec_r:.3f})", rec_r >= rec_p and rec_r > 0.9)
    try:
        mnmg.ivf_pq_extend(dpq, fdata[:8])
        check("ivf_pq_local_extend_guard", False)
    except ValueError:
        check("ivf_pq_local_extend_guard", True)
    dpq2 = mnmg.ivf_pq_extend_local(dpq, my_extra)
    check("ivf_pq_extend_local_n", dpq2.n == nrows + 48 and dpq2.extended)
    _, pxi = mnmg.ivf_pq_search(dpq2, extra[:8], 1, n_probes=16)
    got_px = np.asarray(pxi.addressable_shards[0].data).ravel()
    check("ivf_pq_extend_local_ids",
          np.all((got_px >= 0) & (got_px < nrows + 48)))

    # fused-Pallas engines across the process boundary (interpret mode):
    # per-rank kernel + cross-process merge, overlap vs the exact engines
    _, zfi = mnmg.ivf_flat_search(di, fdata[:16], 5, n_probes=16,
                                  engine="pallas")
    _, zli = mnmg.ivf_flat_search(di, fdata[:16], 5, n_probes=16,
                                  engine="list")
    zf_, zl_ = fetch(zfi)[:16], fetch(zli)[:16]
    hits_f = sum(len(set(a.tolist()) & set(b.tolist()))
                 for a, b in zip(zf_, zl_))
    check(f"mp_flat_pallas_engine ({hits_f / zl_.size:.2f})",
          hits_f / zl_.size >= 0.85)
    _, ids_pallas_trim = mnmg.ivf_pq_search(
        dpq, fdata[:16], 5, n_probes=16,
        engine="recon8_list", trim_engine="pallas")
    _, ids_approx_trim = mnmg.ivf_pq_search(dpq, fdata[:16], 5, n_probes=16,
                                            engine="recon8_list")
    pal_, apx_ = fetch(ids_pallas_trim)[:16], fetch(ids_approx_trim)[:16]
    hits_t = sum(len(set(a.tolist()) & set(b.tolist()))
                 for a, b in zip(pal_, apx_))
    check(f"mp_pq_pallas_trim ({hits_t / apx_.size:.2f})",
          hits_t / apx_.size >= 0.8)
    try:
        mnmg.ivf_pq_save("/tmp/should_not_exist.rtpq", dpq)
        check("ivf_pq_local_save_guard", False)
    except ValueError:
        check("ivf_pq_local_save_guard", True)

    # single-chip -> distributed serving bridge on the spanning mesh:
    # both controllers build the identical single-chip index (same data,
    # same seed), then distribute_index block-splits its lists
    sidx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=4), fdata
    )
    dsrv = mnmg.distribute_index(comms, sidx)
    _, bids = mnmg.ivf_pq_search(dsrv, fdata[:32], 5, n_probes=8)
    got_b = np.asarray(bids.addressable_shards[0].data)
    _, tb = brute_force.knn(fdata, fdata[:32], 5, metric="sqeuclidean")
    tb = np.asarray(tb)
    rec_b = np.mean([len(set(got_b[i]) & set(tb[i])) / 5 for i in range(32)])
    check(f"distribute_index_bridge ({rec_b:.3f})", rec_b > 0.6)

    print("WORKER_OK", flush=True)


if __name__ == "__main__":
    main()
