"""k-means tests (mirrors cpp/test/cluster/kmeans.cu strategy: quality
metrics on blobs rather than exact-match)."""

import numpy as np
import pytest
from sklearn.metrics import adjusted_rand_score

from raft_tpu.cluster import kmeans, KMeansParams
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.random import make_blobs


@pytest.fixture(scope="module")
def blobs():
    data, labels = make_blobs(3000, 16, n_clusters=8, cluster_std=0.4, seed=11)
    return np.asarray(data), np.asarray(labels)


def test_kmeans_fit_quality(blobs):
    data, true_labels = blobs
    centers, inertia, n_iter = kmeans.fit(data, KMeansParams(n_clusters=8, seed=0))
    assert centers.shape == (8, 16)
    assert n_iter >= 1
    pred = np.asarray(kmeans.predict(data, centers))
    assert adjusted_rand_score(true_labels, pred) > 0.95


def test_kmeans_kwargs_api(blobs):
    data, _ = blobs
    centers, inertia, _ = kmeans.fit(data, n_clusters=8, max_iter=50, seed=1)
    assert centers.shape == (8, 16)
    assert np.isfinite(inertia)


def test_kmeans_random_init(blobs):
    data, true_labels = blobs
    centers, _, _ = kmeans.fit(data, KMeansParams(n_clusters=8, init="random", seed=2, n_init=5))
    pred = np.asarray(kmeans.predict(data, centers))
    # random init is statistically weaker than k-means++; modest floor
    assert adjusted_rand_score(true_labels, pred) > 0.75


def test_kmeans_inertia_decreases_vs_random_centers(blobs):
    data, _ = blobs
    rng = np.random.default_rng(0)
    random_centers = rng.random((8, 16), dtype=np.float32) * 10 - 5
    cost_random = kmeans.cluster_cost(data, random_centers)
    centers, inertia, _ = kmeans.fit(data, n_clusters=8)
    assert inertia < cost_random


def test_kmeans_transform_and_cost(blobs):
    data, _ = blobs
    centers, inertia, _ = kmeans.fit(data, n_clusters=8)
    t = np.asarray(kmeans.transform(data[:100], centers))
    assert t.shape == (100, 8)
    cost = kmeans.cluster_cost(data, centers)
    np.testing.assert_allclose(cost, inertia, rtol=1e-3)


def test_compute_new_centroids(blobs):
    data, _ = blobs
    centers, _, _ = kmeans.fit(data, n_clusters=8, max_iter=5)
    updated = np.asarray(kmeans.compute_new_centroids(data, centers))
    assert updated.shape == centers.shape
    # a fixed point of Lloyd: converged centers shouldn't move much
    centers_c, _, _ = kmeans.fit(data, n_clusters=8, max_iter=300)
    moved = np.asarray(kmeans.compute_new_centroids(data, centers_c))
    np.testing.assert_allclose(moved, np.asarray(centers_c), atol=1e-2)


def test_kmeans_init_array(blobs):
    data, _ = blobs
    init = data[:8].copy()
    centers, inertia, _ = kmeans.fit(data, KMeansParams(n_clusters=8, init="array"), centroids=init)
    assert np.isfinite(inertia)


def test_kmeans_weighted(blobs):
    data, _ = blobs
    w = np.ones(len(data), np.float32)
    c1, i1, _ = kmeans.fit(data, KMeansParams(n_clusters=8, seed=3), sample_weights=w)
    assert np.isfinite(i1)


def test_find_k():
    data, _ = make_blobs(1000, 8, n_clusters=4, cluster_std=0.3, seed=5)
    best_k, inertia, _ = kmeans.find_k(np.asarray(data), kmax=10, kmin=1)
    assert 3 <= best_k <= 6


# --- balanced k-means ------------------------------------------------------


def test_balanced_fit_quality(blobs):
    data, true_labels = blobs
    centers = kmeans_balanced.fit(data, 8, n_iters=25, seed=0)
    assert centers.shape == (8, 16)
    pred = np.asarray(kmeans_balanced.predict(data, centers))
    assert adjusted_rand_score(true_labels, pred) > 0.9


def test_balanced_balance_property():
    # heavily skewed data: balanced trainer should not leave clusters empty
    data, _ = make_blobs(4000, 8, n_clusters=2, cluster_std=0.2, seed=7)
    centers = kmeans_balanced.fit(np.asarray(data), 16, n_iters=30, seed=0)
    labels = np.asarray(kmeans_balanced.predict(np.asarray(data), centers))
    sizes = np.bincount(labels, minlength=16)
    assert (sizes > 0).sum() >= 14  # nearly all clusters populated


def test_balanced_int8_data():
    rng = np.random.default_rng(0)
    data = rng.integers(-100, 100, (500, 16), dtype=np.int8)
    centers = kmeans_balanced.fit(data, 4, n_iters=10)
    assert centers.shape == (4, 16)
    labels = np.asarray(kmeans_balanced.predict(data, centers))
    assert labels.shape == (500,)


def test_balanced_inner_product_metric(blobs):
    data, _ = blobs
    centers = kmeans_balanced.fit(data, 8, n_iters=15, metric="inner_product")
    norms = np.linalg.norm(np.asarray(centers), axis=1)
    np.testing.assert_allclose(norms, 1.0, atol=1e-4)  # normalized centers
    labels = np.asarray(kmeans_balanced.predict(data, centers, metric="inner_product"))
    assert labels.min() >= 0 and labels.max() < 8


def test_balanced_hierarchical():
    data, _ = make_blobs(5000, 12, n_clusters=10, cluster_std=0.5, seed=9)
    centers = kmeans_balanced.fit_hierarchical(np.asarray(data), 100, n_iters=10)
    assert centers.shape == (100, 12)
    labels = np.asarray(kmeans_balanced.predict(np.asarray(data), centers))
    assert len(np.unique(labels)) > 50


def test_balanced_hierarchical_vmapped():
    """Mesocluster hierarchy (detail/kmeans_balanced.cuh:756+): one vmapped
    program trains all partitions; centers are balanced and near flat-trainer
    quality."""
    import jax.numpy as jnp
    from raft_tpu.cluster import kmeans_balanced
    from raft_tpu.cluster.kmeans_common import cluster_cost_impl

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(20000, 16)).astype(np.float32))
    c = kmeans_balanced.fit_hierarchical(x, 128, n_iters=6)
    assert c.shape == (128, 16)
    assert np.isfinite(np.asarray(c)).all()
    lbl = np.asarray(kmeans_balanced.predict(x, c))
    sizes = np.bincount(lbl, minlength=128)
    assert (sizes == 0).sum() == 0, "no empty clusters"
    assert sizes.max() < 8 * sizes.mean(), "balanced partitioning"
    flat = kmeans_balanced.fit(x, 128, n_iters=6)
    ratio = float(cluster_cost_impl(x, c)) / float(cluster_cost_impl(x, flat))
    assert ratio < 1.15, f"hierarchical quality off: {ratio}"
    # prime n_clusters works through ceil-split + surplus drop
    c2 = kmeans_balanced.fit_hierarchical(x[:3000], 67, n_iters=3)
    assert c2.shape == (67, 16)
