"""Native C++ runtime tests: build, pack_lists parity, codec roundtrip."""

import numpy as np
import pytest

from raft_tpu import native


def test_native_builds():
    assert native.available(), "native lib should build in this environment (g++ present)"


def test_pack_lists_matches_python(rng):
    labels = rng.integers(0, 7, 500).astype(np.int64)
    out = native.pack_lists(labels, 7, group=32)
    assert out is not None
    row_ids, sizes = out
    np.testing.assert_array_equal(sizes, np.bincount(labels, minlength=7))
    assert row_ids.shape[1] % 32 == 0
    # every row appears exactly once, in its own list
    flat = row_ids[row_ids >= 0]
    assert sorted(flat.tolist()) == list(range(500))
    for l in range(7):
        members = row_ids[l][row_ids[l] >= 0]
        assert np.all(labels[members] == l)
        # stable order
        assert np.all(np.diff(members) > 0)


def test_native_codec_roundtrip(rng, tmp_path):
    from raft_tpu.core.serialize import serialize_arrays, deserialize_arrays

    arrays = {
        "x": rng.random((13, 7), dtype=np.float32),
        "y": rng.integers(0, 255, (100,)).astype(np.uint8),
    }
    p = str(tmp_path / "c.bin")
    serialize_arrays(p, arrays, {"k": 1})  # native write path
    got, meta = deserialize_arrays(p, to_device=False)  # native read path
    assert meta == {"k": 1}
    for k in arrays:
        np.testing.assert_array_equal(got[k], arrays[k])


def test_native_python_cross_compat(rng, tmp_path):
    """Files written by the native codec parse via the pure-Python reader
    and vice versa (same format byte-for-byte semantics)."""
    import io

    from raft_tpu.core.serialize import serialize_arrays, deserialize_arrays

    arrays = {"a": rng.random((4, 4), dtype=np.float32)}
    # python write (stream) -> native-capable read (path)
    buf = io.BytesIO()
    serialize_arrays(buf, arrays, {"v": 2})
    p = tmp_path / "py.bin"
    p.write_bytes(buf.getvalue())
    got, meta = deserialize_arrays(str(p), to_device=False)
    np.testing.assert_array_equal(got["a"], arrays["a"])
    # native write (path) -> python read (stream)
    p2 = str(tmp_path / "nat.bin")
    serialize_arrays(p2, arrays, {"v": 3})
    with open(p2, "rb") as fh:
        got2, meta2 = deserialize_arrays(io.BytesIO(fh.read()), to_device=False)
    assert meta2 == {"v": 3}
    np.testing.assert_array_equal(got2["a"], arrays["a"])


def test_native_coo_and_labels():
    """v2 native ops: CSR indptr, stable row sort permutation, label
    densification (host-scale counterparts of sparse/convert + label/)."""
    from raft_tpu import native

    if not native.available():
        pytest.skip("native lib unavailable")
    rows = np.array([2, 0, 1, 0, 2, 2])
    np.testing.assert_array_equal(native.coo_rows_to_indptr(rows, 3), [0, 2, 3, 6])
    perm = native.coo_sort_perm(rows, 3)
    np.testing.assert_array_equal(rows[perm], np.sort(rows))
    # stability: equal rows keep original relative order
    np.testing.assert_array_equal(perm[:2], [1, 3])
    assert native.coo_rows_to_indptr(np.array([5]), 3) is None  # out of range
    dense, uniq = native.make_monotonic(np.array([10, -5, 10, 7]))
    np.testing.assert_array_equal(uniq, [-5, 7, 10])
    np.testing.assert_array_equal(dense, [2, 0, 2, 1])


def test_native_mst_linkage_matches_python(rng):
    """Native union-find dendrogram == the numpy merge loop, and the flat
    cut matches scipy's fcluster labeling (modulo label permutation)."""
    import importlib

    from raft_tpu import native

    sl = importlib.import_module("raft_tpu.cluster.single_linkage")

    if not native.available():
        import pytest

        pytest.skip("native lib unavailable")
    n = 500
    x = rng.random((n, 8), dtype=np.float32)
    from scipy.spatial.distance import pdist, squareform
    from scipy.sparse.csgraph import minimum_spanning_tree

    mst = minimum_spanning_tree(squareform(pdist(x))).tocoo()
    src = mst.row.astype(np.int32)
    dst = mst.col.astype(np.int32)
    w = mst.data.astype(np.float32)

    order = np.argsort(w, kind="stable")
    ch_n, de_n, sz_n = native.mst_linkage(src[order], dst[order], w[order], n)
    # force the numpy fallback by bypassing the native shortcut
    import unittest.mock as mock

    with mock.patch.object(native, "mst_linkage", lambda *a: None):
        ch_p, de_p, sz_p = sl._mst_linkage(n, src, dst, w)
    np.testing.assert_array_equal(ch_n, ch_p)
    np.testing.assert_allclose(de_n, de_p, rtol=1e-6)
    np.testing.assert_array_equal(sz_n, sz_p)

    lab_n = native.cut_tree(ch_n, n, 4)
    with mock.patch.object(native, "cut_tree", lambda *a: None):
        lab_p = sl._cut_tree(n, ch_p, 4)
    np.testing.assert_array_equal(lab_n, lab_p)
    from sklearn.metrics import adjusted_rand_score
    import scipy.cluster.hierarchy as sch

    Z = np.column_stack([ch_n, de_n, sz_n]).astype(np.float64)
    want = sch.fcluster(Z, 4, criterion="maxclust")
    assert adjusted_rand_score(want, lab_n) == 1.0
