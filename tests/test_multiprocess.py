"""True multi-process distributed tests: N controller processes form one
global mesh and run comms + merge topologies across the process boundary.

Reference parity: raft-dask's test_comms.py:45-317 validates the comms
layer on a LocalCUDACluster — multiple worker PROCESSES on one box
standing in for multi-node. The single-process 8-device mesh tests
(test_comms.py here) cover collective semantics; this suite covers what
they cannot: jax.distributed bootstrap, cross-process Gloo collectives,
process-local data placement (shard_from_local), and fetching rules for
process-spanning arrays.
"""

import os
import subprocess
import sys

import pytest


def _spawn_workers(nproc: int, port: int, timeout: float = 300.0):
    worker = os.path.join(os.path.dirname(__file__), "_mp_worker.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), str(nproc), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        # collect what the workers DID say — a peer crash leaves the
        # others blocked in the distributed barrier, and the crashing
        # worker's traceback is the diagnostic that matters
        diags = []
        for p in procs:
            p.kill()
            out, err = p.communicate()
            diags.append(f"rc={p.returncode}\nstdout:\n{out}\nstderr:\n{err[-3000:]}")
        raise AssertionError(
            "workers timed out\n" + "\n---\n".join(diags)
        ) from None
    return outs


def test_two_process_mesh(unused_tcp_port):
    outs = _spawn_workers(2, unused_tcp_port)
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:\n{out}\nstderr:\n{err[-3000:]}"
        assert "WORKER_OK" in out, out
        assert "FAIL" not in out, out


@pytest.fixture
def unused_tcp_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
