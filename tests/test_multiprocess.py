"""True multi-process distributed tests: N controller processes form one
global mesh and run comms + merge topologies across the process boundary.

Reference parity: raft-dask's test_comms.py:45-317 validates the comms
layer on a LocalCUDACluster — multiple worker PROCESSES on one box
standing in for multi-node. The single-process 8-device mesh tests
(test_comms.py here) cover collective semantics; this suite covers what
they cannot: jax.distributed bootstrap, cross-process Gloo collectives,
process-local data placement (shard_from_local), and fetching rules for
process-spanning arrays.
"""

import os
import subprocess
import sys

import pytest


def _spawn_workers(nproc: int, port: int, timeout: float = 300.0,
                   script: str = "_mp_worker.py", extra_args: tuple = ()):
    worker = os.path.join(os.path.dirname(__file__), script)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), str(nproc), str(port), *extra_args],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        # collect what the workers DID say — a peer crash leaves the
        # others blocked in the distributed barrier, and the crashing
        # worker's traceback is the diagnostic that matters
        diags = []
        for p in procs:
            p.kill()
            out, err = p.communicate()
            diags.append(f"rc={p.returncode}\nstdout:\n{out}\nstderr:\n{err[-3000:]}")
        raise AssertionError(
            "workers timed out\n" + "\n---\n".join(diags)
        ) from None
    return outs


def test_two_process_mesh(unused_tcp_port):
    outs = _spawn_workers(2, unused_tcp_port)
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:\n{out}\nstderr:\n{err[-3000:]}"
        assert "WORKER_OK" in out, out
        assert "FAIL" not in out, out


def test_uneven_and_empty_partitions(unused_tcp_port):
    """Adversarial layouts: heavy padding (pads nearer the query than any
    real row) and a controller with zero rows."""
    outs = _spawn_workers(2, unused_tcp_port, script="_mp_uneven_worker.py")
    for rc, out, err in outs:
        assert rc == 0 and "WORKER_OK" in out, f"{out}\n{err[-3000:]}"
        assert "FAIL" not in out, out


def _build_single_controller_ckpt(ckpt: str, npz: str, seed: int) -> None:
    """Run a single-controller 8-device session in a subprocess: build a
    distributed IVF-Flat index, save it, and write the exact-kNN oracle
    (queries + truth) the loading workers verify against."""
    build = f"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import sys; sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
from raft_tpu.comms import Comms, mnmg
from raft_tpu.neighbors import ivf_flat, brute_force
rng = np.random.default_rng({seed})
cents = rng.uniform(-4, 4, (8, 16)).astype(np.float32)
data = (cents[rng.integers(0, 8, 2048)] + 0.2 * rng.standard_normal((2048, 16))).astype(np.float32)
c = Comms()
di = mnmg.ivf_flat_build(c, ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=6), data)
mnmg.ivf_flat_save({ckpt!r}, di)
q = data[:32]
_, t = brute_force.knn(data, q, 5, metric="sqeuclidean")
np.savez({npz!r}, queries=q, truth=np.asarray(t))
print("SAVED")
"""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, "-c", build], capture_output=True, text=True,
        timeout=300, env=env,
    )
    assert r.returncode == 0 and "SAVED" in r.stdout, r.stderr[-3000:]


def test_checkpoint_load_across_processes(tmp_path, unused_tcp_port):
    """A single-controller session saves a distributed IVF-Flat index;
    two controller processes load it onto a spanning mesh (shared-fs
    contract) and search it at full recall."""
    ckpt = str(tmp_path / "index.rtivf")
    npz = str(tmp_path / "oracle.npz")
    _build_single_controller_ckpt(ckpt, npz, seed=11)

    outs = _spawn_workers(
        2, unused_tcp_port, script="_mp_load_worker.py", extra_args=(ckpt, npz)
    )
    for rc, out, err in outs:
        assert rc == 0 and "LOAD_OK" in out, f"{out}\n{err[-3000:]}"


def test_four_process_mesh(tmp_path, unused_tcp_port):
    """4 controllers x 2 devices: four distinct uneven partitions (one
    empty), comm_split groups straddling process boundaries, the
    query-sharded merge, and a single-controller checkpoint spanning-
    loaded with 2 stored rank shards per process — layouts the 2-way
    tier cannot produce."""
    ckpt = str(tmp_path / "quad.rtivf")
    npz = str(tmp_path / "quad_oracle.npz")
    _build_single_controller_ckpt(ckpt, npz, seed=21)

    outs = _spawn_workers(
        4, unused_tcp_port, timeout=600.0, script="_mp_quad_worker.py",
        extra_args=(ckpt, npz),
    )
    for rc, out, err in outs:
        assert rc == 0 and "WORKER_OK" in out, f"{out}\n{err[-3000:]}"
        assert "FAIL" not in out, out


@pytest.fixture
def unused_tcp_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
