"""Regenerate the legacy-era golden checkpoints (run from the repo
root: ``python tests/goldens/make_legacy_ckpts.py``).

These pin the BACKWARD side of the `CKPT_SCHEMA` compat contract: each
file is byte-for-byte what the pre-`list_radii` / pre-`fused_kb` era
writers emitted — a tiny index serialized WITHOUT the fields later
versions added — so `tests/test_ckpt_schema.py`'s legacy-load
tests can prove every load falls back exactly as the schema declares
(radii-less -> budgets-only adaptive probing, `fused_kb` -> default
None) against real bytes, not a synthetic mock of them. Deterministic:
fixed seeds, fixed geometry, CPU backend.
"""

import os
import sys

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, ROOT)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

OUT = os.path.dirname(os.path.abspath(__file__))


def main():
    from raft_tpu.core.serialize import serialize_arrays
    from raft_tpu.neighbors import ivf_flat, ivf_pq, ivf_rabitq

    rng = np.random.default_rng(20240817)
    data = rng.random((96, 16), dtype=np.float32)

    # ivf_flat, the pre-list_radii v2 writer: same container, no radii
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=4), data)
    serialize_arrays(
        os.path.join(OUT, "legacy_ivf_flat_v2_noradii.ckpt"),
        {
            "centers": idx.centers,
            "list_data": idx.list_data,
            "slot_rows": idx.slot_rows,
            "list_sizes": idx.list_sizes,
            "source_ids": idx.source_ids,
        },
        {"kind": "ivf_flat", "version": 2, "metric": int(idx.metric),
         "metric_arg": idx.params.metric_arg, "n_lists": idx.n_lists,
         "adaptive_centers": idx.params.adaptive_centers},
    )

    # ivf_pq, the pre-list_radii v1 writer
    pidx = ivf_pq.build(ivf_pq.IndexParams(n_lists=4, pq_dim=4), data)
    serialize_arrays(
        os.path.join(OUT, "legacy_ivf_pq_v1_noradii.ckpt"),
        {
            "rotation": pidx.rotation,
            "centers": pidx.centers,
            "pq_centers": pidx.pq_centers,
            "codes": pidx.codes,
            "slot_rows": pidx.slot_rows,
            "list_sizes": pidx.list_sizes,
            "source_ids": pidx.source_ids,
        },
        {"kind": "ivf_pq", "version": 1, "metric": int(pidx.metric),
         "n_lists": pidx.n_lists, "pq_bits": pidx.pq_bits,
         "codebook_kind": pidx.params.codebook_kind},
    )

    # ivf_rabitq, the v1 baseline (pre-fused_kb/codes_t runtime era —
    # the on-disk set never carried them; the golden pins that loads
    # re-default the runtime fields)
    ridx = ivf_rabitq.build(ivf_rabitq.IndexParams(n_lists=4), data)
    quant = ivf_rabitq.RabitqQuantizer(ridx.rot_dim)
    serialize_arrays(
        os.path.join(OUT, "legacy_ivf_rabitq_v1.ckpt"),
        {
            "rotation": ridx.rotation,
            "centers": ridx.centers,
            "codes": ridx.codes,
            "aux": ridx.aux,
            "slot_rows": ridx.slot_rows,
            "list_sizes": ridx.list_sizes,
            "source_ids": ridx.source_ids,
            **quant.state_arrays(),
        },
        {"kind": "ivf_rabitq", "version": 1, "metric": int(ridx.metric),
         "n_lists": ridx.n_lists, **quant.state_meta()},
    )

    # -- the PRE-MUTATION era (immediately before tombstones/mut_cursor/
    # append_slack): flat v2 WITH list_radii and pq v1 WITH list_radii —
    # the newest writers that never emitted the mutation fields, so
    # tests/test_ckpt_schema.py can prove absent-on-load means all-live/
    # cursor-0/no-slack on real bytes. (The rabitq pre-mutation writer
    # is the v1 baseline above — legacy_ivf_rabitq_v1.ckpt covers it.)
    assert idx.list_radii is not None and pidx.list_radii is not None
    serialize_arrays(
        os.path.join(OUT, "legacy_ivf_flat_v2_radii.ckpt"),
        {
            "centers": idx.centers,
            "list_data": idx.list_data,
            "slot_rows": idx.slot_rows,
            "list_sizes": idx.list_sizes,
            "source_ids": idx.source_ids,
            "list_radii": idx.list_radii,
        },
        {"kind": "ivf_flat", "version": 2, "metric": int(idx.metric),
         "metric_arg": idx.params.metric_arg, "n_lists": idx.n_lists,
         "adaptive_centers": idx.params.adaptive_centers},
    )
    serialize_arrays(
        os.path.join(OUT, "legacy_ivf_pq_v1_radii.ckpt"),
        {
            "rotation": pidx.rotation,
            "centers": pidx.centers,
            "pq_centers": pidx.pq_centers,
            "codes": pidx.codes,
            "slot_rows": pidx.slot_rows,
            "list_sizes": pidx.list_sizes,
            "source_ids": pidx.source_ids,
            "list_radii": pidx.list_radii,
        },
        {"kind": "ivf_pq", "version": 1, "metric": int(pidx.metric),
         "n_lists": pidx.n_lists, "pq_bits": pidx.pq_bits,
         "codebook_kind": pidx.params.codebook_kind},
    )
    print("wrote legacy goldens under", OUT)


if __name__ == "__main__":
    main()
