"""raft_tpu.jobs suite: durable resumable jobs + watchdog supervision.

Three layers of drills:

- **JobDir / runner semantics** (fast): manifest commit/skip protocol,
  fingerprint invalidation cascading downstream, torn-manifest
  tolerance, artifact-rot fail-closed, preemption as a graceful suspend
  (real SIGTERM and the injected ``job.preempt`` fault), watchdog
  stall-kills (injected ``job.heartbeat.stall``) retried to completion
  with the stall visible in `obs.report`.
- **Streaming resume** (fast, in-process): a transient
  ``job.stage.crash`` fault aborts a streaming build mid-extend; the
  supervised runner retries the stage, which re-enters through the
  batch cursor and finishes bit-identical to an uninterrupted build;
  chunked dataset synthesis resumes byte-identical after an interrupt.
- **Kill-and-resume bit-identity** (slow, child processes): a seeded
  kill_rank fault at ``job.stage.crash`` SIGKILLs a real child process
  at a batch-boundary checkpoint (`tests/_job_crash_worker.py`);
  re-running the same command resumes from the scratch cursor and the
  final index/dataset is BYTE-IDENTICAL to an uninterrupted run — the
  ISSUE-8 chaos acceptance drill, parametrized over
  {ivf_flat, ivf_pq, ivf_rabitq} and the make_data failure class
  (`BENCH_10M_PARTIAL`).

The three ``job.*`` fault sites drilled here are pinned against
`core.faults.FAULT_SITES` by the drift test in test_raftlint.py.
"""

import contextlib
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from raft_tpu import jobs, obs
from raft_tpu.core import faults
from raft_tpu.jobs import (
    Heartbeat,
    Job,
    JobDir,
    JobPreempted,
    StageFailed,
    StageTimeout,
    Watchdog,
    fingerprint_of,
    run_supervised,
)
from raft_tpu.neighbors import ivf_flat
from raft_tpu.obs import report as obs_report

SEED = int(os.environ.get(faults.ENV_SEED, "1234"))
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_job_crash_worker.py")


@pytest.fixture
def obs_on():
    obs.enable()
    obs.reset()
    yield
    obs.reset()
    obs.disable()


# -- JobDir: the durable commit protocol --------------------------------

def test_fingerprint_of_is_deterministic_and_input_sensitive():
    a = fingerprint_of({"stage": "s", "inputs": {"rows": 10}})
    b = fingerprint_of({"inputs": {"rows": 10}, "stage": "s"})
    c = fingerprint_of({"stage": "s", "inputs": {"rows": 11}})
    assert a == b  # canonical JSON: key order irrelevant
    assert a != c
    assert len(a) == 8 and int(a, 16) >= 0


def test_jobdir_commit_skip_and_artifact_verification(tmp_path):
    jd = JobDir(str(tmp_path / "jd"))
    art = jd.artifact_path("s1")
    with open(art, "w") as fh:
        fh.write("payload")
    entry = jd.commit("s1", "aaaa0000", artifacts={"artifact": art},
                      meta={"rows": 7}, provenance={"git_sha": "deadbee"})
    assert entry["artifacts"]["artifact"]["nbytes"] == 7
    # complete at the committed fingerprint, incomplete at any other
    got = jd.is_complete("s1", "aaaa0000")
    assert got is not None and got["meta"] == {"rows": 7}
    assert jd.is_complete("s1", "bbbb1111") is None
    # later commits win (input change -> re-run appends a fresh line)
    jd.commit("s1", "bbbb1111", artifacts={"artifact": art}, meta={"rows": 8})
    assert jd.is_complete("s1", "aaaa0000") is None
    assert jd.is_complete("s1", "bbbb1111")["meta"] == {"rows": 8}


def test_jobdir_artifact_rot_fails_closed(tmp_path):
    """A committed stage whose artifact rotted (size or CRC mismatch) or
    vanished must re-run — a wrong skip would poison every dependent.
    The (size, mtime_ns) fast path only short-circuits the CRC when the
    file's metadata is IDENTICAL to the commit-time stat; any touched
    file falls back to the streamed CRC."""
    jd = JobDir(str(tmp_path / "jd"))
    art = jd.artifact_path("s1")
    with open(art, "w") as fh:
        fh.write("payload")
    jd.commit("s1", "aaaa0000", artifacts={"artifact": art})
    assert jd.is_complete("s1", "aaaa0000") is not None  # fast path OK
    with open(art, "w") as fh:
        fh.write("pAyload")  # same size, different bytes
    os.utime(art, ns=(1, 1))  # metadata moved -> full CRC catches it
    assert jd.is_complete("s1", "aaaa0000") is None
    # an untouched-content file with a moved mtime re-verifies via CRC
    with open(art, "w") as fh:
        fh.write("payload")
    os.utime(art, ns=(2, 2))
    assert jd.is_complete("s1", "aaaa0000") is not None
    os.remove(art)
    assert jd.is_complete("s1", "aaaa0000") is None


def test_manifest_torn_line_is_skipped_and_terminated(tmp_path):
    """A SIGKILL mid-append leaves an unterminated line; reads skip it
    and the next append terminates it first, so one crash never swallows
    the following commit."""
    jd = JobDir(str(tmp_path / "jd"))
    jd.commit("s1", "aaaa0000")
    with open(jd.manifest_path, "ab") as fh:
        fh.write(b'{"stage": "s2", "fingerpr')  # torn mid-write
    jd.commit("s3", "cccc2222")
    stages = [e["stage"] for e in jd.read_manifest()]
    assert stages == ["s1", "s3"]
    assert jd.is_complete("s3", "cccc2222") is not None


# -- runner: DAG skip/resume/invalidate ---------------------------------

def _three_stage_job(root, calls, x=1):
    job = Job("demo", root)

    def a(ctx):
        calls.append("a")
        with open(ctx.artifact_path(), "w") as fh:
            fh.write("A")
        return {"n": 1}

    def b(ctx):
        calls.append("b")
        assert ctx.dep_meta("a") == {"n": 1}
        assert open(ctx.dep_artifact("a")).read() == "A"
        return {"n": 2}

    def c(ctx):
        calls.append("c")
        return {"n": 3}

    job.add_stage("a", a, inputs={"x": x})
    job.add_stage("b", b, deps=("a",))
    job.add_stage("c", c, deps=("b",))
    return job


def test_rerun_skips_completed_stages(tmp_path):
    calls = []
    root = str(tmp_path / "jd")
    assert _three_stage_job(root, calls).run() == {
        "a": "ran", "b": "ran", "c": "ran"}
    job2 = _three_stage_job(root, calls)
    assert job2.run() == {"a": "skipped", "b": "skipped", "c": "skipped"}
    assert calls == ["a", "b", "c"]  # nothing re-ran
    # skipped stages still hand their committed meta to the caller
    assert job2.results == {"a": {"n": 1}, "b": {"n": 2}, "c": {"n": 3}}


def test_changed_input_reruns_stage_and_everything_downstream(tmp_path):
    calls = []
    root = str(tmp_path / "jd")
    _three_stage_job(root, calls).run()
    # stale intra-stage cursor from the OLD fingerprint must be cleared
    job2 = _three_stage_job(root, calls, x=2)
    stale = os.path.join(job2.jobdir.scratch("a"), "cursor.json")
    with open(stale, "w") as fh:
        fh.write("{}")
    assert job2.run() == {"a": "ran", "b": "ran", "c": "ran"}
    assert not os.path.exists(stale)
    assert calls == ["a", "b", "c", "a", "b", "c"]


def test_commit_clears_stage_scratch(tmp_path):
    """A committed stage's intra-stage checkpoints are superseded by
    its artifact — at 100M scale the final streaming checkpoint is a
    full second copy of the index, so the runner reclaims it."""
    job = Job("clean", str(tmp_path / "jd"))

    def stage(ctx):
        with open(os.path.join(ctx.scratch(), "stream.ckpt"), "w") as fh:
            fh.write("x" * 64)
        return {}

    job.add_stage("s", stage)
    assert job.run() == {"s": "ran"}
    assert not os.path.isdir(job.jobdir.scratch("s")) or not os.listdir(
        job.jobdir.scratch("s"))


def test_stage_failure_raises_with_cause_and_blocks_dependents(tmp_path):
    job = Job("fail", str(tmp_path / "jd"))
    boom = ValueError("boom")

    def bad(ctx):
        raise boom

    ran = []
    job.add_stage("bad", bad)
    job.add_stage("after", lambda ctx: ran.append(1) or {}, deps=("bad",))
    with pytest.raises(StageFailed) as ei:
        job.run()
    assert ei.value.__cause__ is boom
    # queue mode: record the failure, block dependents, keep sweeping
    job2 = Job("fail", str(tmp_path / "jd"))
    job2.add_stage("bad", bad)
    job2.add_stage("after", lambda ctx: {}, deps=("bad",))
    job2.add_stage("indep", lambda ctx: {})
    st = job2.run(continue_on_error=True)
    assert st == {"bad": "failed", "after": "blocked", "indep": "ran"}
    assert not ran


def test_dag_declaration_errors(tmp_path):
    job = Job("bad", str(tmp_path / "jd"))
    job.add_stage("a", lambda ctx: {})
    with pytest.raises(ValueError, match="duplicate"):
        job.add_stage("a", lambda ctx: {})
    with pytest.raises(ValueError, match="unknown stage"):
        job.add_stage("b", lambda ctx: {}, deps=("nope",))


# -- preemption: a graceful suspend, not a failure ----------------------

def test_sigterm_suspends_after_current_stage_and_rerun_resumes(tmp_path):
    root = str(tmp_path / "jd")
    job = Job("pre", root)
    job.add_stage("s1", lambda ctx: {"n": 1})

    def s2(ctx):
        os.kill(os.getpid(), signal.SIGTERM)  # the preemption notice
        time.sleep(0.02)
        return {"n": 2}

    job.add_stage("s2", s2, deps=("s1",))
    job.add_stage("s3", lambda ctx: {"n": 3}, deps=("s2",))
    with pytest.raises(JobPreempted):
        job.run()
    # the in-flight stage COMMITTED before the between-stage check
    assert job.statuses == {"s1": "ran", "s2": "ran"}
    job2 = Job("pre", root)
    job2.add_stage("s1", lambda ctx: {"n": 1})
    job2.add_stage("s2", lambda ctx: {"n": 2}, deps=("s1",))
    job2.add_stage("s3", lambda ctx: {"n": 3}, deps=("s2",))
    assert job2.run() == {"s1": "skipped", "s2": "skipped", "s3": "ran"}


def test_injected_preempt_fault_suspends_like_sigterm(tmp_path, obs_on):
    """The ``job.preempt`` chaos site: a flaky fault there simulates the
    SIGTERM a TPU preemption delivers — the runner suspends as
    JobPreempted between stages and a re-run resumes."""
    root = str(tmp_path / "jd")
    plan = faults.FaultPlan(
        [faults.Fault(kind="flaky_bootstrap", site="job.preempt", count=1)],
        seed=SEED)
    job = Job("chaos_pre", root)
    job.add_stage("s1", lambda ctx: {})
    job.add_stage("s2", lambda ctx: {}, deps=("s1",))
    with plan.install():
        with pytest.raises(JobPreempted):
            job.run()
    evs = [e for e in obs.snapshot()["events"] if e["kind"] == "job"]
    assert ("preempt" in [e.get("action") for e in evs])
    job2 = Job("chaos_pre", root)
    job2.add_stage("s1", lambda ctx: {})
    job2.add_stage("s2", lambda ctx: {}, deps=("s1",))
    st = job2.run()
    assert st["s2"] == "ran"


def test_preempt_point_mid_stage_leaves_durable_state(tmp_path):
    """`StageContext.preempt_point` is the batch-boundary hook: a
    pending preemption raises OUT of the stage after the checkpoint
    commit, and the next run re-enters the same stage."""
    root = str(tmp_path / "jd")
    seen = []

    def build(job):
        def streamy(ctx):
            marker = os.path.join(ctx.scratch(), "cursor.json")
            done = (JobDir.read_json(marker) or {}).get("done", 0)
            for i in range(done, 3):
                ctx.jobdir.write_json(marker, {"done": i + 1})
                seen.append(i)
                if i == 1:
                    job.request_preempt()
                ctx.preempt_point()
            return {"done": 3}

        job.add_stage("streamy", streamy)
        return job

    with pytest.raises(JobPreempted):
        build(Job("mid", root)).run()
    assert seen == [0, 1]
    st = build(Job("mid", root)).run()
    assert st == {"streamy": "ran"} and seen == [0, 1, 2]


# -- watchdog: stalls become typed timeouts, retried --------------------

def test_injected_heartbeat_stall_is_killed_retried_and_reported(
        tmp_path, obs_on):
    """The ``job.heartbeat.stall`` chaos site: an injected slow_rank
    stall swallows the stage's beats; the watchdog kills the attempt as
    StageTimeout, the seeded retry re-runs it, the job completes — and
    the stall, the kill, and the retry are all visible in `obs.report`
    (the fault/health timeline + the new job timeline)."""
    plan = faults.FaultPlan(
        [faults.Fault(kind="slow_rank", site="job.heartbeat.stall",
                      latency_s=5.0, count=1)],
        seed=SEED)
    job = Job("stall", str(tmp_path / "jd"))
    attempts = []

    def work(ctx):
        attempts.append(1)
        for _ in range(3):
            ctx.heartbeat()
            time.sleep(0.01)
        return {"ok": True}

    job.add_stage("w", work, retries=2, stall_timeout_s=0.3)
    with plan.install():
        st = job.run()
    assert st == {"w": "ran"} and len(attempts) == 2
    snap = obs.snapshot()
    acts = [(e["kind"], e.get("action") or e.get("describe"))
            for e in snap["events"] if e["kind"] in ("fault", "retry")]
    assert ("fault", "stall") in acts
    assert ("fault", "watchdog_kill") in acts
    assert ("retry", "job.stall.w") in acts
    out = obs_report.render(snap)
    assert "watchdog_kill" in out          # fault/health timeline
    assert "action=stall" in out
    assert "retry" in out                  # retry joins the timeline
    assert "## Job timeline" in out        # stage transitions render
    assert "stall.w" in out


def test_watchdog_deadline_kills_non_beating_stage(tmp_path):
    job = Job("dead", str(tmp_path / "jd"))

    def hang(ctx):
        time.sleep(30)
        return {}

    job.add_stage("h", hang, deadline_s=0.25)
    t0 = time.monotonic()
    with pytest.raises(StageFailed) as ei:
        job.run()
    assert isinstance(ei.value.__cause__, StageTimeout)
    assert time.monotonic() - t0 < 10  # killed, not served out


def test_watchdog_without_limits_is_a_plain_call():
    dog = Watchdog()
    assert dog.run(lambda: 42) == 42


def test_heartbeat_beat_raises_after_kill():
    hb = Heartbeat()
    hb._kill()
    with pytest.raises(jobs.StageCancelled):
        hb.beat()


def test_run_supervised_child_output_beats_and_exit_code_passthrough():
    rc = run_supervised(
        [sys.executable, "-c", "print('line'); import sys; sys.exit(4)"],
        stall_timeout_s=30.0, echo=False)
    assert rc == 4


@pytest.mark.slow
def test_run_supervised_kills_silent_child(obs_on):
    """The dead-relay bench shape (BENCH_r01–r05): a child that goes
    silent past stall_timeout_s is SIGKILLed and surfaces as a typed
    StageTimeout with a watchdog_kill event — one hung bench no longer
    hangs the whole session."""
    t0 = time.monotonic()
    with pytest.raises(StageTimeout, match="watchdog killed child"):
        run_supervised(
            [sys.executable, "-c",
             "print('warm', flush=True); import time; time.sleep(600)"],
            describe="hung_bench", stall_timeout_s=0.5, echo=False)
    assert time.monotonic() - t0 < 30
    evs = [e for e in obs.snapshot()["events"]
           if e["kind"] == "fault" and e.get("action") == "watchdog_kill"]
    assert evs and evs[0]["stage"] == "hung_bench"


@pytest.mark.slow
def test_run_supervised_kill_reaps_grandchildren(tmp_path):
    """The watchdog kill must take the child's whole process TREE: a
    hung suite whose grandchild holds the single-client chip lease
    would otherwise wedge every later suite in the sweep."""
    pidfile = str(tmp_path / "grandchild.pid")
    child = (
        "import subprocess, sys, time\n"
        "g = subprocess.Popen([sys.executable, '-c',"
        " 'import time; time.sleep(600)'])\n"
        f"open({pidfile!r}, 'w').write(str(g.pid))\n"
        "print('spawned', flush=True)\n"
        "time.sleep(600)\n"
    )
    with pytest.raises(StageTimeout):
        run_supervised([sys.executable, "-c", child],
                       describe="treekill", stall_timeout_s=0.5, echo=False)
    deadline = time.monotonic() + 10
    gpid = int(open(pidfile).read())
    while time.monotonic() < deadline:
        try:
            os.kill(gpid, 0)
        except ProcessLookupError:
            break  # grandchild reaped with the group
        time.sleep(0.1)
    else:
        os.kill(gpid, 9)  # clean up before failing
        raise AssertionError("grandchild survived the watchdog kill")


def test_run_supervised_default_describe_names_script(tmp_path):
    """With CLI args, the auto-describe must name the child's script —
    not its last flag (a kill surfacing as child '--apply' is useless
    to the operator)."""
    script = tmp_path / "toy_bench.py"
    script.write_text("import time; time.sleep(600)\n")
    with pytest.raises(StageTimeout, match="toy_bench.py"):
        run_supervised(
            [sys.executable, str(script), "--apply"],
            stall_timeout_s=0.5, echo=False)


# -- streaming resume (in-process) --------------------------------------

def _stream_dataset(tmp_path, rows=80, dim=8):
    path = str(tmp_path / "ds.npy")
    rng = np.random.default_rng(0)
    np.save(path, rng.random((rows, dim), dtype=np.float32))
    return path


def _flat_index(path):
    data = np.load(path)
    return ivf_flat.build(
        ivf_flat.IndexParams(n_lists=4, kmeans_n_iters=2,
                             add_data_on_build=False),
        data[:40])


class _Interrupted(RuntimeError):
    pass


def test_streaming_preempt_at_batch_boundary_resumes_bit_identical(tmp_path):
    """The in-process cursor contract: interrupt the streaming extend at
    a batch-boundary checkpoint (the `preempt` hook fires AFTER the
    commit), re-enter, and the finished index is bit-identical to an
    uninterrupted build (arrays compared exactly)."""
    path = _stream_dataset(tmp_path)
    ref, _ = jobs.resumable_extend_from_file(
        "ivf_flat", _flat_index(path), path, 16,
        scratch=str(tmp_path / "ref_scr"), checkpoint_every=1)

    scratch = str(tmp_path / "scr")
    os.makedirs(scratch, exist_ok=True)
    commits = []

    def preempt():
        commits.append(1)
        if len(commits) == 2:
            raise _Interrupted("preempted at batch boundary")

    with pytest.raises(_Interrupted):
        jobs.resumable_extend_from_file(
            "ivf_flat", _flat_index(path), path, 16, scratch=scratch,
            checkpoint_every=1, preempt=preempt)
    got, stats = jobs.resumable_extend_from_file(
        "ivf_flat", _flat_index(path), path, 16, scratch=scratch,
        checkpoint_every=1)
    assert stats["resumed_from_batch"] == 2  # really resumed, not redone
    np.testing.assert_array_equal(np.asarray(got.list_data),
                                  np.asarray(ref.list_data))
    np.testing.assert_array_equal(np.asarray(got.source_ids),
                                  np.asarray(ref.source_ids))
    np.testing.assert_array_equal(np.asarray(got.list_sizes),
                                  np.asarray(ref.list_sizes))


def test_streaming_torn_commit_window_resumes_consistently(tmp_path):
    """Crash-atomicity of the two-file checkpoint commit: a kill BETWEEN
    the index save and the cursor write leaves an orphan newer
    checkpoint beside a cursor naming the previous one. The resume must
    follow the CURSOR (re-extending from the previous state) and still
    finish bit-identical — a shared mutable checkpoint name would pair
    the new index with the old cursor and double-ingest a batch."""
    path = _stream_dataset(tmp_path)
    ref, _ = jobs.resumable_extend_from_file(
        "ivf_flat", _flat_index(path), path, 16,
        scratch=str(tmp_path / "ref_scr"), checkpoint_every=1)

    scratch = str(tmp_path / "scr")
    os.makedirs(scratch, exist_ok=True)
    commits = []

    def preempt():
        commits.append(1)
        if len(commits) == 2:
            raise _Interrupted("killed at batch boundary")

    with pytest.raises(_Interrupted):
        jobs.resumable_extend_from_file(
            "ivf_flat", _flat_index(path), path, 16, scratch=scratch,
            checkpoint_every=1, preempt=preempt)
    # simulate the torn window: a batch-3 save landed but the process
    # died before the cursor advanced past 2
    import shutil

    shutil.copy(os.path.join(scratch, "stream_index.2.ckpt"),
                os.path.join(scratch, "stream_index.3.ckpt"))
    got, stats = jobs.resumable_extend_from_file(
        "ivf_flat", _flat_index(path), path, 16, scratch=scratch,
        checkpoint_every=1)
    assert stats["resumed_from_batch"] == 2  # the cursor, not the orphan
    np.testing.assert_array_equal(np.asarray(got.list_data),
                                  np.asarray(ref.list_data))
    np.testing.assert_array_equal(np.asarray(got.source_ids),
                                  np.asarray(ref.source_ids))
    # the sweep reclaimed superseded checkpoints once the run finished
    lingering = [n for n in os.listdir(scratch)
                 if n.startswith("stream_index.")]
    assert lingering == ["stream_index.5.ckpt"], lingering


def test_watchdog_zombie_attempt_cannot_be_revived_by_retry():
    """A previous attempt's worker that outlived its kill (blocked in
    plain IO where the cooperative cancel can't reach) must stay dead
    once a new attempt adopts the heartbeat — its next beat raises even
    though the new attempt cleared the cancel flag, so two attempts can
    never run the stage concurrently."""
    import threading

    hb = Heartbeat()
    zombie_result = []
    adopted = threading.Event()
    release = threading.Event()

    def zombie():
        hb.adopt()
        adopted.set()
        release.wait(10)  # the stage 'blocked in IO' past its kill
        try:
            hb.beat()
            zombie_result.append("revived")
        except jobs.StageCancelled:
            zombie_result.append("stayed_dead")

    th = threading.Thread(target=zombie, daemon=True)
    th.start()
    assert adopted.wait(10)
    hb._kill()                       # watchdog kills attempt 1
    hb.rearm()                       # supervisor re-arms for attempt 2
    hb.adopt()                       # attempt 2's worker takes ownership
    hb.beat()                        # the new owner beats freely
    release.set()
    th.join(10)
    assert zombie_result == ["stayed_dead"]


def test_streaming_flaky_crash_site_retried_by_supervised_runner(tmp_path):
    """The transient flavor of ``job.stage.crash``: a flaky fault raises
    FaultInjected inside the stream; the supervised runner retries the
    stage until the fault budget is spent and the job completes."""
    path = _stream_dataset(tmp_path)
    plan = faults.FaultPlan(
        [faults.Fault(kind="flaky_bootstrap", site="job.stage.crash",
                      count=2)],
        seed=SEED)
    job = Job("stream", str(tmp_path / "jd"))

    def stage(ctx):
        _, stats = jobs.resumable_extend_from_file(
            "ivf_flat", _flat_index(path), path, 16, ctx=ctx,
            checkpoint_every=1)
        return stats

    job.add_stage("extend", stage, retries=3)
    with plan.install():
        st = job.run()
    assert st == {"extend": "ran"}
    assert job.results["extend"]["rows_ingested"] == 80
    f = plan.faults[0]
    assert plan.fire_count("job.stage.crash", f) == 2  # both firings spent


def test_resumable_write_npy_resumes_byte_identical(tmp_path):
    """Chunked dataset synthesis (the `BENCH_10M_PARTIAL` root fix):
    interrupt after 2 chunks, resume, and the finished file is
    byte-equal to a one-shot write — including the torn-tail truncate."""
    dim, rows, chunk = 4, 20, 3

    def mk(lo, hi):
        rng = np.random.default_rng((5, lo))
        return rng.random((hi - lo, dim), dtype=np.float32)

    one = str(tmp_path / "one.npy")
    jobs.resumable_write_npy(one, rows, dim, chunk, mk,
                             scratch=str(tmp_path / "s1"))

    two = str(tmp_path / "two.npy")
    calls = []

    def mk_interrupted(lo, hi):
        if len(calls) == 2:
            raise RuntimeError("preempted mid-synthesis")
        calls.append(lo)
        return mk(lo, hi)

    with pytest.raises(RuntimeError):
        jobs.resumable_write_npy(two, rows, dim, chunk, mk_interrupted,
                                 scratch=str(tmp_path / "s2"))
    # simulate a torn tail past the durable marker: garbage after the
    # committed chunks must be truncated away on resume
    with open(two, "ab") as fh:
        fh.write(b"\xff" * 7)
    jobs.resumable_write_npy(two, rows, dim, chunk, mk,
                             scratch=str(tmp_path / "s2"))
    assert open(one, "rb").read() == open(two, "rb").read()
    np.testing.assert_array_equal(np.load(one), np.load(two))


def test_resumable_write_npy_bad_chunk_leaves_no_file(tmp_path):
    """A make_chunk returning the wrong shape raises BEFORE any bytes
    land — no torn header-only .npy for a later np.load to trip over."""
    path = str(tmp_path / "bad.npy")
    with pytest.raises(ValueError, match="expected"):
        jobs.resumable_write_npy(
            path, 20, 4, 3,
            lambda lo, hi: np.zeros((hi - lo, 5), dtype=np.float32),
            scratch=str(tmp_path / "s"))
    assert not os.path.exists(path)


def test_resumable_write_npy_stale_config_starts_over(tmp_path):
    """A marker from DIFFERENT geometry never carries into a resume."""
    dim = 4

    def mk(lo, hi):
        rng = np.random.default_rng((5, lo))
        return rng.random((hi - lo, dim), dtype=np.float32)

    path = str(tmp_path / "d.npy")
    scratch = str(tmp_path / "s")
    jobs.resumable_write_npy(path, 6, dim, 3, mk, scratch=scratch)
    jobs.resumable_write_npy(path, 9, dim, 3, mk, scratch=scratch)
    assert np.load(path).shape == (9, dim)


# -- kill-and-resume bit-identity (child-process SIGKILL drills) --------

def _worker(args, workdir):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    return subprocess.run(
        [sys.executable, WORKER, *args, "--workdir", str(workdir)],
        env=env, capture_output=True, text=True, timeout=600)


def _search_ids(kind, ckpt, queries):
    if kind == "ivf_flat":
        from raft_tpu.neighbors import ivf_flat as mod
    elif kind == "ivf_pq":
        from raft_tpu.neighbors import ivf_pq as mod
    else:
        from raft_tpu.neighbors import ivf_rabitq as mod
    index = mod.load(ckpt)
    d, i = mod.search(mod.SearchParams(n_probes=4), index, queries, 5)
    return np.asarray(d), np.asarray(i)


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["ivf_flat", "ivf_pq", "ivf_rabitq"])
def test_sigkill_mid_stream_resumes_bit_identical(tmp_path, kind):
    """THE chaos acceptance drill: a streaming build SIGKILLed at a
    seeded batch boundary (kill_rank at ``job.stage.crash`` — a real
    SIGKILL of a real child process, after the checkpoint commit)
    resumes from its scratch cursor and produces a checkpoint
    BYTE-IDENTICAL to an uninterrupted build, with identical search
    results — tables, aux and slot ids all carried by the artifact on
    disk, not process luck."""
    data = _stream_dataset(tmp_path, rows=80, dim=8)

    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    r = _worker(["stream", "--data", data, "--kind", kind], ref_dir)
    assert r.returncode == 0, r.stderr[-2000:]

    kill_dir = tmp_path / "kill"
    kill_dir.mkdir()
    r1 = _worker(["stream", "--data", data, "--kind", kind,
                  "--kill", "2", "--seed", str(SEED)], kill_dir)
    assert r1.returncode == -signal.SIGKILL, (r1.returncode, r1.stderr[-2000:])
    r2 = _worker(["stream", "--data", data, "--kind", kind], kill_dir)
    assert r2.returncode == 0, r2.stderr[-2000:]
    stats = json.loads(r2.stdout.strip().splitlines()[-1])["stats"]
    assert stats["resumed_from_batch"] >= 2  # really resumed, not redone

    ref_ckpt = str(ref_dir / "out.ckpt")
    got_ckpt = str(kill_dir / "out.ckpt")
    with open(ref_ckpt, "rb") as fa, open(got_ckpt, "rb") as fb:
        assert fa.read() == fb.read(), "resumed index is not bit-identical"
    q = np.load(data)[:7]
    dv, iv = _search_ids(kind, ref_ckpt, q)
    dg, ig = _search_ids(kind, got_ckpt, q)
    np.testing.assert_array_equal(iv, ig)
    np.testing.assert_array_equal(dv, dg)


@pytest.mark.slow
def test_sigkill_mid_make_data_resumes_byte_identical(tmp_path):
    """Kill-mid-make_data (the `BENCH_10M_PARTIAL` failure class, at its
    root): dataset synthesis SIGKILLed between chunk commits resumes
    from the progress marker and finishes a file byte-equal to a
    one-shot run."""
    # one seed for every invocation: it seeds the per-chunk generator
    # (byte-identity needs it) AND the kill run's fault plan
    args = ["datagen", "--rows", "40", "--dim", "6", "--chunk", "8",
            "--seed", str(SEED)]
    one = tmp_path / "one"
    one.mkdir()
    r = _worker(args, one)
    assert r.returncode == 0, r.stderr[-2000:]

    killed = tmp_path / "killed"
    killed.mkdir()
    r1 = _worker(args + ["--kill", "2"], killed)
    assert r1.returncode == -signal.SIGKILL, (r1.returncode, r1.stderr[-2000:])
    marker = JobDir.read_json(
        str(killed / "scratch" / "datagen_progress.json"))
    assert marker and 0 < marker["rows_done"] < 40  # died mid-file
    r2 = _worker(args, killed)
    assert r2.returncode == 0, r2.stderr[-2000:]
    with open(one / "data.npy", "rb") as fa, \
            open(killed / "data.npy", "rb") as fb:
        assert fa.read() == fb.read()


# -- MNMG: checkpointed distributed build stages ------------------------

@pytest.fixture(scope="module")
def comms4():
    from raft_tpu.comms import Comms

    return Comms(n_devices=4)


@pytest.fixture(scope="module")
def mnmg_blobs():
    from raft_tpu.random import make_blobs

    data, _ = make_blobs(800, 16, n_clusters=6, cluster_std=0.4, seed=13)
    return np.asarray(data)


def test_agreed_on_all_hosts_single_process_passthrough():
    """The ISSUE-9 divergence-audit fix: the MNMG resume decision rides
    `_agreed_on_all_hosts` (min over an allgather), never a raw per-host
    `os.path.exists` — on a non-shared filesystem controllers could
    otherwise split between rehydrate's collective load and the build's
    collectives and wedge the mesh. Single-controller worlds (this
    harness) must pass the flag through unchanged; the multi-host
    min-wins vote is exercised by the raftlint fixture suite and the
    on-chip queue."""
    from raft_tpu.jobs.streaming import _agreed_on_all_hosts

    assert _agreed_on_all_hosts(True) is True
    assert _agreed_on_all_hosts(False) is False


@pytest.mark.slow
def test_checkpointed_mnmg_build_resumes_via_rehydrate(
        tmp_path, comms4, mnmg_blobs):
    """A preempted distributed build re-enters through the PR-4
    rehydrate path: the second run must NOT call build_fn again, and the
    rehydrated index serves bit-identically to the built one."""
    from raft_tpu.comms import mnmg

    ckpt = str(tmp_path / "mnmg_flat.ckpt")

    def build_fn():
        return mnmg.ivf_flat_build(
            comms4, ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4),
            mnmg_blobs)

    index, health, resumed = jobs.checkpointed_mnmg_build(
        comms4, "ivf_flat", build_fn, ckpt)
    assert not resumed and health.coverage() == 1.0
    q = mnmg_blobs[:23]
    v0, i0 = mnmg.ivf_flat_search(index, q, 5, n_probes=8)

    def must_not_build():
        raise AssertionError("resume must skip the build")

    index2, health2, resumed2 = jobs.checkpointed_mnmg_build(
        comms4, "ivf_flat", must_not_build, ckpt)
    assert resumed2 and health2.coverage() == 1.0
    v1, i1 = mnmg.ivf_flat_search(index2, q, 5, n_probes=8)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))


@pytest.mark.slow
def test_resumable_extend_local_interrupt_and_resume(
        tmp_path, comms4, mnmg_blobs):
    """The collective streaming twin: the distributed extend is
    interrupted at a batch-boundary checkpoint; re-entry resumes at the
    durable cursor through the PR-4 rehydrate load and finishes with
    the same search results as an uninterrupted run."""
    from raft_tpu.comms import mnmg

    path = str(tmp_path / "part.npy")
    rng = np.random.default_rng(3)
    np.save(path, rng.random((64, 16), dtype=np.float32))
    params = ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4)

    def fresh():
        # build_local keeps the per-process mirrors extend_local appends
        # against (the driver-build layout refuses collective extends)
        return mnmg.ivf_flat_build_local(comms4, params, mnmg_blobs)

    # uninterrupted reference
    ref, _ = jobs.resumable_extend_local_from_file(
        comms4, "ivf_flat", fresh(), mnmg.ivf_flat_extend_local, path, 16,
        scratch=str(tmp_path / "ref_scr"),
        ckpt_path=str(tmp_path / "ref.ckpt"), checkpoint_every=1)
    q = mnmg_blobs[:23]
    v0, i0 = mnmg.ivf_flat_search(ref, q, 5, n_probes=8)

    scratch = str(tmp_path / "scr")
    os.makedirs(scratch, exist_ok=True)
    ckpt = str(tmp_path / "mn.ckpt")
    commits = []

    def preempt():
        commits.append(1)
        if len(commits) == 2:
            raise _Interrupted("preempted at collective batch boundary")

    with pytest.raises(_Interrupted):
        jobs.resumable_extend_local_from_file(
            comms4, "ivf_flat", fresh(), mnmg.ivf_flat_extend_local,
            path, 16, scratch=scratch, ckpt_path=ckpt,
            checkpoint_every=1, preempt=preempt)
    # the cursor is durable at batch 2; resume re-enters via rehydrate
    got, stats = jobs.resumable_extend_local_from_file(
        comms4, "ivf_flat", fresh(), mnmg.ivf_flat_extend_local, path, 16,
        scratch=scratch, ckpt_path=ckpt, checkpoint_every=1)
    assert stats["resumed_from_batch"] > 0
    v1, i1 = mnmg.ivf_flat_search(got, q, 5, n_probes=8)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))


# -- obs.report: the job timeline section -------------------------------

def test_job_timeline_and_retry_render_sections():
    """Pin the render shapes the drills above rely on: kind="job"
    events get their own section, and retry events join the main
    timeline kinds."""
    snap = {
        "metrics": {"counters": {}, "gauges": {}, "histograms": {}},
        "events": [
            {"kind": "job", "seq": 1, "t": 10.0, "job": "b100m",
             "stage": "make_data", "action": "start",
             "fingerprint": "ab12cd34"},
            {"kind": "retry", "seq": 2, "t": 10.5,
             "describe": "job.b100m.make_data", "attempt": 1,
             "max_retries": 2, "delay_s": 0.05, "error": "X"},
            {"kind": "job", "seq": 3, "t": 11.0, "job": "b100m",
             "stage": "make_data", "action": "commit",
             "fingerprint": "ab12cd34"},
        ],
    }
    out = obs_report.render(snap, title="pinned jobs")
    assert "## Job timeline (stage transitions; last 80)" in out
    assert "b100m.make_data" in out and "commit" in out
    assert ("## Timeline (fault, health, retry, compile, log, mutation; "
            "last 60)" in out)
    assert "attempt=1" in out and "delay_s=0.05" in out
