"""File-backed batch loader tests: format probing, native prefetch ring,
memmap fallback, padding, and streamed extend (batch_load_iterator host-IO
parity, spatial/knn/detail/ann_utils.cuh:388)."""

import os

import numpy as np
import pytest

from raft_tpu.io import FileBatchLoader, extend_from_file, probe_file
from raft_tpu import native


def _write_fbin(path, arr):
    with open(path, "wb") as f:
        np.asarray(arr.shape, np.uint32).tofile(f)
        arr.tofile(f)


def test_probe_npy(tmp_path):
    p = str(tmp_path / "a.npy")
    a = np.arange(60, dtype=np.float32).reshape(12, 5)
    np.save(p, a)
    off, shape, dtype = probe_file(p)
    assert shape == (12, 5) and dtype == np.float32
    raw = np.fromfile(p, np.float32, offset=off).reshape(12, 5)
    np.testing.assert_array_equal(raw, a)


def test_probe_bin_formats(tmp_path):
    for ext, dt in [(".fbin", np.float32), (".u8bin", np.uint8), (".ibin", np.int32)]:
        p = str(tmp_path / f"d{ext}")
        a = (np.arange(24) % 7).astype(dt).reshape(6, 4)
        _write_fbin(p, a)
        off, shape, dtype = probe_file(p)
        assert (off, shape, dtype) == (8, (6, 4), dt)


def test_probe_rejects(tmp_path):
    with pytest.raises(ValueError):
        probe_file(str(tmp_path / "x.csv"))
    p = str(tmp_path / "trunc.fbin")
    with open(p, "wb") as f:
        np.asarray([100, 100], np.uint32).tofile(f)  # promises 40kB, has 0
    with pytest.raises(ValueError):
        probe_file(p)


@pytest.mark.parametrize("use_native", [True, False])
@pytest.mark.parametrize("n,batch", [(37, 8), (32, 8), (5, 16), (0, 4)])
def test_loader_roundtrip(tmp_path, use_native, n, batch, rng):
    if use_native and not native.available():
        pytest.skip("native library unavailable")
    p = str(tmp_path / "d.npy")
    a = rng.random((n, 6), dtype=np.float32)
    np.save(p, a)
    loader = FileBatchLoader(p, batch, native=use_native, copy=True)
    got, total = [], 0
    for block, valid in loader:
        assert block.shape == (batch, 6)
        got.append(block[:valid])
        total += valid
        if valid < batch:  # padded tail
            assert np.all(block[valid:] == 0)
    assert total == n and len(loader) == (-(-n // batch) if n else 0)
    if n:
        np.testing.assert_array_equal(np.concatenate(got), a)


def test_loader_native_zero_copy_lifetime(tmp_path, rng):
    """With copy=False, a yielded view stays valid while the next
    depth-2 batches are consumed — the contract streamed builds rely on."""
    if not native.available():
        pytest.skip("native library unavailable")
    p = str(tmp_path / "d.npy")
    a = rng.random((64, 4), dtype=np.float32)
    np.save(p, a)
    for depth, lag in [(3, 1), (4, 2)]:
        held = []
        for i, (block, valid) in enumerate(
            FileBatchLoader(p, 8, depth=depth, copy=False)
        ):
            held.append((i, block))
            for j, b in held[-(lag + 1):]:
                np.testing.assert_array_equal(b, a[j * 8 : (j + 1) * 8])


@pytest.mark.parametrize("use_native", [True, False])
@pytest.mark.parametrize("n,batch,start", [(37, 8, 2), (32, 8, 3),
                                           (37, 8, 5), (37, 8, 0)])
def test_loader_start_batch_tail_bit_identical(tmp_path, use_native, n,
                                               batch, start, rng):
    """`start_batch=` (ISSUE 8, the streaming-resume cursor): batches
    [start, n_batches) are bit-identical — contents AND tail padding —
    to the same positions of a from-zero iteration, because the batch
    grid is anchored to the file start."""
    if use_native and not native.available():
        pytest.skip("native library unavailable")
    p = str(tmp_path / "d.npy")
    a = rng.random((n, 6), dtype=np.float32)
    np.save(p, a)
    full = [(np.array(b, copy=True), v) for b, v in
            FileBatchLoader(p, batch, native=use_native, copy=True)]
    tail = [(np.array(b, copy=True), v) for b, v in
            FileBatchLoader(p, batch, native=use_native, copy=True,
                            start_batch=start)]
    assert len(tail) == len(full) - start
    for (bf, vf), (bt, vt) in zip(full[start:], tail):
        assert vf == vt
        np.testing.assert_array_equal(bf, bt)  # incl. padded tail zeros


def test_loader_start_batch_bounds(tmp_path, rng):
    p = str(tmp_path / "d.npy")
    np.save(p, rng.random((20, 3), dtype=np.float32))
    # fully-consumed resume: a valid no-op iterator, not an error
    done = FileBatchLoader(p, 6, start_batch=4)
    assert list(done) == []
    with pytest.raises(ValueError, match="start_batch"):
        FileBatchLoader(p, 6, start_batch=5)
    with pytest.raises(ValueError, match="start_batch"):
        FileBatchLoader(p, 6, start_batch=-1)


def test_loader_reiteration(tmp_path, rng):
    p = str(tmp_path / "d.npy")
    a = rng.random((20, 3), dtype=np.float32)
    np.save(p, a)
    loader = FileBatchLoader(p, 6, copy=True)
    for _ in range(2):
        got = np.concatenate([b[:v] for b, v in loader])
        np.testing.assert_array_equal(got, a)


@pytest.mark.slow
def test_extend_from_file(tmp_path, rng):
    """Streamed file build reaches the same index contents as a direct
    build: the 100M-scale path in miniature."""
    from raft_tpu.neighbors import ivf_flat, brute_force

    data = rng.random((600, 16), dtype=np.float32).astype(np.float32)
    p = str(tmp_path / "corpus.fbin")
    _write_fbin(p, data)

    params = ivf_flat.IndexParams(n_lists=8, add_data_on_build=False)
    index = ivf_flat.build(params, data[:200])  # train quantizer only
    index = extend_from_file(ivf_flat.extend, index, p, batch_rows=256)

    q = data[:32]
    _, ids = ivf_flat.search(ivf_flat.SearchParams(n_probes=8), index, q, 5)
    _, truth = brute_force.knn(data, q, 5, metric="sqeuclidean")
    got, want = np.asarray(ids), np.asarray(truth)
    recall = np.mean([len(set(got[i]) & set(want[i])) / 5 for i in range(32)])
    assert recall > 0.95, recall


def test_extend_from_file_local(tmp_path):
    """Collective file-backed ingestion: stream an on-disk partition into
    a *_build_local index via extend_local (single-process degenerate:
    the batch-count consensus and empty-tail handling still run)."""
    import numpy as np
    from raft_tpu import io
    from raft_tpu.comms import Comms, mnmg
    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.random import make_blobs

    data = np.asarray(make_blobs(1200, 16, n_clusters=4, seed=6)[0])
    path = str(tmp_path / "part.npy")
    np.save(path, data[800:])

    comms = Comms()
    idx = mnmg.ivf_flat_build_local(
        comms, ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4), data[:800])
    idx = io.extend_from_file_local(mnmg.ivf_flat_extend_local, idx, path,
                                    batch_rows=150)  # 400 rows -> 3 batches
    assert idx.n == 1200
    # streamed rows findable with their continued ids
    _, i = mnmg.ivf_flat_search(idx, data[900:904], 1, n_probes=8)
    np.testing.assert_array_equal(np.asarray(i).ravel(),
                                  np.arange(900, 904))
