"""Observability subsystem tests.

The load-bearing contract here is DETERMINISM: the same seeded chaos
drill must produce the same span/event snapshot (modulo clock fields),
because that is what makes obs snapshots assertable in CI and
comparable across runs in an incident. The drill test below runs a full
scenario twice — comms collectives with an injected drop, serving with
warmup + compile-cache hits + an injected slow batch and a flaky
submit, host corruption, rank-health transitions — and pins the exact
event sequence, collective byte counts, and compile-cache hits.
"""

import json
import threading

import numpy as np
import jax.numpy as jnp
import pytest

import importlib

from raft_tpu import obs

# the core package re-binds the attribute `logger` to the Logger object,
# shadowing the module for attribute-based import forms
logger_mod = importlib.import_module("raft_tpu.core.logger")
from raft_tpu.core import faults, tracing
from raft_tpu.obs import report as obs_report
from raft_tpu.obs.registry import Registry


@pytest.fixture
def obs_on():
    obs.reset()
    obs.enable()
    yield
    obs.disable()
    obs.reset()


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_instruments():
    reg = Registry()
    c = reg.counter("a.calls")
    c.inc()
    c.inc(4)
    assert reg.counter("a.calls") is c  # get-or-create is idempotent
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("depth")
    g.set(3)
    g.add(2.5)
    assert g.value == 5.5
    h = reg.histogram("lat")
    for v in (2.0, 1.0, 4.0):
        h.observe(v)
    agg = h.aggregate()
    assert agg == {"count": 3, "total": 7.0, "min": 1.0, "max": 4.0,
                   "mean": 7.0 / 3, "last": 4.0}
    # one name, one instrument kind
    with pytest.raises(ValueError):
        reg.gauge("a.calls")


def test_registry_snapshot_deterministic_and_reset():
    reg = Registry()
    reg.counter("z").inc(1)
    reg.counter("a").inc(2)
    reg.gauge("m").set(7)
    snap = reg.snapshot()
    assert list(snap["counters"]) == ["a", "z"]  # sorted
    assert snap["counters"] == {"a": 2, "z": 1}
    reg.reset()
    snap2 = reg.snapshot()
    assert snap2["counters"] == {"a": 0, "z": 0}  # values zeroed, names kept
    assert snap2["gauges"]["m"] == 0.0


def test_registry_collector_sections():
    reg = Registry()
    reg.add_collector("svc", lambda: {"x": 1})
    reg.add_collector("bad", lambda: 1 / 0)
    snap = reg.snapshot()
    assert snap["collectors"]["svc"] == {"x": 1}
    assert "error" in snap["collectors"]["bad"]  # failure never raises
    reg.remove_collector("bad")
    assert "bad" not in reg.snapshot().get("collectors", {})


def test_registry_thread_safety():
    reg = Registry()
    c = reg.counter("n")

    def work():
        for _ in range(1000):
            c.inc()

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000


# ---------------------------------------------------------------------------
# bus
# ---------------------------------------------------------------------------

def test_bus_ordering_ring_and_subscribers():
    from raft_tpu.obs.bus import EventBus

    bus = EventBus(maxlen=4)
    seen = []
    bus.subscribe(seen.append)
    bus.subscribe(lambda e: 1 / 0)  # broken subscriber must not poison
    for i in range(6):
        bus.publish("k", i=i)
    evs = bus.events()
    assert [e["seq"] for e in evs] == [3, 4, 5, 6]  # ring kept the tail
    assert len(seen) == 6  # subscribers saw everything, in order
    assert [e["i"] for e in seen] == list(range(6))
    assert bus.events(kind="nope") == []
    bus.clear()
    assert len(bus) == 0
    assert bus.publish("k") == 1  # sequence restarted


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

def test_span_nesting_and_aggregation(obs_on):
    with obs.span("outer"):
        with obs.span("inner", k=7) as sp:
            sp.set(extra="x")
    evs = obs.bus().events(kind="span")
    # close order: inner first, then outer
    assert [(e["name"], e["depth"], e["parent"]) for e in evs] == [
        ("inner", 1, "outer"), ("outer", 0, None)]
    assert evs[0]["k"] == 7 and evs[0]["extra"] == "x"
    assert evs[0]["dur_s"] >= 0.0
    agg = obs.registry().snapshot()["histograms"]["span.outer"]
    assert agg["count"] == 1


def test_span_capture_totals(obs_on):
    with obs.capture_spans() as cap:
        for _ in range(3):
            with obs.span("phase.a"):
                pass
        with obs.span("phase.b"):
            pass
    with obs.span("phase.a"):  # outside the capture window
        pass
    totals = cap.totals()
    assert totals["phase.a"]["calls"] == 3
    assert totals["phase.b"]["calls"] == 1
    assert set(totals) == {"phase.a", "phase.b"}


def test_disabled_is_inert():
    obs.disable()
    obs.reset()
    with obs.span("nope") as sp:
        sp.set(a=1)
    obs.event("fault", site="x")
    obs.collective("allreduce", np.zeros((4,), np.float32))
    assert obs.bus().events() == []
    snap = obs.registry().snapshot()
    # reset() keeps instrument definitions from earlier tests; disabled
    # hooks must not have moved any of them off zero
    assert all(v == 0 for v in snap["counters"].values())
    assert all(agg["count"] == 0 for agg in snap["histograms"].values())


def test_span_decorator_and_current_span(obs_on):
    @obs.spanned("deco.fn", tag=1)
    def fn():
        assert obs.current_span().name == "deco.fn"
        return 42

    assert fn() == 42
    assert obs.current_span() is None
    ev = obs.bus().events(kind="span")[-1]
    assert ev["name"] == "deco.fn" and ev["tag"] == 1


# ---------------------------------------------------------------------------
# tracing satellites
# ---------------------------------------------------------------------------

def test_trace_range_disabled_accepts_kwargs():
    tracing.enable(False)
    try:
        with tracing.trace_range("x", foo=1):  # must not TypeError
            pass

        @tracing.annotate("y", foo=2)
        def f():
            return 7

        assert f() == 7
    finally:
        tracing.enable(True)


def test_obs_reexports_tracing():
    assert obs.trace_range is tracing.trace_range
    assert obs.annotate is tracing.annotate


# ---------------------------------------------------------------------------
# logger bridge
# ---------------------------------------------------------------------------

def test_logger_routes_to_bus_when_enabled(obs_on):
    logger_mod.set_level(logger_mod.RAFT_LEVEL_INFO)
    try:
        logger_mod.logger.info("bridged %d", 1)
        evs = obs.bus().events(kind="log")
        assert len(evs) == 1
        assert evs[0]["msg"] == "bridged 1" and evs[0]["level"] == "INFO"
        obs.disable()
        logger_mod.logger.info("not bridged")
        assert len(obs.bus().events(kind="log")) == 1  # handler removed
    finally:
        logger_mod.set_level(logger_mod.RAFT_LEVEL_WARN)


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------

def test_collective_accounting_exact(obs_on):
    from raft_tpu.comms.comms import Comms, op_t

    comms = Comms()
    x = np.arange(32, dtype=np.float32).reshape(8, 4)

    def prog(ac, xs):
        return ac.allreduce(jnp.sum(xs, axis=0))[None, :]

    out = comms.run(prog, x)  # (8, 4): one replicated result row per rank
    np.testing.assert_allclose(np.asarray(out), np.tile(x.sum(axis=0), (8, 1)))
    counters = obs.registry().snapshot()["counters"]
    # one allreduce traced; per-rank payload is the (4,) f32 row sum
    assert counters["comms.allreduce.calls"] == 1
    assert counters["comms.allreduce.bytes"] == 16
    evs = obs.bus().events(kind="collective")
    assert [(e["op"], e["bytes"]) for e in evs] == [("allreduce", 16)]


def test_barrier_counts_itself_and_its_allreduce(obs_on):
    from raft_tpu.comms.comms import Comms

    comms = Comms()

    def prog(ac, xs):
        return jnp.reshape(ac.barrier(jnp.sum(xs)), (1,))

    comms.run(prog, np.ones(8, np.float32))
    counters = obs.registry().snapshot()["counters"]
    assert counters["comms.barrier.calls"] == 1
    assert counters["comms.allreduce.calls"] == 1  # delegation layer


# ---------------------------------------------------------------------------
# exporters + report
# ---------------------------------------------------------------------------

_PROM_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
# exposition series: bare `name value`, or `name{le="<bound>"} value`
# (the histogram bucket label the exporter emits)
_PROM_LINE = _PROM_NAME + r'(?:\{le="(?:[0-9.e+-]+|\+Inf)"\})? (\S+)'


def _assert_prometheus(text: str):
    import re

    lines = text.strip().split("\n")
    assert lines
    for line in lines:
        m = re.fullmatch(_PROM_LINE, line)
        assert m, f"not exposition format: {line!r}"
        float(m.group(1))  # value must parse as a float (nan/inf ok)


def test_render_prometheus_format(obs_on):
    obs.counter("a.b.calls").inc(3)
    obs.gauge("depth").set(1.5)
    obs.histogram("span.x").observe(0.25)
    text = obs.render_registry_prometheus()
    _assert_prometheus(text)
    assert "raft_tpu_a_b_calls 3" in text.split("\n")
    assert "raft_tpu_span_x_count 1" in text.split("\n")
    # None aggregates (empty histogram min/max) are skipped, not "None"
    obs.histogram("span.empty")
    assert "None" not in obs.render_registry_prometheus()


def test_render_prometheus_histogram_buckets(obs_on):
    """Real `le`-bucketed exposition: cumulative _bucket series with a
    +Inf terminal equal to _count, plus the _sum series — not just
    aggregate gauges."""
    h = obs.histogram("span.x")
    for v in (0.004, 0.004, 0.3, 99.0):
        h.observe(v)
    lines = obs.render_registry_prometheus().split("\n")
    _assert_prometheus("\n".join(l for l in lines if l))
    assert 'raft_tpu_span_x_bucket{le="0.005"} 2' in lines
    assert 'raft_tpu_span_x_bucket{le="0.5"} 3' in lines
    assert 'raft_tpu_span_x_bucket{le="10"} 3' in lines  # 99.0 only in +Inf
    assert 'raft_tpu_span_x_bucket{le="+Inf"} 4' in lines
    assert "raft_tpu_span_x_count 4" in lines
    assert any(l.startswith("raft_tpu_span_x_sum ") for l in lines)
    # cumulative counts must be monotone in bound order
    counts = [h.bucket_counts()]
    for seq in counts:
        vals = [n for _, n in seq]
        assert vals == sorted(vals) and vals[-1] == 4


def test_snapshot_save_and_report_cli(obs_on, tmp_path, capsys):
    obs.counter("comms.allreduce.calls").inc(2)
    obs.counter("comms.allreduce.bytes").inc(4096)
    obs.counter("serve.compile_cache.hit").inc(5)
    obs.counter("serve.compile_cache.miss").inc(1)
    with obs.span("neighbors.ivf_flat.search"):
        pass
    obs.event("fault", site="serve.batch", action="slow")
    path = tmp_path / "snap.json"
    snap = obs.save_snapshot(str(path))
    assert json.loads(path.read_text())["metrics"]["counters"] == \
        snap["metrics"]["counters"]
    rc = obs_report.main([str(path), "--title", "drill"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "# drill" in out
    assert "allreduce" in out and "4.0 KiB" in out
    assert "neighbors.ivf_flat.search" in out
    assert "bucket-program hits: 5/6" in out
    assert "serve.batch" in out  # fault timeline


def test_server_metrics_joins_global_snapshot(obs_on):
    from raft_tpu.serve.metrics import ServerMetrics

    m = ServerMetrics(latency_window=8)
    m.observe_submit()
    sections = obs.snapshot()["metrics"]["collectors"]
    mine = [v for k, v in sections.items() if k.startswith("serve#")]
    assert any(sec.get("submitted") == 1 for sec in mine)


# ---------------------------------------------------------------------------
# the chaos-drill determinism contract (ISSUE 3 acceptance)
# ---------------------------------------------------------------------------

def _normalize(snap: dict) -> dict:
    """Strip clock-derived fields; keep everything a replay must pin."""
    events = [
        {k: v for k, v in e.items() if k not in ("t", "dur_s", "marks")}
        for e in snap["events"]
    ]
    hist_counts = {name: agg["count"]
                   for name, agg in snap["metrics"]["histograms"].items()}
    return {
        "counters": snap["metrics"]["counters"],
        "events": events,
        "hist_counts": hist_counts,
    }


def _chaos_drill():
    """One full instrumented scenario; returns the normalized snapshot.

    Built exclusively from per-call-traced programs (`comms.run`
    re-traces; the serve bucket ladder is warmed explicitly) so a
    second run of the same seeded plan reproduces the event sequence
    bit-for-bit.
    """
    from raft_tpu.comms.comms import Comms
    from raft_tpu.comms.resilience import RankHealth
    from raft_tpu.serve.engine import SearchServer, ServerConfig

    obs.reset()
    plan = faults.FaultPlan([
        faults.Fault("drop_collective", site="comms.allreduce", rank=3),
        faults.Fault("slow_rank", site="serve.batch", latency_s=0.002),
        faults.Fault("flaky_bootstrap", site="serve.submit", count=1),
        faults.Fault("corrupt_shard", site="batch_loader.load", rank=-1,
                     fraction=0.5),
    ], seed=77)

    comms = Comms()
    x = np.ones((8, 4), np.float32)

    def prog(ac, xs):
        return ac.allreduce(jnp.sum(xs, axis=0))

    # healthy collective, then the same program under the chaos plan
    # (the drop event lands at trace time, the jaxpr changes)
    comms.run(prog, x, out_specs=None)
    with plan.install():
        comms.run(prog, x, out_specs=None)

        # host-side corruption: seeded, so the cell count replays
        block = np.ones((16, 4), np.float32)
        faults.corrupt_host("batch_loader.load", block)

        # rank-health transitions (duplicate marks emit no event)
        health = RankHealth.all_healthy(8)
        health.mark_unhealthy(3)
        health.mark_unhealthy(3)
        health.mark_healthy(3)

        # serving: warmup compiles both buckets, then three batches —
        # two compile-cache hits and one miss (new k)
        rng = np.random.default_rng(0)
        server = SearchServer(
            rng.standard_normal((64, 16)).astype(np.float32),
            ServerConfig(buckets=(4, 8), max_wait_ms=0.0),
        )
        server.warmup(3)
        with pytest.raises(faults.FaultInjected):
            server.submit(rng.standard_normal((2, 16)).astype(np.float32), k=3)
        server.submit(rng.standard_normal((2, 16)).astype(np.float32), k=3)
        server.step()
        server.submit(rng.standard_normal((6, 16)).astype(np.float32), k=3)
        server.step()
        server.submit(rng.standard_normal((2, 16)).astype(np.float32), k=5)
        server.step()
    return _normalize(obs.snapshot())


def test_chaos_drill_snapshot_exact(obs_on):
    snap = _chaos_drill()

    # -- collective accounting: 2 traced allreduces, (4,) f32 payloads
    assert snap["counters"]["comms.allreduce.calls"] == 2
    assert snap["counters"]["comms.allreduce.bytes"] == 32

    # -- compile cache: warmup seeds (4,3) and (8,3); k=3 batches hit,
    #    the k=5 batch misses
    assert snap["counters"]["serve.compile_cache.hit"] == 2
    assert snap["counters"]["serve.compile_cache.miss"] == 1
    assert snap["hist_counts"]["serve.warmup_compile_s"] == 2

    # -- fault timeline, in order
    fault_evs = [e for e in snap["events"] if e["kind"] == "fault"]
    assert [(e["site"], e["action"]) for e in fault_evs] == [
        ("comms.allreduce", "drop"),
        ("batch_loader.load", "corrupt_host"),
        ("serve.submit", "flaky"),
        ("serve.batch", "slow"),
        ("serve.batch", "slow"),
        ("serve.batch", "slow"),
    ]
    corrupt = fault_evs[1]
    assert corrupt["cells"] == 25  # seeded draw: fixed forever by seed=77

    # -- health transitions: only real flips, in order
    health_evs = [e for e in snap["events"] if e["kind"] == "health"]
    assert [(e["rank"], e["healthy"]) for e in health_evs] == [
        (3, False), (3, True)]

    # -- compile events: two warmups then hit/hit/miss
    compile_evs = [e for e in snap["events"] if e["kind"] == "compile"]
    assert [(e["phase"], e["bucket"], e["k"], e.get("cached"))
            for e in compile_evs] == [
        ("warmup", 4, 3, None), ("warmup", 8, 3, None),
        ("serve", 4, 3, True), ("serve", 8, 3, True),
        ("serve", 4, 5, False),
    ]

    # -- spans: the serving path nests under serve.batch
    span_evs = [e for e in snap["events"] if e["kind"] == "span"]
    serve_batches = [e for e in span_evs if e["name"] == "serve.batch"]
    assert len(serve_batches) == 3
    knn_spans = [e for e in span_evs
                 if e["name"] == "neighbors.brute_force.knn"]
    assert len(knn_spans) == 5  # 2 warmup + 3 batches
    assert {e["parent"] for e in knn_spans} == {"serve.warmup", "serve.batch"}


@pytest.mark.parametrize("runs", [2])
def test_chaos_drill_replays_identically(obs_on, runs):
    snaps = [_chaos_drill() for _ in range(runs)]
    assert snaps[0] == snaps[1]
