"""Fused Pallas distance + partial select-k: exact-agreement suite.

The fused kernel family (ops/fused_scan.py) must BIT-AGREE with the
two-phase reference select-k (`matrix.scan_select_k(strategy=
"two_phase")`) — ids AND values, min (L2) and max (inner-product)
selection, k in {1, 10, 100}, ragged tails and padded rows excluded via
the valid mask. Agreement inputs are bf16-embeddable integers so both
paths compute the identical geometry (the fused kernel scores bf16
operands; the documented compute_dtype=bfloat16 trade) and every
intermediate is exact. The tie-break property test uses adversarial
duplicate-distance inputs: recall@k must be 1.0 with ties broken
deterministically by row id (lax.top_k's stable order).

Everything runs the kernels in interpret mode on CPU (the repo-wide
Pallas testing convention).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.matrix import scan_select_k, select_k
from raft_tpu.matrix.select_k import resolve_scan_strategy
from raft_tpu.neighbors import brute_force, refine


def _grid(rng, shape, lo=-8, hi=8):
    """bf16-embeddable integer data: small integers are exact in bf16
    AND every dot/norm stays well under 2^24, so the fused bf16 matmul,
    the f32 reference, and the numpy oracle all agree bit-for-bit."""
    return rng.integers(lo, hi, shape).astype(np.float32)


def _oracle(x, y, metric):
    if metric == "inner_product":
        return -(x @ y.T)  # canonical minimizing
    return (y**2).sum(1)[None, :] + (x**2).sum(1)[:, None] - 2.0 * x @ y.T


# -- exact agreement vs the two-phase reference -------------------------


@pytest.mark.parametrize("k", [1, 10, 100])
@pytest.mark.parametrize(
    "metric", ["sqeuclidean", "euclidean", "inner_product"]
)
def test_fused_agrees_exactly_with_two_phase(rng, metric, k):
    """ids AND values, min (L2) and max (IP) selection, across the k
    ladder, on ragged (non-lane-aligned) shapes."""
    x = _grid(rng, (29, 33))
    y = _grid(rng, (517, 33))
    vf, jf = scan_select_k(x, y, k, metric=metric, strategy="fused")
    vr, jr = scan_select_k(x, y, k, metric=metric, strategy="two_phase")
    np.testing.assert_array_equal(np.asarray(jf), np.asarray(jr))
    np.testing.assert_array_equal(np.asarray(vf), np.asarray(vr))


def test_fused_valid_mask_excludes_rows_exactly(rng):
    """Ragged tails / padded rows ride the valid mask: masked rows must
    be invisible to the selection on both paths, and a sub-k survivor
    set leaves a (+inf, -1) tail on the fused path."""
    x = _grid(rng, (17, 24))
    y = _grid(rng, (300, 24))
    valid = rng.random(300) < 0.4
    for k in (1, 10, 100):
        vf, jf = scan_select_k(x, y, k, strategy="fused", valid=valid)
        vr, jr = scan_select_k(x, y, k, strategy="two_phase", valid=valid)
        nvalid = int(valid.sum())
        kk = min(k, nvalid)
        np.testing.assert_array_equal(
            np.asarray(jf)[:, :kk], np.asarray(jr)[:, :kk]
        )
        np.testing.assert_array_equal(
            np.asarray(vf)[:, :kk], np.asarray(vr)[:, :kk]
        )
        assert not np.isin(np.asarray(jf)[:, :kk], np.where(~valid)[0]).any()
    # fewer than k survivors: worst-value tail with id -1 — the SAME
    # public contract on both strategies (a caller consuming ids must
    # never receive a masked row back from either path)
    sparse = np.zeros(300, bool)
    sparse[[7, 123, 250]] = True
    for strat in ("fused", "two_phase"):
        vs, js = scan_select_k(x, y, 10, strategy=strat, valid=sparse)
        js = np.asarray(js)
        assert all(set(js[r, :3]) == {7, 123, 250} for r in range(17))
        assert np.array_equal(js[:, 3:], np.full((17, 7), -1))
        assert np.isinf(np.asarray(vs)[:, 3:]).all()


def test_fused_tie_break_recall_one_on_adversarial_duplicates(rng):
    """Property: on duplicate-distance inputs (every row repeated 32x ->
    tie classes of 32 identical distances) the partial-sort epilogue's
    recall@k == 1.0 against the id-tie-breaking oracle, and the ids are
    EXACTLY the oracle's — deterministic smallest-id-first ties, the
    stable lax.top_k order."""
    base = _grid(rng, (16, 8), -4, 4)
    y = np.repeat(base, 32, axis=0)  # 512 rows, massive tie classes
    x = _grid(rng, (20, 8), -4, 4)
    for metric in ("sqeuclidean", "inner_product"):
        for k in (1, 10, 100):
            vf, jf = scan_select_k(x, y, k, metric=metric, strategy="fused")
            d = _oracle(x, y, metric)
            order = np.argsort(d, axis=1, kind="stable")[:, :k]
            jf = np.asarray(jf)
            np.testing.assert_array_equal(jf, order)
            # recall@k vs the oracle set (redundant given exact ids,
            # stated separately because it is the acceptance property)
            recall = np.mean([
                len(set(jf[r]) & set(order[r])) / k for r in range(len(x))
            ])
            assert recall == 1.0
    # determinism: same inputs -> bit-identical outputs across calls
    v1, i1 = scan_select_k(x, y, 10, strategy="fused")
    v2, i2 = scan_select_k(x, y, 10, strategy="fused")
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


# -- the list kernel ----------------------------------------------------


def test_fused_list_topk_matches_oracle(rng):
    """Per-(chunk row, list) exact top-k straight from the kernel:
    values, slots, tie order, and +inf-masked invalid slots."""
    from raft_tpu.ops.fused_scan import fused_list_topk

    n_lists, L, rot, chunk, k = 5, 256, 24, 8, 16
    store = _grid(rng, (n_lists, L, rot))
    base = (store.astype(np.float32) ** 2).sum(2)[:, None, :]
    # invalidate a ragged tail per list (padded-slot semantics)
    for l in range(n_lists):
        base[l, 0, L - 1 - l * 13:] = np.inf
    qres = _grid(rng, (11, chunk, rot))
    lof = rng.integers(0, n_lists, 11).astype(np.int32)
    vals, slots = fused_list_topk(
        jnp.asarray(lof), jnp.asarray(qres), jnp.asarray(store),
        jnp.asarray(base), k, interpret=True,
    )
    vals, slots = np.asarray(vals), np.asarray(slots)
    assert vals.shape == (11, chunk, 128)  # kbuf = fused_kbuf(16)
    for c in range(11):
        d = base[lof[c], 0][None, :] - 2.0 * qres[c] @ store[lof[c]].T
        order = np.argsort(d, axis=1, kind="stable")[:, :k]
        np.testing.assert_array_equal(slots[c][:, :k], order)
        np.testing.assert_array_equal(
            vals[c][:, :k], np.take_along_axis(d, order, axis=1)
        )


def test_fused_list_topk_kbuf_contract():
    """A cached candidate-buffer width narrower than k must refuse
    loudly — the silent-truncation bug class the ivf_flat lazy store
    guards against."""
    from raft_tpu.ops.fused_scan import (
        FUSED_MAX_K, fused_kbuf, fused_list_topk,
    )

    assert fused_kbuf(1) == 128 and fused_kbuf(128) == 128
    assert fused_kbuf(129) == 256 and fused_kbuf(256) == 256
    with pytest.raises(ValueError, match="caps k"):
        fused_kbuf(FUSED_MAX_K + 1)
    lof = jnp.zeros((1,), jnp.int32)
    qres = jnp.zeros((1, 8, 16), jnp.float32)
    store = jnp.zeros((1, 128, 16), jnp.float32)
    base = jnp.zeros((1, 1, 128), jnp.float32)
    with pytest.raises(ValueError, match="cannot hold"):
        fused_list_topk(lof, qres, store, base, 200, kbuf=128,
                        interpret=True)


# -- dispatch contract --------------------------------------------------


def test_scan_dispatch_resolution(monkeypatch):
    """The tuned `select_k_strategy` winner promotes fused ONLY on a TPU
    backend where the kernel fits; explicit strategies always win; the
    fallback is the two-phase reference."""
    from raft_tpu.core import tuned
    from raft_tpu.core import config

    assert resolve_scan_strategy(1000, 32, 10, "fused") == "fused"
    assert resolve_scan_strategy(1000, 32, 10, "two_phase") == "two_phase"
    with pytest.raises(ValueError, match="strategy"):
        resolve_scan_strategy(1000, 32, 10, "warpsort")
    # no tuned winner -> reference path
    assert resolve_scan_strategy(1000, 32, 10, None) == "two_phase"
    monkeypatch.setitem(tuned._load(), "select_k_strategy", "fused")
    # CPU backend: the chip-measured winner must not flip interpret mode
    assert resolve_scan_strategy(1000, 32, 10, None) == "two_phase"
    monkeypatch.setattr(config, "is_tpu_backend", lambda: True)
    assert resolve_scan_strategy(1000, 32, 10, None) == "fused"
    # a geometry past the kernel's envelope falls back even when tuned
    assert resolve_scan_strategy(1000, 32, 500, None) == "two_phase"


def test_scan_select_k_validation(rng):
    x = _grid(rng, (4, 8))
    y = _grid(rng, (50, 8))
    with pytest.raises(ValueError, match="k="):
        scan_select_k(x, y, 51)
    with pytest.raises(ValueError, match="metrics"):
        scan_select_k(x, y, 5, metric="canberra", strategy="fused")
    with pytest.raises(ValueError, match="caps k|envelope"):
        scan_select_k(x, _grid(rng, (600, 8)), 500, strategy="fused")
    # unsupported metrics still work through the materializing path
    v, i = scan_select_k(x, y, 5, metric="canberra")
    assert np.asarray(v).shape == (4, 5)


def test_select_k_matrix_strategy_promotion(monkeypatch, rng):
    """The tuned `select_k_strategy` key steers the MATRIX-input auto
    dispatch too ("topk"/"two_phase" forcing), and explicit strategies
    stay exact."""
    from raft_tpu.core import tuned

    x = rng.random((3, 70000), dtype=np.float32)
    want_v, want_i = select_k(x, 9, strategy="topk")
    for forced in ("topk", "two_phase"):
        monkeypatch.setitem(tuned._load(), "select_k_strategy", forced)
        jax.clear_caches()  # the forced strategy is read at trace time
        try:
            v, i = select_k(x, 9)
        finally:
            tuned.reload()
            jax.clear_caches()
        np.testing.assert_array_equal(np.asarray(i), np.asarray(want_i))
        np.testing.assert_array_equal(np.asarray(v), np.asarray(want_v))


# -- consumers ----------------------------------------------------------


def test_brute_force_fused_engine_bit_agrees(rng):
    """knn(engine="pallas") is a thin wrapper over the fused dispatch:
    on bf16-exact data it must bit-agree with the tiled engine, and the
    "fused" spelling is the same engine."""
    data = _grid(rng, (2000, 24))
    q = _grid(rng, (31, 24))
    dt, it = brute_force.knn(data, q, 10)
    dp, ip_ = brute_force.knn(data, q, 10, engine="pallas")
    np.testing.assert_array_equal(np.asarray(it), np.asarray(ip_))
    np.testing.assert_array_equal(np.asarray(dt), np.asarray(dp))
    df, if_ = brute_force.knn(data, q, 10, engine="fused")
    np.testing.assert_array_equal(np.asarray(if_), np.asarray(ip_))


def test_brute_force_fused_prefilter(rng):
    data = _grid(rng, (800, 16))
    q = _grid(rng, (9, 16))
    keep = rng.random(800) < 0.5
    df, jf = brute_force.knn(data, q, 8, engine="pallas", prefilter=keep)
    dr, jr = brute_force.knn(data, q, 8, prefilter=keep)
    jf, jr = np.asarray(jf), np.asarray(jr)
    np.testing.assert_array_equal(np.asarray(df), np.asarray(dr))
    # ids agree wherever the filter left a survivor
    live = jr >= 0
    np.testing.assert_array_equal(jf[live], jr[live])
    assert not np.isin(jf[jf >= 0], np.where(~keep)[0]).any()


def test_refine_fused_bit_agrees(rng):
    """The fused exact-distance rerank (refine strategy="fused") must
    bit-agree with the materializing reference on bf16-exact data —
    including skipped (-1) candidate ids."""
    data = _grid(rng, (1500, 24))
    q = _grid(rng, (40, 24))
    cand = rng.integers(0, 1500, (40, 37)).astype(np.int64)
    cand[5, 10:] = -1
    for metric in ("sqeuclidean", "euclidean", "inner_product"):
        vr, jr = refine(data, q, cand, 8, metric=metric,
                        strategy="two_phase")
        vf, jf = refine(data, q, cand, 8, metric=metric, strategy="fused")
        np.testing.assert_array_equal(np.asarray(vf), np.asarray(vr))
        np.testing.assert_array_equal(np.asarray(jf), np.asarray(jr))


def test_refine_fused_offset_data_matches_bf16_reference(rng):
    """Regression: the fused rerank must derive |v|^2 and |q|^2 from the
    SAME bf16-rounded rows the kernel dots. Mixing unrounded f32 norms
    with bf16 dots cancels wrong on data with a large common offset
    (|v|^2 - 2<q,v> is a difference of huge near-equal terms) — caught
    in review with ~0% id agreement on offset-heavy embeddings."""
    data = (0.01 * rng.random((2000, 32)) + 100.0).astype(np.float32)
    q = (data[:30] + 1e-3 * rng.random((30, 32))).astype(np.float32)
    cand = rng.integers(0, 2000, (30, 40)).astype(np.int64)
    cand[:, 0] = np.arange(30)  # the near-duplicate row is a candidate
    vf, jf = refine(data, q, cand, 5, strategy="fused")
    # the bf16-rounded reference: exact rerank over bf16-rounded rows
    vr, jr = refine(data.astype(jnp.bfloat16).astype(np.float32),
                    q.astype(jnp.bfloat16).astype(np.float32),
                    cand, 5, strategy="two_phase")
    jf, jr = np.asarray(jf), np.asarray(jr)
    agree = np.mean([len(set(jf[r]) & set(jr[r])) / 5 for r in range(30)])
    assert agree >= 0.95, f"fused rerank diverged on offset data: {agree}"
    # the near-duplicate must rank first with a near-zero distance
    assert np.array_equal(jf[:, 0], np.arange(30))
    assert np.asarray(vf)[:, 0].max() < 1.0


def test_refine_fused_envelope_guard(rng):
    """An explicit fused rerank past the kernel's VMEM envelope must
    refuse loudly (auto falls back silently) — the same contract as
    every other fused call site."""
    data = _grid(rng, (300, 2048))
    q = _grid(rng, (2, 2048))
    cand = rng.integers(0, 300, (2, 5000)).astype(np.int64)
    with pytest.raises(ValueError, match="envelope"):
        refine(data, q, cand, 5, strategy="fused")
    v, i = refine(data, q, cand, 5)  # auto: falls back to two_phase
    assert np.asarray(v).shape == (2, 5)


def test_ivf_pq_fused_trim_matches_exact_trim(rng):
    """trim_engine="fused" == trim_engine="exact" candidates modulo the
    bf16 scoring round: on an integer dataset the recon8 scores embed in
    bf16 and the two trims must agree exactly."""
    from raft_tpu.neighbors import ivf_pq

    data = _grid(rng, (4000, 32))
    q = _grid(rng, (16, 32))
    idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=16, kmeans_n_iters=4, pq_dim=16), data
    )
    d_e, i_e = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, score_mode="recon8_list",
                            trim_engine="exact"), idx, q, 10
    )
    d_f, i_f = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, trim_engine="fused"), idx, q, 10
    )
    i_e, i_f = np.asarray(i_e), np.asarray(i_f)
    overlap = np.mean(
        [len(set(i_e[r]) & set(i_f[r])) / 10 for r in range(len(q))]
    )
    assert overlap >= 0.9, overlap
    assert np.all(np.diff(np.asarray(d_f), axis=1) >= -1e-4)
    # ISSUE 11: score_dtype="int8" no longer refuses — it routes through
    # the dispatch layer's fused_int8 strategy (deep agreement suite in
    # tests/test_fused_int_scan.py; here just the contract change)
    d_i, i_i = ivf_pq.search(
        ivf_pq.SearchParams(trim_engine="fused", score_dtype="int8"),
        idx, q, 10,
    )
    assert np.asarray(d_i).shape == (len(q), 10)
