"""Live-mutation suite: crash-atomic upsert/delete with tombstone-aware
scans and zero-dip serving (`neighbors.mutation`, `comms.mnmg_mutation`,
the serve-layer `MutationFeed` swap-in, and `jobs.resumable_mutate`).

Four layers of drills:

- **Semantics** (fast): deletes equal an exclusion prefilter
  bit-for-bit on every index family; tombstoned ids never surface;
  unaffected queries stay bit-identical; upserts retire every prior
  live row for an id; `ensure_append_slack` reserves tail slots so
  steady-state churn never re-pads; `compact`/`rebalance` drop the mask
  without changing a single answer.
- **Crash-atomicity** (fast, in-process): `MutationLog` torn-line /
  CRC-rot / seq-gap prefix semantics; `Mutator` cold-resume and
  re-issued-sequence dedupe converge bit-identically; an externally
  truncated log is a typed `MutationLogError` refuse.
- **Serving** (fast): committed batches drain BETWEEN device batches —
  coverage never dips below 1.0, in-flight batches keep the old index
  object, and the MNMG path defers while the health mask is degraded
  (replica failover keeps serving meanwhile) then applies coherently
  across primaries + replica mirrors after the heal.
- **Kill-and-resume bit-identity** (slow, child processes): a seeded
  kill_rank fault at ``mutation.log.commit`` SIGKILLs a real child
  (`tests/_mutation_crash_worker.py`) mid-upsert and mid-delete;
  re-running the same command converges on a committed checkpoint
  BYTE-IDENTICAL to an uninterrupted run, for all three index kinds.

The three ``mutation.*`` fault sites drilled here are pinned against
`core.faults.FAULT_SITES` by the drift test in test_raftlint.py.
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from raft_tpu import jobs, obs, serve
from raft_tpu.core import faults
from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import ivf_flat, ivf_pq, ivf_rabitq, mutation
from raft_tpu.obs import report as obs_report
from raft_tpu.random import make_blobs

SEED = int(os.environ.get(faults.ENV_SEED, "1234"))
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_mutation_crash_worker.py")

KINDS = ("ivf_flat", "ivf_pq", "ivf_rabitq")


@pytest.fixture
def obs_on():
    obs.enable()
    obs.reset()
    yield
    obs.reset()
    obs.disable()


@pytest.fixture(scope="module")
def blobs():
    data, _ = make_blobs(512, 16, n_clusters=6, cluster_std=0.4, seed=13)
    return np.asarray(data)


def _build(kind, data, **over):
    """One tiny deterministic index per family. ivf_rabitq uses
    store_dataset=False so in-memory and reloaded indexes rank the same
    way (the raw-row store is never serialized)."""
    if kind == "ivf_flat":
        p = ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=3, **over)
        return ivf_flat, ivf_flat.build(p, data)
    if kind == "ivf_pq":
        p = ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=3,
                               kmeans_trainset_fraction=1.0, **over)
        return ivf_pq, ivf_pq.build(p, data)
    p = ivf_rabitq.IndexParams(n_lists=8, kmeans_n_iters=3,
                               store_dataset=False, **over)
    return ivf_rabitq, ivf_rabitq.build(p, np.asarray(data, np.float32))


def _search(mod, index, q, k=10, prefilter=None):
    kw = {} if prefilter is None else {"prefilter": prefilter}
    v, i = mod.search(mod.SearchParams(n_probes=4), index, q, k, **kw)
    return np.asarray(v), np.asarray(i)


def _queries(dim=16, n=16, seed=7):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, dim)).astype(np.float32)


# -- tombstone semantics ------------------------------------------------

@pytest.mark.parametrize("kind", KINDS)
def test_delete_equals_exclusion_prefilter(blobs, kind):
    """THE end-to-end tombstone contract: deleting ids answers
    bit-identically to searching the unmutated index under a prefilter
    that excludes them — every engine, merge and rerank treats a dead
    slot exactly like a filtered-out row."""
    mod, idx = _build(kind, blobs)
    q = _queries()
    victims = np.array([5, 17, 40, 41, 300])
    out = mutation.delete(idx, victims)
    want_v, want_i = _search(
        mod, idx, q, prefilter=Bitset.excluding(idx.id_bound, victims))
    got_v, got_i = _search(mod, out, q)
    np.testing.assert_array_equal(want_i, got_i)
    np.testing.assert_array_equal(want_v, got_v)
    assert not np.isin(got_i, victims).any()
    # the input object is untouched (serve keeps scanning it zero-dip)
    assert idx.tombstones is None
    assert mutation.live_rows(out) == mutation.live_rows(idx) - victims.size


@pytest.mark.parametrize("kind", KINDS)
def test_delete_unaffected_queries_bit_identical(blobs, kind):
    mod, idx = _build(kind, blobs)
    q = _queries(n=32)
    pre_v, pre_i = _search(mod, idx, q)
    victims = np.unique(pre_i[0])[:4]  # ids the FIRST query returns
    out = mutation.delete(idx, victims)
    post_v, post_i = _search(mod, out, q)
    assert not np.isin(post_i, victims).any()
    untouched = ~np.isin(pre_i, victims).any(axis=1)
    assert untouched.sum() > 0, "drill needs at least one unaffected query"
    np.testing.assert_array_equal(pre_i[untouched], post_i[untouched])
    np.testing.assert_array_equal(pre_v[untouched], post_v[untouched])


def test_delete_is_idempotent_and_ignores_unknown_ids(blobs):
    _, idx = _build("ivf_flat", blobs)
    out, n = mutation.tombstone(idx, [3, 3, 10_000, -5])
    assert n == 1
    again, n2 = mutation.tombstone(out, [3])
    assert n2 == 0 and again is out  # no-op returns the same object


@pytest.mark.parametrize("kind", KINDS)
def test_upsert_retires_old_rows(blobs, kind):
    """An upserted id must be findable AT ITS NEW LOCATION and never at
    the old one: the query sitting on the old vector no longer returns
    the id, the query on the new vector ranks it first (flat) or within
    the top-k (quantized)."""
    mod, idx = _build(kind, blobs)
    victim = 7
    old_vec = blobs[victim]
    new_vec = (old_vec + 40.0).astype(np.float32)  # far from every blob
    out = mutation.upsert(idx, new_vec[None], np.array([victim]))
    _, i_old = _search(mod, out, old_vec[None].astype(np.float32))
    _, i_new = _search(mod, out, new_vec[None])
    # the old location's neighborhood still answers, minus the victim
    assert victim not in i_old[0][:5]
    if kind == "ivf_flat":
        assert victim == i_new[0][0]
    else:  # quantized rankers: within the top-k is the contract
        assert victim in i_new[0]
    assert mutation.live_rows(out) == mutation.live_rows(idx)


def test_upsert_fresh_ids_from_id_bound(blobs):
    _, idx = _build("ivf_flat", blobs)
    base = int(idx.id_bound)
    rng = np.random.default_rng(3)
    out = mutation.upsert(idx, rng.standard_normal((3, 16)).astype(np.float32))
    sid = np.asarray(out.source_ids)
    assert set(sid[-3:]) == {base, base + 1, base + 2}
    assert mutation.live_rows(out) == mutation.live_rows(idx) + 3


def test_upsert_id_count_mismatch_raises(blobs):
    _, idx = _build("ivf_flat", blobs)
    with pytest.raises(ValueError, match="ids"):
        mutation.upsert(idx, np.zeros((2, 16), np.float32), np.array([1]))


# -- append regions -----------------------------------------------------

@pytest.mark.parametrize("kind", KINDS)
def test_ensure_append_slack_reserves_tail_slots(blobs, kind):
    mod, idx = _build(kind, blobs)
    q = _queries()
    pre_v, pre_i = _search(mod, idx, q)
    wide = mutation.ensure_append_slack(idx, 64)
    width = int(np.asarray(wide.slot_rows).shape[1])
    assert width >= int(np.asarray(idx.list_sizes).max()) + 64
    assert width % mutation.GROUP == 0
    assert mutation.ensure_append_slack(wide, 64) is wide  # idempotent
    got_v, got_i = _search(mod, wide, q)
    np.testing.assert_array_equal(pre_i, got_i)
    np.testing.assert_array_equal(pre_v, got_v)
    # steady-state churn scatters into the reserve: no re-pad
    rng = np.random.default_rng(5)
    out = mutation.upsert(wide, rng.standard_normal((8, 16)).astype(np.float32))
    assert int(np.asarray(out.slot_rows).shape[1]) == width


def test_ensure_append_slack_rejects_negative(blobs):
    _, idx = _build("ivf_flat", blobs)
    with pytest.raises(ValueError, match="slack"):
        mutation.ensure_append_slack(idx, -1)


# -- rebalance / compaction ---------------------------------------------

@pytest.mark.parametrize("kind", KINDS)
def test_compact_drops_tombstones_without_changing_answers(blobs, kind):
    mod, idx = _build(kind, blobs)
    q = _queries(n=24)
    victims = np.arange(0, 60, 3)
    dead = mutation.delete(idx, victims)
    pre_v, pre_i = _search(mod, dead, q)
    packed = mutation.compact(dead)
    assert packed.tombstones is None
    assert mutation.live_rows(packed) == mutation.live_rows(dead)
    assert int(np.asarray(packed.slot_rows).shape[1]) <= \
        int(np.asarray(dead.slot_rows).shape[1])
    got_v, got_i = _search(mod, packed, q)
    np.testing.assert_array_equal(pre_i, got_i)
    np.testing.assert_array_equal(pre_v, got_v)


def test_rebalance_threshold_gates_compaction(blobs):
    _, idx = _build("ivf_flat", blobs)
    dead = mutation.delete(idx, np.arange(4))  # ~0.8% dead
    same, did = mutation.rebalance(dead, min_dead_frac=0.5)
    assert not did and same is dead
    out, did = mutation.rebalance(dead, min_dead_frac=0.001)
    assert did and out.tombstones is None
    clean, did = mutation.rebalance(idx)  # nothing dead -> no-op
    assert not did and clean is idx


# -- fault sites (pinned against FAULT_SITES by the raftlint drift test)

def test_tombstone_fault_leaves_state_untouched(blobs):
    """``mutation.tombstone`` raises BEFORE any state changes: the
    caller retries and the index is exactly as it was."""
    _, idx = _build("ivf_flat", blobs)
    plan = faults.FaultPlan(
        [faults.Fault(kind="flaky_bootstrap", site="mutation.tombstone",
                      count=1)],
        seed=SEED,
    )
    with plan.install():
        with pytest.raises(faults.FaultInjected):
            mutation.delete(idx, [3])
        assert idx.tombstones is None  # untouched
        out = mutation.delete(idx, [3])  # retry lands
    assert int(out.n_tombstones) == 1


def test_rebalance_fault_retried_to_success(blobs):
    _, idx = _build("ivf_flat", blobs)
    dead = mutation.delete(idx, np.arange(8))
    plan = faults.FaultPlan(
        [faults.Fault(kind="flaky_bootstrap", site="mutation.rebalance",
                      count=1)],
        seed=SEED,
    )
    with plan.install():
        with pytest.raises(faults.FaultInjected):
            mutation.rebalance(dead)
        out, did = mutation.rebalance(dead)
    assert did and out.tombstones is None


def _kill_plan(count: int) -> faults.Fault:
    """The SIGKILL fault the child worker arms: the count-th visit of
    ``mutation.log.commit`` kills the process. Sites fire after EVERY
    log append and EVERY checkpoint commit, so count=1 dies mid-upsert
    (log ahead of the checkpoint) and count=2 dies mid-delete."""
    return faults.Fault(kind="kill_rank", site="mutation.log.commit",
                        count=count)


# -- mutation log -------------------------------------------------------

def test_mutation_log_roundtrip_and_torn_tail(tmp_path):
    log = mutation.MutationLog(str(tmp_path))
    log.append("upsert", 0, "mut_000000.ckpt")
    log.append("delete", 1, "mut_000001.ckpt")
    assert [e["op"] for e in log.entries()] == ["upsert", "delete"]
    # a torn final line (the kill-mid-append artifact) is invisible...
    with open(log.path, "ab") as fh:
        fh.write(b'{"v": 1, "seq": 2, "op": "delete"')
    assert len(log.entries()) == 2
    # ...and the next append terminates it without corrupting itself
    log.append("rebalance", 2, None)
    entries = log.entries()
    assert len(entries) == 3 and entries[2]["op"] == "rebalance"


def test_mutation_log_crc_rot_ends_prefix(tmp_path):
    log = mutation.MutationLog(str(tmp_path))
    for seq in range(3):
        log.append("delete", seq, f"mut_{seq:06d}.ckpt")
    lines = open(log.path, "rb").read().splitlines(keepends=True)
    rotted = lines[1].replace(b'"op": "delete"', b'"op": "upsert"')
    with open(log.path, "wb") as fh:
        fh.writelines([lines[0], rotted, lines[2]])
    # the rotted line ends the log THERE: seq 2 cannot be trusted even
    # though its own CRC is fine (the dense-prefix rule)
    assert [e["seq"] for e in log.entries()] == [0]


def test_mutation_log_seq_gap_ends_prefix(tmp_path):
    log = mutation.MutationLog(str(tmp_path))
    log.append("delete", 0, "mut_000000.ckpt")
    log.append("delete", 2, "mut_000002.ckpt")  # gap: seq 1 missing
    assert [e["seq"] for e in log.entries()] == [0]


# -- Mutator: crash-atomic protocol (in-process) ------------------------

def _scripted(mut, dim=16, seed=11):
    """A deterministic mixed batch sequence (pure function of the
    seed): upserts over build ids, fresh inserts, deletes including a
    just-upserted id, one logged rebalance."""
    rng = np.random.default_rng(seed)
    mut.upsert(rng.standard_normal((4, dim)).astype(np.float32),
               np.array([2, 3, 600, 601]))
    mut.delete(np.array([3, 10, 11]))
    mut.rebalance()
    mut.upsert(rng.standard_normal((2, dim)).astype(np.float32),
               np.array([3, 602]))
    mut.delete(np.array([600]))


@pytest.mark.parametrize("kind", KINDS)
def test_mutator_cold_resume_bit_identical(tmp_path, blobs, kind):
    mod, idx = _build(kind, blobs)
    q = _queries()
    mut = mutation.Mutator(str(tmp_path / "m"), idx, ckpt_every=2, slack=8)
    _scripted(mut)
    mut.commit()
    want_v, want_i = _search(mod, mut.index, q)
    # cold resume: no index argument, just the kind — the committed
    # checkpoint + log tail are the whole state
    again = mutation.Mutator(str(tmp_path / "m"), kind=kind)
    assert again.applied == mut.applied
    got_v, got_i = _search(mod, again.index, q)
    np.testing.assert_array_equal(want_i, got_i)
    np.testing.assert_array_equal(want_v, got_v)


def test_mutator_reissued_sequence_dedupes(tmp_path, blobs):
    """The kill-anywhere convergence model: a re-run driver re-issues
    its WHOLE sequence against a resumed mutator; already-logged seqs
    skip, the state converges identically."""
    mod, idx = _build("ivf_flat", blobs)
    q = _queries()
    mut = mutation.Mutator(str(tmp_path / "m"), idx, ckpt_every=2, slack=8)
    _scripted(mut)
    mut.commit()
    want_v, want_i = _search(mod, mut.index, q)
    again = mutation.Mutator(str(tmp_path / "m"), idx, ckpt_every=2, slack=8)
    _scripted(again)  # every call dedupes by seq
    again.commit()
    assert again.applied == mut.applied
    got_v, got_i = _search(mod, again.index, q)
    np.testing.assert_array_equal(want_i, got_i)
    np.testing.assert_array_equal(want_v, got_v)


def test_mutator_refuses_externally_truncated_log(tmp_path, blobs):
    _, idx = _build("ivf_flat", blobs)
    mut = mutation.Mutator(str(tmp_path / "m"), idx, ckpt_every=1)
    mut.delete(np.array([1]))
    mut.delete(np.array([2]))
    os.remove(mut.log.path)  # external damage: checkpoint is now ahead
    with pytest.raises(mutation.MutationLogError, match="truncated"):
        mutation.Mutator(str(tmp_path / "m"), kind="ivf_flat")


def test_mutator_requires_index_or_checkpoint(tmp_path):
    with pytest.raises(ValueError, match="checkpoint"):
        mutation.Mutator(str(tmp_path / "m"), kind="ivf_flat")


# -- serialization: mutation state rides the checkpoint -----------------

@pytest.mark.parametrize("kind", KINDS)
def test_save_load_roundtrip_carries_mutation_state(tmp_path, blobs, kind):
    mod, idx = _build(kind, blobs)
    out = mutation.delete(idx, np.arange(10))
    out = mutation.ensure_append_slack(out, 32)
    out.mut_cursor = 5
    path = str(tmp_path / "m.ckpt")
    mod.save(path, out)
    back = mod.load(path)
    assert int(back.mut_cursor) == 5
    assert int(back.append_slack) == 32
    np.testing.assert_array_equal(
        np.asarray(out.tombstones), np.asarray(back.tombstones).astype(bool))
    q = _queries()
    want_v, want_i = _search(mod, out, q)
    got_v, got_i = _search(mod, back, q)
    np.testing.assert_array_equal(want_i, got_i)
    np.testing.assert_array_equal(want_v, got_v)


def test_unmutated_checkpoint_omits_mutation_fields(tmp_path, blobs):
    """An unmutated index serializes WITHOUT the mutation fields — the
    bytes stay what the pre-mutation writer emitted (modulo version),
    and absent-on-load means all-live/cursor-0/no-slack."""
    from raft_tpu.core.serialize import read_ckpt

    _, idx = _build("ivf_flat", blobs)
    path = str(tmp_path / "clean.ckpt")
    ivf_flat.save(path, idx)
    arrays, meta = read_ckpt(path, "ivf_flat")
    assert "tombstones" not in arrays
    back = ivf_flat.load(path)
    assert back.tombstones is None
    assert int(back.mut_cursor) == 0 and int(back.append_slack) == 0


# -- serve: zero-dip swap-in --------------------------------------------

def test_serve_zero_dip_single_chip(blobs):
    """The serving drill: committed batches drain BETWEEN device
    batches. The batch in flight when a delete is published still
    serves the OLD index (its results are untouched), the next batch
    sees the mutation, coverage never leaves 1.0, and queries whose
    answers don't involve the victims stay bit-identical."""
    mod, idx = _build("ivf_flat", blobs)
    sp = ivf_flat.SearchParams(n_probes=4, engine="query")
    server = serve.SearchServer(
        idx, serve.ServerConfig(buckets=(16,)), search_params=sp)
    feed = mutation.MutationFeed()
    server.attach_mutations(feed)
    q = _queries()

    fut = server.submit(q, k=10)
    assert server.step() == 1
    pre = fut.result(timeout=5.0)
    assert pre.coverage == 1.0
    victims = np.unique(pre.ids[0])[:3]

    feed.publish(("delete", victims))
    fut = server.submit(q, k=10)
    assert server.step() == 1
    mid = fut.result(timeout=5.0)
    # this batch was collected before the between-batches drain: it
    # served the old object, bit-identically — THAT is zero-dip
    np.testing.assert_array_equal(pre.ids, mid.ids)
    np.testing.assert_array_equal(pre.values, mid.values)
    assert mid.coverage == 1.0
    assert server.searcher.index is not idx  # swap landed after

    fut = server.submit(q, k=10)
    assert server.step() == 1
    post = fut.result(timeout=5.0)
    assert post.coverage == 1.0
    assert not np.isin(post.ids, victims).any()
    untouched = ~np.isin(pre.ids, victims).any(axis=1)
    assert untouched.sum() > 0
    np.testing.assert_array_equal(pre.ids[untouched], post.ids[untouched])
    np.testing.assert_array_equal(pre.values[untouched], post.values[untouched])
    # the original index object never mutated under the server's feet
    assert idx.tombstones is None


def test_serve_upsert_and_rebalance_through_feed(blobs):
    mod, idx = _build("ivf_flat", blobs)
    sp = ivf_flat.SearchParams(n_probes=4, engine="query")
    server = serve.SearchServer(
        idx, serve.ServerConfig(buckets=(16,)), search_params=sp)
    feed = mutation.MutationFeed()
    server.attach_mutations(feed)
    far = (blobs[3] + 40.0).astype(np.float32)
    feed.publish(("upsert", far[None], np.array([3])))
    feed.publish(("delete", np.array([5])))
    feed.publish(("rebalance",))
    reply = server.search(np.zeros((1, 16), np.float32), k=5, timeout=5.0)
    assert reply.coverage == 1.0  # batch 1 served the old index
    reply = server.search(far[None], k=5, timeout=5.0)
    assert reply.ids[0][0] == 3
    live = server.searcher.index
    assert live.tombstones is None  # rebalance applied
    sr = np.asarray(live.slot_rows)
    assert 5 not in np.asarray(live.source_ids)[sr[sr >= 0]]


def test_feed_rejects_unknown_batch():
    feed = mutation.MutationFeed()
    with pytest.raises(ValueError, match="unknown"):
        feed.publish(("drop_table",))
    feed.publish(("rebalance",))
    assert feed.drain() == [("rebalance",)]
    assert feed.drain() == []


# -- MNMG: rank-local mutation + zero-dip + degraded deferral -----------

WORLD = 4


@pytest.fixture(scope="module")
def comms4():
    from raft_tpu.comms import Comms

    return Comms(n_devices=WORLD)


@pytest.fixture(scope="module")
def dist_flat_r2(comms4, blobs):
    from raft_tpu.comms import mnmg

    return mnmg.ivf_flat_build(
        comms4, ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=3), blobs,
        replication=2)


def _mnmg_search_ids(index, q, k=10, health=None):
    from raft_tpu.comms import mnmg

    out = mnmg.ivf_flat_search(index, q, k, n_probes=4, engine="list",
                               query_mode="replicated", health=health)
    if hasattr(out, "coverage"):  # DegradedSearchResult under a mask
        return (np.asarray(out.values), np.asarray(out.ids),
                float(out.coverage))
    v, i = out
    return np.asarray(v), np.asarray(i), 1.0


def test_mnmg_delete_masks_every_copy(comms4, dist_flat_r2, blobs):
    from raft_tpu.comms import mnmg_mutation

    q = _queries()
    _, pre_i, _ = _mnmg_search_ids(dist_flat_r2, q)
    victims = np.unique(pre_i[0])[:4]
    out = mnmg_mutation.delete(dist_flat_r2, victims)
    _, post_i, cov = _mnmg_search_ids(out, q)
    assert cov == 1.0
    assert not np.isin(post_i, victims).any()
    untouched = ~np.isin(pre_i, victims).any(axis=1)
    assert untouched.sum() > 0
    np.testing.assert_array_equal(pre_i[untouched], post_i[untouched])
    # every copy is coherent: host mirrors + the replica mirror tables
    assert not np.isin(np.asarray(out.host_gids), victims).any()
    assert not np.isin(
        np.asarray(out.replicas.tables["slot_gids"]), victims).any()
    # the input index (in-flight traffic's object) is untouched
    assert np.isin(np.asarray(dist_flat_r2.host_gids), victims).any()


def test_mnmg_deleted_ids_stay_dead_under_failover(comms4, dist_flat_r2):
    """A tombstoned id must not resurrect when a rank dies and its
    replica copy serves: the mirrors were masked too."""
    from raft_tpu.comms import mnmg_mutation
    from raft_tpu.comms.resilience import RankHealth

    q = _queries()
    _, pre_i, _ = _mnmg_search_ids(dist_flat_r2, q)
    victims = np.unique(pre_i)[:6]
    out = mnmg_mutation.delete(dist_flat_r2, victims)
    for dead_rank in range(WORLD):
        health = RankHealth.all_healthy(WORLD).mark_unhealthy(dead_rank)
        _, ids, cov = _mnmg_search_ids(out, q, health=health)
        assert cov == 1.0  # replica failover is lossless
        assert not np.isin(ids, victims).any()


def test_mnmg_upsert_remaps_tail_gids(comms4, dist_flat_r2, blobs):
    from raft_tpu.comms import mnmg_mutation

    far = (blobs[11] + 40.0).astype(np.float32)
    out = mnmg_mutation.upsert(dist_flat_r2, "ivf_flat", far[None],
                               np.array([11]))
    _, i_new, cov = _mnmg_search_ids(out, far[None])
    assert cov == 1.0 and i_new[0][0] == 11
    _, i_old, _ = _mnmg_search_ids(out, blobs[11][None].astype(np.float32))
    assert 11 not in i_old[0][:5]  # the old row is dead everywhere


def test_mnmg_rabitq_upsert_refused_loudly(comms4, blobs):
    from raft_tpu.comms import mnmg, mnmg_mutation

    idx = mnmg.ivf_rabitq_build(
        comms4, ivf_rabitq.IndexParams(n_lists=8, kmeans_n_iters=3),
        np.asarray(blobs, np.float32))
    with pytest.raises(NotImplementedError, match="distributed extend"):
        mnmg_mutation.upsert(idx, "ivf_rabitq", blobs[:1], np.array([0]))
    # deletes still work: they are pure gid transforms
    out = mnmg_mutation.delete(idx, np.array([0]))
    assert not (np.asarray(out.host_gids) == 0).any()


def test_mnmg_serve_defers_mutations_while_degraded(comms4, dist_flat_r2):
    """The coherence gate: while the health mask is degraded the feed
    stays queued (failover keeps serving at coverage 1.0), and the
    batches apply — primaries AND mirrors — once the mask heals."""
    from raft_tpu.comms.resilience import RankHealth

    degraded = RankHealth.all_healthy(WORLD).mark_unhealthy(1)
    server = serve.SearchServer(
        dist_flat_r2, serve.ServerConfig(buckets=(16,)),
        health=degraded, n_probes=4, engine="list", auto_heal=False)
    feed = mutation.MutationFeed()
    server.attach_mutations(feed)
    q = _queries()

    fut = server.submit(q, k=10)
    assert server.step() == 1
    pre = fut.result(timeout=10.0)
    assert pre.coverage == 1.0  # replicated failover, not a dip
    victims = np.unique(pre.ids[0])[:3]
    feed.publish(("delete", victims))

    fut = server.submit(q, k=10)
    assert server.step() == 1
    fut.result(timeout=10.0)
    # degraded -> deferred: nothing drained, nothing swapped
    assert server.searcher.index is dist_flat_r2
    pending = feed.drain()
    assert len(pending) == 1  # the batch is still queued, not dropped
    feed.publish(pending[0])  # put the peeked batch back

    server.set_health(RankHealth.all_healthy(WORLD))
    fut = server.submit(q, k=10)
    assert server.step() == 1
    fut.result(timeout=10.0)
    assert server.searcher.index is not dist_flat_r2  # applied post-heal

    fut = server.submit(q, k=10)
    assert server.step() == 1
    post = fut.result(timeout=10.0)
    assert post.coverage == 1.0
    assert not np.isin(post.ids, victims).any()
    untouched = ~np.isin(pre.ids, victims).any(axis=1)
    np.testing.assert_array_equal(pre.ids[untouched], post.ids[untouched])


# -- jobs: resumable mutation stage -------------------------------------

def test_resumable_mutate_flaky_reentry_converges(tmp_path, blobs):
    """A transient ``mutation.tombstone`` fault aborts the stage
    mid-sequence; re-entering with the SAME ops list resumes through
    the log and converges bit-identically to an uninterrupted run."""
    mod, idx = _build("ivf_flat", blobs)
    rng = np.random.default_rng(17)
    ops = [
        ("upsert", rng.standard_normal((4, 16)).astype(np.float32),
         np.array([2, 3, 700, 701])),
        ("delete", np.array([3, 10])),
        ("rebalance",),
        ("upsert", rng.standard_normal((2, 16)).astype(np.float32),
         np.array([10, 702])),
    ]
    q = _queries()
    ref, _ = jobs.resumable_mutate(
        "ivf_flat", idx, ops, scratch=str(tmp_path / "ref"), ckpt_every=2)
    want_v, want_i = _search(mod, ref, q)

    plan = faults.FaultPlan(
        [faults.Fault(kind="flaky_bootstrap", site="mutation.tombstone",
                      count=1)],
        seed=SEED,
    )
    scratch = str(tmp_path / "chaos")
    with plan.install():
        with pytest.raises(faults.FaultInjected):
            jobs.resumable_mutate("ivf_flat", idx, ops, scratch=scratch,
                                  ckpt_every=2)
        got, stats = jobs.resumable_mutate(  # the supervised retry
            "ivf_flat", idx, ops, scratch=scratch, ckpt_every=2)
    assert stats["resumed_at"] > 0, "the retry must re-enter, not redo"
    assert stats["applied"] == len(ops)
    got_v, got_i = _search(mod, got, q)
    np.testing.assert_array_equal(want_i, got_i)
    np.testing.assert_array_equal(want_v, got_v)


def test_resumable_mutate_rebalance_only_is_compaction_stage(tmp_path, blobs):
    _, idx = _build("ivf_flat", blobs)
    dead = mutation.delete(idx, np.arange(12))
    out, stats = jobs.resumable_mutate(
        "ivf_flat", dead, [("rebalance",)], scratch=str(tmp_path / "s"))
    assert out.tombstones is None
    assert stats["tombstones"] == 0
    assert stats["live_rows"] == mutation.live_rows(dead)


# -- accounting: counters, timeline, truthful row counts ----------------

def test_obs_counters_and_timeline(blobs, obs_on):
    mod, idx = _build("ivf_flat", blobs)
    rng = np.random.default_rng(23)
    out = mutation.upsert(idx, rng.standard_normal((4, 16)).astype(np.float32),
                          np.array([1, 2, 800, 801]))
    out = mutation.delete(out, np.array([5, 6]))
    out, _ = mutation.rebalance(out)
    assert obs.counter("mutation.upserts").value == 4
    assert obs.counter("mutation.tombstones").value == 4  # 2 upserts + 2
    assert obs.counter("mutation.rebalances").value == 1
    snap = obs.snapshot()
    ops = [e.get("op") for e in snap["events"] if e["kind"] == "mutation"]
    # the upsert's internal retire emits its own delete event first
    assert ops == ["delete", "upsert", "delete", "rebalance"]
    out_txt = obs_report.render(snap)
    assert "mutation" in out_txt and "op=rebalance" in out_txt


def test_live_rows_is_truthful(blobs):
    """`live_rows` charges live rows only — superseded upsert versions
    and tombstones never inflate it (`index.size` does count them)."""
    _, idx = _build("ivf_flat", blobs)
    n0 = mutation.live_rows(idx)
    rng = np.random.default_rng(29)
    out = mutation.upsert(idx, rng.standard_normal((3, 16)).astype(np.float32),
                          np.array([1, 2, 3]))
    assert mutation.live_rows(out) == n0          # upsert: net zero
    assert int(out.size) == n0 + 3                # raw slots grew
    out = mutation.delete(out, np.array([1, 9]))
    assert mutation.live_rows(out) == n0 - 2
    packed = mutation.compact(out)
    assert mutation.live_rows(packed) == n0 - 2


# -- kill-and-resume bit-identity (child-process SIGKILL drills) --------

def _worker(args, workdir):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    return subprocess.run(
        [sys.executable, WORKER, *args, "--workdir", str(workdir)],
        env=env, capture_output=True, text=True, timeout=600)


_REF_CACHE = {}


def _ref_run(kind, tmp_path_factory):
    """One uninterrupted reference run per kind, shared by the kill
    drills (the worker is deterministic in its CLI args)."""
    if kind not in _REF_CACHE:
        workdir = tmp_path_factory.mktemp(f"mutref_{kind}")
        r = _worker(["--kind", kind, "--seed", str(SEED)], workdir)
        assert r.returncode == 0, r.stderr[-2000:]
        _REF_CACHE[kind] = (workdir,
                            json.loads(r.stdout.strip().splitlines()[-1]))
    return _REF_CACHE[kind]


@pytest.mark.slow
@pytest.mark.parametrize("kind", KINDS)
@pytest.mark.parametrize("kill", [1, 2, 3])
def test_sigkill_mid_mutation_resumes_bit_identical(
        tmp_path, tmp_path_factory, kind, kill):
    """THE mutation chaos acceptance drill: a real child process is
    SIGKILLed at the count-th ``mutation.log.commit`` visit — count=1
    lands mid-upsert with the log ahead of the checkpoint, count=2
    mid-delete, count=3 just after a checkpoint commit — then the SAME
    command re-runs. The resumed run must converge on a committed
    checkpoint BYTE-IDENTICAL to an uninterrupted run's, with identical
    search results. A separate process is the point: SIGKILL leaves no
    chance for in-process cleanup to cheat (`_kill_plan` documents the
    fault the worker arms)."""
    ref_dir, ref_out = _ref_run(kind, tmp_path_factory)
    assert _kill_plan(kill).site == "mutation.log.commit"

    r1 = _worker(["--kind", kind, "--seed", str(SEED),
                  "--kill", str(kill)], tmp_path)
    assert r1.returncode == -signal.SIGKILL, (r1.returncode, r1.stderr[-2000:])
    r2 = _worker(["--kind", kind, "--seed", str(SEED)], tmp_path)
    assert r2.returncode == 0, r2.stderr[-2000:]
    got = json.loads(r2.stdout.strip().splitlines()[-1])

    assert got["applied"] == ref_out["applied"]
    assert got["live_rows"] == ref_out["live_rows"]
    assert got["ids"] == ref_out["ids"]
    assert got["vals"] == ref_out["vals"]
    with open(os.path.join(ref_dir, "mut", "index.ckpt"), "rb") as fa, \
            open(os.path.join(tmp_path, "mut", "index.ckpt"), "rb") as fb:
        assert fa.read() == fb.read(), "resumed checkpoint is not bit-identical"
