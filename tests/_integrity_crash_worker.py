"""Child worker for the scrub kill-and-resume drills
(tests/test_integrity.py).

Builds a deterministic index, rots ONE payload list (the LAST one, so
the final slice of any walk must still re-hash it), then runs the
cursor-checkpointed `jobs.resumable_scrub` over it — optionally under a
seeded FaultPlan whose kill_rank fault at ``integrity.scrub.crash``
SIGKILLs THIS process on the count-th scrub-cursor commit. The parent
re-runs the same command line minus the kill; the cursor sidecar must
carry the resume (resumed_at > 0), the remaining walk must not re-scan
committed slices, and the rotted list must still be named. A separate
process is the point: SIGKILL leaves no chance for in-process cleanup
to cheat.

Not a test module (underscore prefix keeps pytest away).
"""

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

_ROT_FIELD = {"ivf_flat": "list_data", "ivf_pq": "codes",
              "ivf_rabitq": "codes"}


def _params(kind: str):
    if kind == "ivf_flat":
        from raft_tpu.neighbors import ivf_flat as mod

        return mod, mod.IndexParams(n_lists=8, kmeans_n_iters=2)
    if kind == "ivf_pq":
        from raft_tpu.neighbors import ivf_pq as mod

        return mod, mod.IndexParams(n_lists=8, pq_dim=4, pq_bits=4,
                                    kmeans_n_iters=2,
                                    kmeans_trainset_fraction=1.0)
    if kind == "ivf_rabitq":
        from raft_tpu.neighbors import ivf_rabitq as mod

        return mod, mod.IndexParams(n_lists=8, kmeans_n_iters=2,
                                    store_dataset=False)
    raise SystemExit(f"unknown kind {kind!r}")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--kind", default="ivf_flat")
    ap.add_argument("--kill", type=int, default=0,
                    help="SIGKILL on the kill-th integrity.scrub.crash visit")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--rows", type=int, default=64)
    ap.add_argument("--dim", type=int, default=8)
    ap.add_argument("--budget", type=int, default=4)
    ap.add_argument("--laps", type=int, default=2)
    args = ap.parse_args()

    import contextlib

    from raft_tpu import jobs
    from raft_tpu.core import faults
    from raft_tpu.integrity import scrub

    cm = contextlib.nullcontext()
    if args.kill > 0:
        cm = faults.FaultPlan(
            [faults.Fault(kind="kill_rank", site=scrub.SCRUB_CRASH_SITE,
                          count=args.kill)],
            seed=args.seed,
        ).install()

    mod, params = _params(args.kind)
    rng = np.random.default_rng(args.seed)
    data = rng.standard_normal((args.rows, args.dim)).astype(np.float32)
    # deterministic cold start: every invocation builds the same index
    # and rots the same list, so only the committed scrub cursor
    # distinguishes a resume
    index = mod.build(params, data)
    rot_lid = int(index.n_lists) - 1
    scrub.rot_list(index, rot_lid, _ROT_FIELD[args.kind], frac=0.5,
                   seed=args.seed)

    with cm:
        bad, stats = jobs.resumable_scrub(
            args.kind, index, scratch=args.workdir,
            budget_lists=args.budget, laps=args.laps)

    print(json.dumps({
        "rot": [_ROT_FIELD[args.kind], rot_lid],
        "bad": [[f, int(lid)] for f, lid in bad],
        **{k: int(v) for k, v in stats.items()},
    }), flush=True)


if __name__ == "__main__":
    main()
