"""White-box suite for the kernelcheck abstract interpreter
(tools/raftlint/kernels.py): the symbolic polynomial domain, block-byte
accounting (revisited buffers once, scalars uncharged), scalar-prefetch
arity variants, the dtype lattice, envelope formula evaluation, the
ceil-pad canonicalization, constraint extraction from validation
raises, and concrete probe evaluation through interpreted helpers.

These tests build tiny synthetic modules — independent of the real
fused_scan.py, which the fixture/mutation tests in test_raftlint.py
cover end to end.
"""

import ast
import textwrap

import pytest

from tools.raftlint.engine import Module
from tools.raftlint import kernels as K


def mod(src, path="raft_tpu/ops/mini.py"):
    text = textwrap.dedent(src)
    return Module(path, ast.parse(text), text.splitlines(), text)


def poly_of(src, env_syms=()):
    m = mod("X = 0\n")
    interp = K.ModuleInterp(m)
    env = {name: K.Poly.sym(name) for name in env_syms}
    return interp.eval(ast.parse(src, mode="eval").body, env)


# -- the polynomial domain ----------------------------------------------

def test_poly_canonicalization_and_arithmetic():
    p = poly_of("4 * a * b + 2 * b * a", ("a", "b"))
    assert isinstance(p, K.Poly)
    # both orderings land on one monomial
    assert len(p.terms) == 1
    assert list(p.terms.values()) == [6]
    q = poly_of("(a + b) * (a - b)", ("a", "b"))
    r = poly_of("a * a - b * b", ("a", "b"))
    assert q == r


def test_poly_constant_folding_and_floordiv():
    assert poly_of("(7 // 2) * 4").as_const() == 12
    sym = poly_of("a // 2", ("a",))
    assert sym.as_const() is None  # opaque atom, not a guess


def test_ceil_pad_idiom_lands_on_positive_monomials():
    """`-(-d // 128) * 128` must canonicalize to +128*ceildiv(d,128):
    byte coefficients compare in the right direction (the under-charge
    check is a >= over coefficients)."""
    p = poly_of("-(-d // 128) * 128", ("d",))
    assert all(c > 0 for c in p.terms.values())
    # and evaluates like the real ceil pad
    val = p.concrete(lambda kind, name: 130, lambda *a: 0)
    assert val == 256


def test_structural_atoms_agree_across_expressions():
    a = poly_of("q * (x // 16)", ("q", "x"))
    b = poly_of("(x // 16) * q", ("q", "x"))
    assert a == b
    c = poly_of("q * (x // 8)", ("q", "x"))
    assert a != c


def test_monomials_below_reports_the_shortfall():
    blocks = poly_of("4 * a * b + 8 * a", ("a", "b"))
    envelope = poly_of("2 * a * b + 8 * a", ("a", "b"))
    short = blocks.monomials_below(envelope)
    assert len(short) == 1
    mono, need, got = short[0]
    assert need == 4 and got == 2 and "a" in mono and "b" in mono
    assert blocks.monomials_below(blocks) == []


# -- dtype lattice -------------------------------------------------------

@pytest.mark.parametrize("a,b,out", [
    ("bfloat16", "bfloat16", "bfloat16"),
    ("bfloat16", "float32", "float32"),
    ("float16", "bfloat16", "float32"),
    ("int8", "int32", "int32"),
    ("bool", "int8", "int8"),
    (None, "float32", None),  # unknown poisons: silence, never a guess
])
def test_promote_lattice(a, b, out):
    assert K.promote(a, b) == out


# -- envelope formula evaluation ----------------------------------------

ENVELOPE_SRC = """
_LANES = 128

def helper(k):
    return max(_LANES, -(-int(k) // _LANES) * _LANES)

def fits_mini(chunk, L, k, store_itemsize=2, kbuf=None):
    if not 0 < k <= 256:
        return False
    kbuf = helper(k) if kbuf is None else int(kbuf)
    step = (
        4 * chunk * L
        + store_itemsize * L * 96
        + 8 * chunk * kbuf
    )
    return L % _LANES == 0 and step <= 10 * 1024 * 1024
"""


def test_envelope_extraction_and_budget():
    m = mod(ENVELOPE_SRC)
    interp = K.ModuleInterp(m)
    ei = K.envelope_info(interp, interp.functions["fits_mini"], {})
    assert ei.failed is None
    assert ei.budget == 10 * 1024 * 1024
    # the kbuf-provided convention: the symbol `kbuf` appears
    assert any("s:kbuf" in mono for mono in
               ("*".join(mo) for mo in ei.bytes_poly.terms))
    # itemsize param binds to the operand itemsize atom
    assert any("i:store" in mono for mono in
               ("*".join(mo) for mo in ei.bytes_poly.terms))


def test_envelope_binding_overrides_pin_parameters():
    m = mod(ENVELOPE_SRC)
    interp = K.ModuleInterp(m)
    ei = K.envelope_info(interp, interp.functions["fits_mini"],
                         {"store_itemsize": 1})
    mono = {("*".join(mo)): c for mo, c in
            ((tuple(mo), c) for mo, c in ei.bytes_poly.terms.items())}
    # the store term collapsed to a plain 96*L with coefficient 1*96
    flat = {"*".join(mo): c for mo, c in ei.bytes_poly.terms.items()}
    assert any(c == 96 for c in flat.values())


def test_probe_eval_interprets_project_helpers():
    m = mod(ENVELOPE_SRC)
    interp = K.ModuleInterp(m)
    ei = K.envelope_info(interp, interp.functions["fits_mini"], {})
    # kbuf left symbolic -> probe point supplies it; helper() atoms
    # would interpret the function body concretely
    v = K.probe_eval(interp, ei.bytes_poly,
                     {"chunk": 128, "L": 1024, "k": 100, "kbuf": 128},
                     {"store": 2})
    assert v == 4 * 128 * 1024 + 2 * 1024 * 96 + 8 * 128 * 128


# -- pallas site extraction ---------------------------------------------

KERNEL_SRC = """
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128

def _make_kernel(bn, kbuf, k):
    def kernel(x_ref, y_ref, vals_ref, idx_ref):
        dots = lax.dot_general(
            x_ref[:], y_ref[:],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        col = lax.broadcasted_iota(jnp.int32, dots.shape, 1)
        vals_ref[:] = dots
        idx_ref[:] = col
    return kernel

def scan(x, y, k, bq=128, bn=512):
    m, d = x.shape
    n = y.shape[0]
    d_pad = -(-d // _LANES) * _LANES
    xb = x.astype(jnp.bfloat16)
    yb = y.astype(jnp.bfloat16)
    vals, idx = pl.pallas_call(
        _make_kernel(bn, 128, int(k)),
        grid=(m // bq, n // bn),
        in_specs=[
            pl.BlockSpec((bq, d_pad), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, d_pad), lambda i, j: (j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((bq, bn), lambda i, j: (i, 0)),
            pl.BlockSpec((bq, bn), lambda i, j: (i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((m, bn), jnp.float32),
            jax.ShapeDtypeStruct((m, bn), jnp.int32),
        ),
    )(xb, yb)
    return vals, idx
"""


def test_site_extraction_block_bytes_and_dtypes():
    m = mod(KERNEL_SRC)
    ana = K.analyze_module(m)
    assert ana.pallas_wrappers == ["scan"]
    (site,) = ana.sites["scan"]
    assert site.nsp == 0 and len(site.grid) == 2
    assert len(site.in_specs) == 2 and len(site.out_specs) == 2
    blocks, why = site.block_bytes()
    assert why is None
    flat = {"*".join(sorted(mo)): c for mo, c in blocks.terms.items()}
    # bf16 operand blocks: 2 bytes x (bq|bn) x ceil-padded d; outputs
    # f32+int32: 8*bq*bn. Each block charged ONCE per step even though
    # the out blocks are revisited across the j axis.
    assert any(c == 8 for mono, c in flat.items()
               if "s:bn" in mono and "s:bq" in mono)
    # dot operands both bf16 -> f32 accumulate
    (dot,) = site.body.dots
    assert (dot.lhs, dot.rhs, dot.preferred) == \
        ("bfloat16", "bfloat16", "float32")
    # final stores land on the declared out dtypes
    assert site.body.out_store_dtype(site, 0) == "float32"
    assert site.body.out_store_dtype(site, 1) == "int32"


SCALAR_PREFETCH_SRC = """
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def _make_kernel(kbuf, with_valid):
    def kernel(lof_ref, *refs):
        if with_valid:
            cva_ref, q_ref, vals_ref = refs
        else:
            q_ref, vals_ref = refs
        vals_ref[0] = q_ref[0].astype(jnp.float32)
    return kernel

def list_scan(lof, qres, k, chunk_valid=None):
    ncb, chunk, rot = qres.shape
    if qres.dtype != jnp.float32:
        raise ValueError("needs f32")
    with_valid = chunk_valid is not None
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2 if with_valid else 1,
        grid=(ncb,),
        in_specs=[pl.BlockSpec((1, chunk, rot), lambda i, *s: (i, 0, 0))],
        out_specs=(
            pl.BlockSpec((1, chunk, rot), lambda i, *s: (i, 0, 0)),
        ),
    )
    scalars = (lof, chunk_valid) if with_valid else (lof,)
    vals = pl.pallas_call(
        _make_kernel(128, with_valid),
        out_shape=jax.ShapeDtypeStruct((ncb, chunk, rot), jnp.float32),
        grid_spec=grid_spec,
    )(*scalars, qres)
    return vals
"""


def test_optional_operand_variants_track_nsp_and_unpack():
    """The chunk_valid pattern: two interpretations, each with the
    matching num_scalar_prefetch and kernel ref unpacking."""
    m = mod(SCALAR_PREFETCH_SRC)
    ana = K.analyze_module(m)
    sites = ana.sites["list_scan"]
    assert sorted(s.variant for s in sites) == \
        ["chunk_valid=None", "chunk_valid=given"]
    by = {s.variant: s for s in sites}
    assert by["chunk_valid=None"].nsp == 1
    assert by["chunk_valid=given"].nsp == 2
    assert by["chunk_valid=None"].scalar_count == 1
    assert by["chunk_valid=given"].scalar_count == 2
    # the raise-guard pinned the operand dtype, so the store resolves
    for s in sites:
        assert s.body.out_store_dtype(s, 0) == "float32"
        blocks, why = s.block_bytes()
        assert why is None
        flat = {"*".join(sorted(mo)): c for mo, c in blocks.terms.items()}
        # scalar-prefetch operands are SMEM: only the f32 in-block and
        # the f32 out-block are charged (4 + 4 bytes x chunk x rot)
        assert flat == {"s:chunk*s:rot": 8}


def test_constraint_rewrite_from_inequality_raise():
    src = """
def wrap(planes, bits, words):
    ncb, chunk, pw = planes.shape
    if pw != int(bits) * words:
        raise ValueError("drift")
    return pw * 4
"""
    m = mod(src)
    interp = K.ModuleInterp(m)
    fn = interp.functions["wrap"]
    env = interp.base_env()
    env["planes"] = K.Arr(None, None, "planes")
    env["bits"] = K.Poly.sym("bits")
    env["words"] = K.Poly.sym("words")
    ex = K._BodyExec(interp, env, 0)
    ex.run(fn.body)
    # `pw` was rewritten to bits*words on the fallthrough path
    assert isinstance(ex.retval, K.Poly)
    flat = {"*".join(sorted(mo)): c for mo, c in ex.retval.terms.items()}
    assert flat == {"s:bits*s:words": 4}


def test_dtype_pin_from_validation_raise():
    src = """
import jax.numpy as jnp

def wrap(q8, store):
    if q8.dtype != jnp.int8 or store.dtype != jnp.int8:
        raise ValueError("int8 only")
    return q8
"""
    m = mod(src)
    interp = K.ModuleInterp(m)
    fn = interp.functions["wrap"]
    env = interp.base_env()
    q8 = K.Arr(None, None, "q8")
    store = K.Arr(None, None, "store")
    env["q8"], env["store"] = q8, store
    K._BodyExec(interp, env, 0).run(fn.body)
    assert q8.dtype == "int8" and store.dtype == "int8"


def test_registry_reader_parses_literal_pairings():
    src = """
KERNEL_ENVELOPES = {
    "scan": ("fits_scan", {}),
    "scan_int8": ("fits_scan", {"store_itemsize": 1}),
}
"""
    reg = K.read_kernel_envelopes(mod(src))
    assert reg == {"scan": ("fits_scan", {}),
                   "scan_int8": ("fits_scan", {"store_itemsize": 1})}
    assert K.read_kernel_envelopes(mod("X = 1\n")) is None
