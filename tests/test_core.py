"""Core runtime tests: Resources, validation, serialization, device_ndarray."""

import io
import threading

import numpy as np
import pytest

from raft_tpu import Resources, device_ndarray
from raft_tpu.core import (
    auto_sync_resources,
    check_matrix,
    serialize_arrays,
    deserialize_arrays,
)
from raft_tpu.core.interruptible import (
    synchronize,
    cancel,
    InterruptedException,
    TimeoutException,
)


def test_resources_rng_keys_differ():
    r = Resources(seed=1)
    k1, k2 = r.new_key(), r.new_key()
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))


def test_resources_registry():
    r = Resources()
    calls = []
    r.add_resource_factory("thing", lambda: calls.append(1) or {"x": 1})
    assert r.get_resource("thing")["x"] == 1
    r.get_resource("thing")
    assert len(calls) == 1  # lazily created once
    with pytest.raises(KeyError):
        r.get_resource("missing")


def test_resources_comms_roundtrip():
    r = Resources()
    assert not r.comms_initialized()
    with pytest.raises(RuntimeError):
        r.get_comms()
    r.set_comms("fake-comms")
    assert r.get_comms() == "fake-comms"
    r.set_sub_comms("tp", "sub")
    assert r.get_sub_comms("tp") == "sub"


def test_with_mesh_shares_registry():
    r = Resources()
    r.set_comms("c")
    r2 = r.with_mesh("mesh-placeholder")
    assert r2.get_comms() == "c"
    assert r2.mesh == "mesh-placeholder"


def test_auto_sync_decorator():
    seen = {}

    @auto_sync_resources
    def f(x, resources=None):
        seen["res"] = resources
        return x + 1

    assert f(1) == 2
    assert seen["res"] is not None


def test_validation():
    with pytest.raises(ValueError):
        check_matrix(np.zeros(3))
    with pytest.raises(ValueError):
        check_matrix(np.zeros((2, 2), np.int16), dtypes=[np.float32])
    out = check_matrix(np.zeros((2, 2), np.float32), dtypes=[np.float32])
    assert out.shape == (2, 2)


def test_device_ndarray_roundtrip(rng):
    x = rng.random((4, 5), dtype=np.float32)
    d = device_ndarray(x)
    assert d.shape == (4, 5) and d.dtype == np.float32
    np.testing.assert_array_equal(d.copy_to_host(), x)


def test_serialize_roundtrip(rng, tmp_path):
    arrays = {
        "a": rng.random((3, 4), dtype=np.float32),
        "b": rng.integers(0, 100, (7,), dtype=np.int64),
        "c": np.zeros((0, 5), np.float32),
    }
    meta = {"kind": "test-index", "version": 3}
    path = tmp_path / "container.bin"
    serialize_arrays(str(path), arrays, meta)
    got, got_meta = deserialize_arrays(str(path), to_device=False)
    assert got_meta == meta
    for k in arrays:
        np.testing.assert_array_equal(got[k], arrays[k])
        assert got[k].dtype == arrays[k].dtype


def test_serialize_stream(rng):
    buf = io.BytesIO()
    serialize_arrays(buf, {"x": np.arange(10)}, {"v": 1})
    buf.seek(0)
    got, meta = deserialize_arrays(buf, to_device=False)
    np.testing.assert_array_equal(got["x"], np.arange(10))


def test_serialize_bad_magic():
    buf = io.BytesIO(b"NOTMAGIC" + b"\x00" * 100)
    with pytest.raises(ValueError):
        deserialize_arrays(buf)


def test_crc32c_reference_vectors():
    from raft_tpu.core.serialize import crc32c

    # RFC 3720 / Castagnoli check values
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"") == 0
    assert crc32c(b"\x00" * 32) == 0x8A9136AA
    assert crc32c(bytes(range(32))) == 0x46DD794E
    # chaining equals one pass
    assert crc32c(b" world", crc32c(b"hello")) == crc32c(b"hello world")
    # block-vectorized path (>= 1 block + ragged tail) matches a
    # bytewise reference
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, 5000, dtype=np.uint8).tobytes()
    tbl = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
        tbl.append(c)
    ref = 0xFFFFFFFF
    for b in data:
        ref = tbl[(ref ^ b) & 0xFF] ^ (ref >> 8)
    assert crc32c(data) == ref ^ 0xFFFFFFFF


def test_serialize_truncated_and_torn_raise_typed(tmp_path):
    """Truncated/empty/garbage containers raise SerializationError
    naming the file and expected magic — not a raw struct.error or
    KeyError (satellite: typed decode failures)."""
    from raft_tpu.core.serialize import SerializationError, peek_meta

    empty = tmp_path / "empty.bin"
    empty.write_bytes(b"")
    with pytest.raises(SerializationError, match="empty.bin"):
        peek_meta(str(empty))
    short = tmp_path / "short.bin"
    short.write_bytes(b"RAFT")
    with pytest.raises(SerializationError, match="RAFTTPU"):
        deserialize_arrays(str(short))
    # magic intact, header length fields cut off
    torn = tmp_path / "torn.bin"
    torn.write_bytes(b"RAFTTPU\x00\x01\x00")
    with pytest.raises(SerializationError, match="torn.bin"):
        peek_meta(str(torn))
    # header says more bytes than the file holds
    half = tmp_path / "half.bin"
    serialize_arrays(str(half), {"x": np.arange(100)}, {"v": 1})
    data = half.read_bytes()
    half.write_bytes(data[:40])
    with pytest.raises(SerializationError, match="half.bin"):
        peek_meta(str(half))
    # SerializationError subclasses ValueError (old except-clauses hold)
    assert issubclass(SerializationError, ValueError)


def test_serialize_checksum_roundtrip_and_detect(tmp_path):
    from raft_tpu.core.serialize import ChecksumError

    path = tmp_path / "c.bin"
    arrays = {"a": np.arange(300, dtype=np.float32), "b": np.arange(50)}
    serialize_arrays(str(path), arrays, {"k": 1})
    got, _ = deserialize_arrays(str(path), to_device=False)
    np.testing.assert_array_equal(got["a"], arrays["a"])
    # flip one payload byte: the checksum names the corrupt field
    raw = bytearray(path.read_bytes())
    raw[-10] ^= 0xFF
    path.write_bytes(bytes(raw))
    with pytest.raises(ChecksumError, match="'b'"):
        deserialize_arrays(str(path), to_device=False)
    # forensic read still possible
    got2, _ = deserialize_arrays(str(path), to_device=False, verify=False)
    assert got2["b"].shape == (50,)


def test_serialize_atomic_write_leaves_no_temp(tmp_path):
    """Path writes are write-to-temp-then-rename: success leaves no temp
    file; a failing writer leaves neither temp nor final file."""
    from raft_tpu.core.serialize import atomic_write

    path = tmp_path / "ok.bin"
    serialize_arrays(str(path), {"x": np.arange(10)}, {})
    assert [f for f in tmp_path.iterdir()] == [path]
    doomed = tmp_path / "doomed.bin"
    with pytest.raises(RuntimeError, match="mid-write"):
        with atomic_write(str(doomed)) as tmp:
            with open(tmp, "wb") as fh:
                fh.write(b"partial")
            raise RuntimeError("mid-write crash")
    assert not doomed.exists()
    assert [f.name for f in tmp_path.iterdir()] == ["ok.bin"]


def test_interruptible_cancel():
    tid = threading.get_ident()
    cancel(tid)
    with pytest.raises(InterruptedException):
        synchronize()
    # flag cleared after raise
    synchronize()


class _NeverReady:
    """A pending 'array': polls as not-ready forever (the hung-mesh
    stand-in for the timeout/cancel paths of the health-check barrier)."""

    def is_ready(self):
        return False

    def block_until_ready(self):
        raise AssertionError("synchronize must poll is_ready, not block")


def test_interruptible_timeout_raises_and_clears():
    import time

    t0 = time.monotonic()
    with pytest.raises(TimeoutException, match="timeout_s=0.1"):
        synchronize(_NeverReady(), timeout_s=0.1, poll_interval_s=0.005)
    assert time.monotonic() - t0 >= 0.1
    # the cancellation flag was never set: later waits work unscathed
    synchronize()
    # no timeout on a ready value, even a tiny deadline
    synchronize(np.zeros(1), timeout_s=0.001)


def test_interruptible_cancel_mid_wait():
    """Another thread cancels a wait in flight (the health barrier's
    escape hatch): InterruptedException, and the flag clears so the
    thread's next wait is clean."""
    tid = threading.get_ident()
    t = threading.Timer(0.05, cancel, args=(tid,))
    t.start()
    try:
        with pytest.raises(InterruptedException):
            synchronize(_NeverReady(), timeout_s=10, poll_interval_s=0.005)
    finally:
        t.join()
    synchronize()  # flag cleared


def test_interruptible_cancel_beats_timeout():
    """Cancel landing before the deadline wins over the timeout."""
    tid = threading.get_ident()
    t = threading.Timer(0.02, cancel, args=(tid,))
    t.start()
    try:
        with pytest.raises(InterruptedException):
            synchronize(_NeverReady(), timeout_s=5, poll_interval_s=0.005)
    finally:
        t.join()
    synchronize()


def test_operators_vocabulary():
    """core/operators.hpp parity: composable ops drive the generic reduce."""
    import jax.numpy as jnp
    from raft_tpu.core import operators as op, KeyValuePair
    from raft_tpu.linalg import reduce

    x = jnp.asarray(np.arange(12, dtype=np.float32).reshape(3, 4))
    r = reduce(x, axis=1, main_op=op.sq_op, reduce_op="add", final_op=op.sqrt_op)
    np.testing.assert_allclose(
        np.asarray(r), np.linalg.norm(np.asarray(x), axis=1), rtol=1e-6
    )
    a = KeyValuePair(jnp.asarray(0), jnp.asarray(3.0))
    b = KeyValuePair(jnp.asarray(1), jnp.asarray(2.0))
    assert int(op.argmin_op(a, b).key) == 1
    assert int(op.argmax_op(a, b).key) == 0
    assert float(op.compose_op(op.sqrt_op, op.sq_op)(jnp.asarray(-4.0))) == 4.0
    assert op.cast_op(jnp.int32)(jnp.asarray(3.7)).dtype == jnp.int32
    assert op.const_op(7)(123) == 7
    assert float(op.nz_op(jnp.asarray([0.0, 2.0])).sum()) == 1.0


def test_logger_set_callback_flush():
    """Regression (callback_sink.hpp parity): the callback sink must
    deliver records to `cb`, propagate handler flushes to `flush_cb`,
    and uninstall cleanly on `set_callback(None)`."""
    import importlib

    logger_mod = importlib.import_module("raft_tpu.core.logger")
    records, flushes = [], []
    logger_mod.set_callback(lambda lvl, msg: records.append((lvl, msg)),
                            flush_cb=lambda: flushes.append(1))
    try:
        logger_mod.set_level(logger_mod.RAFT_LEVEL_INFO)
        logger_mod.logger.info("cb %s", "works")
        assert len(records) == 1 and records[0][1].endswith("cb works")
        sinks = [h for h in logger_mod.logger.handlers
                 if isinstance(h, logger_mod._CallbackHandler)]
        assert len(sinks) == 1
        sinks[0].flush()
        assert flushes == [1]
        # a flush-less sink must be a no-op, not an AttributeError
        logger_mod.set_callback(lambda lvl, msg: None)
        [h.flush() for h in logger_mod.logger.handlers]
        # installing replaced the first sink; None removes the last one
        logger_mod.set_callback(None)
        assert not any(isinstance(h, logger_mod._CallbackHandler)
                       for h in logger_mod.logger.handlers)
        logger_mod.logger.info("after removal")
        assert len(records) == 1
    finally:
        logger_mod.set_callback(None)
        logger_mod.set_level(logger_mod.RAFT_LEVEL_WARN)


def test_output_type_config():
    """pylibraft set_output_as parity: numpy/torch/callable conversion."""
    from raft_tpu.core import set_output_as, convert_output
    import jax.numpy as jnp

    x = jnp.ones((2, 2), jnp.float32)
    try:
        set_output_as("numpy")
        out = convert_output((x, 5))
        assert isinstance(out[0], np.ndarray) and out[1] == 5
        set_output_as(lambda a: "custom")
        assert convert_output(x) == "custom"
        with pytest.raises(ValueError):
            set_output_as("cupy")
    finally:
        set_output_as("jax")
