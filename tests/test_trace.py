"""Request-scope tracing, flight recorder, and SLO watchtower suite
(ISSUE 18): exact deterministic trace pins over a step-mode serve run,
Chrome trace-event export validity + byte-stability, telescoping stage
accounting against measured e2e latency, flight-ring eviction and
atomic dumps (including a real SIGKILLed watchdog child), the two new
chaos sites (`obs.flight.dump`, `serve.trace.stamp`) with their
degrade-not-die contracts, and multi-window burn-rate math (fast trips
before slow; recover hysteresis)."""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from raft_tpu import obs, serve
from raft_tpu.core import faults
from raft_tpu.jobs.watchdog import StageTimeout, run_supervised
from raft_tpu.obs import flight, slo, trace

SEED = int(os.environ.get(faults.ENV_SEED, "1234"))


@pytest.fixture
def obs_on():
    obs.reset()
    trace.reset(seed=0)  # a prior test may have re-seeded the mint
    obs.enable()
    yield
    flight.uninstall()
    obs.disable()
    obs.reset()


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(0)
    return rng.standard_normal((64, 16)).astype(np.float32)


def _normalize_trace(e: dict) -> dict:
    """Strip clock-derived fields; keep everything a replay must pin."""
    return {k: v for k, v in e.items() if k not in ("seq", "t", "marks")}


def _serve_three_batches(dataset):
    """Three single-request batches in step mode (deterministic worker
    thread = MainThread); returns the bus trace events."""
    rng = np.random.default_rng(1)
    server = serve.SearchServer(
        dataset, serve.ServerConfig(buckets=(8,), max_wait_ms=0.0))
    for _ in range(3):
        fut = server.submit(rng.standard_normal((2, 16)).astype(np.float32),
                            k=3)
        assert server.step() == 1
        assert fut.result(timeout=1.0).ids.shape == (2, 3)
    return [e for e in obs.snapshot()["events"] if e["kind"] == "trace"]


# ---------------------------------------------------------------------------
# trace ids: pure, seeded, pinned
# ---------------------------------------------------------------------------

def test_trace_id_pure_and_pinned():
    # splitmix64 of (seed 0, n 1..3): fixed forever — a replayed drill
    # must mint these exact ids
    assert trace.trace_id(0, 1) == 10451216379200822465
    assert trace.trace_id(0, 2) == trace.trace_id(0, 2)
    assert len({trace.trace_id(0, n) for n in range(1, 100)}) == 99
    assert len({trace.trace_id(s, 1) for s in range(100)}) == 100
    for s, n in ((0, 1), (7, 3), (2**63, 12)):
        assert 0 <= trace.trace_id(s, n) < 2**64


def test_mint_matches_pure_function_and_resets(obs_on):
    got = [trace.begin().trace_id for _ in range(3)]
    assert got == [trace.trace_id(0, n) for n in (1, 2, 3)]
    trace.reset(seed=5)
    assert trace.begin().trace_id == trace.trace_id(5, 1)
    obs.reset()  # resets the count, keeps the seed
    assert trace.begin().trace_id == trace.trace_id(5, 1)


def test_begin_returns_none_when_disabled():
    obs.reset()
    assert trace.begin() is None


# ---------------------------------------------------------------------------
# exact deterministic trace pin (ISSUE 18 acceptance)
# ---------------------------------------------------------------------------

def test_three_batch_trace_pin_exact(obs_on, dataset):
    events = _serve_three_batches(dataset)
    want = [
        {
            "kind": "trace",
            "trace_id": trace.trace_id(0, i + 1),
            "outcome": "ok",
            "stages": ["admitted", "coalesced", "dispatched", "fenced",
                       "scattered"],
            "worker": "MainThread",
            "rows": 2,
            "k": 3,
            "bucket": 8,
            "cached": i > 0,  # first batch compiles, the rest hit
            "probe": "None",  # exact searcher: probe plan is a no-op
            "coverage": 1.0,
        }
        for i in range(3)
    ]
    assert [_normalize_trace(e) for e in events] == want
    # every mark is a monotonic timestamp in pipeline order
    for e in events:
        marks = e["marks"]
        ts = [marks[s] for s in want[0]["stages"]]
        assert ts == sorted(ts)
    # each completed request observed every stage histogram once
    hists = obs.snapshot()["metrics"]["histograms"]
    for name in ("serve.stage.queue_wait_s", "serve.stage.linger_s",
                 "serve.stage.device_s", "serve.stage.scatter_s"):
        assert hists[name]["count"] == 3
    counters = obs.snapshot()["metrics"]["counters"]
    assert counters["serve.outcome.ok"] == 3


def test_trace_pin_replays_identically(obs_on, dataset):
    runs = []
    for _ in range(2):
        obs.reset()
        runs.append([_normalize_trace(e)
                     for e in _serve_three_batches(dataset)])
    assert runs[0] == runs[1]


def test_stage_sum_covers_measured_e2e(obs_on, dataset):
    """Acceptance: summed per-stage times >= 95% of the measured e2e
    latency per request. An injected 80 ms slow dispatch makes the
    traced window dominate whatever sub-ms slack sits outside it."""
    server = serve.SearchServer(
        dataset, serve.ServerConfig(buckets=(8,), max_wait_ms=0.0))
    plan = faults.FaultPlan(
        [faults.Fault(kind="slow_rank", site="serve.batch",
                      latency_s=0.08)],
        seed=SEED)
    rng = np.random.default_rng(2)
    t_sub = []
    futs = []
    for _ in range(4):
        q = rng.standard_normal((2, 16)).astype(np.float32)
        t_sub.append(time.monotonic())
        futs.append(server.submit(q, k=3))
    with plan.install():
        assert server.step() == 4
    t_done = time.monotonic()
    for fut in futs:
        assert fut.result(timeout=1.0).coverage == 1.0
    events = [e for e in obs.snapshot()["events"] if e["kind"] == "trace"]
    assert len(events) == 4
    for e, t0 in zip(events, t_sub):
        marks = e["marks"]
        stage_sum = sum(
            marks[b] - marks[a]
            for (a, b) in zip(trace.STAGES, trace.STAGES[1:]))
        e2e = t_done - t0
        assert stage_sum == pytest.approx(
            marks["scattered"] - marks["admitted"])  # deltas telescope
        assert stage_sum >= 0.95 * e2e, (stage_sum, e2e)


# ---------------------------------------------------------------------------
# Chrome/Perfetto export
# ---------------------------------------------------------------------------

def test_chrome_trace_valid_and_byte_stable(obs_on, dataset):
    with obs.span("drill.outer"):
        _serve_three_batches(dataset)
    one = obs.to_chrome_trace()
    two = obs.to_chrome_trace()
    assert one == two  # byte-identical across renders of the same bus
    payload = json.loads(one)
    assert payload["displayTimeUnit"] == "ms"
    evs = payload["traceEvents"]
    assert all(e["ph"] in ("M", "X") for e in evs)
    xs = [e for e in evs if e["ph"] == "X"]
    assert all(e["ts"] >= 0.0 and e["dur"] >= 0.0 for e in xs)
    # stage segments on the worker track, named after the histograms
    stage_names = {e["name"] for e in xs if e["pid"] == 1}
    assert stage_names == {"queue_wait", "linger", "device", "scatter"}
    # one whole-request event per request on the bucket-ladder track
    reqs = [e for e in xs if e["pid"] == 2]
    assert len(reqs) == 3
    assert {e["name"] for e in reqs} == {
        f"request {trace.trace_id(0, n):016x}" for n in (1, 2, 3)}
    # span events land on the span track with their own duration
    assert any(e["pid"] == 3 and e["name"] == "serve.batch" for e in xs)
    # metadata rows name the tracks
    metas = {(e["pid"], e["name"], e["args"]["name"])
             for e in evs if e["ph"] == "M"}
    assert (1, "process_name", "serve workers") in metas
    assert (2, "process_name", "bucket ladder") in metas
    assert (1, "thread_name", "MainThread") in metas
    assert (2, "thread_name", "bucket=8") in metas


def test_chrome_trace_empty_bus(obs_on):
    payload = json.loads(obs.to_chrome_trace([]))
    assert payload["traceEvents"] == []


# ---------------------------------------------------------------------------
# terminal outcomes
# ---------------------------------------------------------------------------

def test_outcome_counters_and_drop_wait(obs_on, dataset):
    server = serve.SearchServer(
        dataset, serve.ServerConfig(buckets=(8,), max_wait_ms=0.0))
    ok = server.submit(np.zeros((2, 16), np.float32), k=3)
    dead = server.submit(np.zeros((2, 16), np.float32), k=3, deadline_s=0.0)
    assert server.step() == 2
    assert ok.result(timeout=1.0).coverage == 1.0
    with pytest.raises(serve.DeadlineExceeded):
        dead.result(timeout=0.1)
    snap = obs.snapshot()
    counters = snap["metrics"]["counters"]
    assert counters["serve.outcome.ok"] == 1
    assert counters["serve.outcome.expired"] == 1
    assert "serve.outcome.rejected" not in counters
    # the killed request's queue wait landed in the drop histogram
    assert snap["metrics"]["histograms"]["serve.drop_wait_s"]["count"] == 1
    # and its trace closed with the expired outcome (admitted only —
    # it never reached a later stage)
    traces = {e["outcome"]: e for e in snap["events"]
              if e["kind"] == "trace"}
    assert traces["expired"]["stages"] == ["admitted"]
    assert traces["ok"]["stages"][-1] == "scattered"


def test_rejected_request_closes_its_trace(obs_on, dataset):
    server = serve.SearchServer(
        dataset,
        serve.ServerConfig(
            buckets=(8,),
            admission=serve.AdmissionConfig(max_pending_rows=2,
                                            policy="reject")))
    server.submit(np.zeros((2, 16), np.float32), k=3)
    with pytest.raises(serve.RejectedError):
        server.submit(np.zeros((2, 16), np.float32), k=3)
    counters = obs.snapshot()["metrics"]["counters"]
    assert counters["serve.outcome.rejected"] == 1
    rejected = [e for e in obs.snapshot()["events"]
                if e["kind"] == "trace" and e["outcome"] == "rejected"]
    assert len(rejected) == 1


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_ring_evicts_oldest_first(obs_on):
    rec = flight.FlightRecorder(maxlen=4).install()
    try:
        for i in range(10):
            obs.event("tick", i=i)
        assert [e["i"] for e in rec.events()] == [6, 7, 8, 9]
    finally:
        rec.uninstall()
    obs.event("tick", i=99)  # uninstalled: the ring stops recording
    assert [e["i"] for e in rec.events()] == [6, 7, 8, 9]


def test_flight_dump_atomic_and_readable(obs_on, tmp_path):
    flight.install(maxlen=64, dump_dir=str(tmp_path))
    obs.counter("drill.widgets").inc(3)
    obs.event("tick", i=0)
    with obs.span("drill.open"):
        path = flight.maybe_dump("unit_test", detail="abc")
    assert path is not None and os.path.exists(path)
    # atomic_write leaves no temp droppings behind
    assert [p for p in os.listdir(tmp_path) if ".tmp." in p] == []
    with open(path) as f:
        snap = json.load(f)
    assert snap["reason"] == "unit_test"
    assert snap["detail"] == "abc"
    assert snap["pid"] == os.getpid()
    assert snap["registry_delta"]["drill.widgets"] == 3
    assert any(e["kind"] == "tick" for e in snap["events"])
    # the dump ran inside an open span; the stack was captured
    assert any(s["name"] == "drill.open" for s in snap["open_spans"])


def test_flight_dump_disarmed_is_noop(obs_on):
    assert flight.installed() is None
    assert flight.maybe_dump("nobody_home") is None


def test_open_spans_capture(obs_on):
    with obs.span("outer"):
        with obs.span("inner", depth_attr=1):
            stacks = obs.open_spans()
    names = [(s["name"], s["depth"]) for s in stacks
             if s["thread"] == threading.current_thread().name]
    assert names == [("outer", 0), ("inner", 1)]
    assert obs.open_spans() == []  # closed spans leave no residue


# ---------------------------------------------------------------------------
# chaos drills: the two new sites degrade, never kill (ISSUE 18 sat. 3)
# ---------------------------------------------------------------------------

def test_sites_registered():
    known = faults.known_sites()
    assert flight.DUMP_SITE == "obs.flight.dump" and flight.DUMP_SITE in known
    assert trace.STAMP_SITE == "serve.trace.stamp" and trace.STAMP_SITE in known


def test_flaky_dump_is_swallowed(obs_on, tmp_path):
    flight.install(dump_dir=str(tmp_path))
    obs.event("tick", i=1)
    plan = faults.FaultPlan(
        [faults.Fault(kind="flaky_bootstrap", site="obs.flight.dump",
                      count=1)],
        seed=SEED)
    with plan.install():
        assert flight.maybe_dump("drill") is None  # failed, did not raise
        path = flight.maybe_dump("drill")  # armed once: retry succeeds
    assert path is not None and os.path.exists(path)
    actions = [e["action"] for e in obs.snapshot()["events"]
               if e["kind"] == "flight"]
    assert actions == ["dump_failed", "dump"]


def test_flaky_dump_never_kills_worker_loop(obs_on, dataset, tmp_path):
    """A batcher bug inside the threaded worker loop triggers a flight
    dump; with the dump ALSO failing (injected), the worker must still
    survive both and keep serving."""
    flight.install(dump_dir=str(tmp_path))
    server = serve.SearchServer(
        dataset, serve.ServerConfig(buckets=(8,), max_wait_ms=0.0))
    real_collect = server.batcher.collect
    boom = threading.Event()

    def collect_once_broken(timeout_s=None):
        if not boom.is_set():
            boom.set()
            raise ValueError("injected batcher bug")
        return real_collect(timeout_s=timeout_s)

    server.batcher.collect = collect_once_broken
    plan = faults.FaultPlan(
        [faults.Fault(kind="flaky_bootstrap", site="obs.flight.dump",
                      count=1)],
        seed=SEED)
    with plan.install():
        server.start()
        try:
            fut = server.submit(np.zeros((2, 16), np.float32), k=3)
            assert fut.result(timeout=5.0).coverage == 1.0  # still serving
        finally:
            server.stop()
    events = obs.snapshot()["events"]
    assert any(e["kind"] == "serve_worker_error" for e in events)
    assert any(e["kind"] == "flight" and e["action"] == "dump_failed"
               for e in events)


def test_corrupt_stamp_degrades_to_untraced_bit_identical(obs_on, dataset):
    """An injected stamp corruption kills request 1's trace; the request
    itself is served with results bit-identical to an uninjected run."""
    from raft_tpu.neighbors import brute_force

    rng = np.random.default_rng(3)
    qs = [rng.standard_normal((2, 16)).astype(np.float32) for _ in range(2)]
    server = serve.SearchServer(
        dataset, serve.ServerConfig(buckets=(8,), max_wait_ms=0.0))
    plan = faults.FaultPlan(
        [faults.Fault(kind="flaky_bootstrap", site="serve.trace.stamp",
                      count=1)],
        seed=SEED)
    results = []
    with plan.install():
        for q in qs:
            fut = server.submit(q, k=3)
            server.step()
            results.append(fut.result(timeout=1.0))
    for q, got in zip(qs, results):
        want_v, want_i = brute_force.knn(dataset, q, 3)
        np.testing.assert_array_equal(np.asarray(want_v), got.values)
        np.testing.assert_array_equal(np.asarray(want_i), got.ids)
    traces = [e for e in obs.snapshot()["events"] if e["kind"] == "trace"]
    # request 1 degraded to untraced (its first stamp died and the ctx
    # stopped consuming arms); request 2 traced normally, and because
    # the dead ctx minted first, its id is still trace_id(0, 1)
    assert [e["trace_id"] for e in traces] == [trace.trace_id(0, 2)]
    assert traces[0]["outcome"] == "ok"
    fault_evs = [e for e in obs.snapshot()["events"]
                 if e["kind"] == "fault"]
    assert [(e["site"], e["action"]) for e in fault_evs] == [
        ("serve.trace.stamp", "flaky")]


# ---------------------------------------------------------------------------
# watchdog-armed dump, end to end (real SIGKILLed child)
# ---------------------------------------------------------------------------

def test_watchdog_kill_leaves_readable_flight_dump(obs_on, tmp_path):
    flight.install(maxlen=128, dump_dir=str(tmp_path))
    child = ("import sys, time; print('up', flush=True); "
             "time.sleep(60)")
    with pytest.raises(StageTimeout):
        run_supervised([sys.executable, "-c", child], describe="stall-child",
                       stall_timeout_s=0.3, echo=False)
    dumps = sorted(p for p in os.listdir(tmp_path)
                   if p.startswith("flight-") and p.endswith(".json"))
    assert len(dumps) == 1
    assert [p for p in os.listdir(tmp_path) if ".tmp." in p] == []
    with open(os.path.join(tmp_path, dumps[0])) as f:
        snap = json.load(f)
    assert snap["reason"] == "watchdog_kill"
    assert snap["stage"] == "stall-child"
    # the ring CONTAINS the kill's own event (dump runs before SIGKILL)
    kills = [e for e in snap["events"]
             if e["kind"] == "fault" and e["action"] == "watchdog_kill"]
    assert len(kills) == 1 and kills[0]["stage"] == "stall-child"


# ---------------------------------------------------------------------------
# SLO watchtower
# ---------------------------------------------------------------------------

def test_objective_validation():
    with pytest.raises(ValueError, match="kind"):
        slo.Objective("x", "latencyy", target=0.99)
    with pytest.raises(ValueError, match="target"):
        slo.Objective("x", "latency", target=1.0)
    with pytest.raises(ValueError, match="hysteresis"):
        slo.Watchtower([slo.Objective("x", "error", target=0.99)],
                       breach_burn=2.0, recover_burn=2.0)
    assert slo.Objective("x", "error", target=0.99).budget == pytest.approx(0.01)


def test_burn_rate_fast_trips_before_slow_then_breach_then_recover(obs_on):
    """The multi-window guard, driven by explicit synthetic time: the
    fast window trips first but a breach needs the slow window's
    confirmation; recovery needs BOTH burns under the (lower) recover
    threshold — hysteresis against flapping."""
    wt = slo.Watchtower([slo.Objective("error_rate", "error", target=0.99)],
                        fast_s=300.0, slow_s=3600.0,
                        breach_burn=14.0, recover_burn=1.0)
    # budget = 0.01, so burn = 100 * bad_fraction; breach needs
    # bad_fraction >= 0.14 in BOTH windows
    for _ in range(900):
        wt.observe("error_rate", bad=False, t=100.0)
    for _ in range(50):
        wt.observe("error_rate", bad=True, t=1000.0)
    for _ in range(50):
        wt.observe("error_rate", bad=False, t=1000.0)
    fast, slow = wt.burns("error_rate", t=1000.0)
    assert fast == pytest.approx(50.0)   # 50/100 bad in the fast window
    assert slow == pytest.approx(5.0)    # 50/1000 bad in the slow window
    assert fast >= wt.breach_burn and slow < wt.breach_burn
    assert wt.evaluate(t=1000.0) == []   # fast tripped, slow vetoed
    assert not wt.state(t=1000.0)["error_rate"]["breached"]

    # the error keeps burning: now the slow window confirms -> breach
    for _ in range(150):
        wt.observe("error_rate", bad=True, t=1010.0)
    [tr] = wt.evaluate(t=1010.0)
    assert tr["objective"] == "error_rate" and tr["transition"] == "breach"
    assert tr["fast_burn"] >= 14.0 and tr["slow_burn"] >= 14.0

    # fast window drains below breach_burn -> still breached
    # (recover needs BOTH burns < recover_burn)
    assert wt.evaluate(t=1400.0) == []
    fast, slow = wt.burns("error_rate", t=1400.0)
    assert fast < wt.recover_burn <= slow
    assert wt.state(t=1400.0)["error_rate"]["breached"]

    # slow window drains too -> recover
    [tr] = wt.evaluate(t=5000.0)
    assert tr["transition"] == "recover"
    counters = obs.snapshot()["metrics"]["counters"]
    assert counters["slo.breach"] == 1
    assert counters["slo.recover"] == 1
    kinds = [e["kind"] for e in obs.snapshot()["events"]
             if e["kind"].startswith("slo.")]
    assert kinds == ["slo.breach", "slo.recover"]


def test_watchtower_attached_to_server(obs_on, dataset):
    """The serve integration: terminal outcomes feed the watchtower via
    ServerMetrics; an all-expired burst breaches the error objective on
    both windows at once (same synthetic clock instant)."""
    t_fake = [1000.0]
    wt = slo.Watchtower(slo.serve_objectives(), clock=lambda: t_fake[0])
    server = serve.SearchServer(
        dataset, serve.ServerConfig(buckets=(8,), max_wait_ms=0.0))
    server.attach_watchtower(wt)
    futs = [server.submit(np.zeros((2, 16), np.float32), k=3,
                          deadline_s=0.0) for _ in range(3)]
    assert server.step() == 3
    for fut in futs:
        with pytest.raises(serve.DeadlineExceeded):
            fut.result(timeout=0.1)
    assert wt.state()["error_rate"]["breached"]
    assert obs.snapshot()["metrics"]["counters"]["slo.breach"] == 1
    # healthy traffic at a later instant recovers it
    t_fake[0] += 4000.0
    for _ in range(3):
        fut = server.submit(np.zeros((2, 16), np.float32), k=3)
        server.step()
        assert fut.result(timeout=1.0).coverage == 1.0
    assert not wt.state()["error_rate"]["breached"]
    assert obs.snapshot()["metrics"]["counters"]["slo.recover"] == 1


def test_judge_serve_verdicts():
    good = {"submitted": 100, "expired": 0, "rejected": 0, "failed": 0,
            "latency_ms_p99": 12.0, "coverage_min": 1.0,
            "batch_occupancy": 0.5}
    v = slo.judge_serve(good, p99_ms=250.0)
    assert v["slo_ok"] and v["slo_p99_ok"] and v["slo_error_ok"]
    assert v["slo_error_rate"] == 0.0
    # two expiries out of 100 blow a 1% error budget
    v = slo.judge_serve({**good, "expired": 2})
    assert not v["slo_error_ok"] and not v["slo_ok"]
    assert v["slo_error_rate"] == pytest.approx(0.02)
    # an empty run cannot claim its SLOs held (NaN stats judge failing)
    v = slo.judge_serve({"submitted": 0, "latency_ms_p99": float("nan"),
                         "batch_occupancy": float("nan")})
    assert not v["slo_ok"] and not v["slo_p99_ok"] and not v["slo_error_ok"]


# ---------------------------------------------------------------------------
# disabled path
# ---------------------------------------------------------------------------

def test_disabled_serve_is_untraced(dataset):
    obs.reset()
    assert not obs.enabled()
    server = serve.SearchServer(
        dataset, serve.ServerConfig(buckets=(8,), max_wait_ms=0.0))
    fut = server.submit(np.zeros((2, 16), np.float32), k=3)
    server.step()
    assert fut.result(timeout=1.0).coverage == 1.0
    obs.enable()
    try:
        snap = obs.snapshot()
        assert [e for e in snap["events"] if e["kind"] == "trace"] == []
        # instrument NAMES may linger from earlier tests (the global
        # registry resets values, not names); the disabled run must not
        # have moved any of them
        assert snap["metrics"]["counters"].get("serve.outcome.ok", 0) == 0
        device = snap["metrics"]["histograms"].get("serve.stage.device_s")
        assert device is None or device["count"] == 0
    finally:
        obs.disable()
        obs.reset()
