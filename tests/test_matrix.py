"""Matrix ops vs numpy oracles (mirrors cpp/test/matrix/{gather,argmax,slice,
math,columnSort,linewise_op,...}.cu)."""

import numpy as np

from raft_tpu import matrix as M


def test_gather_scatter(rng):
    a = rng.standard_normal((20, 8)).astype(np.float32)
    idx = rng.integers(0, 20, size=7)
    np.testing.assert_allclose(np.asarray(M.gather(a, idx)), a[idx])
    upd = rng.standard_normal((7, 8)).astype(np.float32)
    out = np.asarray(M.scatter(a, idx, upd))
    ref = a.copy()
    ref[idx] = upd
    np.testing.assert_allclose(out, ref)


def test_gather_if(rng):
    a = rng.standard_normal((10, 4)).astype(np.float32)
    idx = np.arange(10)[::-1].copy()
    mask = (np.arange(10) % 2).astype(bool)
    out = np.asarray(M.gather_if(a, idx, mask, fill_value=-1.0))
    ref = np.where(mask[:, None], a[idx], -1.0)
    np.testing.assert_allclose(out, ref)


def test_argmax_argmin(rng):
    a = rng.standard_normal((16, 33)).astype(np.float32)
    np.testing.assert_array_equal(np.asarray(M.argmax(a)), a.argmax(axis=1))
    np.testing.assert_array_equal(np.asarray(M.argmin(a)), a.argmin(axis=1))


def test_slice_reverse(rng):
    a = rng.standard_normal((12, 9)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(M.slice(a, 2, 9, 1, 5)), a[2:9, 1:5])
    np.testing.assert_allclose(np.asarray(M.reverse(a, axis=1)), a[:, ::-1])


def test_linewise_op(rng):
    a = rng.standard_normal((8, 6)).astype(np.float32)
    v = rng.standard_normal(6).astype(np.float32)
    out = np.asarray(M.linewise_op(a, v, lambda m, w: m * w, along_rows=True))
    np.testing.assert_allclose(out, a * v[None, :], rtol=1e-6)


def test_col_wise_sort(rng):
    a = rng.standard_normal((32, 5)).astype(np.float32)
    s, idx = M.col_wise_sort(a)
    np.testing.assert_allclose(np.asarray(s), np.sort(a, axis=0))
    np.testing.assert_allclose(
        np.take_along_axis(a, np.asarray(idx), axis=0), np.sort(a, axis=0)
    )


def test_diag_triangular(rng):
    a = rng.standard_normal((7, 7)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(M.diagonal(a)), np.diag(a))
    v = np.arange(7, dtype=np.float32)
    out = np.asarray(M.set_diagonal(a, v))
    np.testing.assert_allclose(np.diag(out), v)
    np.testing.assert_allclose(np.asarray(M.upper_triangular(a)), np.triu(a))
    np.testing.assert_allclose(np.asarray(M.lower_triangular(a)), np.tril(a))


def test_math_ops(rng):
    a = np.abs(rng.standard_normal((6, 6))).astype(np.float32) + 0.1
    np.testing.assert_allclose(np.asarray(M.power(a, 2.0)), a**2, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(M.sqrt(a)), np.sqrt(a), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(M.ratio(a)), a / a.sum(), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(M.reciprocal(a)), 1.0 / a, rtol=1e-5)


def test_reciprocal_guard():
    a = np.array([[0.0, 2.0], [1e-9, -4.0]], dtype=np.float32)
    out = np.asarray(M.reciprocal(a, scalar=1.0, thres=1e-6))
    np.testing.assert_allclose(out, [[0.0, 0.5], [0.0, -0.25]])


def test_sign_flip(rng):
    a = rng.standard_normal((9, 4)).astype(np.float32)
    out = np.asarray(M.sign_flip(a))
    # Each column's max-|value| entry must be positive; directions preserved.
    piv = out[np.abs(out).argmax(axis=0), np.arange(4)]
    assert (piv > 0).all()
    np.testing.assert_allclose(np.abs(out), np.abs(a), rtol=1e-6)


def test_threshold():
    a = np.array([[0.1, 0.9], [0.5, 0.2]], dtype=np.float32)
    out = np.asarray(M.threshold(a, 0.3))
    np.testing.assert_allclose(out, [[0.0, 0.9], [0.5, 0.0]])


def test_norm_rows_eye_fill(rng):
    a = rng.standard_normal((5, 11)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(M.norm_rows(a)), np.linalg.norm(a, axis=1), rtol=1e-5
    )
    np.testing.assert_allclose(np.asarray(M.eye(3)), np.eye(3, dtype=np.float32))
    np.testing.assert_allclose(
        np.asarray(M.fill((2, 2), 7.0)), np.full((2, 2), 7.0, np.float32)
    )
