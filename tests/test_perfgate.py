"""perfgate suite: metric extraction, rolling-baseline comparison with
direction/tolerance semantics, deterministic --json output (the CI
acceptance literally cmp's two runs), and the CLI exit-code contract
(report-only vs --enforce)."""

import json
import subprocess
import sys

import pytest

from tools import perfgate


def entry(sha, value, *, bench="BENCH_x", platform="cpu", case="qps_case",
          unit="qps", **row_extra):
    return {"sha": sha, "utc": "2026-08-03T00:00:00Z", "platform": platform,
            "bench": bench,
            "row": {"case": case, "value": value, "unit": unit, **row_extra}}


def write_ledger(path, entries):
    with open(path, "w") as f:
        for e in entries:
            f.write(json.dumps(e) + "\n")
    return str(path)


# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------

def test_extract_metrics_value_and_named_fields():
    e = {"sha": "a", "platform": "cpu", "bench": "b",
         "row": {"case": "server", "value": 100.0, "unit": "req/s",
                 "p50_ms": 1.5, "p99_ms": 9.0, "recall": 0.97}}
    metrics = dict((m, (v, u)) for m, v, u in perfgate.extract_metrics(e))
    assert metrics["server"] == (100.0, "req/s")
    assert metrics["server:p50_ms"] == (1.5, "ms")
    assert metrics["server:p99_ms"] == (9.0, "ms")
    assert metrics["server:recall"] == (0.97, "recall")


def test_extract_metrics_headline_recall_spelling():
    # bench.py headline rows spell it "recall@10" — the 1% recall band
    # must cover the flagship metric, not just plain "recall" rows
    e = {"sha": "a", "platform": "tpu", "bench": "bench_headline",
         "row": {"metric": "ann_qps_1Mx96_k10_recall95", "value": 5315.2,
                 "unit": "qps", "recall@10": 0.9965}}
    metrics = dict((m, (v, u)) for m, v, u in perfgate.extract_metrics(e))
    assert metrics["ann_qps_1Mx96_k10_recall95:recall@10"] == \
        (0.9965, "recall")


def test_extract_metrics_engine_and_seconds_alias():
    e = {"sha": "a", "platform": "cpu", "bench": "b",
         "row": {"case": "build", "engine": "ivf_rabitq", "seconds": 2.5}}
    metrics = perfgate.extract_metrics(e)
    assert ("build/ivf_rabitq:seconds", 2.5, "s") in metrics


def test_read_ledger_skips_torn_lines(tmp_path):
    p = tmp_path / "ledger.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps(entry("a", 1.0)) + "\n")
        f.write('{"torn": \n')
        f.write(json.dumps(entry("b", 2.0)) + "\n")
        f.write(json.dumps({"no_row_key": 1}) + "\n")
    rows = perfgate.read_ledger(str(p))
    assert [e["sha"] for e in rows] == ["a", "b"]
    assert perfgate.read_ledger(str(tmp_path / "missing")) == []


# ---------------------------------------------------------------------------
# evaluation semantics
# ---------------------------------------------------------------------------

def test_regression_direction_higher_better():
    # qps: fresh 30% below the baseline median -> regression
    entries = [entry("old1", 100.0), entry("old2", 110.0),
               entry("new", 70.0)]
    doc = perfgate.evaluate(entries)
    assert doc["fresh_sha"] == "new"
    (f,) = doc["findings"]
    assert f["status"] == "regression" and f["baseline"] == 105.0
    # within the 20% band -> ok; above it -> improved
    assert perfgate.evaluate([entry("o", 100.0), entry("n", 90.0)])[
        "findings"][0]["status"] == "ok"
    assert perfgate.evaluate([entry("o", 100.0), entry("n", 150.0)])[
        "findings"][0]["status"] == "improved"


def test_regression_direction_lower_better():
    # latency: growing is the regression
    entries = [entry("old", 10.0, case="p99", unit="ms"),
               entry("new", 14.0, case="p99", unit="ms")]
    assert perfgate.evaluate(entries)["findings"][0]["status"] == "regression"
    entries = [entry("old", 10.0, case="p99", unit="ms"),
               entry("new", 7.0, case="p99", unit="ms")]
    assert perfgate.evaluate(entries)["findings"][0]["status"] == "improved"


def test_recall_band_is_tight():
    entries = [entry("old", 0.97, case="recall", unit="recall"),
               entry("new", 0.95, case="recall", unit="recall")]
    assert perfgate.evaluate(entries)["findings"][0]["status"] == "regression"


def test_platform_groups_never_mix():
    # a CPU fallback row must not be gated against chip history
    entries = [entry("old", 5315.0, platform="tpu"),
               entry("new", 90.0, platform="cpu")]
    doc = perfgate.evaluate(entries)
    (f,) = doc["findings"]
    assert f["platform"] == "cpu" and f["status"] == "no_baseline"
    assert doc["regressions"] == 0 and doc["no_baseline"] == 1


def test_rolling_window_bounds_baseline():
    ancient = [entry(f"s{i}", 1000.0) for i in range(10)]
    recent = [entry(f"r{i}", 100.0) for i in range(8)]
    doc = perfgate.evaluate(ancient + recent + [entry("new", 95.0)],
                            window=8)
    (f,) = doc["findings"]
    assert f["baseline"] == 100.0 and f["status"] == "ok"


def test_multiple_fresh_rows_gate_the_last():
    entries = [entry("old", 100.0), entry("new", 50.0), entry("new", 99.0)]
    (f,) = perfgate.evaluate(entries)["findings"]
    assert f["n_fresh"] == 2 and f["fresh"] == 99.0 and f["status"] == "ok"


def test_empty_ledger():
    doc = perfgate.evaluate([])
    assert doc["checked"] == 0 and doc["fresh_sha"] is None


# ---------------------------------------------------------------------------
# determinism + CLI contract
# ---------------------------------------------------------------------------

def _run_cli(args):
    return subprocess.run(
        [sys.executable, "-m", "tools.perfgate", *args],
        capture_output=True, text=True, timeout=60,
        cwd=perfgate.__file__.rsplit("/tools/", 1)[0])


def test_cli_json_deterministic_and_report_only(tmp_path):
    path = write_ledger(tmp_path / "ledger.jsonl", [
        entry("old1", 100.0), entry("old2", 102.0), entry("new", 60.0),
        entry("new", 0.99, case="recall", unit="recall"),
    ])
    r1 = _run_cli(["--ledger", path, "--json"])
    r2 = _run_cli(["--ledger", path, "--json"])
    assert r1.returncode == 0 and r2.returncode == 0  # report-only: exit 0
    assert r1.stdout == r2.stdout  # byte-identical (the acceptance check)
    doc = json.loads(r1.stdout)
    assert doc["regressions"] == 1
    assert doc["ledger"] == "ledger.jsonl"  # basename only, no temp paths
    statuses = {f["metric"]: f["status"] for f in doc["findings"]}
    assert statuses["qps_case"] == "regression"
    assert statuses["recall"] == "no_baseline"


def test_cli_enforce_exit_code(tmp_path):
    path = write_ledger(tmp_path / "ledger.jsonl",
                        [entry("old", 100.0), entry("new", 60.0)])
    assert _run_cli(["--ledger", path]).returncode == 0
    assert _run_cli(["--ledger", path, "--enforce"]).returncode == 1
    ok = write_ledger(tmp_path / "ok.jsonl",
                      [entry("old", 100.0), entry("new", 101.0)])
    assert _run_cli(["--ledger", ok, "--enforce"]).returncode == 0


def test_cli_text_mode_mentions_regressions(tmp_path):
    path = write_ledger(tmp_path / "ledger.jsonl",
                        [entry("old", 100.0), entry("new", 60.0)])
    r = _run_cli(["--ledger", path])
    assert "1 regression(s)" in r.stdout
    assert "[regression " in r.stdout and "qps_case" in r.stdout


def test_fresh_sha_override(tmp_path):
    entries = [entry("a", 100.0), entry("b", 60.0), entry("c", 100.0)]
    doc = perfgate.evaluate(entries, fresh_sha="b")
    (f,) = doc["findings"]
    assert doc["fresh_sha"] == "b" and f["status"] == "regression"


def test_perfgate_never_imports_raft_tpu():
    """raftlint-style independence: the gate must run even when the
    library is broken."""
    r = subprocess.run(
        [sys.executable, "-c",
         "import sys; import tools.perfgate, tools.perfgate.__main__; "
         "sys.exit(1 if any(m.startswith('raft_tpu') for m in sys.modules)"
         " else 0)"],
        capture_output=True, text=True, timeout=60,
        cwd=perfgate.__file__.rsplit("/tools/", 1)[0])
    assert r.returncode == 0, r.stderr
