"""ball_cover / epsilon_neighborhood / masked_nn / gram kernels tests
(mirrors cpp/test/neighbors/ball_cover.cu, epsilon_neighborhood.cu,
cpp/test/distance/masked_nn.cu, gram.cu)."""

import numpy as np
import pytest
from scipy.spatial import distance as spdist

from raft_tpu.neighbors import ball_cover, eps_neighbors, brute_force
from raft_tpu.distance import masked_l2_nn, gram_matrix, KernelParams, KernelType


def latlon(rng, n):
    lat = rng.uniform(-np.pi / 2, np.pi / 2, (n, 1))
    lon = rng.uniform(-np.pi, np.pi, (n, 1))
    return np.concatenate([lat, lon], 1).astype(np.float32)


def test_ball_cover_haversine_exact(rng):
    pts = latlon(rng, 500)
    index = ball_cover.build_index(pts, metric="haversine")
    d, i = ball_cover.all_knn_query(index, 5)
    dbf, ibf = brute_force.knn(pts, pts, 5, metric="haversine")
    np.testing.assert_allclose(np.asarray(d), np.asarray(dbf), rtol=1e-4, atol=1e-5)
    # self-match on first column
    np.testing.assert_array_equal(np.asarray(i)[:, 0], np.arange(500))


def test_ball_cover_query_subset(rng):
    pts = rng.random((300, 3), dtype=np.float32)
    index = ball_cover.build_index(pts, metric="sqeuclidean", n_landmarks=16)
    q = rng.random((20, 3), dtype=np.float32)
    d, i = ball_cover.knn_query(index, q, 4)
    dbf, ibf = brute_force.knn(pts, q, 4)
    np.testing.assert_allclose(np.asarray(d), np.asarray(dbf), rtol=1e-3, atol=1e-5)


def test_eps_neighbors(rng):
    x = rng.random((40, 4), dtype=np.float32)
    y = rng.random((60, 4), dtype=np.float32)
    eps = 0.3
    adj, deg = eps_neighbors(x, y, eps)
    full = spdist.cdist(x, y, "sqeuclidean")
    want = full <= eps
    np.testing.assert_array_equal(np.asarray(adj), want)
    np.testing.assert_array_equal(np.asarray(deg), want.sum(1))


def test_masked_l2_nn(rng):
    x = rng.random((50, 8), dtype=np.float32)
    y = rng.random((80, 8), dtype=np.float32)
    groups = rng.integers(0, 4, 80)
    adj = rng.random((50, 4)) > 0.4
    adj[0] = False  # row with nothing allowed
    d, i = masked_l2_nn(x, y, adj, groups)
    d, i = np.asarray(d), np.asarray(i)
    full = spdist.cdist(x, y, "sqeuclidean")
    for r in range(50):
        allowed = adj[r][groups]
        if not allowed.any():
            assert i[r] == -1 and np.isinf(d[r])
            continue
        masked = np.where(allowed, full[r], np.inf)
        assert i[r] == masked.argmin()
        np.testing.assert_allclose(d[r], masked.min(), rtol=1e-3, atol=1e-4)


def test_gram_kernels(rng):
    x = rng.random((10, 6), dtype=np.float32)
    y = rng.random((8, 6), dtype=np.float32)
    lin = np.asarray(gram_matrix(x, y))
    np.testing.assert_allclose(lin, x @ y.T, rtol=1e-4)
    poly = np.asarray(
        gram_matrix(x, y, KernelParams(KernelType.POLYNOMIAL, degree=2, gamma=0.5, coef0=1.0))
    )
    np.testing.assert_allclose(poly, (0.5 * x @ y.T + 1.0) ** 2, rtol=1e-4)
    rbf = np.asarray(gram_matrix(x, y, KernelParams(KernelType.RBF, gamma=0.7)))
    want = np.exp(-0.7 * spdist.cdist(x, y, "sqeuclidean"))
    np.testing.assert_allclose(rbf, want, rtol=1e-4, atol=1e-5)
    th = np.asarray(gram_matrix(x, y, KernelParams(KernelType.TANH, gamma=0.3, coef0=0.1)))
    np.testing.assert_allclose(th, np.tanh(0.3 * x @ y.T + 0.1), rtol=1e-4)


@pytest.mark.slow
def test_batch_load_iterator():
    """ann_utils.cuh:388 batch_load_iterator parity: uniform padded blocks,
    valid counts, and streamed extend producing the same index contents."""
    import numpy as np
    import jax.numpy as jnp
    from raft_tpu.neighbors import BatchLoadIterator, ivf_flat
    from raft_tpu.neighbors.batch_loader import extend_batched

    rng = np.random.default_rng(0)
    x = rng.random((1000, 16), dtype=np.float32)
    it = BatchLoadIterator(x, batch_size=256)
    blocks = list(it)
    assert len(blocks) == len(it) == 4
    assert all(b.shape == (256, 16) for b, _ in blocks)
    assert [v for _, v in blocks] == [256, 256, 256, 232]
    recon = np.concatenate([np.asarray(b)[:v] for b, v in blocks])
    np.testing.assert_array_equal(recon, x)
    # empty input
    assert list(BatchLoadIterator(x[:0], 64)) == []

    # streamed build: train on a head sample, extend batch-by-batch
    params = ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4, add_data_on_build=False)
    idx = ivf_flat.build(params, x[:200])
    idx = extend_batched(ivf_flat.extend, idx, x, batch_size=300)
    assert idx.size == 1000
    d, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=8), idx, jnp.asarray(x[:5]), 1)
    np.testing.assert_array_equal(np.asarray(i)[:, 0], np.arange(5))


def test_ball_cover_two_pass_pruning_exact(rng):
    """The default (n_probes=0) query is EXACT through the two-pass
    triangle pruning — and on clustered data pass 1 + the pruned pass 2
    probe fewer balls than L (the pruning actually fires)."""
    import jax.numpy as jnp

    from raft_tpu.neighbors import ball_cover as bc
    from raft_tpu.random import make_blobs

    pts, _ = make_blobs(4000, 3, n_clusters=24, cluster_std=0.25, seed=3)
    pts = np.asarray(pts)
    q = pts[::97][:40]
    index = ball_cover.build_index(pts, metric="sqeuclidean")
    d, i = ball_cover.knn_query(index, q, 5)
    dbf, ibf = brute_force.knn(pts, q, 5)
    np.testing.assert_allclose(np.asarray(d), np.asarray(dbf), rtol=1e-3,
                               atol=1e-5)
    # the prune bound itself: count surviving balls — far below L on
    # clustered data (this is the work the two-pass scheme skips)
    lb = bc._landmark_lower_bounds(index, jnp.asarray(q))
    bound = bc._root_domain(index, jnp.asarray(np.asarray(d))[:, 4])
    survived = int(jnp.max(jnp.sum(lb <= bound[:, None], axis=1)))
    assert survived < index.n_landmarks // 2, (survived, index.n_landmarks)


def test_ball_cover_squared_metric_root_domain(rng):
    """sqeuclidean bounds must compare in the root domain (the triangle
    inequality does not hold on squared distances): adversarial far-apart
    clusters stay exact."""
    a = rng.random((200, 2), dtype=np.float32)
    b = rng.random((200, 2), dtype=np.float32) + 50.0  # far cluster
    pts = np.concatenate([a, b])
    q = np.concatenate([a[:5], b[:5]])
    index = ball_cover.build_index(pts, metric="sqeuclidean", n_landmarks=20)
    d, i = ball_cover.knn_query(index, q, 3)
    dbf, _ = brute_force.knn(pts, q, 3)
    np.testing.assert_allclose(np.asarray(d), np.asarray(dbf), rtol=1e-3,
                               atol=1e-5)


def test_ball_cover_non_metric_and_empty(rng):
    """Non-triangle metrics (cosine) stay exact by probing every ball;
    empty query batches return empty results instead of crashing."""
    pts = rng.random((400, 4), dtype=np.float32) + 0.1
    q = pts[:12]
    index = ball_cover.build_index(pts, metric="cosine", n_landmarks=16)
    d, i = ball_cover.knn_query(index, q, 3)
    dbf, _ = brute_force.knn(pts, q, 3, metric="cosine")
    np.testing.assert_allclose(np.asarray(d), np.asarray(dbf), rtol=1e-3,
                               atol=1e-5)
    d0, i0 = ball_cover.knn_query(index, np.empty((0, 4), np.float32), 3)
    assert np.asarray(d0).shape == (0, 3) and np.asarray(i0).shape == (0, 3)
