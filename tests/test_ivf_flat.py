"""IVF-Flat tests: recall-gated against brute force (mirrors
cpp/test/neighbors/ann_ivf_flat fixtures + ann_utils.cuh:121 eval_neighbours)."""

import numpy as np
import pytest

from raft_tpu.neighbors import brute_force, ivf_flat
from raft_tpu.random import make_blobs


def recall(found: np.ndarray, truth: np.ndarray) -> float:
    hits = 0
    for f, t in zip(found, truth):
        hits += len(set(f.tolist()) & set(t.tolist()))
    return hits / truth.size


@pytest.fixture(scope="module")
def dataset():
    data, _ = make_blobs(20000, 32, n_clusters=50, cluster_std=1.0, seed=21)
    q, _ = make_blobs(100, 32, n_clusters=50, cluster_std=1.0, seed=22)
    return np.asarray(data), np.asarray(q)


def test_build_and_search_recall(dataset):
    data, queries = dataset
    params = ivf_flat.IndexParams(n_lists=64, kmeans_n_iters=15)
    index = ivf_flat.build(params, data)
    assert index.size == len(data)
    assert index.n_lists == 64
    _, truth = brute_force.knn(data, queries, 10)
    truth = np.asarray(truth)

    d, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=16), index, queries, 10)
    r = recall(np.asarray(i), truth)
    assert r >= 0.95, f"recall {r}"
    # distances sorted ascending
    d = np.asarray(d)
    assert np.all(np.diff(d, axis=1) >= -1e-5)


def test_more_probes_higher_recall(dataset):
    data, queries = dataset
    index = ivf_flat.build(ivf_flat.IndexParams(n_lists=64), data)
    _, truth = brute_force.knn(data, queries, 10)
    truth = np.asarray(truth)
    r_few = recall(
        np.asarray(ivf_flat.search(ivf_flat.SearchParams(n_probes=1), index, queries, 10)[1]),
        truth,
    )
    r_all = recall(
        np.asarray(ivf_flat.search(ivf_flat.SearchParams(n_probes=64), index, queries, 10)[1]),
        truth,
    )
    assert r_all >= r_few
    assert r_all >= 0.999  # probing everything == exact


def test_inner_product_metric(dataset):
    data, queries = dataset
    from raft_tpu.distance import DistanceType

    index = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=32, metric=DistanceType.InnerProduct), data
    )
    d, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=32), index, queries, 5)
    _, truth = brute_force.knn(data, queries, 5, metric="inner_product")
    r = recall(np.asarray(i), np.asarray(truth))
    assert r >= 0.999  # all lists probed -> exact
    d = np.asarray(d)
    assert np.all(np.diff(d, axis=1) <= 1e-5)  # descending similarity


def test_extend(dataset):
    data, queries = dataset
    params = ivf_flat.IndexParams(n_lists=32, add_data_on_build=False)
    index = ivf_flat.build(params, data)
    assert index.size == 0
    index = ivf_flat.extend(index, data[:5000])
    assert index.size == 5000
    index = ivf_flat.extend(index, data[5000:])
    assert index.size == len(data)
    d, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=8), index, queries, 5)
    _, truth = brute_force.knn(data, queries, 5)
    # extend assigned ids 0..n in order, so ids match row numbers
    r = recall(np.asarray(i), np.asarray(truth))
    assert r >= 0.9


def test_adaptive_centers(dataset):
    data, _ = dataset
    params = ivf_flat.IndexParams(n_lists=16, adaptive_centers=True, add_data_on_build=False)
    index = ivf_flat.build(params, data[:4000])
    c0 = np.asarray(index.centers).copy()
    index = ivf_flat.extend(index, data[4000:8000])
    c1 = np.asarray(index.centers)
    assert not np.allclose(c0, c1)  # centers moved with the data


def test_save_load_roundtrip(dataset, tmp_path):
    data, queries = dataset
    index = ivf_flat.build(ivf_flat.IndexParams(n_lists=32), data)
    f = str(tmp_path / "ivf_flat.bin")
    ivf_flat.save(f, index)
    loaded = ivf_flat.load(f)
    assert loaded.n_lists == index.n_lists and loaded.metric == index.metric
    d0, i0 = ivf_flat.search(ivf_flat.SearchParams(n_probes=8), index, queries, 5)
    d1, i1 = ivf_flat.search(ivf_flat.SearchParams(n_probes=8), loaded, queries, 5)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_allclose(np.asarray(d0), np.asarray(d1), rtol=1e-6)


def test_list_major_engine(dataset):
    """List-major engine streams each list once per batch; results must
    match the exact query-major engine (modulo the 0.99 chunk-trim target
    and top-k ties)."""
    data, queries = dataset
    index = ivf_flat.build(ivf_flat.IndexParams(n_lists=64), data)
    _, i_q = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=32, engine="query"), index, queries, 10
    )
    d_l, i_l = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=32, engine="list"), index, queries, 10
    )
    i_q, i_l = np.asarray(i_q), np.asarray(i_l)
    overlap = np.mean([len(set(i_q[r]) & set(i_l[r])) / 10 for r in range(len(i_q))])
    assert overlap >= 0.95, f"engine disagreement: {overlap}"
    assert np.all(np.diff(np.asarray(d_l), axis=1) >= -1e-4)
    # auto dispatch: large batch -> list engine; both shapes well-formed
    d_a, i_a = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=32, engine="auto"), index, queries, 10
    )
    assert np.asarray(i_a).shape == (len(queries), 10)
    # empty batch through the list engine returns (0, k)
    d0, i0 = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=8, engine="list"), index, queries[:0], 5
    )
    assert np.asarray(d0).shape == (0, 5) and np.asarray(i0).shape == (0, 5)
    with pytest.raises(ValueError):
        ivf_flat.search(ivf_flat.SearchParams(engine="nope"), index, queries, 5)


def test_listmajor_setup_impl_equivalence_flat(dataset, monkeypatch):
    """Flat-engine mirror of the PQ setup-impl equivalence gate (ADVICE
    r5): invert_impl=count and qs_impl=onehot_f32h are bit-preserving on
    the IVF-Flat list-major engine; a SHARED tuned onehot_bf16 winner is
    gated back to gather for flat (this engine scores at f32
    Precision.HIGHEST — bf16-rounded query rows would silently degrade
    it); the flat-specific key `listmajor_qs_impl_flat` opts bf16 in
    explicitly (overlap gate, near-ties only)."""
    from raft_tpu.core import tuned
    from raft_tpu.neighbors.probe_invert import resolve_qs_impl

    data, queries = dataset
    index = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=8), data[:5000])
    p = ivf_flat.SearchParams(n_probes=8, engine="list")
    d_ref, i_ref = ivf_flat.search(p, index, queries, 10)
    i_ref = np.asarray(i_ref)

    real = tuned.get_choice

    def force(**keys):
        def fake(key, allowed, default):
            return keys[key] if key in keys else real(key, allowed, default)

        monkeypatch.setattr(tuned, "get_choice", fake)
        out = ivf_flat.search(p, index, queries, 10)
        monkeypatch.setattr(tuned, "get_choice", real)
        return out

    # counting inversion + f32-highest one-hot: bit-preserving
    d_c, i_c = force(invert_impl="count", listmajor_qs_impl="onehot_f32h")
    assert np.array_equal(np.asarray(i_c), i_ref)
    np.testing.assert_allclose(np.asarray(d_c), np.asarray(d_ref), rtol=1e-6)

    # the SHARED bf16 winner resolves to gather on flat -> bit-equal
    def fake_shared_bf16(key, allowed, default):
        if key == "listmajor_qs_impl":
            return "onehot_bf16"
        return real(key, allowed, default)

    monkeypatch.setattr(tuned, "get_choice", fake_shared_bf16)
    assert resolve_qs_impl("flat") == "gather"
    assert resolve_qs_impl("pq") == "onehot_bf16"
    _, i_g = ivf_flat.search(p, index, queries, 10)
    monkeypatch.setattr(tuned, "get_choice", real)
    assert np.array_equal(np.asarray(i_g), i_ref)

    # the flat-specific key opts bf16 in explicitly: near-ties only
    def fake_flat_bf16(key, allowed, default):
        if key == "listmajor_qs_impl_flat":
            return "onehot_bf16"
        return real(key, allowed, default)

    monkeypatch.setattr(tuned, "get_choice", fake_flat_bf16)
    assert resolve_qs_impl("flat") == "onehot_bf16"
    _, i_b = ivf_flat.search(p, index, queries, 10)
    monkeypatch.setattr(tuned, "get_choice", real)
    i_b = np.asarray(i_b)
    overlap = np.mean(
        [len(set(i_b[r]) & set(i_ref[r])) / 10 for r in range(len(i_ref))]
    )
    assert overlap >= 0.95, f"bf16 one-hot moved results: overlap {overlap}"


def test_pallas_fused_engine(dataset):
    """The fused Pallas list-scan engine (interpret mode on CPU) must agree
    with the exact query-major engine, pad the store monotonically, and
    keep the index extendable afterwards."""
    data, queries = dataset
    index = ivf_flat.build(ivf_flat.IndexParams(n_lists=64), data[:18000])
    _, i_q = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=32, engine="query"), index, queries, 10
    )
    d_p, i_p = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=32, engine="pallas"), index, queries, 10
    )
    i_q, i_p = np.asarray(i_q), np.asarray(i_p)
    overlap = np.mean([len(set(i_q[r]) & set(i_p[r])) / 10 for r in range(len(i_q))])
    assert overlap >= 0.95, f"pallas/query disagreement: {overlap}"
    assert np.all(np.diff(np.asarray(d_p), axis=1) >= -1e-4)
    # store got lane-padded in place (monotone)
    lpad = index.list_data.shape[1]
    assert lpad % 128 == 0 and lpad >= 256
    assert index.slot_rows.shape[1] == lpad
    # query engine still correct on the padded store
    _, i_q2 = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=32, engine="query"), index, queries, 10
    )
    np.testing.assert_array_equal(np.asarray(i_q2), i_q)
    # extend still works on the padded store and new rows are findable
    index = ivf_flat.extend(index, data[18000:])
    assert index.size == len(data)
    _, truth = brute_force.knn(data, queries, 10)
    d3, i3 = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=32, engine="pallas"), index, queries, 10
    )
    r = recall(np.asarray(i3), np.asarray(truth))
    assert r >= 0.9, f"post-extend pallas recall {r}"
    # IP metric through the fused kernel
    ip_index = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=64, metric="inner_product"), data
    )
    _, truth_ip = brute_force.knn(data, queries, 10, metric="inner_product")
    _, i_ip = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=64, engine="pallas"), ip_index, queries, 10
    )
    r_ip = recall(np.asarray(i_ip), np.asarray(truth_ip))
    assert r_ip >= 0.9, f"pallas IP recall {r_ip}"
    # k over the bin cap is rejected without mutating a fresh index
    small = ivf_flat.build(ivf_flat.IndexParams(n_lists=8), data[:2000])
    w = small.list_data.shape[1]
    with pytest.raises(ValueError, match="pallas"):
        ivf_flat.search(ivf_flat.SearchParams(engine="pallas"), small, queries, 300)
    assert small.list_data.shape[1] == w


def test_int8_uint8_datasets():
    """Reference parity: ivf_flat supports T in {float, int8, uint8}
    (ivf_flat_types.hpp index<T,IdxT>; pylibraft accepts all three). The
    store keeps the input dtype; scoring casts to f32."""
    rng = np.random.default_rng(0)
    for dt, lo, hi in ((np.int8, -100, 100), (np.uint8, 0, 200)):
        data = rng.integers(lo, hi, (5000, 16)).astype(dt)
        q = data[:20]
        index = ivf_flat.build(ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=5), data)
        assert index.list_data.dtype == dt
        _, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=16), index, q, 5)
        _, t = brute_force.knn(data.astype(np.float32), q.astype(np.float32), 5)
        r = recall(np.asarray(i), np.asarray(t))
        assert r >= 0.99, f"{dt} recall {r}"  # all lists probed -> near exact
        # list-major engine handles integer stores too
        _, il = ivf_flat.search(
            ivf_flat.SearchParams(n_probes=16, engine="list"), index, q, 5
        )
        assert recall(np.asarray(il), np.asarray(t)) >= 0.95


def test_validation(dataset):
    data, queries = dataset
    index = ivf_flat.build(ivf_flat.IndexParams(n_lists=16), data)
    with pytest.raises(ValueError):
        ivf_flat.search(ivf_flat.SearchParams(), index, queries[:, :10], 5)
    with pytest.raises(ValueError):
        ivf_flat.build(ivf_flat.IndexParams(n_lists=10**6), data)


def test_pallas_fused_kb_grows_with_k(dataset):
    """The lazy pallas store records the candidate-buffer width the
    fused kernel was compiled for (Index.fused_kb); a later search with
    k past that width must GROW it (monotone, like the lane pad) —
    never silently truncate the per-list candidates to the stale
    width."""
    data, queries = dataset
    index = ivf_flat.build(ivf_flat.IndexParams(n_lists=8), data[:8000])
    p = ivf_flat.SearchParams(n_probes=8, engine="pallas")
    assert index.fused_kb is None
    ivf_flat.search(p, index, queries, 10)
    assert index.fused_kb == 128
    # k past the compiled width: the store invalidation must widen the
    # buffer...
    d_p, i_p = ivf_flat.search(p, index, queries, 200)
    assert index.fused_kb == 256
    # ...and the widened run really carries 200 candidates per
    # (query, list): it agrees with the exact query-major engine (all
    # lists probed -> both are exact modulo the bf16 residual round)
    _, i_q = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=8, engine="query"), index, queries,
        200,
    )
    i_p, i_q = np.asarray(i_p), np.asarray(i_q)
    overlap = np.mean(
        [len(set(i_p[r]) & set(i_q[r])) / 200 for r in range(len(i_p))]
    )
    assert overlap >= 0.95, f"truncated candidates: overlap {overlap}"
    assert np.all(np.diff(np.asarray(d_p), axis=1) >= -1e-4)
    # a smaller k afterwards keeps the wider compiled width (monotone)
    ivf_flat.search(p, index, queries, 5)
    assert index.fused_kb == 256


def test_pallas_packed_fold_engine(monkeypatch):
    """pallas_fold="packed" routes the IVF-PQ pallas trim through the
    bf16-coarse fold (fold_variant() wiring): results must track the
    exact-fold engine at trim-noise level. (The IVF-Flat fused engine
    no longer consults the fold knob — its in-kernel select is exact by
    construction, tests/test_fused_scan.py.)"""
    from raft_tpu.core import tuned
    from raft_tpu.neighbors import ivf_pq

    rng = np.random.default_rng(5)
    data = rng.random((6000, 32), dtype=np.float32)
    queries = data[:40]
    index = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=16, kmeans_n_iters=4, pq_dim=16), data
    )
    p = ivf_pq.SearchParams(n_probes=16, trim_engine="pallas")
    # pin the baseline: a committed pallas_fold="packed" tuned key must
    # not silently turn this into packed-vs-packed
    monkeypatch.setitem(tuned._load(), "pallas_fold", "exact")
    i_exact = np.asarray(ivf_pq.search(p, index, queries, 10)[1])
    monkeypatch.setitem(tuned._load(), "pallas_fold", "packed")
    try:
        d_p, i_p = ivf_pq.search(p, index, queries, 10)
    finally:
        tuned.reload()
    i_p = np.asarray(i_p)
    overlap = np.mean(
        [len(set(i_exact[r]) & set(i_p[r])) / 10 for r in range(len(i_exact))]
    )
    assert overlap >= 0.9, f"packed fold diverged: overlap {overlap}"
    assert np.all(np.diff(np.asarray(d_p), axis=1) >= -1e-4)


def test_build_trainset_subsample_unbiased_on_sorted_data():
    """VERDICT r4 #8: the trainset must be a random subsample, not the
    first n_train rows (parity with ivf_flat_build.cuh's subsampled
    trainset). On a cluster-sorted dataset a first-n slice trains
    centers on a fraction of the clusters only."""
    import numpy as np

    from raft_tpu.neighbors import brute_force, ivf_flat
    from raft_tpu.random import make_blobs

    data, labels = make_blobs(12000, 24, n_clusters=24, cluster_std=0.6, seed=7)
    data = np.asarray(data)[np.argsort(np.asarray(labels), kind="stable")]
    queries = data[:: len(data) // 64][:64]
    idx = ivf_flat.build(
        ivf_flat.IndexParams(
            n_lists=24, kmeans_trainset_fraction=0.1, kmeans_n_iters=10
        ),
        data,
    )
    # the bias shows up as list imbalance, not recall (search still finds
    # the crammed lists): centers trained on the first-n rows see only
    # the first few clusters and the rest of the data piles into a few
    # lists — measured max_list 5307 vs 548 (mean 500) at this geometry
    sizes = np.asarray(idx.list_sizes)
    assert sizes.max() <= 2.5 * sizes.mean(), sizes
    _, t = brute_force.knn(data, queries, 10)
    _, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=8), idx, queries, 10)
    t, i = np.asarray(t), np.asarray(i)
    rec = np.mean([len(set(i[r]) & set(t[r])) / 10 for r in range(len(t))])
    assert rec >= 0.9, rec
