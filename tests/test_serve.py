"""Serving-engine suite: micro-batch/unbatched bit-identity across the
three index families, admission control (deadlines, rejection,
backpressure, overload degradation), metrics accounting over a 1k-query
threaded run, and the chaos cases (slow-rank degraded serving, slow
batch dispatch) under seeded FaultPlans."""

import os
import threading
import time

import numpy as np
import pytest

from raft_tpu import serve
from raft_tpu.core import faults
from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq
from raft_tpu.random import make_blobs

SEED = int(os.environ.get(faults.ENV_SEED, "1234"))


@pytest.fixture(scope="module")
def blobs():
    data, _ = make_blobs(1024, 16, n_clusters=6, cluster_std=0.4, seed=13)
    return np.asarray(data)


@pytest.fixture(scope="module")
def queries():
    rng = np.random.default_rng(7)
    return [rng.standard_normal((n, 16)).astype(np.float32) for n in (3, 5, 7)]


@pytest.fixture(scope="module")
def flat_idx(blobs):
    return ivf_flat.build(ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=3), blobs)


@pytest.fixture(scope="module")
def pq_idx(blobs):
    return ivf_pq.build(
        ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=3), blobs)


class CountingSearcher(serve.Searcher):
    """Wraps a searcher and counts device executions (proves expired
    requests never reach the device)."""

    def __init__(self, inner):
        self.inner = inner
        self.dim = inner.dim
        self.calls = 0

    def search(self, queries, k, probe_scale=1.0, recall_target=None):
        self.calls += 1
        return self.inner.search(queries, k, probe_scale, recall_target)


# -- batching / bit-identity -------------------------------------------

def test_bucket_ladder():
    assert serve.bucket_for(1, (8, 32)) == 8
    assert serve.bucket_for(8, (8, 32)) == 8
    assert serve.bucket_for(9, (8, 32)) == 32
    with pytest.raises(ValueError):
        serve.bucket_for(33, (8, 32))


def _assert_bit_identical(server, queries, k, reference_fn):
    futs = [server.submit(q, k=k) for q in queries]
    while not all(f.done() for f in futs):
        assert server.step() > 0, "queued requests but nothing served"
    for q, f in zip(queries, futs):
        want_v, want_i = reference_fn(q, k)
        got = f.result(timeout=1.0)
        np.testing.assert_array_equal(np.asarray(want_i), got.ids)
        np.testing.assert_array_equal(np.asarray(want_v), got.values)
        assert got.coverage == 1.0


def test_batched_equals_unbatched_brute_force(blobs, queries):
    server = serve.SearchServer(blobs, serve.ServerConfig(buckets=(8, 32)))
    _assert_bit_identical(
        server, queries, 6, lambda q, k: brute_force.knn(blobs, q, k))


def test_batched_equals_unbatched_ivf_flat(flat_idx, queries):
    sp = ivf_flat.SearchParams(n_probes=4, engine="query")
    server = serve.SearchServer(
        flat_idx, serve.ServerConfig(buckets=(8, 32)), search_params=sp)
    _assert_bit_identical(
        server, queries, 6, lambda q, k: ivf_flat.search(sp, flat_idx, q, k))


def test_batched_equals_unbatched_ivf_pq(pq_idx, queries):
    sp = ivf_pq.SearchParams(n_probes=4, score_mode="recon8")
    server = serve.SearchServer(
        pq_idx, serve.ServerConfig(buckets=(8, 32)), search_params=sp)
    _assert_bit_identical(
        server, queries, 6, lambda q, k: ivf_pq.search(sp, pq_idx, q, k))


def test_batched_equals_unbatched_ivf_rabitq(blobs, queries):
    from raft_tpu.neighbors import ivf_rabitq

    rb_idx = ivf_rabitq.build(
        ivf_rabitq.IndexParams(n_lists=8, kmeans_n_iters=3),
        np.asarray(blobs, np.float32))
    sp = ivf_rabitq.SearchParams(n_probes=4, rerank_mult=4)
    server = serve.SearchServer(
        rb_idx, serve.ServerConfig(buckets=(8, 32)), search_params=sp)
    assert isinstance(server.searcher, serve.IvfRabitqSearcher)
    _assert_bit_identical(
        server, queries, 6, lambda q, k: ivf_rabitq.search(sp, rb_idx, q, k))


def test_auto_modes_refused_for_serving(flat_idx, pq_idx):
    # auto engines resolve per batch shape -> numerics would depend on
    # batch-mates; the adapters must refuse them
    with pytest.raises(ValueError, match="auto"):
        serve.SearchServer(
            flat_idx, search_params=ivf_flat.SearchParams(engine="auto"))
    with pytest.raises(ValueError, match="auto"):
        serve.SearchServer(
            pq_idx, search_params=ivf_pq.SearchParams(score_mode="auto"))


def test_mixed_k_requests_split_batches(blobs, queries):
    server = serve.SearchServer(blobs, serve.ServerConfig(buckets=(8, 32)))
    f5 = server.submit(queries[0], k=5)
    f7 = server.submit(queries[1], k=7)
    assert server.step() == 1  # only the k=5 request merges
    assert f5.done() and not f7.done()
    assert server.step() == 1
    assert f7.result(1.0).ids.shape == (queries[1].shape[0], 7)
    assert f5.result(1.0).ids.shape == (queries[0].shape[0], 5)


def test_sync_search_and_1d_query(blobs):
    server = serve.SearchServer(blobs, serve.ServerConfig(buckets=(8,)))
    reply = server.search(np.zeros(16, np.float32), k=3, timeout=5.0)
    assert reply.ids.shape == (1, 3)


def test_submit_validation(blobs):
    server = serve.SearchServer(blobs, serve.ServerConfig(buckets=(8, 32)))
    with pytest.raises(ValueError, match="dim"):
        server.submit(np.zeros((2, 9), np.float32), k=3)
    with pytest.raises(ValueError, match="largest bucket"):
        server.submit(np.zeros((33, 16), np.float32), k=3)
    with pytest.raises(ValueError, match="k must be positive"):
        server.submit(np.zeros((2, 16), np.float32), k=0)


# -- admission ----------------------------------------------------------

def test_deadline_expired_rejected_without_executing(blobs):
    counting = CountingSearcher(serve.BruteForceSearcher(blobs))
    server = serve.SearchServer(counting, serve.ServerConfig(buckets=(8,)))
    fut = server.submit(np.zeros((2, 16), np.float32), k=3, deadline_s=1e-3)
    time.sleep(5e-3)
    assert server.step() == 1  # the expiry counts as an answer
    with pytest.raises(serve.DeadlineExceeded):
        fut.result(timeout=0.1)
    assert counting.calls == 0
    assert server.metrics.snapshot()["expired"] == 1


def test_default_deadline_from_config(blobs):
    counting = CountingSearcher(serve.BruteForceSearcher(blobs))
    cfg = serve.ServerConfig(
        buckets=(8,),
        admission=serve.AdmissionConfig(default_deadline_s=1e-3))
    server = serve.SearchServer(counting, cfg)
    fut = server.submit(np.zeros((1, 16), np.float32), k=3)
    time.sleep(5e-3)
    server.step()
    with pytest.raises(serve.DeadlineExceeded):
        fut.result(timeout=0.1)
    assert counting.calls == 0


def test_reject_policy_full_queue(blobs):
    cfg = serve.ServerConfig(
        buckets=(8,),
        admission=serve.AdmissionConfig(max_pending_rows=8, policy="reject"))
    server = serve.SearchServer(blobs, cfg)
    server.submit(np.zeros((6, 16), np.float32), k=3)
    with pytest.raises(serve.RejectedError):
        server.submit(np.zeros((6, 16), np.float32), k=3)
    assert server.metrics.snapshot()["rejected"] == 1
    # room frees after a batch drains
    server.step()
    server.submit(np.zeros((6, 16), np.float32), k=3)


def test_block_policy_timeout_and_unblock(blobs):
    cfg = serve.ServerConfig(
        buckets=(8,),
        admission=serve.AdmissionConfig(
            max_pending_rows=8, policy="block", block_timeout_s=0.05))
    server = serve.SearchServer(blobs, cfg)
    server.submit(np.zeros((8, 16), np.float32), k=3)
    # full queue + nobody draining -> the blocked submit times out
    t0 = time.monotonic()
    with pytest.raises(serve.RejectedError):
        server.submit(np.zeros((4, 16), np.float32), k=3)
    assert time.monotonic() - t0 >= 0.04
    # with a drainer running, the same submit unblocks instead
    done = threading.Event()

    def drain():
        while not done.is_set() and server.batcher.pending_rows:
            server.step()
        done.set()

    t = threading.Thread(target=drain)
    t.start()
    fut = server.submit(np.zeros((4, 16), np.float32), k=3)
    server.step()
    assert fut.result(timeout=5.0).ids.shape == (4, 3)
    done.set()
    t.join(timeout=5.0)


def test_oversized_request_always_rejected(blobs):
    cfg = serve.ServerConfig(
        buckets=(8,), admission=serve.AdmissionConfig(max_pending_rows=4))
    server = serve.SearchServer(blobs, cfg)
    with pytest.raises(serve.RejectedError, match="split"):
        server.batcher.submit(np.zeros((6, 16), np.float32), k=3)


def test_probe_scale_degradation_curve():
    ctl = serve.AdmissionController(serve.AdmissionConfig(
        max_pending_rows=100, degrade_at=0.5, min_probe_scale=0.25))
    assert ctl.probe_scale(0) == 1.0
    assert ctl.probe_scale(50) == 1.0
    assert np.isclose(ctl.probe_scale(75), 0.625)
    assert np.isclose(ctl.probe_scale(100), 0.25)
    assert np.isclose(ctl.probe_scale(10_000), 0.25)  # clamped past full


def test_overload_shrinks_probes(flat_idx):
    seen = []

    class ProbeSpy(serve.IvfFlatSearcher):
        def search(self, queries, k, probe_scale=1.0, recall_target=None):
            seen.append(probe_scale)
            return super().search(queries, k, probe_scale, recall_target)

    cfg = serve.ServerConfig(
        buckets=(8,),
        admission=serve.AdmissionConfig(
            max_pending_rows=16, degrade_at=0.25, min_probe_scale=0.25))
    spy = ProbeSpy(flat_idx, ivf_flat.SearchParams(n_probes=8, engine="query"))
    server = serve.SearchServer(spy, cfg)
    for _ in range(2):
        server.submit(np.zeros((8, 16), np.float32), k=3)
    server.step()  # 8 rows still queued when this batch dispatches
    assert seen and seen[0] < 1.0


def test_server_closed(blobs):
    server = serve.SearchServer(blobs, serve.ServerConfig(buckets=(8,)))
    fut = server.submit(np.zeros((2, 16), np.float32), k=3)
    server.stop()
    with pytest.raises(serve.ServerClosed):
        fut.result(timeout=0.1)
    with pytest.raises(serve.ServerClosed):
        server.submit(np.zeros((2, 16), np.float32), k=3)
    # the lifecycle is one-shot: a restart would silently serve nothing
    with pytest.raises(serve.ServerClosed, match="one-shot"):
        server.start()


def test_flaky_batch_fault_delivered_not_raised(blobs):
    """An injected flaky fault at the dispatch site must fail the
    batch's futures, not kill the worker/step caller."""
    server = serve.SearchServer(blobs, serve.ServerConfig(buckets=(8,)))
    plan = faults.FaultPlan(
        [faults.Fault(kind="flaky_bootstrap", site="serve.batch", count=1)],
        seed=SEED,
    )
    fut = server.submit(np.zeros((1, 16), np.float32), k=3)
    with plan.install():
        assert server.step() == 1  # must not raise
    with pytest.raises(faults.FaultInjected):
        fut.result(timeout=0.1)
    assert server.metrics.snapshot()["failed"] == 1
    # the server keeps serving afterwards
    fut = server.submit(np.zeros((1, 16), np.float32), k=3)
    server.step()
    assert fut.result(timeout=1.0).ids.shape == (1, 3)


def test_all_expired_queue_wakes_blocked_submitter(blobs):
    """When every queued request expires at collect time, a blocked
    submitter must be woken by the freed room, not sleep out its whole
    block_timeout_s."""
    cfg = serve.ServerConfig(
        buckets=(8,),
        admission=serve.AdmissionConfig(
            max_pending_rows=8, policy="block", block_timeout_s=30.0))
    server = serve.SearchServer(blobs, cfg)
    server.submit(np.zeros((8, 16), np.float32), k=3, deadline_s=1e-3)
    time.sleep(5e-3)  # the queued request is now expired

    worker = threading.Thread(target=lambda: (time.sleep(0.05), server.step()))
    worker.start()
    t0 = time.monotonic()
    fut = server.submit(np.zeros((4, 16), np.float32), k=3)  # blocks, then wakes
    blocked_s = time.monotonic() - t0
    worker.join(timeout=5.0)
    server.step()
    assert fut.result(timeout=5.0).ids.shape == (4, 3)
    assert blocked_s < 5.0  # freed room woke it; nowhere near the 30s timeout


# -- metrics ------------------------------------------------------------

def test_metrics_after_1k_query_run(blobs):
    rng = np.random.default_rng(3)
    all_q = rng.standard_normal((1000, 16)).astype(np.float32)
    want_v, want_i = brute_force.knn(blobs, all_q, 10)
    want_v, want_i = np.asarray(want_v), np.asarray(want_i)
    cfg = serve.ServerConfig(buckets=(16, 64, 256), max_wait_ms=1.0,
                             warmup_k=10)
    with serve.SearchServer(blobs, cfg) as server:
        results = [None] * all_q.shape[0]

        def client(lo, hi):
            futs = [(i, server.submit(all_q[i], k=10)) for i in range(lo, hi)]
            for i, fut in futs:
                results[i] = fut.result(timeout=60.0)

        threads = [threading.Thread(target=client, args=(lo, lo + 250))
                   for lo in range(0, 1000, 250)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
        snap = server.metrics.snapshot()
    for i, reply in enumerate(results):
        np.testing.assert_array_equal(want_i[i][None], reply.ids)
        np.testing.assert_array_equal(want_v[i][None], reply.values)
    assert snap["completed"] == 1000
    assert snap["qps"] > 0.0
    assert np.isfinite(snap["latency_ms_p99"])
    assert snap["latency_ms_p50"] <= snap["latency_ms_p99"]
    assert 0.0 < snap["batch_occupancy"] <= 1.0
    assert snap["batches"] >= 4  # 1000 rows can't fit one 256 bucket
    assert snap["expired"] == 0 and snap["rejected"] == 0


def test_metrics_snapshot_and_render_empty():
    m = serve.ServerMetrics(latency_window=16)
    snap = m.snapshot()
    assert snap["completed"] == 0 and np.isnan(snap["qps"])
    text = m.render_text()
    assert "raft_tpu_serve_qps" in text and text.endswith("\n")


def test_render_text_is_prometheus_exposition():
    """`render_text` must stay scrape-able: every line `name value` with
    a legal metric name and a float-parseable value (nan included) —
    the shared `obs.export` formatter's contract."""
    import re

    m = serve.ServerMetrics(latency_window=16)
    m.observe_submit()
    m.observe_batch(n_requests=1, valid_rows=2, bucket_rows=8,
                    latencies_s=[0.01], coverage=0.75)
    for line in m.render_text().strip().split("\n"):
        match = re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]* (\S+)", line)
        assert match, f"not exposition format: {line!r}"
        float(match.group(1))  # accepts nan/inf spellings too
    assert "raft_tpu_serve_coverage_min 0.75" in m.render_text().split("\n")


def test_warmup_compiles_every_bucket(blobs):
    counting = CountingSearcher(serve.BruteForceSearcher(blobs))
    server = serve.SearchServer(
        counting, serve.ServerConfig(buckets=(8, 32, 128)))
    assert server.warmup(k=5) == 3
    assert counting.calls == 3


# -- chaos --------------------------------------------------------------

def test_slow_rank_fault_degrades_coverage_within_deadline(blobs):
    """The acceptance drill: a slow rank past the health deadline gets
    masked, and the server answers with coverage < 1.0 WITHIN the
    request deadline instead of hanging on the straggler."""
    from raft_tpu.comms import Comms, mnmg, resilience

    comms = Comms(n_devices=4)
    idx = mnmg.ivf_flat_build(
        comms, ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=3), blobs)
    plan = faults.FaultPlan(
        [faults.Fault(kind="slow_rank", site="resilience.barrier", rank=2,
                      latency_s=60.0)],
        seed=SEED,
    )
    with plan.install():
        health = resilience.probe_health(comms, timeout_s=0.5)
    assert health.degraded and health.coverage() == 0.75
    server = serve.SearchServer(
        idx, serve.ServerConfig(buckets=(8,)), health=health, n_probes=4,
        engine="list")
    fut = server.submit(np.zeros((4, 16), np.float32), k=5, deadline_s=120.0)
    server.step()
    reply = fut.result(timeout=60.0)
    assert reply.coverage == 0.75
    assert reply.ids.shape == (4, 5)
    assert server.metrics.snapshot()["coverage_min"] == 0.75
    # recovery: swapping a healthy mask restores full coverage
    server.set_health(resilience.RankHealth.all_healthy(4))
    fut = server.submit(np.zeros((4, 16), np.float32), k=5)
    server.step()
    assert fut.result(timeout=60.0).coverage == 1.0


def test_slow_batch_dispatch_expires_queued_requests(blobs):
    """An injected slow device dispatch ("serve.batch") burns the queued
    requests' budgets; they must expire at dispatch time, before the
    searcher runs."""
    counting = CountingSearcher(serve.BruteForceSearcher(blobs))
    server = serve.SearchServer(counting, serve.ServerConfig(buckets=(8,)))
    plan = faults.FaultPlan(
        [faults.Fault(kind="slow_rank", site="serve.batch", latency_s=0.05)],
        seed=SEED,
    )
    futs = [server.submit(np.zeros((2, 16), np.float32), k=3,
                          deadline_s=0.02) for _ in range(2)]
    with plan.install():
        # dispatch-time expiries still count as answered requests
        assert server.step() == 2
    for fut in futs:
        with pytest.raises(serve.DeadlineExceeded):
            fut.result(timeout=0.1)
    assert counting.calls == 0
    assert server.metrics.snapshot()["expired"] == 2


def test_flaky_submit_site(blobs):
    server = serve.SearchServer(blobs, serve.ServerConfig(buckets=(8,)))
    plan = faults.FaultPlan(
        [faults.Fault(kind="flaky_bootstrap", site="serve.submit", count=1)],
        seed=SEED,
    )
    with plan.install():
        with pytest.raises(faults.FaultInjected):
            server.submit(np.zeros((1, 16), np.float32), k=3)
        fut = server.submit(np.zeros((1, 16), np.float32), k=3)  # retries fine
    server.step()
    assert fut.result(timeout=1.0).ids.shape == (1, 3)


def test_searcher_failure_delivered_not_raised(blobs):
    class Exploding(serve.Searcher):
        dim = 16

        def search(self, queries, k, probe_scale=1.0, recall_target=None):
            raise RuntimeError("boom")

    server = serve.SearchServer(Exploding(), serve.ServerConfig(buckets=(8,)))
    fut = server.submit(np.zeros((1, 16), np.float32), k=3)
    server.step()  # must not raise
    with pytest.raises(RuntimeError, match="boom"):
        fut.result(timeout=0.1)
    assert server.metrics.snapshot()["failed"] == 1
