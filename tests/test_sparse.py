"""Sparse module tests vs scipy.sparse oracles (mirrors cpp/test/sparse/*)."""

import numpy as np
import pytest
import scipy.sparse as sp
from scipy.sparse.csgraph import minimum_spanning_tree

from raft_tpu import sparse as rsp


@pytest.fixture
def rand_csr(rng):
    def make(m=30, n=20, density=0.2, seed=None):
        d = (rng.random((m, n)) < density) * rng.random((m, n))
        return d.astype(np.float32)

    return make


def test_conversions(rand_csr):
    d = rand_csr()
    csr = rsp.dense_to_csr(d)
    back = np.asarray(rsp.csr_to_dense(csr))
    np.testing.assert_allclose(back, d, rtol=1e-6)
    coo = rsp.csr_to_coo(csr)
    np.testing.assert_allclose(np.asarray(rsp.coo_to_dense(coo)), d, rtol=1e-6)
    csr2 = rsp.coo_to_csr(coo)
    np.testing.assert_allclose(np.asarray(rsp.csr_to_dense(csr2)), d, rtol=1e-6)


def test_spmv_spmm(rand_csr, rng):
    d = rand_csr()
    csr = rsp.dense_to_csr(d)
    x = rng.random(d.shape[1], dtype=np.float32)
    np.testing.assert_allclose(np.asarray(rsp.linalg.spmv(csr, x)), d @ x, rtol=1e-4)
    B = rng.random((d.shape[1], 7), dtype=np.float32)
    np.testing.assert_allclose(np.asarray(rsp.linalg.spmm(csr, B)), d @ B, rtol=1e-4)


def test_transpose_add(rand_csr):
    d = rand_csr()
    csr = rsp.dense_to_csr(d)
    t = rsp.linalg.transpose(csr)
    np.testing.assert_allclose(np.asarray(rsp.csr_to_dense(t)), d.T, rtol=1e-6)
    d2 = rand_csr()
    s = rsp.linalg.add(rsp.dense_to_csr(d), rsp.dense_to_csr(d2))
    np.testing.assert_allclose(np.asarray(rsp.csr_to_dense(s)), d + d2, rtol=1e-5)


def test_symmetrize(rand_csr):
    d = rand_csr(15, 15)
    coo = rsp.dense_to_coo(d)
    s = rsp.linalg.symmetrize(coo, op="max")
    ds = np.asarray(rsp.coo_to_dense(s))
    np.testing.assert_allclose(ds, np.maximum(d, d.T), rtol=1e-6)


def test_degree_and_norms(rand_csr):
    d = rand_csr()
    csr = rsp.dense_to_csr(d)
    coo = rsp.csr_to_coo(csr)
    np.testing.assert_array_equal(np.asarray(rsp.degree(coo)), (d != 0).sum(1))
    np.testing.assert_allclose(
        np.asarray(rsp.linalg.row_norm_csr(csr, "l2")), (d**2).sum(1), rtol=1e-5
    )


def test_dedup_and_filter(rng):
    import jax.numpy as jnp

    rows = jnp.asarray([0, 0, 1, 1, 0])
    cols = jnp.asarray([1, 1, 2, 2, 3])
    vals = jnp.asarray([1.0, 2.0, 3.0, 4.0, 0.0])
    coo = rsp.CooMatrix(rows, cols, vals, (3, 4))
    dd = rsp.max_duplicates(coo)
    dense = np.asarray(rsp.coo_to_dense(dd))
    assert dense[0, 1] == 3.0 and dense[1, 2] == 7.0
    filtered = rsp.coo_remove_zeros(coo)
    assert filtered.nnz == 4


def test_sparse_pairwise_distance(rand_csr, rng):
    from scipy.spatial.distance import cdist

    xa = rand_csr(12, 16)
    yb = rand_csr(9, 16)
    got = np.asarray(
        rsp.distance.pairwise_distance(rsp.dense_to_csr(xa), rsp.dense_to_csr(yb), "euclidean")
    )
    np.testing.assert_allclose(got, cdist(xa, yb), rtol=1e-3, atol=1e-3)
    with pytest.raises(ValueError):
        rsp.distance.pairwise_distance(
            rsp.dense_to_csr(xa), rsp.dense_to_csr(yb), "haversine"
        )


def test_sparse_knn(rand_csr):
    xa = rand_csr(50, 10, density=0.5)
    d, i = rsp.distance.knn(rsp.dense_to_csr(xa), rsp.dense_to_csr(xa), 3)
    np.testing.assert_array_equal(np.asarray(i)[:, 0], np.arange(50))


def test_knn_graph():
    from raft_tpu.random import make_blobs

    x, _ = make_blobs(100, 5, n_clusters=3, seed=2)
    g = rsp.neighbors.knn_graph(np.asarray(x), 4)
    dense = np.asarray(rsp.coo_to_dense(g))
    np.testing.assert_allclose(dense, dense.T, rtol=1e-5)  # symmetric
    assert (dense > 0).sum(1).min() >= 4


def test_connect_components():
    # two separated blobs labeled as two components
    a = np.random.default_rng(0).random((20, 3)).astype(np.float32)
    b = a + 100.0
    X = np.concatenate([a, b])
    labels = np.array([0] * 20 + [1] * 20)
    edges = rsp.neighbors.connect_components(X, labels)
    r, c = np.asarray(edges.rows), np.asarray(edges.cols)
    assert len(r) > 0
    assert all(labels[ri] != labels[ci] for ri, ci in zip(r, c))


def test_mst_matches_scipy(rng):
    n = 40
    pts = rng.random((n, 2), dtype=np.float32)
    from scipy.spatial.distance import cdist

    full = cdist(pts, pts).astype(np.float32)
    # complete graph COO (off-diagonal)
    rows, cols = np.nonzero(~np.eye(n, dtype=bool))
    import jax.numpy as jnp

    coo = rsp.CooMatrix(
        jnp.asarray(rows.astype(np.int32)),
        jnp.asarray(cols.astype(np.int32)),
        jnp.asarray(full[rows, cols]),
        (n, n),
    )
    tree = rsp.solver.mst(coo)
    got_w = float(np.asarray(tree.vals).sum())
    want = minimum_spanning_tree(sp.csr_matrix(full)).sum()
    np.testing.assert_allclose(got_w, want, rtol=1e-4)
    assert tree.nnz == n - 1


def test_lanczos_smallest():
    rng = np.random.default_rng(3)
    # symmetric PSD matrix with known spectrum
    q, _ = np.linalg.qr(rng.random((30, 30)))
    w = np.linspace(0.1, 5.0, 30).astype(np.float32)
    A = (q * w) @ q.T
    csr = rsp.dense_to_csr(A.astype(np.float32), tol=-1.0)
    vals, vecs = rsp.solver.compute_smallest_eigenvectors(csr, 3)
    np.testing.assert_allclose(np.asarray(vals), w[:3], atol=1e-2)
    # residual check
    for j in range(3):
        v = np.asarray(vecs)[:, j]
        r = A @ v - float(np.asarray(vals)[j]) * v
        assert np.linalg.norm(r) < 1e-2


def test_blocked_sparse_distance_and_knn(monkeypatch):
    """The >_ROW_BLOCK streaming paths (block densify + top-k merge) must
    match the one-shot dense results exactly."""
    import jax.numpy as jnp
    import raft_tpu.sparse.distance as sd
    from raft_tpu.sparse import dense_to_csr
    from raft_tpu.distance.pairwise import _pairwise_impl
    from raft_tpu.distance.distance_types import resolve_metric, DistanceType
    from raft_tpu.neighbors.brute_force import _bf_knn_impl

    monkeypatch.setattr(sd, "_ROW_BLOCK", 300)  # force several blocks
    rng = np.random.default_rng(7)
    d1 = rng.random((1000, 24)).astype(np.float32)
    d1[d1 < 0.6] = 0
    d2 = rng.random((100, 24)).astype(np.float32)
    d2[d2 < 0.6] = 0
    x, y = dense_to_csr(d1), dense_to_csr(d2)
    for metric in ("sqeuclidean", "l1"):
        got = np.asarray(sd.pairwise_distance(x, y, metric=metric))
        want = np.asarray(
            _pairwise_impl(jnp.asarray(d1), jnp.asarray(d2), resolve_metric(metric), metric_arg=2.0)
        )
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
    # kNN merge across blocks, both metric orientations
    for metric, ref_metric in (("sqeuclidean", DistanceType.L2Expanded),
                               ("inner_product", DistanceType.InnerProduct)):
        dv, di = sd.knn(x, y, 5, metric=metric)
        _, wi = _bf_knn_impl(jnp.asarray(d1), jnp.asarray(d2), 5, ref_metric)
        np.testing.assert_array_equal(np.asarray(di), np.asarray(wi))


@pytest.mark.slow
def test_densify_budget_chunks_y_and_guards(monkeypatch):
    """Over-budget dense y falls back to y-row-block streaming (exact for
    row-wise metrics); an impossible budget raises instead of OOMing."""
    import jax.numpy as jnp
    import raft_tpu.sparse.distance as sd
    from raft_tpu.sparse import dense_to_csr
    from raft_tpu.distance.pairwise import _pairwise_impl
    from raft_tpu.distance.distance_types import resolve_metric

    monkeypatch.setattr(sd, "_ROW_BLOCK", 128)
    rng = np.random.default_rng(11)
    d1 = rng.random((300, 16)).astype(np.float32)
    d1[d1 < 0.5] = 0
    d2 = rng.random((400, 16)).astype(np.float32)
    d2[d2 < 0.5] = 0
    x, y = dense_to_csr(d1), dense_to_csr(d2)
    # budget admits one 128-row block pair but not dense y (400*16*4B)
    budget = 4 * 16 * (128 + 128)
    for metric in ("sqeuclidean", "cosine"):
        got = np.asarray(
            sd.pairwise_distance(x, y, metric=metric, densify_budget_bytes=budget)
        )
        want = np.asarray(
            _pairwise_impl(jnp.asarray(d1), jnp.asarray(d2),
                           resolve_metric(metric), metric_arg=2.0)
        )
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
    with pytest.raises(ValueError, match="densify_budget_bytes"):
        sd.pairwise_distance(x, y, densify_budget_bytes=64)


def test_deprecated_alias_shims():
    """sparse.selection / sparse.hierarchy forward to their new homes
    (reference sparse/selection/knn.cuh:17-27, sparse/hierarchy/)."""
    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        import importlib

        sel = importlib.import_module("raft_tpu.sparse.selection")
        hier = importlib.import_module("raft_tpu.sparse.hierarchy")
    from raft_tpu.sparse import neighbors as sn
    from raft_tpu.cluster.single_linkage import single_linkage

    assert sel.knn_graph is sn.knn_graph
    assert sel.connect_components is sn.connect_components
    assert hier.single_linkage is single_linkage


def test_compact_column_space_over_budget(rand_csr, monkeypatch):
    """VERDICT r4 #7: the truly-sparse regime (huge column count, one
    densified block pair over any budget) computes via the compacted
    active-column space instead of raising — exact vs scipy for every
    supported metric, including the three that reference the full column
    count (hamming / russelrao / correlation)."""
    from scipy.spatial.distance import cdist

    import raft_tpu.sparse.distance as sd
    from raft_tpu.sparse import dense_to_csr

    rng = np.random.default_rng(5)
    n_cols = 5000
    # sparse rows over a wide column space: ~8 nnz/row
    def make(nr):
        dense = np.zeros((nr, n_cols), np.float32)
        for r in range(nr):
            cols = rng.choice(n_cols, 8, replace=False)
            dense[r, cols] = rng.random(8).astype(np.float32) + 0.1
        return dense

    d1, d2 = make(40), make(30)
    x, y = dense_to_csr(d1), dense_to_csr(d2)
    # full-space block pair needs 4*5000*(40+30) = 1.4 MB; compact fits
    budget = 600_000
    cases = [
        ("euclidean", cdist(d1, d2)),
        ("cityblock", cdist(d1, d2, "cityblock")),
        ("cosine", cdist(d1, d2, "cosine")),
        ("hamming", cdist(d1 != 0, d2 != 0, "hamming")),
        ("russellrao", cdist(d1 != 0, d2 != 0, "russellrao")),
        ("correlation", cdist(d1, d2, "correlation")),
    ]
    for metric, want in cases:
        got = np.asarray(
            sd.pairwise_distance(x, y, metric=metric,
                                 densify_budget_bytes=budget)
        )
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3, err_msg=metric)
    # all-zero inputs stay finite through the compaction
    z = dense_to_csr(np.zeros((3, n_cols), np.float32))
    got = np.asarray(sd.pairwise_distance(z, z, metric="euclidean",
                                          densify_budget_bytes=budget))
    np.testing.assert_allclose(got, 0.0)


@pytest.mark.slow
def test_compact_column_space_1m_cols():
    """The VERDICT r4 #7 acceptance case: a 4096-row x 1M-column CSR the
    dense path refuses (one block pair = 32 GB) computes through the
    compact path under the DEFAULT budget; truth from scipy.sparse."""
    import raft_tpu.sparse.distance as sd
    from raft_tpu.sparse.formats import CsrMatrix

    rng = np.random.default_rng(9)
    n_rows, n_cols, nnz_row = 4096, 1_000_000, 8
    idx = rng.integers(0, n_cols, (n_rows, nnz_row), dtype=np.int64)
    idx.sort(axis=1)  # CSR wants sorted column indices per row
    data = (rng.random((n_rows, nnz_row)).astype(np.float32) + 0.1).reshape(-1)
    indptr = np.arange(0, n_rows * nnz_row + 1, nnz_row, dtype=np.int64)
    x = CsrMatrix(indptr, idx.reshape(-1), data, (n_rows, n_cols))
    yr = 256
    y = CsrMatrix(indptr[: yr + 1], idx[:yr].reshape(-1),
                  data[: yr * nnz_row], (yr, n_cols))
    got = np.asarray(sd.pairwise_distance(x, y, metric="sqeuclidean"))
    assert got.shape == (n_rows, yr)
    xs = sp.csr_matrix((data, idx.reshape(-1), indptr), shape=(n_rows, n_cols))
    ys = xs[:yr]
    dots = (xs @ ys.T).toarray()
    nx = np.asarray(xs.multiply(xs).sum(axis=1)).ravel()
    want = nx[:, None] + nx[None, :yr] - 2.0 * dots
    np.testing.assert_allclose(got, np.maximum(want, 0.0), rtol=3e-3, atol=3e-3)
    # self-distances are zero on the diagonal of the shared prefix
    assert np.abs(np.diag(got[:yr])).max() < 1e-2


def test_compact_column_space_shrinks_row_blocks(rng):
    """When the active-column union itself is wide, the compact path
    shrinks the dense row tiles instead of refusing (more, smaller
    matmuls; same results)."""
    from scipy.spatial.distance import cdist

    import raft_tpu.sparse.distance as sd
    from raft_tpu.sparse import dense_to_csr

    n_cols = 20000
    dense = np.zeros((500, n_cols), np.float32)
    for r in range(500):
        c = rng.choice(n_cols, 40, replace=False)
        dense[r, c] = rng.random(40).astype(np.float32) + 0.1
    x = dense_to_csr(dense)
    # E[u] = 20000*(1-(1-40/20000)^500) ~ 12.65k active columns; this
    # budget admits ~256-row tiles (4*12650*2*256 bytes) but not the
    # 4096 default, so the shrink loop must fire
    budget = 4 * 12650 * 2 * 256 + 1000
    got = np.asarray(
        sd.pairwise_distance(x, x, "euclidean", densify_budget_bytes=budget)
    )
    # atol covers expanded-L2 f32 cancellation on near-zero distances
    # (measured 2.8e-3 on the self-distance diagonal at this geometry)
    np.testing.assert_allclose(got, cdist(dense, dense), rtol=2e-3, atol=5e-3)
