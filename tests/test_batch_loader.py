"""`neighbors/batch_loader.py` double-buffering coverage: prefetch
ordering (batch b+1's host read is issued before batch b is consumed),
the uniform padded batch shape with a correct final `valid` count, and
block-content equality against plain numpy slicing — the padding
discipline the serve batcher reuses."""

import numpy as np
import pytest

from raft_tpu.neighbors.batch_loader import BatchLoadIterator


class RecordingHost:
    """Host-array stand-in that logs every slice read into a shared
    event list, so tests can interleave load events with consume
    events and assert the prefetch schedule."""

    def __init__(self, arr, events):
        self.arr = arr
        self.events = events

    @property
    def shape(self):
        return self.arr.shape

    def __getitem__(self, key):
        self.events.append(("load", key.start // 16))
        return self.arr[key]


def test_prefetch_loads_one_batch_ahead():
    events = []
    arr = np.arange(16 * 5, dtype=np.float32).reshape(80, 1)
    host = RecordingHost(arr, events)
    for b, (block, valid) in enumerate(BatchLoadIterator(host, 16, prefetch=True)):
        events.append(("consume", b))
    # double buffering: batch b+1's host read happens BEFORE batch b is
    # handed to the consumer (so the device transfer overlaps compute)
    for b in range(4):
        assert events.index(("load", b + 1)) < events.index(("consume", b)), events
    assert [e for e in events if e[0] == "load"] == [("load", b) for b in range(5)]


def test_no_prefetch_interleaves_strictly():
    events = []
    arr = np.zeros((48, 2), np.float32)
    host = RecordingHost(arr, events)
    for b, _ in enumerate(BatchLoadIterator(host, 16, prefetch=False)):
        events.append(("consume", b))
    assert events == [("load", 0), ("consume", 0), ("load", 1), ("consume", 1),
                      ("load", 2), ("consume", 2)]


@pytest.mark.parametrize("prefetch", [True, False])
def test_final_padded_batch_valid_count(prefetch):
    arr = np.arange(40 * 3, dtype=np.float32).reshape(40, 3)
    out = list(BatchLoadIterator(arr, 16, prefetch=prefetch))
    assert len(out) == 3
    valids = [v for _, v in out]
    assert valids == [16, 16, 8]
    for block, _ in out:
        # every block keeps the SAME padded shape (one XLA compilation)
        assert block.shape == (16, 3)
    # content: valid rows match numpy slicing, pad rows are zero
    blocks = np.concatenate([np.asarray(b) for b, _ in out])
    np.testing.assert_array_equal(blocks[:40], arr)
    np.testing.assert_array_equal(blocks[40:], 0.0)


@pytest.mark.parametrize("prefetch", [True, False])
def test_exact_multiple_has_full_final_batch(prefetch):
    arr = np.ones((32, 2), np.float32)
    out = list(BatchLoadIterator(arr, 16, prefetch=prefetch))
    assert [v for _, v in out] == [16, 16]
    assert all(b.shape == (16, 2) for b, _ in out)


def test_single_partial_batch_and_dtype():
    out = list(BatchLoadIterator(np.ones((5, 2), np.float64), 16,
                                 dtype=np.float32))
    assert len(out) == 1
    block, valid = out[0]
    assert valid == 5 and block.shape == (16, 2)
    assert np.asarray(block).dtype == np.float32
