"""Pod-width rehearsal tier: the distributed surface on 16/32-device
virtual meshes (VERDICT r3 #4).

Everything else in the suite runs at world=8; these subprocesses re-run
the scale-sensitive paths — many-group collectives, merge topologies,
uneven extend_local, spanning checkpoint loads — at the widths where
their costs change shape. Reference parity: raft-dask test_comms.py
breadth on a grown LocalCUDACluster (survey §2.15)."""

import os
import subprocess
import sys

import pytest


def _run_worker(world: int, timeout: float = 540.0) -> str:
    worker = os.path.join(os.path.dirname(__file__), "_bigmesh_worker.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    try:
        proc = subprocess.run(
            [sys.executable, worker, str(world)],
            capture_output=True, text=True, timeout=timeout, env=env,
        )
    except subprocess.TimeoutExpired as e:
        raise AssertionError(
            f"bigmesh worker (world={world}) timed out\n"
            f"stdout:\n{e.stdout}\nstderr:\n{str(e.stderr)[-3000:]}"
        ) from None
    assert proc.returncode == 0, (
        f"worker rc={proc.returncode}\nstdout:\n{proc.stdout}\n"
        f"stderr:\n{proc.stderr[-3000:]}"
    )
    return proc.stdout


@pytest.mark.slow
@pytest.mark.parametrize("world", [16, 32])
def test_bigmesh_surface(world):
    out = _run_worker(world)
    assert "BIGMESH_OK" in out, out
    assert "FAIL" not in out, out
