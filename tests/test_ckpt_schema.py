"""The CKPT_SCHEMA runtime contract (core/serialize.py): legacy golden
checkpoints load with exactly the registered absent-on-load behavior,
newer-than-library checkpoints refuse typed, required-field absence
refuses typed, and corrupt registered-optional fields degrade (drop)
instead of crashing. The lint half of the contract — save coverage,
guarded load fallbacks, symmetry — lives in
tests/test_raftlint_statecheck.py; the seeded chaos flavor of the
degrade drill lives with the other ckpt drills in
tests/test_replication.py.

Goldens (tests/goldens/legacy_*.ckpt, regenerate with
tests/goldens/make_legacy_ckpts.py) are byte-for-byte what the
pre-`list_radii` / pre-`fused_kb` era writers emitted — real old bytes,
not a mock of them.
"""

import os

import numpy as np
import pytest

from raft_tpu.core.serialize import (
    CKPT_SCHEMA,
    ChecksumError,
    SerializationError,
    check_ckpt_version,
    field_byte_range,
    serialize_arrays,
)
from raft_tpu.neighbors import ivf_flat, ivf_pq, ivf_rabitq

GOLDENS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "goldens")


def _golden(name):
    return os.path.join(GOLDENS, name)


def test_legacy_flat_golden_loads_budgets_only():
    # the schema DECLARES radii-less -> default(None); the golden proves
    # the load honors it on real pre-radii bytes
    assert CKPT_SCHEMA["ivf_flat"]["fields"]["list_radii"][3] == "default"
    index = ivf_flat.load(_golden("legacy_ivf_flat_v2_noradii.ckpt"))
    assert index.list_radii is None
    assert index.fused_kb is None  # runtime field re-defaults
    q = np.asarray(index.centers)[:3] + 0.01
    # budgets-only adaptive probing: without radii the early-term bounds
    # stay off but the per-query budget path must still serve
    p = ivf_flat.SearchParams(n_probes=4, recall_target=0.9)
    vals, ids = ivf_flat.search(p, index, q.astype(np.float32), 3)
    assert np.asarray(ids).shape == (3, 3)
    assert (np.asarray(ids) >= 0).all()


def test_legacy_pq_golden_loads_budgets_only():
    assert CKPT_SCHEMA["ivf_pq"]["fields"]["list_radii"][3] == "default"
    index = ivf_pq.load(_golden("legacy_ivf_pq_v1_noradii.ckpt"))
    assert index.list_radii is None
    assert index.fused_kb is None
    q = np.asarray(index.centers)[:3] + 0.01
    p = ivf_pq.SearchParams(n_probes=4, recall_target=0.9)
    vals, ids = ivf_pq.search(p, index, q.astype(np.float32), 3)
    assert np.asarray(ids).shape == (3, 3)
    assert (np.asarray(ids) >= 0).all()


def test_legacy_rabitq_golden_loads_runtime_defaults():
    for f in ("fused_kb", "codes_t", "bp_meta"):
        assert CKPT_SCHEMA["ivf_rabitq"]["fields"][f][0] == "runtime"
    index = ivf_rabitq.load(_golden("legacy_ivf_rabitq_v1.ckpt"))
    assert index.fused_kb is None
    assert index.codes_t is None and index.bp_meta is None
    # rabitq centers live in the rotated space — query in data space
    q = np.random.default_rng(3).random((3, index.dim), dtype=np.float32)
    vals, ids = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=4), index, q.astype(np.float32), 3)
    assert np.asarray(ids).shape == (3, 3)


@pytest.mark.parametrize("kind,mod,golden", [
    ("ivf_flat", ivf_flat, "legacy_ivf_flat_v2_radii.ckpt"),
    ("ivf_pq", ivf_pq, "legacy_ivf_pq_v1_radii.ckpt"),
    ("ivf_rabitq", ivf_rabitq, "legacy_ivf_rabitq_v1.ckpt"),
])
def test_premutation_goldens_load_all_live(kind, mod, golden):
    """The mutation-era fields (tombstones / mut_cursor / append_slack)
    are declared absent-on-load defaults, and real pre-mutation bytes
    load with exactly the pre-mutation semantics: every row live,
    cursor 0, no reserved slack — and the index both serves and accepts
    a first mutation."""
    from raft_tpu.neighbors import mutation

    spec = CKPT_SCHEMA[kind]
    assert spec["fields"]["tombstones"][3] == "default"
    # mutation-era fields arrived together, strictly after v1 and no
    # later than the current version (the integrity era bumped past it)
    assert 1 < spec["fields"]["tombstones"][2] <= spec["version"]
    assert spec["fields"]["tombstones"][2] == spec["fields"]["mut_cursor"][2]
    assert spec["fields"]["mut_cursor"][3] == "default"
    assert spec["fields"]["append_slack"][3] == "default"
    index = mod.load(_golden(golden))
    assert index.tombstones is None
    assert int(index.mut_cursor) == 0 and int(index.append_slack) == 0
    assert mutation.live_rows(index) == int(index.size)  # all live
    sid = np.asarray(index.source_ids)
    out = mutation.delete(index, sid[:2])  # a legacy index is mutable
    assert int(out.n_tombstones) == 2


def test_newer_version_refuses_typed(tmp_path):
    """The since-version refusal: a checkpoint declaring a version newer
    than the library refuses with a TYPED SerializationError instead of
    loading fields whose semantics this build cannot know."""
    path = str(tmp_path / "future.ckpt")
    serialize_arrays(
        path, {"centers": np.zeros((2, 2), np.float32)},
        {"kind": "ivf_flat", "version": 99, "metric": 0, "n_lists": 2},
    )
    with pytest.raises(SerializationError, match="newer than the library"):
        ivf_flat.load(path)
    # the mnmg loads route the same gate through _load_verified
    with pytest.raises(SerializationError, match="newer than the library"):
        check_ckpt_version({"kind": "mnmg_ivf_pq", "version": 12}, path)
    # unregistered kinds pass (generic containers gate elsewhere)
    check_ckpt_version({"kind": "not_an_index", "version": 7}, path)


def test_missing_required_field_refuses_typed(tmp_path):
    path = str(tmp_path / "torn.ckpt")
    serialize_arrays(
        path, {"centers": np.zeros((2, 2), np.float32)},
        {"kind": "ivf_flat", "version": 2, "metric": 0, "n_lists": 2},
    )
    with pytest.raises(SerializationError, match="missing required"):
        ivf_flat.load(path)


def test_missing_required_meta_refuses_typed(tmp_path):
    """Meta-category refuse fields gate too: a foreign writer dropping
    'pq_bits' surfaces as the typed refusal, not a KeyError three
    layers into IndexParams construction."""
    path = str(tmp_path / "nometa.ckpt")
    arrays = {
        name: np.zeros((2, 2), np.float32)
        for name in ("rotation", "centers", "pq_centers", "codes",
                     "slot_rows", "list_sizes", "source_ids")
    }
    serialize_arrays(path, arrays,
                     {"kind": "ivf_pq", "version": 1, "metric": 0,
                      "n_lists": 2, "codebook_kind": "per_subspace"})
    with pytest.raises(SerializationError,
                       match=r"missing required field\(s\) \['pq_bits'\]"):
        ivf_pq.load(path)


def _flip(path, start, end):
    with open(path, "r+b") as fh:
        fh.seek(start)
        blk = fh.read(end - start)
        fh.seek(start)
        fh.write(bytes(b ^ 0xFF for b in blk))


def test_corrupt_optional_field_degrades_not_crashes(tmp_path, rng):
    """Rot exactly the registered-optional list_radii bytes: the load
    drops the field (absent='default' declared behavior) and serves
    budgets-only — the same container with a rotted REQUIRED field
    still raises ChecksumError naming it."""
    data = rng.random((96, 16), dtype=np.float32)
    index = ivf_flat.build(ivf_flat.IndexParams(n_lists=4), data)
    assert index.list_radii is not None
    path = str(tmp_path / "radii.ckpt")
    ivf_flat.save(path, index)
    _flip(path, *field_byte_range(path, "list_radii"))
    loaded = ivf_flat.load(path)
    assert loaded.list_radii is None  # dropped, not garbage, not a crash
    p = ivf_flat.SearchParams(n_probes=4, recall_target=0.9)
    _, ids = ivf_flat.search(p, loaded, data[:5], 3)
    assert (np.asarray(ids) >= 0).all()

    path2 = str(tmp_path / "centers.ckpt")
    ivf_flat.save(path2, index)
    _flip(path2, *field_byte_range(path2, "centers"))
    with pytest.raises(ChecksumError, match="centers"):
        ivf_flat.load(path2)
