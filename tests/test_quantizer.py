"""Quantizer-abstraction suite (neighbors/quantizer.py): the RaBitQ
estimator's unbiasedness property, packed-code round-trips, the query
bit-plane scan's agreement with its exact form, and the PqQuantizer's
equivalence with the functions it absorbed from ivf_pq.py. (The PQ
index-level bit-identity goldens live in tests/test_ivf_pq.py.)"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.neighbors import quantizer
from raft_tpu.neighbors.quantizer import (
    PqQuantizer,
    RabitqQuantizer,
    binary_dot,
    pack_bits,
    packed_words,
    quantize_queries,
    unpack_bits,
)


# -- bit packing --------------------------------------------------------

def test_pack_unpack_roundtrip(rng):
    for rot_dim in (32, 64, 128, 256):
        bits = (rng.random((13, rot_dim)) < 0.5).astype(np.int32)
        packed = np.asarray(pack_bits(bits))
        assert packed.shape == (13, packed_words(rot_dim))
        assert packed.dtype == np.uint32
        back = np.asarray(unpack_bits(jnp.asarray(packed), rot_dim))
        np.testing.assert_array_equal(back, bits)


def test_pack_rejects_unaligned_dim():
    with pytest.raises(ValueError, match="multiple of 32"):
        packed_words(48)


def test_encode_decode_roundtrip_signs():
    """encode -> decode preserves the sign pattern exactly, and
    re-encoding the decoded reconstruction reproduces the codes bit for
    bit (the packed-code round-trip the satellite pins)."""
    rng = np.random.default_rng(1)
    r = rng.normal(size=(64, 64)).astype(np.float32)
    quant = RabitqQuantizer(64)
    payload = quant.encode(r)
    dec = np.asarray(quant.decode(payload))
    # decoded rows point along sign(r): sign agreement everywhere the
    # residual is nonzero
    np.testing.assert_array_equal(np.sign(dec), np.sign(r))
    payload2 = quant.encode(dec)
    np.testing.assert_array_equal(np.asarray(payload2["codes"]),
                                  np.asarray(payload["codes"]))


def test_encode_correction_factors():
    rng = np.random.default_rng(2)
    r = rng.normal(size=(32, 96)).astype(np.float32)
    quant = RabitqQuantizer(96)
    aux = np.asarray(quant.encode(r)["aux"])
    np.testing.assert_allclose(aux[:, 0], np.linalg.norm(r, axis=1),
                               rtol=1e-5)
    # <o, x_bar> = sum|r_i| / (|r| sqrt(D)) in (0, 1]
    expect = np.abs(r).sum(1) / (np.linalg.norm(r, axis=1) * np.sqrt(96))
    np.testing.assert_allclose(aux[:, 1], expect, rtol=1e-5)
    assert (aux[:, 1] > 0).all() and (aux[:, 1] <= 1 + 1e-6).all()
    # zero residual: finite corrections, zero norm
    z = np.asarray(quant.encode(np.zeros((1, 96), np.float32))["aux"])
    assert z[0, 0] == 0.0 and np.isfinite(z[0, 1])


# -- the unbiasedness property -----------------------------------------

def test_estimator_unbiased_over_rotations():
    """The RaBitQ estimator <q, x_bar>/<o, x_bar> is unbiased for
    <q, o> in expectation over the random rotation: the MEAN signed
    distance error over seeds shrinks toward zero while the per-seed
    error magnitude stays an order of magnitude larger (satellite:
    'mean error -> 0 over seeds')."""
    from raft_tpu.neighbors.ivf_pq import _make_rotation

    rng = np.random.default_rng(7)
    D = 64
    r = rng.normal(size=(256, D)).astype(np.float32)
    q = rng.normal(size=(4, D)).astype(np.float32)
    true = ((q[:, None, :] - r[None, :, :]) ** 2).sum(-1)

    biases, mags = [], []
    for seed in range(24):
        rot = np.asarray(_make_rotation(jax.random.PRNGKey(seed), D, D, True))
        quant = RabitqQuantizer(D)
        payload = quant.encode(r @ rot.T)
        table = quant.score_table(q @ rot.T)
        # exact_queries isolates the estimator (no scalar-quantization
        # noise on the query side)
        est = np.asarray(quant.estimate_distances(
            table, payload, exact_queries=q @ rot.T))
        err = est - true
        biases.append(err.mean())
        mags.append(np.abs(err).mean())
    mean_bias = float(np.mean(biases))
    mean_mag = float(np.mean(mags))
    assert mean_mag > 0  # the estimator is lossy per pair...
    # ...but unbiased in the mean: the seed-averaged signed error is a
    # small fraction of the per-pair error magnitude
    assert abs(mean_bias) < 0.1 * mean_mag, (mean_bias, mean_mag)
    # and a small fraction of the distance scale itself
    assert abs(mean_bias) < 0.02 * true.mean(), (mean_bias, true.mean())


def test_estimator_exact_on_code_directions():
    """A residual that IS its own quantization direction (r parallel to
    sign(r)/sqrt(D), i.e. all-equal magnitudes) estimates its distance
    EXACTLY: <o, x_bar> = 1 and the estimator collapses to the true
    inner product."""
    D = 32
    signs = np.where(np.random.default_rng(3).random((8, D)) < 0.5, -1.0, 1.0)
    r = (signs / np.sqrt(D) * 2.5).astype(np.float32)  # |r| = 2.5
    q = np.random.default_rng(4).normal(size=(4, D)).astype(np.float32)
    quant = RabitqQuantizer(D)
    payload = quant.encode(r)
    aux = np.asarray(payload["aux"])
    np.testing.assert_allclose(aux[:, 1], 1.0, rtol=1e-5)
    est = np.asarray(quant.estimate_distances(
        quant.score_table(q), payload, exact_queries=q))
    true = ((q[:, None, :] - r[None, :, :]) ** 2).sum(-1)
    np.testing.assert_allclose(est, true, rtol=1e-4, atol=1e-3)


def test_query_bitplane_scan_matches_exact_sum():
    """binary_dot over the quantized bit planes reproduces the exact
    sum-over-set-bits within scalar-quantization error, and converges to
    it as query_bits grows."""
    rng = np.random.default_rng(9)
    D = 64
    q = rng.normal(size=(6, D)).astype(np.float32)
    codes = pack_bits((rng.random((50, D)) < 0.5).astype(np.int32))
    bits01 = np.asarray(unpack_bits(codes, D)).astype(np.float32)
    exact = q @ bits01.T  # (6, 50)
    pop = bits01.sum(1)
    prev = np.inf
    for bq in (2, 4, 8):
        planes, lo, delta = quantize_queries(jnp.asarray(q), bq)
        s_u = np.asarray(binary_dot(jnp.asarray(codes)[None, :, :],
                                    planes[:, None]))
        s = np.asarray(lo) * pop[None, :] + np.asarray(delta) * s_u
        err = np.abs(s - exact).max()
        # quantization step bounds the error: delta/2 per set bit
        bound = (np.asarray(delta).max() / 2) * pop.max() + 1e-4
        assert err <= bound, (bq, err, bound)
        assert err <= prev + 1e-5
        prev = err


# -- PqQuantizer equivalence -------------------------------------------

def test_pq_quantizer_train_encode_are_the_moved_functions():
    """The refactor moved, not rewrote: ivf_pq's underscore entry points
    ARE the quantizer module's functions (same objects, same jit
    caches), and PqQuantizer.train/encode reproduce them exactly."""
    from raft_tpu.neighbors import ivf_pq

    assert ivf_pq._encode is quantizer._encode
    assert (ivf_pq._train_codebooks_per_subspace
            is quantizer._train_codebooks_per_subspace)
    assert (ivf_pq._train_codebooks_per_cluster
            is quantizer._train_codebooks_per_cluster)

    rng = np.random.default_rng(5)
    res = rng.normal(size=(300, 32)).astype(np.float32)
    key = jax.random.PRNGKey(0)
    direct = quantizer._train_codebooks_per_subspace(key, jnp.asarray(res),
                                                     4, 16, 5)
    q = PqQuantizer(pq_bits=4, pq_dim=4, pq_len=8, n_iters=5)
    via = q.train(key, jnp.asarray(res)).pq_centers
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(via))

    labels = jnp.zeros((300,), jnp.int32)
    codes_direct = quantizer._encode(jnp.asarray(res), labels, direct, False)
    codes_via = q.encode(jnp.asarray(res), labels)["codes"]
    np.testing.assert_array_equal(np.asarray(codes_direct),
                                  np.asarray(codes_via))


def test_pq_quantizer_estimate_matches_decode_distance():
    """The PQ reference scorer (LUT gather) equals the distance to the
    decoded reconstruction — the semantics every PQ engine approximates."""
    rng = np.random.default_rng(6)
    res = rng.normal(size=(400, 16)).astype(np.float32)
    q = PqQuantizer(pq_bits=4, pq_dim=4, pq_len=4, n_iters=8)
    q.train(jax.random.PRNGKey(1), jnp.asarray(res))
    payload = q.encode(jnp.asarray(res[:50]),
                       jnp.zeros((50,), jnp.int32))
    dec = np.asarray(q.decode(payload))
    queries = rng.normal(size=(3, 16)).astype(np.float32)
    est = np.asarray(q.estimate_distances(
        q.score_table(jnp.asarray(queries)), payload))
    true = ((queries[:, None, :] - dec[None, :, :]) ** 2).sum(-1)
    np.testing.assert_allclose(est, true, rtol=1e-3, atol=1e-3)


def test_rabitq_serialize_hooks_roundtrip():
    q = RabitqQuantizer(128, query_bits=6)
    q2 = RabitqQuantizer.from_state(q.state_arrays(), q.state_meta())
    assert q2.rot_dim == 128 and q2.query_bits == 6 and q2.words == 4


def test_pq_serialize_hooks_roundtrip():
    rng = np.random.default_rng(8)
    res = rng.normal(size=(200, 16)).astype(np.float32)
    q = PqQuantizer(pq_bits=4, pq_dim=4, pq_len=4, n_iters=3)
    q.train(jax.random.PRNGKey(2), jnp.asarray(res))
    q2 = PqQuantizer.from_state(q.state_arrays(), q.state_meta())
    np.testing.assert_array_equal(np.asarray(q.pq_centers),
                                  np.asarray(q2.pq_centers))
    assert q2.codebook_kind == q.codebook_kind and q2.pq_bits == 4


def test_rerank_candidates_is_shared_refine():
    """Every quantizer reranks through the ONE refine stage — exact
    distances, -1 candidates skipped."""
    rng = np.random.default_rng(10)
    ds = rng.normal(size=(40, 8)).astype(np.float32)
    q = ds[:2]
    cand = np.array([[0, 5, 9, -1], [1, 7, 3, -1]], np.int32)
    quant = RabitqQuantizer(32)
    vals, ids = quant.rerank_candidates(ds, q, cand, 2)
    ids = np.asarray(ids)
    assert ids[0, 0] == 0 and ids[1, 0] == 1  # the query rows themselves
    np.testing.assert_allclose(np.asarray(vals)[:, 0], 0.0, atol=1e-5)
