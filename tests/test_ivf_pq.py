"""IVF-PQ tests: recall-gated against brute force (mirrors
cpp/test/neighbors/ann_ivf_pq.cuh:164-265 semantics: recall floor with
tolerance, serialization roundtrip inside fixtures)."""

import numpy as np
import pytest

from raft_tpu.neighbors import brute_force, ivf_pq
from raft_tpu.random import make_blobs


def recall(found, truth):
    found, truth = np.asarray(found), np.asarray(truth)
    hits = sum(len(set(f.tolist()) & set(t.tolist())) for f, t in zip(found, truth))
    return hits / truth.size


@pytest.fixture(scope="module")
def dataset():
    data, _ = make_blobs(20000, 64, n_clusters=40, cluster_std=1.5, seed=31)
    q, _ = make_blobs(80, 64, n_clusters=40, cluster_std=1.5, seed=32)
    return np.asarray(data), np.asarray(q)


@pytest.fixture(scope="module")
def truth10(dataset):
    data, queries = dataset
    _, t = brute_force.knn(data, queries, 10)
    return np.asarray(t)


@pytest.fixture(scope="module")
def index32(dataset):
    """Shared pq_dim=32 index (the middle-quantization config): three
    tests read it, none mutates it — one build instead of three
    (full-suite cost discipline, VERDICT r3 #8)."""
    data, _ = dataset
    return ivf_pq.build(ivf_pq.IndexParams(n_lists=50, pq_dim=32), data)


@pytest.fixture(scope="module")
def index16(dataset):
    """Shared n_lists=32/pq_dim=16 index: a dozen engine/validation tests
    search it read-only (lazy recon/lane-pad caches are idempotent) —
    one build instead of twelve (VERDICT r3 #8)."""
    data, _ = dataset
    return ivf_pq.build(ivf_pq.IndexParams(n_lists=32, pq_dim=16), data)


def test_build_search_recall(dataset, truth10):
    # Floor calibrated against an oracle: sklearn-trained codebooks on this
    # dataset reach 0.6525 recall@10 (quantization-resolution-bound, 2 bits/
    # dim); the reference pairs IVF-PQ with `refine` for high recall, tested
    # below in test_search_plus_refine.
    data, queries = dataset
    params = ivf_pq.IndexParams(n_lists=50, pq_dim=16, pq_bits=8)
    index = ivf_pq.build(params, data)
    assert index.size == len(data)
    assert index.pq_dim == 16 and index.rot_dim == 64
    d, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=20), index, queries, 10)
    r = recall(i, truth10)
    assert r >= 0.6, f"recall {r}"
    d = np.asarray(d)
    assert np.all(np.diff(d, axis=1) >= -1e-4)


def test_search_plus_refine(dataset, truth10):
    """IVF-PQ shortlist + exact refinement: the reference's high-recall
    pipeline (neighbors/refine.cuh)."""
    from raft_tpu.neighbors.refine import refine

    data, queries = dataset
    index = ivf_pq.build(ivf_pq.IndexParams(n_lists=50, pq_dim=16), data)
    _, cand = ivf_pq.search(ivf_pq.SearchParams(n_probes=20), index, queries, 40)
    d, i = refine(data, queries, cand, 10)
    r = recall(i, truth10)
    assert r >= 0.9, f"refined recall {r}"
    assert np.all(np.diff(np.asarray(d), axis=1) >= -1e-5)


def test_reference_grade_recall95(dataset, truth10, index32):
    """Pins a reference-grade >= 0.95 recall@10 configuration end-to-end
    (ann_ivf_pq.cuh:257-265 gates 0.85-0.99 per config; BASELINE.md's
    north star counts QPS only at recall@10 >= 0.95): finer quantization
    (pq_dim=32 on 64 dims), wide probing, and exact refine over a 10x
    shortlist — the same pipeline the headline bench ladder runs."""
    from raft_tpu.neighbors.refine import refine

    data, queries = dataset
    _, cand = ivf_pq.search(ivf_pq.SearchParams(n_probes=32), index32,
                            queries, 100)
    d, i = refine(data, queries, cand, 10)
    r = recall(i, truth10)
    assert r >= 0.95, f"reference-grade recall {r}"


def test_unrefined_middle_recall85(dataset, truth10, index32):
    """The MIDDLE quantization config (pq_dim = dim/2: 4 rotated bits per
    input dim) must clear a reference-grade unrefined gate
    (ann_ivf_pq.cuh:257-265 gates 0.85-0.99): measured 0.894 recall@10 at
    this geometry, gated at 0.85 — the headline ladder's second unrefined
    rung (bench.py 'mid' variant), so the bench gate does not depend
    solely on the refine pipeline or the full-fidelity index."""
    data, queries = dataset
    _, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=25), index32,
                         queries, 10)
    r = recall(i, truth10)
    assert r >= 0.85, f"unrefined middle recall {r}"


def test_unrefined_high_fidelity_recall90(dataset, truth10):
    """An UNREFINED config must clear a reference-grade gate too
    (ann_ivf_pq.cuh:257-265 gates 0.85-0.99 without refine): pq_dim ==
    dim keeps 8 rotated bits per input dim, so raw PQ scores alone reach
    high recall — measured 0.976 on this geometry; gated at 0.9. The
    bench ladder's fine-index variant (bench.py) is the 1Mx96 analogue."""
    data, queries = dataset
    index = ivf_pq.build(ivf_pq.IndexParams(n_lists=50, pq_dim=64), data)
    _, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=25), index, queries, 10)
    r = recall(i, truth10)
    assert r >= 0.9, f"unrefined high-fidelity recall {r}"


def test_probe_scaling(dataset, truth10, index32):
    data, queries = dataset
    index = index32
    r1 = recall(ivf_pq.search(ivf_pq.SearchParams(n_probes=2), index, queries, 10)[1], truth10)
    r2 = recall(ivf_pq.search(ivf_pq.SearchParams(n_probes=50), index, queries, 10)[1], truth10)
    assert r2 >= r1
    assert r2 >= 0.85, f"all-probe recall {r2}"


def test_pq_dim_quality_tradeoff(dataset, truth10, index16):
    """More subspaces -> better recall (finer quantization), asserted as
    a monotone chain over the full 8 -> 16 -> 32 span (the 16 point rides
    the shared fixture; the endpoints build here/below)."""
    data, queries = dataset
    def rec_at(index):
        return recall(
            ivf_pq.search(ivf_pq.SearchParams(n_probes=32), index,
                          queries, 10)[1], truth10)
    r8 = rec_at(ivf_pq.build(ivf_pq.IndexParams(n_lists=32, pq_dim=8), data))
    r16 = rec_at(index16)
    r32 = rec_at(ivf_pq.build(ivf_pq.IndexParams(n_lists=32, pq_dim=32), data))
    assert r16 >= r8 - 0.02, (r8, r16)
    assert r32 >= r16 - 0.02, (r16, r32)


def test_pq_bits_4(dataset, truth10):
    data, queries = dataset
    index = ivf_pq.build(ivf_pq.IndexParams(n_lists=32, pq_dim=32, pq_bits=4), data)
    assert np.asarray(index.codes).max() < 16
    r = recall(ivf_pq.search(ivf_pq.SearchParams(n_probes=32), index, queries, 10)[1], truth10)
    # 4 bits over 2-d subspaces = 2 bits/dim; calibrated floor
    assert r >= 0.4, f"4-bit recall {r}"


def test_per_cluster_codebooks(dataset, truth10):
    data, queries = dataset
    params = ivf_pq.IndexParams(n_lists=32, pq_dim=16, codebook_kind=ivf_pq.PER_CLUSTER)
    index = ivf_pq.build(params, data)
    ids = ivf_pq.search(ivf_pq.SearchParams(n_probes=32), index, queries, 10)[1]
    r = recall(ids, truth10)
    # one codebook shared across subspaces is coarser than per-subspace
    assert r >= 0.45, f"per-cluster recall {r}"
    # recon engines decode per-cluster codebooks correctly (exercises the
    # per-cluster branch of _decode_quantize)
    i_lut = np.asarray(ids)
    for mode in ("recon8", "recon8_list"):
        i_rec = np.asarray(
            ivf_pq.search(ivf_pq.SearchParams(n_probes=32, score_mode=mode), index, queries, 10)[1]
        )
        ov = np.mean([len(set(i_lut[r_]) & set(i_rec[r_])) / 10 for r_ in range(len(i_lut))])
        assert ov >= 0.9, f"{mode} per-cluster overlap {ov}"


@pytest.fixture(scope="module")
def index_ip(dataset):
    """Shared inner-product index (read-only consumers)."""
    data, _ = dataset
    return ivf_pq.build(
        ivf_pq.IndexParams(n_lists=32, pq_dim=32, metric="inner_product"),
        data)


def test_inner_product(dataset, index_ip):
    data, queries = dataset
    _, truth = brute_force.knn(data, queries, 10, metric="inner_product")
    r = recall(ivf_pq.search(ivf_pq.SearchParams(n_probes=32), index_ip, queries, 10)[1], truth)
    assert r >= 0.7, f"IP recall {r}"


def test_extend_separate(dataset, truth10):
    """Incremental extend must be EXACTLY equivalent to one-shot extend."""
    data, queries = dataset
    params = ivf_pq.IndexParams(n_lists=32, pq_dim=16, add_data_on_build=False)
    base = ivf_pq.build(params, data)
    assert base.size == 0
    one = ivf_pq.extend(base, data)
    two = ivf_pq.extend(ivf_pq.extend(base, data[:10000]), data[10000:])
    assert two.size == len(data)
    d1, i1 = ivf_pq.search(ivf_pq.SearchParams(n_probes=32), one, queries, 10)
    d2, i2 = ivf_pq.search(ivf_pq.SearchParams(n_probes=32), two, queries, 10)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-5)
    r = recall(i2, truth10)
    assert r >= 0.45, f"extend recall {r}"


def test_bf16_lut(dataset, truth10, index16):
    data, queries = dataset
    index = index16
    r32 = recall(ivf_pq.search(ivf_pq.SearchParams(n_probes=16), index, queries, 10)[1], truth10)
    rb = recall(
        ivf_pq.search(ivf_pq.SearchParams(n_probes=16, lut_dtype="bfloat16"), index, queries, 10)[1],
        truth10,
    )
    assert rb >= r32 - 0.05  # bf16 LUT costs little recall


def test_save_load(dataset, tmp_path, index16):
    data, queries = dataset
    index = index16
    f = str(tmp_path / "ivf_pq.bin")
    ivf_pq.save(f, index)
    loaded = ivf_pq.load(f)
    d0, i0 = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), index, queries, 5)
    d1, i1 = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), loaded, queries, 5)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_allclose(np.asarray(d0), np.asarray(d1), rtol=1e-5)


def test_param_validation():
    with pytest.raises(ValueError):
        ivf_pq.IndexParams(pq_bits=9)
    with pytest.raises(ValueError):
        ivf_pq.IndexParams(codebook_kind="nope")
    # negative pq_dim rejects cleanly (0 is the documented auto sentinel;
    # without the guard a negative leaked into an XLA reshape error)
    with pytest.raises(ValueError, match="pq_dim"):
        ivf_pq.IndexParams(pq_dim=-3)
    assert ivf_pq.IndexParams(pq_dim=0).pq_dim == 0  # auto stays valid


def test_recon8_score_mode(dataset, truth10, index16):
    """int8 reconstruction scoring matches LUT scoring recall (TPU fast
    path; same math, decode-side int8 quantization only)."""
    data, queries = dataset
    index = index16
    r_lut = recall(
        ivf_pq.search(ivf_pq.SearchParams(n_probes=16), index, queries, 10)[1], truth10
    )
    r_rec = recall(
        ivf_pq.search(ivf_pq.SearchParams(n_probes=16, score_mode="recon8"), index, queries, 10)[1],
        truth10,
    )
    assert r_rec >= r_lut - 0.02, f"recon8 {r_rec} vs lut {r_lut}"
    assert index.recon8 is not None  # lazily built and cached
    # extend invalidates the cached reconstruction (new Index)
    ext = ivf_pq.extend(index, data[:10])
    assert ext.recon8 is None


def test_recon8_listmajor(dataset, truth10, index16):
    """List-major engine scores the same int8 reconstructions as the
    query-major recon8 engine — results must agree (modulo top-k ties) and
    pass the same recall floor."""
    data, queries = dataset
    index = index16
    i_qm = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, score_mode="recon8"), index, queries, 10
    )[1]
    d_lm, i_lm = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, score_mode="recon8_list"), index, queries, 10
    )
    i_qm, i_lm = np.asarray(i_qm), np.asarray(i_lm)
    overlap = np.mean(
        [len(set(i_qm[r]) & set(i_lm[r])) / 10 for r in range(len(i_qm))]
    )
    assert overlap >= 0.95, f"engine disagreement: overlap {overlap}"
    assert recall(i_lm, truth10) >= recall(i_qm, truth10) - 0.02
    assert np.all(np.diff(np.asarray(d_lm), axis=1) >= -1e-4)


def test_recon8_listmajor_int8_queries(dataset, truth10, index16):
    """score_dtype="int8" (symmetric int8 x int8 scoring) must track the
    bf16 list-major engine: the extra query-side quantization may shift
    near-tie candidates but not the recalled set materially."""
    data, queries = dataset
    index = index16
    i_bf = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, score_mode="recon8_list"), index, queries, 10
    )[1]
    d_i8, i_i8 = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, score_mode="recon8_list", score_dtype="int8"),
        index, queries, 10,
    )
    i_bf, i_i8 = np.asarray(i_bf), np.asarray(i_i8)
    overlap = np.mean(
        [len(set(i_bf[r]) & set(i_i8[r])) / 10 for r in range(len(i_bf))]
    )
    assert overlap >= 0.9, f"int8 engine diverged: overlap {overlap}"
    assert recall(i_i8, truth10) >= recall(i_bf, truth10) - 0.03
    assert np.all(np.diff(np.asarray(d_i8), axis=1) >= -1e-4)


def test_recon8_listmajor_bf16_trim(dataset, truth10, index16):
    """internal_distance_dtype="bfloat16" trims the list-major engine in
    bf16 — near-tie ranking noise only; the recalled set must track f32."""
    data, queries = dataset
    index = index16
    i_f32 = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, score_mode="recon8_list"), index, queries, 10
    )[1]
    d_bf, i_bf = ivf_pq.search(
        ivf_pq.SearchParams(
            n_probes=16, score_mode="recon8_list", internal_distance_dtype="bfloat16"
        ),
        index, queries, 10,
    )
    assert np.asarray(d_bf).dtype == np.float32  # returned distances stay f32
    i_f32, i_bf = np.asarray(i_f32), np.asarray(i_bf)
    overlap = np.mean(
        [len(set(i_f32[r]) & set(i_bf[r])) / 10 for r in range(len(i_f32))]
    )
    assert overlap >= 0.9, f"bf16 trim diverged: overlap {overlap}"
    assert recall(i_bf, truth10) >= recall(i_f32, truth10) - 0.03


def test_internal_distance_dtype_auto_resolves_f32_off_tpu(dataset, index16):
    """The "auto" default resolves to exact f32 trim on non-TPU backends
    (the bf16 tuned hint was measured on chip and is TPU-gated), so the
    default-params result is bit-identical to an explicit float32."""
    data, queries = dataset
    d_auto, i_auto = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, score_mode="recon8_list"),
        index16, queries, 10,
    )
    d_f32, i_f32 = ivf_pq.search(
        ivf_pq.SearchParams(
            n_probes=16, score_mode="recon8_list",
            internal_distance_dtype="float32",
        ),
        index16, queries, 10,
    )
    np.testing.assert_array_equal(np.asarray(i_auto), np.asarray(i_f32))
    np.testing.assert_array_equal(np.asarray(d_auto), np.asarray(d_f32))
    with pytest.raises(ValueError, match="internal_distance_dtype"):
        ivf_pq.search(
            ivf_pq.SearchParams(n_probes=16, internal_distance_dtype="fp8"),
            index16, queries, 10,
        )


def test_recon8_listmajor_pallas_trim(dataset, truth10, index16):
    """trim_engine="pallas" (fused list-scan, interpret mode on CPU) must
    track the XLA approx-trim engine: same scores modulo bf16 matmul
    rounding and bin-collision trim noise."""
    data, queries = dataset
    index = index16
    i_x = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, score_mode="recon8_list"), index, queries, 10
    )[1]
    d_p, i_p = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, score_mode="recon8_list", trim_engine="pallas"),
        index, queries, 10,
    )
    i_x, i_p = np.asarray(i_x), np.asarray(i_p)
    overlap = np.mean([len(set(i_x[r]) & set(i_p[r])) / 10 for r in range(len(i_x))])
    # best+second-best per bin leaves only 3-way collisions as trim loss
    assert overlap >= 0.95, f"pallas trim diverged: overlap {overlap}"
    assert recall(i_p, truth10) >= recall(i_x, truth10) - 0.05
    assert np.all(np.diff(np.asarray(d_p), axis=1) >= -1e-4)
    assert np.asarray(d_p).dtype == np.float32


def test_recon8_listmajor_pallas_packed_fold(dataset, truth10, index16, monkeypatch):
    """pallas_fold="packed" tuned key routes the fused trim through the
    bf16-coarse packed fold end-to-end (fold_variant() wiring): results
    must track the exact-fold pallas engine at trim-noise level."""
    from raft_tpu.core import tuned

    data, queries = dataset
    index = index16
    p = ivf_pq.SearchParams(
        n_probes=16, score_mode="recon8_list", trim_engine="pallas"
    )
    # pin the baseline: a committed pallas_fold="packed" tuned key must
    # not silently turn this into packed-vs-packed
    monkeypatch.setitem(tuned._load(), "pallas_fold", "exact")
    i_exact = np.asarray(ivf_pq.search(p, index, queries, 10)[1])
    monkeypatch.setitem(tuned._load(), "pallas_fold", "packed")
    try:
        d_p, i_p = ivf_pq.search(p, index, queries, 10)
    finally:
        tuned.reload()
    i_p = np.asarray(i_p)
    overlap = np.mean(
        [len(set(i_exact[r]) & set(i_p[r])) / 10 for r in range(len(i_exact))]
    )
    assert overlap >= 0.9, f"packed fold diverged: overlap {overlap}"
    assert recall(i_p, truth10) >= recall(i_exact, truth10) - 0.05
    assert np.all(np.diff(np.asarray(d_p), axis=1) >= -1e-4)


def test_pallas_trim_validation(dataset, index16):
    data, queries = dataset
    index = index16
    with pytest.raises(ValueError, match="trim_engine"):
        ivf_pq.search(
            ivf_pq.SearchParams(score_mode="lut", trim_engine="pallas"),
            index, queries, 5,
        )
    with pytest.raises(ValueError, match="trim_engine"):
        ivf_pq.search(
            ivf_pq.SearchParams(score_mode="recon8_list", trim_engine="warp"),
            index, queries, 5,
        )


def test_pallas_trim_int8_queries(dataset, truth10, index16):
    """Symmetric int8 scoring inside the fused kernel: must track the XLA
    int8 engine (same quantization, different trim)."""
    data, queries = dataset
    index = index16
    i_x = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, score_mode="recon8_list",
                            score_dtype="int8"),
        index, queries, 10,
    )[1]
    d_p, i_p = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, score_mode="recon8_list",
                            score_dtype="int8", trim_engine="pallas"),
        index, queries, 10,
    )
    i_x, i_p = np.asarray(i_x), np.asarray(i_p)
    overlap = np.mean([len(set(i_x[r]) & set(i_p[r])) / 10 for r in range(len(i_x))])
    assert overlap >= 0.95, f"int8 pallas trim diverged: overlap {overlap}"
    assert recall(i_p, truth10) >= recall(i_x, truth10) - 0.05
    assert np.all(np.diff(np.asarray(d_p), axis=1) >= -1e-4)


def test_pallas_trim_inner_product(dataset):
    data, queries = dataset
    params = ivf_pq.IndexParams(
        n_lists=32, pq_dim=16, metric="inner_product", force_random_rotation=True
    )
    index = ivf_pq.build(params, data)
    i_x = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, score_mode="recon8_list"), index, queries, 10
    )[1]
    i_p = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, score_mode="recon8_list", trim_engine="pallas"),
        index, queries, 10,
    )[1]
    i_x, i_p = np.asarray(i_x), np.asarray(i_p)
    overlap = np.mean([len(set(i_x[r]) & set(i_p[r])) / 10 for r in range(len(i_x))])
    assert overlap >= 0.85, f"IP pallas trim diverged: overlap {overlap}"


def test_bad_score_dtype_raises(dataset, index16):
    data, queries = dataset
    index = index16
    with pytest.raises(ValueError, match="score_dtype"):
        ivf_pq.search(
            ivf_pq.SearchParams(score_mode="recon8_list", score_dtype="fp64"),
            index, queries, 5,
        )


def test_recon8_listmajor_inner_product(dataset, index_ip):
    data, queries = dataset
    _, truth = brute_force.knn(data, queries, 10, metric="inner_product")
    r = recall(
        ivf_pq.search(
            ivf_pq.SearchParams(n_probes=32, score_mode="recon8_list"),
            index_ip, queries, 10
        )[1],
        truth,
    )
    assert r >= 0.7, f"IP list-major recall {r}"


def test_auto_score_mode(dataset, truth10, index16):
    """auto picks an engine by batch duplication factor; both regimes work."""
    data, queries = dataset
    index = index16
    # 80 queries * 16 probes / 32 lists = 40x duplication -> list-major
    i_auto = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, score_mode="auto"), index, queries, 10
    )[1]
    i_lut = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, score_mode="lut"), index, queries, 10
    )[1]
    assert recall(i_auto, truth10) >= recall(i_lut, truth10) - 0.03
    # single query -> query-major lut
    d, i = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, score_mode="auto"), index, queries[:1], 10
    )
    assert np.asarray(i).shape == (1, 10)


def test_recon8_bad_mode(dataset, index16):
    data, queries = dataset
    index = index16
    with pytest.raises(ValueError):
        ivf_pq.search(ivf_pq.SearchParams(score_mode="nope"), index, queries, 5)


def test_integer_dtype_datasets():
    """int8/uint8 datasets build and search through the upcast path with
    reference-grade recall (ann_ivf_pq.cuh instantiates the full test
    grid for T in {float, int8_t, uint8_t}; the TPU build upcasts to f32
    at ingest — same results class, no separate kernel family needed)."""
    rng = np.random.default_rng(0)
    for dt, lo, hi in ((np.uint8, 0, 256), (np.int8, -128, 128)):
        data = rng.integers(lo, hi, (3000, 32)).astype(dt)
        q = data[:10]
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_dim=16), data)
        _, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), idx, q, 5)
        _, t = brute_force.knn(data.astype(np.float32),
                               q.astype(np.float32), 5)
        r = recall(i, np.asarray(t))
        assert r >= 0.85, (dt, r)
        # round-trip ids are valid rows of the integer dataset (min >= 0
        # also excludes the -1 invalid-id sentinel)
        assert np.asarray(i).min() >= 0 and np.asarray(i).max() < len(data)


def test_default_params_route_to_measured_engine(monkeypatch):
    """VERDICT r4 #5: a default-constructed SearchParams must land on the
    measured winner, never the device-faulting lut engine, on TPU."""
    p = ivf_pq.SearchParams()
    assert p.score_mode == "auto"
    # TPU resolution: small-dup batches fall back to the gather-free
    # recon8 engine, large-dup to recon8_list; NEVER lut — even when a
    # (CPU-fitted) tuned key says lut
    import jax

    from raft_tpu.core import tuned

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    # pin the tuned state: a chip session may have committed a
    # pq_auto_engine key, and the heuristic asserts below assume none
    # (monkeypatch.setitem restores/deletes on teardown; no reload —
    # reload would re-read whatever is on disk mid-test)
    monkeypatch.setitem(tuned._load(), "pq_auto_engine", None)
    assert ivf_pq._resolve_score_mode(p, nq=1, n_probes=4, n_lists=64) == "recon8"
    assert (
        ivf_pq._resolve_score_mode(p, nq=4096, n_probes=32, n_lists=64)
        == "recon8_list"
    )
    monkeypatch.setitem(tuned._load(), "pq_auto_engine", "lut")
    assert ivf_pq._resolve_score_mode(p, nq=1, n_probes=4, n_lists=64) == "recon8"
    monkeypatch.setitem(tuned._load(), "pq_auto_engine", "recon8_list")
    assert (
        ivf_pq._resolve_score_mode(p, nq=1, n_probes=4, n_lists=64) == "recon8_list"
    )
    # CPU keeps the classic small-batch lut (no fault class there)
    monkeypatch.setitem(tuned._load(), "pq_auto_engine", None)
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    assert ivf_pq._resolve_score_mode(p, nq=1, n_probes=4, n_lists=64) == "lut"


def test_lut_fenced_on_tpu(dataset, index16, monkeypatch):
    """Explicit score_mode='lut' on TPU raises the documented guard; the
    env override (profiling-only) lifts it."""
    import jax

    data, queries = dataset
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    with pytest.raises(ValueError, match="fenced on TPU"):
        ivf_pq.search(
            ivf_pq.SearchParams(score_mode="lut"), index16, queries[:4], 5
        )
    # the override lifts the fence WITH the backend still reading "tpu"
    # (the profiler's sanctioned fault-repro path); the engine itself
    # runs on this process's real CPU devices regardless of the mock
    monkeypatch.setenv(ivf_pq._LUT_TPU_OVERRIDE, "1")
    d, i = ivf_pq.search(
        ivf_pq.SearchParams(score_mode="lut", n_probes=8), index16, queries[:4], 5
    )
    assert np.asarray(i).shape == (4, 5)


def test_exact_trim_engine(dataset, truth10, index16):
    """trim_engine='exact' (per-superblock lax.top_k) loses zero
    candidates: recall >= the approx bin-trim's on the same index."""
    data, queries = dataset
    p_ex = ivf_pq.SearchParams(
        n_probes=16, score_mode="recon8_list", trim_engine="exact"
    )
    p_ap = ivf_pq.SearchParams(
        n_probes=16, score_mode="recon8_list", trim_engine="approx"
    )
    d_ex, i_ex = ivf_pq.search(p_ex, index16, queries, 10)
    _, i_ap = ivf_pq.search(p_ap, index16, queries, 10)
    assert recall(i_ex, truth10) >= recall(i_ap, truth10) - 1e-9
    # sorted best-first, ids valid
    assert np.all(np.diff(np.asarray(d_ex), axis=1) >= -1e-5)
    assert np.asarray(i_ex).min() >= 0
    # exact trim requires the list-major engine
    with pytest.raises(ValueError, match="exact"):
        ivf_pq.search(
            ivf_pq.SearchParams(score_mode="recon8", trim_engine="exact"),
            index16, queries, 10,
        )


def test_listmajor_setup_impl_equivalence(dataset, truth10, index16, monkeypatch):
    """The tuned setup impls (counting inversion, one-hot query rows) must
    not change the list-major engine's results: invert_impl=count and
    qs_impl=onehot_f32h are bit-preserving by construction (counting
    tables are bit-identical, f32-highest one-hot reproduces the gather),
    and onehot_bf16 may only move near-ties (overlap gate)."""
    from raft_tpu.core import tuned

    _, queries = dataset
    p = ivf_pq.SearchParams(n_probes=16, score_mode="recon8_list")
    d_ref, i_ref = ivf_pq.search(p, index16, queries, 10)
    i_ref = np.asarray(i_ref)

    def force(invert, qs):
        real = tuned.get_choice

        def fake(key, allowed, default):
            if key == "invert_impl":
                return invert
            if key == "listmajor_qs_impl":
                return qs
            return real(key, allowed, default)

        monkeypatch.setattr(tuned, "get_choice", fake)
        out = ivf_pq.search(p, index16, queries, 10)
        monkeypatch.setattr(tuned, "get_choice", real)
        return out

    d_c, i_c = force("count", "onehot_f32h")
    assert np.array_equal(np.asarray(i_c), i_ref)
    np.testing.assert_allclose(np.asarray(d_c), np.asarray(d_ref), rtol=1e-6)

    _, i_b = force("count", "onehot_bf16")
    i_b = np.asarray(i_b)
    overlap = np.mean(
        [len(set(i_b[r]) & set(i_ref[r])) / 10 for r in range(len(i_ref))]
    )
    assert overlap >= 0.95, f"bf16 one-hot moved results: overlap {overlap}"


# -- quantizer-refactor bit-identity goldens ----------------------------

def test_refactor_bit_identical_to_prerefactor_goldens():
    """PR 6 moved codebook training + encode into the shared quantizer
    layer (neighbors/quantizer.py). This pins the refactor to goldens
    captured from the PRE-refactor code (tests/goldens/
    ivf_pq_prerefactor.json): codes, codebooks and all three engines'
    search results must stay BIT-identical. Any drift here means the
    'refactor' changed numerics and is a bug by definition."""
    import hashlib
    import json
    import os

    gold_path = os.path.join(os.path.dirname(__file__), "goldens",
                             "ivf_pq_prerefactor.json")
    with open(gold_path) as f:
        gold = json.load(f)
    data, _ = make_blobs(2000, 32, n_clusters=8, cluster_std=0.6, seed=5)
    data = np.asarray(data, np.float32)
    idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=4), data,
        seed=0)
    assert hashlib.sha256(
        np.asarray(idx.codes).tobytes()).hexdigest() == gold["codes_sha"]
    assert hashlib.sha256(
        np.asarray(idx.pq_centers, np.float32).tobytes()
    ).hexdigest() == gold["pq_centers_sha"]
    assert hashlib.sha256(
        np.asarray(idx.centers, np.float32).tobytes()
    ).hexdigest() == gold["centers_sha"]
    for mode in ("recon8", "recon8_list", "lut"):
        v, i = ivf_pq.search(
            ivf_pq.SearchParams(n_probes=8, score_mode=mode,
                                internal_distance_dtype="float32"),
            idx, data[:8], 5)
        assert np.asarray(v, np.float32).tolist() == gold[mode]["values"], mode
        assert np.asarray(i, np.int32).tolist() == gold[mode]["ids"], mode
    # per-cluster codebooks cover the second trainer + encode path
    idx2 = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=4,
                           codebook_kind="per_cluster"), data[:1000], seed=3)
    assert hashlib.sha256(
        np.asarray(idx2.codes).tobytes()).hexdigest() == gold["pc_codes_sha"]
    v, i = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=4, score_mode="lut",
                            internal_distance_dtype="float32"),
        idx2, data[:5], 4)
    assert np.asarray(v, np.float32).tolist() == gold["pc_lut"]["values"]
    assert np.asarray(i, np.int32).tolist() == gold["pc_lut"]["ids"]
