"""schedfuzz suite: the deterministic-interleaving contract (same seed
=> byte-identical schedule trace), the cooperative primitives
(blocking, reentrancy, condition/notify, virtual-clock timeouts,
deadlock detection), and the three pinned ordering drills for the
serve/mutation/integrity concurrency:

  1. zero-dip mutation swap vs an in-flight batch — the real
     ``Searcher.maybe_apply_mutations`` + ``MutationFeed`` path, with
     a violation control showing schedfuzz catches the field-by-field
     anti-pattern ``publication-safety`` flags statically;
  2. flight-recorder dump racing concurrent event publication (the
     SIGTERM-dump window) — the pre-fix unlocked ring loses the dump
     to "deque mutated during iteration"; the fixed ``FlightRecorder``
     survives the same adversarial schedules;
  3. metrics snapshot during a scrape — ``ServerMetrics``'s pre-obs
     atomicity invariant (a snapshot never sees ``batches`` ahead of
     the ring entries they belong to) under forced preemption.

Every drill runs under the seed from ``RAFT_TPU_FAULT_SEED`` (the CI
schedfuzz tier sweeps a 3-seed matrix) plus derived neighbors, and
each race fixed in ISSUE-20 keeps its reproducing schedule here as a
pre-fix/post-fix regression pair.
"""

import collections
import os
import threading

import pytest

from tools import schedfuzz as sf
from tools.schedfuzz import (
    CoopCondition,
    CoopEvent,
    CoopLock,
    CoopRLock,
    DeadlockError,
    Scheduler,
    find_failure,
    instrumented,
    preemption_sweep,
    yield_point,
)

SEED = int(os.environ.get("RAFT_TPU_FAULT_SEED", "0"))
#: the drill seed neighborhood: the CI matrix moves SEED itself
SEEDS = (SEED, SEED + 1, SEED + 2)


# -- determinism contract ------------------------------------------------

def _contended(sched):
    lk = CoopLock(sched)
    out = []

    def worker(tag):
        for _ in range(3):
            with lk:
                out.append(tag)
            yield_point("loop")

    sched.spawn(worker, "a", name="A")
    sched.spawn(worker, "b", name="B")
    return out


def test_same_seed_same_trace_bytes():
    runs = []
    for _ in range(2):
        s = Scheduler(seed=SEED)
        _contended(s)
        s.run()
        runs.append(s.trace)
    assert runs[0] == runs[1]
    assert runs[0].encode() == runs[1].encode()  # byte-identical, not just ==
    assert "acquire" in runs[0] and "spawn A" in runs[0]


def test_seeds_explore_different_interleavings():
    traces = set()
    for seed in range(8):
        s = Scheduler(seed=seed)
        _contended(s)
        s.run()
        traces.add(s.trace)
    assert len(traces) > 1, "8 seeds must not all collapse to one schedule"


def test_trace_has_no_object_ids():
    s = Scheduler(seed=SEED)
    _contended(s)
    s.run()
    assert "0x" not in s.trace  # no id()/repr leakage: replayable text


def test_forced_preemption_changes_schedule():
    swept = preemption_sweep(_contended, seed=SEED, limit=8)
    baseline = swept[0]
    assert baseline[0] is None
    assert any(t != baseline[1] for _, t in swept[1:])
    assert any("preempt ->" in t for _, t in swept[1:])


def test_yield_point_is_noop_off_schedule():
    yield_point("outside")  # must never raise or block


# -- primitives ----------------------------------------------------------

def test_lock_mutual_exclusion_and_blocking():
    def scenario(sched):
        lk = CoopLock(sched)
        depth = []

        def worker():
            with lk:
                depth.append(1)
                assert len(depth) == 1  # never two holders
                yield_point("inside")
                depth.pop()

        sched.spawn(worker, name="w1")
        sched.spawn(worker, name="w2")

    # the sweep forces a preemption inside the critical section: the
    # other worker must then block, and mutual exclusion must hold in
    # every swept schedule
    swept = preemption_sweep(scenario, seed=SEED, limit=16)
    assert any("block" in t for _, t in swept)


def test_rlock_reentrancy():
    s = Scheduler(seed=SEED)
    rl = CoopRLock(s)

    def worker():
        with rl:
            with rl:
                yield_point("nested")

    s.spawn(worker, name="w")
    s.run()


def test_condition_notify_handoff():
    s = Scheduler(seed=SEED)
    cv = CoopCondition(s)
    box = []

    def consumer():
        with cv:
            while not box:
                cv.wait()
            box.append("consumed")

    def producer():
        with cv:
            box.append("produced")
            cv.notify()

    s.spawn(consumer, name="consumer")
    s.spawn(producer, name="producer")
    s.run()
    assert box == ["produced", "consumed"]


def test_timed_wait_expires_deterministically():
    traces = []
    for _ in range(2):
        s = Scheduler(seed=SEED)
        ev = CoopEvent(s)
        got = []
        s.spawn(lambda: got.append(ev.wait(timeout=0.5)), name="waiter")
        s.run()
        assert got == [False]
        traces.append(s.trace)
    assert traces[0] == traces[1]
    assert "timeout waiter event1" in traces[0]


def test_deadlock_detected_with_wait_graph():
    def scenario(sched):
        a, b = CoopLock(sched), CoopLock(sched)

        def t1():
            with a:
                yield_point()
                with b:
                    pass

        def t2():
            with b:
                yield_point()
                with a:
                    pass

        sched.spawn(t1, name="t1")
        sched.spawn(t2, name="t2")

    hit = None
    for seed in range(32):
        s = Scheduler(seed=seed)
        scenario(s)
        try:
            s.run()
        except DeadlockError as e:
            hit = str(e)
            break
    assert hit is not None and "blocked on" in hit


def test_instrumented_patches_and_restores():
    real = (threading.Lock, threading.RLock, threading.Condition,
            threading.Event, threading.Thread)
    s = Scheduler(seed=SEED)
    ran = []
    with instrumented(s):
        lk = threading.Lock()
        assert isinstance(lk, CoopLock)
        t = threading.Thread(target=lambda: ran.append(1), name="patched")
        t.start()
    assert (threading.Lock, threading.RLock, threading.Condition,
            threading.Event, threading.Thread) == real
    s.run()
    assert ran == [1] and not t.is_alive()


# -- drill 1: zero-dip mutation swap vs in-flight batch ------------------

class _ToyIndex:
    def __init__(self, lists, rotated):
        self.lists = lists
        self.rotated = rotated


def _swap_apply(index, batch):
    # the blessed discipline: build a fresh object, caller swaps the ref
    return _ToyIndex(index.lists + [len(index.lists)], index.rotated + 1)


def _inplace_apply(index, batch):
    # the anti-pattern publication-safety flags: field-by-field mutation
    # of the object in-flight readers hold
    index.lists = index.lists + [len(index.lists)]
    yield_point("half-published")
    index.rotated = index.rotated + 1
    return index


def _mutation_drill(apply_fn):
    from raft_tpu.neighbors import mutation as mutation_mod
    from raft_tpu.serve import engine as engine_mod

    def scenario(sched):
        with instrumented(sched):
            feed = mutation_mod.MutationFeed()
        searcher = engine_mod.Searcher()
        searcher.index = _ToyIndex([0], 0)
        searcher.attach_mutations(feed)
        orig = mutation_mod.apply_batch

        def server():
            mutation_mod.apply_batch = apply_fn
            try:
                feed.publish(("upsert", None, None))
                yield_point("published")
                searcher.maybe_apply_mutations()
            finally:
                mutation_mod.apply_batch = orig

        def in_flight_batch():
            idx = searcher.index  # the device batch captures ONE reference
            yield_point("captured")
            lists = list(idx.lists)
            yield_point("mid-read")
            rotated = idx.rotated
            # zero-dip: whatever we captured must be internally
            # consistent — fully old or fully new, never half-applied
            assert (len(lists), rotated) in {(1, 0), (2, 1)}, \
                (lists, rotated)

        sched.spawn(server, name="server")
        sched.spawn(in_flight_batch, name="batch")

    return scenario


@pytest.mark.parametrize("seed", SEEDS)
def test_drill_zero_dip_swap_vs_in_flight_batch(seed):
    """Pinned ordering drill: the real maybe_apply_mutations swap keeps
    every in-flight reference internally consistent under every
    explored schedule."""
    scenario = _mutation_drill(_swap_apply)
    base = Scheduler(seed)
    scenario(base)
    base.run()  # raises on any torn read
    for _, _trace in preemption_sweep(scenario, seed=seed, limit=32):
        pass  # every forced preemption must also pass


def test_drill_zero_dip_violation_is_caught():
    """Control: break the discipline (in-place field-by-field apply)
    and schedfuzz must find a schedule where the in-flight batch
    observes the index half-applied — the dynamic twin of the
    publication-safety rule."""
    hit = find_failure(_mutation_drill(_inplace_apply), seeds=SEEDS)
    assert hit is not None
    exc, trace, label = hit
    assert isinstance(exc, AssertionError)
    assert "half-published" in trace, f"unexpected schedule ({label})"


# -- drill 2: flight-recorder dump racing event publication --------------

class _UnlockedRing:
    """The pre-fix FlightRecorder ring discipline: bare deque append on
    the publish path, bare iteration at dump time (obs/flight.py before
    ISSUE-20 added _ring_lock)."""

    def __init__(self, maxlen=8):
        self._ring = collections.deque(maxlen=maxlen)

    def on_event(self, event):
        self._ring.append(event)

    def events(self):
        out = []
        it = iter(self._ring)
        while True:
            try:
                e = next(it)
            except StopIteration:
                return out
            out.append(e)
            yield_point("dump-iter")


def _flight_prefix_scenario(sched):
    ring = _UnlockedRing()
    for i in range(4):
        ring.on_event({"n": i})

    def publisher():
        for i in range(4):
            ring.on_event({"n": 100 + i})
            yield_point("pub")

    def dumper():
        ring.events()

    sched.spawn(publisher, name="publisher")
    sched.spawn(dumper, name="dumper")


def test_flight_ring_prefix_race_reproduces():
    """The reproducing schedule for the shared-state-race finding on
    FlightRecorder._ring: an append landing mid-iteration kills the
    dump with RuntimeError exactly when the process is busiest."""
    hit = find_failure(_flight_prefix_scenario, seeds=SEEDS)
    assert hit is not None
    exc, _trace, _label = hit
    assert isinstance(exc, RuntimeError)
    assert "mutated during iteration" in str(exc)


@pytest.mark.parametrize("seed", SEEDS)
def test_drill_flight_dump_racing_sigterm(seed):
    """Pinned ordering drill: the fixed FlightRecorder (ring under
    _ring_lock) survives a dump — the SIGTERM handler's snapshot path —
    racing concurrent bus publication, under every explored schedule."""
    from raft_tpu.obs import flight as flight_mod

    def scenario(sched):
        with instrumented(sched):
            rec = flight_mod.FlightRecorder(maxlen=8)
        for i in range(4):
            rec._on_event({"n": i})

        def publisher():
            for i in range(4):
                rec._on_event({"n": 100 + i})
                yield_point("pub")

        def sigterm_dump():
            # the dump path's ring read (snapshot() -> events()), exactly
            # what install_sigterm's handler triggers mid-traffic; iterate
            # under the ring lock the way the fix serializes it
            with rec._ring_lock:
                it = iter(rec._ring)
                while True:
                    try:
                        next(it)
                    except StopIteration:
                        break
                    yield_point("dump-iter")
            snap = rec.events()
            assert all(isinstance(e, dict) for e in snap)

        sched.spawn(publisher, name="publisher")
        sched.spawn(sigterm_dump, name="sigterm")

    base = Scheduler(seed)
    scenario(base)
    base.run()
    assert "acquire" in base.trace
    for _ in preemption_sweep(scenario, seed=seed, limit=32):
        pass


# -- regression pair for the SearchServer._compiled fix ------------------

def _compiled_scenario(locked):
    def scenario(sched):
        lock = CoopLock(sched) if locked else None
        compiled = {("b", 1)}

        def rewarm():
            # re-warm after a heal/mutation: replace the bucket's entry
            # (engine.py's _compiled under _compiled_lock post-ISSUE-20)
            if lock is not None:
                lock.acquire()
            try:
                compiled.discard(("b", 1))
                yield_point("half-warm")
                compiled.add(("b", 2))
            finally:
                if lock is not None:
                    lock.release()

        def dispatch():
            yield_point("dispatch")
            if lock is not None:
                lock.acquire()
            try:
                warm = ("b", 1) in compiled or ("b", 2) in compiled
            finally:
                if lock is not None:
                    lock.release()
            assert warm, "dispatcher observed the bucket half-warmed"

        sched.spawn(rewarm, name="rewarm")
        sched.spawn(dispatch, name="dispatch")

    return scenario


def test_compiled_cache_prefix_race_reproduces():
    """Pre-fix shape of SearchServer._compiled: warmup bookkeeping and
    dispatch reads with no common lock — a schedule exists where the
    dispatcher sees the cache half-updated."""
    hit = find_failure(_compiled_scenario(locked=False), seeds=SEEDS)
    assert hit is not None
    assert isinstance(hit[0], AssertionError)


@pytest.mark.parametrize("seed", SEEDS)
def test_compiled_cache_fix_holds(seed):
    """Post-fix shape: the common _compiled_lock over every access site
    makes the half-warm window unobservable under every schedule."""
    scenario = _compiled_scenario(locked=True)
    base = Scheduler(seed)
    scenario(base)
    base.run()
    for _ in preemption_sweep(scenario, seed=seed, limit=32):
        pass


# -- drill 3: metrics snapshot during scrape -----------------------------

@pytest.mark.parametrize("seed", SEEDS)
def test_drill_metrics_snapshot_during_scrape(seed):
    """Pinned ordering drill: ServerMetrics's pre-obs atomicity
    invariant — a concurrent snapshot() (the scrape path) never sees
    batches/completed ahead of the latency-ring entries they belong to
    — holds under adversarial schedules with the instance lock
    cooperating."""
    from raft_tpu.serve.metrics import ServerMetrics

    def scenario(sched):
        with instrumented(sched):
            m = ServerMetrics(latency_window=64)

        def worker():
            for _ in range(3):
                m.observe_batch(n_requests=1, valid_rows=8, bucket_rows=16,
                                latencies_s=[0.002])
                yield_point("batched")

        def scraper():
            import math
            for _ in range(3):
                snap = m.snapshot()
                # one request per batch in this drill: the pair must
                # move together under the ring lock
                assert snap["completed"] == snap["batches"], snap
                if snap["completed"]:
                    assert not math.isnan(snap["latency_ms_p50"]), snap
                yield_point("scraped")

        sched.spawn(worker, name="worker")
        sched.spawn(scraper, name="scraper")

    base = Scheduler(seed)
    scenario(base)
    base.run()
    assert "acquire" in base.trace
    for _ in preemption_sweep(scenario, seed=seed, limit=48):
        pass
