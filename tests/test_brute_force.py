"""Brute-force kNN tests vs numpy oracle (mirrors cpp/test/neighbors/knn.cu)."""

import numpy as np
import pytest
from scipy.spatial import distance as spdist

from raft_tpu.neighbors import brute_force
from raft_tpu.random import make_blobs


@pytest.mark.parametrize("metric", ["sqeuclidean", "euclidean", "cosine", "l1"])
def test_knn_exact(metric, rng):
    ds = rng.random((500, 32), dtype=np.float32)
    q = rng.random((37, 32), dtype=np.float32)
    k = 10
    d, i = brute_force.knn(ds, q, k, metric=metric)
    d, i = np.asarray(d), np.asarray(i)
    full = spdist.cdist(q.astype(np.float64), ds.astype(np.float64), METRICS[metric])
    want_i = np.argsort(full, axis=1)[:, :k]
    want_d = np.take_along_axis(full, want_i, axis=1)
    np.testing.assert_allclose(d, want_d, rtol=2e-3, atol=2e-3)
    # indices can differ on ties; distances must match
    got_d_of_i = np.take_along_axis(full, i, axis=1)
    np.testing.assert_allclose(got_d_of_i, want_d, rtol=2e-3, atol=2e-3)


METRICS = {
    "sqeuclidean": "sqeuclidean",
    "euclidean": "euclidean",
    "cosine": "cosine",
    "l1": "cityblock",
}


def test_knn_inner_product(rng):
    ds = rng.random((200, 16), dtype=np.float32)
    q = rng.random((11, 16), dtype=np.float32)
    d, i = brute_force.knn(ds, q, 5, metric="inner_product")
    full = q @ ds.T
    want_i = np.argsort(-full, axis=1)[:, :5]
    want_d = np.take_along_axis(full, want_i, axis=1)
    np.testing.assert_allclose(np.asarray(d), want_d, rtol=1e-3)


def test_knn_tiled_path(rng):
    """Dataset large enough to force the scanned/tiled path."""
    ds = rng.random((70000, 8), dtype=np.float32)
    q = rng.random((5, 8), dtype=np.float32)
    k = 7
    d, i = brute_force.knn(ds, q, k, metric="sqeuclidean")
    d, i = np.asarray(d), np.asarray(i)
    full = spdist.cdist(q, ds, "sqeuclidean")
    want_i = np.argsort(full, axis=1)[:, :k]
    want_d = np.take_along_axis(full, want_i, axis=1)
    np.testing.assert_allclose(d, want_d, rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(
        np.take_along_axis(full, i, axis=1), want_d, rtol=2e-3, atol=2e-3
    )


def test_knn_compute_dtype_bf16(rng):
    """compute_dtype=bfloat16 ranks the rounded points: near-exact vs
    the f32 oracle (swaps only below bf16 noise), distances finite f32,
    and the speed knob must not change the API shape."""
    import jax.numpy as jnp

    ds = make_blobs(4000, 24, n_clusters=8, seed=3)[0]
    q = np.asarray(ds[:50])
    k = 10
    d16, i16 = brute_force.knn(ds, q, k, compute_dtype=jnp.bfloat16)
    d32, i32 = brute_force.knn(ds, q, k)
    d16, i16, i32 = np.asarray(d16), np.asarray(i16), np.asarray(i32)
    assert d16.dtype == np.float32 and np.isfinite(d16).all()
    overlap = np.mean(
        [len(set(i16[j]) & set(i32[j])) / k for j in range(len(q))]
    )
    assert overlap >= 0.95, overlap
    assert (i16[:, 0] == np.arange(50)).all()  # self is still 1-NN
    # the fused engine already streams a bf16 store: pre-rounding would
    # only lose recall, so the knob is tiled-only
    with pytest.raises(ValueError, match="tiled"):
        brute_force.knn(ds, q, k, engine="pallas",
                        compute_dtype=jnp.bfloat16)


def test_knn_merge_parts(rng):
    parts_d = rng.random((3, 10, 4), dtype=np.float32)
    parts_i = rng.integers(0, 1000, (3, 10, 4))
    d, i = brute_force.knn_merge_parts(parts_d, parts_i, k=4)
    d = np.asarray(d)
    allv = np.moveaxis(parts_d, 0, 1).reshape(10, 12)
    want = np.sort(allv, axis=1)[:, :4]
    np.testing.assert_allclose(d, want, rtol=1e-6)


def test_knn_on_blobs():
    data, labels = make_blobs(2000, 16, n_clusters=5, cluster_std=0.5, seed=3)
    data, labels = np.asarray(data), np.asarray(labels)
    d, i = brute_force.knn(data, data, 5, metric="sqeuclidean")
    i = np.asarray(i)
    # a point's nearest neighbor is itself
    np.testing.assert_array_equal(i[:, 0], np.arange(2000))
    # neighbors overwhelmingly share the query's blob label
    same = (labels[i[:, 1:]] == labels[:, None]).mean()
    assert same > 0.95


def test_knn_fused_pallas_engine(rng):
    """Fused-scan engine (fused_l2_knn analogue) vs the exact tiled path:
    near-exact under the bin trim, ids valid, guards enforced."""
    data = rng.random((1500, 24), dtype=np.float32)
    q = data[:32]
    _, it = brute_force.knn(data, q, 10)
    _, ip = brute_force.knn(data, q, 10, engine="pallas")
    g, t = np.asarray(ip), np.asarray(it)
    overlap = np.mean([len(set(g[i]) & set(t[i])) / 10 for i in range(32)])
    assert overlap >= 0.95, overlap
    assert g.min() >= 0 and g.max() < 1500
    # self-match survives the trim
    assert all(g[i, 0] == i for i in range(32))
    with pytest.raises(ValueError):
        brute_force.knn(data, q, 300, engine="pallas")
    with pytest.raises(ValueError):
        brute_force.knn(data, q, 5, metric="canberra", engine="pallas")
    with pytest.raises(ValueError):
        brute_force.knn(data, q, 5, engine="warp")
