"""Bitset + pre-filtered search (forward-parity with RAFT's
core/bitset + `search_with_filtering`; the ~23.02 reference snapshot
predates the feature, so the oracle here is a numpy filtered brute
force, mirroring how cpp/test/neighbors/ann_utils.cuh:121 builds naive
ground truth)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from raft_tpu.core.bitset import Bitset, as_bitset, filter_slot_table


def _naive_filtered_knn(data, queries, k, mask):
    """Filtered brute-force oracle: ids where mask holds, -1 tail."""
    d = ((queries[:, None, :] - data[None, :, :]) ** 2).sum(-1)
    d = np.where(mask[None, :], d, np.inf)
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    vals = np.take_along_axis(d, idx, axis=1)
    idx = np.where(np.isfinite(vals), idx, -1)
    return vals, idx


class TestBitset:
    @pytest.mark.parametrize("n", [1, 31, 32, 33, 257, 4096])
    def test_mask_roundtrip_count(self, n):
        rng = np.random.default_rng(n)
        mask = rng.random(n) < 0.3
        b = Bitset.from_mask(mask)
        np.testing.assert_array_equal(np.asarray(b.to_mask()), mask)
        assert int(b.count()) == int(mask.sum())
        assert len(b) == n

    def test_full_and_excluding(self):
        f = Bitset.full(100)
        assert int(f.count()) == 100
        e = Bitset.excluding(100, np.array([3, 3, 99, 200, -1]))
        assert int(e.count()) == 98
        got = np.asarray(e.test(np.array([3, 99, 4, -1, 200])))
        np.testing.assert_array_equal(got, [False, False, True, False, False])

    def test_set_and_flip(self):
        b = Bitset.full(70, value=False).set(np.array([0, 69, 69, 33]))
        assert int(b.count()) == 3
        assert int(b.flip().count()) == 67
        b2 = b.set(np.array([0]), False)
        assert int(b2.count()) == 2

    def test_and_or_length_check(self):
        a = Bitset.from_mask(np.array([1, 0, 1, 0], bool))
        b = Bitset.from_mask(np.array([1, 1, 0, 0], bool))
        np.testing.assert_array_equal(np.asarray((a & b).to_mask()),
                                      [True, False, False, False])
        np.testing.assert_array_equal(np.asarray((a | b).to_mask()),
                                      [True, True, True, False])
        with pytest.raises(ValueError, match="length mismatch"):
            a & Bitset.full(5)

    def test_jit_pytree_arg(self):
        # bit values can change without retracing (bits is a leaf)
        calls = []

        @jax.jit
        def probe(bs, ids):
            calls.append(1)
            return bs.test(ids)

        ids = jnp.arange(4)
        m1 = probe(Bitset.from_mask(np.array([1, 0, 1, 0], bool)), ids)
        m2 = probe(Bitset.from_mask(np.array([0, 1, 0, 1], bool)), ids)
        np.testing.assert_array_equal(np.asarray(m1), [True, False, True, False])
        np.testing.assert_array_equal(np.asarray(m2), [False, True, False, True])
        assert len(calls) == 1  # one trace

    def test_empty_bitset(self):
        # n==0 is reachable (empty bridged index); test/set must not
        # gather from the zero-word bits array
        b = Bitset.full(0)
        got = np.asarray(b.test(np.array([0, -1, 5])))
        np.testing.assert_array_equal(got, [False, False, False])
        assert int(b.set(np.array([0, 3])).count()) == 0
        assert int(b.count()) == 0

    def test_inf_score_survivor_keeps_id(self):
        # rows passing the filter whose true distance overflows to +inf:
        # masked-slot detection is by id re-test, not score, so a
        # returned id is ALWAYS a survivor (never the masked row 0) even
        # though every candidate ties at +inf. Which inf-tied survivors
        # fill the slots is unspecified (a masked row may consume a slot
        # as -1), but a survivor id must never be clobbered when one is
        # selected.
        from raft_tpu.neighbors import brute_force

        data = np.array([[0.0], [1e25], [2e25], [3e25]], np.float32)
        q = np.array([[-3e25]], np.float32)  # d^2 to rows 1-3 overflows
        mask = np.array([False, True, True, True])
        d, i = brute_force.knn(data, q, k=3, prefilter=mask)
        got = np.asarray(i).ravel()
        assert set(got.tolist()) <= {-1, 1, 2, 3}
        assert len(set(got.tolist()) & {1, 2, 3}) >= 2
        assert np.all(np.isinf(np.asarray(d)))

    def test_as_bitset_validation(self):
        with pytest.raises(ValueError, match="covers 4 ids"):
            as_bitset(Bitset.full(4), 5)
        with pytest.raises(ValueError, match="boolean mask"):
            as_bitset(np.array([1.0, 0.0]), 2)
        with pytest.raises(ValueError, match="has 3 entries"):
            as_bitset(np.array([1, 0, 1], bool), 4)

    def test_filter_slot_table(self):
        slot_rows = jnp.array([[0, 2, -1], [1, 3, -1]], jnp.int32)
        source_ids = jnp.array([10, 11, 12, 13], jnp.int32)
        bs = Bitset.excluding(14, np.array([12, 13]))
        out = np.asarray(filter_slot_table(slot_rows, source_ids, bs))
        np.testing.assert_array_equal(out, [[0, -1, -1], [1, -1, -1]])
        # direct-id table (source_ids=None)
        bs2 = Bitset.excluding(4, np.array([0]))
        out2 = np.asarray(filter_slot_table(slot_rows, None, bs2))
        np.testing.assert_array_equal(out2, [[-1, 2, -1], [1, 3, -1]])


class TestFilteredSearch:
    @pytest.fixture(scope="class")
    def blobs(self):
        rng = np.random.default_rng(7)
        centers = rng.uniform(-5, 5, (16, 24)).astype(np.float32)
        assign = rng.integers(0, 16, 3000)
        data = centers[assign] + rng.standard_normal((3000, 24)).astype(np.float32)
        queries = centers[rng.integers(0, 16, 40)] + rng.standard_normal(
            (40, 24)
        ).astype(np.float32)
        mask = rng.random(3000) < 0.5
        return data, queries, mask

    def test_brute_force_exact(self, blobs):
        from raft_tpu.neighbors import brute_force

        data, queries, mask = blobs
        want_v, want_i = _naive_filtered_knn(data, queries, 8, mask)
        d, i = brute_force.knn(data, queries, 8, prefilter=mask)
        np.testing.assert_array_equal(np.asarray(i), want_i)
        np.testing.assert_allclose(np.asarray(d), want_v, rtol=1e-4)
        # Bitset input path agrees with the mask path
        d2, i2 = brute_force.knn(data, queries, 8,
                                 prefilter=Bitset.from_mask(mask))
        np.testing.assert_array_equal(np.asarray(i2), want_i)

    def test_brute_force_tiled_path(self, blobs):
        from raft_tpu.neighbors import brute_force

        data, queries, mask = blobs
        want_v, want_i = _naive_filtered_knn(data, queries, 5, mask)
        # tile smaller than n forces the scan/merge path
        d, i = brute_force._bf_knn_impl(
            jnp.asarray(data), jnp.asarray(queries), 5,
            brute_force.resolve_metric("sqeuclidean"), tile=512,
            prefilter=Bitset.from_mask(mask),
        )
        np.testing.assert_array_equal(np.asarray(i), want_i)

    def test_brute_force_fused_respects_filter(self, blobs):
        from raft_tpu.neighbors import brute_force

        data, queries, mask = blobs
        d, i = brute_force.knn(data, queries, 8, prefilter=mask,
                               engine="pallas")
        got = np.asarray(i)
        bad = got[(got >= 0) & ~mask[np.maximum(got, 0)]]
        assert bad.size == 0, f"filtered ids returned: {bad[:5]}"

    def test_brute_force_filter_everything_but_k(self, blobs):
        from raft_tpu.neighbors import brute_force

        data, queries, _ = blobs
        only = np.zeros(len(data), bool)
        only[:3] = True  # fewer than k survivors
        d, i = brute_force.knn(data, queries, 8, prefilter=only)
        got = np.asarray(i)
        assert set(got[:, :3].ravel()) <= {0, 1, 2}
        np.testing.assert_array_equal(got[:, 3:], -1)
        assert np.all(np.isinf(np.asarray(d)[:, 3:]))

    @pytest.fixture(scope="class")
    def pq_index(self, blobs):
        from raft_tpu.neighbors import ivf_pq

        data, _, _ = blobs
        return ivf_pq.build(
            ivf_pq.IndexParams(n_lists=8, pq_dim=12, kmeans_n_iters=4), data
        )

    @pytest.mark.parametrize(
        "mode,trim", [
            # one engine combo smokes the filter invariant in the quick
            # tier; the full matrix (compile-heavy on 1 core) is slow-tier
            pytest.param("lut", "approx", marks=pytest.mark.slow),
            ("recon8", "approx"),
            pytest.param("recon8_list", "approx", marks=pytest.mark.slow),
            pytest.param("recon8_list", "pallas", marks=pytest.mark.slow),
        ]
    )
    def test_ivf_pq_engines(self, blobs, pq_index, mode, trim):
        from raft_tpu.neighbors import ivf_pq

        data, queries, mask = blobs
        index = pq_index
        p = ivf_pq.SearchParams(n_probes=8, score_mode=mode, trim_engine=trim)
        _, want = _naive_filtered_knn(data, queries, 10, mask)
        d, i = ivf_pq.search(p, index, queries, 10, prefilter=mask)
        got = np.asarray(i)
        # invariant: nothing filtered comes back
        bad = got[(got >= 0) & ~mask[np.maximum(got, 0)]]
        assert bad.size == 0, f"filtered ids returned: {bad[:5]}"
        # recall vs the FILTERED oracle (all lists probed, PQ loss only)
        rec = np.mean([
            len(set(got[j]) & set(want[j][want[j] >= 0])) / max(1, (want[j] >= 0).sum())
            for j in range(len(queries))
        ])
        assert rec >= 0.55, rec

    def test_ivf_pq_unfiltered_unchanged(self, blobs, pq_index):
        from raft_tpu.neighbors import ivf_pq

        data, queries, mask = blobs
        index = pq_index
        p = ivf_pq.SearchParams(n_probes=8)
        d0, i0 = ivf_pq.search(p, index, queries, 10)
        d1, i1 = ivf_pq.search(p, index, queries, 10,
                               prefilter=np.ones(len(data), bool))
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))

    @pytest.mark.parametrize("engine", ["query", "list", "pallas"])
    def test_ivf_flat_engines(self, blobs, engine):
        from raft_tpu.neighbors import ivf_flat

        data, queries, mask = blobs
        index = ivf_flat.build(
            ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4), data
        )
        p = ivf_flat.SearchParams(n_probes=8, engine=engine)
        _, want = _naive_filtered_knn(data, queries, 10, mask)
        d, i = ivf_flat.search(p, index, queries, 10, prefilter=mask)
        got = np.asarray(i)
        bad = got[(got >= 0) & ~mask[np.maximum(got, 0)]]
        assert bad.size == 0, f"filtered ids returned: {bad[:5]}"
        if engine != "pallas":  # exact scan: full-probe recall ~1
            rec = np.mean([
                len(set(got[j]) & set(want[j][want[j] >= 0]))
                / max(1, (want[j] >= 0).sum())
                for j in range(len(queries))
            ])
            assert rec >= 0.99, rec

    @pytest.mark.slow
    def test_custom_extend_ids(self, blobs):
        """extend(new_indices=...) ids live beyond index.size; the filter
        covers index.id_bound and those rows stay reachable."""
        from raft_tpu.neighbors import ivf_flat

        data, queries, _ = blobs
        index = ivf_flat.build(
            ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4,
                                 add_data_on_build=False), data[:2000]
        )
        index = ivf_flat.extend(index, data[:2000])
        index = ivf_flat.extend(
            index, data[2000:], np.arange(50_000, 50_000 + 1000, dtype=np.int32)
        )
        assert index.size == 3000 and index.id_bound == 51_000
        with pytest.raises(ValueError, match="covers 3000 ids"):
            ivf_flat.search(ivf_flat.SearchParams(n_probes=8), index,
                            queries, 5, prefilter=Bitset.full(3000))
        # keep ONLY the custom-id rows: they must come back, not vanish
        keep = Bitset.full(51_000, value=False).set(
            np.arange(50_000, 51_000))
        _, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=8), index,
                               queries, 5, prefilter=keep)
        got = np.asarray(i)
        assert np.all((got >= 50_000) | (got == -1))
        assert np.any(got >= 50_000)

    def test_ivf_flat_filter_to_one_list(self, blobs):
        """Filter keeps only one list's members; every engine must still
        find them through other probes' masking (no cross-list leak)."""
        from raft_tpu.neighbors import ivf_flat

        data, queries, _ = blobs
        index = ivf_flat.build(
            ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4), data
        )
        # keep exactly the members of list 0
        sr = np.asarray(index.slot_rows)
        keep_rows = np.asarray(index.source_ids)[sr[0][sr[0] >= 0]]
        mask = np.zeros(index.size, bool)
        mask[keep_rows] = True
        p = ivf_flat.SearchParams(n_probes=8, engine="query")
        _, i = ivf_flat.search(p, index, queries, 5, prefilter=mask)
        got = np.asarray(i)
        assert set(got[got >= 0].ravel()) <= set(keep_rows.tolist())
