"""linalg tests vs numpy oracles (mirrors cpp/test/linalg/*)."""

import numpy as np
import pytest

from raft_tpu import linalg


def test_gemm_gemv_axpy_dot(rng):
    a = rng.random((8, 5), dtype=np.float32)
    b = rng.random((5, 7), dtype=np.float32)
    np.testing.assert_allclose(np.asarray(linalg.gemm(a, b)), a @ b, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(linalg.gemm(a, b.T, trans_b=True, alpha=2.0)), 2 * (a @ b), rtol=1e-5
    )
    x = rng.random(5, dtype=np.float32)
    np.testing.assert_allclose(np.asarray(linalg.gemv(a, x)), a @ x, rtol=1e-5)
    y = rng.random(8, dtype=np.float32)
    np.testing.assert_allclose(np.asarray(linalg.axpy(3.0, y, y)), 4 * y, rtol=1e-6)
    np.testing.assert_allclose(float(linalg.dot(x, x)), float(x @ x), rtol=1e-5)


def test_eigh(rng):
    a = rng.random((6, 6), dtype=np.float32)
    s = (a + a.T) / 2
    w, v = linalg.eigh(s)
    w, v = np.asarray(w), np.asarray(v)
    np.testing.assert_allclose(s @ v, v * w[None, :], atol=1e-4)
    assert np.all(np.diff(w) >= -1e-6)  # ascending


def test_svd(rng):
    a = rng.random((10, 6), dtype=np.float32)
    u, s, v = linalg.svd(a)
    u, s, v = np.asarray(u), np.asarray(s), np.asarray(v)
    np.testing.assert_allclose(u @ np.diag(s) @ v.T, a, atol=1e-4)


def test_rsvd_approximates(rng):
    # low-rank matrix: rsvd should nail it
    u0 = rng.random((50, 4), dtype=np.float32)
    v0 = rng.random((4, 30), dtype=np.float32)
    a = u0 @ v0
    u, s, v = linalg.rsvd(a, k=4, p=8, n_iter=3)
    approx = np.asarray(u) @ np.diag(np.asarray(s)) @ np.asarray(v).T
    rel = np.linalg.norm(approx - a) / np.linalg.norm(a)
    assert rel < 1e-3


def test_qr(rng):
    a = rng.random((8, 5), dtype=np.float32)
    q, r = linalg.qr(a)
    np.testing.assert_allclose(np.asarray(q) @ np.asarray(r), a, atol=1e-5)
    np.testing.assert_allclose(np.asarray(q).T @ np.asarray(q), np.eye(5), atol=1e-5)


@pytest.mark.parametrize("method", ["svd", "eig"])
def test_lstsq(method, rng):
    a = rng.random((20, 4), dtype=np.float32)
    x_true = rng.random(4, dtype=np.float32)
    b = a @ x_true
    x = np.asarray(linalg.lstsq(a, b, method=method))
    np.testing.assert_allclose(x, x_true, atol=1e-3)


def test_cholesky_r1_update(rng):
    a = rng.random((5, 5), dtype=np.float32)
    A = a @ a.T + 5 * np.eye(5, dtype=np.float32)
    L = np.linalg.cholesky(A)
    x = rng.random(5, dtype=np.float32)
    L2 = np.asarray(linalg.cholesky_r1_update(L, x))
    np.testing.assert_allclose(L2 @ L2.T, A + np.outer(x, x), atol=1e-3)


def test_reductions(rng):
    x = rng.random((6, 9), dtype=np.float32)
    np.testing.assert_allclose(
        np.asarray(linalg.reduce(x, axis=1, main_op=lambda v: v**2)),
        (x**2).sum(1), rtol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(linalg.norm(x, "l2", axis=1)), (x**2).sum(1), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(linalg.norm(x, "l1", axis=0)), np.abs(x).sum(0), rtol=1e-5
    )
    nrm = np.asarray(linalg.normalize(x))
    np.testing.assert_allclose((nrm**2).sum(1), np.ones(6), rtol=1e-5)
    np.testing.assert_allclose(
        float(linalg.mean_squared_error(x, x + 1)), 1.0, rtol=1e-5
    )
    np.testing.assert_allclose(
        float(linalg.map_reduce(lambda a, b: a * b, x, x)), (x * x).sum(), rtol=2e-5
    )


def test_reduce_rows_by_key(rng):
    x = rng.random((10, 4), dtype=np.float32)
    keys = np.array([0, 1, 0, 2, 1, 0, 2, 2, 1, 0])
    out = np.asarray(linalg.reduce_rows_by_key(x, keys, 3))
    for k in range(3):
        np.testing.assert_allclose(out[k], x[keys == k].sum(0), rtol=1e-5)


def test_reduce_cols_by_key(rng):
    x = rng.random((4, 6), dtype=np.float32)
    keys = np.array([0, 1, 0, 1, 2, 2])
    out = np.asarray(linalg.reduce_cols_by_key(x, keys, 3))
    for k in range(3):
        np.testing.assert_allclose(out[:, k], x[:, keys == k].sum(1), rtol=1e-5)


def test_matrix_vector_op(rng):
    m = rng.random((3, 4), dtype=np.float32)
    v = rng.random(4, dtype=np.float32)
    np.testing.assert_allclose(
        np.asarray(linalg.matrix_vector_op(m, v)), m + v[None, :], rtol=1e-6
    )
