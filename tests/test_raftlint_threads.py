"""Threadcheck (raftlint 5.0) suite: fixture snippets for the
``thread-root-unknown`` / ``thread-root-unused`` registry-drift pair
and the ``shared-state-race`` / ``publication-safety`` race rules —
escape analysis through the call graph, the common-lock proof (both
directions), the whole-reference-swap exemption, both
publication-safety patterns, fail-closed registry handling, and the
justified-pragma + baseline workflows — plus real-source checks: the
live THREAD_ROOTS registry must stay in sync with the live spawn
sites, and single-line mutations of copied serve sources must fire
exactly the finding threadcheck exists to catch.

Fixture trees are written under tmp_path mirroring the repo layout
(rules scope on repo-relative paths like ``raft_tpu/...``), with
``repo_root=tmp_path`` so the real repo never leaks into a fixture
run. The registry fixture lives at its real path,
``raft_tpu/core/threads.py``.
"""

import os
import shutil
import textwrap

import pytest

from tools.raftlint import lint_paths
from tools.raftlint.engine import write_baseline
from tools.raftlint.threads import REGISTRY_RELPATH, load_registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

THREAD_RULES = ["thread-root-unknown", "thread-root-unused",
                "shared-state-race", "publication-safety"]


def run_lint(tmp_path, files, rules, whole=False):
    files = dict(files)
    if whole:
        files.setdefault("raft_tpu/__init__.py", "")
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return lint_paths([str(tmp_path)], repo_root=str(tmp_path),
                      baseline=None, rules=rules)


def rules_at(res, relpath=None):
    return [(f.rule, f.line) for f in res.findings
            if relpath is None or f.path == relpath]


# one registered root whose spawn site lives in the server fixture
REG_OK = """
    THREAD_ROOTS = {
        "raft_tpu/serve/eng.py::Server._run": "worker loop",
    }
"""

# the shared skeleton: a worker root spawned in __init__, a caller-root
# public surface, one shared counter
SERVER_TMPL = """
    import threading


    class Server:
        def __init__(self):
            self._lock = threading.Lock()
            self._aux = threading.Lock()
            self.count = 0
            self._t = threading.Thread(target=self._run)

        def start(self):
            self._t.start()

        def _run(self):
            while True:
                {worker_body}

        def poll(self):
            {caller_body}
"""


def server_fixture(worker_body, caller_body):
    return {
        REGISTRY_RELPATH: REG_OK,
        "raft_tpu/serve/eng.py": SERVER_TMPL.format(
            worker_body=worker_body, caller_body=caller_body),
    }


# -- shared-state-race ---------------------------------------------------

def test_unguarded_cross_root_write_fires(tmp_path):
    res = run_lint(tmp_path, server_fixture(
        "self.count += 1", "return self.count"), THREAD_RULES)
    assert rules_at(res) == [("shared-state-race", 17)]
    assert "Server.count" in res.findings[0].message
    assert "Server._run+caller" in res.findings[0].message


def test_common_lock_proof_clean(tmp_path):
    res = run_lint(tmp_path, server_fixture(
        """\
with self._lock:
                    self.count += 1""",
        """\
with self._lock:
                return self.count"""), THREAD_RULES)
    assert res.findings == []


def test_disjoint_locks_are_no_proof(tmp_path):
    # writer under _lock, a second WRITE site under _aux: the write-site
    # lock intersection is empty, so mutual exclusion is unproven
    res = run_lint(tmp_path, server_fixture(
        """\
with self._lock:
                    self.count += 1""",
        """\
with self._aux:
                self.count -= 1"""), THREAD_RULES)
    assert [f.rule for f in res.findings] == ["shared-state-race"]


def test_reference_swap_exempt(tmp_path):
    # whole-reference publication: readers see old-or-new, never torn
    res = run_lint(tmp_path, server_fixture(
        "self.count = object()", "return self.count"), THREAD_RULES)
    assert res.findings == []


def test_escape_analysis_through_helper(tmp_path):
    # the write is two call-graph hops from the root: _run -> _bump
    files = server_fixture("self._bump()", "return self.count")
    files["raft_tpu/serve/eng.py"] += (
        "\n        def _bump(self):\n            self.count += 1\n")
    res = run_lint(tmp_path, files, THREAD_RULES)
    assert [f.rule for f in res.findings] == ["shared-state-race"]
    assert "Server.count" in res.findings[0].message


def test_init_only_state_clean(tmp_path):
    # construction happens-before sharing: __init__ writes are exempt
    res = run_lint(tmp_path, server_fixture(
        "self.count = self.count", "return 1"), THREAD_RULES)
    assert res.findings == []


def test_single_root_state_clean(tmp_path):
    # private helper reached only from the worker root: one root, no race
    files = {
        REGISTRY_RELPATH: REG_OK,
        "raft_tpu/serve/eng.py": """
            import threading


            class Server:
                def __init__(self):
                    self.count = 0
                    self._t = threading.Thread(target=self._run)

                def _run(self):
                    self._bump()

                def _bump(self):
                    self.count += 1
        """,
    }
    res = run_lint(tmp_path, files, THREAD_RULES)
    assert res.findings == []


def test_module_global_race_fires(tmp_path):
    files = {
        REGISTRY_RELPATH: REG_OK,
        "raft_tpu/serve/eng.py": """
            import threading

            _PLANS: list = []


            class Server:
                def __init__(self):
                    self._t = threading.Thread(target=self._run)

                def _run(self):
                    _PLANS.append(1)


            def install(plan):
                _PLANS.append(plan)
        """,
    }
    res = run_lint(tmp_path, files, THREAD_RULES)
    assert [f.rule for f in res.findings] == ["shared-state-race"]
    assert "module global" in res.findings[0].message


# -- publication-safety --------------------------------------------------

def test_field_store_through_shared_ref_fires(tmp_path):
    # pattern (a): mutating the object other roots read through self.cfg
    res = run_lint(tmp_path, server_fixture(
        "x = self.cfg", "self.cfg.limit = 3"), THREAD_RULES)
    assert [f.rule for f in res.findings] == ["publication-safety"]
    assert "Server.cfg" in res.findings[0].message


def test_field_store_under_common_lock_clean(tmp_path):
    res = run_lint(tmp_path, server_fixture(
        """\
with self._lock:
                    x = self.cfg""",
        """\
with self._lock:
                self.cfg.limit = 3"""), THREAD_RULES)
    assert res.findings == []


def test_split_publication_fires(tmp_path):
    # pattern (b): two cross-root-visible fields published by separate
    # swaps — each atomic, the pair observable half-applied
    res = run_lint(tmp_path, server_fixture(
        "x = (self.left, self.right)",
        """\
self.left = object()
            self.right = object()"""), THREAD_RULES)
    assert [f.rule for f in res.findings] == ["publication-safety"]
    assert "2 cross-thread-visible fields" in res.findings[0].message


def test_single_swap_publication_clean(tmp_path):
    # publishing ONE field by one swap is the blessed idiom
    res = run_lint(tmp_path, server_fixture(
        "x = self.left", "self.left = object()"), THREAD_RULES)
    assert res.findings == []


# -- thread-root registry (FAULT_SITES pattern) --------------------------

def test_unregistered_spawn_fires(tmp_path):
    files = server_fixture("pass", "return 1")
    files[REGISTRY_RELPATH] = "THREAD_ROOTS: dict = {}\n"
    res = run_lint(tmp_path, files, THREAD_RULES)
    assert [f.rule for f in res.findings] == ["thread-root-unknown"]
    assert "Server._run" in res.findings[0].message


def test_unresolvable_spawn_fails_closed(tmp_path):
    files = server_fixture("pass", "return 1")
    files["raft_tpu/serve/dyn.py"] = """
        import threading


        def launch(factory):
            threading.Thread(target=factory()).start()
    """
    res = run_lint(tmp_path, files, THREAD_RULES)
    assert rules_at(res, "raft_tpu/serve/dyn.py") == \
        [("thread-root-unknown", 6)]
    assert "unresolvable" in res.findings[0].message


def test_malformed_registry_fails_closed(tmp_path):
    files = server_fixture("pass", "return 1")
    files[REGISTRY_RELPATH] = "THREAD_ROOTS = build()\n"
    res = run_lint(tmp_path, files, THREAD_RULES)
    assert rules_at(res, REGISTRY_RELPATH) == [("thread-root-unknown", 1)]
    assert "dict literal" in res.findings[0].message


def test_callback_registration_is_a_root(tmp_path):
    files = {
        REGISTRY_RELPATH: "THREAD_ROOTS: dict = {}\n",
        "raft_tpu/obs/rec.py": """
            class Recorder:
                def _on_event(self, event):
                    pass

                def install(self, bus):
                    bus.subscribe(self._on_event)
        """,
    }
    res = run_lint(tmp_path, files, THREAD_RULES)
    assert [f.rule for f in res.findings] == ["thread-root-unknown"]
    assert "Recorder._on_event" in res.findings[0].message


def test_stale_registry_entry_fires_on_whole_scan(tmp_path):
    files = server_fixture("pass", "return 1")
    files[REGISTRY_RELPATH] = textwrap.dedent("""
        THREAD_ROOTS = {
            "raft_tpu/serve/eng.py::Server._run": "worker loop",
            "raft_tpu/serve/eng.py::Server._gone": "removed in a refactor",
        }
    """)
    res = run_lint(tmp_path, files, THREAD_RULES, whole=True)
    assert [f.rule for f in res.findings] == ["thread-root-unused"]
    assert "Server._gone" in res.findings[0].message


def test_stale_entry_silent_on_partial_scan(tmp_path):
    # without raft_tpu/__init__.py the scan is partial: a spawn site in
    # an unscanned module could still use the entry — stay silent
    files = server_fixture("pass", "return 1")
    files[REGISTRY_RELPATH] = textwrap.dedent("""
        THREAD_ROOTS = {
            "raft_tpu/serve/eng.py::Server._run": "worker loop",
            "raft_tpu/serve/eng.py::Server._gone": "removed in a refactor",
        }
    """)
    res = run_lint(tmp_path, files, THREAD_RULES, whole=False)
    assert res.findings == []


def test_bench_roots_gated_on_bench_scan(tmp_path):
    # a bench/ key can only be called stale when bench/ files were
    # actually scanned
    files = server_fixture("pass", "return 1")
    files[REGISTRY_RELPATH] = textwrap.dedent("""
        THREAD_ROOTS = {
            "raft_tpu/serve/eng.py::Server._run": "worker loop",
            "bench/bench_x.py::main.client": "load client",
        }
    """)
    res = run_lint(tmp_path, files, THREAD_RULES, whole=True)
    assert res.findings == []
    files["bench/bench_x.py"] = "def main():\n    pass\n"
    res = run_lint(tmp_path, files, THREAD_RULES, whole=True)
    assert [f.rule for f in res.findings] == ["thread-root-unused"]


# -- pragmas and baseline ------------------------------------------------

def test_justified_pragma_suppresses_race(tmp_path):
    res = run_lint(tmp_path, server_fixture(
        "self.count += 1  "
        "# raftlint: disable=shared-state-race  -- fixture-benign",
        "return self.count"), THREAD_RULES)
    assert res.findings == []
    assert res.pragma_suppressed == 1


def test_justified_pragma_suppresses_publication(tmp_path):
    res = run_lint(tmp_path, server_fixture(
        "x = self.cfg",
        "self.cfg.limit = 3  "
        "# raftlint: disable=publication-safety  -- fixture-benign"),
        THREAD_RULES)
    assert res.findings == []
    assert res.pragma_suppressed == 1


def test_baseline_suppresses_threadcheck(tmp_path):
    files = server_fixture("self.count += 1", "return self.count")
    res = run_lint(tmp_path, files, THREAD_RULES)
    assert len(res.findings) == 1
    bl = tmp_path / "bl.json"
    write_baseline(str(bl), res.findings)
    res2 = lint_paths([str(tmp_path)], repo_root=str(tmp_path),
                      baseline=str(bl), rules=THREAD_RULES)
    assert res2.findings == []
    assert res2.baseline_suppressed == 1


# -- real-source checks --------------------------------------------------

def test_real_tree_registry_in_sync():
    """registered <=> discovered on the live tree: the drift test that
    keeps THREAD_ROOTS honest (ISSUE-20 satellite)."""
    res = lint_paths(
        [os.path.join(REPO, "raft_tpu"), os.path.join(REPO, "bench")],
        repo_root=REPO, baseline=None,
        rules=["thread-root-unknown", "thread-root-unused"])
    assert res.findings == [], "\n".join(f.format() for f in res.findings)


def test_real_tree_races_triaged():
    """The full race sweep stays at zero unjustified findings: every
    genuine race is fixed, every benign one carries a justified
    pragma."""
    res = lint_paths(
        [os.path.join(REPO, "raft_tpu"), os.path.join(REPO, "bench")],
        repo_root=REPO, baseline=None,
        rules=["shared-state-race", "publication-safety"])
    assert res.findings == [], "\n".join(f.format() for f in res.findings)


def test_supervisor_root_registered():
    # the run_supervised pump thread is the easy one to forget: it is
    # spawned per supervised stage, not per server
    import ast
    src = open(os.path.join(REPO, REGISTRY_RELPATH)).read()
    mod = type("M", (), {})()
    mod.tree = ast.parse(src)
    mod.path = REGISTRY_RELPATH
    reg = load_registry([mod])
    assert reg is not None
    assert "raft_tpu/jobs/watchdog.py::run_supervised.pump" in reg
    assert "raft_tpu/serve/engine.py::SearchServer._run" in reg
    assert all("::" in k for k in reg)


_THREAD_MUTATIONS = [
    # move the pending-rows accounting out of the condition's lock: the
    # exact single-line slip threadcheck's race rule exists to catch
    ("race-unlocked-counter",
     ["raft_tpu/serve/batcher.py", "raft_tpu/serve/engine.py",
      REGISTRY_RELPATH],
     "raft_tpu/serve/batcher.py",
     "            self._cond.notify_all()\n        return req.reply",
     "            self._cond.notify_all()\n"
     "        self._pending_rows += req.n\n        return req.reply",
     "shared-state-race", "MicroBatcher._pending_rows"),
    # split the zero-dip reference swap into two field stores: the
    # anti-pattern the publication-safety rule machine-checks
    ("publication-split-swap",
     ["raft_tpu/serve/engine.py", REGISTRY_RELPATH],
     "raft_tpu/serve/engine.py",
     "        for batch in batches:\n"
     "            index = mutation.apply_batch(index, batch)\n"
     "        self.index = index\n",
     "        for batch in batches:\n"
     "            index = mutation.apply_batch(index, batch)\n"
     "        self.index.lists = index.lists\n"
     "        self.index.rotated = index.rotated\n",
     "publication-safety", "Searcher.index"),
]


@pytest.mark.parametrize(
    "label,copies,target,old,new,rule_name,needle",
    _THREAD_MUTATIONS, ids=[m[0] for m in _THREAD_MUTATIONS])
def test_mutation_smoke_real_sources(tmp_path, label, copies, target, old,
                                     new, rule_name, needle):
    for rel in copies:
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(REPO, rel), dst)
    clean = lint_paths([str(tmp_path)], repo_root=str(tmp_path),
                       baseline=None,
                       rules=["shared-state-race", "publication-safety"])
    assert clean.findings == [], \
        "unmutated copies must lint clean:\n" + "\n".join(
            f.format() for f in clean.findings)
    src = (tmp_path / target).read_text()
    assert old in src, f"mutation anchor drifted: {old!r}"
    (tmp_path / target).write_text(src.replace(old, new, 1))
    mutated = lint_paths([str(tmp_path)], repo_root=str(tmp_path),
                         baseline=None,
                         rules=["shared-state-race", "publication-safety"])
    assert len(mutated.findings) == 1, \
        f"{label}: expected exactly one finding:\n" + "\n".join(
            f.format() for f in mutated.findings)
    assert mutated.findings[0].rule == rule_name
    assert needle in mutated.findings[0].message
