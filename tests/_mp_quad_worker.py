"""Worker for the 4-process x 2-device tier: layouts the 2-process tier
cannot produce — four distinct uneven partition sizes (one empty), rank
groups that straddle process boundaries (comm_split over a spanning
mesh), the query-sharded merge across processes, and a checkpoint saved
by an 8-rank single-controller session loading onto 8 ranks spread over
4 controllers.

Run: python tests/_mp_quad_worker.py <pid> <nproc> <port> <ckpt> <npz>
"""

import os
import sys

PID = int(sys.argv[1])
NPROC = int(sys.argv[2])
PORT = sys.argv[3]
CKPT = sys.argv[4]
NPZ = sys.argv[5]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from raft_tpu.comms import Comms, bootstrap_multihost, mnmg
from raft_tpu.comms.comms import op_t


def check(name, ok):
    if not ok:
        print(f"FAIL {name}", flush=True)
        sys.exit(1)
    print(f"PASS {name}", flush=True)


def fetch(a):
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(a, tiled=True))


def main():
    bootstrap_multihost(f"127.0.0.1:{PORT}", num_processes=NPROC, process_id=PID)
    check("bootstrap", jax.process_count() == NPROC
          and len(jax.local_devices()) == 2)
    mesh = Mesh(np.array(jax.devices()), ("data",))
    comms = Comms(mesh=mesh)
    R = comms.get_size()
    check("world", R == 2 * NPROC and comms.spans_processes())
    rng = np.random.default_rng(3)

    # --- grouped collectives straddling process boundaries: the 3+5
    # split puts group 0 across procs {0,1} and group 1 across {1,2,3}
    colors = [0, 0, 0, 1, 1, 1, 1, 1]
    xf = rng.standard_normal((R, 6)).astype(np.float32)  # same on every proc

    def grouped(ac, xs):
        sub = ac.comm_split(colors)
        s = sub.allreduce(xs[0], op_t.SUM)
        mn = sub.allreduce(xs[0], op_t.MIN)
        # chunk j of the payload = xs[0] + j: every chunk of the group
        # reduction differs, so the scatter PLACEMENT (group-local rank p
        # owns chunk p) is verified, not just the reduction values
        payload = (xs[0][None, :] + jnp.arange(5.0)[:, None]).reshape(30)
        rs = sub.reducescatter(payload, op_t.SUM)
        return s[None], mn[None], rs[None]

    lr = [2 * PID, 2 * PID + 1]  # this process's global ranks
    xs = comms.shard_from_local(xf[lr], axis=0)
    s, mn, rs = comms.run(
        grouped, xs, in_specs=P("data", None),
        out_specs=(P("data", None), P("data", None), P("data", None)))
    s, mn, rs = fetch(s), fetch(mn), fetch(rs)
    groups = {0: [0, 1, 2], 1: [3, 4, 5, 6, 7]}
    ok = True
    for g in groups.values():
        for pos, r in enumerate(g):
            ok &= np.allclose(s[r], xf[g].sum(0), atol=1e-5)
            ok &= np.array_equal(mn[r], xf[g].min(0))
            # reducescatter over 30 elems, m=5 chunks of 6: group-local
            # rank p owns chunk p; chunk j's group sum = sum(xf) + |g|*j
            want = xf[g].sum(0) + len(g) * pos
            ok &= np.allclose(rs[r], want, atol=1e-5)
    check("grouped_collectives_cross_process", ok)

    # --- four distinct uneven partitions, one empty: layouts a 2-way
    # split cannot express (proc 2 empty, sizes 130/7/0/63)
    sizes = [130, 7, 0, 63]
    cents = rng.uniform(-4, 4, (6, 12)).astype(np.float32)
    full = (cents[rng.integers(0, 6, sum(sizes))]
            + 0.3 * rng.standard_normal((sum(sizes), 12))).astype(np.float32)
    bounds = np.cumsum([0] + sizes)
    local = full[bounds[PID]:bounds[PID + 1]]
    q = full[:16]
    _, kids = mnmg.knn_local(comms, local, q, 5)
    from raft_tpu.neighbors import brute_force

    _, tk = brute_force.knn(full, q, 5, metric="sqeuclidean")
    got_k = fetch(kids)[:16]
    tk = np.asarray(tk)
    rec = np.mean([len(set(got_k[i]) & set(tk[i])) / 5 for i in range(16)])
    check(f"quad_uneven_knn_exact ({rec:.3f})", rec == 1.0)

    # query-sharded merge across 4 processes: same ids as replicated
    _, kids_s = mnmg.knn_local(comms, local, q, 5, query_mode="sharded")
    check("quad_query_sharded_matches",
          np.array_equal(fetch(kids_s)[:16], got_k))

    # distributed k-means over the same uneven partitions
    from raft_tpu.cluster import kmeans as local_kmeans

    centers, inertia, _ = mnmg.kmeans_fit_local(comms, local, 6, max_iter=15,
                                                n_init=2, seed=0)
    _, inertia_single, _ = local_kmeans.fit(full, n_clusters=6, seed=0,
                                            n_init=2)
    check(f"quad_uneven_kmeans ({inertia:.2f} vs {float(inertia_single):.2f})",
          np.isfinite(inertia) and inertia <= float(inertia_single) * 1.5 + 1e-6)

    # IVF-Flat build from the uneven partitions, searched cross-process
    from raft_tpu.neighbors import ivf_flat

    di = mnmg.ivf_flat_build_local(
        comms, ivf_flat.IndexParams(n_lists=6, kmeans_n_iters=5), local)
    _, fids = mnmg.ivf_flat_search(di, q, 5, n_probes=6)
    got_f = fetch(fids)[:16]
    rec_f = np.mean([len(set(got_f[i]) & set(tk[i])) / 5 for i in range(16)])
    check(f"quad_uneven_ivf_flat ({rec_f:.3f})", rec_f > 0.9)

    # sharded checkpoint written BY the 4 controllers (each its own part
    # file), re-loaded on the same spanning mesh, identical results,
    # then grown collectively
    spath = CKPT + ".sharded"
    mnmg.ivf_flat_save_local(spath, di)
    di_re = mnmg.ivf_flat_load(comms, spath)
    _, rids = mnmg.ivf_flat_search(di_re, q, 5, n_probes=6)
    check("quad_sharded_ckpt_roundtrip",
          np.array_equal(fetch(rids)[:16], got_f))
    di_grown = mnmg.ivf_flat_extend_local(di_re, local[:5])
    want_new = sum(min(5, s) for s in sizes)  # proc 2 contributes 0 rows
    check("quad_sharded_ckpt_extend", di_grown.n == sum(sizes) + want_new)

    # --- checkpoint spanning-load: 8 stored rank shards fold onto 8
    # ranks owned by 4 controllers (2 shards per process — the
    # per-process multi-shard layout the 2-way tier can't produce)
    oracle = np.load(NPZ)
    loaded = mnmg.ivf_flat_load(comms, CKPT)
    _, lids = mnmg.ivf_flat_search(loaded, oracle["queries"], 5, n_probes=8)
    got_l = fetch(lids)[:len(oracle["queries"])]
    tl = oracle["truth"]
    rec_l = np.mean([len(set(got_l[i]) & set(tl[i])) / 5
                     for i in range(len(tl))])
    check(f"quad_spanning_checkpoint_load ({rec_l:.3f})", rec_l > 0.95)

    print("WORKER_OK", flush=True)


if __name__ == "__main__":
    main()
