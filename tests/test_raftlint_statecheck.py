"""Statecheck (raftlint 4.0) suite: fixture snippets for the
``cache-key-completeness`` and ``ckpt-schema-registry`` families —
positive, negative, derivation-closure, fail-closed, pragma — plus the
--stats CLI contract. The real-source mutation smoke tests live with
the other families in tests/test_raftlint.py::_MUTATIONS.

Fixture trees are written under tmp_path mirroring the repo layout
(rules scope on repo-relative paths like ``raft_tpu/...``), with
``repo_root=tmp_path`` so the real repo never leaks into a fixture run.
"""

import os
import subprocess
import sys
import textwrap

import pytest

from tools.raftlint import lint_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the memoized-trace plumbing every cache fixture shares (the real
# shapes live in raft_tpu/comms/mnmg_common.py)
WRAPPER_SRC = """
_JIT_WRAPPER_CACHE: dict = {}


def _cached_wrapper(key, build):
    f = _JIT_WRAPPER_CACHE.get(key)
    if f is None:
        f = build()
        _JIT_WRAPPER_CACHE[key] = f
    return f


def wrapper_key(tag, comms, *parts):
    return (tag, comms.mesh, comms.axis) + parts
"""


def run_lint(tmp_path, files, rules, whole=False):
    files = dict(files)
    if whole:
        files.setdefault("raft_tpu/__init__.py", "")
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return lint_paths([str(tmp_path)], repo_root=str(tmp_path),
                      baseline=None, rules=rules)


def rules_at(res, relpath=None):
    return [(f.rule, f.line) for f in res.findings
            if relpath is None or f.path == relpath]


# -- cache-key-completeness ---------------------------------------------

def test_cache_key_missing_closure_input_fires(tmp_path):
    res = run_lint(tmp_path, {
        "raft_tpu/comms/mnmg_common.py": WRAPPER_SRC,
        "raft_tpu/comms/searchy.py": """
            from raft_tpu.comms.mnmg_common import _cached_wrapper, wrapper_key

            def search(comms, mode, k):
                def build():
                    def run(x):
                        if mode == "replicated":
                            return x + k
                        return x
                    return run

                return _cached_wrapper(wrapper_key("s", comms, k), build)
        """}, rules=["cache-key-completeness"])
    assert rules_at(res, "raft_tpu/comms/searchy.py") == [
        ("cache-key-completeness", 12)]
    assert "'mode'" in res.findings[0].message


def test_cache_key_complete_and_derived_names_are_clean(tmp_path):
    # `worst` is not in the key but derives from keyed `metric` through
    # `select_min` — the derivation closure covers it; `impl` resolves
    # through a function-scope import (static)
    res = run_lint(tmp_path, {
        "raft_tpu/comms/mnmg_common.py": WRAPPER_SRC,
        "raft_tpu/comms/searchy.py": """
            from raft_tpu.comms.mnmg_common import _cached_wrapper, wrapper_key

            def search(comms, metric, mode, k):
                from raft_tpu.ops.impls import fancy_impl as impl

                select_min = metric != 3
                worst = float("inf") if select_min else float("-inf")

                def finish(v):
                    return impl(v, worst) if select_min else v

                def build():
                    def run(x):
                        if mode == "replicated":
                            return finish(x + k)
                        return finish(x)
                    return run

                return _cached_wrapper(
                    wrapper_key("s", comms, metric, mode, k), build)
        """}, rules=["cache-key-completeness"])
    assert res.findings == []


def test_cache_key_sibling_helper_reads_propagate(tmp_path):
    # build only calls `finish`, but finish reads `refine` — the input
    # surface crosses the sibling def, exactly like the real `finish`
    res = run_lint(tmp_path, {
        "raft_tpu/comms/mnmg_common.py": WRAPPER_SRC,
        "raft_tpu/comms/searchy.py": """
            from raft_tpu.comms.mnmg_common import _cached_wrapper, wrapper_key

            def search(comms, refine, k):
                def finish(v):
                    return v + 1 if refine else v

                def build():
                    return lambda x: finish(x + k)

                return _cached_wrapper(wrapper_key("s", comms, k), build)
        """}, rules=["cache-key-completeness"])
    assert rules_at(res, "raft_tpu/comms/searchy.py") == [
        ("cache-key-completeness", 11)]
    assert "'refine'" in res.findings[0].message


def test_cache_key_tuned_derivation_is_never_covered(tmp_path):
    # cb derives from a tuned read: process-global but NOT
    # process-stable — omitting it from the key must fire even though
    # its assignment has no non-static free names
    res = run_lint(tmp_path, {
        "raft_tpu/comms/mnmg_common.py": WRAPPER_SRC,
        "raft_tpu/comms/searchy.py": """
            from raft_tpu.comms.mnmg_common import _cached_wrapper, wrapper_key
            from raft_tpu.core import tuned

            def search(comms, k):
                cb = int(tuned.get_choice("chunk", (4, 8), 0))

                def build():
                    return lambda x: x[:cb] + k

                return _cached_wrapper(wrapper_key("s", comms, k), build)
        """}, rules=["cache-key-completeness"])
    assert rules_at(res, "raft_tpu/comms/searchy.py") == [
        ("cache-key-completeness", 11)]
    assert "'cb'" in res.findings[0].message


def test_cache_key_tuned_read_inside_build_fires(tmp_path):
    res = run_lint(tmp_path, {
        "raft_tpu/comms/mnmg_common.py": WRAPPER_SRC,
        "raft_tpu/comms/searchy.py": """
            from raft_tpu.comms.mnmg_common import _cached_wrapper, wrapper_key
            from raft_tpu.core import tuned

            def search(comms, k):
                def build():
                    cb = tuned.get("chunk")
                    return lambda x: x[:cb] + k

                return _cached_wrapper(wrapper_key("s", comms, k), build)
        """}, rules=["cache-key-completeness"])
    assert rules_at(res, "raft_tpu/comms/searchy.py") == [
        ("cache-key-completeness", 7)]
    assert "tuned-registry read inside" in res.findings[0].message


def test_cache_key_fail_closed_on_opaque_key_or_build(tmp_path):
    res = run_lint(tmp_path, {
        "raft_tpu/comms/mnmg_common.py": WRAPPER_SRC,
        "raft_tpu/comms/searchy.py": """
            from raft_tpu.comms.mnmg_common import _cached_wrapper

            def make_key(k):
                return ("s", k)

            def search_opaque_key(comms, k):
                def build():
                    return lambda x: x + k

                return _cached_wrapper(make_key(k), build)

            def search_opaque_build(comms, k, builder):
                return _cached_wrapper(("s", comms.mesh, comms.axis, k),
                                       builder)
        """}, rules=["cache-key-completeness"])
    msgs = [f.message for f in res.findings]
    assert len(msgs) == 2
    assert any("not a tuple literal or wrapper_key" in m for m in msgs)
    assert any("does not resolve to a local def" in m for m in msgs)


def test_cache_key_dict_cache_unkeyed_param_fires(tmp_path):
    res = run_lint(tmp_path, {
        "raft_tpu/comms/masks.py": """
            _ONES_CACHE: dict = {}

            def ones_mask(comms, scale):
                key = (comms.mesh, comms.axis)
                m = _ONES_CACHE.get(key)
                if m is None:
                    m = comms.replicate(scale)
                    _ONES_CACHE[key] = m
                return m
        """}, rules=["cache-key-completeness"])
    assert rules_at(res) == [("cache-key-completeness", 6)]
    assert "'scale'" in res.findings[0].message
    # keyed: clean
    res2 = run_lint(tmp_path, {
        "raft_tpu/comms/masks2.py": """
            _ONES_CACHE: dict = {}

            def ones_mask(comms, scale):
                key = (comms.mesh, comms.axis, scale)
                m = _ONES_CACHE.get(key)
                if m is None:
                    m = comms.replicate(scale)
                    _ONES_CACHE[key] = m
                return m
        """}, rules=["cache-key-completeness"])
    assert rules_at(res2, "raft_tpu/comms/masks2.py") == []


def test_cache_key_probe_key_contract(tmp_path):
    res = run_lint(tmp_path, {
        "raft_tpu/serve/engine.py": """
            class Searcher:
                def search(self, q, k, probe_scale=1.0, recall_target=None):
                    raise NotImplementedError

                def probe_key(self, probe_scale=1.0, recall_target=None):
                    return None


            class ProbedSearcher(Searcher):
                def search(self, q, k, probe_scale=1.0, recall_target=None):
                    n = max(1, int(self.n_probes * probe_scale))
                    return self._go(q, k, n, recall_target)


            class ExactSearcher(Searcher):
                def search(self, q, k, probe_scale=1.0, recall_target=None):
                    return self._go(q, k)


            class KeyedSearcher(Searcher):
                def search(self, q, k, probe_scale=1.0, recall_target=None):
                    return self._go(q, k, probe_scale)

                def probe_key(self, probe_scale=1.0, recall_target=None):
                    return max(1, int(self.n_probes * probe_scale))
        """}, rules=["cache-key-completeness"])
    assert rules_at(res) == [("cache-key-completeness", 11)]
    assert "ProbedSearcher" in res.findings[0].message


def test_cache_key_pragma_and_scope(tmp_path):
    files = {
        "raft_tpu/comms/mnmg_common.py": WRAPPER_SRC,
        "raft_tpu/comms/searchy.py": """
            from raft_tpu.comms.mnmg_common import _cached_wrapper, wrapper_key

            def search(comms, mode, k):
                def build():
                    return lambda x: x + k if mode else x

                return _cached_wrapper(wrapper_key("s", comms, k), build)  # raftlint: disable=cache-key-completeness
        """,
        # identical site OUTSIDE raft_tpu/: out of scope
        "bench/searchy.py": """
            from raft_tpu.comms.mnmg_common import _cached_wrapper, wrapper_key

            def search(comms, mode, k):
                def build():
                    return lambda x: x + k if mode else x

                return _cached_wrapper(wrapper_key("s", comms, k), build)
        """}
    res = run_lint(tmp_path, files, rules=["cache-key-completeness"])
    assert res.findings == []
    assert res.pragma_suppressed == 1


# -- ckpt-schema-registry -----------------------------------------------

MINI_SCHEMA = """
CKPT_SCHEMA = {
    "toy": {
        "version": 2,
        "fields": {
            "centers": ("array", "f32", 1, "refuse"),
            "radii": ("array", "f32", 2, "default"),
            "mirror": ("array", "f32", 1, "derive"),
            "kind": ("meta", "str", 1, "refuse"),
            "version": ("meta", "int", 1, "default"),
            "n_lists": ("meta", "int", 1, "refuse"),
        },
    },
    "mnmg_sharded_part": {
        "version": 1,
        "fields": {
            "store": ("array", "f32", 1, "refuse"),
            "kind": ("meta", "str", 1, "refuse"),
            "ranks": ("meta", "json", 1, "refuse"),
        },
    },
}


def serialize_arrays(f, arrays, meta=None):
    pass


def read_ckpt(f, kind, to_device=True):
    return {}, {}


def check_ckpt_version(meta, path="<container>"):
    pass
"""

CLEAN_TOY = """
    from raft_tpu.core.serialize import read_ckpt, serialize_arrays

    def save(filename, index):
        arrays = {"centers": index.centers}
        if index.radii is not None:
            arrays["radii"] = index.radii
        serialize_arrays(filename, arrays,
                         {"kind": "toy", "version": 2,
                          "n_lists": index.n_lists})

    def load(filename):
        arrays, meta = read_ckpt(filename, "toy")
        index = Index(arrays["centers"], meta["n_lists"])
        index.radii = arrays.get("radii")
        return index
"""


def test_ckpt_clean_roundtrip_and_symmetry(tmp_path):
    res = run_lint(tmp_path, {
        "raft_tpu/core/serialize.py": MINI_SCHEMA,
        "raft_tpu/neighbors/toy.py": CLEAN_TOY,
    }, rules=["ckpt-schema-registry"], whole=True)
    assert res.findings == []


def test_ckpt_unregistered_field_and_unknown_kind_fire(tmp_path):
    res = run_lint(tmp_path, {
        "raft_tpu/core/serialize.py": MINI_SCHEMA,
        "raft_tpu/neighbors/toy.py": """
            from raft_tpu.core.serialize import serialize_arrays

            def save(filename, index):
                serialize_arrays(filename,
                                 {"centers": index.centers,
                                  "magnet": index.magnet},
                                 {"kind": "toy", "version": 2,
                                  "n_lists": 4})

            def save_other(filename, index):
                serialize_arrays(filename, {"centers": index.centers},
                                 {"kind": "mystery", "version": 1})
        """}, rules=["ckpt-schema-registry"])
    msgs = [f.message for f in res.findings]
    assert len(msgs) == 2
    assert any("unregistered toy array field 'magnet'" in m for m in msgs)
    assert any("no such kind" in m for m in msgs)


def test_ckpt_unguarded_optional_read_fires(tmp_path):
    res = run_lint(tmp_path, {
        "raft_tpu/core/serialize.py": MINI_SCHEMA,
        "raft_tpu/neighbors/toy.py": """
            from raft_tpu.core.serialize import read_ckpt

            def load(filename):
                arrays, meta = read_ckpt(filename, "toy")
                index = Index(arrays["centers"], meta["n_lists"])
                index.radii = arrays["radii"]
                return index
        """}, rules=["ckpt-schema-registry"])
    assert [f.rule for f in res.findings] == ["ckpt-schema-registry"]
    assert "UNGUARDED" in res.findings[0].message


def test_ckpt_fallback_off_mainline_fires(tmp_path):
    # one branch constructs and returns the index WITHOUT the fallback:
    # a single-kind load must apply the declared default on every
    # constructing path (the must-reach check)
    res = run_lint(tmp_path, {
        "raft_tpu/core/serialize.py": MINI_SCHEMA,
        "raft_tpu/neighbors/toy.py": """
            from raft_tpu.core.serialize import read_ckpt

            def load(filename):
                arrays, meta = read_ckpt(filename, "toy")
                if meta["n_lists"] == 1:
                    return Index(arrays["centers"], 1)
                index = Index(arrays["centers"], meta["n_lists"])
                index.radii = arrays.get("radii")
                return index
        """}, rules=["ckpt-schema-registry"])
    assert [f.rule for f in res.findings] == ["ckpt-schema-registry"]
    assert "not on the mainline load path" in res.findings[0].message


def test_ckpt_missing_version_gate_fires(tmp_path):
    res = run_lint(tmp_path, {
        "raft_tpu/core/serialize.py": MINI_SCHEMA,
        "raft_tpu/neighbors/toy.py": """
            def load(filename, deserialize):
                arrays, meta = deserialize(filename)
                if meta.get("kind") != "toy":
                    raise ValueError("wrong kind")
                index = Index(arrays["centers"], meta["n_lists"])
                index.radii = arrays.get("radii")
                return index
        """}, rules=["ckpt-schema-registry"])
    assert [f.rule for f in res.findings] == ["ckpt-schema-registry"]
    assert "never reaches the schema gate" in res.findings[0].message


def test_ckpt_symmetry_whole_scan_only(tmp_path):
    # "radii" registered (absent=default) but never written and never
    # read -> two symmetry findings at the registry, on whole scans only
    files = {
        "raft_tpu/core/serialize.py": MINI_SCHEMA,
        "raft_tpu/neighbors/toy.py": """
            from raft_tpu.core.serialize import read_ckpt, serialize_arrays

            def save(filename, index):
                serialize_arrays(filename, {"centers": index.centers},
                                 {"kind": "toy", "version": 2,
                                  "n_lists": index.n_lists})

            def load(filename):
                arrays, meta = read_ckpt(filename, "toy")
                return Index(arrays["centers"], meta["n_lists"])
        """}
    res = run_lint(tmp_path, files, rules=["ckpt-schema-registry"],
                   whole=True)
    msgs = [f.message for f in res.findings]
    assert len(msgs) == 2, msgs
    assert any("never written" in m for m in msgs)
    assert any("never read" in m for m in msgs)
    assert all(f.path == "raft_tpu/core/serialize.py"
               for f in res.findings)
    # partial scan: silent (no basis to call a field dead)
    import shutil

    shutil.rmtree(tmp_path / "raft_tpu")
    res2 = run_lint(tmp_path, files, rules=["ckpt-schema-registry"])
    assert res2.findings == []


def test_ckpt_parameterized_writer_resolves_at_caller(tmp_path):
    # the _save_local_impl pattern: the helper writes param-supplied
    # dicts under `kind + "_part"`; the caller's const kind + dict
    # literal resolve it — an unregistered caller field still fires
    res = run_lint(tmp_path, {
        "raft_tpu/core/serialize.py": MINI_SCHEMA,
        "raft_tpu/comms/ckpt.py": """
            from raft_tpu.core.serialize import serialize_arrays

            def _save_impl(filename, part_arrays, kind):
                serialize_arrays(filename, part_arrays,
                                 {"kind": kind + "_part", "ranks": [0]})

            def save_local(filename, index):
                _save_impl(filename, {"store": index.store}, "mnmg_sharded")

            def save_local_bad(filename, index):
                _save_impl(filename, {"store": index.store,
                                      "bogus": index.bogus}, "mnmg_sharded")
        """}, rules=["ckpt-schema-registry"])
    msgs = [f.message for f in res.findings]
    assert len(msgs) == 1, msgs
    assert "unregistered mnmg_sharded_part array field 'bogus'" in msgs[0]
    assert res.findings[0].path == "raft_tpu/comms/ckpt.py"


def test_ckpt_registry_fails_closed_when_missing(tmp_path):
    res = run_lint(tmp_path, {
        "raft_tpu/core/serialize.py": """
            CKPT_SCHEMA = build_schema()   # not a literal any more

            def serialize_arrays(f, arrays, meta=None):
                pass
        """,
        "raft_tpu/neighbors/toy.py": """
            from raft_tpu.core.serialize import serialize_arrays

            def save(filename, index):
                serialize_arrays(filename, {"centers": index.centers},
                                 {"kind": "toy", "version": 1})
        """}, rules=["ckpt-schema-registry"])
    assert [f.rule for f in res.findings] == ["ckpt-schema-registry"]
    assert "restore the literal dict" in res.findings[0].message


def test_ckpt_pragma_suppresses(tmp_path):
    res = run_lint(tmp_path, {
        "raft_tpu/core/serialize.py": MINI_SCHEMA,
        "raft_tpu/neighbors/toy.py": """
            from raft_tpu.core.serialize import serialize_arrays

            def save(filename, index):
                serialize_arrays(filename,
                                 {"centers": index.centers,
                                  "magnet": index.magnet},  # raftlint: disable=ckpt-schema-registry
                                 {"kind": "toy", "version": 2,
                                  "n_lists": 4})
        """}, rules=["ckpt-schema-registry"])
    assert res.findings == []
    assert res.pragma_suppressed == 1


# -- integrity-digest-registry -------------------------------------------

# the toy schema again, plus the sidecar fields the integrity layer
# appends (list_digests is itself an array field — and exempt)
DIGEST_SCHEMA = MINI_SCHEMA.replace(
    '"radii": ("array", "f32", 2, "default"),',
    '"radii": ("array", "f32", 2, "default"),\n'
    '            "list_digests": ("array", "u32", 2, "default"),\n'
    '            "table_digests": ("meta", "json", 2, "default"),')

DIGEST_OK = """
    DIGEST_FIELDS = {
        "toy": {
            "centers": "table",
            "radii": "list",
            "mirror": "table",
        },
    }
"""


def digest_lint(tmp_path, digest_src, schema=DIGEST_SCHEMA, whole=True):
    return run_lint(tmp_path, {
        "raft_tpu/core/serialize.py": schema,
        "raft_tpu/integrity/digest.py": digest_src,
    }, rules=["integrity-digest-registry"], whole=whole)


def test_digest_registry_clean_and_sidecar_exempt(tmp_path):
    # every toy array field has a row; list_digests (sidecar) needs
    # none; mnmg_sharded_part declares no digest coverage and owes none
    res = digest_lint(tmp_path, DIGEST_OK)
    assert res.findings == []


def test_digest_registry_uncovered_array_field_fires(tmp_path):
    res = digest_lint(tmp_path, """
        DIGEST_FIELDS = {
            "toy": {
                "centers": "table",
                "mirror": "table",
            },
        }
    """)
    assert [f.rule for f in res.findings] == ["integrity-digest-registry"]
    assert "array field 'radii' has no DIGEST_FIELDS row" \
        in res.findings[0].message


def test_digest_registry_dangling_and_meta_rows_fire(tmp_path):
    res = digest_lint(tmp_path, """
        DIGEST_FIELDS = {
            "toy": {
                "centers": "table",
                "radii": "list",
                "mirror": "table",
                "ghost": "list",
                "n_lists": "table",
            },
        }
    """)
    msgs = [f.message for f in res.findings]
    assert len(msgs) == 2, msgs
    assert any("toy.ghost names no registered checkpoint field" in m
               for m in msgs)
    assert any("toy.n_lists names a 'meta' field" in m for m in msgs)


def test_digest_registry_unknown_kind_fires(tmp_path):
    res = digest_lint(tmp_path, """
        DIGEST_FIELDS = {
            "toy": {
                "centers": "table",
                "radii": "list",
                "mirror": "table",
            },
            "mystery": {
                "centers": "table",
            },
        }
    """)
    assert [f.rule for f in res.findings] == ["integrity-digest-registry"]
    assert "CKPT_SCHEMA has no such kind" in res.findings[0].message


def test_digest_registry_fails_closed(tmp_path):
    # a computed registry (or a bogus granularity) is unanalyzable: one
    # finding at the registry, not silence
    for src in ("DIGEST_FIELDS = build_fields()",
                """
                DIGEST_FIELDS = {
                    "toy": {"centers": "whole-table"},
                }
                """):
        res = digest_lint(tmp_path, src)
        assert [f.rule for f in res.findings] == \
            ["integrity-digest-registry"], src
        assert "fail closed" in res.findings[0].message


def test_digest_registry_whole_scan_only(tmp_path):
    # same broken registry, partial scan (no raft_tpu/__init__.py):
    # silent — a subdirectory lint has no basis to judge coverage
    res = digest_lint(tmp_path, "DIGEST_FIELDS = build_fields()",
                      whole=False)
    assert res.findings == []


def test_digest_registry_real_source_mutation_fires(tmp_path):
    """The live-wire check: the REAL serialize.py + digest.py lint
    clean together, and growing the real schema by one array field
    without a digest row fires — the registry pin actually guards the
    real checkpoint surface, not just fixtures."""
    import shutil

    for rel in ("raft_tpu/core/serialize.py",
                "raft_tpu/integrity/digest.py"):
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copyfile(os.path.join(REPO, rel), dst)
    (tmp_path / "raft_tpu/__init__.py").write_text("")
    res = lint_paths([str(tmp_path)], repo_root=str(tmp_path),
                     baseline=None, rules=["integrity-digest-registry"])
    assert res.findings == []
    src = (tmp_path / "raft_tpu/core/serialize.py").read_text()
    anchor = '"list_data": ("array", "f32", 1, "refuse"),'
    assert anchor in src
    (tmp_path / "raft_tpu/core/serialize.py").write_text(src.replace(
        anchor, anchor + '\n            "phantom": ("array", "f32", 4, "default"),'))
    res2 = lint_paths([str(tmp_path)], repo_root=str(tmp_path),
                      baseline=None, rules=["integrity-digest-registry"])
    assert [f.rule for f in res2.findings] == ["integrity-digest-registry"]
    assert "'phantom' has no DIGEST_FIELDS row" in res2.findings[0].message


# -- --stats CLI contract ------------------------------------------------

def _cli(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.raftlint", *args],
        capture_output=True, text=True, cwd=cwd)


def test_cli_stats_on_stderr_json_unchanged(tmp_path):
    tree = tmp_path / "raft_tpu"
    tree.mkdir()
    (tree / "mod.py").write_text("x = 1\n")
    base = ["--root", str(tmp_path), "--no-baseline", str(tree)]
    plain = _cli(["--json", *base])
    stats = _cli(["--json", "--stats", *base])
    assert plain.returncode == 0 and stats.returncode == 0
    # stdout (the archived/banked artifact) is byte-identical with and
    # without --stats; the stats land on stderr, one line per family
    assert stats.stdout == plain.stdout
    lines = [ln for ln in stats.stderr.splitlines()
             if ln.startswith("raftlint: stats: family=")]
    assert lines, stats.stderr
    assert any("family=statecheck rules=3" in ln for ln in lines)
    assert any(ln.startswith("raftlint: stats: total rules wall=")
               for ln in stats.stderr.splitlines())
