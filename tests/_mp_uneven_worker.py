"""Worker for uneven/empty partition distributed tests: the adversarial
layouts where zero-pad rows could displace true neighbors (pads sit at
the origin, nearer a query than any real row) and where one controller
contributes nothing at all.

Run: python tests/_mp_uneven_worker.py <pid> <nproc> <port>
"""

import os
import sys

PID = int(sys.argv[1])
NPROC = int(sys.argv[2])
PORT = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from raft_tpu.comms import Comms, bootstrap_multihost, mnmg
from jax.sharding import Mesh


def check(name, ok):
    if not ok:
        print(f"FAIL {name}", flush=True)
        sys.exit(1)
    print(f"PASS {name}", flush=True)


def main():
    bootstrap_multihost(f"127.0.0.1:{PORT}", num_processes=NPROC, process_id=PID)
    mesh = Mesh(np.array(jax.devices()), ("data",))
    comms = Comms(mesh=mesh)
    rng = np.random.default_rng(2)

    # heavily uneven: proc 0 holds 512 FAR rows, proc 1 only 10 MID rows.
    # Query at the origin: the true top-5 are proc 1's rows, while proc
    # 1's shard is mostly zero pads sitting exactly at the query — an
    # after-selection mask would let the pads displace every real row.
    full = np.concatenate([
        100.0 + rng.random((512, 8)).astype(np.float32),
        10.0 + rng.random((10, 8)).astype(np.float32),
    ])
    local = full[:512] if PID == 0 else full[512:]
    q = np.zeros((1, 8), np.float32)
    _, ids = mnmg.knn_local(comms, local, q, 5)
    got = set(np.asarray(ids.addressable_shards[0].data)[0].tolist())
    check("uneven_knn_pads_masked", got <= set(range(512, 522)) and len(got) == 5)

    # empty partition: proc 1 contributes zero rows; every collective
    # must still run and results must only reference proc 0's rows
    local_e = full[:64] if PID == 0 else full[:0]
    _, ids_e = mnmg.knn_local(comms, local_e, q, 3)
    got_e = np.asarray(ids_e.addressable_shards[0].data)[0]
    check("empty_partition_knn", set(got_e.tolist()) <= set(range(64)))

    # inertia must match a single-process fit on the same 64 rows: if the
    # empty partition's zero pads leaked into the EM, a center would sit
    # at the origin and inertia would diverge from the oracle
    from raft_tpu.cluster import kmeans as local_kmeans

    centers, inertia, _ = mnmg.kmeans_fit_local(
        comms, local_e, 4, max_iter=10, n_init=2
    )
    _, inertia_single, _ = local_kmeans.fit(full[:64], n_clusters=4, seed=0, n_init=2)
    check(
        f"empty_partition_kmeans ({inertia:.2f} vs {float(inertia_single):.2f})",
        np.isfinite(inertia) and inertia <= float(inertia_single) * 1.5 + 1e-6,
    )

    from raft_tpu.neighbors import ivf_flat

    di = mnmg.ivf_flat_build_local(
        comms, ivf_flat.IndexParams(n_lists=4, kmeans_n_iters=4), local_e
    )
    _, fids = mnmg.ivf_flat_search(di, full[:8], 3, n_probes=4)
    got_f = np.asarray(fids.addressable_shards[0].data)
    # min() >= 0 matters: pad slots are stamped gid -1, and a pad leak
    # would otherwise satisfy max() < 64
    check(
        "empty_partition_ivf_flat",
        got_f.shape == (8, 3) and got_f.min() >= 0 and got_f.max() < 64,
    )

    # IVF-PQ build from heavily uneven partitions: the proportional
    # trainset draw and per-process packing must survive a 50:1 skew,
    # and the refined search must stay exact on owned candidates
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.neighbors import brute_force

    big = (10.0 + rng.random((500, 16)).astype(np.float32))
    small = (10.0 + rng.random((10, 16)).astype(np.float32))
    pdata = np.concatenate([big, small])
    plocal = big if PID == 0 else small
    pq_params = ivf_pq.IndexParams(n_lists=4, pq_dim=8, kmeans_n_iters=4)
    dpq = mnmg.ivf_pq_build_local(comms, pq_params, plocal)
    _, pids = mnmg.ivf_pq_search(
        dpq, pdata[:32], 5, n_probes=4, refine_dataset=plocal
    )
    got_p = np.asarray(pids.addressable_shards[0].data)
    _, tp = brute_force.knn(pdata, pdata[:32], 5, metric="sqeuclidean")
    tp = np.asarray(tp)
    rec_p = np.mean([len(set(got_p[i]) & set(tp[i])) / 5 for i in range(32)])
    check(f"uneven_pq_refined ({rec_p:.3f})", rec_p > 0.9)

    print("WORKER_OK", flush=True)


if __name__ == "__main__":
    main()
