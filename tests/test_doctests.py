"""Doctest runner (pylibraft test/test_doctests.py parity): every Examples
block in the public docstrings must execute and match."""

import doctest
import importlib

import pytest

_MODULE_NAMES = [
    "raft_tpu.distance.pairwise",
    "raft_tpu.label",
    "raft_tpu.matrix.select_k",
    "raft_tpu.neighbors.brute_force",
]


@pytest.mark.parametrize("name", _MODULE_NAMES)
def test_doctests(name):
    # importlib (not attribute access): package __init__s rebind some
    # submodule names to same-named functions (matrix.select_k)
    mod = importlib.import_module(name)
    results = doctest.testmod(mod, verbose=False)
    assert results.attempted > 0, f"no doctests collected in {mod.__name__}"
    assert results.failed == 0, f"{results.failed} doctest failures in {mod.__name__}"
