"""Test configuration: force a virtual 8-device CPU platform.

Mirrors the reference's distributed test strategy (survey §4): RAFT tests
multi-node code paths with multiple worker processes on one box
(LocalCUDACluster); we test SPMD/mesh code paths with 8 virtual CPU devices
(`--xla_force_host_platform_device_count=8`), which exercises real XLA
collectives and shardings without TPU hardware. Must run before jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# The image's sitecustomize registers an 'axon' PJRT plugin and force-sets
# jax_platforms; override it back to CPU before any backend initializes.
jax.config.update("jax_platforms", "cpu")

# Quick-tier compile accelerator (ci/test.sh quick sets this): skip most
# XLA optimization passes. On this 1-core box the tier is compile-bound
# (~40% of wall-clock is XLA passes); correctness is unaffected, and the
# full tier still compiles at production optimization levels.
if os.environ.get("RAFT_TPU_TEST_FAST_COMPILE") == "1":
    jax.config.update("jax_disable_most_optimizations", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

assert jax.default_backend() == "cpu", "tests must run on the virtual CPU mesh"
assert len(jax.devices()) == 8, "expected 8 virtual CPU devices"


# Compile-heavy suites (ANN builds dominate; measured with --durations):
# excluded from the `quick` tier (`ci/test.sh quick` == `-m "not slow"`,
# <2 min) so day-to-day iteration isn't throttled by the full ~17 min run.
_SLOW_MODULES = {
    "test_ivf_pq",
    "test_ivf_flat",
    "test_ivf_rabitq",
    "test_mnmg",
    "test_kmeans",
    "test_refine",
    # integration-grade: subprocess bootstraps + many-shape compiles
    "test_multiprocess",
    "test_local_equivalence",
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: compile-heavy suite, excluded from the quick tier"
    )


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.module.__name__ in _SLOW_MODULES:
            item.add_marker(pytest.mark.slow)


@pytest.fixture
def rng():
    return np.random.default_rng(42)
