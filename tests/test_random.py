"""Random-generation tests: statistical-tolerance checks per distribution
(mirrors cpp/test/random/rng.cu's MeanVar-style fixtures, survey §4 —
compute with raft_tpu, compare moments against closed forms), plus exact
structural properties for permute / sample_without_replacement /
multi_variable_gaussian / make_regression."""

import numpy as np
import pytest

from raft_tpu import random as rnd
from raft_tpu.random import RngState

N = 60_000
TOL = 0.05  # moment tolerance at N=60k (same spirit as rng.cu's num_sigma gates)


def moments(x):
    x = np.asarray(x, np.float64)
    return float(x.mean()), float(x.var())


@pytest.mark.parametrize(
    "name,kwargs,mean,var",
    [
        ("uniform", dict(low=-1.0, high=3.0), 1.0, 16.0 / 12.0),
        ("normal", dict(mu=0.5, sigma=2.0), 0.5, 4.0),
        ("lognormal", dict(mu=0.0, sigma=0.5), np.exp(0.125), (np.exp(0.25) - 1) * np.exp(0.25)),
        ("logistic", dict(mu=1.0, scale=0.5), 1.0, (np.pi**2 / 3) * 0.25),
        ("exponential", dict(lambda_=2.0), 0.5, 0.25),
        ("rayleigh", dict(sigma=1.5), 1.5 * np.sqrt(np.pi / 2), (4 - np.pi) / 2 * 2.25),
        ("laplace", dict(mu=-1.0, scale=1.0), -1.0, 2.0),
        ("gumbel", dict(mu=0.0, beta=1.0), np.euler_gamma, np.pi**2 / 6),
    ],
)
def test_distribution_moments(name, kwargs, mean, var):
    x = getattr(rnd, name)(RngState(3), (N,), **kwargs)
    m, v = moments(x)
    scale = max(1.0, abs(mean))
    assert abs(m - mean) < TOL * scale, f"{name} mean {m} vs {mean}"
    assert abs(v - var) < 3 * TOL * max(1.0, var), f"{name} var {v} vs {var}"


def test_bernoulli_and_scaled():
    b = np.asarray(rnd.bernoulli(RngState(1), (N,), prob=0.3))
    assert abs(b.mean() - 0.3) < TOL
    s = np.asarray(rnd.scaled_bernoulli(RngState(2), (N,), prob=0.5, scale=2.0))
    assert set(np.unique(s)) <= {-2.0, 2.0}
    assert abs(s.mean()) < 4 * TOL


def test_uniform_int_bounds_and_discrete():
    u = np.asarray(rnd.uniform_int(RngState(4), (N,), 5, 11))
    assert u.min() >= 5 and u.max() <= 10
    w = np.array([0.1, 0.0, 0.6, 0.3])
    d = np.asarray(rnd.discrete(RngState(5), (N,), w))
    freq = np.bincount(d, minlength=4) / N
    assert freq[1] == 0.0
    np.testing.assert_allclose(freq, w, atol=3 * TOL)


def test_normal_table_columns():
    mu = np.array([0.0, 5.0, -3.0], np.float32)
    sig = np.array([1.0, 0.1, 2.0], np.float32)
    t = np.asarray(rnd.normal_table(RngState(6), 20_000, mu, sig))
    np.testing.assert_allclose(t.mean(axis=0), mu, atol=0.1)
    np.testing.assert_allclose(t.std(axis=0), sig, rtol=0.1)


def test_permute_and_shuffle_rows():
    p = np.asarray(rnd.permute(RngState(7), 1000))
    assert sorted(p.tolist()) == list(range(1000))
    m = np.arange(50, dtype=np.float32).reshape(10, 5)
    shuffled, perm = rnd.shuffle_rows(RngState(8), m)
    np.testing.assert_array_equal(np.asarray(shuffled), m[np.asarray(perm)])


def test_sample_without_replacement_unique():
    s = np.asarray(rnd.sample_without_replacement(RngState(9), 500, 64))
    assert len(set(s.tolist())) == 64
    assert s.min() >= 0 and s.max() < 500
    # k << n routes through the top-k-of-random-keys fast path
    s2 = np.asarray(rnd.sample_without_replacement(RngState(9), 4096, 64))
    assert len(set(s2.tolist())) == 64
    assert s2.min() >= 0 and s2.max() < 4096
    # roughly uniform over the population: mean of a 64-sample from
    # [0, 4096) concentrates near 2048 (checks the low-index tie bias
    # the float32-keys variant would introduce)
    means = [
        float(np.mean(np.asarray(rnd.sample_without_replacement(RngState(t), 4096, 64))))
        for t in range(20)
    ]
    assert abs(np.mean(means) - 2047.5) < 150


def test_multi_variable_gaussian_covariance():
    cov = np.array([[2.0, 0.8], [0.8, 1.0]], np.float32)
    x = np.asarray(
        rnd.multi_variable_gaussian(RngState(10), np.zeros(2, np.float32), cov, 40_000)
    )
    emp = np.cov(x.T)
    np.testing.assert_allclose(emp, cov, atol=0.1)


def test_make_regression_recoverable():
    X, y, coef = rnd.make_regression(2000, 8, n_informative=8, noise=0.0, seed=0)
    X, y, coef = np.asarray(X), np.asarray(y), np.asarray(coef)
    np.testing.assert_allclose(np.squeeze(X @ coef), np.squeeze(y), rtol=1e-3, atol=1e-2)


def test_rng_state_streams_differ_and_reproduce():
    a = np.asarray(rnd.uniform(RngState(11), (64,)))
    b = np.asarray(rnd.uniform(RngState(11), (64,)))
    c = np.asarray(rnd.uniform(RngState(12), (64,)))
    np.testing.assert_array_equal(a, b)  # same seed -> same stream
    assert not np.array_equal(a, c)
    st = RngState(13)
    d = np.asarray(rnd.uniform(st, (64,)))
    e = np.asarray(rnd.uniform(st, (64,)))
    assert not np.array_equal(d, e)  # advancing state -> new draws
