"""Chaos suite: seeded fault-injection plans replayed against the
resilience layer — degraded-mode MNMG search (kill a rank, merge the
survivors, report coverage), health-check barrier + liveness probing,
bootstrap retry, and checkpoint re-hydration. Runs on a 4-rank submesh
of the virtual 8-device CPU mesh; `RAFT_TPU_FAULT_SEED` pins the chaos
seed in CI (ci/test.sh)."""

import os
import threading
import time

import numpy as np
import pytest

from raft_tpu.comms import Comms, mnmg, resilience
from raft_tpu.comms.resilience import DegradedSearchResult, RankHealth
from raft_tpu.core import faults
from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq
from raft_tpu.random import make_blobs

SEED = int(os.environ.get(faults.ENV_SEED, "1234"))
WORLD = 4


@pytest.fixture(scope="module")
def comms4():
    return Comms(n_devices=WORLD)


@pytest.fixture(scope="module")
def blobs():
    data, _ = make_blobs(1600, 16, n_clusters=6, cluster_std=0.4, seed=13)
    return np.asarray(data)


@pytest.fixture(scope="module")
def flat8(comms4, blobs):
    return mnmg.ivf_flat_build(
        comms4, ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4), blobs)


@pytest.fixture(scope="module")
def pq8(comms4, blobs):
    return mnmg.ivf_pq_build(
        comms4, ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=4),
        blobs)


@pytest.fixture(scope="module")
def rb8(comms4, blobs):
    from raft_tpu.neighbors import ivf_rabitq

    return mnmg.ivf_rabitq_build(
        comms4, ivf_rabitq.IndexParams(n_lists=8, kmeans_n_iters=4), blobs)


def _surviving_prefilter(index, dead_rank: int) -> np.ndarray:
    """Boolean keep-mask excluding every row the dead rank's shard owns
    (its slot table holds the global ids)."""
    hg = np.asarray(index.host_gids[dead_rank])
    mask = np.ones(index.n, bool)
    mask[hg[hg >= 0]] = False
    return mask


# -- FaultPlan registry -------------------------------------------------

def test_fault_plan_registry_and_determinism():
    plan = faults.FaultPlan(
        [faults.Fault(kind="kill_rank", rank=1),
         faults.Fault(kind="slow_rank", site="resilience.*", rank=2,
                      latency_s=0.5)],
        seed=SEED,
    )
    assert plan.killed_ranks() == (1,)
    assert plan.matching("resilience.barrier", "slow_rank")[0].rank == 2
    assert plan.matching("mnmg.knn.scores", "slow_rank") == ()
    # fingerprint is stable and replayable
    replay = faults.FaultPlan(plan.faults, seed=SEED)
    assert plan.trace_key() == replay.trace_key()
    assert plan.site_seed("a") == replay.site_seed("a")
    assert plan.site_seed("a") != plan.site_seed("b")
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.Fault(kind="meteor_strike")
    with pytest.raises(ValueError, match="fraction"):
        faults.Fault(kind="corrupt_shard", fraction=1.5)
    # no plan installed -> every hook is inert
    assert faults.active_plan() is None
    assert faults.trace_key() is None
    assert not faults.active_for("comms.allreduce")
    with plan.install():
        assert faults.active_plan() is plan
    assert faults.active_plan() is None


def test_rank_health_mask():
    h = RankHealth.all_healthy(WORLD)
    assert h.coverage() == 1.0 and not h.degraded
    h.mark_unhealthy(3)
    assert h.coverage() == 0.75 and h.degraded
    assert h.healthy_ranks() == (0, 1, 2)
    h.mark_healthy(3)
    assert h.coverage() == 1.0


# -- degraded-mode distributed search ----------------------------------

def test_degraded_ivf_flat_matches_survivor_merge(comms4, blobs, flat8):
    """1 of 4 ranks killed mid-serving: coverage == 0.75 and the merged
    result is EXACTLY the 3-shard reference merge (== prefiltering the
    dead shard's rows on a healthy mesh)."""
    q = blobs[:23]
    plan = faults.FaultPlan(
        [faults.Fault(kind="kill_rank", rank=1)], seed=SEED)
    with plan.install():
        health = resilience.probe_health(comms4, timeout_s=30)
        res = mnmg.ivf_flat_search(flat8, q, 5, n_probes=8, health=health)
    assert isinstance(res, DegradedSearchResult)
    assert res.coverage == 0.75
    rv, ri = mnmg.ivf_flat_search(
        flat8, q, 5, n_probes=8, prefilter=_surviving_prefilter(flat8, 1))
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(ri))
    np.testing.assert_array_equal(np.asarray(res.values), np.asarray(rv))
    # healthy mask returns coverage 1.0 and the undegraded result
    full = mnmg.ivf_flat_search(flat8, q, 5, n_probes=8,
                                health=RankHealth.all_healthy(WORLD))
    assert full.coverage == 1.0
    v0, i0 = mnmg.ivf_flat_search(flat8, q, 5, n_probes=8)
    np.testing.assert_array_equal(np.asarray(full.ids), np.asarray(i0))


def test_degraded_ivf_pq_matches_survivor_merge(comms4, blobs, pq8):
    q = blobs[:23]
    health = RankHealth.all_healthy(WORLD).mark_unhealthy(1)
    res = mnmg.ivf_pq_search(pq8, q, 5, n_probes=8, health=health)
    assert res.coverage == 0.75
    rv, ri = mnmg.ivf_pq_search(
        pq8, q, 5, n_probes=8, prefilter=_surviving_prefilter(pq8, 1))
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(ri))
    np.testing.assert_array_equal(np.asarray(res.values), np.asarray(rv))
    # every surviving id is a real row the dead rank does not own
    dead = set(np.asarray(pq8.host_gids[1]).ravel().tolist()) - {-1}
    assert not (set(np.asarray(res.ids).ravel().tolist()) & dead)


def test_degraded_knn_matches_survivor_merge(comms4, blobs):
    q = blobs[:17]
    health = RankHealth.all_healthy(WORLD).mark_unhealthy(2)
    res = mnmg.knn(comms4, blobs, q, 10, health=health)
    assert res.coverage == 0.75
    # reference: prefilter the dead rank's contiguous row block away
    n = len(blobs)
    per = -(-n // WORLD)
    mask = np.ones(n, bool)
    mask[2 * per: min(3 * per, n)] = False
    rv, ri = mnmg.knn(comms4, blobs, q, 10, prefilter=mask)
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(ri))
    np.testing.assert_array_equal(np.asarray(res.values), np.asarray(rv))


def test_degraded_sharded_request_degrades_to_replicated(comms4, blobs, flat8):
    q = blobs[:32]
    health = RankHealth.all_healthy(WORLD).mark_unhealthy(0)
    with pytest.warns(UserWarning, match="REPLICATED"):
        res = mnmg.ivf_flat_search(flat8, q, 5, n_probes=8,
                                   query_mode="sharded", health=health)
    assert res.coverage == 0.75
    assert np.asarray(res.ids).shape == (32, 5)
    # health sized for the wrong mesh is rejected loudly
    with pytest.raises(ValueError, match="health mask covers"):
        mnmg.ivf_flat_search(flat8, q, 5, n_probes=8,
                             health=RankHealth.all_healthy(8))


def test_corrupt_shard_masked_by_degraded_mode(comms4, blobs, flat8):
    """A poisoned shard (NaN scores) must not leak once the rank is
    masked: kill+corrupt rank 1 and the result still equals the 3-shard
    reference. The same corruption WITHOUT the mask visibly poisons."""
    q = blobs[:23]
    kill_and_corrupt = faults.FaultPlan(
        [faults.Fault(kind="kill_rank", rank=1),
         faults.Fault(kind="corrupt_shard", site="mnmg.ivf_flat.scores",
                      rank=1, fraction=1.0)],
        seed=SEED,
    )
    with kill_and_corrupt.install():
        health = resilience.probe_health(comms4, timeout_s=30)
        res = mnmg.ivf_flat_search(flat8, q, 5, n_probes=8, health=health)
    rv, ri = mnmg.ivf_flat_search(
        flat8, q, 5, n_probes=8, prefilter=_surviving_prefilter(flat8, 1))
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(ri))
    np.testing.assert_array_equal(np.asarray(res.values), np.asarray(rv))
    # unmasked corruption really fires (the drill is not a no-op)
    corrupt_only = faults.FaultPlan(
        [faults.Fault(kind="corrupt_shard", site="mnmg.ivf_flat.scores",
                      rank=1, fraction=1.0)],
        seed=SEED,
    )
    clean_v, _ = mnmg.ivf_flat_search(flat8, q, 5, n_probes=8)
    with corrupt_only.install():
        bad_v, _ = mnmg.ivf_flat_search(flat8, q, 5, n_probes=8)
    assert not np.array_equal(np.asarray(bad_v), np.asarray(clean_v),
                              equal_nan=True)


def test_seeded_fault_replay_is_bit_deterministic(comms4, blobs, flat8):
    """Replaying the same seeded FaultPlan produces bit-identical
    degraded output across two runs (the chaos-drill reproducibility
    contract)."""
    q = blobs[:23]
    def run():
        plan = faults.FaultPlan(
            [faults.Fault(kind="corrupt_shard", site="mnmg.ivf_flat.scores",
                          rank=0, fraction=0.3),
             faults.Fault(kind="kill_rank", rank=3)],
            seed=SEED,
        )
        with plan.install():
            health = resilience.probe_health(comms4, timeout_s=30)
            return mnmg.ivf_flat_search(flat8, q, 5, n_probes=8,
                                        health=health)
    a, b = run(), run()
    assert a.coverage == b.coverage == 0.75
    np.testing.assert_array_equal(np.asarray(a.ids), np.asarray(b.ids))
    np.testing.assert_array_equal(np.asarray(a.values), np.asarray(b.values))


# -- health barrier + probing ------------------------------------------

def test_health_barrier_and_probe(comms4):
    elapsed = resilience.health_barrier(comms4, timeout_s=30)
    assert elapsed < 30
    assert resilience.probe_health(comms4, timeout_s=30).coverage() == 1.0
    # a small injected straggler latency delays but passes
    slow = faults.FaultPlan(
        [faults.Fault(kind="slow_rank", site="resilience.barrier", rank=1,
                      latency_s=0.15)],
        seed=SEED,
    )
    with slow.install():
        elapsed = resilience.health_barrier(comms4, timeout_s=30)
    assert elapsed >= 0.15
    # a straggler declared beyond the deadline is masked WITHOUT
    # sleeping the deadline out
    dead_slow = faults.FaultPlan(
        [faults.Fault(kind="slow_rank", site="resilience.barrier", rank=2,
                      latency_s=9999.0)],
        seed=SEED,
    )
    t0 = time.monotonic()
    with dead_slow.install():
        health = resilience.probe_health(comms4, timeout_s=5)
    assert time.monotonic() - t0 < 5
    assert health.coverage() == 0.75 and not health.mask[2]
    # rank=-1 scopes the straggler to EVERY rank: all masked, no sleep
    all_slow = faults.FaultPlan(
        [faults.Fault(kind="slow_rank", site="resilience.barrier",
                      latency_s=9999.0)],
        seed=SEED,
    )
    t0 = time.monotonic()
    with all_slow.install():
        health = resilience.probe_health(comms4, timeout_s=5)
    assert time.monotonic() - t0 < 5
    assert health.coverage() == 0.0


def test_health_barrier_deadline_covers_injected_latency(comms4):
    """The barrier deadline spans the straggler sleep at the injection
    site: latency past timeout_s raises HealthCheckTimeout instead of
    handing synchronize a fresh budget."""
    slow = faults.FaultPlan(
        [faults.Fault(kind="slow_rank", site="resilience.barrier",
                      latency_s=0.2)],
        seed=SEED,
    )
    with slow.install():
        with pytest.raises(resilience.HealthCheckTimeout):
            resilience.health_barrier(comms4, timeout_s=0.1)
    # plenty of budget: the same injected latency passes
    plan = faults.FaultPlan(
        [faults.Fault(kind="slow_rank", site="resilience.barrier",
                      latency_s=0.05)],
        seed=SEED,
    )
    with plan.install():
        assert resilience.health_barrier(comms4, timeout_s=30) >= 0.05


def test_probe_health_passed_plan_drives_barrier(comms4):
    """A plan passed explicitly (not installed) must drive the barrier's
    injection site exactly like an installed one — sub-deadline
    straggler latency shows up in the probe's wall time."""
    plan = faults.FaultPlan(
        [faults.Fault(kind="slow_rank", site="resilience.barrier", rank=1,
                      latency_s=0.1)],
        seed=SEED,
    )
    t0 = time.monotonic()
    health = resilience.probe_health(comms4, timeout_s=30, plan=plan)
    assert time.monotonic() - t0 >= 0.1
    assert health.coverage() == 1.0  # slow but under deadline: healthy


def test_health_barrier_cancellable(comms4):
    """The barrier wait rides interruptible.synchronize: another thread
    can cancel it (the operator's escape hatch from a hung mesh)."""
    from raft_tpu.core.interruptible import InterruptedException, cancel

    tid = threading.get_ident()
    t = threading.Timer(0.05, cancel, args=(tid,))
    slow = faults.FaultPlan(
        [faults.Fault(kind="slow_rank", site="resilience.barrier",
                      latency_s=0.2)],
        seed=SEED,
    )
    t.start()
    try:
        with slow.install():
            # the injected sleep holds the wait window open long enough
            # for the timer to land before/during synchronize
            resilience.health_barrier(comms4, timeout_s=30)
    except InterruptedException:
        pass  # cancel landed mid-wait — also a pass
    finally:
        t.join()
    # flag fully cleared either way: the next barrier completes
    assert resilience.health_barrier(comms4, timeout_s=30) >= 0


# -- bootstrap retry ----------------------------------------------------

def test_bootstrap_retry_recovers_from_flaky_init(monkeypatch):
    """2 injected flaky-init failures recover without operator
    intervention (the retry-with-backoff acceptance bar)."""
    from raft_tpu.comms import comms as comms_mod

    calls = {"n": 0}

    def fake_initialize(**kwargs):
        calls["n"] += 1

    monkeypatch.setattr(comms_mod.jax.distributed, "initialize",
                        fake_initialize)
    monkeypatch.setattr(comms_mod, "_MULTIHOST_INITIALIZED", False)
    plan = faults.FaultPlan(
        [faults.Fault(kind="flaky_bootstrap", site="comms.bootstrap",
                      count=2)],
        seed=SEED,
    )
    with plan.install():
        assert comms_mod.bootstrap_multihost(backoff_s=0.01) is True
    assert calls["n"] == 1  # two injected failures, then one real init
    f = plan.faults[0]
    assert plan.fire_count("comms.bootstrap", f) == 2
    # idempotent after success
    assert comms_mod.bootstrap_multihost() is False
    monkeypatch.setattr(comms_mod, "_MULTIHOST_INITIALIZED", False)


def test_bootstrap_retry_exhaustion_propagates(monkeypatch):
    from raft_tpu.comms import comms as comms_mod

    monkeypatch.setattr(
        comms_mod.jax.distributed, "initialize",
        lambda **kw: (_ for _ in ()).throw(RuntimeError("unreachable")))
    monkeypatch.setattr(comms_mod, "_MULTIHOST_INITIALIZED", False)
    with pytest.raises(RuntimeError, match="unreachable"):
        comms_mod.bootstrap_multihost(max_retries=1, backoff_s=0.01)
    assert comms_mod._MULTIHOST_INITIALIZED is False


def test_retry_with_backoff_policy():
    attempts = []

    def flaky():
        attempts.append(time.monotonic())
        if len(attempts) < 3:
            raise RuntimeError("transient")
        return "ok"

    assert resilience.retry_with_backoff(flaky, base_delay_s=0.01) == "ok"
    assert len(attempts) == 3
    with pytest.raises(ValueError):
        resilience.retry_with_backoff(
            lambda: (_ for _ in ()).throw(ValueError("genuine")),
            retry_on=(RuntimeError,), base_delay_s=0.01)


def test_retry_backoff_seeded_jitter_and_obs_events():
    """The jitter schedule is SEEDED (same seed + describe -> identical
    delays, so chaos drills replay bit-for-bit) and every retry lands a
    kind="retry" event on the obs bus."""
    from raft_tpu import obs

    def delays_for(seed, describe="op"):
        obs.reset()
        def always_fail():
            raise RuntimeError("transient")
        with pytest.raises(resilience.RetryExhausted):
            resilience.retry_with_backoff(
                always_fail, max_retries=3, base_delay_s=0.001,
                jitter=0.5, seed=seed, describe=describe)
        return [e["delay_s"] for e in obs.bus().events(kind="retry")]

    obs.enable()
    try:
        a = delays_for(11)
        b = delays_for(11)
        c = delays_for(12)
        assert len(a) == 3  # one event per retry attempt
        assert a == b  # seeded: identical schedule
        assert a != c  # a different seed jitters differently
        # jitter in [1, 1.5): every delay at least the base schedule
        assert all(d >= 0.001 * 2 ** i for i, d in enumerate(a))
        ev = obs.bus().events(kind="retry")[-1]
        assert ev["attempt"] == 3 and "transient" in ev["error"]
    finally:
        obs.reset()
        obs.disable()


def test_retry_backoff_max_elapsed_cap():
    """max_elapsed_s bounds the WHOLE retry window: a budget smaller
    than the first backoff sleep gives up immediately instead of
    retrying past it, and the exhaustion error chains the last cause."""
    attempts = []

    def always_fail():
        attempts.append(1)
        raise RuntimeError("still down")

    t0 = time.monotonic()
    with pytest.raises(resilience.RetryExhausted, match="budget spent"):
        resilience.retry_with_backoff(
            always_fail, max_retries=50, base_delay_s=10.0,
            max_elapsed_s=0.05)
    assert time.monotonic() - t0 < 5  # never slept the 10 s backoff
    assert len(attempts) == 1


def test_rehydrate_retry_exhaustion_chains_last_cause(comms4, blobs, flat8,
                                                      tmp_path):
    """Retry exhaustion surfaces as RetryExhausted CHAINING the final
    underlying failure — the last real error is never lost behind the
    retry machinery."""
    path = str(tmp_path / "flat_exhaust.ckpt")
    mnmg.ivf_flat_save(path, flat8)
    plan = faults.FaultPlan(
        [faults.Fault(kind="flaky_bootstrap", site="mnmg_ckpt.load",
                      count=10)],
        seed=SEED,
    )
    with plan.install():
        with pytest.raises(resilience.RetryExhausted,
                           match="rehydrate") as ei:
            resilience.rehydrate(comms4, path, max_retries=2)
    # the cause chain holds the LAST injected failure (attempt 3 of 10)
    assert isinstance(ei.value.__cause__, faults.FaultInjected)
    assert "3/10" in str(ei.value.__cause__)
    f = plan.faults[0]
    assert plan.fire_count("mnmg_ckpt.load", f) == 3  # 1 try + 2 retries


# -- collective + loader + kmeans drills --------------------------------

def test_drop_collective_degrades_kmeans_not_crashes(comms4, blobs):
    plan = faults.FaultPlan(
        [faults.Fault(kind="drop_collective", site="comms.allreduce",
                      rank=3)],
        seed=SEED,
    )
    with plan.install():
        centers, inertia, _ = mnmg.kmeans_fit(comms4, blobs, 6, max_iter=5,
                                              seed=0)
    assert np.isfinite(np.asarray(centers)).all()
    assert np.isfinite(inertia)


def test_batch_loader_chaos():
    from raft_tpu.neighbors.batch_loader import BatchLoadIterator

    host = np.random.default_rng(0).normal(size=(64, 8)).astype(np.float32)
    plan = faults.FaultPlan(
        [faults.Fault(kind="slow_rank", site="batch_loader.load",
                      latency_s=0.02),
         faults.Fault(kind="corrupt_shard", site="batch_loader.load",
                      fraction=0.25)],
        seed=SEED,
    )
    t0 = time.monotonic()
    with plan.install():
        blocks = [np.asarray(b) for b, _ in
                  BatchLoadIterator(host, 16, prefetch=False)]
    assert time.monotonic() - t0 >= 4 * 0.02
    assert any(np.isnan(b).any() for b in blocks)
    # successive equally-shaped blocks draw DIFFERENT corruption masks
    # (periodic corruption would blind drills to offset-dependent bugs)
    masks = [np.isnan(b) for b in blocks if np.isnan(b).any()]
    assert len(masks) >= 2 and not np.array_equal(masks[0], masks[1])
    # ...but a reset plan replays the identical sequence
    plan.reset()
    with plan.install():
        replay = [np.asarray(b) for b, _ in
                  BatchLoadIterator(host, 16, prefetch=False)]
    for a, b in zip(blocks, replay):
        np.testing.assert_array_equal(a, b)
    # rank-scoped host faults miss this controller (process_index 0)
    scoped = faults.FaultPlan(
        [faults.Fault(kind="corrupt_shard", site="batch_loader.load",
                      rank=3, fraction=1.0)],
        seed=SEED,
    )
    with scoped.install():
        missed = [np.asarray(b) for b, _ in BatchLoadIterator(host, 16)]
    assert not any(np.isnan(b).any() for b in missed)
    # without a plan the loader is untouched
    clean = [np.asarray(b) for b, _ in BatchLoadIterator(host, 16)]
    assert not any(np.isnan(b).any() for b in clean)


def test_corrupt_pq_shard_masked_by_degraded_mode(comms4, blobs, pq8):
    """IVF-PQ twin of the flat drill: a poisoned PQ shard (NaN scores at
    site mnmg.ivf_pq.scores) must not leak once the rank is masked."""
    q = blobs[:23]
    kill_and_corrupt = faults.FaultPlan(
        [faults.Fault(kind="kill_rank", rank=1),
         faults.Fault(kind="corrupt_shard", site="mnmg.ivf_pq.scores",
                      rank=1, fraction=1.0)],
        seed=SEED,
    )
    with kill_and_corrupt.install():
        health = resilience.probe_health(comms4, timeout_s=30)
        res = mnmg.ivf_pq_search(pq8, q, 5, n_probes=8, health=health)
    assert res.coverage == 0.75
    rv, ri = mnmg.ivf_pq_search(
        pq8, q, 5, n_probes=8, prefilter=_surviving_prefilter(pq8, 1))
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(ri))
    np.testing.assert_array_equal(np.asarray(res.values), np.asarray(rv))
    # unmasked corruption really fires (the drill is not a no-op)
    corrupt_only = faults.FaultPlan(
        [faults.Fault(kind="corrupt_shard", site="mnmg.ivf_pq.scores",
                      rank=1, fraction=1.0)],
        seed=SEED,
    )
    clean_v, _ = mnmg.ivf_pq_search(pq8, q, 5, n_probes=8)
    with corrupt_only.install():
        bad_v, _ = mnmg.ivf_pq_search(pq8, q, 5, n_probes=8)
    assert not np.array_equal(np.asarray(bad_v), np.asarray(clean_v),
                              equal_nan=True)


def test_corrupt_rabitq_shard_masked_by_degraded_mode(comms4, blobs, rb8):
    """IVF-RaBitQ twin of the PQ drill (site mnmg.ivf_rabitq.scores): a
    poisoned estimator shard must not leak once the rank is masked —
    degraded result == survivor-prefilter reference, bit for bit."""
    q = blobs[:23]
    kill_and_corrupt = faults.FaultPlan(
        [faults.Fault(kind="kill_rank", rank=1),
         faults.Fault(kind="corrupt_shard", site="mnmg.ivf_rabitq.scores",
                      rank=1, fraction=1.0)],
        seed=SEED,
    )
    with kill_and_corrupt.install():
        health = resilience.probe_health(comms4, timeout_s=30)
        res = mnmg.ivf_rabitq_search(rb8, q, 5, n_probes=8, health=health)
    assert res.coverage == 0.75
    rv, ri = mnmg.ivf_rabitq_search(
        rb8, q, 5, n_probes=8, prefilter=_surviving_prefilter(rb8, 1))
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(ri))
    np.testing.assert_array_equal(np.asarray(res.values), np.asarray(rv))
    # unmasked corruption really fires (the drill is not a no-op)
    corrupt_only = faults.FaultPlan(
        [faults.Fault(kind="corrupt_shard", site="mnmg.ivf_rabitq.scores",
                      rank=1, fraction=1.0)],
        seed=SEED,
    )
    clean_v, _ = mnmg.ivf_rabitq_search(rb8, q, 5, n_probes=8)
    with corrupt_only.install():
        bad_v, _ = mnmg.ivf_rabitq_search(rb8, q, 5, n_probes=8)
    assert not np.array_equal(np.asarray(bad_v), np.asarray(clean_v),
                              equal_nan=True)


def test_rabitq_build_encode_chaos(blobs):
    """Host site ivf_rabitq.build.encode: a slow encode pass pays the
    injected latency WITHOUT touching results (host sleeps must never
    change traced math), and a flaky encode raises FaultInjected so
    callers' retry loops see chaos distinctly from real failures."""
    from raft_tpu.neighbors import ivf_rabitq

    params = ivf_rabitq.IndexParams(n_lists=8, kmeans_n_iters=4)
    clean = ivf_rabitq.build(params, blobs, seed=0)
    slow_plan = faults.FaultPlan(
        [faults.Fault(kind="slow_rank", site="ivf_rabitq.build.encode",
                      latency_s=0.05)],
        seed=SEED,
    )
    t0 = time.monotonic()
    with slow_plan.install():
        slowed = ivf_rabitq.build(params, blobs, seed=0)
    assert time.monotonic() - t0 >= 0.05
    np.testing.assert_array_equal(np.asarray(slowed.codes),
                                  np.asarray(clean.codes))
    np.testing.assert_array_equal(np.asarray(slowed.aux),
                                  np.asarray(clean.aux))
    flaky_plan = faults.FaultPlan(
        [faults.Fault(kind="flaky_bootstrap",
                      site="ivf_rabitq.build.encode", count=1)],
        seed=SEED,
    )
    with flaky_plan.install():
        with pytest.raises(faults.FaultInjected):
            ivf_rabitq.build(params, blobs, seed=0)
        # the armed count is spent: the retry (same plan) succeeds
        retry = ivf_rabitq.build(params, blobs, seed=0)
    np.testing.assert_array_equal(np.asarray(retry.codes),
                                  np.asarray(clean.codes))


def test_rabitq_mnmg_encode_site_fires_per_call(comms4, blobs):
    """The distributed build's encode hook is HOST-side: it must fire on
    EVERY call, including ones served entirely by the warm jit-wrapper
    cache (a trace-time hook would silently disarm after the first
    build per cache key)."""
    from raft_tpu.neighbors import ivf_rabitq

    params = ivf_rabitq.IndexParams(n_lists=8, kmeans_n_iters=2)
    mnmg.ivf_rabitq_build(comms4, params, blobs)  # warm every wrapper
    plan = faults.FaultPlan(
        [faults.Fault(kind="flaky_bootstrap",
                      site="ivf_rabitq.build.encode", count=2)],
        seed=SEED,
    )
    with plan.install():
        for _ in range(2):  # both warm-cache calls still inject
            with pytest.raises(faults.FaultInjected):
                mnmg.ivf_rabitq_build(comms4, params, blobs)
        mnmg.ivf_rabitq_build(comms4, params, blobs)  # count spent


def test_corrupt_knn_shard_masked_by_degraded_mode(comms4, blobs):
    """Distributed brute-force twin (site mnmg.knn.scores): poisoned
    shard + mask == survivor-prefilter reference, bit for bit."""
    q = blobs[:17]
    plan = faults.FaultPlan(
        [faults.Fault(kind="kill_rank", rank=2),
         faults.Fault(kind="corrupt_shard", site="mnmg.knn.scores",
                      rank=2, fraction=1.0)],
        seed=SEED,
    )
    with plan.install():
        health = resilience.probe_health(comms4, timeout_s=30)
        res = mnmg.knn(comms4, blobs, q, 10, health=health)
    assert res.coverage == 0.75
    n = len(blobs)
    per = -(-n // WORLD)
    mask = np.ones(n, bool)
    mask[2 * per: min(3 * per, n)] = False
    rv, ri = mnmg.knn(comms4, blobs, q, 10, prefilter=mask)
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(ri))
    np.testing.assert_array_equal(np.asarray(res.values), np.asarray(rv))
    # unmasked corruption visibly poisons (the drill is not a no-op)
    corrupt_only = faults.FaultPlan(
        [faults.Fault(kind="corrupt_shard", site="mnmg.knn.scores",
                      rank=2, fraction=1.0)],
        seed=SEED,
    )
    clean_v, _ = mnmg.knn(comms4, blobs, q, 10)
    with corrupt_only.install():
        bad_v, _ = mnmg.knn(comms4, blobs, q, 10)
    assert not np.array_equal(np.asarray(bad_v), np.asarray(clean_v),
                              equal_nan=True)


def test_corrupt_fused_scan_candidates_drill():
    """Site fused.scan.scores: corrupt_shard NaNs the fused kernel's
    candidate buffer in-trace (ops/fused_scan._maybe_corrupt). The
    fused brute-force engine must visibly poison under the plan — and
    return BIT-IDENTICAL clean results once the plan is cleared, which
    pins the fault_key-retrace contract of the fused jits (a stale
    clean trace under an installed plan, or a stale poisoned trace
    after clearing, both fail here)."""
    rng = np.random.default_rng(SEED)
    data = rng.integers(-8, 8, (1200, 16)).astype(np.float32)
    q = data[:19]
    clean_v, clean_i = brute_force.knn(data, q, 5, engine="pallas")
    plan = faults.FaultPlan(
        [faults.Fault(kind="corrupt_shard", site="fused.scan.scores",
                      fraction=1.0)],
        seed=SEED,
    )
    with plan.install():
        bad_v, _ = brute_force.knn(data, q, 5, engine="pallas")
    assert np.isnan(np.asarray(bad_v)).all()  # fraction=1.0: total rot
    # plan cleared: bit-identical to the pre-drill clean run
    v2, i2 = brute_force.knn(data, q, 5, engine="pallas")
    np.testing.assert_array_equal(np.asarray(v2), np.asarray(clean_v))
    np.testing.assert_array_equal(np.asarray(i2), np.asarray(clean_i))
    # the same site guards the list-scan geometry (IVF-Flat fused
    # engine): corruption must reach it too, through the shared hook
    index = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4), data)
    sp = ivf_flat.SearchParams(n_probes=8, engine="pallas")
    flat_clean_v, _ = ivf_flat.search(sp, index, q, 5)
    with plan.install():
        flat_bad_v, _ = ivf_flat.search(sp, index, q, 5)
    assert np.isnan(np.asarray(flat_bad_v)).all()
    flat_v2, _ = ivf_flat.search(sp, index, q, 5)
    np.testing.assert_array_equal(
        np.asarray(flat_v2), np.asarray(flat_clean_v))


def test_corrupt_probe_budget_drill():
    """Site ivf.probe_budget: corrupt_shard NaNs the traced per-query
    budget vector inside the adaptive plan; the plan clamps corrupted
    entries down to min_probes — SHRUNKEN budgets. The drill proves the
    degradation is visible (fewer lists actually scanned, results drift
    from the clean adaptive run) yet SAFE (full-shape valid results, no
    crash), and that clearing the plan restores bit-identical clean
    results (the fault_key-retrace contract of the plan jit)."""
    from raft_tpu.neighbors import probe_budget

    rng = np.random.default_rng(SEED)
    # OVERLAPPING clusters: true neighbor sets span several lists, so a
    # budget shrunk to 1 probed list visibly loses neighbors
    cent = rng.normal(size=(16, 24)) * 1.5
    data = (cent[rng.integers(0, 16, 3000)]
            + rng.normal(size=(3000, 24))).astype(np.float32)
    q = data[:32]
    index = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=4), data)
    # saturated budgets: every query scans all 8 probes when clean
    sp = ivf_flat.SearchParams(n_probes=8, budget_tau=1.0, early_term=False)
    clean_v, clean_i = ivf_flat.search(sp, index, q, 10)
    plan = faults.FaultPlan(
        [faults.Fault(kind="corrupt_shard", site="ivf.probe_budget",
                      fraction=1.0)],
        seed=SEED,
    )
    with plan.install():
        _, scanned_bad = probe_budget.probe_plan(
            q, index.centers, n_probes=8, min_probes=1, k=10,
            metric=index.metric, tau=1.0)
        bad_v, bad_i = ivf_flat.search(sp, index, q, 10)
    # every budget shrank to the floor: 1 list scanned per query
    assert (np.asarray(scanned_bad) == 1).all()
    # degraded recall is VISIBLE (results drift from clean) yet safe
    assert np.asarray(bad_i).shape == (32, 10)
    assert not np.array_equal(np.asarray(bad_i), np.asarray(clean_i))
    # plan cleared: bit-identical to the pre-drill clean run
    v2, i2 = ivf_flat.search(sp, index, q, 10)
    np.testing.assert_array_equal(np.asarray(v2), np.asarray(clean_v))
    np.testing.assert_array_equal(np.asarray(i2), np.asarray(clean_i))


def test_corrupt_fused_scan_integer_geometries_drill():
    """Site fused.scan.scores on BOTH integer fused geometries
    (ISSUE 11): the int8 PQ-recon list scan and the RaBitQ bit-plane
    scan run the shared `_maybe_corrupt` hook on their candidate
    buffers, so a corrupt_shard plan visibly poisons each — and a
    cleared plan restores BIT-IDENTICAL clean results (the
    fault_key-retrace contract, replayed under the chaos tier's 3-seed
    RAFT_TPU_FAULT_SEED matrix)."""
    from raft_tpu.neighbors import ivf_pq, ivf_rabitq

    rng = np.random.default_rng(SEED)
    data = rng.integers(-8, 8, (2000, 32)).astype(np.float32)
    q = data[:9]
    plan = faults.FaultPlan(
        [faults.Fault(kind="corrupt_shard", site="fused.scan.scores",
                      fraction=1.0)],
        seed=SEED,
    )

    # int8 PQ-recon fused trim
    idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=8, kmeans_n_iters=4, pq_dim=16), data)
    sp = ivf_pq.SearchParams(n_probes=8, trim_engine="fused",
                             score_dtype="int8")
    clean_v, clean_i = ivf_pq.search(sp, idx, q, 5)
    with plan.install():
        bad_v, _ = ivf_pq.search(sp, idx, q, 5)
    assert np.isnan(np.asarray(bad_v)).all()  # fraction=1.0: total rot
    v2, i2 = ivf_pq.search(sp, idx, q, 5)
    np.testing.assert_array_equal(np.asarray(v2), np.asarray(clean_v))
    np.testing.assert_array_equal(np.asarray(i2), np.asarray(clean_i))

    # RaBitQ bit-plane fused scan (no rerank: the estimator scores ARE
    # the output, so the poisoned candidate buffer is directly visible)
    bidx = ivf_rabitq.build(
        ivf_rabitq.IndexParams(n_lists=8, kmeans_n_iters=4,
                               store_dataset=False), data)
    bsp = ivf_rabitq.SearchParams(n_probes=8, scan_engine="fused")
    bclean_v, bclean_i = ivf_rabitq.search(bsp, bidx, q, 5)
    with plan.install():
        bbad_v, _ = ivf_rabitq.search(bsp, bidx, q, 5)
    assert np.isnan(np.asarray(bbad_v)).all()
    bv2, bi2 = ivf_rabitq.search(bsp, bidx, q, 5)
    np.testing.assert_array_equal(np.asarray(bv2), np.asarray(bclean_v))
    np.testing.assert_array_equal(np.asarray(bi2), np.asarray(bclean_i))


def test_drop_allgather_contribution(comms4):
    """drop_collective at comms.allgather: the faulted rank's rows come
    back as the reduction identity (zeros) on EVERY rank — the
    non-deadlocking model of 'this rank's data never arrived'."""
    import jax
    from jax.sharding import PartitionSpec as P

    ac = comms4.comms
    x = np.arange(WORLD * 3, dtype=np.float32).reshape(WORLD, 3) + 1.0

    def run():
        def body(s):
            return ac.allgather(s[0])[None]

        return np.asarray(jax.shard_map(
            body, mesh=comms4.mesh, in_specs=P("data"), out_specs=P("data"),
            check_vma=False,
        )(comms4.shard(x)))

    clean = run()
    np.testing.assert_array_equal(clean[0], x)
    plan = faults.FaultPlan(
        [faults.Fault(kind="drop_collective", site="comms.allgather",
                      rank=2)],
        seed=SEED,
    )
    with plan.install():
        dropped = run()
    assert (dropped[0][2] == 0).all()  # rank 2's rows never arrived
    np.testing.assert_array_equal(dropped[0][[0, 1, 3]], x[[0, 1, 3]])


def test_kmeans_partials_corruption_fires_and_replays(comms4, blobs):
    """corrupt_shard at mnmg.kmeans.partials (a poisoned shard's EM
    contribution BEFORE the allreduce) visibly changes the fit, and a
    replayed plan reproduces it bit-for-bit."""
    clean_c, _, _ = mnmg.kmeans_fit(comms4, blobs, 6, max_iter=5, seed=0)
    plan = faults.FaultPlan(
        [faults.Fault(kind="corrupt_shard", site="mnmg.kmeans.partials",
                      rank=1, fraction=0.5)],
        seed=SEED,
    )
    with plan.install():
        c1, _, _ = mnmg.kmeans_fit(comms4, blobs, 6, max_iter=5, seed=0)
    replay = faults.FaultPlan(plan.faults, seed=SEED)
    with replay.install():
        c2, _, _ = mnmg.kmeans_fit(comms4, blobs, 6, max_iter=5, seed=0)
    assert not np.array_equal(np.asarray(c1), np.asarray(clean_c),
                              equal_nan=True)
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))


def test_kmeans_step_straggler_slows_but_identical(comms4, blobs):
    """slow_rank at the host driver site mnmg.kmeans.step: every
    iteration pays the injected latency, and the math is untouched —
    host sleeps must never change traced results."""
    clean_c, _, clean_it = mnmg.kmeans_fit(comms4, blobs, 6, max_iter=4,
                                           seed=0)
    plan = faults.FaultPlan(
        [faults.Fault(kind="slow_rank", site="mnmg.kmeans.step",
                      latency_s=0.02)],
        seed=SEED,
    )
    t0 = time.monotonic()
    with plan.install():
        c, _, it = mnmg.kmeans_fit(comms4, blobs, 6, max_iter=4, seed=0)
    assert time.monotonic() - t0 >= it * 0.02
    assert it == clean_it
    np.testing.assert_array_equal(np.asarray(c), np.asarray(clean_c))


# -- checkpoint re-hydration --------------------------------------------

def test_rehydrate_restores_full_coverage(comms4, blobs, flat8, tmp_path):
    path = str(tmp_path / "flat.ckpt")
    mnmg.ivf_flat_save(path, flat8)
    q = blobs[:23]
    v0, i0 = mnmg.ivf_flat_search(flat8, q, 5, n_probes=8)
    degraded = mnmg.ivf_flat_search(
        flat8, q, 5, n_probes=8,
        health=RankHealth.all_healthy(WORLD).mark_unhealthy(1))
    assert degraded.coverage == 0.75
    # rehydrate through 2 injected flaky checkpoint reads
    plan = faults.FaultPlan(
        [faults.Fault(kind="flaky_bootstrap", site="mnmg_ckpt.load",
                      count=2)],
        seed=SEED,
    )
    with plan.install():
        fresh, health = resilience.rehydrate(comms4, path)
    assert health.coverage() == 1.0
    res = mnmg.ivf_flat_search(fresh, q, 5, n_probes=8, health=health)
    assert res.coverage == 1.0
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(i0))
    np.testing.assert_allclose(np.asarray(res.values), np.asarray(v0),
                               rtol=1e-6)
    with pytest.raises(ValueError, match="checkpoint"):
        bad = str(tmp_path / "bad.ckpt")
        from raft_tpu.core.serialize import serialize_arrays

        serialize_arrays(bad, {"x": np.zeros(1)}, {"kind": "not_an_index"})
        resilience.rehydrate(comms4, bad)


def test_ivf_pq_save_local_load_chaos_roundtrip(comms4, blobs, tmp_path):
    """IVF-PQ sharded-checkpoint round-trip under a corrupt_shard fault
    plan (the flat path had the only ckpt chaos drill before): the
    seeded "ckpt.corrupt_file" sector rot hits the part files at save;
    the checksum-verified load heals from the mirror slices of a
    replicated index and the loaded search stays bit-identical."""
    pq2 = mnmg.ivf_pq_build(
        comms4, ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=4),
        blobs, replication=2)
    q = blobs[:23]
    v0, i0 = mnmg.ivf_pq_search(pq2, q, 5, n_probes=8)
    path = str(tmp_path / "pq_chaos.ckpt")
    plan = faults.FaultPlan(
        [faults.Fault(kind="corrupt_shard", site="ckpt.corrupt_file",
                      fraction=0.01)],  # a ~1%-of-file bad sector
        seed=SEED,
    )
    from raft_tpu.core.serialize import ChecksumError

    with plan.install():
        mnmg.ivf_pq_save_local(path, pq2)
    try:
        loaded = mnmg.ivf_pq_load(comms4, path)
    except ChecksumError:
        # the seeded sector landed on something unmirrored (quantizer
        # manifest): detection without heal — still never silent
        return
    assert loaded.replicas is not None and loaded.replicas.r == 2
    v1, i1 = mnmg.ivf_pq_search(loaded, q, 5, n_probes=8)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v0))
    # round-trip again through the healed load (save path unpoisoned):
    # a clean save/load of the HEALED index must also be bit-identical
    path2 = str(tmp_path / "pq_clean.ckpt")
    mnmg.ivf_pq_save_local(path2, loaded)
    again = mnmg.ivf_pq_load(comms4, path2)
    v2, i2 = mnmg.ivf_pq_search(again, q, 5, n_probes=8)
    np.testing.assert_array_equal(np.asarray(i2), np.asarray(i0))


# -- quantized-transport chaos drills (comms/quantized fault surface) ---

def test_corrupt_quant_encode_degrades_but_serves(comms4, blobs):
    """Seeded scale-sidecar rot at the quantized encoder on rank 1: the
    candidate exchange visibly degrades (rank 1's candidates decode to
    NaN and fall out of the shortlist) but never crashes, and the exact
    resolve round keeps every reported score finite — quantization
    corruption can cost recall, never correctness of what IS reported."""
    q = blobs[:19]
    cv, ci = mnmg.knn(comms4, blobs, q, 10, quantization="int8")
    plan = faults.FaultPlan(
        [faults.Fault(kind="corrupt_shard", site="comms.quant.encode",
                      rank=1, fraction=1.0)],
        seed=SEED,
    )
    with plan.install():
        bv, bi = mnmg.knn(comms4, blobs, q, 10, quantization="int8")
    assert np.isfinite(np.asarray(bv)).all()
    assert (np.any(np.asarray(bi) != np.asarray(ci))
            or np.any(np.asarray(bv) != np.asarray(cv)))
    # plan uninstalled -> the same call is clean again (trace-key hygiene)
    rv, ri = mnmg.knn(comms4, blobs, q, 10, quantization="int8")
    np.testing.assert_array_equal(np.asarray(ri), np.asarray(ci))
    np.testing.assert_array_equal(np.asarray(rv), np.asarray(cv))


def test_corrupt_quant_decode_degrades_but_serves(comms4, blobs):
    """Decode-side scale rot on rank 0 (the rank whose buffer the host
    reads): the corrupted rank's shortlist diverges, so its merged view
    degrades visibly — but the masked exact-score psums only ever sum
    finite owner contributions, so the served payload stays finite."""
    q = blobs[:19]
    cv, ci = mnmg.knn(comms4, blobs, q, 10, quantization="int8")
    plan = faults.FaultPlan(
        [faults.Fault(kind="corrupt_shard", site="comms.quant.decode",
                      rank=0, fraction=1.0)],
        seed=SEED,
    )
    with plan.install():
        bv, bi = mnmg.knn(comms4, blobs, q, 10, quantization="int8")
    assert np.isfinite(np.asarray(bv)).all()
    assert (np.any(np.asarray(bi) != np.asarray(ci))
            or np.any(np.asarray(bv) != np.asarray(cv)))
