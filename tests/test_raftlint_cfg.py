"""Unit tests for the raftlint 2.0 analysis core: CFG construction
(branch/loop/try-finally/with lowering, back-edges, early exits),
dominance and postdominance, control dependence, bounded emission-
sequence enumeration (tools/raftlint/cfg.py), and the project-wide
symbol table / call graph / interprocedural summaries and rank-taint
engine (tools/raftlint/project.py).

These are white-box tests of the analysis primitives the four new rule
families sit on — the rule-level fixtures live in test_raftlint.py.
Everything here is stdlib-only by construction (the engine under test
may never import raft_tpu).
"""

import ast
import sys
import textwrap

from tools.raftlint.cfg import (
    back_edges,
    build_cfg,
    control_deps,
    dominates,
    dominators,
    emission_sequences,
    guard_blocks,
    postdominators,
)
from tools.raftlint.engine import Module, terminal_name
from tools.raftlint.project import (
    ProjectIndex,
    local_taints,
    taint_reason,
)


def fn_cfg(src, name=None):
    """(cfg, fn node) for the first (or named) def in `src`."""
    tree = ast.parse(textwrap.dedent(src))
    fns = [n for n in ast.walk(tree)
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
           and (name is None or n.name == name)]
    fn = fns[0]
    return build_cfg(fn), fn


def stmt_block(cfg, fn, needle):
    """Block id of the statement whose source segment mentions `needle`
    (via the call/assign name) — anchors assertions on real statements
    instead of block-id arithmetic."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and terminal_name(node.func) == needle:
            b = cfg.block_of(node)
            if b is not None:
                return b
    raise AssertionError(f"no call {needle!r} mapped to a block")


# -- construction ---------------------------------------------------------

def test_cfg_if_else_diamond_dominance():
    cfg, fn = fn_cfg("""
        def f(c):
            pre()
            if c:
                left()
            else:
                right()
            join()
    """)
    pre, left = stmt_block(cfg, fn, "pre"), stmt_block(cfg, fn, "left")
    right, join = stmt_block(cfg, fn, "right"), stmt_block(cfg, fn, "join")
    # the branch header (pre's block) has two successors and dominates
    # everything; neither arm dominates the join, the join postdominates
    # both arms and the header
    assert len(cfg.blocks[pre].succs) == 2
    assert dominates(cfg, pre, left) and dominates(cfg, pre, right)
    assert dominates(cfg, pre, join)
    assert not dominates(cfg, left, join) and not dominates(cfg, right, join)
    pdom = postdominators(cfg)
    assert join in pdom[left] and join in pdom[right] and join in pdom[pre]
    # control dependence: the arms depend on the header, the join doesn't
    cd = control_deps(cfg)
    assert pre in cd[left] and pre in cd[right]
    assert pre not in cd[join]


def test_cfg_if_without_else_join_edge():
    cfg, fn = fn_cfg("""
        def f(c):
            if c:
                then()
            after()
    """)
    then, after = stmt_block(cfg, fn, "then"), stmt_block(cfg, fn, "after")
    header = cfg.blocks[then].preds[0]
    # fallthrough edge header -> join exists, so `then` does not
    # dominate `after` but the header does
    assert not dominates(cfg, then, after)
    assert dominates(cfg, header, after)


def test_cfg_loop_back_edge_and_zero_trip_path():
    cfg, fn = fn_cfg("""
        def f(xs):
            for x in xs:
                body()
            after()
    """)
    body, after = stmt_block(cfg, fn, "body"), stmt_block(cfg, fn, "after")
    header = [b for b in cfg.blocks if body in cfg.blocks[b].succs][0]
    # exactly one back-edge, closing body -> header
    be = back_edges(cfg)
    assert (body, header) in be and len(be) == 1
    # the zero-trip path bypasses the body: body does not dominate after
    assert not dominates(cfg, body, after)
    assert dominates(cfg, header, after)
    # the body is control-dependent on the loop header
    assert header in guard_blocks(cfg, body)


def test_cfg_while_true_without_break_has_no_exit_fallthrough():
    cfg, fn = fn_cfg("""
        def f():
            while True:
                body()
            after()
    """)
    body = stmt_block(cfg, fn, "body")
    header = [b for b in cfg.blocks if body in cfg.blocks[b].succs][0]
    after = stmt_block(cfg, fn, "after")
    assert after not in cfg.blocks[header].succs  # no zero-trip escape


def test_cfg_break_exits_loop():
    cfg, fn = fn_cfg("""
        def f(xs):
            for x in xs:
                if x:
                    break
                body()
            after()
    """)
    after = stmt_block(cfg, fn, "after")
    # some block inside the loop (the break's) jumps straight to after
    body = stmt_block(cfg, fn, "body")
    loop_blocks = {b for b in cfg.blocks if guard_blocks(cfg, b)}
    break_preds = [p for p in cfg.blocks[after].preds if p in loop_blocks]
    assert break_preds, "break edge must land on the loop's after block"
    assert not dominates(cfg, body, after)


def test_cfg_early_return_guards_rest_without_lexical_nesting():
    cfg, fn = fn_cfg("""
        def f(c):
            if c:
                return None
            tail()
    """)
    tail = stmt_block(cfg, fn, "tail")
    guards = guard_blocks(cfg, tail)
    # the branch block (the if header) decides whether tail runs, even
    # though tail is not indented under it — the property the divergence
    # rule needs for `if rank != 0: return` shapes
    assert len(guards) == 1
    header = next(iter(guards))
    assert cfg.blocks[header].test is fn.body[0].test


def test_cfg_finally_on_normal_and_exceptional_paths():
    cfg, fn = fn_cfg("""
        def f():
            try:
                risky()
            finally:
                cleanup()
            after()
    """)
    risky, cleanup = stmt_block(cfg, fn, "risky"), stmt_block(cfg, fn, "cleanup")
    after = stmt_block(cfg, fn, "after")
    pdom = postdominators(cfg)
    # the finally postdominates the try body: every path out of risky()
    # runs cleanup()
    assert cleanup in pdom[risky]
    # but after() does NOT postdominate risky: the exceptional path exits
    # through the finally without reaching it
    assert after not in pdom[risky]
    assert cfg.exit in [s for s in cfg.blocks[cleanup].succs] or any(
        cfg.exit in cfg.blocks[s].succs for s in cfg.blocks[cleanup].succs)


def test_cfg_return_in_try_routes_through_finally():
    cfg, fn = fn_cfg("""
        def f(c):
            try:
                if c:
                    return early()
                late()
            finally:
                cleanup()
    """)
    early, cleanup = stmt_block(cfg, fn, "early"), stmt_block(cfg, fn, "cleanup")
    # the return must not bypass the finally
    assert cleanup in postdominators(cfg)[early]
    assert cfg.exit not in cfg.blocks[early].succs


def test_cfg_except_handler_reachable_from_body():
    cfg, fn = fn_cfg("""
        def f():
            try:
                one()
                two()
            except ValueError:
                handler()
            after()
    """)
    one, two = stmt_block(cfg, fn, "one"), stmt_block(cfg, fn, "two")
    handler, after = stmt_block(cfg, fn, "handler"), stmt_block(cfg, fn, "after")
    # straight-line try-body statements share a block; that block has an
    # exceptional edge into the handler
    assert one == two
    assert handler in cfg.blocks[one].succs
    # the handler is on only one of two paths: it neither dominates nor
    # postdominates the join, while the body block dominates it
    assert not dominates(cfg, handler, after)
    assert after in postdominators(cfg)[handler]
    assert dominates(cfg, one, after)
    assert handler not in postdominators(cfg)[one]


def test_cfg_with_enter_may_raise_body_not_postdominating():
    cfg, fn = fn_cfg("""
        def f(lock):
            with lock:
                body()
            after()
    """)
    body, after = stmt_block(cfg, fn, "body"), stmt_block(cfg, fn, "after")
    entry_block = cfg.blocks[body].preds[0]
    # the with-entry block has an exceptional __enter__-failure edge that
    # bypasses the body entirely
    assert cfg.exit in cfg.blocks[entry_block].succs
    assert body not in postdominators(cfg)[entry_block]
    assert after not in postdominators(cfg)[entry_block]


def test_cfg_lambda_single_block():
    tree = ast.parse("f = lambda x: g(x)")
    lam = next(n for n in ast.walk(tree) if isinstance(n, ast.Lambda))
    cfg = build_cfg(lam)
    b = cfg.block_of(lam.body)
    assert b is not None and cfg.exit in cfg.blocks[b].succs


def test_cfg_memoized_per_node():
    tree = ast.parse("def f():\n    pass\n")
    fn = tree.body[0]
    assert build_cfg(fn) is build_cfg(fn)


def test_cfg_deep_nesting_does_not_blow_recursion():
    # back_edges() DFS is recursive with a raised limit — a deep chain
    # of ifs must not crash (regression guard for pathological files)
    n = 200
    src = "def f(c):\n" + "".join(
        f"{'    ' * (1)}if c:\n{'    ' * (1)}    x{i} = {i}\n"
        for i in range(n)) + "    tail()\n"
    cfg, fn = fn_cfg(src)
    assert back_edges(cfg) == set()
    assert len(cfg.blocks) > n


# -- emission sequences ---------------------------------------------------

def _token_emit(cfg, fn):
    tokens = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            b = cfg.block_of(node)
            if b is not None:
                tokens.setdefault(b, []).append(
                    ((node.lineno, node.col_offset), terminal_name(node.func)))
    return lambda blk: tuple(
        t for _pos, t in sorted(tokens.get(blk.id, ())))


def test_emission_sequences_enumerate_branch_orders():
    cfg, fn = fn_cfg("""
        def f(c):
            if c:
                a()
                b()
            else:
                b()
                a()
    """)
    seqs = emission_sequences(cfg, cfg.entry, _token_emit(cfg, fn))
    assert seqs == frozenset({("a", "b"), ("b", "a")})


def test_emission_sequences_loop_counts_once_and_zero():
    cfg, fn = fn_cfg("""
        def f(xs):
            for x in xs:
                a()
            tail()
    """)
    seqs = emission_sequences(cfg, cfg.entry, _token_emit(cfg, fn))
    # back-edge cut: the one-iteration path ends at the cut edge (the
    # body's emission is represented once), the zero-trip path falls
    # through the header to the tail
    assert seqs == frozenset({("a",), ("tail",)})


def test_emission_sequences_cap_returns_none():
    # 2^8 distinct sequences from 8 independent emitting branches
    src = "def f(c):\n" + "".join(
        f"    if c[{i}]:\n        a{i}()\n    else:\n        b{i}()\n"
        for i in range(8))
    cfg, fn = fn_cfg(src)
    assert emission_sequences(cfg, cfg.entry, _token_emit(cfg, fn),
                              cap=64) is None


# -- project index: resolution and summaries ------------------------------

def mk_modules(files):
    mods = []
    for path, src in sorted(files.items()):
        text = textwrap.dedent(src)
        mods.append(Module(path, ast.parse(text), text.splitlines(), text))
    return mods


def test_resolve_call_same_module_import_and_self():
    idx = ProjectIndex(mk_modules({
        "raft_tpu/comms/a.py": """
            from raft_tpu.comms.b import helper
            from raft_tpu.comms import b

            def local():
                pass

            def caller():
                local()
                helper()
                b.helper()

            class C:
                def m(self):
                    self.n()

                def n(self):
                    pass
        """,
        "raft_tpu/comms/b.py": """
            def helper():
                pass
        """,
    }))

    def calls_in(qname):
        fn = idx.functions[qname].node
        return [n for n in ast.walk(fn) if isinstance(n, ast.Call)]

    caller = calls_in("raft_tpu/comms/a.py::caller")
    resolved = [idx.resolve_call("raft_tpu/comms/a.py", c.func)
                for c in caller]
    assert resolved == [["raft_tpu/comms/a.py::local"],
                        ["raft_tpu/comms/b.py::helper"],
                        ["raft_tpu/comms/b.py::helper"]]
    (self_call,) = calls_in("raft_tpu/comms/a.py::C.m")
    assert idx.resolve_call("raft_tpu/comms/a.py", self_call.func,
                            cls="raft_tpu/comms/a.py::C") == [
        "raft_tpu/comms/a.py::C.n"]


def test_summary_transitive_collectives_through_two_calls():
    idx = ProjectIndex(mk_modules({
        "raft_tpu/comms/deep.py": """
            def leaf(comms):
                comms.allreduce(1)

            def mid(comms):
                leaf(comms)

            def top(comms):
                mid(comms)

            def clean(x):
                return x + 1
        """,
    }))
    s = idx.summaries
    assert s["raft_tpu/comms/deep.py::leaf"].collectives
    assert s["raft_tpu/comms/deep.py::mid"].collectives
    assert s["raft_tpu/comms/deep.py::top"].collectives
    assert not s["raft_tpu/comms/deep.py::clean"].collectives


def test_summary_collective_method_receiver_guard():
    # functools.reduce / np.* must not count as AxisComms ops
    idx = ProjectIndex(mk_modules({
        "raft_tpu/core/m.py": """
            import functools
            import numpy as np

            def not_comms(xs):
                functools.reduce(lambda a, b: a + b, xs)
                np.gather(xs, 0)

            def is_comms(comms):
                comms.reduce(1)
        """,
    }))
    assert not idx.summaries["raft_tpu/core/m.py::not_comms"].collectives
    assert idx.summaries["raft_tpu/core/m.py::is_comms"].collectives


def test_summary_rank_source_is_return_value_not_internal_use():
    idx = ProjectIndex(mk_modules({
        "raft_tpu/comms/r.py": """
            import jax

            def my_rank():
                return jax.process_index()

            def uses_rank_internally(x):
                r = jax.process_index()
                log(r)
                return x

            def wraps(offset):
                return my_rank() + offset
        """,
    }))
    s = idx.summaries
    assert s["raft_tpu/comms/r.py::my_rank"].rank_source
    assert not s["raft_tpu/comms/r.py::uses_rank_internally"].rank_source
    # rank-sourceness propagates through RETURN-site callees in the
    # fixpoint: a wrapper of a wrapper is itself a source (calling
    # get_rank internally, above, still is not)
    assert s["raft_tpu/comms/r.py::wraps"].rank_source is True
    call = ast.parse("my_rank() == 0", mode="eval").body
    assert taint_reason(call, {}, idx, "raft_tpu/comms/r.py") == "rank"


def test_summary_lock_acquires_cross_class():
    idx = ProjectIndex(mk_modules({
        "raft_tpu/serve/l.py": """
            import threading

            class A:
                def __init__(self):
                    self._la = threading.Lock()

                def grab(self):
                    with self._la:
                        pass
        """,
    }))
    s = idx.summaries["raft_tpu/serve/l.py::A.grab"]
    assert s.acquires == frozenset({("raft_tpu/serve/l.py::A", "_la")})


# -- taint ----------------------------------------------------------------

def _taint_fixture(body):
    files = {"raft_tpu/comms/t.py": f"""
        import jax
        import os

        def get_rank():
            return jax.process_index()

        def f(health, rank, plain):
        {body}
    """}
    mods = mk_modules(files)
    idx = ProjectIndex(mods)
    fn = idx.functions["raft_tpu/comms/t.py::f"].node
    return fn, idx


def test_taint_param_seeds_and_assignment_flow():
    fn, idx = _taint_fixture("""
            r = rank + 1
            h = health.coverage
            p = plain * 2
            return r, h, p
    """)
    t = local_taints(fn, idx, "raft_tpu/comms/t.py")
    assert t["rank"] == "rank" and t["r"] == "rank"
    assert t["health"] == "health" and t["h"] == "health"
    assert "p" not in t and "plain" not in t


def test_taint_reasons_rank_health_filesystem():
    fn, idx = _taint_fixture("""
            return 0
    """)
    t = local_taints(fn, idx, "raft_tpu/comms/t.py")
    path = "raft_tpu/comms/t.py"

    def reason(src):
        return taint_reason(ast.parse(src, mode="eval").body, t, idx, path)

    assert reason("get_rank() == 0") == "rank"
    assert reason("jax.process_index() != 0") == "rank"
    assert reason("health.degraded") == "health"
    assert reason("os.path.exists(p)") == "filesystem"
    assert reason("n_probes > 4") is None


def test_taint_calls_are_opaque_but_transparent_transforms_flow():
    fn, idx = _taint_fixture("""
            return 0
    """)
    t = {"rank": "rank"}
    path = "raft_tpu/comms/t.py"

    def reason(src):
        return taint_reason(ast.parse(src, mode="eval").body, t, idx, path)

    # laundering through an opaque call clears taint (documented bound)
    assert reason("launder(rank)") is None
    # transparent value transforms keep it
    assert reason("int(rank)") == "rank"
    assert reason("bool(min(rank, 3))") == "rank"
    # receiver chains stay inspected
    assert reason("rank.bit_length()") == "rank"


def test_taint_loop_target_flows():
    fn, idx = _taint_fixture("""
            for i in range(rank):
                use(i)
            return 0
    """)
    t = local_taints(fn, idx, "raft_tpu/comms/t.py")
    assert t.get("i") == "rank"


if __name__ == "__main__":
    sys.exit(__import__("pytest").main([__file__, "-q"]))
