"""Pallas kernels vs the XLA reference paths (interpret mode on CPU).

Mirrors how cpp/test/distance/*.cu validate the tiled kernel against naive
implementations; here the oracle is the jnp path already validated against
numpy/scipy in test_distance.py.
"""

import numpy as np
import pytest

from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.distance.pairwise import _pairwise_impl
from raft_tpu.ops.fused_l2_argmin import fused_l2_argmin_pallas
from raft_tpu.ops.pairwise_pallas import METRIC_OPS, pairwise_tiled

_METRIC_TO_ENUM = {
    "l1": DistanceType.L1,
    "linf": DistanceType.Linf,
    "l2_unexpanded": DistanceType.L2Unexpanded,
    "l2_sqrt_unexpanded": DistanceType.L2SqrtUnexpanded,
    "canberra": DistanceType.Canberra,
    "kl_divergence": DistanceType.KLDivergence,
    "hamming": DistanceType.HammingUnexpanded,
}


@pytest.mark.parametrize("metric", sorted(METRIC_OPS))
def test_pairwise_tiled_matches_xla(metric, rng):
    m, n, k = 33, 47, 10  # deliberately unaligned -> exercises padding
    if metric in ("kl_divergence",):
        x = rng.random((m, k)).astype(np.float32) + 0.01
        y = rng.random((n, k)).astype(np.float32) + 0.01
        x /= x.sum(axis=1, keepdims=True)
        y /= y.sum(axis=1, keepdims=True)
    elif metric == "hamming":
        x = rng.integers(0, 3, (m, k)).astype(np.float32)
        y = rng.integers(0, 3, (n, k)).astype(np.float32)
    else:
        x = rng.standard_normal((m, k)).astype(np.float32)
        y = rng.standard_normal((n, k)).astype(np.float32)

    got = np.asarray(pairwise_tiled(x, y, metric, bm=16, bn=128, interpret=True))
    want = np.asarray(_pairwise_impl(x, y, _METRIC_TO_ENUM[metric]))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_fused_l2_argmin_matches_oracle(rng):
    m, n, k = 70, 300, 12  # n not a multiple of bn -> padded cols masked
    x = rng.standard_normal((m, k)).astype(np.float32)
    y = rng.standard_normal((n, k)).astype(np.float32)
    dist, idx = fused_l2_argmin_pallas(x, y, bm=16, bn=128, interpret=True)
    full = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    np.testing.assert_array_equal(np.asarray(idx), full.argmin(axis=1))
    np.testing.assert_allclose(np.asarray(dist), full.min(axis=1), rtol=1e-4, atol=1e-4)


def test_fused_l2_argmin_sqrt(rng):
    x = rng.standard_normal((20, 8)).astype(np.float32)
    y = rng.standard_normal((50, 8)).astype(np.float32)
    dist, idx = fused_l2_argmin_pallas(x, y, bm=16, bn=128, sqrt=True, interpret=True)
    full = np.sqrt(((x[:, None, :] - y[None, :, :]) ** 2).sum(-1))
    np.testing.assert_allclose(np.asarray(dist), full.min(axis=1), rtol=1e-4, atol=1e-4)


def test_fused_l2_argmin_tie_break_lowest_index(rng):
    # Duplicate rows of y in different lanes: lowest column index must win,
    # matching jnp.argmin semantics (the XLA path).
    y = rng.standard_normal((300, 8)).astype(np.float32)
    y[130] = y[5]
    y[257] = y[5]
    x = y[[5]]
    _, idx = fused_l2_argmin_pallas(x, y, bm=16, bn=128, interpret=True)
    assert int(np.asarray(idx)[0]) == 5


def test_dispatch_glue_routes_through_pallas(rng):
    # Force the production dispatch (use_pallas + fits_pallas + interpret
    # threading) on CPU via the test hooks.
    from raft_tpu import ops
    from raft_tpu.distance.pairwise import pairwise_distance
    from raft_tpu.distance.fused_l2_nn import fused_l2_nn_argmin

    x = rng.standard_normal((40, 16)).astype(np.float32)
    y = rng.standard_normal((90, 16)).astype(np.float32)
    want_l1 = np.abs(x[:, None, :] - y[None, :, :]).sum(-1)
    want_ham = (x[:, None, :] != y[None, :, :]).mean(-1)
    full = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)

    ops.set_pallas_override(True)
    ops.set_pallas_interpret(True)
    try:
        got = np.asarray(pairwise_distance(x, y, metric="cityblock"))
        np.testing.assert_allclose(got, want_l1, rtol=1e-5, atol=1e-5)
        got = np.asarray(pairwise_distance(x, y, metric="hamming"))
        np.testing.assert_allclose(got, want_ham, rtol=1e-5, atol=1e-5)
        idx = np.asarray(fused_l2_nn_argmin(x, y))
        np.testing.assert_array_equal(idx, full.argmin(axis=1))
    finally:
        ops.set_pallas_override(None)
        ops.set_pallas_interpret(False)


def test_fused_l2_argmin_exact_duplicate(rng):
    # x rows present in y must map to themselves with ~zero distance.
    y = rng.standard_normal((100, 16)).astype(np.float32)
    x = y[[3, 42, 99]]
    dist, idx = fused_l2_argmin_pallas(x, y, bm=16, bn=128, interpret=True)
    np.testing.assert_array_equal(np.asarray(idx), [3, 42, 99])
    assert np.asarray(dist).max() < 1e-5


def test_pq_list_scan_bins_match_oracle(rng):
    """Fused list-scan kernel (interpret mode) vs a bf16-faithful numpy
    oracle: every (chunk, bin) running-best value and index must equal the
    per-bin minimum over that bin's lane-column class."""
    import ml_dtypes
    import jax.numpy as jnp

    from raft_tpu.ops.pq_list_scan import pq_list_scan, _BINS

    n_lists, L, rot, ncb, chunk = 5, 384, 32, 8, 16
    r8 = rng.integers(-127, 128, (n_lists, L, rot)).astype(np.int8)
    rn = (rng.random((n_lists, 1, L)) * 10).astype(np.float32)
    invalid = rng.random((n_lists, 1, L)) < 0.3
    base = np.where(invalid, np.inf, rn).astype(np.float32)
    lof = rng.integers(0, n_lists, (ncb,)).astype(np.int32)
    qres = rng.normal(size=(ncb, chunk, rot)).astype(np.float32)

    vals, idx = pq_list_scan(
        jnp.asarray(lof), jnp.asarray(qres), jnp.asarray(r8), jnp.asarray(base),
        interpret=True,
    )
    vals, idx = np.asarray(vals), np.asarray(idx)

    assert vals.shape[-1] == 2 * _BINS  # best + second-best per bin
    bins = (np.arange(L) % 128) + 128 * ((np.arange(L) // 128) % 2)
    for b in range(ncb):
        qb = qres[b].astype(ml_dtypes.bfloat16).astype(np.float32)
        rb = r8[lof[b]].astype(ml_dtypes.bfloat16).astype(np.float32)
        scores = base[lof[b]][0][None, :] - 2.0 * (qb @ rb.T)
        for bin_ in range(0, _BINS, 17):  # stride keeps runtime modest
            cols = np.nonzero(bins == bin_)[0]
            srt = np.sort(scores[:, cols], axis=1)
            for rank_, off in ((0, 0), (1, _BINS)):  # best, second-best
                want = srt[:, rank_] if srt.shape[1] > rank_ else np.full(
                    (chunk,), np.inf, np.float32
                )
                got = vals[b, :, bin_ + off]
                finite = np.isfinite(want)
                np.testing.assert_allclose(
                    got[finite], want[finite], rtol=1e-5, atol=1e-3
                )
                assert not np.isfinite(got[~finite]).any()
                # idx only meaningful where the slot held a finite candidate
                assert (bins[idx[b, finite, bin_ + off]] == bin_).all()


def test_pq_list_scan_packed_fold_matches_oracle(rng):
    """fold="packed" (interpret mode) vs a numpy oracle that applies the
    SAME int32 packing (bf16-coarse score image | fold id): per (lane,
    bank) the kernel must return exactly the two packed-smallest
    candidates, values equal to the coarse band bound, indices exact."""
    import jax.numpy as jnp

    from raft_tpu.ops.pq_list_scan import pq_list_scan, _BINS, _LANES

    n_lists, L, rot, ncb, chunk = 5, 384, 32, 8, 16
    r8 = rng.integers(-127, 128, (n_lists, L, rot)).astype(np.int8)
    rn = (rng.random((n_lists, 1, L)) * 10).astype(np.float32)
    invalid = rng.random((n_lists, 1, L)) < 0.3
    base = np.where(invalid, np.inf, rn).astype(np.float32)
    lof = rng.integers(0, n_lists, (ncb,)).astype(np.int32)
    qres = rng.normal(size=(ncb, chunk, rot)).astype(np.float32)

    vals, idx = pq_list_scan(
        jnp.asarray(lof), jnp.asarray(qres), jnp.asarray(r8), jnp.asarray(base),
        interpret=True, fold="packed",
    )
    vals, idx = np.asarray(vals), np.asarray(idx)

    def pack_np(scores, folds):
        i = scores.view(np.int32)
        u = np.where(i < 0, ~i, i | np.int32(-2147483648))
        return ((u & np.int32(-65536)) | folds) ^ np.int32(-2147483648)

    import ml_dtypes

    n_folds = L // _LANES
    for b in range(ncb):
        qb = qres[b].astype(ml_dtypes.bfloat16).astype(np.float32)
        rb = r8[lof[b]].astype(ml_dtypes.bfloat16).astype(np.float32)
        scores = (base[lof[b]][0][None, :] - 2.0 * (qb @ rb.T)).astype(np.float32)
        folds = (np.arange(L, dtype=np.int32) // _LANES)[None, :]
        packed = pack_np(scores, np.broadcast_to(folds, scores.shape))
        for lane in range(0, _LANES, 13):
            for bank, off in ((0, 0), (1, _LANES)):
                cols = [
                    c * _LANES + lane
                    for c in range(bank, n_folds, 2)
                ]
                srt = np.sort(packed[:, cols], axis=1)
                for rank_, roff in ((0, 0), (1, _BINS)):
                    slot = lane + off + roff
                    got_v, got_i = vals[b, :, slot], idx[b, :, slot]
                    if srt.shape[1] > rank_:
                        want_p = srt[:, rank_]
                    else:
                        want_p = np.full((chunk,), np.int32(2147483647))
                    # decode expected value/index from the packed oracle
                    p = want_p ^ np.int32(-2147483648)
                    want_fold = p & np.int32(0xFFFF)
                    u = p & np.int32(-65536)
                    i32 = np.where(u < 0, u & np.int32(2147483647), ~u)
                    want_v = i32.view(np.float32)
                    sentinel = want_fold >= n_folds
                    np.testing.assert_array_equal(
                        got_v[~sentinel], want_v[~sentinel]
                    )
                    assert not np.isfinite(got_v[sentinel]).any()
                    np.testing.assert_array_equal(
                        got_i[~sentinel],
                        want_fold[~sentinel] * _LANES + lane,
                    )


def test_pq_list_scan_int8_queries_match_oracle(rng):
    """The q_scale (int8 x int8) kernel branch against an exact integer
    oracle: int32 dots * per-row scale, then the same bin reduction."""
    import jax.numpy as jnp

    from raft_tpu.ops.pq_list_scan import pq_list_scan, _BINS

    n_lists, L, rot, ncb, chunk = 4, 384, 16, 6, 8
    r8 = rng.integers(-127, 128, (n_lists, L, rot)).astype(np.int8)
    rn = (rng.random((n_lists, 1, L)) * 10).astype(np.float32)
    invalid = rng.random((n_lists, 1, L)) < 0.25
    base = np.where(invalid, np.inf, rn).astype(np.float32)
    lof = rng.integers(0, n_lists, (ncb,)).astype(np.int32)
    q8 = rng.integers(-127, 128, (ncb, chunk, rot)).astype(np.int8)
    rs = (rng.random((ncb, chunk, 1)) * 0.01 + 0.001).astype(np.float32)

    vals, idx = pq_list_scan(
        jnp.asarray(lof), jnp.asarray(q8), jnp.asarray(r8), jnp.asarray(base),
        interpret=True, q_scale=jnp.asarray(rs),
    )
    vals, idx = np.asarray(vals), np.asarray(idx)
    assert vals.shape[-1] == 2 * _BINS

    bins = (np.arange(L) % 128) + 128 * ((np.arange(L) // 128) % 2)
    for b in range(ncb):
        dots = q8[b].astype(np.int64) @ r8[lof[b]].astype(np.int64).T  # exact
        scores = base[lof[b]][0][None, :] - 2.0 * dots.astype(np.float32) * rs[b]
        for bin_ in range(0, _BINS, 37):
            cols = np.nonzero(bins == bin_)[0]
            srt = np.sort(scores[:, cols], axis=1)
            for rank_, off in ((0, 0), (1, _BINS)):
                want = srt[:, rank_] if srt.shape[1] > rank_ else np.full(
                    (chunk,), np.inf, np.float32
                )
                got = vals[b, :, bin_ + off]
                finite = np.isfinite(want)
                np.testing.assert_allclose(got[finite], want[finite],
                                           rtol=1e-5, atol=1e-4)
                assert not np.isfinite(got[~finite]).any()
                assert (bins[idx[b, finite, bin_ + off]] == bin_).all()

    # dtype validation: q_scale demands int8 operands
    import pytest

    with pytest.raises(ValueError, match="int8"):
        pq_list_scan(
            jnp.asarray(lof), jnp.asarray(q8, jnp.float32), jnp.asarray(r8),
            jnp.asarray(base), interpret=True, q_scale=jnp.asarray(rs),
        )


def test_pq_list_scan_rot_pad_bit_identical(rng, monkeypatch):
    """RAFT_TPU_PALLAS_ROT_PAD: the lane-padded contracting dim (the
    one-flag fallback if the first Mosaic compile rejects rot % 128 != 0)
    must be BIT-identical to the unpadded kernel — zero lanes contribute
    zero to every dot — on both the bf16 and the int8-MXU paths."""
    import jax
    import jax.numpy as jnp

    from raft_tpu.ops.pq_list_scan import pq_list_scan

    n_lists, L, rot, ncb, chunk = 4, 256, 96, 6, 8  # rot = bench geometry
    r8 = rng.integers(-127, 128, (n_lists, L, rot)).astype(np.int8)
    base = (rng.random((n_lists, 1, L)) * 10).astype(np.float32)
    lof = rng.integers(0, n_lists, (ncb,)).astype(np.int32)
    qres = rng.normal(size=(ncb, chunk, rot)).astype(np.float32)
    q8 = rng.integers(-127, 128, (ncb, chunk, rot)).astype(np.int8)
    qs = (rng.random((ncb, chunk, 1)) + 0.5).astype(np.float32)

    args = (jnp.asarray(lof), jnp.asarray(qres), jnp.asarray(r8),
            jnp.asarray(base))
    v0, i0 = pq_list_scan(*args, interpret=True)
    vi0, ii0 = pq_list_scan(jnp.asarray(lof), jnp.asarray(q8),
                            jnp.asarray(r8), jnp.asarray(base),
                            interpret=True, q_scale=jnp.asarray(qs))

    monkeypatch.setenv("RAFT_TPU_PALLAS_ROT_PAD", "1")
    jax.clear_caches()  # the flag is read at trace time
    try:
        v1, i1 = pq_list_scan(*args, interpret=True)
        vi1, ii1 = pq_list_scan(jnp.asarray(lof), jnp.asarray(q8),
                                jnp.asarray(r8), jnp.asarray(base),
                                interpret=True, q_scale=jnp.asarray(qs))
    finally:
        monkeypatch.delenv("RAFT_TPU_PALLAS_ROT_PAD")
        jax.clear_caches()
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v0))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(vi1), np.asarray(vi0))
    np.testing.assert_array_equal(np.asarray(ii1), np.asarray(ii0))


def test_rot_pad_flag_semantics(monkeypatch, tmp_path):
    """Env wins in both directions over the tuned key; fits_pallas sizes
    the envelope against the rot the kernel will actually run with."""
    import json
    from raft_tpu.ops import pq_list_scan as mod
    from raft_tpu.core import tuned

    p = str(tmp_path / "tuned_defaults.json")
    with open(p, "w") as f:
        json.dump({"pallas_rot_pad": True}, f)
    monkeypatch.setattr(tuned, "_PATH", p)
    tuned.reload()
    try:
        assert mod.rot_pad_enabled() is True          # tuned on
        monkeypatch.setenv("RAFT_TPU_PALLAS_ROT_PAD", "0")
        assert mod.rot_pad_enabled() is False         # env force-off wins
        monkeypatch.setenv("RAFT_TPU_PALLAS_ROT_PAD", "True")
        assert mod.rot_pad_enabled() is True          # case-insensitive
        # envelope accounts for the padded rot: pick L so rot=96 fits but
        # rot->128 does not (store_itemsize=2, chunk=128)
        chunk, si = 128, 2
        L = 40960
        assert mod.fits_pallas(chunk, L, 96, si) == mod.fits_pallas(
            chunk, L, 128, si), "padded-rot envelope must match rot=128"
        monkeypatch.setenv("RAFT_TPU_PALLAS_ROT_PAD", "0")
        bytes96 = 4 * chunk * L + si * L * 96 + 4 * chunk * 96 + 8 * chunk * mod._CANDS
        if bytes96 <= 10 * 1024 * 1024:
            assert mod.fits_pallas(chunk, L, 96, si)  # unpadded fits
    finally:
        tuned.reload()
