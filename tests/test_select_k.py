"""select_k tests vs numpy argsort oracle (mirrors cpp/test/matrix/select_k.cu)."""

import numpy as np
import pytest

from raft_tpu.matrix import select_k


@pytest.mark.parametrize("batch,length,k", [(1, 100, 5), (16, 1000, 32), (4, 257, 257), (3, 70000, 17)])
@pytest.mark.parametrize("select_min", [True, False])
def test_select_k(batch, length, k, select_min, rng):
    x = rng.random((batch, length), dtype=np.float32)
    vals, idx = select_k(x, k, select_min=select_min)
    vals, idx = np.asarray(vals), np.asarray(idx)
    assert vals.shape == (batch, k) and idx.shape == (batch, k)
    order = np.argsort(x, axis=1)
    if not select_min:
        order = order[:, ::-1]
    want_vals = np.take_along_axis(x, order[:, :k], axis=1)
    np.testing.assert_allclose(vals, want_vals, rtol=1e-6)
    # indices must retrieve the reported values
    np.testing.assert_allclose(np.take_along_axis(x, idx, axis=1), vals, rtol=1e-6)


def test_select_k_1d(rng):
    x = rng.random(50, dtype=np.float32)
    vals, idx = select_k(x, 3)
    assert vals.shape == (3,)
    np.testing.assert_allclose(np.asarray(vals), np.sort(x)[:3], rtol=1e-6)


def test_select_k_custom_indices(rng):
    x = rng.random((2, 20), dtype=np.float32)
    ids = np.arange(100, 120, dtype=np.int64)[None, :].repeat(2, axis=0)
    vals, idx = select_k(x, 4, indices=ids)
    assert np.all(np.asarray(idx) >= 100)


def test_select_k_validates():
    with pytest.raises(ValueError):
        select_k(np.zeros((2, 5), np.float32), 6)
    with pytest.raises(ValueError):
        select_k(np.zeros((2, 5), np.float32), 2, strategy="warpsort")


@pytest.mark.parametrize("batch,length,k", [(1, 128, 5), (7, 1000, 32), (3, 4096, 256), (2, 70000, 17)])
@pytest.mark.parametrize("select_min", [True, False])
def test_select_k_counting_oracle(batch, length, k, select_min, rng):
    """Counting-select engine vs argsort oracle (interpret mode on CPU)."""
    x = (rng.random((batch, length), dtype=np.float32) - 0.5) * 100.0
    vals, idx = select_k(x, k, select_min=select_min, strategy="counting")
    vals, idx = np.asarray(vals), np.asarray(idx)
    order = np.argsort(x, axis=1)
    if not select_min:
        order = order[:, ::-1]
    want_vals = np.take_along_axis(x, order[:, :k], axis=1)
    np.testing.assert_allclose(vals, want_vals, rtol=1e-6)
    np.testing.assert_allclose(np.take_along_axis(x, idx, axis=1), vals, rtol=1e-6)


def test_select_k_counting_ties_and_extremes(rng):
    """Exactness under heavy ties, negatives, and infs: the bit-fixing
    threshold must count ties stably (lowest index wins)."""
    x = np.array(
        [
            [2.0, -1.0, 2.0, 2.0, -1.0, 0.0, np.inf, -np.inf] * 16,
            [0.5] * 64 + [0.25] * 64,
        ],
        dtype=np.float32,
    )
    vals, idx = select_k(x, 5, strategy="counting")
    vals, idx = np.asarray(vals), np.asarray(idx)
    # 16 copies of -inf (one per 8-element repeat): stable ties pick the
    # earliest occurrences in index order
    np.testing.assert_allclose(vals[0], [-np.inf] * 5)
    assert list(idx[0]) == [7, 15, 23, 31, 39]
    np.testing.assert_allclose(vals[1], [0.25] * 5)
    assert list(idx[1]) == [64, 65, 66, 67, 68]
