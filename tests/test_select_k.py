"""select_k tests vs numpy argsort oracle (mirrors cpp/test/matrix/select_k.cu)."""

import numpy as np
import pytest

from raft_tpu.matrix import select_k


@pytest.mark.parametrize("batch,length,k", [(1, 100, 5), (16, 1000, 32), (4, 257, 257), (3, 70000, 17)])
@pytest.mark.parametrize("select_min", [True, False])
def test_select_k(batch, length, k, select_min, rng):
    x = rng.random((batch, length), dtype=np.float32)
    vals, idx = select_k(x, k, select_min=select_min)
    vals, idx = np.asarray(vals), np.asarray(idx)
    assert vals.shape == (batch, k) and idx.shape == (batch, k)
    order = np.argsort(x, axis=1)
    if not select_min:
        order = order[:, ::-1]
    want_vals = np.take_along_axis(x, order[:, :k], axis=1)
    np.testing.assert_allclose(vals, want_vals, rtol=1e-6)
    # indices must retrieve the reported values
    np.testing.assert_allclose(np.take_along_axis(x, idx, axis=1), vals, rtol=1e-6)


def test_select_k_1d(rng):
    x = rng.random(50, dtype=np.float32)
    vals, idx = select_k(x, 3)
    assert vals.shape == (3,)
    np.testing.assert_allclose(np.asarray(vals), np.sort(x)[:3], rtol=1e-6)


def test_select_k_custom_indices(rng):
    x = rng.random((2, 20), dtype=np.float32)
    ids = np.arange(100, 120, dtype=np.int64)[None, :].repeat(2, axis=0)
    vals, idx = select_k(x, 4, indices=ids)
    assert np.all(np.asarray(idx) >= 100)


def test_select_k_validates():
    with pytest.raises(ValueError):
        select_k(np.zeros((2, 5), np.float32), 6)
