"""Randomized equivalence sweep: the *_local (per-process-partition) API
must agree with the driver API when both run single-process on the same
data — the degenerate case every multi-controller code path shares.
Shapes, k, and cluster counts are drawn randomly so layout edge cases
(odd row counts, pad-heavy shards, k near row count) get swept instead
of hand-picked."""

import numpy as np
import pytest

from raft_tpu.comms import Comms, mnmg
from raft_tpu.neighbors import ivf_flat


@pytest.fixture(scope="module")
def comms():
    return Comms()


@pytest.mark.parametrize("trial", range(4))
def test_knn_local_matches_knn(comms, trial):
    r = np.random.default_rng(100 + trial)
    n = int(r.integers(40, 900))
    d = int(r.integers(3, 40))
    k = int(r.integers(1, min(n, 20)))
    nq = int(r.integers(1, 16))
    x = r.random((n, d), dtype=np.float32)
    q = r.random((nq, d), dtype=np.float32)
    metric = ["sqeuclidean", "inner_product"][trial % 2]
    v1, i1 = mnmg.knn(comms, x, q, k, metric=metric)
    v2, i2 = mnmg.knn_local(comms, x, q, k, metric=metric)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v2), rtol=1e-5)


@pytest.mark.parametrize("trial", range(2))
def test_kmeans_local_matches_fit(comms, trial):
    r = np.random.default_rng(200 + trial)
    n = int(r.integers(100, 600))
    d = int(r.integers(4, 24))
    k = int(r.integers(2, 12))
    x = r.random((n, d), dtype=np.float32)
    _, in1, _ = mnmg.kmeans_fit(comms, x, k, max_iter=15, seed=trial, n_init=2)
    _, in2, _ = mnmg.kmeans_fit_local(comms, x, k, max_iter=15, seed=trial, n_init=2)
    # same seeds, same data, same restart trials -> same best inertia
    assert abs(in1 - in2) <= 1e-3 * max(1.0, abs(in1)), (in1, in2)


@pytest.mark.parametrize("trial", range(2))
def test_ivf_flat_local_matches_build(comms, trial):
    r = np.random.default_rng(300 + trial)
    n = int(r.integers(400, 1500))
    d = int(r.integers(4, 32))
    n_lists = int(r.integers(2, 9))
    k = int(r.integers(1, 8))
    x = r.random((n, d), dtype=np.float32)
    params = ivf_flat.IndexParams(n_lists=n_lists, kmeans_n_iters=4)
    d1 = mnmg.ivf_flat_build(comms, params, x)
    d2 = mnmg.ivf_flat_build_local(comms, params, x)
    q = x[: min(16, n)]
    _, i1 = mnmg.ivf_flat_search(d1, q, k, n_probes=n_lists)
    _, i2 = mnmg.ivf_flat_search(d2, q, k, n_probes=n_lists)
    # probing every list makes both exact over the same data -> same ids
    # up to tie order; compare as sets per row
    g1, g2 = np.asarray(i1), np.asarray(i2)
    for row1, row2 in zip(g1, g2):
        assert set(row1) == set(row2), (row1, row2)


@pytest.mark.parametrize("trial", range(2))
def test_extend_local_matches_extend(comms, trial):
    """Growing an index with the collective extend_local must agree with
    the driver extend on the same data: same n, same id space, and the
    appended rows equally findable (randomized shapes sweep the padded
    rank-block layouts)."""
    r = np.random.default_rng(400 + trial)
    n = int(r.integers(300, 900))
    n_new = int(r.integers(1, 200))
    d = int(r.integers(4, 24))
    x = r.random((n + n_new, d), dtype=np.float32)
    params = ivf_flat.IndexParams(
        n_lists=int(r.integers(2, 8)), kmeans_n_iters=4)

    a = mnmg.ivf_flat_build(comms, params, x[:n])
    a = mnmg.ivf_flat_extend(a, x[n:])
    b = mnmg.ivf_flat_build_local(comms, params, x[:n])
    b = mnmg.ivf_flat_extend_local(b, x[n:])
    assert a.n == b.n == n + n_new

    q = x[r.integers(0, n + n_new, 8)]
    nl = params.n_lists
    _, ia = mnmg.ivf_flat_search(a, q, 3, n_probes=nl)
    _, ib = mnmg.ivf_flat_search(b, q, 3, n_probes=nl)
    # same data, all lists probed: exact scan -> identical neighbor sets
    # (coarse centers may differ between the two builds only via RNG —
    # both paths seed identically, so ids must match)
    np.testing.assert_array_equal(np.asarray(ia), np.asarray(ib))
