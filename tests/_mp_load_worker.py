"""Worker for the cross-process checkpoint-load test: bootstraps the
distributed runtime, loads a single-controller-written distributed
IVF-Flat checkpoint onto the process-spanning mesh (shared-filesystem
contract), searches, and checks recall against the saved ground truth.

Run: python tests/_mp_load_worker.py <pid> <nproc> <port> <ckpt> <npz>
"""

import os
import sys

PID = int(sys.argv[1])
NPROC = int(sys.argv[2])
PORT = sys.argv[3]
CKPT = sys.argv[4]
NPZ = sys.argv[5]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from raft_tpu.comms import Comms, bootstrap_multihost, mnmg
from jax.sharding import Mesh


def main():
    bootstrap_multihost(f"127.0.0.1:{PORT}", num_processes=NPROC, process_id=PID)
    mesh = Mesh(np.array(jax.devices()), ("data",))
    comms = Comms(mesh=mesh)
    assert comms.spans_processes()

    blob = np.load(NPZ)
    queries, truth = blob["queries"], blob["truth"]

    index = mnmg.ivf_flat_load(comms, CKPT)
    _, ids = mnmg.ivf_flat_search(index, queries, truth.shape[1], n_probes=8)
    got = np.asarray(ids.addressable_shards[0].data)
    k = truth.shape[1]
    rec = np.mean(
        [len(set(got[i]) & set(truth[i])) / k for i in range(truth.shape[0])]
    )
    if rec < 0.9:
        print(f"FAIL load recall {rec:.3f}", flush=True)
        sys.exit(1)
    print(f"LOAD_OK {rec:.3f}", flush=True)


if __name__ == "__main__":
    main()
