"""raftlint suite: per-rule fixture snippets (positive, negative,
pragma-suppressed, baseline-matched), engine mechanics (deterministic
output, baseline lifecycle, CLI), the end-to-end contract that the repo
itself lints clean, and the fault-site drift test tying
``core.faults.FAULT_SITES`` to the chaos drills.

Fixture trees are written under tmp_path mirroring the repo layout
(rules scope on repo-relative paths like ``raft_tpu/...``), with
``repo_root=tmp_path`` so the real repo never leaks into a fixture run.
"""

import ast
import fnmatch
import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools.raftlint import Finding, lint_paths
from tools.raftlint.engine import write_baseline

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MINI_REGISTRY = """
FAULT_SITES = {
    "good.site": "a registered site",
    "other.site": "another registered site",
}
"""


def run_lint(tmp_path, files, rules=None, baseline=None, registry=True):
    """Write `files` ({relpath: source}) under tmp_path and lint them."""
    if registry and "raft_tpu/core/faults.py" not in files:
        files = dict(files)
        files["raft_tpu/core/faults.py"] = MINI_REGISTRY
        # the unused-site check only runs on whole-package scans,
        # detected by the package root being in the scan set
        files.setdefault("raft_tpu/__init__.py", "")
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    res = lint_paths([str(tmp_path)], repo_root=str(tmp_path),
                     baseline=baseline, rules=rules)
    return res


def rules_at(res, relpath=None):
    return [(f.rule, f.line) for f in res.findings
            if relpath is None or f.path == relpath]


# -- trace safety -------------------------------------------------------

def test_trace_host_effect_fires_with_location(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/distance/mod.py": """
        import time
        import jax

        @jax.jit
        def traced(x):
            t = time.monotonic()
            print("hello")
            return x + t

        def host():
            print(time.monotonic())
    """}, rules=["trace-host-effect"])
    assert rules_at(res) == [("trace-host-effect", 7),
                             ("trace-host-effect", 8)]
    f = res.findings[0]
    assert f.path == "raft_tpu/distance/mod.py" and f.col > 0


def test_trace_rules_exempt_tests_and_host_code(tmp_path):
    res = run_lint(tmp_path, {"tests/test_mod.py": """
        import time
        import jax

        @jax.jit
        def hostile(x):
            return x + time.monotonic()
    """}, rules=["trace-host-effect"])
    assert res.findings == []


def test_trace_detects_name_passing_and_pallas(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/ops/kern.py": """
        import time
        import jax
        from jax.experimental import pallas as pl

        def kernel(ref, out):
            time.sleep(1)

        def body(x):
            time.sleep(2)
            return x

        def launch(x):
            out = pl.pallas_call(kernel, out_shape=None)(x)
            return jax.shard_map(body, mesh=None)(out)
    """}, rules=["trace-host-effect"])
    assert rules_at(res) == [("trace-host-effect", 7),
                             ("trace-host-effect", 10)]


def test_trace_nested_defs_inherit_traced_context(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/ops/nested.py": """
        import time
        import jax

        @jax.jit
        def outer(x):
            def inner(y):
                return y + time.monotonic()
            return inner(x)
    """}, rules=["trace-host-effect"])
    assert rules_at(res) == [("trace-host-effect", 8)]


def test_trace_nondeterminism_flags_module_rng_not_jax_random(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/random/mod.py": """
        import random
        import numpy as np
        import jax

        @jax.jit
        def traced(x, key):
            a = random.random()
            b = np.random.default_rng(0).normal()
            c = jax.random.uniform(key, (2,))
            return x + a + b + c
    """}, rules=["trace-nondeterminism"])
    assert rules_at(res) == [("trace-nondeterminism", 8),
                             ("trace-nondeterminism", 9)]


def test_trace_host_sync_item_and_builtins_on_traced_args(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/matrix/mod.py": """
        import jax

        @jax.jit
        def traced(x, k):
            n = int(x.shape[0])   # shapes are static: int() on an
            v = float(x)          # attribute chain is not flagged
            flag = bool(k)
            return v + x.item() + flag + n
    """}, rules=["trace-host-sync"])
    assert rules_at(res) == [("trace-host-sync", 7),
                             ("trace-host-sync", 8),
                             ("trace-host-sync", 9)]


def test_trace_static_argnames_exempt_from_host_sync(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/matrix/mod.py": """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("k",))
        def traced(x, k):
            return x[: int(k)] + float(x)
    """}, rules=["trace-host-sync"])
    # int(k) exempt (static), float(x) still flagged
    assert rules_at(res) == [("trace-host-sync", 7)]
    assert "float(x)" in res.findings[0].message


def test_trace_try_except_around_lax_only(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/linalg/mod.py": """
        import jax
        from jax import lax

        @jax.jit
        def traced(x):
            try:
                y = lax.add(x, x)
            except ValueError:
                y = x
            try:
                z = {}["missing"]
            except KeyError:
                z = 0
            return y + z
    """}, rules=["trace-try-except"])
    assert rules_at(res) == [("trace-try-except", 7)]


# -- lock discipline ----------------------------------------------------

LOCKY = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0          # __init__ is exempt (pre-publication)
            self._free = 0

        def bump(self):
            with self._lock:
                self._n += 1

        def read_locked_ok(self):
            with self._lock:
                return self._n

        def racy_read(self):
            return self._n

        def suppressed(self):
            return self._n       # raftlint: disable=lock-discipline

        def _peek_locked(self):
            return self._n       # *_locked naming convention

        def untracked(self):
            return self._free    # never written under the lock
"""


def test_lock_discipline_positive_negative_pragma_convention(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/serve/mod.py": LOCKY},
                   rules=["lock-discipline"])
    assert rules_at(res) == [("lock-discipline", 19)]
    assert "_n" in res.findings[0].message
    assert res.pragma_suppressed == 1


def test_lock_discipline_nested_callbacks_are_lock_free(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/serve/mod.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def set(self, v):
                with self._lock:
                    self._v = v
                    return lambda: self._v
    """}, rules=["lock-discipline"])
    assert rules_at(res) == [("lock-discipline", 11)]


# -- fault-site drift ---------------------------------------------------

def test_fault_site_unknown_literal_glob_and_const(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/comms/mod.py": """
        from raft_tpu.core import faults

        BAD_SITE = "not.registered"
        GOOD_SITE = "good.site"

        def f(plan):
            faults.fault_point("bogus.site")
            faults.fault_point(GOOD_SITE)
            plan.matching("good.*", "slow_rank")
            plan.matching("zzz.*", "slow_rank")
    """}, rules=["fault-site-unknown"])
    assert rules_at(res) == [("fault-site-unknown", 4),
                             ("fault-site-unknown", 8),
                             ("fault-site-unknown", 11)]


def test_fault_site_unused_reported_at_registry_entry(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/comms/mod.py": """
        from raft_tpu.core import faults

        def f():
            faults.fault_point("good.site")
    """}, rules=["fault-site-unused"])
    assert [(f.rule, f.path) for f in res.findings] == [
        ("fault-site-unused", "raft_tpu/core/faults.py")]
    assert "'other.site'" in res.findings[0].message


def test_fault_site_unused_skipped_on_partial_scans(tmp_path):
    """Linting a subdirectory (no package root in the scan) must not
    declare every registered site unused — the hooks live elsewhere."""
    for rel, src in {
        "raft_tpu/__init__.py": "",
        "raft_tpu/core/faults.py": MINI_REGISTRY,
        "raft_tpu/serve/mod.py": "x = 1\n",
    }.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    res = lint_paths([str(tmp_path / "raft_tpu/serve")],
                     repo_root=str(tmp_path), baseline=None,
                     rules=["fault-site-unused"])
    assert res.findings == []


def test_nonexistent_path_fails_loudly(tmp_path):
    """A typo'd path must never turn the gate green while linting
    nothing."""
    with pytest.raises(ValueError, match="does not exist"):
        lint_paths([str(tmp_path / "renamed_away")],
                   repo_root=str(tmp_path), baseline=None)
    r = _cli(["--root", str(tmp_path), str(tmp_path / "renamed_away")])
    assert r.returncode == 2 and "does not exist" in r.stderr
    # same for an explicit non-Python file: exit 0 having linted
    # nothing is the failure mode, not a convenience
    notpy = tmp_path / "data.json"
    notpy.write_text("{}")
    with pytest.raises(ValueError, match="not a Python file"):
        lint_paths([str(notpy)], repo_root=str(tmp_path), baseline=None)


def test_write_baseline_preserves_unscanned_paths_and_stale_scoping(tmp_path):
    """Path-subset runs see only a slice of the repo: --write-baseline
    must preserve other paths' grandfathered entries, and live entries
    for unscanned files must not be reported stale."""
    for rel in ("raft_tpu/util/a.py", "raft_tpu/serve/b.py"):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text("import time\nx = time.time()\n")
    base = tmp_path / "base.json"
    # baseline the whole tree (2 entries), then re-write from a subset
    full = ["--baseline", str(base), "--root", str(tmp_path)]
    assert _cli(full + ["--write-baseline", str(tmp_path)]).returncode == 0
    assert _cli(full + ["--write-baseline",
                        str(tmp_path / "raft_tpu/serve")]).returncode == 0
    entries = json.load(open(base))["findings"]
    assert sorted(e["path"] for e in entries) == [
        "raft_tpu/serve/b.py", "raft_tpu/util/a.py"]
    # subset lint run: suppressed by baseline, and the util entry
    # (unscanned) is NOT advertised as stale
    r = _cli(full + [str(tmp_path / "raft_tpu/serve")])
    assert r.returncode == 0
    assert "stale" not in r.stderr


def test_fault_site_gate_fails_closed_on_unparseable_registry(tmp_path):
    """A refactor that makes FAULT_SITES non-literal (dict(...), merge
    expressions) must fail the gate, not silently disable it."""
    res = run_lint(tmp_path, {
        "raft_tpu/core/faults.py": "FAULT_SITES = dict(a='x')\n",
        "raft_tpu/__init__.py": "",
        "raft_tpu/comms/mod.py": """
            from raft_tpu.core import faults

            def f():
                faults.fault_point("totally.bogus.site")
        """,
    }, rules=["fault-site-unknown"], registry=False)
    assert [(f.rule, f.path) for f in res.findings] == [
        ("fault-site-unknown", "raft_tpu/core/faults.py")]
    assert "literal dict" in res.findings[0].message


def test_fault_site_fixture_tree_clean_when_all_used(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/comms/mod.py": """
        from raft_tpu.core import faults

        def f(plan):
            faults.fault_point("good.site")
            faults.corrupt_host("other.site", None)
    """}, rules=["fault-site-unknown", "fault-site-unused"])
    assert res.findings == []


# -- layer purity -------------------------------------------------------

def test_layer_purity_dag_and_lazy_escape(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/core/mod.py": """
        from raft_tpu.obs import registry   # core must import no sibling

        def lazy():
            from raft_tpu import obs        # sanctioned escape hatch
            return obs
    """}, rules=["layer-purity"], registry=False)
    assert rules_at(res) == [("layer-purity", 2)]


def test_layer_purity_sealed_packages(tmp_path):
    res = run_lint(tmp_path, {
        "raft_tpu/comms/mod.py": """
            def lazy():
                from raft_tpu.serve import engine   # apex: banned even lazily
        """,
        "bench/bench_mod.py": """
            import tests.conftest                    # nothing imports tests
        """,
    }, rules=["layer-purity"], registry=False)
    assert [(f.path, f.rule) for f in res.findings] == [
        ("bench/bench_mod.py", "layer-purity"),
        ("raft_tpu/comms/mod.py", "layer-purity"),
    ]


def test_layer_purity_relative_imports_resolve(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/comms/mod.py": """
        from ..neighbors import ivf_flat   # comms may not import neighbors
        from ..matrix import select_k      # allowed by the layer map
    """}, rules=["layer-purity"], registry=False)
    assert rules_at(res) == [("layer-purity", 2)]


def test_layer_purity_quantizer_cycle_ban(tmp_path):
    """The shared quantizer layer must never import an index module back
    at module scope (ivf_pq/ivf_rabitq import IT — the cycle would close
    on first import). Absolute, from-import and relative forms all
    fire; the sanctioned function-level lazy import does not; and the
    same imports are fine from any OTHER neighbors module."""
    res = run_lint(tmp_path, {"raft_tpu/neighbors/quantizer.py": """
        from raft_tpu.neighbors import ivf_pq          # banned: cycle
        from raft_tpu.neighbors.ivf_rabitq import search  # banned: cycle
        from .ivf_flat import _pack_lists              # banned: cycle
        from raft_tpu.neighbors import refine          # fine: not an index
        from raft_tpu.cluster import kmeans_balanced   # fine: MODULE_ALLOWED

        def lazy():
            from raft_tpu.neighbors.ivf_pq import SearchParams  # sanctioned
    """}, rules=["layer-purity"], registry=False)
    assert rules_at(res) == [("layer-purity", 2), ("layer-purity", 3),
                             ("layer-purity", 4)]
    # the same import is fine from any OTHER neighbors module (scoped by
    # path: the quantizer fixture file from above is still on disk)
    ok = run_lint(tmp_path, {"raft_tpu/neighbors/other.py": """
        from raft_tpu.neighbors import ivf_pq  # any other module may
    """}, rules=["layer-purity"], registry=False)
    assert rules_at(ok, "raft_tpu/neighbors/other.py") == []


def test_layer_purity_probe_budget_cycle_ban(tmp_path):
    """The adaptive-probing budget layer (ISSUE 12) is held to the
    quantizer's contract: every index engine imports IT at module
    scope, so a module-scope import of any index module (or of
    probe_invert, which the engines also wire it through) closes a
    cycle and fires; the sanctioned lazy form does not."""
    res = run_lint(tmp_path, {"raft_tpu/neighbors/probe_budget.py": """
        from raft_tpu.neighbors import ivf_flat        # banned: cycle
        from .probe_invert import chunk_validity       # banned: cycle
        from raft_tpu.matrix.select_k import _select_k_impl  # MODULE_ALLOWED
        from raft_tpu.distance.distance_types import DistanceType  # fine

        def lazy():
            from raft_tpu.neighbors.ivf_pq import SearchParams  # sanctioned
    """}, rules=["layer-purity"], registry=False)
    assert rules_at(res) == [("layer-purity", 2), ("layer-purity", 3)]


def test_layer_purity_probe_budget_module_allowed_is_stricter(tmp_path):
    """MODULE_ALLOWED narrows probe_budget below the neighbors
    allowance: notably ops (which full neighbors may import) is sealed
    for it — the budget layer steers kernels only through the
    matrix/select_k dispatch door, never directly."""
    res = run_lint(tmp_path, {"raft_tpu/neighbors/probe_budget.py": """
        from raft_tpu.ops import fused_scan    # banned: below its allowance
        from raft_tpu.cluster import kmeans_balanced  # banned: not allowed
        from raft_tpu import obs               # fine: MODULE_ALLOWED
    """}, rules=["layer-purity"], registry=False)
    assert rules_at(res, "raft_tpu/neighbors/probe_budget.py") == [
        ("layer-purity", 2), ("layer-purity", 3)]


def test_probe_budget_importable_by_all_engines_without_cycle():
    """The real modules: probe_budget imports cleanly on its own, all
    three engines import it, and its own module scope contains no
    neighbors-sibling import (the cycle ban's real-world pin)."""
    import ast as _ast

    src = open(os.path.join(REPO, "raft_tpu", "neighbors",
                            "probe_budget.py")).read()
    tree = _ast.parse(src)
    for node in _ast.walk(tree):
        if isinstance(node, _ast.ImportFrom) and node.col_offset == 0:
            mod = node.module or ""
            assert not mod.startswith("raft_tpu.neighbors"), mod
            assert not mod.startswith("raft_tpu.ops"), mod
    from raft_tpu.neighbors import ivf_flat, ivf_pq, ivf_rabitq  # noqa: F401
    from raft_tpu.neighbors import probe_budget  # noqa: F401


def test_layer_purity_mutation_cycle_ban(tmp_path):
    """The live-mutation layer (ISSUE 16) orchestrates ABOVE the index
    modules — it calls extend/save/load on all three kinds at call time
    — so a module-scope import of any engine is banned (the lazy
    `_index_module` dispatch is the sanctioned form, the jobs-runner
    posture one layer down); non-index siblings stay fine."""
    res = run_lint(tmp_path, {"raft_tpu/neighbors/mutation.py": """
        from raft_tpu.neighbors import ivf_flat        # banned: cycle
        from .ivf_pq import _grow_and_scatter_multi    # banned: cycle
        from raft_tpu.neighbors.ivf_rabitq import search  # banned: cycle
        from raft_tpu.core import serialize            # fine: MODULE_ALLOWED

        def lazy():
            from raft_tpu.neighbors import ivf_flat as mod  # sanctioned
    """}, rules=["layer-purity"], registry=False)
    assert rules_at(res) == [("layer-purity", 2), ("layer-purity", 3),
                             ("layer-purity", 4)]


def test_layer_purity_mutation_module_allowed_is_stricter(tmp_path):
    """MODULE_ALLOWED seals mutation.py to core+obs — strictly below
    the full neighbors allowance: distance/matrix/cluster are all fine
    for neighbors at large but banned here. The mutation layer moves
    rows and writes logs; it never computes."""
    res = run_lint(tmp_path, {"raft_tpu/neighbors/mutation.py": """
        from raft_tpu.distance import pairwise_distance  # banned
        from raft_tpu.matrix.select_k import select_k    # banned
        from raft_tpu import obs                  # fine: MODULE_ALLOWED
        from raft_tpu.core import faults          # fine: MODULE_ALLOWED
    """}, rules=["layer-purity"], registry=False)
    assert rules_at(res, "raft_tpu/neighbors/mutation.py") == [
        ("layer-purity", 2), ("layer-purity", 3)]


def test_mutation_importable_without_cycle():
    """The real module: mutation.py imports cleanly on its own, and its
    module scope contains no neighbors-sibling (or compute-layer)
    import — the cycle ban's real-world pin."""
    import ast as _ast

    src = open(os.path.join(REPO, "raft_tpu", "neighbors",
                            "mutation.py")).read()
    tree = _ast.parse(src)
    for node in _ast.walk(tree):
        if isinstance(node, _ast.ImportFrom) and node.col_offset == 0:
            mod = node.module or ""
            assert not mod.startswith("raft_tpu.neighbors"), mod
            assert not mod.startswith("raft_tpu.distance"), mod
            assert not mod.startswith("raft_tpu.matrix"), mod
    from raft_tpu.neighbors import mutation  # noqa: F401


def test_layer_purity_ops_never_imports_dispatch_back(tmp_path):
    """ANY_LEVEL_BAN (ISSUE 10): `ops` is the kernel layer matrix and
    neighbors dispatch INTO (select_k's fused strategy, every fused
    engine) — an ops -> matrix/neighbors import closes a dispatch cycle
    and is banned at any level, lazy function-level included. Reaching
    DOWN (core/distance) stays fine, and matrix/neighbors importing ops
    stays the sanctioned direction."""
    res = run_lint(tmp_path, {"raft_tpu/ops/fused_scan.py": """
        from raft_tpu.matrix.select_k import select_k   # banned: cycle
        from raft_tpu.distance import pairwise          # fine: reaches down

        def lazy():
            from raft_tpu.neighbors import brute_force  # banned EVEN lazily
    """}, rules=["layer-purity"], registry=False)
    assert rules_at(res) == [("layer-purity", 2), ("layer-purity", 6)]
    # the INTEGER kernels (ISSUE 11) are held to the same contract: a
    # bit-plane kernel module in ops that reaches for the quantizer's
    # estimator helpers (neighbors) fires — which is WHY the estimator
    # math is inlined in ops/fused_scan.py and pinned against the
    # quantizer reference by tests instead of imported
    res_int = run_lint(tmp_path, {"raft_tpu/ops/bitplane_kernel.py": """
        def kernel_wrapper():
            from raft_tpu.neighbors.quantizer import estimate_dot  # banned
    """}, rules=["layer-purity"], registry=False)
    assert rules_at(res_int, "raft_tpu/ops/bitplane_kernel.py") == [
        ("layer-purity", 3)]
    ok = run_lint(tmp_path, {
        "raft_tpu/matrix/fine.py": """
            from raft_tpu.ops.fused_scan import fused_topk  # dispatch -> ops
        """,
        "raft_tpu/neighbors/fine.py": """
            from raft_tpu.ops import fused_scan             # engines -> ops
        """,
    }, rules=["layer-purity"], registry=False)
    assert rules_at(ok, "raft_tpu/matrix/fine.py") == []
    assert rules_at(ok, "raft_tpu/neighbors/fine.py") == []


def test_integer_kernels_live_under_ops():
    """ISSUE 11 location pin: the integer fused kernels are defined in
    the ops layer (where every Pallas kernel lives) and the engine
    layers reach them only through the matrix/select_k dispatch — no
    pallas_call outside ops/ in the neighbors engines."""
    import ast as _ast

    from raft_tpu.ops import fused_scan

    for name in ("fused_list_topk_int8", "fused_bitplane_topk",
                 "fits_fused_bitplane"):
        assert hasattr(fused_scan, name), name
    for mod in ("raft_tpu/neighbors/ivf_pq.py",
                "raft_tpu/neighbors/ivf_rabitq.py"):
        src = open(os.path.join(REPO, mod)).read()
        assert "pallas_call" not in src, f"{mod} must dispatch, not own kernels"
        tree = _ast.parse(src)
        for node in _ast.walk(tree):
            if isinstance(node, _ast.ImportFrom) and node.module:
                assert not node.module.startswith("jax.experimental.pallas"), (
                    f"{mod} imports pallas directly"
                )


def test_layer_purity_library_never_imports_bench(tmp_path):
    """LIB_SEALED (ISSUE 7): the measurement layer reads raft_tpu, never
    the reverse — an `import bench` anywhere in the library (obs
    especially: the ledger/cost model live there precisely to keep this
    edge out) fires at any level, even lazily; bench/ files themselves
    are exempt (they import each other freely)."""
    res = run_lint(tmp_path, {
        "raft_tpu/obs/evil.py": """
            import bench

            def lazy():
                from bench.common import Banker   # banned even lazily
        """,
        "bench/fine.py": """
            import bench                           # bench may see itself
        """,
    }, rules=["layer-purity"], registry=False)
    assert [(f.path, f.line) for f in res.findings] == [
        ("raft_tpu/obs/evil.py", 2), ("raft_tpu/obs/evil.py", 5)]


def test_layer_purity_jobs_layer(tmp_path):
    """ISSUE 8: the job runner sits beside serve at the apex — it may
    import core/io/comms/obs at module scope, reaches index modules only
    through the lazy escape hatch, and serve/bench stay sealed against
    it even lazily (a runner importing the apex could never supervise
    it from outside; the library never imports the measurement layer)."""
    res = run_lint(tmp_path, {"raft_tpu/jobs/mod.py": """
        from raft_tpu.core import faults            # fine: layer map
        from raft_tpu import io, comms, obs         # fine: layer map
        from raft_tpu.neighbors import ivf_flat     # module-scope: fires

        def lazy():
            from raft_tpu.neighbors import ivf_pq   # sanctioned escape
            from raft_tpu.serve import engine       # sealed even lazily
            from bench.common import Banker         # LIB_SEALED: fires
    """}, rules=["layer-purity"], registry=False)
    assert rules_at(res) == [("layer-purity", 4), ("layer-purity", 8),
                             ("layer-purity", 9)]


def test_layer_purity_new_perf_modules_lint_clean(tmp_path):
    """The ISSUE-7 shapes stay legal: obs modules importing core +
    stdlib, comms importing obs, bench importing raft_tpu.obs.ledger."""
    res = run_lint(tmp_path, {
        "raft_tpu/obs/perf2.py": """
            import subprocess
            from raft_tpu.core import config
        """,
        "raft_tpu/comms/mnmg_extra.py": """
            from raft_tpu import obs
        """,
        "bench/common2.py": """
            from raft_tpu.obs import ledger
        """,
    }, rules=["layer-purity"], registry=False)
    assert rules_at(res) == []


def test_layer_purity_quantizer_module_allowed_is_stricter(tmp_path):
    """MODULE_ALLOWED narrows the quantizer below the neighbors
    subpackage map: `random` is allowed for neighbors at large but NOT
    for the quantizer module."""
    res = run_lint(tmp_path, {"raft_tpu/neighbors/quantizer.py": """
        from raft_tpu.random import rng     # outside the module map
        from raft_tpu.matrix import select_k  # inside it
    """}, rules=["layer-purity"], registry=False)
    assert rules_at(res, "raft_tpu/neighbors/quantizer.py") == [
        ("layer-purity", 2)]
    ok = run_lint(tmp_path, {"raft_tpu/neighbors/other.py": """
        from raft_tpu.random import rng     # neighbors at large: fine
    """}, rules=["layer-purity"], registry=False)
    assert rules_at(ok, "raft_tpu/neighbors/other.py") == []


def test_quantizer_importable_by_both_indexes_without_cycle():
    """The real modules: quantizer imports cleanly on its own, both
    index modules import it, and the quantizer's own module-scope
    imports touch neither — the import graph the cycle ban freezes."""
    import ast as _ast

    src = open(os.path.join(REPO, "raft_tpu", "neighbors",
                            "quantizer.py")).read()
    tree = _ast.parse(src)
    top_imports = []
    for node in tree.body:
        if isinstance(node, _ast.Import):
            top_imports += [a.name for a in node.names]
        elif isinstance(node, _ast.ImportFrom):
            top_imports.append(node.module or "")
    banned = ("ivf_pq", "ivf_rabitq", "ivf_flat")
    assert not [m for m in top_imports
                if any(b in (m or "") for b in banned)], top_imports
    # and the live import graph works both ways
    from raft_tpu.neighbors import ivf_pq, ivf_rabitq, quantizer

    assert ivf_pq._encode is quantizer._encode
    assert ivf_rabitq.packed_words is quantizer.packed_words


# -- hygiene ------------------------------------------------------------

def test_hygiene_bare_except_and_untyped_raise(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/util/mod.py": """
        def f():
            try:
                g()
            except:
                raise RuntimeError("boom")
            try:
                g()
            except ValueError:
                raise TimeoutError("typed is fine")
    """}, rules=["hygiene-bare-except", "hygiene-untyped-raise"])
    assert rules_at(res) == [("hygiene-bare-except", 5),
                             ("hygiene-untyped-raise", 6)]


def test_hygiene_wallclock_scoped_out_of_tests(tmp_path):
    files = {
        "raft_tpu/util/mod.py": "import time\nt = time.time()\n",
        "bench/bench_mod.py": "import time\nt = time.time()\n",
        "tests/test_mod.py": "import time\nt = time.time()\n",
    }
    res = run_lint(tmp_path, files, rules=["hygiene-wallclock"])
    assert sorted(f.path for f in res.findings) == [
        "bench/bench_mod.py", "raft_tpu/util/mod.py"]


def test_hygiene_raw_write_with_serialize_exemption(tmp_path):
    files = {
        "raft_tpu/io/mod.py": """
            import os

            import gzip

            def f(a, b):
                os.rename(a, b)
                os.replace(a, b)
                with open(a, "wb") as fh:
                    fh.write(b"x")
                with gzip.open(a, "wb") as fh:   # attribute opens too
                    fh.write(b"x")
                with a.open("wb") as fh:    # Path.open: mode is arg 0
                    fh.write(b"x")
                with open(a, "rb") as fh:   # reads are fine
                    fh.read()
                open("file.wb.bin")         # filename is not a mode
        """,
        "raft_tpu/core/serialize.py": """
            import os

            def atomic_write(a, b):
                os.replace(a, b)
        """,
    }
    res = run_lint(tmp_path, files, rules=["hygiene-raw-write"])
    assert rules_at(res, "raft_tpu/io/mod.py") == [
        ("hygiene-raw-write", 7), ("hygiene-raw-write", 8),
        ("hygiene-raw-write", 9), ("hygiene-raw-write", 11),
        ("hygiene-raw-write", 13)]
    assert rules_at(res, "raft_tpu/core/serialize.py") == []


def test_hygiene_float64_only_when_reaching_jax(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/stats/mod.py": """
        import numpy as np
        import jax.numpy as jnp

        host = np.zeros(4, np.float64)          # host-side numpy: fine
        dev = jnp.zeros(4, dtype="float64")     # reaches jax: flagged
        also = jnp.asarray(host, dtype=np.float64)
        alias = jnp.float64
        once = jnp.zeros(3, dtype=jnp.float64)  # exactly ONE finding
    """}, rules=["hygiene-float64"])
    assert rules_at(res) == [("hygiene-float64", 6),
                             ("hygiene-float64", 7),
                             ("hygiene-float64", 8),
                             ("hygiene-float64", 9)]


# -- collective divergence / order (raftlint 2.0, CFG-based) ------------

RANKY = """
    import jax

    def get_rank():
        return jax.process_index()
"""


def test_collective_divergence_direct_rank_guard(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/comms/mod.py": RANKY + """
    def diverge(comms):
        if get_rank() == 0:
            comms.allreduce(1)

    def uniform(comms, n_probes):
        if n_probes > 4:          # static config: every rank agrees
            comms.allreduce(1)
    """}, rules=["collective-divergence"])
    assert rules_at(res) == [("collective-divergence", 8)]
    assert "allreduce" in res.findings[0].message
    assert "rank-dependent" in res.findings[0].message


def test_collective_divergence_health_and_filesystem_taint(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/comms/mod.py": """
        import os

        def on_health(comms, health):
            if health.degraded:
                comms.barrier()

        def on_fs(comms, path):
            if os.path.exists(path):     # per-host fs probe
                comms.allgather(1)
    """}, rules=["collective-divergence"])
    assert rules_at(res) == [("collective-divergence", 5),
                             ("collective-divergence", 9)]
    assert "health-dependent" in res.findings[0].message
    assert "filesystem-dependent" in res.findings[1].message


def test_collective_divergence_interprocedural_two_calls_away(tmp_path):
    """`if health.degraded: repair(...)` fires even though the ppermute
    lives two resolved calls away — the project-summary half of the
    engine."""
    res = run_lint(tmp_path, {
        "raft_tpu/comms/top.py": RANKY + """
    from raft_tpu.comms.mid import repair

    def heal(comms, health):
        if health.degraded:
            repair(comms)
    """,
        "raft_tpu/comms/mid.py": """
            from raft_tpu.comms.leaf import mirror

            def repair(comms):
                return mirror(comms)
        """,
        "raft_tpu/comms/leaf.py": """
            from jax import lax

            def mirror(comms):
                return lax.ppermute(1, "ranks", [(0, 1)])
        """,
    }, rules=["collective-divergence"])
    assert rules_at(res, "raft_tpu/comms/top.py") == [
        ("collective-divergence", 10)]
    assert "repair" in res.findings[0].message


def test_collective_divergence_multi_level_rank_wrapper(tmp_path):
    """Rank-sourceness must survive wrapper CHAINS (review finding): a
    branch on rank_of() — two resolved calls from process_index — is as
    divergent as a branch on get_rank()."""
    res = run_lint(tmp_path, {"raft_tpu/comms/mod.py": RANKY + """
    def rank_of():
        return get_rank()

    def f(comms):
        if rank_of() == 0:
            comms.allreduce(1)
    """}, rules=["collective-divergence"])
    assert rules_at(res) == [("collective-divergence", 11)]


def test_collective_divergence_ternary_in_nested_def_reported_once(tmp_path):
    """A ternary inside a nested def must be reported exactly once (by
    the nested def's own analysis), not again by every enclosing
    function's walk (review finding: duplicate findings double baseline
    entries and pragma counts)."""
    res = run_lint(tmp_path, {"raft_tpu/comms/mod.py": """
        def outer(comms, health):
            def body(x):
                return comms.allreduce(x) if health.degraded else x
            return body
    """}, rules=["collective-divergence"])
    assert rules_at(res) == [("collective-divergence", 4)]


def test_collective_divergence_early_return_guard(tmp_path):
    """`if rank != 0: return` guards the collective after it without
    lexically enclosing it — control dependence, not indentation."""
    res = run_lint(tmp_path, {"raft_tpu/comms/mod.py": RANKY + """
    def driver_only(comms):
        if get_rank() != 0:
            return None
        return comms.gather(1)
    """}, rules=["collective-divergence"])
    assert rules_at(res) == [("collective-divergence", 8)]


def test_collective_divergence_rank_dependent_loop_trip_count(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/comms/mod.py": RANKY + """
    def uneven(comms):
        for _ in range(get_rank()):
            comms.barrier()

    def even(comms, n):
        for _ in range(n):
            comms.barrier()
    """}, rules=["collective-divergence"])
    assert rules_at(res) == [("collective-divergence", 8)]
    assert "trip count" in res.findings[0].message


def test_collective_divergence_ternary_arm(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/comms/mod.py": RANKY + """
    def pick(comms, health):
        x = comms.allreduce(1) if health.degraded else 0
        return x
    """}, rules=["collective-divergence"])
    assert rules_at(res) == [("collective-divergence", 8)]
    assert "conditional expression" in res.findings[0].message


def test_collective_divergence_nested_def_reference_counts(tmp_path):
    """A rank-guarded *reference* to a collective-emitting nested def
    (the shard_map/retry callback shape) is the emission point."""
    res = run_lint(tmp_path, {"raft_tpu/comms/mod.py": RANKY + """
    def launch(comms, shard_map):
        def body(x):
            return comms.allreduce(x)

        if get_rank() == 0:
            return shard_map(body)
        return None
    """}, rules=["collective-divergence"])
    assert rules_at(res) == [("collective-divergence", 11)]
    assert "body()" in res.findings[0].message


def test_collective_divergence_both_sides_emitting_is_clean(tmp_path):
    """Rank-dependent branch where BOTH sides emit the same sequence:
    no divergence (and no order drift) — the mesh stays in lockstep."""
    res = run_lint(tmp_path, {"raft_tpu/comms/mod.py": RANKY + """
    def symmetric(comms, payload):
        if get_rank() == 0:
            out = comms.allreduce(payload)
        else:
            out = comms.allreduce(0)
        return out
    """}, rules=["collective-divergence", "collective-order"])
    assert res.findings == []


def test_collective_divergence_pragma_and_baseline(tmp_path):
    files = {"raft_tpu/comms/mod.py": RANKY + """
    def driver_work(comms):
        if get_rank() == 0:  # raftlint: disable=collective-divergence
            comms.gather(1)

    def unjustified(comms):
        if get_rank() == 0:
            comms.gather(1)
    """}
    res = run_lint(tmp_path, files, rules=["collective-divergence"])
    assert rules_at(res) == [("collective-divergence", 12)]
    assert res.pragma_suppressed == 1
    base = tmp_path / "base.json"
    write_baseline(str(base), res.findings)
    again = lint_paths([str(tmp_path)], repo_root=str(tmp_path),
                       baseline=str(base), rules=["collective-divergence"])
    assert again.ok and again.baseline_suppressed == 1


def test_collective_order_drift_and_pragma(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/comms/mod.py": """
        def drift(comms, health):
            if health.degraded:
                comms.allreduce(1)
                comms.allgather(2)
            else:
                comms.allgather(2)
                comms.allreduce(1)

        def same_order(comms, health):
            if health.degraded:
                comms.allreduce(1)
                comms.allgather(2)
            else:
                comms.allreduce(0)
                comms.allgather(0)

        def justified(comms, health):
            if health.degraded:  # raftlint: disable=collective-order
                comms.allreduce(1)
                comms.allgather(2)
            else:
                comms.allgather(2)
                comms.allreduce(1)
    """}, rules=["collective-order"])
    assert rules_at(res) == [("collective-order", 3)]
    assert "different orders" in res.findings[0].message
    assert res.pragma_suppressed == 1


def test_collective_rules_scope_out_of_tools_and_tests(tmp_path):
    """Divergence analysis runs on raft_tpu/ only — bench drivers and
    tests branch on rank freely (single-process harnesses)."""
    src = RANKY + """
    def diverge(comms):
        if get_rank() == 0:
            comms.allreduce(1)
    """
    res = run_lint(tmp_path, {"bench/mod.py": src, "tests/test_x.py": src,
                              "tools/mod.py": src},
                   rules=["collective-divergence"])
    assert res.findings == []


def test_divergence_rule_catches_what_the_13_syntactic_rules_miss(tmp_path):
    """The acceptance drill: a rank-guarded collective that every PR-5
    rule walks straight past (it is well-typed, lock-free, fault-site-
    clean, layer-pure, hygienic and untraced) is caught only by the
    flow-sensitive divergence rule."""
    files = {"raft_tpu/comms/mod.py": RANKY + """
    from raft_tpu.core import faults

    def checkpoint_then_sync(comms, path):
        faults.fault_point("good.site")
        if get_rank() == 0:
            comms.barrier()
        faults.fault_point("other.site")
        return path
    """}
    pr5_rules = ["trace-host-effect", "trace-nondeterminism",
                 "trace-host-sync", "trace-try-except", "lock-discipline",
                 "fault-site-unknown", "fault-site-unused", "layer-purity",
                 "hygiene-bare-except", "hygiene-wallclock",
                 "hygiene-raw-write", "hygiene-untyped-raise",
                 "hygiene-float64"]
    blind = run_lint(tmp_path, files, rules=pr5_rules)
    assert blind.findings == []
    caught = run_lint(tmp_path, files, rules=["collective-divergence"])
    assert rules_at(caught) == [("collective-divergence", 11)]


# -- lock-order deadlock (raftlint 2.0, interprocedural) ----------------

DEADLOCKY = """
    import threading

    class A:
        def __init__(self):
            self._la = threading.Lock()

        def one(self, b):
            with self._la:
                b.grab()

    class B:
        def __init__(self):
            self._lb = threading.Lock()

        def grab(self):
            with self._lb:
                pass

        def two(self, a):
            with self._lb:
                a.one(self)
"""


def test_lock_order_cycle_across_classes(tmp_path):
    """A holds la and (via the by-name-resolved b.grab()) takes lb; B
    holds lb and takes la through a.one(): both edges of the cycle are
    reported, at the acquisition sites."""
    res = run_lint(tmp_path, {"raft_tpu/serve/mod.py": DEADLOCKY},
                   rules=["lock-order-deadlock"])
    assert rules_at(res) == [("lock-order-deadlock", 10),
                             ("lock-order-deadlock", 22)]
    assert "cycle" in res.findings[0].message
    assert "A._la" in res.findings[0].message
    assert "B._lb" in res.findings[0].message


def test_lock_order_consistent_order_is_clean(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/serve/mod.py": """
        import threading

        class A:
            def __init__(self):
                self._la = threading.Lock()

            def one(self, b):
                with self._la:
                    b.grab()

        class B:
            def __init__(self):
                self._lb = threading.Lock()

            def grab(self):
                with self._lb:
                    pass

            def standalone(self):
                with self._lb:      # never takes la while holding lb
                    return 1
    """}, rules=["lock-order-deadlock"])
    assert res.findings == []


def test_lock_order_self_reacquire_lock_vs_rlock(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/serve/mod.py": """
        import threading

        class Plain:
            def __init__(self):
                self._l = threading.Lock()

            def boom(self):
                with self._l:
                    with self._l:
                        pass

        class Reentrant:
            def __init__(self):
                self._l = threading.RLock()

            def fine(self):
                with self._l:
                    with self._l:
                        pass
    """}, rules=["lock-order-deadlock"])
    assert rules_at(res) == [("lock-order-deadlock", 10)]
    assert "re-acquiring" in res.findings[0].message


def test_lock_order_self_reacquire_through_self_call(tmp_path):
    """Interprocedural self-edge: m() holds the lock and calls a sibling
    method that takes it again — resolved through `self`, no by-name
    guessing."""
    res = run_lint(tmp_path, {"raft_tpu/obs/mod.py": """
        import threading

        class Box:
            def __init__(self):
                self._l = threading.Lock()

            def outer(self):
                with self._l:
                    self.inner()

            def inner(self):
                with self._l:
                    pass
    """}, rules=["lock-order-deadlock"])
    assert rules_at(res) == [("lock-order-deadlock", 10)]


def test_lock_order_by_name_fallback_requires_unique_name(tmp_path):
    """obj.clear() where several classes define clear(): the by-name
    fallback must NOT union all candidates into fabricated cycles."""
    res = run_lint(tmp_path, {"raft_tpu/serve/mod.py": """
        import threading

        class A:
            def __init__(self):
                self._la = threading.Lock()

            def run(self, other):
                with self._la:
                    other.clear()

            def clear(self):
                with self._la:
                    pass

        class B:
            def __init__(self):
                self._lb = threading.Lock()

            def clear(self):
                with self._lb:
                    pass
    """}, rules=["lock-order-deadlock"])
    assert res.findings == []


def test_lock_order_pragma_and_locked_convention(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/serve/mod.py": DEADLOCKY.replace(
        "b.grab()", "b.grab()  # raftlint: disable=lock-order-deadlock")},
        rules=["lock-order-deadlock"])
    # A-side edge suppressed in place; the B-side edge still reported
    assert rules_at(res) == [("lock-order-deadlock", 22)]
    assert res.pragma_suppressed == 1
    # *_locked methods run with "the" class lock already held (single-
    # lock classes): re-taking it inside one is a self-deadlock the seed
    # makes visible
    res2 = run_lint(tmp_path, {"raft_tpu/serve/mod2.py": """
        import threading

        class Box:
            def __init__(self):
                self._l = threading.Lock()

            def peek_locked(self):
                with self._l:      # caller already holds it: deadlock
                    return 1
    """}, rules=["lock-order-deadlock"])
    assert rules_at(res2, "raft_tpu/serve/mod2.py") == [
        ("lock-order-deadlock", 9)]
    assert "re-acquiring" in res2.findings[-1].message


# -- commit ordering (raftlint 2.0, dominance-based) --------------------

def test_commit_ordering_cursor_first_fires(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/jobs/mod.py": """
        from raft_tpu.core.serialize import atomic_write

        def bad(jd, path, payload):
            jd.write_json(path / "cursor.json", {"n": 1})
            atomic_write(path / "data.bin", payload)
    """}, rules=["commit-ordering"])
    assert rules_at(res) == [("commit-ordering", 5)]
    assert "cursor-written-LAST" in res.findings[0].message


def test_commit_ordering_branch_only_artifact_does_not_dominate(tmp_path):
    """An artifact write inside one branch does not protect a cursor
    write after the join — dominance, not lexical order."""
    res = run_lint(tmp_path, {"raft_tpu/jobs/mod.py": """
        from raft_tpu.core.serialize import atomic_write

        def racy(jd, path, payload, fresh):
            if fresh:
                atomic_write(path / "data.bin", payload)
            jd.write_json(path / "cursor.json", {"n": 1})
    """}, rules=["commit-ordering"])
    assert rules_at(res) == [("commit-ordering", 7)]


def test_commit_ordering_dominating_artifact_is_clean(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/jobs/mod.py": """
        from raft_tpu.core.serialize import atomic_write

        def good(jd, path, payload, index):
            index.save(str(path / "ckpt"))
            jd.write_json(path / "cursor.json", {"n": 1})

        def also_good(jd, path, payload, fresh):
            if fresh:
                atomic_write(path / "a.bin", payload)
            else:
                atomic_write(path / "b.bin", payload)
            jd.write_json(path / "progress.json", {"n": 1})
    """}, rules=["commit-ordering"])
    assert res.findings == []


def test_commit_ordering_skips_pure_sidecar_helpers(tmp_path):
    """Functions with no artifact write (JobDir.write_json itself, pure
    config writers) have no intra-function protocol to check."""
    res = run_lint(tmp_path, {"raft_tpu/jobs/mod.py": """
        def write_json(path, obj):
            tmp = str(path) + ".tmp"
            _dump(tmp, obj)

        def sidecar_only(jd, path):
            jd.write_json(path / "cursor.json", {"n": 1})
    """}, rules=["commit-ordering"])
    assert res.findings == []


def test_commit_ordering_pragma_and_baseline(tmp_path):
    files = {"raft_tpu/jobs/mod.py": """
        from raft_tpu.core.serialize import atomic_write

        def known(jd, path, payload):
            jd.write_json(path / "cursor.json", {})  # raftlint: disable=commit-ordering
            atomic_write(path / "data.bin", payload)

        def fresh(jd, path, payload):
            jd.write_json(path / "marker.json", {})
            atomic_write(path / "data.bin", payload)
    """}
    res = run_lint(tmp_path, files, rules=["commit-ordering"])
    assert rules_at(res) == [("commit-ordering", 9)]
    assert res.pragma_suppressed == 1
    base = tmp_path / "base.json"
    write_baseline(str(base), res.findings)
    again = lint_paths([str(tmp_path)], repo_root=str(tmp_path),
                       baseline=str(base), rules=["commit-ordering"])
    assert again.ok and again.baseline_suppressed == 1


# -- engine mechanics ---------------------------------------------------

def test_pragma_multi_rule_and_all(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/util/mod.py": """
        import time
        a = time.time()  # raftlint: disable=hygiene-wallclock
        b = time.time()  # raftlint: disable=all
        c = time.time()  # raftlint: disable=hygiene-bare-except
    """}, rules=["hygiene-wallclock"])
    assert rules_at(res) == [("hygiene-wallclock", 5)]
    assert res.pragma_suppressed == 2


def test_baseline_suppresses_and_reports_stale(tmp_path):
    files = {"raft_tpu/util/mod.py": "import time\nt = time.time()\n"}
    first = run_lint(tmp_path, files, rules=["hygiene-wallclock"])
    assert len(first.findings) == 1
    base = tmp_path / "baseline.json"
    write_baseline(str(base), first.findings
                   + [Finding("raft_tpu/gone.py", 1, 1,
                              "hygiene-wallclock", "already fixed")])
    res = lint_paths([str(tmp_path)], repo_root=str(tmp_path),
                     baseline=str(base), rules=["hygiene-wallclock"])
    assert res.findings == [] and res.ok
    assert res.baseline_suppressed == 1
    assert res.stale_baseline == [
        ("raft_tpu/gone.py", "hygiene-wallclock", "already fixed")]
    # a --rules subset must not report other rules' live entries stale
    other = lint_paths([str(tmp_path)], repo_root=str(tmp_path),
                       baseline=str(base), rules=["hygiene-bare-except"])
    assert other.stale_baseline == []


def test_lint_paths_deterministic(tmp_path):
    files = {
        "raft_tpu/util/a.py": "import time\nx = time.time()\n",
        "raft_tpu/util/b.py": "import time\nx = time.time()\ny = time.time()\n",
    }
    a = run_lint(tmp_path, files, rules=["hygiene-wallclock"]).findings
    b = run_lint(tmp_path, files, rules=["hygiene-wallclock"]).findings
    assert a == b == sorted(a)


# -- CLI ----------------------------------------------------------------

@pytest.fixture()
def cli_tree(tmp_path):
    (tmp_path / "raft_tpu/util").mkdir(parents=True)
    (tmp_path / "raft_tpu/util/mod.py").write_text(
        "import time\nt = time.time()\n")
    return tmp_path


def _cli(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.raftlint"] + args,
        capture_output=True, text=True, cwd=cwd)


def test_cli_json_stable_and_exit_codes(cli_tree):
    args = ["--json", "--no-baseline", "--root", str(cli_tree),
            "--rules", "hygiene-wallclock", str(cli_tree / "raft_tpu")]
    r1, r2 = _cli(args), _cli(args)
    assert r1.returncode == 1 and r2.returncode == 1
    assert r1.stdout == r2.stdout  # byte-stable across runs
    payload = json.loads(r1.stdout)
    assert [f["rule"] for f in payload["findings"]] == ["hygiene-wallclock"]
    f = payload["findings"][0]
    assert f["path"] == "raft_tpu/util/mod.py" and f["line"] == 2
    # sorted output contract
    keys = [(f["path"], f["line"], f["col"]) for f in payload["findings"]]
    assert keys == sorted(keys)
    # clean tree exits 0
    (cli_tree / "raft_tpu/util/mod.py").write_text("x = 1\n")
    assert _cli(args).returncode == 0


def test_cli_write_baseline_refuses_rule_filter(cli_tree):
    """--write-baseline over a rule-filtered run would clobber every
    other rule's grandfathered entries; the CLI refuses."""
    r = _cli(["--write-baseline", "--rules", "hygiene-wallclock",
              "--baseline", str(cli_tree / "b.json"),
              "--root", str(cli_tree), str(cli_tree / "raft_tpu")])
    assert r.returncode == 2
    assert "clobber" in r.stderr
    assert not (cli_tree / "b.json").exists()


def _git(tree, *args):
    return subprocess.run(
        ["git", "-C", str(tree), "-c", "user.email=t@t", "-c",
         "user.name=t", *args], capture_output=True, text=True)


def test_cli_changed_lints_only_the_diff(tmp_path):
    """--changed = merge-base drift + working tree + untracked, scoped
    to the given paths: a dirty file and a fresh file are linted, an
    untouched committed file with a live finding is NOT."""
    tree = tmp_path
    (tree / "raft_tpu/util").mkdir(parents=True)
    dirty = tree / "raft_tpu/util/dirty.py"
    stale = tree / "raft_tpu/util/stale.py"
    dirty.write_text("x = 1\n")
    stale.write_text("import time\nt = time.time()\n")  # pre-existing
    assert _git(tree, "init", "-q").returncode == 0
    _git(tree, "add", "-A")
    assert _git(tree, "commit", "-qm", "seed").returncode == 0
    dirty.write_text("import time\nt = time.time()\n")          # modified
    (tree / "raft_tpu/util/fresh.py").write_text(
        "import time\nt = time.time()\n")                        # untracked
    args = ["--changed", "--no-baseline", "--root", str(tree),
            "--rules", "hygiene-wallclock", str(tree / "raft_tpu")]
    r = _cli(args)
    assert r.returncode == 1, r.stderr
    assert "dirty.py" in r.stdout and "fresh.py" in r.stdout
    assert "stale.py" not in r.stdout
    # committed drift against an explicit base ref is picked up too
    _git(tree, "add", "-A")
    _git(tree, "commit", "-qm", "drift")
    r2 = _cli(["--changed", "HEAD~1"] + args[1:])
    assert r2.returncode == 1
    assert "dirty.py" in r2.stdout and "fresh.py" in r2.stdout
    assert "stale.py" not in r2.stdout
    # a fully clean diff is a no-op success, not a usage error
    r3 = _cli(["--changed", "HEAD", "--no-baseline", "--root", str(tree),
               str(tree / "raft_tpu")])
    assert r3.returncode == 0
    assert "nothing to lint" in r3.stderr


def test_cli_changed_bad_base_ref_is_usage_error(tmp_path):
    """A typo'd BASE (or a path operand swallowed into BASE position)
    must fail loudly, not silently anchor at HEAD and skip every
    committed drift (review finding)."""
    (tmp_path / "raft_tpu").mkdir(parents=True)
    (tmp_path / "raft_tpu/mod.py").write_text("x = 1\n")
    assert _git(tmp_path, "init", "-q").returncode == 0
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    r = _cli(["--changed", "no-such-ref", "--root", str(tmp_path),
              str(tmp_path / "raft_tpu")])
    assert r.returncode == 2
    assert "does not resolve" in r.stderr


def test_cli_changed_outside_git_is_usage_error(tmp_path):
    (tmp_path / "raft_tpu").mkdir(parents=True)
    (tmp_path / "raft_tpu/mod.py").write_text("x = 1\n")
    r = _cli(["--changed", "--root", str(tmp_path),
              str(tmp_path / "raft_tpu")])
    assert r.returncode == 2
    assert "git repository" in r.stderr


def test_cli_unknown_rule_is_usage_error(cli_tree):
    r = _cli(["--rules", "no-such-rule", "--root", str(cli_tree),
              str(cli_tree / "raft_tpu")])
    assert r.returncode == 2
    assert "unknown rule" in r.stderr


def test_cli_list_rules_names_every_family():
    r = _cli(["--list-rules"])
    assert r.returncode == 0
    for fam in ("trace-host-effect", "trace-nondeterminism",
                "trace-host-sync", "trace-try-except", "lock-discipline",
                "fault-site-unknown", "fault-site-unused", "layer-purity",
                "hygiene-bare-except", "hygiene-wallclock",
                "hygiene-raw-write", "hygiene-untyped-raise",
                "hygiene-float64",
                # raftlint 2.0 CFG/interprocedural families
                "collective-divergence", "collective-order",
                "lock-order-deadlock", "commit-ordering",
                # raftlint 3.0 kernelcheck + tuned registry families
                "kernel-vmem-envelope", "kernel-blockspec-consistency",
                "kernel-dtype-flow", "dispatch-envelope-guard",
                "tuned-key-registry",
                # raftlint 4.0 statecheck families
                "cache-key-completeness", "ckpt-schema-registry"):
        assert fam in r.stdout, fam


def test_parse_error_is_a_finding(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/util/broken.py": "def f(:\n"},
                   registry=False)
    assert [f.rule for f in res.findings] == ["parse-error"]


# -- end-to-end contracts ----------------------------------------------

def test_repo_lints_clean_end_to_end():
    """The acceptance bar: the linter exits 0 over the whole repo (after
    fixes/pragmas/baseline). A regression anywhere in the library fails
    here with the precise finding in the assert message."""
    res = lint_paths(["raft_tpu", "bench", "tests", "tools"], repo_root=REPO)
    assert res.ok, "\n" + "\n".join(f.format() for f in res.findings)


def test_check_style_delegates_greps_to_raftlint():
    """The four grep gates must live in raftlint now: reintroducing them
    as greps (or dropping the raftlint invocation) fails here."""
    sh = open(os.path.join(REPO, "ci", "check_style.sh")).read()
    assert "tools.raftlint" in sh
    for gone in ("except[[:space:]]*:", "time\\.time", "os\\.rename",
                 "'wb'"):
        assert gone not in sh, f"grep gate {gone!r} should live in raftlint"


# -- FAULT_SITES drift --------------------------------------------------

def _drill_sites(path):
    """Site literals exercised by Fault(...) constructions in a test
    file (site= keyword or second positional)."""
    tree = ast.parse(open(path).read())
    sites = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and getattr(node.func, "attr", getattr(node.func, "id", None))
                == "Fault"):
            continue
        expr = None
        for kw in node.keywords:
            if kw.arg == "site":
                expr = kw.value
        if expr is None and len(node.args) > 1:
            expr = node.args[1]
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            sites.add(expr.value)
    return sites


def test_fault_sites_match_chaos_drills_exactly():
    """Drift test: FAULT_SITES == the union of sites the chaos drills
    actually install faults at (test_resilience + test_replication, plus
    test_serve for the serving sites). A site registered but never
    drilled — or drilled but unregistered — fails here."""
    from raft_tpu.core import faults

    exercised = set()
    for name in ("test_resilience.py", "test_replication.py",
                 "test_serve.py", "test_jobs.py", "test_mutation.py",
                 "test_trace.py", "test_integrity.py"):
        exercised |= _drill_sites(os.path.join(REPO, "tests", name))
    known = set(faults.known_sites())
    expanded = set()
    for s in exercised:
        if any(c in s for c in "*?["):
            expanded |= set(fnmatch.filter(sorted(known), s))
        else:
            expanded.add(s)
    assert expanded == known, (
        f"undrilled registry sites: {sorted(known - expanded)}; "
        f"unregistered drill sites: {sorted(expanded - known)}")


def test_fault_sites_registry_renders_docstring():
    from raft_tpu.core import faults

    assert faults.known_sites() == tuple(sorted(faults.FAULT_SITES))
    for site in faults.known_sites():
        assert site in faults.__doc__


# -- kernelcheck (raftlint 3.0) -----------------------------------------

MINI_TUNED_REGISTRY = """
TUNED_KEYS = {
    "good_key": {"kind": "choice", "choices": ("a", "b"),
                 "bench": "bench/bench_mini.py"},
    "num_key": {"kind": "int", "choices": None, "bench": None},
    "hints": {"kind": "hints", "choices": None, "bench": None},
}
"""

MINI_KERNEL_MODULE = """
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128

KERNEL_ENVELOPES = {
    "scan": ("fits_scan", {}),
}


def fits_scan(chunk, L, k):
    step = (
        4 * chunk * L        # score tile
        + 4 * chunk * L      # slot plane
        + 4 * chunk * 128    # query rows
        + 8 * chunk * 128    # output buffers
    )
    return L % _LANES == 0 and step <= 10 * 1024 * 1024


def _make_kernel(k):
    def kernel(q_ref, store_ref, vals_ref, idx_ref):
        dots = lax.dot_general(
            q_ref[:].astype(jnp.bfloat16),
            store_ref[:].astype(jnp.bfloat16),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        slot = lax.broadcasted_iota(jnp.int32, dots.shape, 1)
        vals_ref[:] = dots
        idx_ref[:] = slot
    return kernel


def scan(q, store, k, chunk=128):
    nq, rot = q.shape
    L = store.shape[0]
    if q.dtype != jnp.float32 or store.dtype != jnp.float32:
        raise ValueError("f32 operands")
    vals, idx = pl.pallas_call(
        _make_kernel(int(k)),
        grid=(nq // chunk, 1),
        in_specs=[
            pl.BlockSpec((chunk, 128), lambda i, j: (i, 0)),
            pl.BlockSpec((L, 128), lambda i, j: (0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((chunk, 128), lambda i, j: (i, 0)),
            pl.BlockSpec((chunk, 128), lambda i, j: (i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((nq, 128), jnp.float32),
            jax.ShapeDtypeStruct((nq, 128), jnp.int32),
        ),
    )(q, store)
    return vals, idx
"""


def test_kernel_vmem_envelope_clean_on_matching_pair(tmp_path):
    src = MINI_KERNEL_MODULE.replace(
        "+ 4 * chunk * 128    # query rows",
        "+ 4 * chunk * 128 + 4 * L * 128",
    )
    res = run_lint(tmp_path, {"raft_tpu/ops/mini.py": src},
                   rules=["kernel-vmem-envelope"])
    assert res.findings == []


def test_kernel_vmem_envelope_under_charge_fires_at_envelope(tmp_path):
    """The acceptance fixture: the envelope under-charges its kernel by
    ONE buffer (the store block, L x 128 f32, is never charged)."""
    res = run_lint(tmp_path, {"raft_tpu/ops/mini.py": MINI_KERNEL_MODULE},
                   rules=["kernel-vmem-envelope"])
    assert res.findings, "missing-buffer envelope must fire"
    f = res.findings[0]
    assert f.rule == "kernel-vmem-envelope"
    assert "under-charges" in f.message and "fits_scan" in f.message
    # anchored at the envelope def (the formula is what needs fixing)
    assert "def fits_scan" in (tmp_path / "raft_tpu/ops/mini.py") \
        .read_text().splitlines()[f.line - 1]


def test_kernel_vmem_envelope_fails_closed_on_unanalyzable_body(tmp_path):
    # a kernel the interpreter cannot resolve (functools.partial) must
    # not turn the gate green unverified: no dot/store was checked
    src = MINI_KERNEL_MODULE.replace(
        "_make_kernel(int(k)),", "functools.partial(_make_kernel, int(k)),"
    ).replace("import jax\n", "import functools\nimport jax\n")
    res = run_lint(tmp_path, {"raft_tpu/ops/mini.py": src},
                   rules=["kernel-vmem-envelope"])
    assert any("kernel body not analyzable" in f.message
               for f in res.findings), [f.message for f in res.findings]


def test_kernel_vmem_envelope_fails_closed_and_coverage(tmp_path):
    # a registered wrapper that does not exist, and a pallas wrapper
    # that is not registered, both fire
    src = MINI_KERNEL_MODULE.replace(
        '"scan": ("fits_scan", {}),', '"ghost": ("fits_scan", {}),')
    res = run_lint(tmp_path, {"raft_tpu/ops/mini.py": src},
                   rules=["kernel-vmem-envelope"])
    msgs = " | ".join(f.message for f in res.findings)
    assert "no such function" in msgs  # ghost pairing
    assert "not paired with an envelope" in msgs  # scan uncovered


def test_kernel_vmem_envelope_pragma_and_baseline(tmp_path):
    src = MINI_KERNEL_MODULE.replace(
        "def fits_scan(chunk, L, k):",
        "def fits_scan(chunk, L, k):  # raftlint: disable=kernel-vmem-envelope",
    )
    res = run_lint(tmp_path, {"raft_tpu/ops/mini.py": src},
                   rules=["kernel-vmem-envelope"])
    assert res.findings == [] and res.pragma_suppressed >= 1
    # baseline: grandfather the raw finding
    raw = run_lint(tmp_path / "b", {"raft_tpu/ops/mini.py": MINI_KERNEL_MODULE},
                   rules=["kernel-vmem-envelope"])
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), raw.findings)
    res2 = run_lint(tmp_path / "b", {"raft_tpu/ops/mini.py": MINI_KERNEL_MODULE},
                    rules=["kernel-vmem-envelope"], baseline=str(bl))
    assert res2.findings == [] and res2.baseline_suppressed >= 1


def test_blockspec_consistency_arity_rank_and_out_dtype(tmp_path):
    bad = MINI_KERNEL_MODULE.replace(
        "pl.BlockSpec((L, 128), lambda i, j: (0, 0)),",
        "pl.BlockSpec((L, 128), lambda i: (0, 0)),",
    ).replace(
        "jax.ShapeDtypeStruct((nq, 128), jnp.int32),",
        "jax.ShapeDtypeStruct((nq, 128), jnp.bfloat16),",
    )
    res = run_lint(tmp_path, {"raft_tpu/ops/mini.py": bad},
                   rules=["kernel-blockspec-consistency"])
    msgs = " | ".join(f.message for f in res.findings)
    assert "index_map takes" in msgs and "calls it with 2" in msgs
    assert "declares bfloat16 but the kernel body finally stores int32" \
        in msgs


def test_blockspec_consistency_index_map_result_rank(tmp_path):
    bad = MINI_KERNEL_MODULE.replace(
        "pl.BlockSpec((chunk, 128), lambda i, j: (i, 0)),\n"
        "            pl.BlockSpec((L, 128), lambda i, j: (0, 0)),",
        "pl.BlockSpec((chunk, 128), lambda i, j: (i, 0, 0)),\n"
        "            pl.BlockSpec((L, 128), lambda i, j: (0, 0)),",
    )
    res = run_lint(tmp_path, {"raft_tpu/ops/mini.py": bad},
                   rules=["kernel-blockspec-consistency"])
    assert any("returns 3 coordinates for a rank-2 block" in f.message
               for f in res.findings)


def test_blockspec_consistency_negative_and_pragma(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/ops/mini.py": MINI_KERNEL_MODULE},
                   rules=["kernel-blockspec-consistency"])
    assert res.findings == []
    bad = MINI_KERNEL_MODULE.replace(
        "pl.BlockSpec((L, 128), lambda i, j: (0, 0)),",
        "pl.BlockSpec((L, 128), lambda i: (0, 0)),  "
        "# raftlint: disable=kernel-blockspec-consistency",
    )
    res2 = run_lint(tmp_path / "p", {"raft_tpu/ops/mini.py": bad},
                    rules=["kernel-blockspec-consistency"])
    assert res2.findings == [] and res2.pragma_suppressed >= 1


def test_kernel_dtype_flow_f32_dot_and_preferred(tmp_path):
    bad = MINI_KERNEL_MODULE.replace(
        "q_ref[:].astype(jnp.bfloat16),", "q_ref[:],")
    res = run_lint(tmp_path, {"raft_tpu/ops/mini.py": bad},
                   rules=["kernel-dtype-flow"])
    assert res.findings and all(f.rule == "kernel-dtype-flow"
                                for f in res.findings)
    assert "(float32, bfloat16)" in res.findings[0].message
    # wrong accumulator dtype also fires
    bad2 = MINI_KERNEL_MODULE.replace(
        "preferred_element_type=jnp.float32,",
        "preferred_element_type=jnp.bfloat16,")
    res2 = run_lint(tmp_path / "b", {"raft_tpu/ops/mini.py": bad2},
                    rules=["kernel-dtype-flow"])
    assert any("must accumulate to float32" in f.message
               for f in res2.findings)


def test_kernel_dtype_flow_popcount_and_unregistered_exempt(tmp_path):
    bad = MINI_KERNEL_MODULE.replace(
        "slot = lax.broadcasted_iota(jnp.int32, dots.shape, 1)",
        "slot = lax.population_count("
        "lax.broadcasted_iota(jnp.int32, dots.shape, 1))",
    )
    res = run_lint(tmp_path, {"raft_tpu/ops/mini.py": bad},
                   rules=["kernel-dtype-flow"])
    assert any("population_count over int32" in f.message
               for f in res.findings)
    # the same f32 dot in an UNREGISTERED module stays silent: the
    # full-precision kernels (pairwise_pallas, fused_l2_argmin) are f32
    # by design
    unreg = MINI_KERNEL_MODULE.replace("KERNEL_ENVELOPES = {", "IGNORED = {") \
        .replace("q_ref[:].astype(jnp.bfloat16),", "q_ref[:],")
    res2 = run_lint(tmp_path / "u", {"raft_tpu/ops/unreg.py": unreg},
                    rules=["kernel-dtype-flow"])
    assert res2.findings == []


# -- dispatch-envelope-guard --------------------------------------------

GUARDED_ENGINE = """
from raft_tpu.ops.fused_scan import fits_fused_list, fused_list_topk


def search(store, qres, k):
    if not fits_fused_list(128, store.shape[1], store.shape[2], k):
        raise ValueError("past the envelope")
    return fused_list_topk(None, qres, store, None, k)
"""

MINI_SELECT_K = """
from raft_tpu.ops.fused_scan import fits_fused_list, fused_list_topk


def resolve_int8_trim_strategy(L, rot, k):
    if fits_fused_list(128, L, rot, k):
        return "fused_int8"
    return None


def list_scan_select_k(lof, qres, store, base, k):
    return fused_list_topk(lof, qres, store, base, k)
"""

STRATEGY_ENGINE = """
from raft_tpu.matrix.select_k import (
    list_scan_select_k, resolve_int8_trim_strategy,
)


def search(store, qres, k, engine="auto"):
    if engine == "fused_int8":
        checked = resolve_int8_trim_strategy(128, 96, k)
        strat = "fused_int8"
    elif engine == "auto":
        strat = resolve_int8_trim_strategy(128, 96, k)
    else:
        strat = "xla"
    if strat == "fused_int8":
        return list_scan_select_k(None, qres, store, None, k)
    return None
"""


def test_dispatch_guard_unguarded_public_call_fires(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/neighbors/eng.py": """
        from raft_tpu.ops.fused_scan import fused_list_topk

        def search(store, qres, k):
            return fused_list_topk(None, qres, store, None, k)
    """}, rules=["dispatch-envelope-guard"])
    assert [f.rule for f in res.findings] == ["dispatch-envelope-guard"]
    assert "fused_list_topk" in res.findings[0].message


def test_dispatch_guard_dominating_fits_raise_is_clean(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/neighbors/eng.py": GUARDED_ENGINE},
                   rules=["dispatch-envelope-guard"])
    assert res.findings == []


def test_dispatch_guard_strategy_literal_reaching_defs(tmp_path):
    # every reaching assignment of `strat` is benign or validated
    res = run_lint(tmp_path, {"raft_tpu/neighbors/eng.py": STRATEGY_ENGINE,
                              "raft_tpu/matrix/select_k.py": MINI_SELECT_K},
                   rules=["dispatch-envelope-guard"])
    assert res.findings == []
    # ... but a fused literal assigned with NO guard poisons the branch
    bad = STRATEGY_ENGINE.replace(
        "        checked = resolve_int8_trim_strategy(128, 96, k)\n"
        '        strat = "fused_int8"\n',
        '        strat = "fused_int8"\n',
    )
    res2 = run_lint(tmp_path / "b",
                    {"raft_tpu/neighbors/eng.py": bad,
                     "raft_tpu/matrix/select_k.py": MINI_SELECT_K},
                    rules=["dispatch-envelope-guard"])
    assert [f.rule for f in res2.findings] == ["dispatch-envelope-guard"]


def test_dispatch_guard_private_impl_propagates_to_callers(tmp_path):
    files = {"raft_tpu/neighbors/eng.py": """
        from raft_tpu.ops.fused_scan import fits_fused_list, fused_list_topk

        def _impl(store, qres, k):
            return fused_list_topk(None, qres, store, None, k)

        def search(store, qres, k):
            if not fits_fused_list(128, 1024, 96, k):
                raise ValueError("past the envelope")
            return _impl(store, qres, k)
    """}
    res = run_lint(tmp_path, dict(files), rules=["dispatch-envelope-guard"])
    assert res.findings == []
    # a second, unguarded caller breaks the proof — the finding anchors
    # at the routing call inside the impl
    files["raft_tpu/neighbors/eng.py"] += (
        "\n\n        def fast_path(store, qres, k):\n"
        "            return _impl(store, qres, k)\n")
    res2 = run_lint(tmp_path / "b", files,
                    rules=["dispatch-envelope-guard"])
    assert [f.rule for f in res2.findings] == ["dispatch-envelope-guard"]
    assert "fused_list_topk" in res2.findings[0].message


def test_dispatch_guard_scope_and_pragma(tmp_path):
    # ops/ is the kernel layer itself: exempt
    res = run_lint(tmp_path, {"raft_tpu/ops/inner.py": """
        from raft_tpu.ops.fused_scan import fused_list_topk

        def helper(store, qres, k):
            return fused_list_topk(None, qres, store, None, k)
    """}, rules=["dispatch-envelope-guard"])
    assert res.findings == []
    res2 = run_lint(tmp_path / "p", {"raft_tpu/neighbors/eng.py": """
        from raft_tpu.ops.fused_scan import fused_list_topk

        def search(store, qres, k):
            return fused_list_topk(None, qres, store, None, k)  # raftlint: disable=dispatch-envelope-guard
    """}, rules=["dispatch-envelope-guard"])
    assert res2.findings == [] and res2.pragma_suppressed == 1


# -- tuned-key-registry --------------------------------------------------

def run_tuned_lint(tmp_path, files, **kw):
    files = dict(files)
    files.setdefault("raft_tpu/core/tuned.py", MINI_TUNED_REGISTRY)
    # a reader of every registered fixture key, so the unused-entry
    # check stays quiet unless a test removes a read on purpose
    files.setdefault("raft_tpu/matrix/_readers.py", """
        from raft_tpu.core import tuned

        def consult():
            return (tuned.get("good_key"), tuned.get("num_key"),
                    tuned.hints())
    """)
    return run_lint(tmp_path, files, rules=["tuned-key-registry"], **kw)


def test_tuned_key_unknown_read_fires(tmp_path):
    res = run_tuned_lint(tmp_path, {"raft_tpu/matrix/mod.py": """
        from raft_tpu.core import tuned

        def resolve():
            a = tuned.get("good_key")
            b = tuned.get_choice("good_kye", ("a", "b"), "a")
            return a, b
    """})
    assert len(res.findings) == 1
    assert "good_kye" in res.findings[0].message


def test_tuned_key_const_resolution_and_bad_const(tmp_path):
    res = run_tuned_lint(tmp_path, {"raft_tpu/neighbors/mod.py": """
        from raft_tpu.core import tuned

        POLICY_KEY = "good_key"
        BAD_KEY = "not_registered"

        def resolve():
            return tuned.get(POLICY_KEY)
    """})
    # the read through POLICY_KEY resolves and is registered; the BAD
    # constant itself fires (the dedupe contract)
    assert len(res.findings) == 1
    assert "BAD_KEY" in res.findings[0].message


def test_tuned_key_hints_idiom_enforced(tmp_path):
    res = run_tuned_lint(tmp_path, {"raft_tpu/comms/mod.py": """
        from raft_tpu.core import tuned

        def resolve():
            return tuned.get("hints") or {}
    """})
    assert len(res.findings) == 1
    assert "tuned.hints()" in res.findings[0].message


def test_tuned_key_writer_typo_and_bad_choice(tmp_path):
    """The acceptance fixture: an --apply writer writes a typo'd key
    (and, separately, a value outside the registered choice set)."""
    res = run_tuned_lint(tmp_path, {"bench/bench_mini.py": """
        from raft_tpu.core import tuned

        def apply_winners(w):
            updates = {"good_kye": "a", "num_key": 7}
            updates["good_key"] = "z"
            tuned.merge(dict(updates, hints={"measured_on": "cpu"}))
    """})
    msgs = sorted(f.message for f in res.findings)
    assert len(res.findings) == 2
    assert any("unregistered tuned key 'good_kye'" in m for m in msgs)
    assert any("writes 'z' to 'good_key'" in m for m in msgs)


def test_tuned_key_unused_fires_on_whole_scan_only(tmp_path):
    # the default fixture reader is overridden with one that skips
    # num_key: the registry entry goes dead
    reader = {"raft_tpu/matrix/_readers.py": """
        from raft_tpu.core import tuned

        def resolve():
            return tuned.get("good_key"), tuned.hints()
    """}
    res = run_tuned_lint(tmp_path, dict(reader))
    assert [f.message for f in res.findings] == [
        "registered tuned key 'num_key' is never read by any dispatch "
        "path or written by any bench — dead registry entry"]
    # partial scan (no raft_tpu/__init__.py): no basis to call keys dead
    files = dict(reader)
    files["raft_tpu/core/tuned.py"] = MINI_TUNED_REGISTRY
    for rel, src in files.items():
        p = tmp_path / "partial" / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    res2 = lint_paths([str(tmp_path / "partial" / "raft_tpu/matrix")],
                      repo_root=str(tmp_path / "partial"), baseline=None,
                      rules=["tuned-key-registry"])
    assert res2.findings == []


def test_tuned_key_registry_fails_closed_when_missing(tmp_path):
    res = run_lint(tmp_path, {"raft_tpu/matrix/mod.py": """
        from raft_tpu.core import tuned

        def resolve():
            return tuned.get("anything")
    """}, rules=["tuned-key-registry"])
    assert len(res.findings) == 1
    assert "TUNED_KEYS registry missing" in res.findings[0].message


def test_tuned_key_pragma_and_baseline(tmp_path):
    src = {"raft_tpu/matrix/mod.py": """
        from raft_tpu.core import tuned

        def resolve():
            return tuned.get("experimental_key")  # raftlint: disable=tuned-key-registry
    """}
    res = run_tuned_lint(tmp_path, src)
    assert res.findings == [] and res.pragma_suppressed == 1
    raw_src = {"raft_tpu/matrix/mod.py": src["raft_tpu/matrix/mod.py"]
               .replace("  # raftlint: disable=tuned-key-registry", "")}
    raw = run_tuned_lint(tmp_path / "b", raw_src)
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), raw.findings)
    res2 = run_tuned_lint(tmp_path / "b", raw_src, baseline=str(bl))
    assert res2.findings == [] and res2.baseline_suppressed == 1


# -- the mutation smoke test over the REAL modules ----------------------
#
# The acceptance contract: perturbing one BlockSpec width, one envelope
# constant, one dot operand dtype, one dispatch guard, and one tuned-key
# literal in the real sources each yields exactly the expected finding —
# and the unmutated copies lint clean under the same rule. This is the
# proof the abstract interpreter actually covers the production kernels,
# not just fixtures.

_MUTATIONS = [
    ("blockspec-width",
     ["raft_tpu/ops/fused_scan.py"],
     "raft_tpu/ops/fused_scan.py",
     "pl.BlockSpec((bq, d_pad), lambda i, j: (i, 0),",
     "pl.BlockSpec((2 * bq, d_pad), lambda i, j: (i, 0),",
     "kernel-vmem-envelope", "under-charges"),
    ("envelope-constant",
     ["raft_tpu/ops/fused_scan.py"],
     "raft_tpu/ops/fused_scan.py",
     "+ 2 * (bq + bn) * d_pad",
     "+ 1 * (bq + bn) * d_pad",
     "kernel-vmem-envelope", "under-charges"),
    ("dot-operand-dtype",
     ["raft_tpu/ops/fused_scan.py"],
     "raft_tpu/ops/fused_scan.py",
     "q.astype(jnp.bfloat16),",
     "q.astype(jnp.float32),",
     "kernel-dtype-flow", "(float32, bfloat16)"),
    ("dispatch-guard",
     ["raft_tpu/ops/fused_scan.py", "raft_tpu/matrix/select_k.py",
      "raft_tpu/neighbors/ivf_flat.py"],
     "raft_tpu/neighbors/ivf_flat.py",
     "if not _pallas_fits(index, k):",
     "if False:",
     "dispatch-envelope-guard", "list_scan_select_k"),
    ("tuned-key-literal",
     ["raft_tpu/core/tuned.py", "bench/bench_pallas_scan.py"],
     "bench/bench_pallas_scan.py",
     'tuned.merge({"pallas_fold": winner})',
     'tuned.merge({"palas_fold": winner})',
     "tuned-key-registry", "palas_fold"),
    # raftlint 4.0 statecheck: delete one field from a real
    # _cached_wrapper key tuple -> the PR-1/4/12 stale-program class
    ("cache-key-field-deleted",
     ["raft_tpu/comms/mnmg_ivf_search.py", "raft_tpu/comms/mnmg_common.py"],
     "raft_tpu/comms/mnmg_ivf_search.py",
     "            n_probes, refine, refine_merged, pf_n, per_cluster, "
     "adaptive_on,\n            qcfg),",
     "            n_probes, refine, refine_merged, pf_n, per_cluster, "
     "qcfg),",
     "cache-key-completeness", "'adaptive_on'"),
    # save an index attribute the registry has never heard of
    ("ckpt-unregistered-save-field",
     ["raft_tpu/core/serialize.py", "raft_tpu/neighbors/ivf_flat.py"],
     "raft_tpu/neighbors/ivf_flat.py",
     '"source_ids": index.source_ids,',
     '"source_ids": index.source_ids, "magnet": index.centers,',
     "ckpt-schema-registry", "'magnet'"),
    # drop a registered-optional field's legacy-load fallback
    ("ckpt-load-fallback-dropped",
     ["raft_tpu/core/serialize.py", "raft_tpu/neighbors/ivf_flat.py"],
     "raft_tpu/neighbors/ivf_flat.py",
     'index.list_radii = arrays.get("list_radii")',
     'index.list_radii = arrays["list_radii"]',
     "ckpt-schema-registry", "UNGUARDED"),
]


@pytest.mark.parametrize(
    "label,copies,target,old,new,rule_name,needle",
    _MUTATIONS, ids=[m[0] for m in _MUTATIONS])
def test_mutation_smoke_real_sources(tmp_path, label, copies, target, old,
                                     new, rule_name, needle):
    import shutil

    for rel in copies:
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(REPO, rel), dst)
    clean = lint_paths([str(tmp_path)], repo_root=str(tmp_path),
                       baseline=None, rules=[rule_name])
    assert clean.findings == [], \
        "unmutated copies must lint clean:\n" + "\n".join(
            f.format() for f in clean.findings)
    src = (tmp_path / target).read_text()
    assert old in src, f"mutation anchor drifted: {old!r}"
    (tmp_path / target).write_text(src.replace(old, new, 1))
    mutated = lint_paths([str(tmp_path)], repo_root=str(tmp_path),
                         baseline=None, rules=[rule_name])
    assert mutated.findings, f"{label}: mutation must fire {rule_name}"
    assert all(f.rule == rule_name for f in mutated.findings)
    assert any(needle in f.message for f in mutated.findings), \
        "\n".join(f.format() for f in mutated.findings)
