"""Replication & self-healing chaos drills: r-way ring placement,
lossless bit-identical failover through r-1 rank failures, repair +
verified rank rejoin, CRC-checksummed checkpoints healing from peer
mirror slices, and the serving engine's between-batch heal loop. Runs
on a 4-rank submesh of the virtual 8-device CPU mesh;
`RAFT_TPU_FAULT_SEED` pins the chaos seed (ci/test.sh chaos replays a
3-seed matrix)."""

import json
import os
import struct

import numpy as np
import pytest

from raft_tpu import obs
from raft_tpu.comms import Comms, mnmg, recovery, replication, resilience
from raft_tpu.comms.resilience import DegradedSearchResult, RankHealth
from raft_tpu.core import faults
from raft_tpu.core.serialize import ChecksumError
from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.random import make_blobs

SEED = int(os.environ.get(faults.ENV_SEED, "1234"))
WORLD = 4


@pytest.fixture(scope="module")
def comms4():
    return Comms(n_devices=WORLD)


@pytest.fixture(scope="module")
def blobs():
    data, _ = make_blobs(1600, 16, n_clusters=6, cluster_std=0.4, seed=13)
    return np.asarray(data)


def _build_flat(comms4, blobs, replication=2):
    return mnmg.ivf_flat_build(
        comms4, ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4), blobs,
        replication=replication)


def _build_pq(comms4, blobs, replication=2):
    return mnmg.ivf_pq_build(
        comms4, ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=4),
        blobs, replication=replication)


@pytest.fixture(scope="module")
def flat_r2(comms4, blobs):
    return _build_flat(comms4, blobs)


@pytest.fixture(scope="module")
def pq_r2(comms4, blobs):
    return _build_pq(comms4, blobs)


def _poison_primary(comms4, index, rank: int):
    """Overwrite `rank`'s primary store on device with garbage — the
    concrete simulation of a lost/poisoned shard, so a drill proves the
    failover/repair path actually serves from the replica copies (a
    masked-but-intact primary would hide a failover that silently reads
    the primary)."""
    name = "codes" if hasattr(index, "codes") else "list_data"
    a = np.array(np.asarray(getattr(index, name)))
    a[rank] = 0
    setattr(index, name, comms4.shard(a, axis=0))
    g = np.array(np.asarray(index.slot_gids))
    g[rank] = -1
    index.slot_gids = comms4.shard(g, axis=0)
    # drop lazily-derived stores built from the now-poisoned tables
    replication._reset_derived_stores(index)


def _surviving_prefilter(index, dead_rank: int) -> np.ndarray:
    hg = np.asarray(index.host_gids[dead_rank])
    mask = np.ones(index.n, bool)
    mask[hg[hg >= 0]] = False
    return mask


# -- placement ----------------------------------------------------------

def test_replica_placement_ring():
    p = replication.ReplicaPlacement(4, 2)
    assert p.holders(1) == (2,)
    assert p.hosted(2) == (1,)
    assert p.slot(2, 1) == 0
    p3 = replication.ReplicaPlacement(4, 3)
    assert p3.holders(3) == (0, 1)
    assert p3.hosted(0) == (3, 2)
    assert p3.slot(1, 3) == 1
    with pytest.raises(ValueError, match="holds no replica"):
        p3.slot(2, 3)
    with pytest.raises(ValueError, match="replication factor"):
        replication.ReplicaPlacement(4, 5)
    with pytest.raises(ValueError, match="replication factor"):
        replication.ReplicaPlacement(4, 0)


def test_election_is_primary_order_and_total():
    p = replication.ReplicaPlacement(4, 3)
    h = RankHealth.all_healthy(4).mark_unhealthy(1)
    assert p.elect(1, h) == 2  # first holder in ring order
    h.mark_unhealthy(2)
    assert p.elect(1, h) == 3  # next holder when the first is dead too
    assert p.elect(2, h) == 3
    # stale holders are skipped like dead ones
    assert p.elect(1, h, stale=(3,)) is None
    a = p.assignment(h)
    assert a == {1: 3, 2: 3}
    # every caller computes the identical assignment (pure function)
    assert a == p.assignment(RankHealth(h.mask.copy()))


# -- lossless failover --------------------------------------------------

def test_failover_flat_bit_identical(comms4, blobs):
    """Acceptance drill: r=2, one rank killed mid-stream — the search
    returns BIT-IDENTICAL results to the all-healthy run at coverage
    1.0, served from the replica copy (the primary is poisoned to prove
    the replica actually answers)."""
    index = _build_flat(comms4, blobs)
    q = blobs[:23]
    v0, i0 = mnmg.ivf_flat_search(index, q, 5, n_probes=8)
    _poison_primary(comms4, index, 1)
    plan = faults.FaultPlan([faults.Fault(kind="kill_rank", rank=1)],
                            seed=SEED)
    with plan.install():
        health = resilience.probe_health(comms4, timeout_s=30)
        res = mnmg.ivf_flat_search(index, q, 5, n_probes=8, health=health)
    assert isinstance(res, DegradedSearchResult)
    assert res.coverage == 1.0
    assert res.repaired_ranks == (1,)
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(res.values), np.asarray(v0))


def test_failover_pq_bit_identical_and_cached(pq_r2, comms4, blobs):
    q = blobs[:23]
    v0, i0 = mnmg.ivf_pq_search(pq_r2, q, 5, n_probes=8)
    health = RankHealth.all_healthy(WORLD).mark_unhealthy(2)
    res = mnmg.ivf_pq_search(pq_r2, q, 5, n_probes=8, health=health)
    assert res.coverage == 1.0 and res.repaired_ranks == (2,)
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(res.values), np.asarray(v0))
    # the patched view is cached per failure pattern: a second degraded
    # search reuses it (identity), so steady-state failover costs what a
    # healthy search costs
    key = next(iter(pq_r2.replicas._views))
    view0 = pq_r2.replicas._views[key][0]
    res2 = mnmg.ivf_pq_search(pq_r2, q, 5, n_probes=8, health=health)
    assert pq_r2.replicas._views[key][0] is view0
    np.testing.assert_array_equal(np.asarray(res2.ids), np.asarray(i0))


def test_failover_knn_bit_identical(comms4, blobs):
    q = blobs[:17]
    v0, i0 = mnmg.knn(comms4, blobs, q, 10)
    health = RankHealth.all_healthy(WORLD).mark_unhealthy(3)
    res = mnmg.knn(comms4, blobs, q, 10, health=health, replication=2)
    assert res.coverage == 1.0 and res.repaired_ranks == (3,)
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(res.values), np.asarray(v0))
    # without replication the same mask still degrades (PR 1 contract)
    deg = mnmg.knn(comms4, blobs, q, 10, health=health)
    assert deg.coverage == 0.75 and deg.repaired_ranks == ()


def test_beyond_r_failures_degrade(flat_r2, comms4, blobs):
    """r-1 = 1 extra failure: adjacent ranks 1,2 dead under r=2 — shard
    1's only holder (2) is dead, so the old degraded path engages for
    it, while shard 2 still fails over to rank 3."""
    q = blobs[:23]
    health = RankHealth.all_healthy(WORLD).mark_unhealthy(1).mark_unhealthy(2)
    res = mnmg.ivf_flat_search(flat_r2, q, 5, n_probes=8, health=health)
    assert res.coverage == 0.75
    assert res.repaired_ranks == (2,)
    # reference: prefilter ONLY the lost shard's rows on a healthy mesh
    rv, ri = mnmg.ivf_flat_search(
        flat_r2, q, 5, n_probes=8,
        prefilter=_surviving_prefilter(flat_r2, 1))
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(ri))
    np.testing.assert_array_equal(np.asarray(res.values), np.asarray(rv))


def test_stale_replica_site_fails_election(flat_r2, comms4, blobs):
    """A kill_rank fault at site "replica.stale" declares a holder's
    copies unusable: with r=2 the shard is lost (degraded), the holder
    itself keeps serving its own shard."""
    q = blobs[:23]
    health = RankHealth.all_healthy(WORLD).mark_unhealthy(1)
    plan = faults.FaultPlan(
        [faults.Fault(kind="kill_rank", site="replica.stale", rank=2)],
        seed=SEED)
    with plan.install():
        res = mnmg.ivf_flat_search(flat_r2, q, 5, n_probes=8, health=health)
    assert res.coverage == 0.75 and res.repaired_ranks == ()
    rv, ri = mnmg.ivf_flat_search(
        flat_r2, q, 5, n_probes=8,
        prefilter=_surviving_prefilter(flat_r2, 1))
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(ri))


def test_failover_preserves_sharded_query_mode(flat_r2, comms4, blobs):
    """Fully-repaired masks keep the sharded merge topology (degraded
    mode would force replicated with a warning) — failover is invisible
    to the serving layout."""
    import warnings

    q = blobs[:32]
    health = RankHealth.all_healthy(WORLD).mark_unhealthy(0)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any degrade-warning fails
        res = mnmg.ivf_flat_search(flat_r2, q, 5, n_probes=8,
                                   query_mode="sharded", health=health)
    assert res.coverage == 1.0 and res.repaired_ranks == (0,)
    v0, i0 = mnmg.ivf_flat_search(flat_r2, q, 5, n_probes=8,
                                  query_mode="sharded")
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(i0))


# -- repair + rejoin ----------------------------------------------------

def test_repair_rejoin_full_cycle(comms4, blobs):
    """The acceptance heal loop: poison a shard, fail over losslessly,
    repair from the holder, rejoin behind a verified barrier, and prove
    the subsequent search uses the REJOINED PRIMARY again (healthy mask
    -> plain tuple result, bit-identical)."""
    index = _build_flat(comms4, blobs)
    q = blobs[:23]
    v0, i0 = mnmg.ivf_flat_search(index, q, 5, n_probes=8)
    _poison_primary(comms4, index, 1)
    # the poisoned primary visibly breaks an unmasked search (the drill
    # is not a no-op) ...
    _, ibad = mnmg.ivf_flat_search(index, q, 5, n_probes=8)
    assert not np.array_equal(np.asarray(ibad), np.asarray(i0))
    health = RankHealth.all_healthy(WORLD).mark_unhealthy(1)
    # ... failover serves losslessly meanwhile ...
    res = mnmg.ivf_flat_search(index, q, 5, n_probes=8, health=health)
    assert res.coverage == 1.0 and res.repaired_ranks == (1,)
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(i0))
    # ... repair rewrites the primary in place ...
    healed = recovery.repair(comms4, health, index)
    assert healed is index
    assert health.degraded  # repair never flips masks
    # ... rejoin flips the mask only after the verified barrier ...
    health = recovery.rank_rejoin(comms4, health, 1)
    assert health.coverage() == 1.0 and not health.degraded
    # ... and the rejoined primary serves again: healthy-mask search is
    # bit-identical with NO repaired ranks
    res2 = mnmg.ivf_flat_search(index, q, 5, n_probes=8, health=health)
    assert res2.coverage == 1.0 and res2.repaired_ranks == ()
    np.testing.assert_array_equal(np.asarray(res2.ids), np.asarray(i0))
    vfin, ifin = mnmg.ivf_flat_search(index, q, 5, n_probes=8)
    np.testing.assert_array_equal(np.asarray(ifin), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(vfin), np.asarray(v0))
    # a second barrier still passes after the cycle
    assert resilience.health_barrier(comms4, timeout_s=30) >= 0


def test_repair_rejoin_full_cycle_rabitq(comms4, blobs):
    """IVF-RaBitQ rides the same heal loop: ALL THREE mirrored tables
    (codes, aux, slot_gids) fail over and repair — a failover that
    silently skipped the correction table would return finite but WRONG
    distances, so the drill poisons aux too and pins bit-identity."""
    from raft_tpu.neighbors import ivf_rabitq

    index = mnmg.ivf_rabitq_build(
        comms4, ivf_rabitq.IndexParams(n_lists=8, kmeans_n_iters=4),
        np.asarray(blobs, np.float32), replication=2)
    q = np.asarray(blobs[:23], np.float32)
    v0, i0 = mnmg.ivf_rabitq_search(index, q, 5, n_probes=8)
    _poison_primary(comms4, index, 1)
    aux = np.array(np.asarray(index.aux))
    aux[1] = 0.0  # poisoned corrections: every estimate would go to 0
    index.aux = comms4.shard(aux, axis=0)
    _, ibad = mnmg.ivf_rabitq_search(index, q, 5, n_probes=8)
    assert not np.array_equal(np.asarray(ibad), np.asarray(i0))
    health = RankHealth.all_healthy(WORLD).mark_unhealthy(1)
    res = mnmg.ivf_rabitq_search(index, q, 5, n_probes=8, health=health)
    assert res.coverage == 1.0 and res.repaired_ranks == (1,)
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(res.values), np.asarray(v0))
    healed = recovery.repair(comms4, health, index)
    assert healed is index and health.degraded
    health = recovery.rank_rejoin(comms4, health, 1)
    assert health.coverage() == 1.0
    vfin, ifin = mnmg.ivf_rabitq_search(index, q, 5, n_probes=8)
    np.testing.assert_array_equal(np.asarray(ifin), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(vfin), np.asarray(v0))


def test_repair_remirrors_for_next_failure(comms4, blobs):
    """After a repair, the mirrors are re-derived: a SECOND failure of a
    different rank still fails over losslessly."""
    index = _build_flat(comms4, blobs)
    q = blobs[:23]
    v0, i0 = mnmg.ivf_flat_search(index, q, 5, n_probes=8)
    _poison_primary(comms4, index, 1)
    h = RankHealth.all_healthy(WORLD).mark_unhealthy(1)
    index, h = recovery.heal(comms4, h, index)
    assert h.coverage() == 1.0
    _poison_primary(comms4, index, 2)
    h2 = RankHealth.all_healthy(WORLD).mark_unhealthy(2)
    res = mnmg.ivf_flat_search(index, q, 5, n_probes=8, health=h2)
    assert res.coverage == 1.0 and res.repaired_ranks == (2,)
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(res.values), np.asarray(v0))


def test_repair_without_copies_needs_checkpoint(comms4, blobs, tmp_path):
    """Beyond r-1 failures repair falls back to checkpoint rehydration;
    without a checkpoint it raises RecoveryError naming the lost
    ranks."""
    index = _build_flat(comms4, blobs)
    path = str(tmp_path / "flat.ckpt")
    mnmg.ivf_flat_save(path, index)
    q = blobs[:23]
    v0, i0 = mnmg.ivf_flat_search(index, q, 5, n_probes=8)
    health = RankHealth.all_healthy(WORLD).mark_unhealthy(1).mark_unhealthy(2)
    assert recovery.lost_ranks(index, health) == (1,)
    with pytest.raises(recovery.RecoveryError, match=r"\[1\]"):
        recovery.repair(comms4, health, index)
    fresh = recovery.repair(comms4, health, index, checkpoint=path)
    assert fresh is not index
    assert fresh.replicas is not None and fresh.replicas.r == 2
    vf, if_ = mnmg.ivf_flat_search(fresh, q, 5, n_probes=8)
    np.testing.assert_array_equal(np.asarray(if_), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(vf), np.asarray(v0))


def test_extend_carries_replication(comms4, blobs):
    """An extend returns a fresh index: the mirrors must follow (and be
    coherent with the GROWN tables, not the pre-extend ones)."""
    index = _build_flat(comms4, blobs[:1200])
    ext = mnmg.ivf_flat_extend(index, blobs[1200:1400])
    assert ext.replicas is not None and ext.replicas.r == 2
    q = blobs[:23]
    v0, i0 = mnmg.ivf_flat_search(ext, q, 5, n_probes=8)
    _poison_primary(comms4, ext, 1)
    res = mnmg.ivf_flat_search(
        ext, q, 5, n_probes=8,
        health=RankHealth.all_healthy(WORLD).mark_unhealthy(1))
    assert res.coverage == 1.0 and res.repaired_ranks == (1,)
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(i0))


# -- obs timeline -------------------------------------------------------

def test_heal_timeline_on_obs_bus(comms4, blobs):
    obs.enable()
    try:
        obs.reset()
        index = _build_flat(comms4, blobs)
        q = blobs[:23]
        _poison_primary(comms4, index, 1)
        health = RankHealth.all_healthy(WORLD).mark_unhealthy(1)
        mnmg.ivf_flat_search(index, q, 5, n_probes=8, health=health)
        recovery.heal(comms4, health, index)
        evs = [(e["kind"], e.get("rank")) for e in obs.bus().events()
               if e["kind"] in ("failover", "repair", "rejoin")]
        assert ("failover", 1) in evs
        assert ("repair", 1) in evs
        assert ("rejoin", 1) in evs
        # ordering: failover precedes repair precedes rejoin
        kinds = [k for k, _ in evs]
        assert kinds.index("failover") < kinds.index("repair") \
            < kinds.index("rejoin")
    finally:
        obs.reset()
        obs.disable()


# -- checkpoint integrity -----------------------------------------------

def _corrupt_field(path, name):
    """Flip bytes in the middle of field `name`'s buffer (deterministic
    single-field corruption — the checksum must attribute it)."""
    with open(path, "rb") as fh:
        assert fh.read(8) == b"RAFTTPU\x00"
        _, hlen = struct.unpack("<IQ", fh.read(12))
        header = json.loads(fh.read(hlen).decode())
    data_start = (8 + 12 + hlen + 63) // 64 * 64
    f = next(f for f in header["fields"] if f["name"] == name)
    assert f["nbytes"] > 0
    off = data_start + f["offset"] + f["nbytes"] // 2
    with open(path, "r+b") as fh:
        fh.seek(off)
        blk = fh.read(min(4, f["nbytes"]))
        fh.seek(off)
        fh.write(bytes(b ^ 0xFF for b in blk))


def test_ckpt_corrupt_array_heals_from_mirror(comms4, blobs, tmp_path):
    """Acceptance: a corrupted checkpoint array is detected by checksum
    and healed from a peer mirror slice — no process restart, loaded
    search bit-identical."""
    index = _build_flat(comms4, blobs)
    q = blobs[:23]
    v0, i0 = mnmg.ivf_flat_search(index, q, 5, n_probes=8)
    path = str(tmp_path / "flat.ckpt")
    mnmg.ivf_flat_save(path, index)
    _corrupt_field(path, "list_data")
    loaded = mnmg.ivf_flat_load(comms4, path)
    assert loaded.replicas is not None and loaded.replicas.r == 2
    v1, i1 = mnmg.ivf_flat_search(loaded, q, 5, n_probes=8)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v0))
    # a corrupt MIRROR array alone is dropped, not fatal (live replicas
    # re-derive from the healed primaries)
    path2 = str(tmp_path / "flat2.ckpt")
    mnmg.ivf_flat_save(path2, index)
    _corrupt_field(path2, "replica_store")
    loaded2 = mnmg.ivf_flat_load(comms4, path2)
    _, i2 = mnmg.ivf_flat_search(loaded2, q, 5, n_probes=8)
    np.testing.assert_array_equal(np.asarray(i2), np.asarray(i0))


def test_ckpt_unreplicated_corruption_detected(comms4, blobs, tmp_path):
    """Without replicas the flip is still DETECTED (ChecksumError naming
    the field) instead of silently serving flipped bits."""
    index = _build_flat(comms4, blobs, replication=1)
    path = str(tmp_path / "plain.ckpt")
    mnmg.ivf_flat_save(path, index)
    _corrupt_field(path, "list_data")
    with pytest.raises(ChecksumError, match="list_data"):
        mnmg.ivf_flat_load(comms4, path)


def test_ckpt_sharded_part_heals_from_peer_part(comms4, blobs, tmp_path):
    """Sharded checkpoint: a part file with a corrupt store heals the
    affected stored ranks from its ring peers' mirror slices."""
    index = _build_pq(comms4, blobs)
    q = blobs[:23]
    v0, i0 = mnmg.ivf_pq_search(index, q, 5, n_probes=8)
    path = str(tmp_path / "pq.ckpt")
    mnmg.ivf_pq_save_local(path, index)
    _corrupt_field(path + ".part0", "store")
    _corrupt_field(path + ".part0", "sizes")
    loaded = mnmg.ivf_pq_load(comms4, path)
    v1, i1 = mnmg.ivf_pq_search(loaded, q, 5, n_probes=8)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v0))


def test_ckpt_corrupt_file_chaos_drill(comms4, blobs, tmp_path):
    """The seeded "ckpt.corrupt_file" sector-rot drill: wherever the
    seeded sector lands, the load either heals bit-identically or
    raises ChecksumError — NEVER silently serves flipped bits."""
    index = _build_flat(comms4, blobs)
    q = blobs[:23]
    v0, i0 = mnmg.ivf_flat_search(index, q, 5, n_probes=8)
    plan = faults.FaultPlan(
        [faults.Fault(kind="corrupt_shard", site="ckpt.corrupt_file",
                      fraction=0.01)],  # a ~1%-of-file bad sector
        seed=SEED)
    path = str(tmp_path / "chaos.ckpt")
    with plan.install():
        mnmg.ivf_flat_save(path, index)
    try:
        loaded = mnmg.ivf_flat_load(comms4, path)
        v1, i1 = mnmg.ivf_flat_search(loaded, q, 5, n_probes=8)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        np.testing.assert_array_equal(np.asarray(v1), np.asarray(v0))
    except ChecksumError:
        pass  # detection without heal is a legal outcome of sector rot
    # replay determinism: the same seeded plan corrupts identically
    plan.reset()
    path2 = str(tmp_path / "chaos2.ckpt")
    with plan.install():
        mnmg.ivf_flat_save(path2, index)
    with open(path, "rb") as a, open(path2, "rb") as b:
        assert a.read() == b.read()


def test_ckpt_corrupt_optional_field_degrades_per_schema(blobs, tmp_path):
    """The field-targeted flavor of the "ckpt.corrupt_file" drill: rot
    exactly a REGISTERED-OPTIONAL field's bytes (CKPT_SCHEMA declares
    list_radii absent='default') through the seeded hook and prove the
    load DEGRADES as declared — radii dropped, budgets-only serving —
    instead of crashing; the same seeded rot on a required field still
    surfaces as ChecksumError, never silently-served flipped bits."""
    from raft_tpu.core.serialize import CKPT_SCHEMA, field_byte_range

    assert CKPT_SCHEMA["ivf_flat"]["fields"]["list_radii"][3] == "default"
    index = ivf_flat.build(ivf_flat.IndexParams(n_lists=8), blobs)
    assert index.list_radii is not None
    path = str(tmp_path / "radii.ckpt")
    ivf_flat.save(path, index)
    start, end = field_byte_range(path, "list_radii")
    plan = faults.FaultPlan(
        [faults.Fault(kind="corrupt_shard", site="ckpt.corrupt_file",
                      fraction=1.0)],
        seed=SEED)
    with plan.install():
        assert faults.corrupt_file("ckpt.corrupt_file", path,
                                   start=start, end=end)
    loaded = ivf_flat.load(path)
    assert loaded.list_radii is None  # dropped per schema, not garbage
    p = ivf_flat.SearchParams(n_probes=4, recall_target=0.9)
    _, ids = ivf_flat.search(p, loaded, blobs[:5], 3)
    assert (np.asarray(ids) >= 0).all()

    # required-field rot: detection, never degrade-and-serve
    path2 = str(tmp_path / "centers.ckpt")
    ivf_flat.save(path2, index)
    s2, e2 = field_byte_range(path2, "centers")
    plan.reset()
    with plan.install():
        assert faults.corrupt_file("ckpt.corrupt_file", path2,
                                   start=s2, end=e2)
    with pytest.raises(ChecksumError, match="centers"):
        ivf_flat.load(path2)


# -- serving heal loop --------------------------------------------------

def test_serve_heals_between_batches(comms4, blobs):
    """The MNMG serve adapter: a degraded mask on a replicated index
    serves coverage 1.0 in-flight and `step()` runs the heal loop
    between batches — the next batch uses the rejoined primary."""
    from raft_tpu import serve

    index = _build_flat(comms4, blobs)
    q = blobs[:8]
    v0, i0 = mnmg.ivf_flat_search(index, q, 5, n_probes=8,
                                  query_mode="replicated", engine="list")
    _poison_primary(comms4, index, 1)
    health = RankHealth.all_healthy(WORLD).mark_unhealthy(1)
    server = serve.SearchServer(
        index, serve.ServerConfig(buckets=(8,), max_wait_ms=0.0),
        health=health, n_probes=8)
    fut = server.submit(q, k=5)
    assert server.step() == 1
    reply = fut.result(timeout=5)
    # in-flight traffic never saw a coverage dip...
    assert reply.coverage == 1.0
    np.testing.assert_array_equal(np.asarray(reply.ids), np.asarray(i0))
    # ...and the between-batch heal flipped the mask back
    assert server.searcher.health.coverage() == 1.0
    fut2 = server.submit(q, k=5)
    assert server.step() == 1
    reply2 = fut2.result(timeout=5)
    assert reply2.coverage == 1.0
    np.testing.assert_array_equal(np.asarray(reply2.ids), np.asarray(i0))
    server.stop()