"""Property-based sweeps (hypothesis) for exactness-critical primitives:
randomly generated inputs — duplicates, negatives, infs, adversarial
orderings — against reference oracles. Complements the fixed-seed tests
with shrinkable counterexamples."""

import numpy as np
import pytest

# hypothesis is not baked into every CI image: skip cleanly instead of
# erroring collection (the fixed-seed suites still cover these paths)
pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st
from hypothesis.extra import numpy as hnp

from raft_tpu.matrix import select_k

_SETTINGS = dict(max_examples=25, deadline=None)

# bounds must be exactly f32-representable for width=32 strategies
_F32_BOUND = float(np.float32(1e30))
_finite_f32 = st.floats(
    min_value=-_F32_BOUND, max_value=_F32_BOUND, allow_nan=False, width=32
)


@settings(**_SETTINGS)
@given(
    data=hnp.arrays(
        np.float32,
        # few distinct widths: select_k compiles per (shape, k), and 25
        # arbitrary widths would pay ~25 fresh traces for no extra power
        st.tuples(st.integers(1, 4), st.sampled_from([1, 7, 130, 400])),
        elements=_finite_f32,
    ),
    k=st.sampled_from([1, 5, 16]),
    select_min=st.booleans(),
)
def test_select_k_default_matches_argsort(data, k, select_min):
    k = min(k, data.shape[1])
    v, i = select_k(data, k, select_min=select_min)
    order = np.argsort(data, axis=1, kind="stable")
    if not select_min:
        order = order[:, ::-1]
    want = np.take_along_axis(data, order[:, :k], axis=1)
    np.testing.assert_allclose(np.asarray(v), want, rtol=0, atol=0)
    # reported indices must retrieve the reported values
    np.testing.assert_allclose(
        np.take_along_axis(data, np.asarray(i), axis=1), np.asarray(v)
    )


@settings(**_SETTINGS)
@given(
    data=hnp.arrays(
        np.float32,
        st.tuples(st.integers(1, 3), st.sampled_from([3, 64, 300])),
        # allow_subnormal=False: XLA flushes denormals in sort compares
        # (1e-45 ties with 0.0 in lax.top_k) while the counting engine's
        # bit-image distinguishes them; cross-engine equality only holds
        # outside that platform-defined regime
        elements=st.floats(
            min_value=-1e6, max_value=1e6, allow_nan=False, width=32,
            allow_subnormal=False,
        ),
    ),
    k=st.sampled_from([1, 4, 10]),
)
@pytest.mark.slow
def test_counting_select_matches_default(data, k):
    """The Pallas counting engine must agree value-for-value with the
    XLA path. Index equality is only required where values are unique:
    XLA top_k's own tie order for equal values (incl. -0.0 vs 0.0) is
    implementation-defined, so the honest cross-engine contract is
    'same selected values, indices retrieve them'."""
    k = min(k, data.shape[1])
    v1, i1 = select_k(data, k)
    v2, i2 = select_k(data, k, strategy="counting")
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v2), rtol=0, atol=0)
    np.testing.assert_allclose(
        np.take_along_axis(data, np.asarray(i2), axis=1), np.asarray(v2),
        rtol=0, atol=0,
    )
    unique_rows = [len(set(row.tolist())) == len(row) for row in data]
    for r, uniq in enumerate(unique_rows):
        if uniq:
            np.testing.assert_array_equal(np.asarray(i1)[r], np.asarray(i2)[r])


@settings(**_SETTINGS)
@given(
    vals=st.lists(_finite_f32, min_size=2, max_size=64, unique=True)
)
def test_counting_monotone_map_preserves_order(vals):
    """The order-preserving f32 -> uint32 image at the heart of the
    bit-fixing threshold search: strictly monotone over any finite
    floats (incl. -0.0 vs 0.0 collapsing is fine — equality holds)."""
    import jax

    with jax.default_device(jax.devices("cpu")[0]):
        from raft_tpu.ops.select_counting import _monotone_u32
        import jax.numpy as jnp

        x = np.asarray(sorted(vals), np.float32)
        u = np.asarray(_monotone_u32(jnp.asarray(x)))
        assert np.all(u[:-1] <= u[1:])
        # strict where the floats differ as f32
        diff = x[:-1] != x[1:]
        assert np.all(u[:-1][diff] < u[1:][diff])


@settings(**_SETTINGS)
@given(
    arrs=st.lists(
        hnp.arrays(
            st.sampled_from([np.float32, np.int32, np.uint8, np.int8]),
            hnp.array_shapes(min_dims=1, max_dims=3, max_side=8),
            elements=st.integers(0, 100),
        ),
        min_size=1,
        max_size=4,
    )
)
def test_serialize_roundtrip(arrs, tmp_path_factory):
    """Container codec: arbitrary dtype/shape inventories survive the
    save/load cycle bit-for-bit."""
    from raft_tpu.core.serialize import serialize_arrays, deserialize_arrays

    path = str(tmp_path_factory.mktemp("ser") / "c.bin")
    named = {f"a{i}": a for i, a in enumerate(arrs)}
    serialize_arrays(path, named, meta={"k": 1})
    out, meta = deserialize_arrays(path, to_device=False)
    assert meta["k"] == 1
    for name, a in named.items():
        got = np.asarray(out[name])
        assert got.dtype == a.dtype and got.shape == a.shape
        np.testing.assert_array_equal(got, a)
