"""IVF-RaBitQ index suite: single-chip build/search/extend/save, the
recall-with-rerank contract, prefilters, and the full production
surface — MNMG build/search, refine, degraded mode, replica failover
bit-identity, CRC checkpoint round-trip with mirror heal, and serve
batched-vs-unbatched bit-identity. (Estimator/packing property tests
live in tests/test_quantizer.py; chaos drills for the registered fault
sites in tests/test_resilience.py.)"""

import os
import tempfile

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.comms import Comms, mnmg
from raft_tpu.comms.resilience import DegradedSearchResult, RankHealth
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.neighbors import brute_force, ivf_rabitq
from raft_tpu.random import make_blobs

WORLD = 4


@pytest.fixture(scope="module")
def blobs():
    data, _ = make_blobs(4000, 48, n_clusters=24, cluster_std=0.8, seed=21)
    return np.asarray(data, np.float32)


@pytest.fixture(scope="module")
def index(blobs):
    return ivf_rabitq.build(
        ivf_rabitq.IndexParams(n_lists=32, kmeans_n_iters=6), blobs, seed=0)


@pytest.fixture(scope="module")
def exact10(blobs):
    _, ids = brute_force.knn(blobs, blobs[:50], 10)
    return np.asarray(ids)


def _recall(got, exact, k=10):
    got = np.asarray(got)
    return float(np.mean([
        len(set(got[i]) & set(exact[i])) / k for i in range(len(exact))
    ]))


# -- single-chip --------------------------------------------------------

def test_build_geometry(index, blobs):
    assert index.dim == 48
    assert index.rot_dim == 64  # rounded up to whole uint32 words
    assert index.words == 2
    assert index.codes.dtype == jnp.uint32
    assert index.aux.shape == index.codes.shape[:2] + (2,)
    assert index.size == len(blobs)
    # the rotation is column-orthonormal: norms survive the transform
    rot = np.asarray(index.rotation)
    np.testing.assert_allclose(rot.T @ rot, np.eye(48), atol=1e-5)


def test_search_with_rerank_recall(index, blobs, exact10):
    v, i = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=16, rerank_mult=8),
        index, blobs[:50], 10)
    assert _recall(i, exact10) >= 0.9
    # reranked distances are EXACT (squared L2 against the true rows)
    i0 = np.asarray(i)[:, 0]
    d0 = ((blobs[:50] - blobs[i0]) ** 2).sum(1)
    np.testing.assert_allclose(np.asarray(v)[:, 0], d0, rtol=1e-4,
                               atol=1e-3)


def test_rerank_depth_beats_estimator_only(index, blobs, exact10):
    no_ds = ivf_rabitq.build(
        ivf_rabitq.IndexParams(n_lists=32, kmeans_n_iters=6,
                               store_dataset=False), blobs, seed=0)
    assert no_ds.dataset is None
    _, est_ids = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=16), no_ds, blobs[:50], 10)
    _, rr_ids = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=16, rerank_mult=8), index,
        blobs[:50], 10)
    assert _recall(rr_ids, exact10) > _recall(est_ids, exact10)
    # the quantized-only index reranks through an explicit dataset
    _, ref_ids = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=16, rerank_mult=8), no_ds,
        blobs[:50], 10, refine_dataset=blobs)
    np.testing.assert_array_equal(np.asarray(ref_ids), np.asarray(rr_ids))


def test_inner_product_metric(blobs):
    idx = ivf_rabitq.build(
        ivf_rabitq.IndexParams(n_lists=16, kmeans_n_iters=4,
                               metric=DistanceType.InnerProduct),
        blobs, seed=1)
    _, exact = brute_force.knn(blobs, blobs[:20], 10,
                               metric=DistanceType.InnerProduct)
    _, ids = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=8, rerank_mult=8), idx,
        blobs[:20], 10)
    assert _recall(ids, np.asarray(exact)) >= 0.8


def test_extend_appends_and_searches(blobs):
    idx = ivf_rabitq.build(
        ivf_rabitq.IndexParams(n_lists=16, kmeans_n_iters=4),
        blobs[:3000], seed=2)
    idx2 = ivf_rabitq.extend(idx, blobs[3000:])
    assert idx2.size == len(blobs)
    assert idx2.dataset.shape == blobs.shape
    # an appended row finds itself first after rerank
    q = blobs[3500:3510]
    _, ids = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=16, rerank_mult=8), idx2, q, 5)
    assert (np.asarray(ids)[:, 0] == np.arange(3500, 3510)).mean() >= 0.9


def test_extend_custom_indices(blobs):
    idx = ivf_rabitq.build(
        ivf_rabitq.IndexParams(n_lists=8, kmeans_n_iters=4),
        blobs[:1000], seed=3)
    idx2 = ivf_rabitq.extend(idx, blobs[1000:1100],
                             new_indices=np.arange(5000, 5100))
    assert idx2.id_bound == 5100
    q = blobs[1000:1010]
    _, ids = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=8, rerank_mult=8), idx2, q, 3)
    assert (np.asarray(ids)[:, 0] == np.arange(5000, 5010)).mean() >= 0.9


def test_rerank_depth_beyond_probed_width(blobs):
    """kk (rerank_mult * k) larger than the probed slot count must not
    crash the in-trace top-k: the scan selects everything the probes
    hold and pads the tail (worst score, id -1) — with the shipped
    tuned default rerank_mult=16, tiny-probe searches hit this path."""
    idx = ivf_rabitq.build(
        ivf_rabitq.IndexParams(n_lists=32, kmeans_n_iters=4), blobs[:2000],
        seed=4)
    max_list = int(idx.codes.shape[1])
    k = 10
    assert 1 * max_list < 16 * k  # the geometry that used to crash
    v, ids = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=1, rerank_mult=16), idx,
        blobs[:5], k)
    assert np.asarray(ids).shape == (5, k)
    assert (np.asarray(ids)[:, 0] >= 0).all()  # real candidates lead
    # estimator-only path pads too when k exceeds the probed width
    no_ds = ivf_rabitq.build(
        ivf_rabitq.IndexParams(n_lists=32, kmeans_n_iters=4,
                               store_dataset=False), blobs[:2000], seed=4)
    v2, ids2 = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=1), no_ds, blobs[:5], max_list + 7)
    ids2 = np.asarray(ids2)
    assert ids2.shape == (5, max_list + 7)
    assert (ids2[:, -1] == -1).all()  # beyond the probed width: -1 pad


def test_prefilter_excludes_rows(index, blobs):
    q = blobs[:10]
    _, base = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=16, rerank_mult=8), index, q, 5)
    base = np.asarray(base)
    mask = np.ones(index.size, bool)
    mask[base[:, 0]] = False  # ban every top-1
    _, filt = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=16, rerank_mult=8), index, q, 5,
        prefilter=mask)
    filt = np.asarray(filt)
    assert not np.isin(filt, base[:, 0]).any()


def test_save_load_roundtrip(index, blobs, tmp_path):
    path = str(tmp_path / "rb.idx")
    ivf_rabitq.save(path, index)
    loaded = ivf_rabitq.load(path)
    assert loaded.dataset is None  # raw rows are not serialized
    q = blobs[:20]
    v0, i0 = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=16, rerank_mult=8), index, q, 5)
    v1, i1 = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=16, rerank_mult=8), loaded, q, 5,
        refine_dataset=blobs)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v0))


def test_validation_errors(index, blobs):
    with pytest.raises(ValueError, match="query dim"):
        ivf_rabitq.search(ivf_rabitq.SearchParams(), index,
                          np.zeros((2, 7), np.float32), 3)
    with pytest.raises(ValueError, match="k must be positive"):
        ivf_rabitq.search(ivf_rabitq.SearchParams(), index, blobs[:2], 0)
    with pytest.raises(ValueError, match="query_bits"):
        ivf_rabitq.search(ivf_rabitq.SearchParams(query_bits=9), index,
                          blobs[:2], 3)
    with pytest.raises(ValueError, match="rerank_mult"):
        ivf_rabitq.search(ivf_rabitq.SearchParams(rerank_mult=-2), index,
                          blobs[:2], 3)
    with pytest.raises(ValueError, match="n_lists"):
        ivf_rabitq.build(ivf_rabitq.IndexParams(n_lists=64), blobs[:10])


def test_top_level_lazy_exports():
    import raft_tpu

    assert raft_tpu.ivf_rabitq_build is ivf_rabitq.build
    assert raft_tpu.ivf_rabitq_search is ivf_rabitq.search


def test_build_has_no_codebook_stage(index):
    """The structural fast-build claim: the index carries NO trained
    codebooks — encode state is the rotation alone (wall-clock race in
    bench/bench_ivf_rabitq.py)."""
    assert not hasattr(index, "pq_centers")
    quant_meta = __import__(
        "raft_tpu.neighbors.quantizer", fromlist=["RabitqQuantizer"]
    ).RabitqQuantizer(index.rot_dim).state_arrays()
    assert quant_meta == {}  # nothing trained, nothing to serialize


# -- MNMG ---------------------------------------------------------------

@pytest.fixture(scope="module")
def comms4():
    return Comms(n_devices=WORLD)


@pytest.fixture(scope="module")
def mblobs():
    data, _ = make_blobs(1600, 16, n_clusters=6, cluster_std=0.4, seed=13)
    return np.asarray(data, np.float32)


@pytest.fixture(scope="module")
def rb8(comms4, mblobs):
    return mnmg.ivf_rabitq_build(
        comms4, ivf_rabitq.IndexParams(n_lists=8, kmeans_n_iters=4), mblobs)


def test_mnmg_search_and_refine(comms4, mblobs, rb8):
    q = mblobs[:23]
    v, i = mnmg.ivf_rabitq_search(rb8, q, 5, n_probes=8)
    assert np.asarray(v).shape == (23, 5)
    vr, ir = mnmg.ivf_rabitq_search(rb8, q, 5, n_probes=8,
                                    refine_dataset=mblobs)
    ir = np.asarray(ir)
    # refined: each query row (a dataset row) finds itself at distance 0
    assert (ir[:, 0] == np.arange(23)).mean() >= 0.9
    np.testing.assert_allclose(
        np.asarray(vr)[ir[:, 0] == np.arange(23), 0], 0.0, atol=1e-4)


def test_mnmg_degraded_matches_survivor_prefilter(comms4, mblobs, rb8):
    q = mblobs[:23]
    h = RankHealth.all_healthy(WORLD)
    h.mark_unhealthy(1)
    res = mnmg.ivf_rabitq_search(rb8, q, 5, n_probes=8, health=h)
    assert isinstance(res, DegradedSearchResult) and res.coverage == 0.75
    hg = np.asarray(rb8.host_gids[1])
    mask = np.ones(rb8.n, bool)
    mask[hg[hg >= 0]] = False
    rv, ri = mnmg.ivf_rabitq_search(rb8, q, 5, n_probes=8, prefilter=mask)
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(ri))
    np.testing.assert_array_equal(np.asarray(res.values), np.asarray(rv))


def test_mnmg_replication_failover_bit_identical(comms4, mblobs):
    rb2 = mnmg.ivf_rabitq_build(
        comms4, ivf_rabitq.IndexParams(n_lists=8, kmeans_n_iters=4),
        mblobs, replication=2)
    q = mblobs[:23]
    v0, i0 = mnmg.ivf_rabitq_search(rb2, q, 5, n_probes=8)
    h = RankHealth.all_healthy(WORLD)
    h.mark_unhealthy(2)
    res = mnmg.ivf_rabitq_search(rb2, q, 5, n_probes=8, health=h)
    # lossless failover: coverage stays 1.0 and the results are
    # BIT-identical to the all-healthy run
    assert res.coverage == 1.0 and res.repaired_ranks == (2,)
    np.testing.assert_array_equal(np.asarray(res.ids), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(res.values), np.asarray(v0))
    # past r-1 failures the degraded path takes over
    h2 = RankHealth.all_healthy(WORLD)
    h2.mark_unhealthy(2)
    h2.mark_unhealthy(3)
    res2 = mnmg.ivf_rabitq_search(rb2, q, 5, n_probes=8, health=h2)
    assert res2.coverage < 1.0


def test_mnmg_ckpt_roundtrip_with_corrupt_heal(comms4, mblobs, tmp_path):
    from raft_tpu.core import faults
    from raft_tpu.core.serialize import ChecksumError

    rb2 = mnmg.ivf_rabitq_build(
        comms4, ivf_rabitq.IndexParams(n_lists=8, kmeans_n_iters=4),
        mblobs, replication=2)
    q = mblobs[:23]
    v0, i0 = mnmg.ivf_rabitq_search(rb2, q, 5, n_probes=8)
    path = str(tmp_path / "rb_chaos.ckpt")
    plan = faults.FaultPlan(
        [faults.Fault(kind="corrupt_shard", site="ckpt.corrupt_file",
                      fraction=0.01)],
        seed=int(os.environ.get(faults.ENV_SEED, "1234")))
    with plan.install():
        mnmg.ivf_rabitq_save(path, rb2)
    try:
        loaded = mnmg.ivf_rabitq_load(comms4, path)
    except ChecksumError:
        # the seeded sector landed on an unmirrored field (rotation/
        # centers): detection without heal — still never silent
        return
    assert loaded.replicas is not None and loaded.replicas.r == 2
    v1, i1 = mnmg.ivf_rabitq_search(loaded, q, 5, n_probes=8)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v0))


def test_mnmg_ckpt_clean_roundtrip(comms4, mblobs, rb8, tmp_path):
    path = str(tmp_path / "rb.ckpt")
    mnmg.ivf_rabitq_save(path, rb8)
    loaded = mnmg.ivf_rabitq_load(comms4, path)
    q = mblobs[:23]
    v0, i0 = mnmg.ivf_rabitq_search(rb8, q, 5, n_probes=8)
    v1, i1 = mnmg.ivf_rabitq_search(loaded, q, 5, n_probes=8)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v0))
    # the checkpoint-based heal path dispatches rabitq checkpoints too
    # (rehydrate is what recovery.repair/heal and MnmgSearcher's
    # heal_checkpoint fall back to past r-1 failures)
    from raft_tpu.comms import resilience

    fresh, health = resilience.rehydrate(comms4, path)
    assert health.coverage() == 1.0
    v2, i2 = mnmg.ivf_rabitq_search(fresh, q, 5, n_probes=8)
    np.testing.assert_array_equal(np.asarray(i2), np.asarray(i0))
    np.testing.assert_array_equal(np.asarray(v2), np.asarray(v0))


# -- serve --------------------------------------------------------------

def test_serve_batched_bit_identical_to_unbatched(blobs, index):
    from raft_tpu import serve

    q = blobs[:6]
    sp = ivf_rabitq.SearchParams(n_probes=16, rerank_mult=8)
    uv, ui = ivf_rabitq.search(sp, index, q, 5)
    server = serve.SearchServer(index, serve.ServerConfig(buckets=(8, 32)),
                                search_params=sp)
    assert isinstance(server.searcher, serve.IvfRabitqSearcher)
    futs = [server.submit(q[i:i + 1], 5) for i in range(6)]
    while any(not f.done() for f in futs):
        server.step()
    for i, f in enumerate(futs):
        reply = f.result(1.0)
        assert reply.coverage == 1.0
        np.testing.assert_array_equal(np.asarray(reply.ids)[0],
                                      np.asarray(ui)[i])
        np.testing.assert_array_equal(np.asarray(reply.values)[0],
                                      np.asarray(uv)[i])


def test_serve_mnmg_searcher_coverage(comms4, mblobs, rb8):
    from raft_tpu import serve

    searcher = serve.as_searcher(rb8, n_probes=8)
    assert isinstance(searcher, serve.MnmgSearcher)
    assert searcher.kind == "ivf_rabitq" and searcher.engine is None
    h = RankHealth.all_healthy(WORLD)
    h.mark_unhealthy(1)
    searcher.set_health(h)
    _, _, coverage = searcher.search(mblobs[:8], 5)
    assert coverage == 0.75
    # an explicit engine= is a config error for the single-engine index
    # — rejected loudly, never silently ignored
    with pytest.raises(ValueError, match="meaningless for ivf_rabitq"):
        serve.as_searcher(rb8, engine="recon8_list")
