"""Pairwise distance tests vs scipy oracle.

Mirrors cpp/test/distance/* strategy: parameterized over metrics/sizes,
compare against a host reference implementation with tolerance.
"""

import numpy as np
import pytest
from scipy.spatial import distance as spdist

from raft_tpu.distance import pairwise_distance, DistanceType, fused_l2_nn_argmin, fused_l2_nn

METRIC_TO_SCIPY = {
    "sqeuclidean": "sqeuclidean",
    "euclidean": "euclidean",
    "cosine": "cosine",
    "l1": "cityblock",
    "chebyshev": "chebyshev",
    "canberra": "canberra",
    "correlation": "correlation",
    "braycurtis": "braycurtis",
    "jensenshannon": "jensenshannon",
    "hamming": "hamming",
}


@pytest.mark.parametrize("metric", sorted(METRIC_TO_SCIPY))
@pytest.mark.parametrize("m,n,k", [(30, 40, 16), (5, 3, 8), (65, 130, 33)])
def test_pairwise_matches_scipy(metric, m, n, k, rng):
    x = rng.random((m, k), dtype=np.float32)
    y = rng.random((n, k), dtype=np.float32)
    if metric in ("jensenshannon",):
        x /= x.sum(axis=1, keepdims=True)
        y /= y.sum(axis=1, keepdims=True)
    got = np.asarray(pairwise_distance(x, y, metric=metric))
    want = spdist.cdist(x.astype(np.float64), y.astype(np.float64), METRIC_TO_SCIPY[metric])
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_minkowski(rng):
    x = rng.random((20, 10), dtype=np.float32)
    y = rng.random((15, 10), dtype=np.float32)
    got = np.asarray(pairwise_distance(x, y, metric="minkowski", p=3.0))
    want = spdist.cdist(x, y, "minkowski", p=3.0)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_inner_product(rng):
    x = rng.random((12, 7), dtype=np.float32)
    y = rng.random((9, 7), dtype=np.float32)
    got = np.asarray(pairwise_distance(x, y, metric="inner_product"))
    np.testing.assert_allclose(got, x @ y.T, rtol=1e-4, atol=1e-4)


def test_hellinger(rng):
    x = rng.random((10, 6), dtype=np.float32)
    y = rng.random((8, 6), dtype=np.float32)
    x /= x.sum(axis=1, keepdims=True)
    y /= y.sum(axis=1, keepdims=True)
    got = np.asarray(pairwise_distance(x, y, metric="hellinger"))
    want = np.sqrt(
        np.maximum(1.0 - np.sqrt(x[:, None, :] * y[None, :, :]).sum(-1), 0.0)
    )
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_kl_divergence(rng):
    x = rng.random((10, 6), dtype=np.float32) + 0.1
    y = rng.random((8, 6), dtype=np.float32) + 0.1
    x /= x.sum(axis=1, keepdims=True)
    y /= y.sum(axis=1, keepdims=True)
    got = np.asarray(pairwise_distance(x, y, metric="kl_divergence"))
    want = (x[:, None, :] * np.log(x[:, None, :] / y[None, :, :])).sum(-1)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_binary_metrics(rng):
    x = (rng.random((14, 24)) > 0.5).astype(np.float32)
    y = (rng.random((11, 24)) > 0.5).astype(np.float32)
    got_j = np.asarray(pairwise_distance(x, y, metric="jaccard"))
    want_j = spdist.cdist(x.astype(bool), y.astype(bool), "jaccard")
    np.testing.assert_allclose(got_j, want_j, rtol=1e-4, atol=1e-4)
    got_d = np.asarray(pairwise_distance(x, y, metric="dice"))
    want_d = spdist.cdist(x.astype(bool), y.astype(bool), "dice")
    np.testing.assert_allclose(got_d, want_d, rtol=1e-4, atol=1e-4)
    got_r = np.asarray(pairwise_distance(x, y, metric="russellrao"))
    want_r = spdist.cdist(x.astype(bool), y.astype(bool), "russellrao")
    np.testing.assert_allclose(got_r, want_r, rtol=1e-4, atol=1e-4)


def test_haversine(rng):
    lat = rng.uniform(-np.pi / 2, np.pi / 2, (10, 1))
    lon = rng.uniform(-np.pi, np.pi, (10, 1))
    pts = np.concatenate([lat, lon], axis=1).astype(np.float32)
    got = np.asarray(pairwise_distance(pts, pts, metric="haversine"))
    # oracle
    la1, lo1 = lat, lon
    la2, lo2 = lat.T, lon.T
    h = np.sin((la2 - la1) / 2) ** 2 + np.cos(la1) * np.cos(la2) * np.sin((lo2 - lo1) / 2) ** 2
    want = 2 * np.arcsin(np.sqrt(h))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    assert np.allclose(np.diag(got), 0, atol=1e-5)


def test_precomputed_passthrough(rng):
    d = rng.random((5, 5), dtype=np.float32)
    got = np.asarray(pairwise_distance(d, d, metric=DistanceType.Precomputed))
    np.testing.assert_array_equal(got, d)


def test_large_tiled_path(rng):
    # force the row-blocked path for an unexpanded metric
    x = rng.random((700, 64), dtype=np.float32)
    y = rng.random((900, 64), dtype=np.float32)
    got = np.asarray(pairwise_distance(x, y, metric="l1"))
    want = spdist.cdist(x, y, "cityblock")
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_fused_l2_nn(rng):
    x = rng.random((300, 32), dtype=np.float32)
    y = rng.random((50, 32), dtype=np.float32)
    idx = np.asarray(fused_l2_nn_argmin(x, y))
    full = spdist.cdist(x, y, "sqeuclidean")
    np.testing.assert_array_equal(idx, full.argmin(axis=1))
    dmin, idx2 = fused_l2_nn(x, y)
    np.testing.assert_array_equal(np.asarray(idx2), full.argmin(axis=1))
    np.testing.assert_allclose(np.asarray(dmin), full.min(axis=1), rtol=1e-3, atol=1e-3)
