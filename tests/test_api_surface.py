"""Public API surface regression: every entry point the parity doc and
README advertise must import and be callable. Catches silent removals or
re-export drift (e.g. a package attribute shadowing a submodule) that
per-module tests can miss — pylibraft users navigate by these names.
"""

import importlib

import pytest

# (module, attribute) pairs — the API surface docs/api_parity.md claims.
SURFACE = [
    # core
    ("raft_tpu.core.resources", "Resources"),
    ("raft_tpu.core.serialize", "serialize_arrays"),
    ("raft_tpu.core.serialize", "deserialize_arrays"),
    ("raft_tpu.core.device_ndarray", "device_ndarray"),
    ("raft_tpu.core", "Bitset"),
    ("raft_tpu.core.bitset", "as_bitset"),
    ("raft_tpu.core.bitset", "filter_slot_table"),
    # matrix / select_k
    ("raft_tpu.matrix", "select_k"),
    ("raft_tpu.matrix", "gather"),
    ("raft_tpu.matrix", "argmin"),
    ("raft_tpu.ops.select_counting", "counting_select_min"),
    # distance
    ("raft_tpu.distance", "pairwise_distance"),
    ("raft_tpu.distance", "fused_l2_nn"),
    ("raft_tpu.distance.kernels", "gram_matrix"),
    # neighbors
    ("raft_tpu.neighbors.brute_force", "knn"),
    ("raft_tpu.neighbors.brute_force", "knn_merge_parts"),
    ("raft_tpu.neighbors.ivf_flat", "build"),
    ("raft_tpu.neighbors.ivf_flat", "search"),
    ("raft_tpu.neighbors.ivf_pq", "build"),
    ("raft_tpu.neighbors.ivf_pq", "search"),
    ("raft_tpu.neighbors.ivf_pq", "save"),
    ("raft_tpu.neighbors.ivf_pq", "load"),
    ("raft_tpu.neighbors.ivf_rabitq", "build"),
    ("raft_tpu.neighbors.ivf_rabitq", "search"),
    ("raft_tpu.neighbors.ivf_rabitq", "save"),
    ("raft_tpu.neighbors.ivf_rabitq", "load"),
    ("raft_tpu.neighbors.quantizer", "Quantizer"),
    ("raft_tpu.neighbors.quantizer", "PqQuantizer"),
    ("raft_tpu.neighbors.quantizer", "RabitqQuantizer"),
    ("raft_tpu.neighbors", "refine"),
    ("raft_tpu.neighbors.refine", "refine_host"),
    ("raft_tpu.neighbors.ball_cover", "build_index"),
    ("raft_tpu.neighbors.epsilon_neighborhood", "eps_neighbors"),
    ("raft_tpu.neighbors.batch_loader", "BatchLoadIterator"),
    ("raft_tpu.neighbors.batch_loader", "extend_batched"),
    # io
    ("raft_tpu.io", "FileBatchLoader"),
    ("raft_tpu.io", "extend_from_file"),
    ("raft_tpu.io", "extend_from_file_local"),
    ("raft_tpu.io", "probe_file"),
    # cluster
    ("raft_tpu.cluster.kmeans", "fit"),
    ("raft_tpu.cluster.kmeans", "KMeansParams"),
    ("raft_tpu.cluster.kmeans_balanced", "fit"),
    ("raft_tpu.cluster.kmeans_balanced", "fit_hierarchical"),
    ("raft_tpu.cluster.single_linkage", "single_linkage"),
    # sparse / spectral / solver / label
    ("raft_tpu.sparse.distance", "pairwise_distance"),
    ("raft_tpu.sparse.solver", "mst"),
    ("raft_tpu.sparse.solver", "lanczos"),
    ("raft_tpu.spectral", "partition"),
    ("raft_tpu.solver", "linear_assignment"),
    ("raft_tpu.label", "make_monotonic"),
    # random / stats
    ("raft_tpu.random", "make_blobs"),
    ("raft_tpu.random", "rmat"),
    ("raft_tpu.stats", "silhouette_score"),
    ("raft_tpu.stats", "trustworthiness_score"),
    # comms / distributed
    ("raft_tpu.comms", "Comms"),
    ("raft_tpu.comms", "AxisComms"),
    ("raft_tpu.comms", "init_comms"),
    ("raft_tpu.comms", "local_handle"),
    ("raft_tpu.comms", "bootstrap_multihost"),
    ("raft_tpu.comms.mnmg", "kmeans_fit"),
    ("raft_tpu.comms.mnmg", "kmeans_fit_local"),
    ("raft_tpu.comms.mnmg", "kmeans_predict_local"),
    ("raft_tpu.comms.mnmg", "knn"),
    ("raft_tpu.comms.mnmg", "knn_local"),
    ("raft_tpu.comms.mnmg", "ivf_flat_build"),
    ("raft_tpu.comms.mnmg", "ivf_flat_build_local"),
    ("raft_tpu.comms.mnmg", "ivf_flat_search"),
    ("raft_tpu.comms.mnmg", "ivf_flat_save"),
    ("raft_tpu.comms.mnmg", "ivf_flat_save_local"),
    ("raft_tpu.comms.mnmg", "ivf_flat_load"),
    ("raft_tpu.comms.mnmg", "ivf_pq_build"),
    ("raft_tpu.comms.mnmg", "ivf_pq_build_local"),
    ("raft_tpu.comms.mnmg", "ivf_pq_extend"),
    ("raft_tpu.comms.mnmg", "ivf_pq_extend_local"),
    ("raft_tpu.comms.mnmg", "ivf_flat_extend_local"),
    ("raft_tpu.comms.mnmg", "ivf_pq_search"),
    ("raft_tpu.comms.mnmg", "ivf_pq_save"),
    ("raft_tpu.comms.mnmg", "ivf_pq_save_local"),
    ("raft_tpu.comms.mnmg", "ivf_pq_load"),
    ("raft_tpu.comms.mnmg", "ivf_rabitq_build"),
    ("raft_tpu.comms.mnmg", "ivf_rabitq_search"),
    ("raft_tpu.comms.mnmg", "ivf_rabitq_save"),
    ("raft_tpu.comms.mnmg", "ivf_rabitq_load"),
    ("raft_tpu.comms.mnmg", "distribute_index"),
    # resilience / fault injection
    ("raft_tpu.comms", "RankHealth"),
    ("raft_tpu.comms", "DegradedSearchResult"),
    ("raft_tpu.comms", "probe_health"),
    ("raft_tpu.comms", "health_barrier"),
    ("raft_tpu.comms", "rehydrate"),
    ("raft_tpu.comms", "retry_with_backoff"),
    ("raft_tpu.comms.resilience", "HealthCheckTimeout"),
    ("raft_tpu.core.faults", "FaultPlan"),
    ("raft_tpu.core.faults", "Fault"),
    ("raft_tpu.core.faults", "FaultInjected"),
    ("raft_tpu.core.interruptible", "TimeoutException"),
    # native
    ("raft_tpu.native", "available"),
    ("raft_tpu.native", "pack_lists"),
    ("raft_tpu.native", "mst_linkage"),
    # resilience surface at its stable top-level paths (serving code
    # types against these without deep imports — docs/api_parity.md)
    ("raft_tpu", "DegradedSearchResult"),
    ("raft_tpu", "RankHealth"),
    # IVF-RaBitQ headline aliases (renamed lazy exports — tuple-valued
    # _LAZY_ATTRS entries)
    ("raft_tpu", "ivf_rabitq_build"),
    ("raft_tpu", "ivf_rabitq_search"),
    # serving engine
    ("raft_tpu.serve", "SearchServer"),
    ("raft_tpu.serve", "ServerConfig"),
    ("raft_tpu.serve", "ServerMetrics"),
    ("raft_tpu.serve", "AdmissionConfig"),
    ("raft_tpu.serve", "MicroBatcher"),
    ("raft_tpu.serve", "SearchReply"),
    ("raft_tpu.serve", "PendingResult"),
    ("raft_tpu.serve", "RejectedError"),
    ("raft_tpu.serve", "DeadlineExceeded"),
    ("raft_tpu.serve", "IvfRabitqSearcher"),
    ("raft_tpu.serve", "as_searcher"),
]


@pytest.mark.parametrize("module,attr", SURFACE, ids=lambda v: str(v))
def test_symbol_exists(module, attr):
    mod = importlib.import_module(module)
    obj = getattr(mod, attr)
    assert obj is not None


def test_every_on_disk_subpackage_is_navigable():
    """Every subpackage directory that ships an __init__.py must be
    reachable as `raft_tpu.<name>` through the PEP 562 lazy loader (the
    `io`/`native` omission bug class): the on-disk tree IS the surface,
    so the registry can never silently drift from it again."""
    import pathlib

    import raft_tpu

    pkg_dir = pathlib.Path(raft_tpu.__file__).parent
    on_disk = sorted(
        p.name for p in pkg_dir.iterdir()
        if p.is_dir() and (p / "__init__.py").exists()
    )
    assert on_disk, "expected subpackage directories next to __init__.py"
    missing = [name for name in on_disk if name not in raft_tpu._SUBPACKAGES]
    assert not missing, f"subpackages not in raft_tpu._SUBPACKAGES: {missing}"
    for name in on_disk:
        mod = getattr(raft_tpu, name)  # the lazy loader must resolve it
        assert mod.__name__ == f"raft_tpu.{name}"
        assert name in raft_tpu.__all__ and name in dir(raft_tpu)


def test_lazy_resilience_aliases_are_the_same_objects():
    import raft_tpu
    from raft_tpu.comms.resilience import DegradedSearchResult, RankHealth

    assert raft_tpu.DegradedSearchResult is DegradedSearchResult
    assert raft_tpu.RankHealth is RankHealth


def test_refine_is_the_function():
    """The package deliberately re-exports the refine FUNCTION under the
    submodule's name (pylibraft parity); this pins the shape so callers
    (and our own benches) can rely on it."""
    from raft_tpu.neighbors import refine

    assert callable(refine) and not hasattr(refine, "__path__")