"""Integer fused scans (ISSUE 11): exact-agreement + dispatch suite.

Two kernel families on the TPU integer datapath:

  - the int8 PQ-recon list scan (`fused_list_topk_int8`, dispatch
    strategy "fused_int8"): int8 x int8 -> int32 on the MXU, per-row
    dequant, exact partial top-k — its scores must be BIT-IDENTICAL f32
    values to the pallas int8 bin trim's (same `_quantize_query_rows`
    quantization, same op order);
  - the RaBitQ bit-plane scan (`fused_bitplane_topk`, strategy
    "fused_bitplane"): uint32 AND+popcount with the unbiased estimator
    correction in-kernel — its per-(query, slot) scores must equal the
    XLA reference (`_search_impl_rabitq` via `quantizer.binary_dot` /
    `estimate_dot`) EXACTLY: the integer bit-plane sums are associative
    and the f32 correction applies the identical expression.

Everything runs the kernels in interpret mode on CPU (the repo-wide
Pallas testing convention). List geometries here keep L <= 512 so the
pallas bin trim is lossless and the int8 comparison is exact end to
end, not just per-score.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.matrix.select_k import (
    BITPLANE_SCAN_KEY,
    INT8_SCAN_KEY,
    list_scan_select_k,
    resolve_bitplane_strategy,
    resolve_int8_trim_strategy,
)
from raft_tpu.neighbors import ivf_pq, ivf_rabitq


def _grid(rng, shape, lo=-8, hi=8):
    return rng.integers(lo, hi, shape).astype(np.float32)


# -- int8 list kernel ---------------------------------------------------


def test_fused_list_topk_int8_matches_oracle(rng):
    """Per-(chunk row, list) exact top-k straight from the int8 kernel:
    int32 MXU accumulation, per-row dequant, deterministic
    smaller-slot ties."""
    from raft_tpu.ops.fused_scan import fused_list_topk_int8

    n_lists, L, rot, chunk, k = 5, 256, 24, 8, 16
    store = rng.integers(-127, 128, (n_lists, L, rot)).astype(np.int8)
    base = (store.astype(np.float32) ** 2).sum(2)[:, None, :]
    for l in range(n_lists):
        base[l, 0, L - 1 - l * 13:] = np.inf
    q8 = rng.integers(-127, 128, (11, chunk, rot)).astype(np.int8)
    scale = (rng.random((11, chunk, 1)).astype(np.float32) + 0.1) / 127.0
    lof = rng.integers(0, n_lists, 11).astype(np.int32)
    vals, slots = fused_list_topk_int8(
        jnp.asarray(lof), jnp.asarray(q8), jnp.asarray(store),
        jnp.asarray(base), jnp.asarray(scale), k, interpret=True,
    )
    vals, slots = np.asarray(vals), np.asarray(slots)
    assert vals.shape == (11, chunk, 128)  # kbuf = fused_kbuf(16)
    for c in range(11):
        idots = q8[c].astype(np.int32) @ store[lof[c]].astype(np.int32).T
        dots = idots.astype(np.float32) * scale[c]
        d = base[lof[c], 0][None, :] - 2.0 * dots
        order = np.argsort(d, axis=1, kind="stable")[:, :k]
        np.testing.assert_array_equal(slots[c][:, :k], order)
        np.testing.assert_array_equal(
            vals[c][:, :k], np.take_along_axis(d, order, axis=1)
        )


def test_list_scan_dispatch_validation():
    """The dispatch door rejects mismatched operands loudly: unknown
    strategies, missing/misplaced q_scale, non-int8 operands."""
    lof = jnp.zeros((1,), jnp.int32)
    q = jnp.zeros((1, 8, 16), jnp.float32)
    store = jnp.zeros((1, 128, 16), jnp.float32)
    base = jnp.zeros((1, 1, 128), jnp.float32)
    scale = jnp.ones((1, 8, 1), jnp.float32)
    with pytest.raises(ValueError, match="strategy"):
        list_scan_select_k(lof, q, store, base, 5, strategy="warpsort")
    with pytest.raises(ValueError, match="q_scale"):
        list_scan_select_k(lof, q, store, base, 5, strategy="fused_int8")
    with pytest.raises(ValueError, match="q_scale"):
        list_scan_select_k(lof, q, store, base, 5, strategy="fused",
                           q_scale=scale)
    with pytest.raises(ValueError, match="int8"):
        list_scan_select_k(lof, q, store, base, 5, strategy="fused_int8",
                           q_scale=scale, interpret=True)


# -- int8 fused trim vs the pallas bin trim -----------------------------


@pytest.fixture(scope="module")
def pq_int8_setup():
    rng = np.random.default_rng(7)
    data = _grid(rng, (4000, 32))
    q = _grid(rng, (16, 32))
    idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=16, kmeans_n_iters=4, pq_dim=16), data
    )
    return data, q, idx


@pytest.mark.parametrize("k", [1, 10, 100])
def test_ivf_pq_int8_fused_bit_agrees_with_pallas_trim(pq_int8_setup, k):
    """The acceptance pin: fused int8 recon scan bit-agrees with the
    existing pallas int8 trim — identical f32 distance VALUES (same
    quantization, same op order) and the same neighbor sets, across
    the k ladder. L <= 512 here, so the pallas bin trim is lossless and
    any disagreement is a kernel bug, not trim loss."""
    _, q, idx = pq_int8_setup
    d_p, i_p = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, score_mode="recon8_list",
                            trim_engine="pallas", score_dtype="int8"),
        idx, q, k,
    )
    d_f, i_f = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, trim_engine="fused",
                            score_dtype="int8"),
        idx, q, k,
    )
    np.testing.assert_array_equal(np.asarray(d_p), np.asarray(d_f))
    i_p, i_f = np.asarray(i_p), np.asarray(i_f)
    for r in range(len(q)):
        assert set(i_p[r]) == set(i_f[r])
    # determinism: bit-identical across calls
    d_f2, i_f2 = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, trim_engine="fused",
                            score_dtype="int8"),
        idx, q, k,
    )
    np.testing.assert_array_equal(np.asarray(d_f), np.asarray(d_f2))
    np.testing.assert_array_equal(i_f, np.asarray(i_f2))


def test_ivf_pq_int8_fused_prefilter_excludes(pq_int8_setup, rng):
    """valid-mask/tombstone exclusion: a prefilter must be invisible to
    the int8 fused trim's selection — no filtered id ever returns, and
    the surviving results match the pallas trim's."""
    _, q, idx = pq_int8_setup
    keep = rng.random(4000) < 0.5
    d_f, i_f = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, trim_engine="fused",
                            score_dtype="int8"),
        idx, q, 10, prefilter=keep,
    )
    d_p, i_p = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, score_mode="recon8_list",
                            trim_engine="pallas", score_dtype="int8"),
        idx, q, 10, prefilter=keep,
    )
    i_f = np.asarray(i_f)
    assert not np.isin(i_f[i_f >= 0], np.where(~keep)[0]).any()
    np.testing.assert_array_equal(np.asarray(d_f), np.asarray(d_p))


def test_ivf_pq_fused_kb_monotonic_growth(pq_int8_setup):
    """fused_kb grows monotonically with k and never shrinks — the
    silent-truncation bug class the ivf_flat lazy store pinned."""
    data, q, _ = pq_int8_setup
    idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=16, kmeans_n_iters=4, pq_dim=16), data
    )
    assert idx.fused_kb is None
    sp = lambda: ivf_pq.SearchParams(n_probes=4, trim_engine="fused",
                                     score_dtype="int8")
    ivf_pq.search(sp(), idx, q, 10)
    assert idx.fused_kb == 128
    ivf_pq.search(sp(), idx, q, 200)
    assert idx.fused_kb == 256
    ivf_pq.search(sp(), idx, q, 5)  # smaller k must NOT shrink it
    assert idx.fused_kb == 256


# -- bit-plane kernel vs the quantizer reference ------------------------


def test_fused_bitplane_kernel_matches_quantizer_reference(rng):
    """Kernel-level exactness: per (chunk row, slot) the in-kernel
    estimator score equals the reference computed with
    quantizer.binary_dot / estimate_dot — same integer bit-plane sums,
    same f32 correction, same deterministic smaller-slot ties."""
    from raft_tpu.ops.fused_scan import fused_bitplane_topk
    from raft_tpu.neighbors.quantizer import (
        binary_dot, estimate_dot, pack_bits, quantize_queries,
    )

    n_lists, L, rot, chunk, k, bits = 4, 256, 64, 8, 16, 8
    W = rot // 32
    ncb = 9
    resid = rng.standard_normal((n_lists, L, rot)).astype(np.float32)
    codes = np.asarray(pack_bits((resid >= 0).astype(np.uint32)))
    rnorm = np.sqrt((resid**2).sum(-1)).astype(np.float32)
    o_dot = (np.abs(resid).sum(-1)
             / (np.maximum(rnorm, 1e-30) * np.sqrt(rot))).astype(np.float32)
    pop = np.asarray(jnp.sum(
        jax.lax.population_count(jnp.asarray(codes)).astype(jnp.int32),
        axis=-1)).astype(np.float32)
    # tombstone a ragged tail per list
    base = np.zeros((n_lists, 1, L), np.float32)
    for l in range(n_lists):
        base[l, 0, L - 1 - l * 17:] = np.inf

    qres = rng.standard_normal((ncb, chunk, rot)).astype(np.float32)
    planes, lo, delta = quantize_queries(jnp.asarray(qres), bits)
    qsum = qres.sum(-1).astype(np.float32)
    qcn = (qres**2).sum(-1).astype(np.float32)
    qmeta = np.stack([np.asarray(lo)[..., 0], np.asarray(delta)[..., 0],
                      qsum, qcn], axis=1)
    codes_t = np.transpose(codes, (0, 2, 1))
    meta = np.stack([pop, rnorm, o_dot], axis=1)
    lof = rng.integers(0, n_lists, ncb).astype(np.int32)

    vals, slots = fused_bitplane_topk(
        jnp.asarray(lof),
        jnp.asarray(planes).reshape(ncb, chunk, bits * W),
        jnp.asarray(codes_t), jnp.asarray(meta), jnp.asarray(base),
        jnp.asarray(qmeta), k, rot_dim=rot, bits=bits, interpret=True,
    )
    vals, slots = np.asarray(vals), np.asarray(slots)

    # the oracle runs the quantizer reference helpers under jit — the
    # SAME compiled op sequence the XLA engine uses (XLA CPU contracts
    # mul+add into FMA, so a numpy re-derivation is a ulp off while the
    # two compiled paths agree bitwise; tests/test_fused_int_scan pins
    # exactly that compiled-vs-compiled equality)
    @jax.jit
    def oracle(cand, pl, lo_c, delta_c, qsum_c, qcn_c, pop_l, rn_l, od_l,
               base_l):
        s_u = binary_dot(cand[None, :, :], pl[:, None, :, :])
        s = lo_c * pop_l[None, :] + delta_c * s_u
        est = estimate_dot(s, None, qsum_c[:, None], od_l[None, :], rot)
        return (qcn_c[:, None] + rn_l[None, :] ** 2
                - 2.0 * rn_l[None, :] * est) + base_l[None, :]

    for c in range(ncb):
        l = lof[c]
        d = np.asarray(oracle(
            jnp.asarray(codes[l]), jnp.asarray(planes)[c],
            jnp.asarray(lo)[c], jnp.asarray(delta)[c],
            jnp.asarray(qsum[c]), jnp.asarray(qcn[c]),
            jnp.asarray(pop[l]), jnp.asarray(rnorm[l]),
            jnp.asarray(o_dot[l]), jnp.asarray(base[l, 0]),
        ))
        order = np.argsort(d, axis=1, kind="stable")[:, :k]
        np.testing.assert_array_equal(slots[c][:, :k], order)
        np.testing.assert_array_equal(
            vals[c][:, :k], np.take_along_axis(d, order, axis=1)
        )


# -- RaBitQ fused engine end to end -------------------------------------


@pytest.fixture(scope="module")
def rabitq_setup():
    rng = np.random.default_rng(3)
    data = _grid(rng, (3000, 32))
    q = _grid(rng, (16, 32))
    return data, q


def test_rabitq_fused_matches_xla_reference_exactly(rabitq_setup):
    """Estimator-level pin: without rerank the fused scan returns the
    SAME integer-derived scores and the same neighbors as
    `_search_impl_rabitq` — exact, not approximate (acceptance: 'same
    integer scores, same deterministic tie-break')."""
    data, q = rabitq_setup
    idx = ivf_rabitq.build(
        ivf_rabitq.IndexParams(n_lists=16, kmeans_n_iters=4,
                               store_dataset=False), data)
    for k in (1, 10, 100):
        d_x, i_x = ivf_rabitq.search(
            ivf_rabitq.SearchParams(n_probes=16, scan_engine="xla"),
            idx, q, k)
        d_f, i_f = ivf_rabitq.search(
            ivf_rabitq.SearchParams(n_probes=16, scan_engine="fused"),
            idx, q, k)
        np.testing.assert_array_equal(np.asarray(d_x), np.asarray(d_f))
        i_x, i_f = np.asarray(i_x), np.asarray(i_f)
        for r in range(len(q)):
            assert set(i_x[r]) == set(i_f[r])


def test_rabitq_fused_rerank_recall_parity(rabitq_setup):
    """End-to-end with the exact rerank: the fused engine's recall vs
    ground truth equals the XLA engine's (identical candidate scores ->
    identical shortlists -> identical exact rerank)."""
    from raft_tpu.neighbors import brute_force

    data, q = rabitq_setup
    idx = ivf_rabitq.build(
        ivf_rabitq.IndexParams(n_lists=16, kmeans_n_iters=4), data)
    _, gt = brute_force.knn(data, q, 10)
    gt = np.asarray(gt)
    d_x, i_x = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=16, scan_engine="xla"), idx, q, 10)
    d_f, i_f = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=16, scan_engine="fused"), idx, q, 10)
    np.testing.assert_array_equal(np.asarray(d_x), np.asarray(d_f))
    rec_x = np.mean([len(set(np.asarray(i_x)[r]) & set(gt[r])) / 10
                     for r in range(len(q))])
    rec_f = np.mean([len(set(np.asarray(i_f)[r]) & set(gt[r])) / 10
                     for r in range(len(q))])
    assert rec_f == rec_x
    assert rec_f >= 0.9  # probing every list: near-exact after rerank


def test_rabitq_fused_prefilter_and_kb_growth(rabitq_setup, rng):
    """Tombstone exclusion through the padded slot table, and the
    monotonic fused_kb contract on the bit-plane store."""
    data, q = rabitq_setup
    idx = ivf_rabitq.build(
        ivf_rabitq.IndexParams(n_lists=16, kmeans_n_iters=4,
                               store_dataset=False), data)
    keep = rng.random(3000) < 0.5
    d_f, i_f = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=16, scan_engine="fused"),
        idx, q, 10, prefilter=keep)
    d_x, i_x = ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=16, scan_engine="xla"),
        idx, q, 10, prefilter=keep)
    i_f = np.asarray(i_f)
    assert not np.isin(i_f[i_f >= 0], np.where(~keep)[0]).any()
    np.testing.assert_array_equal(np.asarray(d_f), np.asarray(d_x))
    # kb growth: k=10 -> 128; k=200 -> 256; k=5 keeps 256
    assert idx.fused_kb == 128
    ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=4, scan_engine="fused"),
        idx, q, 200)
    assert idx.fused_kb == 256
    ivf_rabitq.search(
        ivf_rabitq.SearchParams(n_probes=4, scan_engine="fused"),
        idx, q, 5)
    assert idx.fused_kb == 256


# -- dispatch contract --------------------------------------------------


def test_integer_dispatch_resolution(monkeypatch):
    """The tuned integer keys promote the fused kernels ONLY on a TPU
    backend where the geometry fits; explicit strategies always win;
    out-of-envelope auto falls back silently."""
    from raft_tpu.core import config, tuned

    # explicit wins regardless of backend/tuned state
    assert resolve_int8_trim_strategy(256, 32, 10,
                                      strategy="fused_int8") == "fused_int8"
    assert resolve_bitplane_strategy(256, 3, 8, 10,
                                     strategy="xla") == "xla"
    with pytest.raises(ValueError, match="strategy"):
        resolve_int8_trim_strategy(256, 32, 10, strategy="warpsort")
    with pytest.raises(ValueError, match="strategy"):
        resolve_bitplane_strategy(256, 3, 8, 10, strategy="warpsort")
    # no tuned winner -> no promotion
    assert resolve_int8_trim_strategy(256, 32, 10) is None
    assert resolve_bitplane_strategy(256, 3, 8, 10) == "xla"
    monkeypatch.setitem(tuned._load(), INT8_SCAN_KEY, "fused_int8")
    monkeypatch.setitem(tuned._load(), BITPLANE_SCAN_KEY, "fused_bitplane")
    # CPU backend: a chip-measured winner must not flip interpret mode
    assert resolve_int8_trim_strategy(256, 32, 10) is None
    assert resolve_bitplane_strategy(256, 3, 8, 10) == "xla"
    monkeypatch.setattr(config, "is_tpu_backend", lambda: True)
    assert resolve_int8_trim_strategy(256, 32, 10) == "fused_int8"
    assert resolve_bitplane_strategy(256, 3, 8, 10) == "fused_bitplane"
    # past the envelope: auto falls back, never crashes
    assert resolve_int8_trim_strategy(1 << 16, 4096, 10) is None
    assert resolve_bitplane_strategy(1 << 16, 512, 8, 10) == "xla"


def test_explicit_integer_engines_raise_past_envelope(rng):
    """An EXPLICIT integer-engine request past the kernel's caps raises
    loudly instead of silently degrading — the same contract as every
    other fused call site."""
    data = _grid(rng, (2000, 32))
    q = _grid(rng, (4, 32))
    idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=8, kmeans_n_iters=4, pq_dim=16), data
    )
    with pytest.raises(ValueError, match="caps per-list"):
        ivf_pq.search(
            ivf_pq.SearchParams(n_probes=8, trim_engine="fused",
                                score_dtype="int8"),
            idx, q, 300,
        )
    bidx = ivf_rabitq.build(
        ivf_rabitq.IndexParams(n_lists=8, kmeans_n_iters=4,
                               store_dataset=False), data)
    with pytest.raises(ValueError, match="caps scan"):
        ivf_rabitq.search(
            ivf_rabitq.SearchParams(n_probes=8, scan_engine="fused"),
            bidx, q, 300,
        )
    with pytest.raises(ValueError, match="scan_engine"):
        ivf_rabitq.search(
            ivf_rabitq.SearchParams(n_probes=8, scan_engine="warpsort"),
            bidx, q, 10,
        )


def test_ivf_pq_auto_trim_promotes_on_tuned_chip_winner(monkeypatch,
                                                        pq_int8_setup):
    """trim_engine='auto' + score_dtype='int8' resolves through the
    dispatch layer: the tuned chip winner flips the fused trim in
    (changing which engine runs — verified via fused_kb, which only the
    fused trim records), and without the key the default approx trim
    stays."""
    from raft_tpu.core import config, tuned

    data, q, _ = pq_int8_setup
    idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=16, kmeans_n_iters=4, pq_dim=16), data
    )
    sp = ivf_pq.SearchParams(n_probes=4, score_mode="recon8_list",
                             score_dtype="int8")
    assert sp.trim_engine == "auto"
    ivf_pq.search(sp, idx, q, 10)
    assert idx.fused_kb is None  # no tuned key: approx trim ran
    monkeypatch.setitem(tuned._load(), INT8_SCAN_KEY, "fused_int8")
    ivf_pq.search(sp, idx, q, 10)
    assert idx.fused_kb is None  # CPU backend: still no flip
    monkeypatch.setattr(config, "is_tpu_backend", lambda: True)
    ivf_pq.search(sp, idx, q, 10)
    assert idx.fused_kb == 128  # the fused int8 trim ran
