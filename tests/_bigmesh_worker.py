"""Single-process pod-width rehearsal worker (16/32 virtual devices).

Invoked by test_bigmesh.py in a subprocess (the main suite's conftest
pins an 8-device platform). Exercises the scale behaviors world=8 cannot
(VERDICT r3 #4 — the closest available proxy for a v5e-64 slice):

- grouped collectives with MANY groups: comm_split into world/2 pairs is
  the worst case for the masked (G, ...) plane stack in comms.py (O(G)
  payload per collective);
- sharded vs replicated merge topology equality at pod widths;
- uneven collective extend_local growth (batch not divisible by world);
- checkpoint loads spanning mesh sizes (save_local at `world`, fold-load
  onto a half-width mesh — raft-dask's grow/shrink-the-cluster story).
"""

import os
import sys
import tempfile

world = int(sys.argv[1])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={world}"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# compile-bound at pod widths on the 1-core box; correctness unaffected
# (same accelerator the quick tier uses)
jax.config.update("jax_disable_most_optimizations", True)

import numpy as np  # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402

from raft_tpu.comms import Comms, mnmg  # noqa: E402
from raft_tpu.comms.comms import op_t  # noqa: E402
from raft_tpu.neighbors import brute_force, ivf_flat  # noqa: E402

failures = []


def check(name, ok):
    print(("OK " if ok else "FAIL ") + name, flush=True)
    if not ok:
        failures.append(name)


comms = Comms()
check("world", comms.get_size() == world)
rng = np.random.default_rng(world)

# --- 1. many-group comm_split: world/2 pairs (O(G) plane worst case) ---
colors = [r // 2 for r in range(world)]
d = 8
xf = rng.standard_normal((world, d)).astype(np.float32)
ac = comms.comms


def body(x):
    sub = ac.comm_split(colors)
    return sub.allreduce(x[0], op_t.SUM), sub.reducescatter(x[0], op_t.MIN)


outs = jax.shard_map(body, mesh=comms.mesh, in_specs=P("data"),
                     out_specs=(P("data"), P("data")), check_vma=False)(
    comms.shard(xf))
s = np.asarray(outs[0]).reshape(world, -1)
rs = np.asarray(outs[1]).reshape(world, -1)
per = d // 2
ok_s = ok_rs = True
for r in range(world):
    g = [2 * (r // 2), 2 * (r // 2) + 1]
    ok_s &= bool(np.allclose(s[r], xf[g].sum(0), rtol=1e-5))
    pos = r % 2
    ok_rs &= bool(np.array_equal(rs[r],
                                 xf[g].min(0)[pos * per:(pos + 1) * per]))
check("grouped_pairs_allreduce", ok_s)
check("grouped_pairs_reducescatter", ok_rs)

# --- 2. exact kNN: sharded vs replicated merge equality at pod width ---
n, dim, k = 24 * world + 5, 16, 4
data = rng.standard_normal((n, dim)).astype(np.float32)
q = data[: 2 * world]  # nq divisible by nothing in particular pre-pad
rv, ri = mnmg.knn(comms, data, q, k, query_mode="replicated")
sv, si = mnmg.knn(comms, data, q, k, query_mode="sharded")
check("knn_merge_topologies_agree",
      np.array_equal(np.asarray(ri), np.asarray(si))
      and np.allclose(np.asarray(rv), np.asarray(sv), rtol=1e-5, atol=1e-5))
_, ti = brute_force.knn(data, q, k)
check("knn_matches_bruteforce",
      np.array_equal(np.sort(np.asarray(ri)), np.sort(np.asarray(ti))))

# --- 2b. forced tournament schedule at pod width: identical to the
# (CPU-default) allgather results through the public search ---
from raft_tpu.core import tuned  # noqa: E402

_orig_tuned_path = tuned._PATH
_fd, _tmp_tuned = tempfile.mkstemp(suffix=f"_bigmesh_tuned_{world}.json")
try:
    with os.fdopen(_fd, "w") as _f:
        # measured_on must match this process's backend or the dispatch
        # (correctly) ignores the key
        _f.write('{"mnmg_replicated_merge_schedule": "tournament", '
                 '"hints": {"merge_schedule_measured_on": "cpu"}}')
    tuned._PATH = _tmp_tuned
    tuned.reload()
    jax.clear_caches()  # the schedule bakes into traces
    tv_, ti_ = mnmg.knn(comms, data, q, k, query_mode="replicated")
    check("tournament_matches_allgather_at_width",
          np.array_equal(np.asarray(ti_), np.asarray(ri))
          and np.allclose(np.asarray(tv_), np.asarray(rv), rtol=1e-5))
finally:
    os.remove(_tmp_tuned)
    tuned._PATH = _orig_tuned_path
    tuned.reload()
    jax.clear_caches()

# --- 3. build_local + UNEVEN extend_local + reachability ---
params = ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=3)
idx = mnmg.ivf_flat_build_local(comms, params, data)
extra = rng.standard_normal((world + 3, dim)).astype(np.float32)  # uneven
idx2 = mnmg.ivf_flat_extend_local(idx, extra)
check("extend_local_n", idx2.n == n + world + 3)
_, ei = mnmg.ivf_flat_search(idx2, extra[:4], 1, n_probes=8)
check("extend_local_reachable", bool(np.asarray(ei).min() >= n))

# --- 4. sharded checkpoint: save at `world`, fold-load at world/2 ---
with tempfile.TemporaryDirectory() as td:
    ck = os.path.join(td, "bigmesh.rtivf")
    mnmg.ivf_flat_save_local(ck, idx2)
    half = Comms(mesh=Mesh(np.array(jax.devices()[: world // 2]),
                           axis_names=("data",)))
    loaded = mnmg.ivf_flat_load(half, ck)
    check("fold_load_n", loaded.n == idx2.n)
    _, fi = mnmg.ivf_flat_search(loaded, q[:8], k, n_probes=8)
    _, oi = mnmg.ivf_flat_search(idx2, q[:8], k, n_probes=8)
    check("fold_load_search_agrees",
          np.array_equal(np.asarray(fi), np.asarray(oi)))

if failures:
    print("WORKER_FAILURES: " + ", ".join(failures))
    sys.exit(1)
print("BIGMESH_OK")
