"""MNMG algorithm tests on the virtual 8-device mesh (the reference tests
distributed algorithms with multi-process-on-one-node; survey §4)."""

import numpy as np
import pytest
from sklearn.metrics import adjusted_rand_score

from raft_tpu.comms import Comms, mnmg
from raft_tpu.cluster import kmeans
from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq
from raft_tpu.random import make_blobs


@pytest.fixture(scope="module")
def comms():
    return Comms()


@pytest.fixture(scope="module")
def blobs():
    data, labels = make_blobs(4003, 16, n_clusters=6, cluster_std=0.4, seed=13)
    return np.asarray(data), np.asarray(labels)


# Shared full-data indexes (VERDICT r3 #8 test-cost discipline): many
# tests search the same geometry and never mutate the index — distributed
# indexes are immutable (extend returns a new object; the lazily derived
# per-rank stores are idempotent caches), so one build serves them all.
# Tests that extend, use other params/metrics, or slice the data still
# build their own.


@pytest.fixture(scope="module")
def flat16(comms, blobs):
    data, _ = blobs
    return mnmg.ivf_flat_build(
        comms, ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=10), data)


@pytest.fixture(scope="module")
def pq16(comms, blobs):
    data, _ = blobs
    return mnmg.ivf_pq_build(
        comms, ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=8),
        data)


def test_distributed_kmeans_matches_quality(comms, blobs):
    data, true_labels = blobs
    centers, inertia, n_iter = mnmg.kmeans_fit(comms, data, 6, seed=0)
    assert centers.shape == (6, 16)
    pred = np.asarray(mnmg.kmeans_predict(comms, data, centers))
    assert pred.shape == (len(data),)
    assert adjusted_rand_score(true_labels, pred) > 0.95
    # single-device reference gets comparable inertia
    _, inertia_local, _ = kmeans.fit(data, n_clusters=6, seed=0)
    assert inertia <= inertia_local * 1.1


def test_distributed_knn_exact_match(comms, blobs):
    data, _ = blobs
    q = data[:17]
    dv, di = mnmg.knn(comms, data, q, 10)
    lv, li = brute_force.knn(data, q, 10)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(lv), rtol=1e-3, atol=1e-3)
    # distances of returned ids must match exact distances (ties may permute ids)
    got = np.sort(np.asarray(di), axis=1)
    # self must be among neighbors
    assert all(i in set(np.asarray(di)[i].tolist()) for i in range(17))


def test_distributed_knn_compute_dtype(comms, blobs):
    """compute_dtype threads through the sharded scan: near-exact vs the
    f32 merge result, same id space, merge semantics unchanged."""
    import jax.numpy as jnp

    data, _ = blobs
    q = data[:17]
    dv, di = mnmg.knn(comms, data, q, 10, compute_dtype=jnp.bfloat16)
    _, li = mnmg.knn(comms, data, q, 10)
    di, li = np.asarray(di), np.asarray(li)
    overlap = np.mean(
        [len(set(di[j]) & set(li[j])) / 10 for j in range(len(q))]
    )
    assert overlap >= 0.95, overlap
    assert all(j in set(di[j].tolist()) for j in range(17))  # self found
    assert np.isfinite(np.asarray(dv)).all()


def test_distributed_ivf_flat(comms, blobs, flat16):
    data, _ = blobs
    q = data[:29]
    dindex = flat16
    dv, di = mnmg.ivf_flat_search(dindex, q, 5, n_probes=16)
    _, truth = brute_force.knn(data, q, 5)
    truth = np.asarray(truth)
    di = np.asarray(di)
    hits = sum(len(set(a.tolist()) & set(b.tolist())) for a, b in zip(di, truth))
    assert hits / truth.size >= 0.99  # all lists probed -> near exact


def test_distributed_ivf_flat_extend(comms, blobs):
    """Distributed IVF-Flat extend: second half appended SPMD; near-exact
    recall with all lists probed (exact-within-probed-lists contract)."""
    data, _ = blobs
    half = len(data) // 2
    q = data[:29]
    params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=10)
    dindex = mnmg.ivf_flat_build(comms, params, data[:half])
    dindex = mnmg.ivf_flat_extend(dindex, data[half:])
    assert dindex.n == len(data)
    assert int(dindex.list_sizes.sum()) == len(data)
    dv, di = mnmg.ivf_flat_search(dindex, q, 5, n_probes=16)
    _, truth = brute_force.knn(data, q, 5)
    truth, di = np.asarray(truth), np.asarray(di)
    hits = sum(len(set(a.tolist()) & set(b.tolist())) for a, b in zip(di, truth))
    assert hits / truth.size >= 0.99, hits / truth.size


def test_distributed_build_balanced_lists(comms, blobs, pq16):
    """The balanced coarse trainer keeps every list populated (the
    adjust_centers re-seed; empty/starved lists inflate max_list padding
    and waste scan work in the list-major engines)."""
    from raft_tpu.neighbors import ivf_pq

    data, _ = blobs
    dindex = pq16
    global_sizes = dindex.list_sizes.sum(axis=0)  # (n_lists,)
    assert int(global_sizes.sum()) == len(data)
    assert int(global_sizes.min()) > 0, global_sizes.tolist()
    mean = len(data) / 16
    assert int(global_sizes.max()) <= 6 * mean, global_sizes.tolist()


def test_distributed_extend_tiny_batch(comms, blobs):
    """Regression: a batch smaller than the rank count leaves trailing
    ranks with empty shards — the host bookkeeping must not crash."""
    from raft_tpu.neighbors import ivf_pq

    data, _ = blobs
    params = ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=4)
    dindex = mnmg.ivf_pq_build(comms, params, data[:500])
    dindex = mnmg.ivf_pq_extend(dindex, data[500:505])  # 5 rows on 8 ranks
    assert dindex.n == 505
    assert int(dindex.list_sizes.sum()) == 505
    fparams = ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4)
    findex = mnmg.ivf_flat_build(comms, fparams, data[:500])
    findex = mnmg.ivf_flat_extend(findex, data[500:505])
    assert findex.n == 505 and int(findex.list_sizes.sum()) == 505
    # the 5 appended rows are findable as their own nearest neighbors
    _, di = mnmg.ivf_flat_search(findex, data[500:505], 1, n_probes=8)
    assert sorted(np.asarray(di).ravel().tolist()) == [500, 501, 502, 503, 504]


def test_distributed_ivf_pq(comms, blobs, pq16):
    data, _ = blobs
    q = data[:29]
    dindex = pq16
    dv, di = mnmg.ivf_pq_search(dindex, q, 5, n_probes=16)
    _, truth = brute_force.knn(data, q, 5)
    truth = np.asarray(truth)
    di = np.asarray(di)
    assert di.shape == (29, 5)
    # every returned id is a valid global row
    assert di.min() >= 0 and di.max() < len(data)
    hits = sum(len(set(a.tolist()) & set(b.tolist())) for a, b in zip(di, truth))
    # PQ-quantized scoring over all lists: recall gated like the
    # single-device ivf_pq tests (quantization-bound, not sharding-bound)
    assert hits / truth.size >= 0.5, hits / truth.size
    # distances sorted best-first
    assert np.all(np.diff(np.asarray(dv), axis=1) >= -1e-4)


def test_distributed_ivf_pq_listmajor_engine(comms, blobs, pq16):
    """The recon8_list (list-major) engine — the single-chip flagship — is
    reachable from the MNMG path and agrees with the LUT engine."""
    data, _ = blobs
    q = data[:29]
    dindex = pq16
    lv, li = mnmg.ivf_pq_search(dindex, q, 5, n_probes=16, engine="recon8_list")
    qv, qi = mnmg.ivf_pq_search(dindex, q, 5, n_probes=16, engine="lut")
    li, qi = np.asarray(li), np.asarray(qi)
    assert li.shape == (29, 5)
    assert li.min() >= 0 and li.max() < len(data)
    # engines score the same candidates modulo int8-reconstruction noise:
    # overlap of returned ids should be high
    hits = sum(len(set(a.tolist()) & set(b.tolist())) for a, b in zip(li, qi))
    assert hits / qi.size >= 0.7, hits / qi.size
    # the auto heuristic routes this (nq*probes/lists = 29) to list-major
    av, ai = mnmg.ivf_pq_search(dindex, q, 5, n_probes=16, engine="auto")
    np.testing.assert_array_equal(np.asarray(ai), li)


def test_distributed_ivf_pq_extend(comms, blobs):
    """Distributed extend: second half appended SPMD; recall matches a
    one-shot build of the full data."""
    from raft_tpu.neighbors import ivf_pq

    data, _ = blobs
    half = len(data) // 2
    q = data[:29]
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=8)
    dindex = mnmg.ivf_pq_build(comms, params, data[:half])
    dindex = mnmg.ivf_pq_extend(dindex, data[half:])
    assert dindex.n == len(data)
    dv, di = mnmg.ivf_pq_search(dindex, q, 5, n_probes=16)
    di = np.asarray(di)
    assert di.min() >= 0 and di.max() < len(data)
    # extended ids exist in results when they are true neighbors
    _, truth = brute_force.knn(data, q, 5)
    truth = np.asarray(truth)
    hits = sum(len(set(a.tolist()) & set(b.tolist())) for a, b in zip(di, truth))
    assert hits / truth.size >= 0.5, hits / truth.size
    # per-rank fill counts track the appended rows
    assert int(dindex.list_sizes.sum()) == len(data)


def test_distributed_ivf_pq_recall_parity_with_single_device(comms, blobs,
                                                             pq16):
    """VERDICT round-1 gate: the 8-device mesh build reaches recall parity
    with the single-device index on the same data/config."""
    from raft_tpu.neighbors import ivf_pq

    data, _ = blobs
    q = data[:64]
    k = 10
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=8)
    _, truth = brute_force.knn(data, q, k)
    truth = np.asarray(truth)

    dindex = pq16
    _, di = mnmg.ivf_pq_search(dindex, q, k, n_probes=16)
    dist_recall = sum(
        len(set(a.tolist()) & set(b.tolist())) for a, b in zip(np.asarray(di), truth)
    ) / truth.size

    sindex = ivf_pq.build(params, data)
    _, si = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), sindex, q, k)
    single_recall = sum(
        len(set(a.tolist()) & set(b.tolist())) for a, b in zip(np.asarray(si), truth)
    ) / truth.size

    # same quantization budget => same recall regime (different RNG paths)
    assert dist_recall >= single_recall - 0.05, (dist_recall, single_recall)


def test_distributed_ivf_pq_inner_product(comms, blobs):
    """IP metric: coarse training assigns by dot against normalized centers
    (regression: the distributed EM used to ignore params.metric)."""
    from raft_tpu.distance.distance_types import DistanceType
    from raft_tpu.neighbors import ivf_pq

    data, _ = blobs
    data = data + 2.0  # keep dots discriminative
    q = data[:29]
    params = ivf_pq.IndexParams(
        n_lists=16, pq_dim=8, kmeans_n_iters=8, metric=DistanceType.InnerProduct
    )
    dindex = mnmg.ivf_pq_build(comms, params, data)
    dv, di = mnmg.ivf_pq_search(dindex, q, 5, n_probes=16)
    _, truth = brute_force.knn(data, q, 5, metric="inner_product")
    truth, di = np.asarray(truth), np.asarray(di)
    hits = sum(len(set(a.tolist()) & set(b.tolist())) for a, b in zip(di, truth))
    assert hits / truth.size >= 0.5, hits / truth.size
    # IP scores come back best(largest)-first
    assert np.all(np.diff(np.asarray(dv), axis=1) <= 1e-3)


def test_distributed_ivf_pq_n_lists_guard(comms):
    from raft_tpu.neighbors import ivf_pq

    data = np.zeros((10, 8), np.float32)
    with pytest.raises(ValueError, match="n_lists"):
        mnmg.ivf_pq_build(comms, ivf_pq.IndexParams(n_lists=64, pq_dim=4), data)


def test_distributed_ivf_pq_save_load(comms, blobs, tmp_path):
    """Distributed index checkpoint: same-mesh round-trip preserves search
    results; a fold-merge load (stored ranks = 2x mesh) keeps recall and
    stays extendable."""
    from raft_tpu.neighbors import ivf_pq

    data, _ = blobs
    q = data[:29]
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=6)
    dindex = mnmg.ivf_pq_build(comms, params, data[:3500])
    dv, di = mnmg.ivf_pq_search(dindex, q, 5, n_probes=16)

    path = str(tmp_path / "dist.idx")
    mnmg.ivf_pq_save(path, dindex)
    loaded = mnmg.ivf_pq_load(comms, path)
    lv, li = mnmg.ivf_pq_search(loaded, q, 5, n_probes=16)
    np.testing.assert_array_equal(np.asarray(li), np.asarray(di))
    np.testing.assert_allclose(np.asarray(lv), np.asarray(dv), rtol=1e-5)

    # fold-merge: fake a 16-rank save by splitting each rank's table in two
    r, n_lists, w, pq = np.asarray(dindex.codes).shape
    half = w // 2
    codes16 = np.asarray(dindex.codes).reshape(r, n_lists, 2, half, pq)
    codes16 = np.moveaxis(codes16, 2, 1).reshape(2 * r, n_lists, half, pq)
    gids16 = dindex.host_gids.reshape(r, n_lists, 2, half)
    gids16 = np.moveaxis(gids16, 2, 1).reshape(2 * r, n_lists, half)
    sizes16 = np.stack([(gids16[rr] >= 0).sum(axis=1) for rr in range(2 * r)])
    from raft_tpu.core.serialize import serialize_arrays

    path2 = str(tmp_path / "dist16.idx")
    serialize_arrays(path2, {
        "rotation": dindex.rotation, "centers": dindex.centers,
        "pq_centers": dindex.pq_centers, "codes": codes16,
        "host_gids": gids16, "list_sizes": sizes16.astype(np.int32),
    }, {
        "kind": "mnmg_ivf_pq", "version": 1, "n": dindex.n, "n_ranks": 2 * r,
        "metric": int(params.metric), "n_lists": 16, "pq_dim": 8,
        "pq_bits": 8, "per_cluster": False,
    })
    merged = mnmg.ivf_pq_load(comms, path2)
    assert int(merged.list_sizes.sum()) == 3500
    # valid slots form a prefix (extend contract)
    hg = merged.host_gids
    for rr in range(r):
        valid = hg[rr] >= 0
        assert np.all(valid[:, :-1] >= valid[:, 1:])  # monotone per row
    mv, mi = mnmg.ivf_pq_search(merged, q, 5, n_probes=16)
    hits = sum(len(set(a.tolist()) & set(b.tolist()))
               for a, b in zip(np.asarray(mi), np.asarray(di)))
    assert hits / np.asarray(di).size >= 0.9  # same index, re-partitioned
    merged = mnmg.ivf_pq_extend(merged, data[3500:4000])
    assert merged.n == 4000 and int(merged.list_sizes.sum()) == 4000


def test_distributed_ivf_flat_save_load(comms, blobs, tmp_path):
    data, _ = blobs
    q = data[:19]
    params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=6)
    dindex = mnmg.ivf_flat_build(comms, params, data[:3000])
    dv, di = mnmg.ivf_flat_search(dindex, q, 5, n_probes=16)
    path = str(tmp_path / "flat.idx")
    mnmg.ivf_flat_save(path, dindex)
    loaded = mnmg.ivf_flat_load(comms, path)
    lv, li = mnmg.ivf_flat_search(loaded, q, 5, n_probes=16)
    np.testing.assert_array_equal(np.asarray(li), np.asarray(di))
    np.testing.assert_allclose(np.asarray(lv), np.asarray(dv), rtol=1e-5)
    loaded = mnmg.ivf_flat_extend(loaded, data[3000:3400])
    assert loaded.n == 3400 and int(loaded.list_sizes.sum()) == 3400

    # fold-merge: fake a 16-rank save by splitting each rank's table in
    # two, then load onto the 8-rank mesh (covers _fold_merge_tables for
    # the (d,)-trailed float32 store)
    from raft_tpu.core.serialize import serialize_arrays

    r, n_lists, w, d = np.asarray(dindex.list_data).shape
    half = w // 2
    ld16 = np.asarray(dindex.list_data).reshape(r, n_lists, 2, half, d)
    ld16 = np.moveaxis(ld16, 2, 1).reshape(2 * r, n_lists, half, d)
    gids16 = dindex.host_gids.reshape(r, n_lists, 2, half)
    gids16 = np.moveaxis(gids16, 2, 1).reshape(2 * r, n_lists, half)
    sizes16 = np.stack([(gids16[rr] >= 0).sum(axis=1) for rr in range(2 * r)])
    path2 = str(tmp_path / "flat16.idx")
    serialize_arrays(path2, {
        "centers": dindex.centers, "list_data": ld16,
        "host_gids": gids16, "list_sizes": sizes16.astype(np.int32),
    }, {
        "kind": "mnmg_ivf_flat", "version": 1, "n": dindex.n,
        "n_ranks": 2 * r, "metric": int(params.metric), "n_lists": 16,
    })
    merged = mnmg.ivf_flat_load(comms, path2)
    assert int(merged.list_sizes.sum()) == 3000
    hg = merged.host_gids
    valid = hg >= 0
    assert np.all(valid[:, :, :-1] >= valid[:, :, 1:])  # prefix-compacted
    mv, mi = mnmg.ivf_flat_search(merged, q, 5, n_probes=16)
    np.testing.assert_array_equal(np.asarray(mi), np.asarray(di))


def test_distributed_ivf_pq_empty_shards(comms):
    """n < n_ranks leaves trailing ranks with empty shards — the build
    must still produce a searchable index (regression: div-by-zero in the
    per-shard encode)."""
    from raft_tpu.neighbors import ivf_pq

    data = np.random.default_rng(0).standard_normal((9, 8)).astype(np.float32)
    didx = mnmg.ivf_pq_build(
        comms, ivf_pq.IndexParams(n_lists=2, pq_dim=4, kmeans_n_iters=2), data
    )
    dv, di = mnmg.ivf_pq_search(didx, data[:3], 2, n_probes=2)
    di = np.asarray(di)
    assert di.shape == (3, 2)
    assert di.min() >= 0 and di.max() < len(data)


def test_distribute_index_bridge(comms, blobs):
    """Single-chip build -> mesh serving: distributed search over the
    block-split lists matches the single-chip search's recall, ids stay
    the caller's, and refine is refused (no contiguous rank ownership)."""
    from raft_tpu.neighbors import ivf_pq, brute_force

    data, _ = blobs
    q = data[:32]
    _, truth = brute_force.knn(data, q, 5, metric="sqeuclidean")
    t = np.asarray(truth)

    si = ivf_pq.build(ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=4), data)
    _, s_ids = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), si, q, 5)
    di = mnmg.distribute_index(comms, si)
    _, d_ids = mnmg.ivf_pq_search(di, q, 5, n_probes=8)

    def rec(ids):
        g = np.asarray(ids)
        return float(np.mean([len(set(g[i]) & set(t[i])) / 5 for i in range(32)]))

    assert abs(rec(s_ids) - rec(d_ids)) < 0.1
    assert np.asarray(d_ids).min() >= -1 and np.asarray(d_ids).max() < data.shape[0]
    with pytest.raises(ValueError):
        mnmg.ivf_pq_search(di, q, 5, refine_dataset=data)


def test_distribute_index_flat_and_flag_persistence(comms, blobs, tmp_path):
    """The flat branch of the bridge, plus: bridged indexes refuse extend,
    and the flag survives save/load (a reloaded bridged index must still
    refuse refine/extend — silent wrong results otherwise)."""
    from raft_tpu.neighbors import ivf_flat as sc_flat, brute_force

    data, _ = blobs
    q = data[:32]
    _, truth = brute_force.knn(data, q, 5, metric="sqeuclidean")
    t = np.asarray(truth)

    si = sc_flat.build(sc_flat.IndexParams(n_lists=8, kmeans_n_iters=4), data)
    di = mnmg.distribute_index(comms, si)
    _, ids = mnmg.ivf_flat_search(di, q, 5, n_probes=8)
    g = np.asarray(ids)
    rec = float(np.mean([len(set(g[i]) & set(t[i])) / 5 for i in range(32)]))
    assert rec > 0.95, rec

    with pytest.raises(ValueError):
        mnmg.ivf_flat_extend(di, data[:8])

    path = str(tmp_path / "bridged.rtivf")
    mnmg.ivf_flat_save(path, di)
    loaded = mnmg.ivf_flat_load(comms, path)
    assert loaded.bridged
    with pytest.raises(ValueError):
        mnmg.ivf_flat_extend(loaded, data[:8])


def test_distributed_prefilter(comms, blobs, flat16, pq16):
    """prefilter excludes global ids on every rank in knn, ivf_flat, and
    ivf_pq distributed search — parity with the single-index prefilter."""
    from raft_tpu.core import Bitset

    data, _ = blobs
    q = data[:13]
    n = len(data)
    rng = np.random.default_rng(5)
    mask = rng.random(n) < 0.5

    # exact kNN vs filtered oracle
    d = ((q[:, None, :] - data[None, :, :]) ** 2).sum(-1)
    d = np.where(mask[None, :], d, np.inf)
    want = np.argsort(d, axis=1, kind="stable")[:, :6]
    dv, di = mnmg.knn(comms, data, q, 6, prefilter=mask)
    np.testing.assert_array_equal(np.asarray(di), want)

    # IVF-Flat, all lists probed: nothing filtered returns; near-exact
    dindex = flat16
    assert dindex.id_bound == n
    _, fi = mnmg.ivf_flat_search(dindex, q, 6, n_probes=16, prefilter=mask)
    got = np.asarray(fi)
    assert np.all((got == -1) | mask[np.maximum(got, 0)])
    hits = sum(len(set(a.tolist()) & set(b.tolist())) for a, b in zip(got, want))
    assert hits / want.size >= 0.99

    # IVF-PQ, both engines: filter invariant + unfiltered-identical check
    pindex = pq16
    for eng in ("lut", "recon8_list"):
        _, pi = mnmg.ivf_pq_search(pindex, q, 6, n_probes=16, engine=eng,
                                   prefilter=mask)
        gp = np.asarray(pi)
        assert np.all((gp == -1) | mask[np.maximum(gp, 0)]), eng
        base = np.asarray(mnmg.ivf_pq_search(pindex, q, 6, n_probes=16,
                                             engine=eng)[1])
        allow = np.asarray(mnmg.ivf_pq_search(
            pindex, q, 6, n_probes=16, engine=eng,
            prefilter=Bitset.full(n))[1])
        np.testing.assert_array_equal(allow, base)

    # refined pipeline composes: _refine_local drops gid=-1 candidates
    _, ri = mnmg.ivf_pq_search(pindex, q, 6, n_probes=16, engine="recon8_list",
                               refine_dataset=data, prefilter=mask)
    gr = np.asarray(ri)
    assert np.all((gr == -1) | mask[np.maximum(gr, 0)])

    # length validation
    with pytest.raises(ValueError, match="covers"):
        mnmg.ivf_flat_search(dindex, q, 3, prefilter=Bitset.full(n + 7))


def test_query_sharded_mode_matches_replicated(comms, blobs, flat16, pq16):
    """query_mode="sharded" (all_to_all merge, R× less traffic) returns
    the same values as the replicated allgather merge for knn, ivf_flat,
    and ivf_pq search — including nq not divisible by the comm size,
    refine, and prefilter composition."""
    data, _ = blobs
    n = len(data)
    q = data[:13]  # 13 % 8 != 0: exercises query padding + strip
    rng = np.random.default_rng(11)
    mask = rng.random(n) < 0.6

    rv, ri = mnmg.knn(comms, data, q, 5, query_mode="replicated")
    sv, si = mnmg.knn(comms, data, q, 5, query_mode="sharded")
    np.testing.assert_allclose(np.asarray(sv), np.asarray(rv), rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(si), np.asarray(ri))

    fidx = flat16
    rv, ri = mnmg.ivf_flat_search(fidx, q, 5, n_probes=16,
                                  query_mode="replicated")
    sv, si = mnmg.ivf_flat_search(fidx, q, 5, n_probes=16,
                                  query_mode="sharded")
    np.testing.assert_array_equal(np.asarray(si), np.asarray(ri))

    pidx = pq16
    for kwargs in (
        dict(engine="lut"),
        dict(engine="recon8_list"),
        dict(engine="recon8_list", refine_dataset=data),
        dict(engine="lut", prefilter=mask),
    ):
        rv, ri = mnmg.ivf_pq_search(pidx, q, 5, n_probes=16,
                                    query_mode="replicated", **kwargs)
        sv, si = mnmg.ivf_pq_search(pidx, q, 5, n_probes=16,
                                    query_mode="sharded", **kwargs)
        np.testing.assert_array_equal(np.asarray(si), np.asarray(ri),
                                      err_msg=str(kwargs))
        np.testing.assert_allclose(np.asarray(sv), np.asarray(rv),
                                   rtol=1e-5, atol=1e-5, err_msg=str(kwargs))

    with pytest.raises(ValueError, match="query_mode"):
        mnmg.knn(comms, data, q, 5, query_mode="bogus")


def test_extend_local(comms, blobs):
    """Collective multi-controller extend (single-process degenerate):
    new rows get ids continuing the build's id space; search over the
    extended index matches brute force over the concatenation."""
    data, _ = blobs
    n0 = 3000
    base, extra = data[:n0], data[n0:3600]
    q = data[:24]

    # IVF-Flat: build_local + extend_local, searched near-exactly
    params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=6)
    fidx = mnmg.ivf_flat_build_local(comms, params, base)
    assert fidx.local_gids is not None and fidx.local_sizes is not None
    fidx2 = mnmg.ivf_flat_extend_local(fidx, extra)
    assert fidx2.n == 3600 and fidx2.id_bound == 3600
    _, ti = brute_force.knn(data[:3600], q, 6, metric="sqeuclidean")
    _, fi = mnmg.ivf_flat_search(fidx2, q, 6, n_probes=16)
    ti, fi = np.asarray(ti), np.asarray(fi)
    rec = np.mean([len(set(fi[i]) & set(ti[i])) / 6 for i in range(len(q))])
    assert rec >= 0.99, rec
    # ids above n0 (the new rows) must be reachable
    probe = extra[:4]
    _, pi_ = mnmg.ivf_flat_search(fidx2, probe, 1, n_probes=16)
    assert np.all(np.asarray(pi_).ravel() >= n0)

    # empty batch is the identity
    assert mnmg.ivf_flat_extend_local(fidx, base[:0]) is fidx

    # IVF-PQ: extend_local + search; refined pipeline refuses extended
    pparams = ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=6)
    pidx = mnmg.ivf_pq_build_local(comms, pparams, base)
    pidx2 = mnmg.ivf_pq_extend_local(pidx, extra)
    assert pidx2.n == 3600 and pidx2.extended
    _, gi = mnmg.ivf_pq_search(pidx2, q, 6, n_probes=16)
    gi = np.asarray(gi)
    rec_p = np.mean([len(set(gi[i]) & set(ti[i])) / 6 for i in range(len(q))])
    assert rec_p >= 0.5, rec_p
    assert gi.max() < 3600
    with pytest.raises(ValueError, match="extend"):
        mnmg.ivf_pq_search(pidx2, q, 6, n_probes=16, refine_dataset=data[:3600])

    # chained extend_local keeps growing the same id space
    fidx3 = mnmg.ivf_flat_extend_local(fidx2, data[3600:3700])
    assert fidx3.n == 3700
    _, ci = mnmg.ivf_flat_search(fidx3, data[3650:3654], 1, n_probes=16)
    assert np.all(np.asarray(ci).ravel() >= 3600)

    # loaded/bridged indexes refuse (no per-process mirrors)
    si = ivf_pq.build(ivf_pq.IndexParams(n_lists=8, pq_dim=8,
                                         kmeans_n_iters=4), np.asarray(base))
    bridged = mnmg.distribute_index(comms, si)
    with pytest.raises(ValueError, match="bridged"):
        mnmg.ivf_pq_extend_local(bridged, extra)


def test_ivf_pq_extend_local_nondividing_pq_dim(comms, blobs):
    """extend_local on a geometry where pq_dim does not divide dim
    (rot_dim = pq_dim*ceil(dim/pq_dim) > dim): row-width validation must
    accept (n, dim) batches — the rotation maps dim -> rot_dim, so the
    INPUT width is rotation.shape[1], not rot_dim (ADVICE r3)."""
    data, _ = blobs
    base, extra = data[:3000], data[3000:3200]
    pparams = ivf_pq.IndexParams(n_lists=16, pq_dim=5, kmeans_n_iters=6)
    pidx = mnmg.ivf_pq_build_local(comms, pparams, base)
    assert int(pidx.rotation.shape[0]) > int(pidx.rotation.shape[1])  # rot_dim > dim
    pidx2 = mnmg.ivf_pq_extend_local(pidx, extra)
    assert pidx2.n == 3200
    # appended rows are reachable under their continued ids
    _, pi_ = mnmg.ivf_pq_search(pidx2, extra[:4], 1, n_probes=16)
    assert np.all(np.asarray(pi_).ravel() >= 3000)
    # a genuinely wrong width still rejects, quoting the INPUT dim
    with pytest.raises(ValueError, match=r"\(n, 16\)"):
        mnmg.ivf_pq_extend_local(pidx2, np.zeros((4, 20), np.float32))


def test_extend_local_after_load(comms, blobs, tmp_path):
    """Checkpoint loads keep per-process mirror slices, so the collective
    extend_local works on a loaded index (the round-trip a serving
    cluster does: build once, load onto the mesh, keep ingesting)."""
    data, _ = blobs
    path = str(tmp_path / "loadext.rtivf")
    params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=6)
    built = mnmg.ivf_flat_build(comms, params, data[:3000])
    mnmg.ivf_flat_save(path, built)
    loaded = mnmg.ivf_flat_load(comms, path)
    assert loaded.local_gids is not None
    grown = mnmg.ivf_flat_extend_local(loaded, data[3000:3200])
    assert grown.n == 3200
    _, gi = mnmg.ivf_flat_search(grown, data[3100:3104], 1, n_probes=16)
    assert np.all(np.asarray(gi).ravel() == np.arange(3100, 3104))


def test_sharded_checkpoint_roundtrip(comms, blobs, tmp_path):
    """save_local (per-process part files + manifest) round-trips through
    the kind-dispatching load for both index types, preserves search
    results exactly, and supports extend_local after load."""
    data, _ = blobs
    q = data[:16]

    params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=6)
    fidx = mnmg.ivf_flat_build_local(comms, params, data[:3000])
    fpath = str(tmp_path / "sharded.rtivf")
    mnmg.ivf_flat_save_local(fpath, fidx)
    import os
    assert os.path.exists(fpath) and os.path.exists(fpath + ".part0")
    floaded = mnmg.ivf_flat_load(comms, fpath)
    assert floaded.n == 3000 and floaded.local_gids is not None
    _, i0 = mnmg.ivf_flat_search(fidx, q, 5, n_probes=16)
    _, i1 = mnmg.ivf_flat_search(floaded, q, 5, n_probes=16)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
    grown = mnmg.ivf_flat_extend_local(floaded, data[3000:3100])
    assert grown.n == 3100

    pparams = ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=6)
    pidx = mnmg.ivf_pq_build_local(comms, pparams, data[:3000])
    ppath = str(tmp_path / "sharded.rtpq")
    mnmg.ivf_pq_save_local(ppath, pidx)
    ploaded = mnmg.ivf_pq_load(comms, ppath)
    assert ploaded.n == 3000
    _, p0 = mnmg.ivf_pq_search(pidx, q, 5, n_probes=16, engine="lut")
    _, p1 = mnmg.ivf_pq_search(ploaded, q, 5, n_probes=16, engine="lut")
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(p0))

    # fold-merge: 8 stored rank shards load onto a 4-device mesh
    small = Comms(n_devices=4)
    ffold = mnmg.ivf_flat_load(small, fpath)
    assert ffold.n == 3000 and ffold.list_data.shape[0] == 4
    _, i2 = mnmg.ivf_flat_search(ffold, q, 5, n_probes=16)
    np.testing.assert_array_equal(np.asarray(i2), np.asarray(i0))

    # single-controller interop: a sharded load's assembly doubles as
    # the global host mirrors, so classic extend/save work on it...
    assert floaded.host_gids is not None
    classic_grown = mnmg.ivf_flat_extend(floaded, data[3000:3050])
    assert classic_grown.n == 3050
    reexport = str(tmp_path / "reexport.rtivf")
    mnmg.ivf_flat_save(reexport, floaded)
    assert mnmg.ivf_flat_load(comms, reexport).n == 3000
    # ...and a classic *_build index sharded-saves via its host mirrors
    params2 = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=6)
    built2 = mnmg.ivf_flat_build(comms, params2, data[:2000])
    spath2 = str(tmp_path / "classic_sharded.rtivf")
    mnmg.ivf_flat_save_local(spath2, built2)
    assert mnmg.ivf_flat_load(comms, spath2).n == 2000

    # classic single-file load still works (kind dispatch)
    spath = str(tmp_path / "classic.rtivf")
    built = mnmg.ivf_flat_build(comms, params, data[:2000])
    mnmg.ivf_flat_save(spath, built)
    assert mnmg.ivf_flat_load(comms, spath).n == 2000
    # wrong-kind error still clean
    with pytest.raises(ValueError, match="not a distributed ivf_pq"):
        mnmg.ivf_pq_load(comms, spath)


def test_refined_search_on_extended_index(comms, blobs):
    """The high-recall pipeline works on driver-built EXTENDED indexes
    via the post-merge refine topology: recall matches the unextended
    refined path and beats the unrefined extended search."""
    data, _ = blobs
    q = data[:24]
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=8)
    dindex = mnmg.ivf_pq_build(comms, params, data[:3000])
    dindex = mnmg.ivf_pq_extend(dindex, data[3000:])
    assert dindex.extended

    _, truth = brute_force.knn(data, q, 5)
    truth = np.asarray(truth)

    def rec(ids):
        ids = np.asarray(ids)
        return np.mean([len(set(ids[i]) & set(truth[i])) / 5
                        for i in range(len(q))])

    _, ui = mnmg.ivf_pq_search(dindex, q, 5, n_probes=16)
    _, ri = mnmg.ivf_pq_search(dindex, q, 5, n_probes=16,
                               refine_dataset=data)
    r_unref, r_ref = rec(ui), rec(ri)
    assert r_ref >= r_unref, (r_ref, r_unref)
    assert r_ref >= 0.95, r_ref
    # extended-block rows must be reachable refined (their gids are in
    # the appended id range)
    probe = np.asarray(data[3200:3204])
    _, pi_ = mnmg.ivf_pq_search(dindex, probe, 1, n_probes=16,
                                refine_dataset=data)
    assert np.all(np.asarray(pi_).ravel() >= 3000)
    # an explicit sharded query_mode request degrades to replicated WITH
    # a warning (the caller asked for a layout it can't get; ADVICE r3),
    # and still returns correct results
    with pytest.warns(UserWarning, match="sharded.*REPLICATED"):
        _, si = mnmg.ivf_pq_search(dindex, q, 5, n_probes=16,
                                   refine_dataset=data, query_mode="sharded")
    np.testing.assert_array_equal(np.asarray(si), np.asarray(ri))
    # auto mode keeps the silent fallback
    import warnings as _warnings
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        mnmg.ivf_pq_search(dindex, q, 5, n_probes=16,
                           refine_dataset=data, query_mode="auto")
    # wrong row count still validated
    with pytest.raises(ValueError, match="rows"):
        mnmg.ivf_pq_search(dindex, q, 5, refine_dataset=data[:3000])


def test_bad_query_mode_rejected_with_refine(comms, blobs):
    """query_mode validation runs even when the refined-extended path
    overrides the mode to replicated."""
    data, _ = blobs
    params = ivf_pq.IndexParams(n_lists=8, pq_dim=8, kmeans_n_iters=4)
    dindex = mnmg.ivf_pq_build(comms, params, data[:600])
    dindex = mnmg.ivf_pq_extend(dindex, data[600:700])
    with pytest.raises(ValueError, match="query_mode"):
        mnmg.ivf_pq_search(dindex, data[:4], 3, refine_dataset=data[:700],
                           query_mode="shraded")


def test_distributed_ivf_flat_engines_agree(comms, blobs, flat16):
    """The list-major engine is reachable from the distributed path and
    agrees with query-major (both exact within probed lists; all lists
    probed -> identical neighbor sets). Bad engine names reject."""
    data, _ = blobs
    q = data[:17]
    dindex = flat16
    _, qi = mnmg.ivf_flat_search(dindex, q, 5, n_probes=16, engine="query")
    _, li = mnmg.ivf_flat_search(dindex, q, 5, n_probes=16, engine="list")
    qi_, li_ = np.asarray(qi), np.asarray(li)
    # overlap gate, not exact equality: the list-major per-chunk trim is
    # approx top-k (0.99 target) on TPU — exact only on the CPU fallback
    # (same tolerance rationale as tests/test_ivf_flat.py)
    hits = sum(len(set(a.tolist()) & set(b.tolist()))
               for a, b in zip(li_, qi_))
    assert hits / qi_.size >= 0.95, hits / qi_.size
    # auto routes this duplication (17*16/16 = 17 >= 4) to list-major:
    # same code path, same inputs -> identical output
    _, ai = mnmg.ivf_flat_search(dindex, q, 5, n_probes=16, engine="auto")
    np.testing.assert_array_equal(np.asarray(ai), li_)
    # prefilter composes with the list engine
    mask = np.ones(len(data), bool); mask[::2] = False
    _, fi = mnmg.ivf_flat_search(dindex, q, 5, n_probes=16, engine="list",
                                 prefilter=mask)
    fi = np.asarray(fi)
    assert np.all((fi == -1) | mask[np.maximum(fi, 0)])
    # fused Pallas scan per rank (interpret on CPU): near-exact, high
    # overlap with the exact list-major engine + prefilter invariant
    _, zi = mnmg.ivf_flat_search(dindex, q, 5, n_probes=16, engine="pallas")
    zi = np.asarray(zi)
    hits_z = sum(len(set(a.tolist()) & set(b.tolist()))
                 for a, b in zip(zi, qi_))
    assert hits_z / qi_.size >= 0.9, hits_z / qi_.size
    _, zf = mnmg.ivf_flat_search(dindex, q, 5, n_probes=16, engine="pallas",
                                 prefilter=mask)
    zf = np.asarray(zf)
    assert np.all((zf == -1) | mask[np.maximum(zf, 0)])
    with pytest.raises(ValueError, match="engine"):
        mnmg.ivf_flat_search(dindex, q, 5, engine="warpsort")


def test_distributed_pallas_trim_engine(comms, blobs):
    """The fused Pallas list-scan trim is reachable from the distributed
    recon8_list path (interpret mode on the CPU mesh): high id overlap
    with the approx trim, prefilter invariant holds, and contract
    violations reject without mutating the index."""
    data, _ = blobs
    q = data[:9]
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=6)
    dindex = mnmg.ivf_pq_build(comms, params, data[:2000])
    _, ai = mnmg.ivf_pq_search(dindex, q, 5, n_probes=16,
                               engine="recon8_list")
    _, pi_ = mnmg.ivf_pq_search(dindex, q, 5, n_probes=16,
                                engine="recon8_list", trim_engine="pallas")
    ai, pi_ = np.asarray(ai), np.asarray(pi_)
    hits = sum(len(set(a.tolist()) & set(b.tolist())) for a, b in zip(pi_, ai))
    assert hits / ai.size >= 0.85, hits / ai.size  # bin-trim loss class
    mask = np.ones(2000, bool); mask[::2] = False
    _, fi = mnmg.ivf_pq_search(dindex, q, 5, n_probes=16,
                               engine="recon8_list", trim_engine="pallas",
                               prefilter=mask)
    fi = np.asarray(fi)
    assert np.all((fi == -1) | mask[np.maximum(fi, 0)])
    with pytest.raises(ValueError, match="recon8_list"):
        mnmg.ivf_pq_search(dindex, q, 5, engine="lut", trim_engine="pallas")
    with pytest.raises(ValueError, match="trim_engine"):
        mnmg.ivf_pq_search(dindex, q, 5, trim_engine="radix")
    # pallas-then-approx on the SAME index: the in-place lane padding
    # must stay consistent with the gid view the approx engine sees
    _, a2 = mnmg.ivf_pq_search(dindex, q, 5, n_probes=16,
                               engine="recon8_list")
    np.testing.assert_array_equal(np.asarray(a2), ai)


def test_distributed_int8_query_scoring(comms, blobs):
    """score_dtype='int8' (symmetric int8 query scoring, the int8 MXU
    path) is reachable distributed: high overlap with bf16 scoring on
    both trim engines; invalid combos reject."""
    data, _ = blobs
    q = data[:9]
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=6)
    dindex = mnmg.ivf_pq_build(comms, params, data[:2000])
    _, b16 = mnmg.ivf_pq_search(dindex, q, 5, n_probes=16,
                                engine="recon8_list")
    b16 = np.asarray(b16)
    for kwargs in (dict(), dict(trim_engine="pallas")):
        _, i8 = mnmg.ivf_pq_search(dindex, q, 5, n_probes=16,
                                   engine="recon8_list",
                                   score_dtype="int8", **kwargs)
        i8 = np.asarray(i8)
        hits = sum(len(set(a.tolist()) & set(b.tolist()))
                   for a, b in zip(i8, b16))
        assert hits / b16.size >= 0.8, (kwargs, hits / b16.size)
    with pytest.raises(ValueError, match="score_dtype"):
        mnmg.ivf_pq_search(dindex, q, 5, engine="lut", score_dtype="int8")
    with pytest.raises(ValueError, match="score_dtype"):
        mnmg.ivf_pq_search(dindex, q, 5, score_dtype="fp8")
    # engine="auto" pins int8 / pallas requests to recon8_list — a tiny
    # batch (heuristic would pick lut) must still be accepted
    _, a8 = mnmg.ivf_pq_search(dindex, q[:2], 5, n_probes=4,
                               score_dtype="int8")
    assert np.asarray(a8).shape == (2, 5)
    _, ap = mnmg.ivf_pq_search(dindex, q[:2], 5, n_probes=4,
                               trim_engine="pallas")
    assert np.asarray(ap).shape == (2, 5)


def test_distributed_int8_fused_trim_engine(comms, blobs):
    """ISSUE 11: trim_engine='fused' + score_dtype='int8' per rank (the
    dispatch layer's fused_int8 strategy) — EXACT value agreement with
    the pallas int8 trim (same quantization, same op order; L <= 512 so
    the bin trim is lossless), prefilter invariant, envelope raises."""
    data, _ = blobs
    q = data[:9]
    params = ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=6)
    dindex = mnmg.ivf_pq_build(comms, params, data[:2000])
    pv, pi_ = mnmg.ivf_pq_search(dindex, q, 5, n_probes=16,
                                 engine="recon8_list", score_dtype="int8",
                                 trim_engine="pallas")
    fv, fi = mnmg.ivf_pq_search(dindex, q, 5, n_probes=16,
                                trim_engine="fused", score_dtype="int8")
    np.testing.assert_array_equal(np.asarray(pv), np.asarray(fv))
    pi_, fi = np.asarray(pi_), np.asarray(fi)
    assert all(set(a.tolist()) == set(b.tolist()) for a, b in zip(pi_, fi))
    assert dindex.fused_kb == 128
    mask = np.ones(2000, bool); mask[::2] = False
    _, mi = mnmg.ivf_pq_search(dindex, q, 5, n_probes=16,
                               trim_engine="fused", score_dtype="int8",
                               prefilter=mask)
    mi = np.asarray(mi)
    assert np.all((mi == -1) | mask[np.maximum(mi, 0)])
    with pytest.raises(ValueError, match="recon8_list"):
        mnmg.ivf_pq_search(dindex, q, 5, engine="lut", trim_engine="fused")
    with pytest.raises(ValueError, match="caps per-list"):
        # k past FUSED_MAX_K: explicit fused must refuse loudly
        mnmg.ivf_pq_search(dindex, q, 300, n_probes=16,
                           trim_engine="fused", score_dtype="int8")


def test_distributed_rabitq_fused_scan_engine(comms, blobs):
    """ISSUE 11: scan_engine='fused' per rank (the fused bit-plane
    scan) returns the SAME estimator scores and neighbors as the XLA
    reference, with and without the exact refine; explicit requests
    past the envelope raise."""
    from raft_tpu.neighbors import ivf_rabitq

    data, _ = blobs
    q = data[:9]
    dindex = mnmg.ivf_rabitq_build(
        comms, ivf_rabitq.IndexParams(n_lists=16, kmeans_n_iters=6),
        data[:2000])
    xv, xi = mnmg.ivf_rabitq_search(dindex, q, 5, n_probes=16,
                                    scan_engine="xla")
    fv, fi = mnmg.ivf_rabitq_search(dindex, q, 5, n_probes=16,
                                    scan_engine="fused")
    np.testing.assert_array_equal(np.asarray(xv), np.asarray(fv))
    np.testing.assert_array_equal(np.asarray(xi), np.asarray(fi))
    assert dindex.fused_kb == 128
    rv, ri = mnmg.ivf_rabitq_search(dindex, q, 5, n_probes=16,
                                    scan_engine="fused",
                                    refine_dataset=data[:2000])
    rxv, rxi = mnmg.ivf_rabitq_search(dindex, q, 5, n_probes=16,
                                      scan_engine="xla",
                                      refine_dataset=data[:2000])
    np.testing.assert_array_equal(np.asarray(rv), np.asarray(rxv))
    np.testing.assert_array_equal(np.asarray(ri), np.asarray(rxi))
    with pytest.raises(ValueError, match="scan_engine"):
        mnmg.ivf_rabitq_search(dindex, q, 5, scan_engine="warpsort")
    with pytest.raises(ValueError, match="caps scan"):
        # k past FUSED_MAX_K: explicit fused must refuse loudly
        mnmg.ivf_rabitq_search(dindex, q, 300, scan_engine="fused")


def test_query_mode_auto_is_volume_aware(comms, monkeypatch, tmp_path):
    """The auto merge-topology policy consults BOTH thresholds: absolute
    batch size and queries-per-k (merge volume is nq*k*world; the round-3
    race surface flips winner with k at fixed nq)."""
    import json
    from raft_tpu.core import tuned

    p = str(tmp_path / "tuned_defaults.json")
    with open(p, "w") as f:
        json.dump({"mnmg_query_sharded_min_nq": 1024,
                   "mnmg_query_sharded_min_nq_per_k": 64}, f)
    monkeypatch.setattr(tuned, "_PATH", p)
    tuned.reload()
    try:
        rq = mnmg._resolve_query_mode
        assert rq("auto", comms, 2048, 10) == "sharded"     # both pass
        assert rq("auto", comms, 2048, 100) == "replicated" # nq < 64*k
        assert rq("auto", comms, 512, 5) == "replicated"    # nq < min_nq
        assert rq("auto", comms, 6400, 100) == "sharded"    # nq == 64*k
        # explicit requests are never overridden by the tuned surface
        assert rq("sharded", comms, 4, 100) == "sharded"
        assert rq("replicated", comms, 10**6, 1) == "replicated"
    finally:
        tuned.reload()


@pytest.mark.parametrize("world", [2, 4, 8])
def test_tournament_merge_matches_allgather_merge(world):
    """The butterfly tournament merge must return EXACTLY what the flat
    allgather merge returns — including on adversarial inputs: exact
    value ties across ranks (broken by rank-major position) and +inf
    padding rows — at every edge width: world=2 runs a single round (no
    interior position re-sort executes), world=4 exactly one, world=8
    two. Runs both implementations on the same per-rank candidates and
    compares bit-for-bit."""
    import jax
    from jax.sharding import PartitionSpec as P
    from raft_tpu.comms.mnmg import (
        _merge_local_topk_tournament, _merge_local_topk_allgather)

    sub = Comms(n_devices=world)
    ac = sub.comms
    rng = np.random.default_rng(3)
    nq, kk, k = 6, 5, 8
    # quantized values force many exact cross-rank ties; one rank all-inf
    v = rng.integers(0, 4, (world, nq, kk)).astype(np.float32)
    v[-1] = np.inf
    v = np.sort(v, axis=-1)
    ids = rng.integers(0, 10_000, (world, nq, kk)).astype(np.int32)

    def both(vv, ii):
        fv, fi = _merge_local_topk_allgather(ac, vv[0], ii[0], k, True)
        tv, ti = _merge_local_topk_tournament(ac, vv[0], ii[0], k, True)
        return fv, fi, tv, ti

    fv, fi, tv, ti = jax.shard_map(
        both, mesh=sub.mesh, in_specs=(P("data"), P("data")),
        out_specs=(P("data"), P("data"), P("data"), P("data")),
        check_vma=False,
    )(sub.shard(v, axis=0), sub.shard(ids, axis=0))
    np.testing.assert_array_equal(np.asarray(tv).reshape(world, nq, k),
                                  np.asarray(fv).reshape(world, nq, k))
    np.testing.assert_array_equal(np.asarray(ti).reshape(world, nq, k),
                                  np.asarray(fi).reshape(world, nq, k))
    # replicated contract: every rank holds the identical merged result
    t_all = np.asarray(tv).reshape(world, nq, k)
    assert all(np.array_equal(t_all[0], t_all[j]) for j in range(world))


def test_replicated_merge_schedule_gate(comms, monkeypatch, tmp_path):
    """The replicated-merge schedule is a backend-dependent engine choice:
    CPU defaults to allgather (tournament measured ~2x slower on the
    memcpy mesh), TPU to tournament, and the tuned key overrides both."""
    import json
    from raft_tpu.comms.mnmg import _replicated_merge_schedule
    from raft_tpu.core import tuned
    import raft_tpu.core.config as cfg

    # isolate from the COMMITTED tuned file up front: once the on-chip
    # queue writes the schedule key there, the default-behavior asserts
    # below would otherwise read it
    p = str(tmp_path / "tuned_defaults.json")
    monkeypatch.setattr(tuned, "_PATH", p)
    tuned.reload()
    try:
        assert _replicated_merge_schedule() == "allgather"  # CPU default
        monkeypatch.setattr(cfg, "is_tpu_backend", lambda: True)
        assert _replicated_merge_schedule() == "tournament"
        # tuned key measured on THIS backend wins
        with open(p, "w") as f:
            json.dump({"mnmg_replicated_merge_schedule": "allgather",
                       "hints": {"merge_schedule_measured_on": "cpu"}}, f)
        tuned.reload()
        assert _replicated_merge_schedule() == "allgather"
        # a key measured on a DIFFERENT backend is ignored (a chip-won
        # tournament must not flip the CPU mesh and vice versa)
        with open(p, "w") as f:
            json.dump({"mnmg_replicated_merge_schedule": "allgather",
                       "hints": {"merge_schedule_measured_on": "axon"}}, f)
        tuned.reload()
        monkeypatch.setattr(cfg, "is_tpu_backend", lambda: False)
        assert _replicated_merge_schedule() == "allgather"  # CPU default anyway
        monkeypatch.setattr(cfg, "is_tpu_backend", lambda: True)
        # backend default (tournament) because the hint says axon != cpu
        assert _replicated_merge_schedule() == "tournament"
    finally:
        tuned.reload()


def test_tournament_schedule_end_to_end(comms, blobs, monkeypatch, tmp_path):
    """Forcing the tournament schedule through the tuned key, the full
    distributed knn returns exactly what the allgather schedule returns
    (integration-level check of the dispatch; CPU defaults to allgather,
    so this is the virtual mesh's only end-to-end tournament exercise)."""
    import json
    import jax
    from raft_tpu.core import tuned

    data, _ = blobs
    q = data[:13]
    base_v, base_i = mnmg.knn(comms, data, q, 6)
    p = str(tmp_path / "tuned_defaults.json")
    with open(p, "w") as f:
        json.dump({"mnmg_replicated_merge_schedule": "tournament",
                   "hints": {"merge_schedule_measured_on": "cpu"}}, f)
    monkeypatch.setattr(tuned, "_PATH", p)
    tuned.reload()
    jax.clear_caches()  # the schedule is baked into traces at trace time
    try:
        tv, ti = mnmg.knn(comms, data, q, 6)
        np.testing.assert_array_equal(np.asarray(ti), np.asarray(base_i))
        np.testing.assert_allclose(np.asarray(tv), np.asarray(base_v),
                                   rtol=1e-6)
    finally:
        tuned.reload()
        jax.clear_caches()


def test_mnmg_lut_fence_and_auto_on_tpu(comms, blobs, pq16, monkeypatch):
    """VERDICT r4 #5 on the distributed path: with the backend reading
    'tpu', engine='auto' never resolves to the device-faulting lut engine
    (even from a tuned key) and explicit engine='lut' raises the fence."""
    import jax

    from raft_tpu.core import tuned
    from raft_tpu.neighbors import ivf_pq as sc_pq

    data, _ = blobs
    q = data[:3]  # dup = 3*4/16 < 4: the heuristic alone would say lut
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setitem(tuned._load(), "pq_auto_engine", "lut")
    try:
        _, i_auto = mnmg.ivf_pq_search(pq16, q, 5, n_probes=4, engine="auto")
        assert np.asarray(i_auto).shape == (3, 5)  # ran recon8_list, not lut
        with pytest.raises(ValueError, match="fenced on TPU"):
            mnmg.ivf_pq_search(pq16, q, 5, n_probes=4, engine="lut")
        # the sanctioned override lifts the distributed fence too
        monkeypatch.setenv(sc_pq._LUT_TPU_OVERRIDE, "1")
        _, i_lut = mnmg.ivf_pq_search(pq16, q, 5, n_probes=4, engine="lut")
        assert np.asarray(i_lut).shape == (3, 5)
    finally:
        tuned.reload()
