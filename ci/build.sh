#!/bin/bash
# Build entry point (reference ci/build_cpp.sh analogue): compiles the
# native C++ helper library (serialization codec, list packer, COO/label
# kernels) out-of-tree and reports where the Python layer will pick it up
# (raft_tpu.native searches the build dir and RAFT_TPU_NATIVE_LIB).
set -euo pipefail
cd "$(dirname "$0")/.."

BUILD_DIR="${BUILD_DIR:-cpp/build}"
cmake -S cpp -B "$BUILD_DIR" -DCMAKE_BUILD_TYPE=Release "$@"
cmake --build "$BUILD_DIR" --parallel
echo "native library built under $BUILD_DIR"
