#!/bin/bash
# Style/lint gate (reference ci/check_style.sh analogue, scaled to this
# repo's toolchain): every Python file must at least compile, no file may
# carry merge markers or tabs-in-indentation, and ruff/flake8 run when
# available (neither is baked into the image; the gate degrades
# gracefully rather than failing on missing tools).
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q raft_tpu tests bench tools bench.py __graft_entry__.py

if grep -rn --include='*.py' -e '^<<<<<<<' -e '^>>>>>>>' raft_tpu tests bench tools; then
  echo "merge markers found" >&2; exit 1
fi
if grep -rn --include='*.py' -P '^\t' raft_tpu tests bench tools; then
  echo "tab indentation found" >&2; exit 1
fi
# invariant gates (formerly four greps here: bare `except:`,
# `time.time()`, raw `os.rename`/`open(.., "wb")`) now live in
# tools/raftlint as scope-aware AST rules, alongside the deeper
# trace-safety / lock-discipline / fault-site-drift / layer-purity
# analyses greps can't express. See docs/linting.md for the rule
# catalog, pragmas and the baseline workflow.
python -m tools.raftlint raft_tpu bench tests tools

if command -v ruff >/dev/null 2>&1; then
  ruff check raft_tpu tests bench tools
elif python -c 'import flake8' >/dev/null 2>&1; then
  python -m flake8 --max-line-length=100 --extend-ignore=E203,W503,E501,E731,E741 raft_tpu
else
  echo "ruff/flake8 unavailable; compile + marker checks only"
fi
echo "style checks passed"
