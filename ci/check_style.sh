#!/bin/bash
# Style/lint gate (reference ci/check_style.sh analogue, scaled to this
# repo's toolchain): every Python file must at least compile, no file may
# carry merge markers or tabs-in-indentation, and ruff/flake8 run when
# available (neither is baked into the image; the gate degrades
# gracefully rather than failing on missing tools).
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q raft_tpu tests bench bench.py __graft_entry__.py

if grep -rn --include='*.py' -e '^<<<<<<<' -e '^>>>>>>>' raft_tpu tests bench; then
  echo "merge markers found" >&2; exit 1
fi
if grep -rn --include='*.py' -P '^\t' raft_tpu tests bench; then
  echo "tab indentation found" >&2; exit 1
fi
# bare `except:` swallows KeyboardInterrupt/SystemExit and masks genuine
# faults — the resilience layer depends on failures surfacing typed
if grep -rn --include='*.py' -E '^[[:space:]]*except[[:space:]]*:' raft_tpu; then
  echo "bare 'except:' found in raft_tpu/ (catch a concrete exception type)" >&2; exit 1
fi

# checkpoint writes must ride core/serialize.py's atomic
# write-to-temp-then-rename helper (crash mid-write must never leave a
# torn file under the final name, and every container write must carry
# the CRC-32C field checksums) — bare renames or raw binary writes in
# the library bypass both
if grep -rn --include='*.py' -E 'os\.rename\(|open\([^)]*, *["'"'"']wb["'"'"']' raft_tpu \
    | grep -v 'raft_tpu/core/serialize\.py'; then
  echo "bare os.rename/open(..., 'wb') in raft_tpu/; route checkpoint writes through core.serialize (atomic_write + checksums)" >&2
  exit 1
fi

# wall-clock in library/bench timing code must be monotonic:
# time.time() jumps under NTP steps and breaks span/latency accounting
# (tests may use it for coarse assertions; the library and benches not)
if grep -rn --include='*.py' -E '\btime\.time\(\)' raft_tpu bench; then
  echo "time.time() found; use time.monotonic() or time.perf_counter() for timing" >&2; exit 1
fi

if command -v ruff >/dev/null 2>&1; then
  ruff check raft_tpu tests bench
elif python -c 'import flake8' >/dev/null 2>&1; then
  python -m flake8 --max-line-length=100 --extend-ignore=E203,W503,E501,E731,E741 raft_tpu
else
  echo "ruff/flake8 unavailable; compile + marker checks only"
fi
echo "style checks passed"
