#!/bin/bash
# Test entry point (reference ci/test_cpp.sh + ci/test_python.sh analogue).
#
#   ci/test.sh quick   — the <2 min tier (skips compile-heavy ANN suites)
#   ci/test.sh full    — everything (default)
#   ci/test.sh chaos   — the fault-injection/resilience suite only
#   ci/test.sh serve   — the serving-engine suite (incl. its seeded
#                        chaos cases: slow-rank degraded serving, slow
#                        batch dispatch) + the batch_loader padding
#                        contract the serve batcher reuses
#   ci/test.sh obs     — the observability suite (span/registry/event
#                        determinism, exporters, report CLI, the
#                        chaos-drill timeline contract)
#
# Tests force the CPU backend with an 8-device virtual mesh via
# tests/conftest.py; no TPU is touched.
#
# The chaos suite (tests/test_resilience.py) replays seeded FaultPlans;
# CI pins the seed so a failing drill reproduces bit-for-bit locally
# (override RAFT_TPU_FAULT_SEED to fuzz other seeds).
set -euo pipefail
cd "$(dirname "$0")/.."

export RAFT_TPU_FAULT_SEED="${RAFT_TPU_FAULT_SEED:-1234}"

tier="${1:-full}"
case "$tier" in
  # quick: fast-compile mode (most XLA opt passes skipped) + "not slow";
  # the full tier keeps production optimization levels
  quick) exec env RAFT_TPU_TEST_FAST_COMPILE=1 python -m pytest tests/ -q -m "not slow" ;;
  # --durations: keep the slowest-test ledger in every full run so the
  # ~20 min tier budget is enforced from data, not memory
  full)  exec python -m pytest tests/ -q --durations=15 ;;
  chaos) exec python -m pytest tests/test_resilience.py -q ;;
  serve) exec python -m pytest tests/test_serve.py tests/test_batch_loader.py -q ;;
  obs)   exec python -m pytest tests/test_obs.py -q ;;
  *) echo "usage: ci/test.sh [quick|full|chaos|serve|obs]" >&2; exit 2 ;;
esac
