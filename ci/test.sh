#!/bin/bash
# Test entry point (reference ci/test_cpp.sh + ci/test_python.sh analogue).
#
#   ci/test.sh quick   — the <2 min tier (skips compile-heavy ANN suites)
#   ci/test.sh full    — everything (default)
#   ci/test.sh chaos   — the fault-injection/resilience suite + the
#                        replica-failover / rejoin / checkpoint-heal
#                        drills (tests/test_replication.py), replayed
#                        under a 3-seed RAFT_TPU_FAULT_SEED matrix so a
#                        drill that only survives one lucky seed fails
#   ci/test.sh serve   — the serving-engine suite (incl. its seeded
#                        chaos cases: slow-rank degraded serving, slow
#                        batch dispatch) + the batch_loader padding
#                        contract the serve batcher reuses
#   ci/test.sh obs     — the observability suite (span/registry/event
#                        determinism, exporters, report CLI, the
#                        chaos-drill timeline contract)
#   ci/test.sh lint    — the static-analysis tier: tools/raftlint over
#                        the whole repo (trace safety, lock discipline +
#                        lock-order deadlock, fault-site drift, layer
#                        purity, hygiene, SPMD collective divergence/
#                        order, commit ordering, and the raftlint 3.0
#                        kernelcheck families: VMEM envelope
#                        cross-check, BlockSpec/scalar-prefetch
#                        consistency, kernel dtype flow, fused dispatch
#                        envelope guards, plus the tuned-key registry,
#                        and the raftlint 4.0 statecheck families:
#                        cache-key completeness over the memoized
#                        serving wrappers, the CKPT_SCHEMA checkpoint
#                        registry, and the DIGEST_FIELDS scrub-coverage
#                        registry, and the raftlint 5.0 threadcheck
#                        families: THREAD_ROOTS registry drift,
#                        whole-program shared-state races, and the
#                        publication-safety zero-dip contract),
#                        --json archived and run
#                        twice + cmp'd (byte-determinism is a
#                        documented contract), per-family --stats
#                        archived with a 10 s soft budget per engine,
#                        wall-time gated under 30 s so the gate never
#                        becomes the slow tier, plus the raftlint unit,
#                        CFG-engine, kernelcheck-interpreter,
#                        statecheck, and threadcheck suites (incl. the
#                        real-source mutation smoke tests)
#   ci/test.sh schedfuzz— the deterministic-interleaving tier: the
#                        schedfuzz scheduler contract (same seed =>
#                        byte-identical schedule trace) and the three
#                        pinned ordering drills (zero-dip mutation swap
#                        vs in-flight batch, flight-recorder dump
#                        racing publication, metrics snapshot during
#                        scrape) plus the pre-fix reproducing schedules
#                        for every race ISSUE-20 fixed, under the
#                        3-seed RAFT_TPU_FAULT_SEED matrix
#   ci/test.sh rabitq  — the quantizer-subsystem tier: the quantizer
#                        abstraction property suite (estimator
#                        unbiasedness, pack/unpack round-trips, the PQ
#                        bit-identity goldens) + the IVF-RaBitQ index
#                        suite (build/search/extend/save, MNMG degraded
#                        + failover + ckpt-heal, serve bit-identity)
#   ci/test.sh perf    — the perf-watchtower tier: a tiny in-process
#                        bench banks fresh rows (span phases + cost-model
#                        MFU) to a temp ledger, tools/perfgate gates them
#                        in report-only mode (and must be byte-identical
#                        across two runs), then the cost-model /
#                        ledger / perfgate unit suites run
#   ci/test.sh fused   — the fused scan+select-k tier: exact-agreement
#                        tests of the fused Pallas kernel family vs the
#                        two-phase reference (ids AND values, min/max,
#                        k ladder, ragged tails, adversarial-tie
#                        recall), the INTEGER fused kernels (int8
#                        PQ-recon trim bit-agreement vs the pallas
#                        trim, RaBitQ bit-plane scan vs the XLA
#                        estimator reference, fused_kb growth,
#                        tombstone exclusion), the scan_select_k /
#                        list-scan dispatch contracts, and the select_k
#                        strategy suite (slow-marked kernel sweeps
#                        excluded)
#   ci/test.sh adaptive— the adaptive-probing tier (ISSUE 12): the
#                        probe-budget suite (saturation bit-identity
#                        on all three engines + MNMG, early-term
#                        oracle, truthful scanned_lists accounting,
#                        serve recall_target plumbing), then the
#                        recall-vs-scanned frontier bench at smoke
#                        scale into a hermetic ledger, gated through
#                        tools/perfgate --json run twice + cmp'd
#                        (byte-determinism over the appended rows)
#   ci/test.sh qcomms  — the quantized-collectives tier (ISSUE 17): the
#                        codec / bit-identity-pin / recall-parity /
#                        wire-accounting suite (tests/test_qcomms.py,
#                        slow driver pins included), then the wire +
#                        recall + mode-race bench at smoke scale into a
#                        hermetic ledger, gated through
#                        tools/perfgate --json run twice + cmp'd
#   ci/test.sh jobs    — the preemption-safety tier: the resumable job
#                        runner + watchdog drills (tests/test_jobs.py),
#                        incl. the child-process SIGKILL kill-and-resume
#                        bit-identity drills over ivf_flat/pq/rabitq and
#                        the kill-mid-make_data datagen drill, replayed
#                        under the 3-seed RAFT_TPU_FAULT_SEED matrix
#   ci/test.sh mutation— the live-mutable-index tier (ISSUE 16): the
#                        mutation suite (tombstone semantics, the
#                        crash-atomic mutation log, zero-dip serving
#                        single-chip + MNMG, and the child-process
#                        SIGKILL mid-upsert/mid-delete resume-
#                        bit-identity drills over all three kinds)
#                        under the 3-seed RAFT_TPU_FAULT_SEED matrix,
#                        then the recall-under-churn / ingest-
#                        throughput bench at smoke scale into a
#                        hermetic ledger, gated through
#                        tools/perfgate --json run twice + cmp'd
#   ci/test.sh integrity— the integrity-watchdog tier (ISSUE 19): the
#                        scrub/quarantine/PITR suite
#                        (tests/test_integrity.py — digest lifecycle,
#                        rot conviction, quarantine bit-identity,
#                        zero-dip serve repair, MNMG mirror repair,
#                        restore byte-identity, and the child-process
#                        SIGKILL mid-scrub resume drills) under the
#                        3-seed RAFT_TPU_FAULT_SEED matrix, then the
#                        scrub-under-churn bench row at smoke scale
#                        into a hermetic ledger, gated through
#                        tools/perfgate --json run twice + cmp'd
#
# Tests force the CPU backend with an 8-device virtual mesh via
# tests/conftest.py; no TPU is touched.
#
# The chaos suite (tests/test_resilience.py) replays seeded FaultPlans;
# CI pins the seed so a failing drill reproduces bit-for-bit locally
# (override RAFT_TPU_FAULT_SEED to fuzz other seeds).
set -euo pipefail
cd "$(dirname "$0")/.."

export RAFT_TPU_FAULT_SEED="${RAFT_TPU_FAULT_SEED:-1234}"

tier="${1:-full}"
case "$tier" in
  # quick: fast-compile mode (most XLA opt passes skipped) + "not slow";
  # the full tier keeps production optimization levels
  quick) exec env RAFT_TPU_TEST_FAST_COMPILE=1 python -m pytest tests/ -q -m "not slow" ;;
  # --durations: keep the slowest-test ledger in every full run so the
  # ~20 min tier budget is enforced from data, not memory
  full)  exec python -m pytest tests/ -q --durations=15 ;;
  chaos)
    # seed matrix: the pinned CI seed first (bit-for-bit repro of CI
    # failures), then two fixed alternates — the failover election,
    # corrupt-file sector draws, and retry jitter all derive from the
    # seed, so the drills must hold across seeds, not just one
    for seed in "${RAFT_TPU_FAULT_SEED}" 7 2025; do
      echo "=== chaos tier @ RAFT_TPU_FAULT_SEED=${seed} ==="
      env RAFT_TPU_FAULT_SEED="${seed}" \
        python -m pytest tests/test_resilience.py tests/test_replication.py -q
    done
    ;;
  serve) exec python -m pytest tests/test_serve.py tests/test_batch_loader.py -q ;;
  obs)
    # seed matrix mirrors the chaos tier: the flaky-dump and
    # corrupt-stamp drills (obs.flight.dump / serve.trace.stamp) arm
    # from the seed, so degrade-not-die must hold across seeds
    for seed in "${RAFT_TPU_FAULT_SEED}" 7 2025; do
      echo "=== obs tier @ RAFT_TPU_FAULT_SEED=${seed} ==="
      env RAFT_TPU_FAULT_SEED="${seed}" \
        python -m pytest tests/test_obs.py tests/test_trace.py -q
    done
    tmp="$(mktemp -d)"
    # hermetic tracing smoke: ~1k traced requests through a step-mode
    # server with the flight recorder + SLO watchtower armed; the
    # script itself enforces chrome-export byte-stability and the
    # atomic-dump contract, then the run report over its snapshot is
    # rendered twice + cmp'd (byte-determinism is the contract) and
    # must carry the tracing + SLO sections
    env RAFT_TPU_OBS=1 JAX_PLATFORMS=cpu \
      python bench/bench_trace_smoke.py --out "${tmp}"
    python -m raft_tpu.obs.report "${tmp}/obs_snapshot.json" \
      > "${tmp}/report1.txt"
    python -m raft_tpu.obs.report "${tmp}/obs_snapshot.json" \
      > "${tmp}/report2.txt"
    cmp "${tmp}/report1.txt" "${tmp}/report2.txt"  # acceptance: deterministic
    grep -q "Request tracing" "${tmp}/report1.txt"
    grep -q "SLO watchtower" "${tmp}/report1.txt"
    # fresh perf-smoke rows (now carrying obs overhead from the traced
    # serve path's instruments) into a hermetic ledger, then the
    # perfgate determinism contract over the appended rows
    env RAFT_TPU_OBS=1 JAX_PLATFORMS=cpu \
      RAFT_TPU_BENCH_LEDGER="${tmp}/ledger.jsonl" \
      RAFT_TPU_BENCH_OUT="${tmp}" \
      python bench/bench_perf_smoke.py
    python -m tools.perfgate --ledger "${tmp}/ledger.jsonl" --json \
      > "${tmp}/gate1.json"
    python -m tools.perfgate --ledger "${tmp}/ledger.jsonl" --json \
      > "${tmp}/gate2.json"
    cmp "${tmp}/gate1.json" "${tmp}/gate2.json"  # acceptance: deterministic
    cat "${tmp}/gate1.json"
    ;;
  lint)
    tmp="$(mktemp -d)"
    # full-tree lint, --json archived (diffable next to BENCH artifacts)
    # and run twice + cmp'd: byte-determinism is part of the contract.
    # The exit code is captured, not fatal, so a failing gate still
    # archives and PRINTS its findings instead of dying into a tmp file
    lint_rc=0
    lint_t0=$SECONDS
    # --stats lands on stderr only (stdout stays the byte-deterministic
    # json): per-rule-family wall times, archived so a slow ENGINE is
    # attributable the day the 30 s repo gate trips
    python -m tools.raftlint --json --stats raft_tpu bench tests tools \
      > "${tmp}/raftlint.json" 2> "${tmp}/raftlint_stats.txt" || lint_rc=$?
    lint_secs=$(( SECONDS - lint_t0 ))
    if [ -n "${RAFT_TPU_CI_ARTIFACTS:-}" ]; then
      mkdir -p "${RAFT_TPU_CI_ARTIFACTS}"
      cp "${tmp}/raftlint.json" "${RAFT_TPU_CI_ARTIFACTS}/raftlint.json"
      cp "${tmp}/raftlint_stats.txt" "${RAFT_TPU_CI_ARTIFACTS}/raftlint_stats.txt"
    fi
    echo "raftlint: json archived at ${RAFT_TPU_CI_ARTIFACTS:-${tmp}}/raftlint.json"
    cat "${tmp}/raftlint_stats.txt"
    # per-family SOFT budget: any single engine past 10 s is called out
    # (warning, not failure — the hard gate is the 30 s repo wall below)
    awk -F'wall=' '/stats: family=/ {
      split($2, a, "s"); fam=$1; sub(/.*family=/, "", fam); sub(/ .*/, "", fam)
      if (a[1] + 0 >= 10)
        printf "raftlint: WARNING: family %s took %ss (soft budget 10s)\n", fam, a[1]
    }' "${tmp}/raftlint_stats.txt" >&2
    if [ "${lint_rc}" -ne 0 ]; then
      echo "raftlint: findings (exit ${lint_rc}):" >&2
      cat "${tmp}/raftlint.json" >&2
      exit "${lint_rc}"
    fi
    python -m tools.raftlint --json raft_tpu bench tests tools \
      > "${tmp}/raftlint2.json"
    cmp "${tmp}/raftlint.json" "${tmp}/raftlint2.json"
    echo "raftlint: repo-wide wall time ${lint_secs}s (budget 30s)"
    # the lint gate must stay the FAST tier: interprocedural analysis
    # that creeps past 30 s gets split or bounded, not waited on
    if [ "${lint_secs}" -ge 30 ]; then
      echo "raftlint: repo-wide lint took ${lint_secs}s (>= 30s budget)" >&2
      exit 1
    fi
    exec python -m pytest tests/test_raftlint.py tests/test_raftlint_cfg.py \
      tests/test_raftlint_kernels.py tests/test_raftlint_statecheck.py \
      tests/test_raftlint_threads.py -q
    ;;
  schedfuzz)
    # seed matrix mirrors the chaos tier: every scheduling decision
    # derives from the seed, so the pinned ordering drills and the
    # reproducing-schedule regressions must hold across seeds — and the
    # byte-identical-trace contract is itself asserted per seed
    for seed in "${RAFT_TPU_FAULT_SEED}" 7 2025; do
      echo "=== schedfuzz tier @ RAFT_TPU_FAULT_SEED=${seed} ==="
      env RAFT_TPU_FAULT_SEED="${seed}" \
        python -m pytest tests/test_schedfuzz.py -q
    done
    exit 0
    ;;
  rabitq)
    exec python -m pytest tests/test_quantizer.py tests/test_ivf_rabitq.py -q
    ;;
  fused)
    exec python -m pytest tests/test_fused_scan.py \
      tests/test_fused_int_scan.py tests/test_select_k.py \
      -q -m "not slow"
    ;;
  jobs)
    # seed matrix mirrors the chaos tier: the crash-site visit counts,
    # stall schedules, and retry jitter all derive from the seed, so the
    # kill-and-resume drills must hold across seeds, not just one
    for seed in "${RAFT_TPU_FAULT_SEED}" 7 2025; do
      echo "=== jobs tier @ RAFT_TPU_FAULT_SEED=${seed} ==="
      env RAFT_TPU_FAULT_SEED="${seed}" \
        python -m pytest tests/test_jobs.py -q
    done
    ;;
  mutation)
    # seed matrix mirrors the chaos/jobs tiers: the flaky-drill arming,
    # SIGKILL visit counts, and churn scripts all derive from the seed,
    # so the crash-atomicity drills must hold across seeds, not just one
    for seed in "${RAFT_TPU_FAULT_SEED}" 7 2025; do
      echo "=== mutation tier @ RAFT_TPU_FAULT_SEED=${seed} ==="
      env RAFT_TPU_FAULT_SEED="${seed}" \
        python -m pytest tests/test_mutation.py -q
    done
    tmp="$(mktemp -d)"
    # churn bench at smoke scale into a hermetic ledger (report-only CI
    # must not write the repo ledger), then the perfgate determinism
    # contract over the appended rows
    env RAFT_TPU_OBS=1 JAX_PLATFORMS=cpu \
      RAFT_TPU_BENCH_LEDGER="${tmp}/ledger.jsonl" \
      RAFT_TPU_BENCH_OUT="${tmp}" \
      python bench/bench_mutation.py --smoke
    python -m tools.perfgate --ledger "${tmp}/ledger.jsonl" --json \
      > "${tmp}/gate1.json"
    python -m tools.perfgate --ledger "${tmp}/ledger.jsonl" --json \
      > "${tmp}/gate2.json"
    cmp "${tmp}/gate1.json" "${tmp}/gate2.json"  # acceptance: deterministic
    cat "${tmp}/gate1.json"
    ;;
  integrity)
    # seed matrix mirrors the chaos/jobs/mutation tiers: the rot victim
    # draws, SIGKILL visit counts, and flaky-drill arming all derive
    # from the seed, so the scrub/quarantine/PITR drills must hold
    # across seeds, not just one
    for seed in "${RAFT_TPU_FAULT_SEED}" 7 2025; do
      echo "=== integrity tier @ RAFT_TPU_FAULT_SEED=${seed} ==="
      env RAFT_TPU_FAULT_SEED="${seed}" \
        python -m pytest tests/test_integrity.py -q
    done
    tmp="$(mktemp -d)"
    # the mutation bench (now carrying the scrub_serve stage: sidecar
    # re-hash lists/s + served-QPS dip) at smoke scale into a hermetic
    # ledger (report-only CI must not write the repo ledger), then the
    # perfgate determinism contract over the appended rows
    env RAFT_TPU_OBS=1 JAX_PLATFORMS=cpu \
      RAFT_TPU_BENCH_LEDGER="${tmp}/ledger.jsonl" \
      RAFT_TPU_BENCH_OUT="${tmp}" \
      python bench/bench_mutation.py --smoke
    grep -q scrub_under_churn "${tmp}/ledger.jsonl"
    python -m tools.perfgate --ledger "${tmp}/ledger.jsonl" --json \
      > "${tmp}/gate1.json"
    python -m tools.perfgate --ledger "${tmp}/ledger.jsonl" --json \
      > "${tmp}/gate2.json"
    cmp "${tmp}/gate1.json" "${tmp}/gate2.json"  # acceptance: deterministic
    cat "${tmp}/gate1.json"
    ;;
  adaptive)
    tmp="$(mktemp -d)"
    python -m pytest tests/test_probe_budget.py -q
    # frontier bench at smoke scale into a hermetic ledger (report-only
    # CI must not write the repo ledger), then the perfgate determinism
    # contract over the appended rows
    env RAFT_TPU_OBS=1 JAX_PLATFORMS=cpu \
      RAFT_TPU_BENCH_LEDGER="${tmp}/ledger.jsonl" \
      RAFT_TPU_BENCH_OUT="${tmp}" \
      python bench/bench_adaptive_probes.py --smoke
    python -m tools.perfgate --ledger "${tmp}/ledger.jsonl" --json \
      > "${tmp}/gate1.json"
    python -m tools.perfgate --ledger "${tmp}/ledger.jsonl" --json \
      > "${tmp}/gate2.json"
    cmp "${tmp}/gate1.json" "${tmp}/gate2.json"  # acceptance: deterministic
    cat "${tmp}/gate1.json"
    ;;
  qcomms)
    tmp="$(mktemp -d)"
    # the full quantized suite, slow driver bit-identity pins included
    python -m pytest tests/test_qcomms.py -q
    # wire/recall/race bench at smoke scale into a hermetic ledger
    # (report-only CI must not write the repo ledger), then the perfgate
    # determinism contract over the appended rows
    env RAFT_TPU_OBS=1 JAX_PLATFORMS=cpu \
      RAFT_TPU_BENCH_LEDGER="${tmp}/ledger.jsonl" \
      RAFT_TPU_BENCH_OUT="${tmp}" \
      python bench/bench_qcomms.py --smoke
    python -m tools.perfgate --ledger "${tmp}/ledger.jsonl" --json \
      > "${tmp}/gate1.json"
    python -m tools.perfgate --ledger "${tmp}/ledger.jsonl" --json \
      > "${tmp}/gate2.json"
    cmp "${tmp}/gate1.json" "${tmp}/gate2.json"  # acceptance: deterministic
    cat "${tmp}/gate1.json"
    ;;
  perf)
    tmp="$(mktemp -d)"
    # fresh rows into a hermetic ledger (report-only CI must not write
    # the repo ledger; real runs do — that's how BENCH_LEDGER.jsonl
    # grows one honest row per bench session)
    env RAFT_TPU_OBS=1 JAX_PLATFORMS=cpu \
      RAFT_TPU_BENCH_LEDGER="${tmp}/ledger.jsonl" \
      RAFT_TPU_BENCH_OUT="${tmp}" \
      python bench/bench_perf_smoke.py
    python -m tools.perfgate --ledger "${tmp}/ledger.jsonl" --json \
      > "${tmp}/gate1.json"
    python -m tools.perfgate --ledger "${tmp}/ledger.jsonl" --json \
      > "${tmp}/gate2.json"
    cmp "${tmp}/gate1.json" "${tmp}/gate2.json"  # acceptance: deterministic
    cat "${tmp}/gate1.json"
    exec python -m pytest tests/test_perf.py tests/test_perfgate.py -q
    ;;
  *) echo "usage: ci/test.sh [quick|full|chaos|serve|obs|lint|schedfuzz|rabitq|fused|perf|jobs|adaptive|mutation|qcomms|integrity]" >&2; exit 2 ;;
esac
