"""raftlint engine: file walking, rule registry, pragma suppression,
baseline matching, deterministic output.

Design constraints (docs/linting.md has the long-form rationale):

  - stdlib only (``ast`` + friends) — the linter must run in any
    environment the library builds in, including the CI image, without
    importing raft_tpu itself (importing the library would drag jax in
    and make lint speed hostage to XLA init).
  - deterministic: findings sort by (path, line, col, rule, message) and
    ``--json`` output is byte-stable across runs, so lint results can be
    diffed and banked next to BENCH artifacts.
  - two suppression channels with different contracts: a per-line pragma
    (``# raftlint: disable=<rule>[,<rule>...]`` on the flagged line) for
    findings that are *intentional and justified in place*, and a
    checked-in baseline file for *grandfathered* findings that predate a
    rule and await a real fix. Baseline entries match on
    (path, rule, message) — not line numbers — so unrelated edits don't
    churn the file; a baselined finding that gets fixed turns its entry
    stale, which the CLI reports so the file shrinks monotonically.
"""

from __future__ import annotations

import ast
import collections
import dataclasses
import json
import os
import re
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

# rule tokens are dash-joined words; the capture stops cleanly before a
# ``--`` so pragmas can carry a justification suffix
# (``# raftlint: disable=<rule>  -- <why>``, the threadcheck convention)
PRAGMA_RE = re.compile(
    r"#\s*raftlint:\s*disable="
    r"([A-Za-z0-9_]+(?:-[A-Za-z0-9_]+)*"
    r"(?:\s*,\s*[A-Za-z0-9_]+(?:-[A-Za-z0-9_]+)*)*)")

BASELINE_DEFAULT = os.path.join(os.path.dirname(__file__), "baseline.json")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One lint finding at a precise location. Ordering is the output
    order (path, then position, then rule) — deterministic by design."""

    path: str  # repo-root-relative, forward slashes
    line: int
    col: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: position-independent so line drift in
        unrelated code doesn't invalidate entries."""
        return (self.path, self.rule, self.message)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Module:
    """One parsed source file handed to per-module rules."""

    path: str  # repo-root-relative, forward slashes
    tree: ast.AST
    lines: List[str]
    text: str


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]  # active (post-pragma, post-baseline), sorted
    pragma_suppressed: int
    baseline_suppressed: int
    stale_baseline: List[Tuple[str, str, str]]  # unmatched baseline keys
    all_findings: List[Finding]  # pre-suppression, for --write-baseline
    scan_prefixes: List[str] = dataclasses.field(default_factory=list)
    #: rule name -> wall seconds spent in its check calls this run (the
    #: --stats payload; stays OUT of --json stdout, whose byte-for-byte
    #: determinism across runs is a documented contract)
    rule_seconds: Dict[str, float] = dataclasses.field(default_factory=dict)

    def covers(self, path: str) -> bool:
        """True when `path` (repo-relative) lies under the scanned
        paths — the scope within which baseline entries are live: an
        entry under a scanned directory whose file is gone is stale
        (the finding was fixed by deletion), one outside the scan was
        simply never looked at."""
        return any(p in (".", "") or path == p or path.startswith(p + "/")
                   for p in self.scan_prefixes)

    @property
    def ok(self) -> bool:
        return not self.findings


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    summary: str
    scope: str  # human-readable path scope, for --list-rules and docs
    check: Callable  # Module -> Iterable[Finding]  (or [Module] if project)
    project: bool = False  # project rules see every module at once


_RULES: Dict[str, Rule] = {}


def rule(name: str, summary: str, scope: str):
    """Register a per-module rule: ``check(module) -> Iterable[Finding]``."""

    def deco(fn):
        _register(Rule(name, summary, scope, fn, project=False))
        return fn

    return deco


def project_rule(name: str, summary: str, scope: str):
    """Register a whole-project rule:
    ``check(modules, repo_root) -> Iterable[Finding]`` (for cross-file
    contracts like the fault-site registry)."""

    def deco(fn):
        _register(Rule(name, summary, scope, fn, project=True))
        return fn

    return deco


def _register(r: Rule) -> None:
    if r.name in _RULES:
        raise ValueError(f"duplicate rule name {r.name!r}")
    _RULES[r.name] = r


def registered_rules() -> Tuple[Rule, ...]:
    return tuple(_RULES[name] for name in sorted(_RULES))


def rule_family(name: str) -> str:
    """The engine family a rule belongs to — the basename of the module
    its check lives in (hygiene, collectives, kernelcheck, statecheck,
    ...). The --stats per-family wall-time aggregation keys on this, so
    the <30 s CI wall gate stays diagnosable as engines accumulate."""
    r = _RULES.get(name)
    if r is None:
        return "unknown"
    return getattr(r.check, "__module__", "unknown").rsplit(".", 1)[-1]


def family_seconds(rule_seconds: Dict[str, float]) -> Dict[str, Tuple[int, float]]:
    """rule-name -> seconds aggregated to family -> (rule count, seconds)."""
    out: Dict[str, Tuple[int, float]] = {}
    for name in sorted(rule_seconds):
        fam = rule_family(name)
        n, s = out.get(fam, (0, 0.0))
        out[fam] = (n + 1, s + rule_seconds[name])
    return out


# -- file discovery -----------------------------------------------------

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules"}


def iter_py_files(paths: Sequence[str], repo_root: str) -> List[str]:
    """Absolute paths of every .py file under `paths`, sorted by their
    repo-relative name so rule execution order is deterministic."""
    out = []
    for p in paths:
        absp = p if os.path.isabs(p) else os.path.join(repo_root, p)
        if not os.path.exists(absp):
            # a typo'd/renamed path must fail loudly: silently linting
            # nothing would turn the CI gate green while covering zero
            # files (the exact drift failure mode this tool polices)
            raise ValueError(f"path does not exist: {p}")
        if os.path.isfile(absp):
            if not absp.endswith(".py"):
                raise ValueError(f"not a Python file: {p}")
            out.append(absp)
        elif os.path.isdir(absp):
            for root, dirs, files in os.walk(absp):
                dirs[:] = sorted(d for d in dirs
                                 if d not in _SKIP_DIRS and not d.startswith("."))
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
    return sorted(set(out), key=lambda a: _relpath(a, repo_root))


def _relpath(abspath: str, repo_root: str) -> str:
    return os.path.relpath(abspath, repo_root).replace(os.sep, "/")


def load_module(abspath: str, repo_root: str) -> Tuple[Optional[Module], Optional[Finding]]:
    rel = _relpath(abspath, repo_root)
    try:
        with open(abspath, "r", encoding="utf-8") as fh:
            text = fh.read()
        tree = ast.parse(text, filename=rel)
    except (SyntaxError, UnicodeDecodeError, OSError) as e:
        line = getattr(e, "lineno", 1) or 1
        col = (getattr(e, "offset", 1) or 1)
        return None, Finding(rel, int(line), int(col), "parse-error",
                             f"cannot parse: {e.__class__.__name__}: {e}")
    return Module(rel, tree, text.splitlines(), text), None


# -- suppression --------------------------------------------------------

def pragma_rules_on_line(module: Module, line: int) -> frozenset:
    """Rule names disabled by a pragma comment on the given 1-based
    physical line (the pragma must sit on the line the finding points
    at; multi-line statements anchor at their first line)."""
    if 1 <= line <= len(module.lines):
        m = PRAGMA_RE.search(module.lines[line - 1])
        if m:
            return frozenset(x.strip() for x in m.group(1).split(",") if x.strip())
    return frozenset()


def load_baseline(path: Optional[str]) -> collections.Counter:
    """Baseline as a Counter of (path, rule, message) keys; a missing
    file is an empty baseline (the gate starts strict)."""
    if not path or not os.path.exists(path):
        return collections.Counter()
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    counter: collections.Counter = collections.Counter()
    for entry in data.get("findings", ()):
        counter[(entry["path"], entry["rule"], entry["message"])] += 1
    return counter


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    entries = sorted(
        ({"path": f.path, "rule": f.rule, "message": f.message} for f in findings),
        key=lambda e: (e["path"], e["rule"], e["message"]),
    )
    payload = {
        "comment": (
            "Grandfathered raftlint findings. Matched on (path, rule, "
            "message); fix the code and the entry goes stale (reported "
            "by the CLI). New code must not add entries — use an inline "
            "justified pragma for intentional exceptions."
        ),
        "findings": entries,
        "version": 1,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")


# -- driver -------------------------------------------------------------

def lint_paths(
    paths: Sequence[str],
    repo_root: Optional[str] = None,
    baseline: Optional[str] = BASELINE_DEFAULT,
    rules: Optional[Sequence[str]] = None,
) -> LintResult:
    """Run every registered rule over the .py files under `paths`.

    `repo_root` anchors the repo-relative paths rules scope on (default:
    the repo containing this file, so invocations from anywhere agree
    with CI). `baseline=None` disables baseline suppression; `rules`
    restricts to a subset of rule names (tests use this for isolation).
    """
    if repo_root is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    selected = registered_rules()
    if rules is not None:
        unknown = set(rules) - {r.name for r in selected}
        if unknown:
            raise ValueError(f"unknown rule(s): {sorted(unknown)}")
        selected = tuple(r for r in selected if r.name in set(rules))

    modules: List[Module] = []
    raw: List[Finding] = []
    for abspath in iter_py_files(paths, repo_root):
        mod, err = load_module(abspath, repo_root)
        if err is not None:
            raw.append(err)
        else:
            modules.append(mod)

    by_path = {m.path: m for m in modules}
    rule_seconds: Dict[str, float] = {}
    for r in selected:
        t0 = time.perf_counter()
        if r.project:
            raw.extend(r.check(modules, repo_root))
        else:
            for m in modules:
                raw.extend(r.check(m))
        rule_seconds[r.name] = time.perf_counter() - t0

    # pragma suppression (needs the module's source line)
    active: List[Finding] = []
    pragma_suppressed = 0
    for f in sorted(raw):
        mod = by_path.get(f.path)
        disabled = pragma_rules_on_line(mod, f.line) if mod else frozenset()
        if f.rule in disabled or "all" in disabled:
            pragma_suppressed += 1
        else:
            active.append(f)

    # baseline suppression
    remaining = load_baseline(baseline)
    baseline_total = sum(remaining.values())
    kept: List[Finding] = []
    for f in active:
        if remaining.get(f.key(), 0) > 0:
            remaining[f.key()] -= 1
        else:
            kept.append(f)
    # under a --rules or path subset, entries for unselected rules or
    # paths outside the scan were never matched against anything:
    # reporting them stale would tell the user to delete live
    # grandfathered entries
    selected_names = {r.name for r in selected}
    prefixes = [_relpath(p if os.path.isabs(p) else os.path.join(repo_root, p),
                         repo_root) for p in paths]
    result = LintResult(
        findings=kept,
        pragma_suppressed=pragma_suppressed,
        baseline_suppressed=baseline_total - sum(remaining.values()),
        stale_baseline=[],
        all_findings=sorted(raw),
        scan_prefixes=prefixes,
        rule_seconds=rule_seconds,
    )
    result.stale_baseline = sorted(
        k for k, n in remaining.items() if n > 0
        and (rules is None or k[1] in selected_names)
        and result.covers(k[0])
        for _ in range(n))
    return result


# -- shared AST helpers (used by several rule modules) ------------------

def terminal_name(node: ast.AST) -> Optional[str]:
    """The rightmost name of a Name/Attribute chain: ``jax.jit`` -> "jit",
    ``jit`` -> "jit", anything else -> None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def dotted_chain(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """("np", "random", "rand") for ``np.random.rand``; None when the
    chain roots in anything but a plain Name (e.g. a call result)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
