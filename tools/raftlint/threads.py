"""Thread-root discovery and shared-state access analysis — the
raftlint 5.0 threadcheck core (rules live in rules/threadcheck.py).

The question this module answers is "which code runs on which thread,
and what mutable state do those threads share". Three layers:

  1. **Thread roots.** Every place the repo hands a callable to another
     execution context: ``threading.Thread(target=...)`` spawns, bus
     fan-out subscriptions (``subscribe(fn)``), Prometheus collector
     registration (``add_collector(name, fn)``), ``weakref.finalize``
     callbacks (run on the GC/finalizer thread), and
     ``signal.signal(SIGTERM, fn)`` handlers (run re-entrantly on the
     main thread at arbitrary bytecode boundaries — a concurrency
     context for race purposes even without a second OS thread).
     Discovered roots are checked both ways against the machine-readable
     ``THREAD_ROOTS`` registry (``raft_tpu/core/threads.py``, read by
     AST — the FAULT_SITES pattern), and an unresolvable spawn target
     fails CLOSED: a thread entry the analysis cannot see is a hole in
     every downstream guarantee.

  2. **Reachability.** From each root, a bounded BFS over resolved call
     edges (ProjectIndex resolution, plus: typed ``self.attr`` receivers
     learned from ``self.attr = ClassName(...)`` assignments,
     ``getattr(obj, "literal")`` method references, and a by-name
     fallback for multi-word method names with few hits). Every function
     a root reaches runs on that root's thread; public (non-underscore)
     methods and anything no root reaches additionally belong to the
     implicit ``caller`` root — the API surface any user thread may
     enter.

  3. **Access sets.** Per function, every ``self.attr`` read/write with
     the set of locks held at that point (``with self._lock:`` blocks,
     the ``*_locked`` suffix convention, module-level ``with _LOCK:``),
     whether a write is a whole-reference swap (a plain ``self.a = expr``
     whose RHS does not read ``self.a`` — old-or-new under the GIL, the
     blessed publication idiom), a container mutation
     (``self.a.append(...)``), or a write-through field store
     (``self.a.f = v`` — the publication-safety hazard). Module-level
     mutable globals get the same treatment.

Resolution is deliberately conservative and everything unresolved
under-reports (the ProjectIndex stance): this engine proves the
*absence* of a common lock on state it can see escaping to two roots,
it does not claim to see all state. stdlib ``ast`` only; raft_tpu is
never imported.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from tools.raftlint.engine import Module, dotted_chain, terminal_name
from tools.raftlint.project import ProjectIndex, project_index

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)

#: where the thread-root registry lives (read by AST, never imported)
REGISTRY_RELPATH = "raft_tpu/core/threads.py"

#: the implicit root: any user thread entering the public API surface
CALLER_ROOT = "caller"

#: threading factories whose product IS a synchronization primitive —
#: attrs holding one are exempt from access tracking (an Event/queue is
#: safe to share by construction; Lock/RLock/Condition are the guards
#: themselves, tracked separately)
LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
SYNC_FACTORIES = LOCK_FACTORIES | {
    "Event", "Semaphore", "BoundedSemaphore", "Barrier", "Queue",
    "SimpleQueue", "LifoQueue", "local",
}

#: receiver-method names that mutate the receiver in place — calling one
#: on ``self.attr`` is a WRITE to that attr for race purposes
MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "add", "update",
    "insert", "pop", "popleft", "popitem", "remove", "discard", "clear",
    "sort", "reverse", "setdefault", "rotate",
}

#: callback registrars: callee terminal name -> index of the callback
#: argument. Guards below keep false friends out (``op.finalize(x)`` in
#: kernel code is a math reduction, not a weakref callback).
CALLBACK_ARG = {"subscribe": 0, "add_collector": 1, "signal": 1,
                "finalize": 1}

#: by-name call resolution: accept multi-hit fallback only for
#: multi-word names (underscore present) with at most this many hits —
#: short verbs like set/get/step collide across unrelated classes
_BY_NAME_FANOUT = 4

#: names so common on NON-project receivers (files, subprocesses,
#: futures, containers, locks) that even a project-unique hit is
#: unreliable evidence — by-name resolution never fires for these
_BY_NAME_STOP = {
    "close", "open", "read", "readline", "readlines", "write", "flush",
    "seek", "tell", "get", "set", "put", "join", "start", "cancel",
    "send", "recv", "result", "copy", "keys", "values", "items",
    "append", "add", "pop", "clear", "update", "remove", "split",
    "strip", "encode", "decode", "format", "acquire", "release",
    "wait", "notify", "notify_all", "exists", "mkdir", "unlink",
    "touch", "terminate", "kill", "poll", "communicate", "run",
}

_REACH_CAP = 600  # functions per root: runaway-resolution backstop


# -- data model ----------------------------------------------------------

@dataclasses.dataclass
class Scope:
    """One function-like body: top-level def, method, nested def, or
    lambda. Nested defs keep their lexical class (``self`` in a closure
    still means the enclosing method's instance)."""

    qname: str          # "<module>::<Outer>.<inner...>" (dot-joined)
    name: str           # terminal name ("<lambda>" for lambdas)
    module: str
    node: ast.AST
    cls: Optional[str]  # owning ClassInfo qname, through closures
    parent: Optional[str]  # enclosing Scope qname (None at module level)
    is_public: bool = False


@dataclasses.dataclass(frozen=True)
class Access:
    """One shared-state touch. ``owner`` is ("attr", cls_qname, attr) or
    ("global", module, name); ``locks`` are tokens of the same two
    shapes naming the lock held at the access point."""

    owner: Tuple[str, str, str]
    kind: str           # "read" | "write" | "write_through"
    swap: bool          # plain whole-reference assignment
    scope: str          # Scope qname
    module: str
    line: int
    col: int
    locks: FrozenSet[Tuple[str, str, str]]


@dataclasses.dataclass
class RootSite:
    """One discovered spawn/registration site."""

    kind: str           # "spawn" | "callback"
    module: str
    line: int
    col: int
    targets: Tuple[str, ...]  # resolved Scope qnames (empty: unresolved)
    detail: str         # for diagnostics ("Thread(target=...)", "subscribe")


# -- registry (AST-read, fail-closed) ------------------------------------

def load_registry(modules: Sequence[Module]) -> Optional[Dict[str, str]]:
    """THREAD_ROOTS from the registry module, or None when the module is
    absent from the scan / the literal is missing or malformed (callers
    fail closed on None)."""
    reg = next((m for m in modules if m.path == REGISTRY_RELPATH), None)
    if reg is None:
        return None
    for node in reg.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            tgt = node.target
        else:
            continue
        if not (isinstance(tgt, ast.Name) and tgt.id == "THREAD_ROOTS"):
            continue
        if not isinstance(node.value, ast.Dict):
            return None
        out: Dict[str, str] = {}
        for k, v in zip(node.value.keys, node.value.values):
            if not (isinstance(k, ast.Constant) and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)):
                return None
            out[k.value] = v.value
        return out
    return None


# -- the index -----------------------------------------------------------

class ThreadIndex:
    """Scopes, call edges, thread roots, and access sets over one module
    set (memoized per lint run alongside the ProjectIndex)."""

    def __init__(self, modules: Sequence[Module], pidx: ProjectIndex):
        self.modules = list(modules)
        self.pidx = pidx
        self.scopes: Dict[str, Scope] = {}
        self._children: Dict[str, List[str]] = {}   # scope -> nested defs
        self._module_defs: Dict[str, Dict[str, str]] = {}
        #: (cls qname, attr) -> class qname the attr holds
        self.attr_types: Dict[Tuple[str, str], str] = {}
        #: cls qname -> attrs holding sync primitives (incl. locks)
        self.sync_attrs: Dict[str, Set[str]] = {}
        #: cls qname -> lock attrs (the guards themselves)
        self.lock_attrs: Dict[str, Set[str]] = {}
        #: module -> module-level lock names
        self.module_locks: Dict[str, Set[str]] = {}
        #: module -> module-level assigned names (global-write candidates)
        self._module_names: Dict[str, Set[str]] = {}
        self.spawn_sites: List[RootSite] = []
        self.callback_sites: List[RootSite] = []
        self._class_by_name: Dict[str, List[str]] = {}
        for cq, ci in pidx.classes.items():
            self._class_by_name.setdefault(ci.name, []).append(cq)
        for m in sorted(self.modules, key=lambda x: x.path):
            self._index_module(m)
        self.edges: Dict[str, Set[str]] = {}
        for q in self.scopes:
            self.edges[q] = self._callees(self.scopes[q])
        self._discover_roots()
        self.accesses: List[Access] = []
        for q in sorted(self.scopes):
            self.accesses.extend(_collect_accesses(self, self.scopes[q]))

    # -- scope + class indexing ------------------------------------------

    def _index_module(self, m: Module) -> None:
        self._module_defs[m.path] = {}
        self._module_names[m.path] = set()
        self.module_locks[m.path] = set()
        for node in m.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self._module_names[m.path].add(t.id)
                        if (isinstance(node.value, ast.Call)
                                and terminal_name(node.value.func)
                                in LOCK_FACTORIES):
                            self.module_locks[m.path].add(t.id)
            elif isinstance(node, ast.AnnAssign) and isinstance(
                    node.target, ast.Name):
                self._module_names[m.path].add(node.target.id)

        def walk(body, prefix, cls, parent):
            for node in body:
                if isinstance(node, _FUNCS):
                    q = f"{m.path}::{prefix}{node.name}" if prefix else \
                        f"{m.path}::{node.name}"
                    sc = Scope(q, node.name, m.path, node, cls, parent,
                               is_public=not node.name.startswith("_"))
                    self.scopes[q] = sc
                    if parent is None and cls is None:
                        self._module_defs[m.path][node.name] = q
                    else:
                        self._children.setdefault(parent, []).append(q)
                    walk(node.body, f"{prefix}{node.name}.", cls, q)
                elif isinstance(node, ast.ClassDef):
                    cq = f"{m.path}::{node.name}"
                    self._index_class(m, node, cq)
                    walk(node.body, f"{node.name}.", cq, parent)
                elif isinstance(node, (ast.If, ast.Try, ast.With)):
                    walk(getattr(node, "body", []), prefix, cls, parent)

        walk(m.tree.body, "", None, None)

    def _index_class(self, m: Module, node: ast.ClassDef, cq: str) -> None:
        sync: Set[str] = set()
        locks: Set[str] = set()
        for sub in ast.walk(node):
            if not (isinstance(sub, ast.Assign)
                    and isinstance(sub.value, ast.Call)):
                continue
            fac = terminal_name(sub.value.func)
            for tgt in sub.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    if fac in SYNC_FACTORIES:
                        sync.add(tgt.attr)
                    if fac in LOCK_FACTORIES:
                        locks.add(tgt.attr)
                    else:
                        self._learn_attr_type(m, cq, tgt.attr, sub.value)
            # typed attrs wrapped in `x or Cls()` / `x if c else Cls()`
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and isinstance(
                    sub.value, (ast.BoolOp, ast.IfExp)):
                for tgt in sub.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        for part in ast.walk(sub.value):
                            if isinstance(part, ast.Call):
                                self._learn_attr_type(m, cq, tgt.attr, part)
        self.sync_attrs[cq] = sync
        self.lock_attrs[cq] = locks

    def _learn_attr_type(self, m: Module, cq: str, attr: str,
                         call: ast.Call) -> None:
        cls_q = self._resolve_class(m.path, call.func)
        if cls_q is None:
            return
        key = (cq, attr)
        if key in self.attr_types and self.attr_types[key] != cls_q:
            self.attr_types[key] = "?ambiguous"  # conflicting evidence
        else:
            self.attr_types.setdefault(key, cls_q)

    def _resolve_class(self, module: str, func: ast.AST) -> Optional[str]:
        name = terminal_name(func)
        if name is None or not name[:1].isupper():
            return None
        if isinstance(func, ast.Name):
            local = f"{module}::{name}"
            if local in self.pidx.classes:
                return local
            imp = self.pidx.imports.get(module, {}).get(name)
            if imp is not None and imp[0] == "symbol":
                target = f"{imp[1].replace('.', '/')}.py::{imp[2]}"
                if target in self.pidx.classes:
                    return target
        hits = self._class_by_name.get(name, ())
        if len(hits) == 1:
            return hits[0]
        return None

    # -- call edges -------------------------------------------------------

    def _resolve_name_in_scope(self, scope: Scope, name: str) -> Optional[str]:
        """A bare Name callable: nested sibling/enclosing defs first,
        then module-level defs (lexical scoping, closures included)."""
        q: Optional[str] = scope.qname
        while q is not None:
            for child in self._children.get(q, ()):
                if self.scopes[child].name == name:
                    return child
            q = self.scopes[q].parent
        return self._module_defs.get(scope.module, {}).get(name)

    def _callees(self, scope: Scope) -> Set[str]:
        out: Set[str] = set()
        for node in _own_nodes(scope.node):
            if not isinstance(node, ast.Call):
                continue
            out.update(self.resolve_callable(scope, node.func))
            # getattr(obj, "m") is a method *reference*; conservatively
            # assume it will be called (engine's maybe_heal hook shape)
            if (isinstance(node.func, ast.Name) and node.func.id == "getattr"
                    and len(node.args) >= 2
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)):
                out.update(self._by_name(node.args[1].value))
        return out

    def _by_name(self, name: str) -> List[str]:
        if name in _BY_NAME_STOP:
            return []
        hits = [q for q in self.pidx.resolve_methods_by_name(name)
                if q in self.scopes]
        if len(hits) == 1 or (len(hits) <= _BY_NAME_FANOUT and "_" in name):
            return sorted(hits)
        return []

    def resolve_callable(self, scope: Scope, func: ast.AST) -> List[str]:
        """Resolve a callable expression to Scope qnames ([] unknown)."""
        if isinstance(func, ast.Name):
            local = self._resolve_name_in_scope(scope, func.id)
            if local is not None:
                return [local]
            for q in self.pidx.resolve_call(scope.module, func,
                                            cls=scope.cls):
                if q in self.scopes:
                    return [q]
            return []
        if isinstance(func, ast.Attribute):
            recv = func.value
            if isinstance(recv, ast.Name) and recv.id == "self" \
                    and scope.cls is not None:
                ci = self.pidx.classes.get(scope.cls)
                if ci is not None:
                    q = f"{ci.module}::{ci.name}.{func.attr}"
                    if q in self.scopes:
                        return [q]
                return []
            # self.attr.m() through the learned attr type
            if (isinstance(recv, ast.Attribute)
                    and isinstance(recv.value, ast.Name)
                    and recv.value.id == "self" and scope.cls is not None):
                tq = self.attr_types.get((scope.cls, recv.attr))
                if tq and tq in self.pidx.classes:
                    ci = self.pidx.classes[tq]
                    q = f"{ci.module}::{ci.name}.{func.attr}"
                    if q in self.scopes:
                        return [q]
                    return []
            # module-function call through imports
            for q in self.pidx.resolve_call(scope.module, func,
                                            cls=scope.cls):
                if q in self.scopes:
                    return [q]
            return self._by_name(func.attr)
        if isinstance(func, ast.Lambda):
            # a single-expression-call lambda is a trampoline: the root
            # is whatever it calls (runner.py's SIGTERM handler shape)
            if isinstance(func.body, ast.Call):
                return self.resolve_callable(scope, func.body.func)
        return []

    # -- root discovery ---------------------------------------------------

    def _discover_roots(self) -> None:
        for q in sorted(self.scopes):
            scope = self.scopes[q]
            for node in _own_nodes(scope.node):
                if isinstance(node, ast.Call):
                    self._classify_call(scope, node)
        # module-level statements (import-time subscribe etc.)
        for m in sorted(self.modules, key=lambda x: x.path):
            pseudo = Scope(f"{m.path}::<module>", "<module>", m.path,
                           m.tree, None, None)
            for node in _module_level_nodes(m.tree):
                if isinstance(node, ast.Call):
                    self._classify_call(pseudo, node)

    def _classify_call(self, scope: Scope, call: ast.Call) -> None:
        name = terminal_name(call.func)
        if name == "Thread":
            chain = dotted_chain(call.func)
            imp = self.pidx.imports.get(scope.module, {}).get("Thread")
            from_threading = (
                (chain is not None and chain[0] == "threading")
                or (isinstance(call.func, ast.Name) and imp is not None
                    and imp[0] == "symbol" and imp[1] == "threading"))
            if not from_threading:
                return
            target = next((kw.value for kw in call.keywords
                           if kw.arg == "target"), None)
            if target is None and len(call.args) >= 2:
                target = call.args[1]
            resolved = self.resolve_callable(scope, target) \
                if target is not None else []
            self.spawn_sites.append(RootSite(
                "spawn", scope.module, call.lineno, call.col_offset,
                tuple(sorted(resolved)), "threading.Thread(target=...)"))
            return
        if name not in CALLBACK_ARG:
            return
        chain = dotted_chain(call.func)
        if name == "finalize" and (chain is None or chain[0] != "weakref"):
            return
        if name == "signal" and (chain is None
                                 or chain != ("signal", "signal")):
            return
        idx = CALLBACK_ARG[name]
        if len(call.args) <= idx:
            return
        cb = call.args[idx]
        # restoring SIG_DFL/SIG_IGN/None is tearing a root DOWN
        cb_chain = dotted_chain(cb)
        if name == "signal" and (
                (isinstance(cb, ast.Constant) and cb.value is None)
                or (cb_chain is not None and cb_chain[-1] in
                    ("SIG_DFL", "SIG_IGN"))):
            return
        resolved = self.resolve_callable(scope, cb)
        if not resolved and isinstance(cb, ast.Attribute):
            resolved = self._by_name(cb.attr)
        self.callback_sites.append(RootSite(
            "callback", scope.module, call.lineno, call.col_offset,
            tuple(sorted(resolved)), f"{name}(...)"))

    # -- reachability -----------------------------------------------------

    def reach(self, root: str) -> Set[str]:
        seen: Set[str] = set()
        frontier = [root] if root in self.scopes else []
        while frontier and len(seen) < _REACH_CAP:
            q = frontier.pop()
            if q in seen:
                continue
            seen.add(q)
            frontier.extend(sorted(self.edges.get(q, ())))
        return seen

    def root_map(self, roots: Sequence[str]) -> Dict[str, FrozenSet[str]]:
        """Scope qname -> thread roots it runs under. Reached private
        scopes belong to their roots; public scopes and unreached ones
        also belong to the implicit ``caller`` root."""
        reached: Dict[str, Set[str]] = {}
        for r in sorted(set(roots)):
            for q in self.reach(r):
                reached.setdefault(q, set()).add(r)
        out: Dict[str, FrozenSet[str]] = {}
        for q, scope in self.scopes.items():
            rs = reached.get(q, set())
            if not rs or scope.is_public:
                rs = rs | {CALLER_ROOT}
            out[q] = frozenset(rs)
        return out


def _own_nodes(root: ast.AST):
    """Descendants excluding nested function/lambda bodies (those are
    their own scopes)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, _FUNCS + (ast.Lambda,)):
            stack.extend(ast.iter_child_nodes(n))


def _module_level_nodes(tree: ast.AST):
    for node in tree.body:
        if isinstance(node, _FUNCS + (ast.ClassDef,)):
            continue
        yield node
        yield from _own_nodes(node)


# -- access collection ---------------------------------------------------

def _reads_self_attr(expr: ast.AST, attr: str) -> bool:
    for n in ast.walk(expr):
        if (isinstance(n, ast.Attribute) and n.attr == attr
                and isinstance(n.value, ast.Name) and n.value.id == "self"):
            return True
    return False


def _collect_accesses(tidx: ThreadIndex, scope: Scope) -> List[Access]:
    """Lock-context-sensitive accesses in one scope. Nested defs and
    lambdas start lock-free — they are separate scopes and the analysis
    does not know when they run (locks.py takes the same stance)."""
    out: List[Access] = []
    cls = scope.cls
    lock_attrs = tidx.lock_attrs.get(cls, set()) if cls else set()
    sync_attrs = tidx.sync_attrs.get(cls, set()) if cls else set()
    mod_locks = tidx.module_locks.get(scope.module, set())
    mod_names = tidx._module_names.get(scope.module, set())
    globals_decl: Set[str] = set()
    for n in _own_nodes(scope.node):
        if isinstance(n, ast.Global):
            globals_decl.update(n.names)

    held: List[Tuple[str, str, str]] = []
    if scope.name.endswith("_locked") and cls:
        # caller-holds-lock convention (serve/batcher._take_locked)
        held.extend(("attr", cls, a) for a in sorted(lock_attrs))

    def lock_token(expr: ast.AST) -> Optional[Tuple[str, str, str]]:
        e = expr.func if isinstance(expr, ast.Call) else expr
        if (cls and isinstance(e, ast.Attribute)
                and isinstance(e.value, ast.Name) and e.value.id == "self"
                and e.attr in lock_attrs):
            return ("attr", cls, e.attr)
        if isinstance(e, ast.Name) and e.id in mod_locks:
            return ("global", scope.module, e.id)
        return None

    def emit(owner, kind, swap, node):
        out.append(Access(owner, kind, swap, scope.qname, scope.module,
                          node.lineno, node.col_offset,
                          frozenset(held)))

    def self_attr(node: ast.AST) -> Optional[str]:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self" and cls is not None
                and node.attr not in lock_attrs
                and node.attr not in sync_attrs):
            return node.attr
        return None

    def visit(node: ast.AST) -> None:
        if isinstance(node, _FUNCS + (ast.Lambda,)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            tokens = [t for t in (lock_token(i.context_expr)
                                  for i in node.items) if t is not None]
            for item in node.items:
                visit(item.context_expr)
            held.extend(tokens)
            for child in node.body:
                visit(child)
            if tokens:
                del held[-len(tokens):]
            return
        if isinstance(node, ast.Assign):
            visit(node.value)
            for tgt in node.targets:
                attr = self_attr(tgt)
                if attr is not None:
                    swap = not _reads_self_attr(node.value, attr)
                    emit(("attr", cls, attr), "write", swap, tgt)
                    continue
                # self.a.f = v / self.a[i] = v: write-through on a
                if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                    inner = self_attr(tgt.value)
                    if inner is not None:
                        emit(("attr", cls, inner), "write_through", False,
                             tgt)
                        continue
                    if (isinstance(tgt.value, ast.Name)
                            and tgt.value.id in mod_names):
                        emit(("global", scope.module, tgt.value.id),
                             "write", False, tgt)
                        continue
                if isinstance(tgt, ast.Name) and tgt.id in globals_decl:
                    swap = not any(
                        isinstance(n, ast.Name) and n.id == tgt.id
                        and isinstance(n.ctx, ast.Load)
                        for n in ast.walk(node.value))
                    emit(("global", scope.module, tgt.id), "write", swap,
                         tgt)
                    continue
                visit(tgt)
            return
        if isinstance(node, ast.AugAssign):
            visit(node.value)
            attr = self_attr(node.target)
            if attr is not None:
                emit(("attr", cls, attr), "write", False, node.target)
                return
            if (isinstance(node.target, ast.Name)
                    and node.target.id in globals_decl):
                emit(("global", scope.module, node.target.id), "write",
                     False, node.target)
                return
            visit(node.target)
            return
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                attr = self_attr(tgt)
                if attr is not None:
                    emit(("attr", cls, attr), "write", False, tgt)
                elif (isinstance(tgt, ast.Subscript)
                      and isinstance(tgt.value, ast.Name)
                      and tgt.value.id in mod_names):
                    emit(("global", scope.module, tgt.value.id), "write",
                         False, tgt)
            return
        if isinstance(node, ast.Call):
            # self.a.append(x): in-place mutation of a
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and f.attr in MUTATOR_METHODS):
                attr = self_attr(f.value)
                if attr is not None:
                    emit(("attr", cls, attr), "write", False, f.value)
                elif (isinstance(f.value, ast.Name)
                      and f.value.id in mod_names
                      and f.value.id not in mod_locks):
                    emit(("global", scope.module, f.value.id), "write",
                         False, f.value)
            for child in ast.iter_child_nodes(node):
                visit(child)
            return
        attr = self_attr(node)
        if attr is not None and isinstance(node.ctx, ast.Load):
            emit(("attr", cls, attr), "read", False, node)
            # fall through: children of an Attribute are just `self`
        if (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
                and (node.id in globals_decl
                     or (node.id in mod_names and _is_tracked_global(
                         tidx, scope.module, node.id)))):
            emit(("global", scope.module, node.id), "read", False, node)
        for child in ast.iter_child_nodes(node):
            visit(child)

    body = scope.node.body if isinstance(scope.node, _FUNCS) \
        else [scope.node.body]
    for stmt in body:
        visit(stmt)
    return out


def _is_tracked_global(tidx: ThreadIndex, module: str, name: str) -> bool:
    """Reads of a module global only matter for names some function
    WRITES (via ``global`` decl, mutator call, or subscript store) —
    plain constants read everywhere would be pure noise. Computed lazily
    and cached on the index."""
    cache = getattr(tidx, "_tracked_globals", None)
    if cache is None:
        cache = {}
        for q, scope in tidx.scopes.items():
            for n in _own_nodes(scope.node):
                if isinstance(n, ast.Global):
                    for nm in n.names:
                        cache.setdefault(scope.module, set()).add(nm)
                elif (isinstance(n, ast.Call)
                      and isinstance(n.func, ast.Attribute)
                      and n.func.attr in MUTATOR_METHODS
                      and isinstance(n.func.value, ast.Name)):
                    cache.setdefault(scope.module, set()).add(
                        n.func.value.id)
                elif isinstance(n, (ast.Assign, ast.AugAssign)):
                    tgts = n.targets if isinstance(n, ast.Assign) \
                        else [n.target]
                    for t in tgts:
                        if (isinstance(t, ast.Subscript)
                                and isinstance(t.value, ast.Name)):
                            cache.setdefault(scope.module, set()).add(
                                t.value.id)
        tidx._tracked_globals = cache
    names = cache.get(module, set())
    return (name in names
            and name in tidx._module_names.get(module, set())
            and name not in tidx.module_locks.get(module, set()))


# -- memoization ---------------------------------------------------------

def thread_index(modules: Sequence[Module]) -> ThreadIndex:
    """Build (and memoize per lint run) the ThreadIndex, anchored on the
    same tree the ProjectIndex memoizes on."""
    pidx = project_index(modules)
    if not modules:
        return ThreadIndex((), pidx)
    anchor = modules[0].tree
    cached = getattr(anchor, "_raftlint_threads", None)
    if cached is None or len(cached.modules) != len(modules):
        cached = ThreadIndex(modules, pidx)
        anchor._raftlint_threads = cached
    return cached
